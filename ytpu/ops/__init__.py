"""Device kernels: batched state-vector math, sequence ops, codec helpers."""

from .compaction import compact_state, grow_state
from .state_vector import (
    diff_start_clocks,
    sv_contains_all,
    sv_diff_mask,
    sv_from_blocks,
    sv_merge,
)

__all__ = [
    "sv_merge",
    "sv_contains_all",
    "sv_diff_mask",
    "sv_from_blocks",
    "diff_start_clocks",
    "compact_state",
    "grow_state",
]
