"""Fused Pallas integrate kernel — the whole update-stream replay in VMEM.

The XLA path (`ytpu.models.batch_doc.apply_update_stream`) streams the full
[docs, capacity] block state through HBM once per update step (every scatter
and select materializes columns). This kernel removes that bottleneck:

- the doc axis is tiled (D_BLK docs per grid program) and each tile's block
  columns are DMA'd into VMEM **once**;
- the *entire* S-step update stream is integrated in-core (YATA conflict
  scans, splits, delete ranges — all vectorized over the doc sublanes with
  one-hot selects over the capacity lanes);
- the tile is written back **once**. HBM traffic drops from
  O(S · docs · capacity) to O(docs · capacity + S).

Semantics mirror `_integrate_row` / `_apply_delete_range` in batch_doc.py
(reference: block.rs:482-769, transaction.rs:472-575); parity is enforced in
tests/test_pallas_kernel.py against both the XLA path and the host oracle.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ytpu.core.content import (
    BLOCK_GC,
    CONTENT_DELETED,
    CONTENT_FORMAT,
    CONTENT_MOVE,
)
from ytpu.models.batch_doc import BlockCols, DocStateBatch, UpdateBatch

__all__ = [
    "pack_state",
    "unpack_state",
    "pack_stream",
    "apply_update_stream_fused",
]

I32 = jnp.int32

# column indices in the packed [NC, D, C] state
(
    CL,  # client
    CK,  # clock
    LN,  # length
    OC,  # origin client
    OK,  # origin clock
    RC,  # right-origin client
    RK,  # right-origin clock
    LT,  # left link
    RT,  # right link
    DL,  # deleted flag
    CN,  # countable flag
    KD,  # content kind
    RF,  # content ref
    OF,  # content offset
    KEY,  # interned parent_sub (-1 = sequence item)
    PA,  # parent ContentType row (-1 = root)
    HD,  # child-sequence head (ContentType rows)
) = range(17)
NC = 17
# move columns are NOT packed: the fused kernel excludes move rows
# (guarded below) — move ownership needs the end-of-update recompute pass
# that only the XLA path runs; moved/mv_* pass through unchanged.

# meta columns in the packed [D, 8] array (padded to a TPU-friendly lane dim)
M_START, M_NBLOCKS, M_ERROR = 0, 1, 2
M_PAD = 8

ERR_CAPACITY = 1
ERR_MISSING_DEP = 2


def pack_state(state: DocStateBatch) -> Tuple[jax.Array, jax.Array]:
    bl = state.blocks
    cols = jnp.stack(
        [
            bl.client,
            bl.clock,
            bl.length,
            bl.origin_client,
            bl.origin_clock,
            bl.ror_client,
            bl.ror_clock,
            bl.left,
            bl.right,
            bl.deleted.astype(I32),
            bl.countable.astype(I32),
            bl.kind,
            bl.content_ref,
            bl.content_off,
            bl.key,
            bl.parent,
            bl.head,
        ]
    )  # [NC, D, C]
    D = state.start.shape[0]
    meta = jnp.zeros((D, M_PAD), I32)
    meta = meta.at[:, M_START].set(state.start)
    meta = meta.at[:, M_NBLOCKS].set(state.n_blocks)
    meta = meta.at[:, M_ERROR].set(state.error)
    return cols, meta


def unpack_state(
    cols: jax.Array, meta: jax.Array, state: DocStateBatch
) -> DocStateBatch:
    """Rebuild state from kernel outputs; move columns pass through from
    the pre-kernel `state` (move rows are excluded from the fused path)."""
    blocks = BlockCols(
        client=cols[CL],
        clock=cols[CK],
        length=cols[LN],
        origin_client=cols[OC],
        origin_clock=cols[OK],
        ror_client=cols[RC],
        ror_clock=cols[RK],
        left=cols[LT],
        right=cols[RT],
        deleted=cols[DL].astype(bool),
        countable=cols[CN].astype(bool),
        kind=cols[KD],
        content_ref=cols[RF],
        content_off=cols[OF],
        key=cols[KEY],
        parent=cols[PA],
        head=cols[HD],
        moved=state.blocks.moved,
        mv_sc=state.blocks.mv_sc,
        mv_sk=state.blocks.mv_sk,
        mv_sa=state.blocks.mv_sa,
        mv_ec=state.blocks.mv_ec,
        mv_ek=state.blocks.mv_ek,
        mv_ea=state.blocks.mv_ea,
        mv_prio=state.blocks.mv_prio,
    )
    return DocStateBatch(
        blocks=blocks,
        start=meta[:, M_START],
        n_blocks=meta[:, M_NBLOCKS],
        error=meta[:, M_ERROR],
    )


def pack_stream(stream: UpdateBatch) -> Tuple[jax.Array, jax.Array]:
    """Stacked doc-axis-free stream → rows [S, U, 15] / dels [S, R, 4] i32."""
    rows = jnp.stack(
        [
            stream.client,
            stream.clock,
            stream.length,
            stream.origin_client,
            stream.origin_clock,
            stream.ror_client,
            stream.ror_clock,
            stream.kind,
            stream.content_ref,
            stream.content_off,
            stream.key,
            stream.p_tag,
            stream.p_client,
            stream.p_clock,
            stream.valid.astype(I32),
        ],
        axis=-1,
    )  # [S, U, 15]
    dels = jnp.stack(
        [
            stream.del_client,
            stream.del_start,
            stream.del_end,
            stream.del_valid.astype(I32),
        ],
        axis=-1,
    )  # [S, R, 4]
    return rows, dels


def _kernel(rows_ref, dels_ref, rank_ref, _cols_in, _meta_in, cols_ref, meta_ref):
    """One doc tile: integrate the whole stream in VMEM.

    cols_ref: [NC, DB, C] out-ref aliased to the input (holds the state),
    meta_ref: [DB, 8] aliased; rows_ref: [S, U, 11], dels_ref: [S, R, 4],
    rank_ref: [1, K]. The plain in-refs are shadows of the aliased buffers
    and are unused.
    """
    S, U, _ = rows_ref.shape
    R = dels_ref.shape[1]
    DB = cols_ref.shape[1]
    C = cols_ref.shape[2]
    iota_c = jax.lax.broadcasted_iota(I32, (DB, C), 1)

    def col(i):
        return cols_ref[i]

    def gather(i, idx, fill):
        """Per-doc element col(i)[d, idx[d]] with idx < 0 -> fill."""
        onehot = iota_c == idx[:, None]
        v = jnp.sum(jnp.where(onehot, col(i), 0), axis=1)
        return jnp.where(idx >= 0, v, fill)

    def put(i, idx, val, active):
        """col(i)[d, idx[d]] = val[d] where active[d] & idx valid."""
        mask = (iota_c == idx[:, None]) & active[:, None] & (idx >= 0)[:, None]
        cols_ref[i] = jnp.where(mask, val[:, None], col(i))

    def put_many(idx, active, writes):
        """Write several columns at one slot, computing the mask once.

        `writes` is [(col_idx, val_vector), ...]; same semantics as `put`."""
        mask = (iota_c == idx[:, None]) & active[:, None] & (idx >= 0)[:, None]
        for i, val in writes:
            cols_ref[i] = jnp.where(mask, val[:, None], col(i))

    def n_blocks():
        return meta_ref[:, M_NBLOCKS]

    K = rank_ref.shape[1]
    iota_k = jax.lax.broadcasted_iota(I32, (DB, K), 1)

    def gather_rank(client_v):
        """rank_ref[0, client_v[d]] per doc (one-hot gather)."""
        onehot = iota_k == jnp.maximum(client_v, 0)[:, None]
        return jnp.sum(jnp.where(onehot, rank_ref[0][None, :], 0), axis=1)

    def find_slot(client_v, clock_v, enable):
        """(idx[DB], found[DB]) of the block covering (client, clock);
        `client_v`/`clock_v` are per-doc (DB,) vectors."""
        valid = iota_c < n_blocks()[:, None]
        m = (
            valid
            & (col(CL) == client_v[:, None])
            & (col(CK) <= clock_v[:, None])
            & (clock_v[:, None] < col(CK) + col(LN))
            & enable[:, None]
        )
        # integer argmax is unsupported in Mosaic: min-reduce the indices
        idx = jnp.min(jnp.where(m, iota_c, C), axis=1).astype(I32)
        found = idx < C
        return jnp.where(found, idx, -1), found

    def client_clock(client_s):
        valid = iota_c < n_blocks()[:, None]
        m = valid & (col(CL) == client_s)
        return jnp.max(jnp.where(m, col(CK) + col(LN), 0), axis=1)

    def split(i_idx, off, want):
        """Split block i at off (per doc); returns right-half slot (or i).

        The whole write phase sits behind `pl.when(any(do))`: the hot replay
        case (appends, whole-block deletes) needs no split in *any* doc of
        the tile, so the ~30 [DB, C] sweeps below are skipped entirely."""
        length_i = gather(LN, i_idx, 0)
        do = want & (i_idx >= 0) & (off > 0) & (off < length_i)
        j = n_blocks()
        overflow = do & (j >= C)
        do = do & (j < C)
        # the error record must not sit behind the lazy write phase: a tile
        # where every needed split overflows has all-False `do`
        meta_ref[:, M_ERROR] = meta_ref[:, M_ERROR] | jnp.where(
            overflow, ERR_CAPACITY, 0
        )

        @pl.when(jnp.any(do))
        def _():
            right_i = gather(RT, i_idx, -1)
            # new row j = right half
            put_many(
                j,
                do,
                [
                    (CL, gather(CL, i_idx, -1)),
                    (CK, gather(CK, i_idx, 0) + off),
                    (LN, length_i - off),
                    (OC, gather(CL, i_idx, -1)),
                    (OK, gather(CK, i_idx, 0) + off - 1),
                    (RC, gather(RC, i_idx, -1)),
                    (RK, gather(RK, i_idx, 0)),
                    (LT, i_idx),
                    (RT, right_i),
                    (DL, gather(DL, i_idx, 0)),
                    (CN, gather(CN, i_idx, 0)),
                    (KD, gather(KD, i_idx, 0)),
                    (RF, gather(RF, i_idx, -1)),
                    (OF, gather(OF, i_idx, 0) + off),
                    (KEY, gather(KEY, i_idx, -1)),
                    (PA, gather(PA, i_idx, -1)),
                    (HD, gather(HD, i_idx, -1)),
                ],
            )
            # fix left half + old right neighbor
            put_many(i_idx, do, [(LN, off), (RT, j)])
            put(LT, right_i, j, do & (right_i >= 0))
            meta_ref[:, M_NBLOCKS] = n_blocks() + do.astype(I32)

        return jnp.where(do, j, i_idx)

    def clean_end(client_s, clock_v, enable):
        i, found = find_slot(client_s, clock_v, enable)
        off = clock_v - gather(CK, i, 0) + 1
        split(i, off, enable & found)
        return i, found

    def clean_start(client_s, clock_v, enable):
        i, found = find_slot(client_s, clock_v, enable)
        off = clock_v - gather(CK, i, 0)
        j = split(i, off, enable & found)
        return jnp.where((i >= 0) & (off > 0), j, i), found

    def integrate_row(s, u):
        r_client = rows_ref[s, u, 0]
        r_clock = rows_ref[s, u, 1]
        r_len = rows_ref[s, u, 2]
        r_oc = rows_ref[s, u, 3]
        r_ok = rows_ref[s, u, 4]
        r_rc = rows_ref[s, u, 5]
        r_rk = rows_ref[s, u, 6]
        r_kind = rows_ref[s, u, 7]
        r_ref = rows_ref[s, u, 8]
        r_off = rows_ref[s, u, 9]
        r_key = rows_ref[s, u, 10]
        r_ptag = rows_ref[s, u, 11]
        r_pclient = rows_ref[s, u, 12]
        r_pclock = rows_ref[s, u, 13]

        local = client_clock(r_client)  # (DB,)
        applicable = local >= r_clock
        missing = ~applicable
        offset = local - r_clock
        dup = applicable & (offset >= r_len)
        do = applicable & ~dup

        clock = r_clock + offset
        length = r_len - offset
        c_off = r_off + offset
        has_origin = (offset > 0) | (r_oc >= 0)
        origin_client = jnp.where(offset > 0, r_client, r_oc)
        origin_clock = jnp.where(offset > 0, clock - 1, r_ok)
        has_ror = r_rc >= 0

        is_gc = r_kind == BLOCK_GC
        linkable = do & ~is_gc

        left_idx, lfound = clean_end(
            origin_client, origin_clock, linkable & has_origin
        )
        right_idx, rfound = clean_start(
            jnp.full((DB,), r_rc, I32), jnp.full((DB,), r_rk, I32),
            linkable & has_ror,
        )
        left_idx = jnp.where(linkable & has_origin, left_idx, -1)
        right_idx = jnp.where(linkable & has_ror, right_idx, -1)

        anchor_missing = (linkable & has_origin & (left_idx < 0)) | (
            linkable & has_ror & (right_idx < 0)
        )
        missing = missing | anchor_missing
        linkable = linkable & ~anchor_missing

        # parent branch (parity: block.rs:503-523): p_tag 2 = nested branch
        # by ContentType item id; 1 = root; 0 = inherit from the resolved
        # left (else right) anchor
        parent_slot, _pfound = find_slot(
            jnp.full((DB,), r_pclient, I32),
            jnp.full((DB,), r_pclock, I32),
            linkable & (r_ptag == 2),
        )
        left_parent = gather(PA, left_idx, -1)
        right_parent = gather(PA, right_idx, -1)
        inherited_parent = jnp.where(left_idx >= 0, left_parent, right_parent)
        parent_row = jnp.where(
            r_ptag == 2,
            parent_slot,
            jnp.where(r_ptag == 1, -1, inherited_parent),
        )
        parent_missing = linkable & (r_ptag == 2) & (parent_slot < 0)
        missing = missing | parent_missing
        linkable = linkable & ~parent_missing

        # parent_sub: inherited from the anchors when omitted on the wire
        # (parity: block.rs:604-612)
        left_key = gather(KEY, left_idx, -1)
        right_key = gather(KEY, right_idx, -1)
        key_v = jnp.where(
            r_key >= 0,
            jnp.full((DB,), r_key, I32),
            jnp.where(left_key >= 0, left_key, right_key),
        )
        is_map = key_v >= 0

        # map rows anchor on their (parent, key) chain's leftmost item
        # (parity: block.rs:541-551); sequence rows on the parent's head
        valid_slots = iota_c < n_blocks()[:, None]
        chain_mask = (
            valid_slots
            & (col(KEY) == key_v[:, None])
            & (col(PA) == parent_row[:, None])
            & (col(LT) == -1)
            & is_map[:, None]
        )
        chain_idx = jnp.min(jnp.where(chain_mask, iota_c, C), axis=1).astype(I32)
        chain_head = jnp.where(chain_idx < C, chain_idx, -1)
        seq_head = jnp.where(
            parent_row >= 0, gather(HD, parent_row, -1), meta_ref[:, M_START]
        )
        anchor0_base = jnp.where(is_map, chain_head, seq_head)

        right_left = gather(LT, right_idx, -1)
        need_scan = linkable & (
            ((left_idx < 0) & ((right_idx < 0) | (right_left >= 0)))
            | ((left_idx >= 0) & (gather(RT, left_idx, -1) != right_idx))
        )
        o0 = jnp.where(left_idx >= 0, gather(RT, left_idx, -1), anchor0_base)
        o0 = jnp.where(need_scan, o0, -1)

        def origins_equal(ha, ca, ka, hb, cb, kb):
            return (~ha & ~hb) | (ha & hb & (ca == cb) & (ka == kb))

        def scan_cond(carry):
            o, left, conflicting, before, brk = carry
            active = (o >= 0) & (o != right_idx) & (brk == 0)
            return jnp.any(active)

        def scan_body(carry):
            o, left, conflicting, before, brk = carry
            active = (o >= 0) & (o != right_idx) & (brk == 0)
            onehot_o = ((iota_c == o[:, None]) & active[:, None]).astype(I32)
            before = before | onehot_o
            conflicting = conflicting | onehot_o
            o_oc = gather(OC, o, -1)
            o_ok = gather(OK, o, 0)
            same_origin = origins_equal(
                has_origin, origin_client, origin_clock, o_oc >= 0, o_oc, o_ok
            )
            o_rc = gather(RC, o, -1)
            o_rk = gather(RK, o, 0)
            same_ror = origins_equal(has_ror, r_rc, r_rk, o_rc >= 0, o_rc, o_rk)
            o_client = gather(CL, o, -1)
            rank_o = gather_rank(o_client)
            rank_r = gather_rank(jnp.full((DB,), r_client, I32))
            case1_take = same_origin & (rank_o < rank_r)
            case1_break = same_origin & ~case1_take & same_ror
            # case 2: does o's origin sit inside the scanned region?
            oo_idx, oo_found = find_slot(o_oc, o_ok, active & (o_oc >= 0))
            # per-doc membership of oo_idx in before/conflicting
            in_before = oo_found & (
                jnp.sum(jnp.where(iota_c == oo_idx[:, None], before, 0), axis=1) > 0
            )
            in_conflicting = oo_found & (
                jnp.sum(jnp.where(iota_c == oo_idx[:, None], conflicting, 0), axis=1)
                > 0
            )
            case2_take = ~same_origin & in_before & ~in_conflicting
            case2_break = ~same_origin & ~in_before

            take = (case1_take | case2_take) & active
            left = jnp.where(take, o, left)
            conflicting = jnp.where(take[:, None], 0, conflicting)
            brk = brk | ((case1_break | case2_break) & active).astype(I32)
            o_next = gather(RT, o, -1)
            o = jnp.where(active & (brk == 0), o_next, o)
            return (o, left, conflicting, before, brk)

        zeros = jnp.zeros((DB, C), I32)
        _, left_scanned, _, _, _ = jax.lax.while_loop(
            scan_cond,
            scan_body,
            (o0, left_idx, zeros, zeros, jnp.zeros((DB,), I32)),
        )
        left_idx = jnp.where(need_scan, left_scanned, left_idx)

        j = n_blocks()
        overflow = do & (j >= C)
        do = do & (j < C)
        linkable = linkable & (j < C)

        has_left = linkable & (left_idx >= 0)
        right_final = jnp.where(
            has_left, gather(RT, left_idx, -1), jnp.where(linkable, anchor0_base, -1)
        )
        put(RT, left_idx, j, has_left)
        # sequence rows with no left become the head: the root start, or
        # the parent branch's head column (map rows never touch the head)
        new_head = linkable & ~has_left & ~is_map
        meta_ref[:, M_START] = jnp.where(
            new_head & (parent_row < 0), j, meta_ref[:, M_START]
        )
        put(HD, parent_row, j, new_head & (parent_row >= 0))
        put(LT, right_final, j, linkable & (right_final >= 0))

        # self-delete on arrival (parity: block.rs:751-765): a row under a
        # tombstoned parent, or a map row landing with a right neighbor (a
        # losing concurrent write), integrates directly as deleted
        parent_deleted = (parent_row >= 0) & (gather(DL, parent_row, 0) == 1)
        dead_on_arrival = linkable & (
            parent_deleted | (is_map & (right_final >= 0))
        )
        row_deleted = is_gc | (r_kind == CONTENT_DELETED) | dead_on_arrival
        row_countable = (
            ~row_deleted & (r_kind != CONTENT_FORMAT) & (r_kind != CONTENT_MOVE)
        )

        put_many(
            j,
            do,
            [
                (CL, jnp.full((DB,), r_client, I32)),
                (CK, clock),
                (LN, length),
                (OC, jnp.where(has_origin, origin_client, -1)),
                (OK, jnp.where(has_origin, origin_clock, 0)),
                (RC, jnp.full((DB,), jnp.where(has_ror, r_rc, -1), I32)),
                (RK, jnp.full((DB,), jnp.where(has_ror, r_rk, 0), I32)),
                (LT, jnp.where(linkable, left_idx, -1)),
                (RT, jnp.where(linkable, right_final, -1)),
                (DL, row_deleted.astype(I32)),
                (CN, row_countable.astype(I32)),
                (KD, jnp.full((DB,), r_kind, I32)),
                (RF, jnp.full((DB,), r_ref, I32)),
                (OF, c_off),
                (KEY, key_v),
                (PA, parent_row),
                (HD, jnp.full((DB,), -1, I32)),
            ],
        )
        # a map row that became its chain's tail is the key's live value;
        # the previous winner — its immediate left — gets tombstoned
        # (parity: block.rs:637-659)
        new_tail = linkable & is_map & (right_final < 0)
        put(DL, left_idx, jnp.ones((DB,), I32), new_tail & has_left)
        meta_ref[:, M_NBLOCKS] = n_blocks() + do.astype(I32)
        meta_ref[:, M_ERROR] = (
            meta_ref[:, M_ERROR]
            | jnp.where(overflow, ERR_CAPACITY, 0)
            | jnp.where(missing, ERR_MISSING_DEP, 0)
        )

    def delete_range(s, r):
        client = dels_ref[s, r, 0]
        start = dels_ref[s, r, 1]
        end = dels_ref[s, r, 2]
        enable = jnp.ones((DB,), bool)
        client_v = jnp.full((DB,), client, I32)
        start_v = jnp.full((DB,), start, I32)
        end_v = jnp.full((DB,), end, I32)
        # split head
        i, found = find_slot(client_v, start_v, enable)
        i_ok = found & (gather(DL, i, 1) == 0)
        split(i, start_v - gather(CK, i, 0), i_ok)
        # split tail
        k, kfound = find_slot(client_v, end_v - 1, enable)
        k_ok = kfound & (gather(DL, k, 1) == 0)
        split(k, end_v - gather(CK, k, 0), k_ok)
        # mark covered blocks deleted
        valid = iota_c < n_blocks()[:, None]
        m = (
            valid
            & (col(CL) == client)
            & (col(CK) >= start)
            & (col(CK) + col(LN) <= end)
        )
        cols_ref[DL] = jnp.where(m, 1, col(DL))

    def step(s, _):
        def row_body(u, __):
            @pl.when(rows_ref[s, u, 14] == 1)
            def _():
                integrate_row(s, u)

            return 0

        jax.lax.fori_loop(0, U, row_body, 0)

        def del_body(r, __):
            @pl.when(dels_ref[s, r, 3] == 1)
            def _():
                delete_range(s, r)

            return 0

        jax.lax.fori_loop(0, R, del_body, 0)
        return 0

    jax.lax.fori_loop(0, S, step, 0)


@partial(jax.jit, static_argnums=(3, 4), donate_argnums=(0, 1))
def _run(cols, meta, packed, d_block: int, interpret: bool):
    rows, dels, rank = packed
    NC_, D, C = cols.shape
    grid = (D // d_block,)
    rank = rank.reshape(1, -1)
    out = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(rows.shape, lambda d: (0, 0, 0)),
            pl.BlockSpec(dels.shape, lambda d: (0, 0, 0)),
            pl.BlockSpec(rank.shape, lambda d: (0, 0)),
            pl.BlockSpec((NC, d_block, C), lambda d: (0, d, 0)),
            pl.BlockSpec((d_block, M_PAD), lambda d: (d, 0)),
        ],
        out_specs=[
            pl.BlockSpec((NC, d_block, C), lambda d: (0, d, 0)),
            pl.BlockSpec((d_block, M_PAD), lambda d: (d, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(cols.shape, I32),
            jax.ShapeDtypeStruct(meta.shape, I32),
        ],
        input_output_aliases={3: 0, 4: 1},
        interpret=interpret,
        # the doc tile ([NC, d_block, C] i32) plus the conflict-scan's
        # [d_block, C] temporaries are the VMEM tenants; the default 16MB
        # scoped limit caps d_block at 32 for C=2048 — v5e/v6e cores have
        # 128MB VMEM, so let tiles use up to half (d_block=128, the
        # measured sweet spot, needs ~56MB; 256 fits only with a ~118MB
        # limit and compiles pathologically slowly — not worth it)
        compiler_params=None
        if interpret
        else pltpu.CompilerParams(vmem_limit_bytes=64 * 1024 * 1024),
    )(rows, dels, rank, cols, meta)
    return out


def apply_update_stream_fused(
    state: DocStateBatch,
    stream: UpdateBatch,
    client_rank: jax.Array,
    d_block: int = 32,
    interpret: bool = False,
    guard: bool = True,
) -> DocStateBatch:
    """Fused-replay drop-in for `apply_update_stream`: sequence rows, map
    rows (per-key LWW chains), and nested-branch parents all integrate
    in-VMEM. Only move rows are excluded — move-ownership recomputation is
    the XLA path's end-of-update pass.

    Callers that built everything through one `BatchEncoder` can check the
    encoder's stream for moves host-side and pass `guard=False` — the
    default device-side guard costs one host-device sync before launch."""
    if guard and bool(
        jnp.any((stream.kind == CONTENT_MOVE) & stream.valid)
        | jnp.any(state.blocks.kind == CONTENT_MOVE)
    ):
        raise NotImplementedError(
            "apply_update_stream_fused excludes move ranges (move claims "
            "need the XLA path's recompute pass); use apply_update_stream "
            "for streams containing ContentMove"
        )
    cols, meta = pack_state(state)
    D = cols.shape[1]
    if D % d_block != 0:
        raise ValueError(f"n_docs {D} must be a multiple of d_block {d_block}")
    rows, dels = pack_stream(stream)
    cols, meta = _run(cols, meta, (rows, dels, client_rank), d_block, interpret)
    return unpack_state(cols, meta, state)
