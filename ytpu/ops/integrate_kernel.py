"""Fused Pallas integrate kernel — the whole update-stream replay in VMEM.

The XLA path (`ytpu.models.batch_doc.apply_update_stream`) streams the full
[docs, capacity] block state through HBM once per update step (every scatter
and select materializes columns). This kernel removes that bottleneck:

- the doc axis is tiled (D_BLK docs per grid program) and each tile's block
  columns are DMA'd into VMEM **once**;
- the *entire* S-step update stream is integrated in-core (YATA conflict
  scans, splits, delete ranges — all vectorized over the doc sublanes with
  one-hot selects over the capacity lanes);
- the tile is written back **once**. HBM traffic drops from
  O(S · docs · capacity) to O(docs · capacity + S).

Semantics mirror `_integrate_row` / `_apply_delete_range` in batch_doc.py
(reference: block.rs:482-769, transaction.rs:472-575); parity is enforced in
tests/test_pallas_kernel.py against both the XLA path and the host oracle.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial
from typing import Optional, Tuple

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ytpu.core.content import (
    BLOCK_GC,
    BLOCK_ROOT_ANCHOR,
    CONTENT_DELETED,
    CONTENT_FORMAT,
    CONTENT_MOVE,
)
from ytpu.models.batch_doc import (
    SCAN_REC_CHEAP,
    SCAN_REC_CHEAP_TRIPS,
    SCAN_REC_MAX,
    SCAN_REC_WIDE,
    SCAN_REC_WIDE_TRIPS,
    SCAN_REC_WIDTH_SUM,
    SCAN_REC_WORDS,
    SCAN_WIDTH_BUCKETS,
    BlockCols,
    DocStateBatch,
    UpdateBatch,
    commit_fold_blocks,
    merge_scan_records,
    scan_tier_plan,
    scan_width_bucket,
    scan_width_quantile,
)

__all__ = [
    "pack_state",
    "unpack_state",
    "pack_stream",
    "apply_update_stream_fused",
    "xla_chunk_step",
    "replay_chunk_program",
    "replay_chunk_program_raw",
    "PackedReplayDriver",
    "ReplayChunkStats",
    "replay_stream_fused",
    "LANE_LADDER",
    "ReplayFault",
    "lane_family",
    "effective_lane",
    "demote_lane",
    "reset_lane_health",
    "lane_health",
    "is_device_fault",
    "N_READOUT",
    "packed_commitments",
]

I32 = jnp.int32

# column indices in the packed [NC, D, C] state
(
    CL,  # client
    CK,  # clock
    LN,  # length
    OC,  # origin client
    OK,  # origin clock
    RC,  # right-origin client
    RK,  # right-origin clock
    LT,  # left link
    RT,  # right link
    DL,  # deleted flag
    CN,  # countable flag
    KD,  # content kind
    RF,  # content ref
    OF,  # content offset
    KEY,  # interned parent_sub (-1 = sequence item)
    PA,  # parent ContentType row (-1 = root)
    HD,  # child-sequence head (ContentType rows)
    MV,  # slot of the move row owning this row (-1 = unowned)
    MSC,  # move rows: range-start id client (-1 = branch-scoped bound)
    MSK,  # move rows: range-start id clock
    MSA,  # move rows: start assoc (>= 0 after, < 0 before)
    MEC,  # move rows: range-end id client
    MEK,  # move rows: range-end id clock
    MEA,  # move rows: end assoc
    MPR,  # move rows: conflict priority
    OS,  # cached origin slot (batch_doc.BlockCols.origin_slot). The kernel
    # itself neither reads nor writes this plane — it rides the packed
    # state so the XLA chunk lane (replay._xla_chunk_step) carries the
    # live cache through pack/unpack at zero cost; kernel-created rows
    # leave it stale, so the fused lane recomputes it wholesale at
    # unpack (apply_update_stream_fused).
) = range(26)
NC = 26

# meta columns in the packed [D, 32] array (padded to a TPU-friendly lane dim)
# M_MDIRTY: move ownership must be recomputed for this doc at step end (a
# move row arrived, an insert straddled differently-owned neighbors, or a
# delete tombstoned a live move — the moves_dirty of batch_doc)
M_START, M_NBLOCKS, M_ERROR, M_MDIRTY = 0, 1, 2, 3
# conflict-scan attribution (ISSUE-11/12): per-doc pow2 bucket counts,
# max width, tier-occupancy and trip-accounting words ride the meta
# tile, accumulated INSIDE the integrate scan (both lanes) so the totals
# survive chunking/compaction/growth for free and surface only through
# the existing lazy readout — never a new sync. Layout mirrors the
# batch_doc.SCAN_REC_* record word-for-word at offset M_HIST0.
M_HIST0 = 4
M_SCANW_MAX = M_HIST0 + SCAN_REC_MAX  # 12: observed max scan width
M_TIER_CHEAP = M_HIST0 + SCAN_REC_CHEAP  # 13: scans resolved cheap-tier
M_TIER_WIDE = M_HIST0 + SCAN_REC_WIDE  # 14: scans escalated to wide tier
M_CHEAP_TRIPS = M_HIST0 + SCAN_REC_CHEAP_TRIPS  # 15: Σ min(width, cheap)
M_WIDE_TRIPS = M_HIST0 + SCAN_REC_WIDE_TRIPS  # 16: Σ wide block trips
M_WIDTH_SUM = M_HIST0 + SCAN_REC_WIDTH_SUM  # 17: Σ width (serial-equiv trips)
M_SCAN_END = M_HIST0 + SCAN_REC_WORDS  # 18 (exclusive)
M_PAD = 32  # the ISSUE-12 trip words outgrew the 16-wide tile (was 8 pre-PR-11)

#: words in the per-chunk lazy readout: the original [3] occupancy/error
#: words + the full scan record (buckets, max, tiers, trips) + the
#: ISSUE-13 state-commitment word (wrap-sum over docs of the per-doc
#: homomorphic lattice digest, `batch_doc.commit_fold_blocks`) + the
#: ISSUE-18 capacity-ledger words (see LEDGER_WORDS)
#: capacity-ledger words (ISSUE-18): Σ occupied rows over docs,
#: Σ dead (tombstoned, GC-able) rows, and the max per-doc dead count —
#: the occupancy/fragmentation gauges ride the SAME lazy readout
#: future, so the zero-sync invariant (`test_async_overlap`) holds
LEDGER_WORDS = 3
N_READOUT = 3 + SCAN_REC_WORDS + 1 + LEDGER_WORDS

ERR_CAPACITY = 1
ERR_MISSING_DEP = 2


def pack_state(state: DocStateBatch) -> Tuple[jax.Array, jax.Array]:
    bl = state.blocks
    cols = jnp.stack(
        [
            bl.client,
            bl.clock,
            bl.length,
            bl.origin_client,
            bl.origin_clock,
            bl.ror_client,
            bl.ror_clock,
            bl.left,
            bl.right,
            bl.deleted.astype(I32),
            bl.countable.astype(I32),
            bl.kind,
            bl.content_ref,
            bl.content_off,
            bl.key,
            bl.parent,
            bl.head,
            bl.moved,
            bl.mv_sc,
            bl.mv_sk,
            bl.mv_sa,
            bl.mv_ec,
            bl.mv_ek,
            bl.mv_ea,
            bl.mv_prio,
            bl.origin_slot,
        ]
    )  # [NC, D, C]
    D = state.start.shape[0]
    meta = jnp.zeros((D, M_PAD), I32)
    meta = meta.at[:, M_START].set(state.start)
    meta = meta.at[:, M_NBLOCKS].set(state.n_blocks)
    meta = meta.at[:, M_ERROR].set(state.error)
    return cols, meta


def unpack_state(
    cols: jax.Array, meta: jax.Array, state: DocStateBatch
) -> DocStateBatch:
    """Rebuild state from kernel outputs."""
    del state  # all columns now live in the packed buffers
    blocks = BlockCols(
        client=cols[CL],
        clock=cols[CK],
        length=cols[LN],
        origin_client=cols[OC],
        origin_clock=cols[OK],
        ror_client=cols[RC],
        ror_clock=cols[RK],
        left=cols[LT],
        right=cols[RT],
        deleted=cols[DL].astype(bool),
        countable=cols[CN].astype(bool),
        kind=cols[KD],
        content_ref=cols[RF],
        content_off=cols[OF],
        key=cols[KEY],
        parent=cols[PA],
        head=cols[HD],
        moved=cols[MV],
        mv_sc=cols[MSC],
        mv_sk=cols[MSK],
        mv_sa=cols[MSA],
        mv_ec=cols[MEC],
        mv_ek=cols[MEK],
        mv_ea=cols[MEA],
        mv_prio=cols[MPR],
        origin_slot=cols[OS],
    )
    return DocStateBatch(
        blocks=blocks,
        start=meta[:, M_START],
        n_blocks=meta[:, M_NBLOCKS],
        error=meta[:, M_ERROR],
    )


def pack_stream(stream: UpdateBatch) -> Tuple[jax.Array, jax.Array]:
    """Stacked doc-axis-free stream → rows [S, U, 23] / dels [S, R, 4] i32."""
    rows = jnp.stack(
        [
            stream.client,
            stream.clock,
            stream.length,
            stream.origin_client,
            stream.origin_clock,
            stream.ror_client,
            stream.ror_clock,
            stream.kind,
            stream.content_ref,
            stream.content_off,
            stream.key,
            stream.p_tag,
            stream.p_client,
            stream.p_clock,
            stream.valid.astype(I32),
            stream.mv_sc,
            stream.mv_sk,
            stream.mv_sa,
            stream.mv_ec,
            stream.mv_ek,
            stream.mv_ea,
            stream.mv_prio,
            stream.p_root,
        ],
        axis=-1,
    )  # [S, U, 23]
    dels = jnp.stack(
        [
            stream.del_client,
            stream.del_start,
            stream.del_end,
            stream.del_valid.astype(I32),
        ],
        axis=-1,
    )  # [S, R, 4]
    return rows, dels


def _kernel(
    rows_ref,
    dels_ref,
    rank_ref,
    _cols_in,
    _meta_in,
    cols_ref,
    meta_ref,
    *,
    phases: int = 3,
    row_phase: int = 4,
    scan_plan: Tuple[int, int] = (32, 8),
):
    """One doc tile: integrate the whole stream in VMEM.

    cols_ref: [NC, DB, C] out-ref aliased to the input (holds the state),
    meta_ref: [DB, M_PAD=32] aliased (cols 0-3 start/n_blocks/error/
    mdirty; cols M_HIST0..M_SCAN_END the scan record); rows_ref:
    [S, U, 23], dels_ref: [S, R, 4], rank_ref: [1, K].

    `phases` / `row_phase` are HARDWARE-BISECT hooks (trace-time static,
    threaded from `apply_update_stream_fused`): they truncate the kernel
    after the row loop / delete loop (phases) or mid-`integrate_row`
    (row_phase) so a Mosaic miscompile or device fault can be localized.
    Production callers leave the defaults (full kernel); partial values
    corrupt state by design and must never ship.

    `scan_plan = (cheap_bound, wide_unroll)` is the ISSUE-12 two-tier
    conflict-scan static: the cheap tier keeps the original one-
    candidate-per-trip loop up to `cheap_bound` trips, the wide tier
    unrolls `wide_unroll` masked candidate steps per while trip for the
    deep-conflict tail. A changed plan recompiles (the public entries
    re-read the env per call, like YTPU_FUSED_VMEM_MB).
    """
    S, U, _ = rows_ref.shape
    R = dels_ref.shape[1]
    DB = cols_ref.shape[1]
    C = cols_ref.shape[2]

    # Initialize the aliased out-refs EXPLICITLY from the in-refs. On
    # standard backends (and in interpret mode) an aliased output's VMEM
    # window starts pre-filled with the input block, so this copy is a
    # no-op; the axon remote backend instead hands the output a buffer
    # whose writeback reads 128 lanes off when the kernel never stores it
    # (bisected 2026-08-01: benches/plane_rmw_repro3.py `v_multi` — a
    # never-stored aliased output returns the whole tile rotated by one
    # lane group; the state-column corruption of mosaic_ladder rung 9
    # was exactly this). Reading the IN-refs is reliable on both.
    for _i in range(cols_ref.shape[0]):
        cols_ref[_i] = _cols_in[_i]
    meta_ref[:, :] = _meta_in[:, :]

    iota_c = jax.lax.broadcasted_iota(I32, (DB, C), 1)

    def col(i):
        return cols_ref[i]

    def mrow(mask):
        """(DB,) bool -> (DB, 1) bool. Mosaic cannot insert a minor dim on
        an i1 vector ("only supported for 32-bit types"), so widen to i32,
        insert, and compare back down."""
        return mask.astype(I32)[:, None] > 0

    def gather(i, idx, fill):
        """Per-doc element col(i)[d, idx[d]] with idx < 0 -> fill."""
        onehot = iota_c == idx[:, None]
        v = jnp.sum(jnp.where(onehot, col(i), 0), axis=1)
        return jnp.where(idx >= 0, v, fill)

    def put(i, idx, val, active):
        """col(i)[d, idx[d]] = val[d] where active[d] & idx valid."""
        mask = (iota_c == idx[:, None]) & mrow(active) & (idx[:, None] >= 0)
        cols_ref[i] = jnp.where(mask, val[:, None], col(i))

    def put_many(idx, active, writes):
        """Write several columns at one slot, computing the mask once.

        `writes` is [(col_idx, val_vector), ...]; same semantics as `put`."""
        mask = (iota_c == idx[:, None]) & mrow(active) & (idx[:, None] >= 0)
        for i, val in writes:
            cols_ref[i] = jnp.where(mask, val[:, None], col(i))

    def n_blocks():
        return meta_ref[:, M_NBLOCKS]

    K = rank_ref.shape[1]
    iota_k = jax.lax.broadcasted_iota(I32, (DB, K), 1)

    def gather_rank(client_v):
        """rank_ref[0, client_v[d]] per doc (one-hot gather)."""
        onehot = iota_k == jnp.maximum(client_v, 0)[:, None]
        return jnp.sum(jnp.where(onehot, rank_ref[0][None, :], 0), axis=1)

    def find_slot(client_v, clock_v, enable):
        """(idx[DB], found[DB]) of the block covering (client, clock);
        `client_v`/`clock_v` are per-doc (DB,) vectors."""
        valid = iota_c < n_blocks()[:, None]
        m = (
            valid
            & (col(CL) == client_v[:, None])
            & (col(CK) <= clock_v[:, None])
            & (clock_v[:, None] < col(CK) + col(LN))
            & mrow(enable)
        )
        # integer argmax is unsupported in Mosaic: min-reduce the indices
        idx = jnp.min(jnp.where(m, iota_c, C), axis=1).astype(I32)
        found = idx < C
        return jnp.where(found, idx, -1), found

    def client_clock(client_s):
        valid = iota_c < n_blocks()[:, None]
        m = valid & (col(CL) == client_s)
        return jnp.max(jnp.where(m, col(CK) + col(LN), 0), axis=1)

    def split(i_idx, off, want):
        """Split block i at off (per doc); returns right-half slot (or i).

        The whole write phase sits behind `pl.when(any(do))`: the hot replay
        case (appends, whole-block deletes) needs no split in *any* doc of
        the tile, so the ~30 [DB, C] sweeps below are skipped entirely."""
        length_i = gather(LN, i_idx, 0)
        do = want & (i_idx >= 0) & (off > 0) & (off < length_i)
        j = n_blocks()
        overflow = do & (j >= C)
        do = do & (j < C)
        # the error record must not sit behind the lazy write phase: a tile
        # where every needed split overflows has all-False `do`
        meta_ref[:, M_ERROR] = meta_ref[:, M_ERROR] | jnp.where(
            overflow, ERR_CAPACITY, 0
        )

        @pl.when(jnp.any(do))
        def _():
            right_i = gather(RT, i_idx, -1)
            # new row j = right half (moved inherits — splice parity; the
            # mv_* range fields stay empty: length-1 move rows never split)
            put_many(
                j,
                do,
                [
                    (CL, gather(CL, i_idx, -1)),
                    (CK, gather(CK, i_idx, 0) + off),
                    (LN, length_i - off),
                    (OC, gather(CL, i_idx, -1)),
                    (OK, gather(CK, i_idx, 0) + off - 1),
                    (RC, gather(RC, i_idx, -1)),
                    (RK, gather(RK, i_idx, 0)),
                    (LT, i_idx),
                    (RT, right_i),
                    (DL, gather(DL, i_idx, 0)),
                    (CN, gather(CN, i_idx, 0)),
                    (KD, gather(KD, i_idx, 0)),
                    (RF, gather(RF, i_idx, -1)),
                    (OF, gather(OF, i_idx, 0) + off),
                    (KEY, gather(KEY, i_idx, -1)),
                    (PA, gather(PA, i_idx, -1)),
                    (HD, gather(HD, i_idx, -1)),
                    (MV, gather(MV, i_idx, -1)),
                    (MSC, jnp.full((DB,), -1, I32)),
                    (MSK, jnp.zeros((DB,), I32)),
                    (MSA, jnp.zeros((DB,), I32)),
                    (MEC, jnp.full((DB,), -1, I32)),
                    (MEK, jnp.zeros((DB,), I32)),
                    (MEA, jnp.zeros((DB,), I32)),
                    (MPR, jnp.full((DB,), -1, I32)),
                ],
            )
            # fix left half + old right neighbor
            put_many(i_idx, do, [(LN, off), (RT, j)])
            put(LT, right_i, j, do & (right_i >= 0))
            meta_ref[:, M_NBLOCKS] = n_blocks() + do.astype(I32)

        return jnp.where(do, j, i_idx)

    def clean_end(client_s, clock_v, enable):
        i, found = find_slot(client_s, clock_v, enable)
        off = clock_v - gather(CK, i, 0) + 1
        split(i, off, enable & found)
        return i, found

    def clean_start(client_s, clock_v, enable):
        i, found = find_slot(client_s, clock_v, enable)
        off = clock_v - gather(CK, i, 0)
        j = split(i, off, enable & found)
        return jnp.where((i >= 0) & (off > 0), j, i), found

    def integrate_row(s, u):
        r_client = rows_ref[s, u, 0]
        r_clock = rows_ref[s, u, 1]
        r_len = rows_ref[s, u, 2]
        r_oc = rows_ref[s, u, 3]
        r_ok = rows_ref[s, u, 4]
        r_rc = rows_ref[s, u, 5]
        r_rk = rows_ref[s, u, 6]
        r_kind = rows_ref[s, u, 7]
        r_ref = rows_ref[s, u, 8]
        r_off = rows_ref[s, u, 9]
        r_key = rows_ref[s, u, 10]
        r_ptag = rows_ref[s, u, 11]
        r_pclient = rows_ref[s, u, 12]
        r_pclock = rows_ref[s, u, 13]
        r_mv_sc = rows_ref[s, u, 15]
        r_mv_sk = rows_ref[s, u, 16]
        r_mv_sa = rows_ref[s, u, 17]
        r_mv_ec = rows_ref[s, u, 18]
        r_mv_ek = rows_ref[s, u, 19]
        r_mv_ea = rows_ref[s, u, 20]
        r_mv_prio = rows_ref[s, u, 21]
        r_proot = rows_ref[s, u, 22]
        is_move_row = r_kind == CONTENT_MOVE

        local = client_clock(r_client)  # (DB,)
        applicable = local >= r_clock
        missing = ~applicable
        offset = local - r_clock
        dup = applicable & (offset >= r_len)
        do = applicable & ~dup

        clock = r_clock + offset
        length = r_len - offset
        c_off = r_off + offset
        has_origin = (offset > 0) | (r_oc >= 0)
        origin_client = jnp.where(offset > 0, r_client, r_oc)
        origin_clock = jnp.where(offset > 0, clock - 1, r_ok)
        has_ror = r_rc >= 0

        is_gc = r_kind == BLOCK_GC
        linkable = do & ~is_gc

        if row_phase < 2:
            meta_ref[:, M_ERROR] = meta_ref[:, M_ERROR] | jnp.where(
                missing, ERR_MISSING_DEP, 0
            )
            return

        left_idx, lfound = clean_end(
            origin_client, origin_clock, linkable & has_origin
        )
        right_idx, rfound = clean_start(
            jnp.full((DB,), r_rc, I32), jnp.full((DB,), r_rk, I32),
            linkable & has_ror,
        )
        left_idx = jnp.where(linkable & has_origin, left_idx, -1)
        right_idx = jnp.where(linkable & has_ror, right_idx, -1)

        anchor_missing = (linkable & has_origin & (left_idx < 0)) | (
            linkable & has_ror & (right_idx < 0)
        )
        missing = missing | anchor_missing
        linkable = linkable & ~anchor_missing

        # parent branch (parity: block.rs:503-523): p_tag 2 = nested branch
        # by ContentType item id; 1 = root; 0 = inherit from the resolved
        # left (else right) anchor
        parent_slot, _pfound = find_slot(
            jnp.full((DB,), r_pclient, I32),
            jnp.full((DB,), r_pclock, I32),
            linkable & (r_ptag == 2),
        )
        left_parent = gather(PA, left_idx, -1)
        right_parent = gather(PA, right_idx, -1)
        inherited_parent = jnp.where(left_idx >= 0, left_parent, right_parent)
        # named-root parents: primary (p_root < 0) -> the doc sequence;
        # non-primary -> the BLOCK_ROOT_ANCHOR row keyed by the root id
        # (created host-side before the apply; absence = missing dep)
        anchor_m = (
            (iota_c < n_blocks()[:, None])
            & (col(KD) == BLOCK_ROOT_ANCHOR)
            & (col(KEY) == r_proot)
        )
        anchor_idx = jnp.min(jnp.where(anchor_m, iota_c, C), axis=1).astype(I32)
        anchor_found = anchor_idx < C
        root_row = jnp.where(
            (r_proot >= 0) & anchor_found, anchor_idx, -1
        )
        parent_row = jnp.where(
            r_ptag == 2,
            parent_slot,
            jnp.where(r_ptag == 1, root_row, inherited_parent),
        )
        parent_missing = linkable & (
            ((r_ptag == 2) & (parent_slot < 0))
            | ((r_ptag == 1) & (r_proot >= 0) & ~anchor_found)
        )
        missing = missing | parent_missing
        linkable = linkable & ~parent_missing
        if row_phase < 3:
            return

        # parent_sub: inherited from the anchors when omitted on the wire
        # (parity: block.rs:604-612)
        left_key = gather(KEY, left_idx, -1)
        right_key = gather(KEY, right_idx, -1)
        key_v = jnp.where(
            r_key >= 0,
            jnp.full((DB,), r_key, I32),
            jnp.where(left_key >= 0, left_key, right_key),
        )
        is_map = key_v >= 0

        # map rows anchor on their (parent, key) chain's leftmost item
        # (parity: block.rs:541-551); sequence rows on the parent's head
        valid_slots = iota_c < n_blocks()[:, None]
        chain_mask = (
            valid_slots
            & (col(KEY) == key_v[:, None])
            & (col(PA) == parent_row[:, None])
            & (col(LT) == -1)
            & mrow(is_map)
        )
        chain_idx = jnp.min(jnp.where(chain_mask, iota_c, C), axis=1).astype(I32)
        chain_head = jnp.where(chain_idx < C, chain_idx, -1)
        seq_head = jnp.where(
            parent_row >= 0, gather(HD, parent_row, -1), meta_ref[:, M_START]
        )
        anchor0_base = jnp.where(is_map, chain_head, seq_head)

        right_left = gather(LT, right_idx, -1)
        need_scan = linkable & (
            ((left_idx < 0) & ((right_idx < 0) | (right_left >= 0)))
            | ((left_idx >= 0) & (gather(RT, left_idx, -1) != right_idx))
        )
        o0 = jnp.where(left_idx >= 0, gather(RT, left_idx, -1), anchor0_base)
        o0 = jnp.where(need_scan, o0, -1)

        def origins_equal(ha, ca, ka, hb, cb, kb):
            return (~ha & ~hb) | (ha & hb & (ca == cb) & (ka == kb))

        cheap_bound, wide_unroll = scan_plan

        def scan_step(carry):
            """One candidate step, fully masked by `active` (a resolved
            doc no-ops through it) — composes both as a whole cheap-tier
            trip and as one sub-step of a wide-tier unrolled block.
            Every carry element is a (DB,)- or (DB, C)-shaped VECTOR:
            the rung-3/5 scalar-fori-carry miscompile family
            (docs/known_backend_issues.md) is never entered."""
            o, left, conflicting, before, brk, width = carry
            active = (o >= 0) & (o != right_idx) & (brk == 0)
            width = width + active.astype(I32)
            onehot_o = ((iota_c == o[:, None]) & mrow(active)).astype(I32)
            before = before | onehot_o
            conflicting = conflicting | onehot_o
            o_oc = gather(OC, o, -1)
            o_ok = gather(OK, o, 0)
            same_origin = origins_equal(
                has_origin, origin_client, origin_clock, o_oc >= 0, o_oc, o_ok
            )
            o_rc = gather(RC, o, -1)
            o_rk = gather(RK, o, 0)
            same_ror = origins_equal(has_ror, r_rc, r_rk, o_rc >= 0, o_rc, o_rk)
            o_client = gather(CL, o, -1)
            rank_o = gather_rank(o_client)
            rank_r = gather_rank(jnp.full((DB,), r_client, I32))
            case1_take = same_origin & (rank_o < rank_r)
            case1_break = same_origin & ~case1_take & same_ror
            # case 2: does o's origin sit inside the scanned region?
            oo_idx, oo_found = find_slot(o_oc, o_ok, active & (o_oc >= 0))
            # per-doc membership of oo_idx in before/conflicting
            in_before = oo_found & (
                jnp.sum(jnp.where(iota_c == oo_idx[:, None], before, 0), axis=1) > 0
            )
            in_conflicting = oo_found & (
                jnp.sum(jnp.where(iota_c == oo_idx[:, None], conflicting, 0), axis=1)
                > 0
            )
            case2_take = ~same_origin & in_before & ~in_conflicting
            case2_break = ~same_origin & ~in_before

            take = (case1_take | case2_take) & active
            left = jnp.where(take, o, left)
            conflicting = jnp.where(mrow(take), 0, conflicting)
            brk = brk | ((case1_break | case2_break) & active).astype(I32)
            o_next = gather(RT, o, -1)
            o = jnp.where(active & (brk == 0), o_next, o)
            return (o, left, conflicting, before, brk, width)

        # --- two-tier dispatch (ISSUE-12) ---
        # CHEAP tier: the original one-candidate-per-trip loop, bounded.
        # All active docs advance in lockstep, so `width` doubles as the
        # tier's trip counter (uniform across active docs) — the bound
        # compare folds into the cond instead of a new carry element.
        def cheap_cond(carry):
            o, left, conflicting, before, brk, width = carry
            active = (o >= 0) & (o != right_idx) & (brk == 0)
            return jnp.any(active & (width < cheap_bound))

        zeros = jnp.zeros((DB, C), I32)
        carry = jax.lax.while_loop(
            cheap_cond,
            scan_step,
            (o0, left_idx, zeros, zeros, jnp.zeros((DB,), I32),
             jnp.zeros((DB,), I32)),
        )

        # WIDE tier: still-unresolved (deep-conflict) docs continue with
        # `wide_unroll` masked candidate steps per while trip — whole-
        # block membership/origin tests per dispatch instead of one
        # element per trip. `wtrips` counts per-doc block trips (the
        # tier-occupancy sample); a (DB,) vector like every other carry.
        def wide_cond(carry):
            inner, wtrips = carry
            o, left, conflicting, before, brk, width = inner
            return jnp.any((o >= 0) & (o != right_idx) & (brk == 0))

        def wide_body(carry):
            inner, wtrips = carry
            o, left, conflicting, before, brk, width = inner
            entered = (o >= 0) & (o != right_idx) & (brk == 0)
            wtrips = wtrips + entered.astype(I32)
            for _ in range(wide_unroll):
                inner = scan_step(inner)
            return inner, wtrips

        (_, left_scanned, _, _, _, scan_width), wide_trips = (
            jax.lax.while_loop(
                wide_cond, wide_body, (carry, jnp.zeros((DB,), I32))
            )
        )
        left_idx = jnp.where(need_scan, left_scanned, left_idx)
        # conflict-tail attribution (ISSUE-11): fold this row's per-doc
        # scan width into the pow2 histogram riding the meta tile — a
        # handful of (DB,)-wide compares per row, no extra HBM traffic,
        # materialized host-side only when the lazy readout is pulled
        wb = jnp.maximum(scan_width, 0)
        # the SAME bucket function as the packed-XLA lane (pure jnp ops,
        # vectorizes over the doc sublanes) — one definition, so the two
        # lanes' histograms can never drift apart
        bucket = scan_width_bucket(wb)
        for _k in range(SCAN_WIDTH_BUCKETS):
            meta_ref[:, M_HIST0 + _k] = meta_ref[:, M_HIST0 + _k] + (
                need_scan & (bucket == _k)
            ).astype(I32)
        meta_ref[:, M_SCANW_MAX] = jnp.maximum(
            meta_ref[:, M_SCANW_MAX], jnp.where(need_scan, wb, 0)
        )
        # tier occupancy + trip accounting (ISSUE-12): identical word
        # semantics to the packed-XLA lane's _fold_scan_width, so the
        # readout record is lane-agnostic (cheap trips use the SAME
        # min(width, bound) accounting — per-doc attribution of the
        # lockstep tile loop matches the vmapped XLA lane exactly)
        wide_used = need_scan & (wide_trips > 0)
        meta_ref[:, M_TIER_CHEAP] = meta_ref[:, M_TIER_CHEAP] + (
            need_scan & ~wide_used
        ).astype(I32)
        meta_ref[:, M_TIER_WIDE] = (
            meta_ref[:, M_TIER_WIDE] + wide_used.astype(I32)
        )
        meta_ref[:, M_CHEAP_TRIPS] = meta_ref[:, M_CHEAP_TRIPS] + jnp.where(
            need_scan, jnp.minimum(wb, cheap_bound), 0
        )
        meta_ref[:, M_WIDE_TRIPS] = meta_ref[:, M_WIDE_TRIPS] + jnp.where(
            need_scan, wide_trips, 0
        )
        meta_ref[:, M_WIDTH_SUM] = meta_ref[:, M_WIDTH_SUM] + jnp.where(
            need_scan, wb, 0
        )
        if row_phase < 4:
            return

        j = n_blocks()
        overflow = do & (j >= C)
        do = do & (j < C)
        linkable = linkable & (j < C)

        has_left = linkable & (left_idx >= 0)
        right_final = jnp.where(
            has_left, gather(RT, left_idx, -1), jnp.where(linkable, anchor0_base, -1)
        )
        put(RT, left_idx, j, has_left)
        # sequence rows with no left become the head: the root start, or
        # the parent branch's head column (map rows never touch the head)
        new_head = linkable & ~has_left & ~is_map
        meta_ref[:, M_START] = jnp.where(
            new_head & (parent_row < 0), j, meta_ref[:, M_START]
        )
        put(HD, parent_row, j, new_head & (parent_row >= 0))
        put(LT, right_final, j, linkable & (right_final >= 0))

        # self-delete on arrival (parity: block.rs:751-765): a row under a
        # tombstoned parent, or a map row landing with a right neighbor (a
        # losing concurrent write), integrates directly as deleted
        parent_deleted = (parent_row >= 0) & (gather(DL, parent_row, 0) == 1)
        dead_on_arrival = linkable & (
            parent_deleted | (is_map & (right_final >= 0))
        )
        row_deleted = is_gc | (r_kind == CONTENT_DELETED) | dead_on_arrival
        row_countable = (
            ~row_deleted & (r_kind != CONTENT_FORMAT) & (r_kind != CONTENT_MOVE)
        )

        # moved-range inheritance (parity: block.rs:677-702): an insert
        # between rows owned by the same move inherits the owner; a
        # mismatch marks the doc for the end-of-step recompute
        left_moved = jnp.where(has_left, gather(MV, left_idx, -1), -1)
        right_moved = jnp.where(
            right_final >= 0, gather(MV, right_final, -1), -1
        )
        inherit_moved = jnp.where(left_moved == right_moved, left_moved, -1)
        moved_conflict = linkable & (left_moved != right_moved)
        meta_ref[:, M_MDIRTY] = meta_ref[:, M_MDIRTY] | (
            (moved_conflict | (do & is_move_row)).astype(I32)
        )

        put_many(
            j,
            do,
            [
                (CL, jnp.full((DB,), r_client, I32)),
                (CK, clock),
                (LN, length),
                (OC, jnp.where(has_origin, origin_client, -1)),
                (OK, jnp.where(has_origin, origin_clock, 0)),
                (RC, jnp.full((DB,), jnp.where(has_ror, r_rc, -1), I32)),
                (RK, jnp.full((DB,), jnp.where(has_ror, r_rk, 0), I32)),
                (LT, jnp.where(linkable, left_idx, -1)),
                (RT, jnp.where(linkable, right_final, -1)),
                (DL, row_deleted.astype(I32)),
                (CN, row_countable.astype(I32)),
                (KD, jnp.full((DB,), r_kind, I32)),
                (RF, jnp.full((DB,), r_ref, I32)),
                (OF, c_off),
                (KEY, key_v),
                (PA, parent_row),
                (HD, jnp.full((DB,), -1, I32)),
                (MV, jnp.where(linkable, inherit_moved, -1)),
                (MSC, jnp.full((DB,), jnp.where(is_move_row, r_mv_sc, -1), I32)),
                (MSK, jnp.full((DB,), jnp.where(is_move_row, r_mv_sk, 0), I32)),
                (MSA, jnp.full((DB,), jnp.where(is_move_row, r_mv_sa, 0), I32)),
                (MEC, jnp.full((DB,), jnp.where(is_move_row, r_mv_ec, -1), I32)),
                (MEK, jnp.full((DB,), jnp.where(is_move_row, r_mv_ek, 0), I32)),
                (MEA, jnp.full((DB,), jnp.where(is_move_row, r_mv_ea, 0), I32)),
                (MPR, jnp.full((DB,), jnp.where(is_move_row, r_mv_prio, -1), I32)),
            ],
        )
        # a map row that became its chain's tail is the key's live value;
        # the previous winner — its immediate left — gets tombstoned
        # (parity: block.rs:637-659)
        new_tail = linkable & is_map & (right_final < 0)
        put(DL, left_idx, jnp.ones((DB,), I32), new_tail & has_left)
        meta_ref[:, M_NBLOCKS] = n_blocks() + do.astype(I32)
        meta_ref[:, M_ERROR] = (
            meta_ref[:, M_ERROR]
            | jnp.where(overflow, ERR_CAPACITY, 0)
            | jnp.where(missing, ERR_MISSING_DEP, 0)
        )

    def delete_range(s, r):
        client = dels_ref[s, r, 0]
        start = dels_ref[s, r, 1]
        end = dels_ref[s, r, 2]
        enable = jnp.ones((DB,), bool)
        client_v = jnp.full((DB,), client, I32)
        start_v = jnp.full((DB,), start, I32)
        end_v = jnp.full((DB,), end, I32)
        # split head
        i, found = find_slot(client_v, start_v, enable)
        i_ok = found & (gather(DL, i, 1) == 0)
        split(i, start_v - gather(CK, i, 0), i_ok)
        # split tail
        k, kfound = find_slot(client_v, end_v - 1, enable)
        k_ok = kfound & (gather(DL, k, 1) == 0)
        split(k, end_v - gather(CK, k, 0), k_ok)
        # mark covered blocks deleted; tombstoning a live move row dirties
        # the doc (its claims must be released — moving.rs:229-280)
        valid = iota_c < n_blocks()[:, None]
        m = (
            valid
            & (col(CL) == client)
            & (col(CK) >= start)
            & (col(CK) + col(LN) <= end)
        )
        hit_move = jnp.any(
            m & (col(KD) == CONTENT_MOVE) & (col(DL) == 0), axis=1
        )
        meta_ref[:, M_MDIRTY] = meta_ref[:, M_MDIRTY] | hit_move.astype(I32)
        cols_ref[DL] = jnp.where(m, 1, col(DL))

    # --- move ownership (parity: moving.rs:149-227 via batch_doc's
    # _claim_move/_move_cycle/_recompute_moves) -----------------------------

    def resolve_move_ptr(c_v, k_v, assoc_v, enable):
        """Sticky (client, clock, assoc) -> first in-range slot per doc."""
        after = assoc_v >= 0
        i_a, found_a = clean_start(c_v, k_v, enable & after & (c_v >= 0))
        i_b, found_b = clean_end(c_v, k_v, enable & ~after & (c_v >= 0))
        right_b = gather(RT, i_b, -1)
        ptr = jnp.where(after, i_a, right_b)
        # logical blend, not jnp.where: Mosaic cannot lower an i1-vector
        # select (trunci i8->i1) on real TPU
        found = (after & found_a) | (~after & found_b)
        return ptr, found

    def claim_move(s_v, enable):
        """One claim pass for per-doc move slot s_v (walk its range,
        claiming rows the move beats on (priority, client rank, clock))."""
        msc = gather(MSC, s_v, -1)
        msk = gather(MSK, s_v, 0)
        msa = gather(MSA, s_v, 0)
        mec = gather(MEC, s_v, -1)
        mek = gather(MEK, s_v, 0)
        mea = gather(MEA, s_v, 0)
        start, s_found = resolve_move_ptr(msc, msk, msa, enable)
        endp, e_found = resolve_move_ptr(mec, mek, mea, enable)
        par = gather(PA, s_v, -1)
        seq_head = jnp.where(
            par < 0, meta_ref[:, M_START], gather(HD, par, -1)
        )
        start = jnp.where(msc < 0, seq_head, start)
        endp = jnp.where(mec < 0, -1, endp)
        unresolved = enable & (
            ((msc >= 0) & ~s_found) | ((mec >= 0) & ~e_found)
        )
        meta_ref[:, M_ERROR] = meta_ref[:, M_ERROR] | jnp.where(
            unresolved, ERR_MISSING_DEP, 0
        )
        enable = enable & ~unresolved
        prio_s = gather(MPR, s_v, -1)
        rank_s = gather_rank(gather(CL, s_v, -1))
        clock_s = gather(CK, s_v, 0)

        def wcond(carry):
            cur, n = carry
            return jnp.any(enable & (cur >= 0) & (cur != endp) & (n <= C))

        def wbody(carry):
            cur, n = carry
            active = enable & (cur >= 0) & (cur != endp) & (n <= C)
            m = gather(MV, cur, -1)
            prev_prio = jnp.where(m >= 0, gather(MPR, m, -1), -1)
            prev_rank = gather_rank(gather(CL, m, -1))
            prev_clock = gather(CK, m, 0)
            takes = (prev_prio < prio_s) | (
                (prev_prio == prio_s)
                & (m >= 0)
                & (
                    (prev_rank < rank_s)
                    | ((prev_rank == rank_s) & (prev_clock < clock_s))
                )
            )
            # a beaten collapsed move tombstones on the spot (parity:
            # _delete_as_cleanup, moving.rs:190-196)
            m_msc = gather(MSC, m, -1)
            m_collapsed = (
                (m >= 0)
                & (m_msc >= 0)
                & (m_msc == gather(MEC, m, -2))
                & (gather(MSK, m, 0) == gather(MEK, m, -1))
            )
            put(DL, m, jnp.ones((DB,), I32), active & takes & m_collapsed)
            put(MV, cur, s_v, active & takes)
            cur = jnp.where(active, gather(RT, cur, -1), cur)
            return cur, n + 1

        jax.lax.while_loop(wcond, wbody, (start, jnp.zeros((DB,), I32)))
        return enable

    def move_cycle(s_v, enable):
        """Does s_v sit on an ownership cycle? Ownership is single-parent,
        so walking the `moved` chain upward from s_v either terminates or
        returns to s_v (find_move_loop parity, moving.rs:113-141). Like
        the XLA `_move_cycle`, the chain only counts LIVE MOVE nodes — a
        stale claim held by a tombstoned move must not close a cycle."""

        def live_move(idx):
            return (gather(KD, idx, -1) == CONTENT_MOVE) & (
                gather(DL, idx, 1) == 0
            )

        # `hit` rides the carry as i32 0/1: an i1-vector loop carry fails
        # Mosaic legalization (scf.yield) on real TPU
        def ccond(carry):
            cur, n, hit = carry
            return jnp.any(enable & (cur >= 0) & (hit == 0) & (n <= C))

        def cbody(carry):
            cur, n, hit = carry
            active = enable & (cur >= 0) & (hit == 0) & (n <= C)
            nxt = gather(MV, cur, -1)
            hit = hit | (active & (nxt == s_v) & (s_v >= 0)).astype(I32)
            # a dead or non-move node breaks the live ownership chain
            nxt = jnp.where(live_move(nxt), nxt, -1)
            cur = jnp.where(active, nxt, cur)
            return cur, n + 1, hit

        first = gather(MV, s_v, -1)
        first = jnp.where(live_move(first), first, -1)
        _, _, hit = jax.lax.while_loop(
            ccond,
            cbody,
            (first, jnp.zeros((DB,), I32), jnp.zeros((DB,), I32)),
        )
        return hit > 0

    def recompute_moves():
        """Per-doc from-scratch ownership recompute for dirty docs (the
        end-of-update pass of batch_doc._recompute_moves)."""
        dirty = meta_ref[:, M_MDIRTY] > 0

        @pl.when(jnp.any(dirty))
        def _():
            cols_ref[MV] = jnp.where(mrow(dirty), -1, col(MV))
            done0 = jnp.zeros((DB, C), I32)

            def active_moves(done):
                return (
                    (iota_c < n_blocks()[:, None])
                    & (col(KD) == CONTENT_MOVE)
                    & (col(DL) == 0)
                    & (done == 0)
                    & mrow(dirty)
                )

            def rcond(done):
                return jnp.any(active_moves(done))

            def rbody(done):
                am = active_moves(done)
                s_idx = jnp.min(jnp.where(am, iota_c, C), axis=1).astype(I32)
                exists = s_idx < C
                s_v = jnp.where(exists, s_idx, -1)
                enable = claim_move(s_v, dirty & exists)
                cyc = move_cycle(s_v, enable) & exists
                put(DL, s_v, jnp.ones((DB,), I32), cyc)
                # cycle: release every claim and replay without s
                cols_ref[MV] = jnp.where(mrow(cyc), -1, col(MV))
                onehot_s = (iota_c == s_v[:, None]) & mrow(exists)
                done = jnp.where(
                    mrow(cyc), 0, done | onehot_s.astype(I32)
                )
                return done

            jax.lax.while_loop(rcond, rbody, done0)

        meta_ref[:, M_MDIRTY] = jnp.zeros((DB,), I32)

    def step(s, _):
        if phases >= 1:
            def row_body(u, __):
                @pl.when(rows_ref[s, u, 14] == 1)
                def _():
                    integrate_row(s, u)

                return 0

            jax.lax.fori_loop(0, U, row_body, 0)

        if phases >= 2:
            def del_body(r, __):
                @pl.when(dels_ref[s, r, 3] == 1)
                def _():
                    delete_range(s, r)

                return 0

            jax.lax.fori_loop(0, R, del_body, 0)
        if phases >= 3:
            recompute_moves()
        return 0

    jax.lax.fori_loop(0, S, step, 0)


def _run_body(
    cols, meta, packed, d_block: int, interpret: bool,
    phases: int = 3, row_phase: int = 4, vmem_limit_mb: int = 64,
    scan_plan: Optional[Tuple[int, int]] = None,
):
    if scan_plan is None:
        scan_plan = scan_tier_plan()
    rows, dels, rank = packed
    NC_, D, C = cols.shape
    grid = (D // d_block,)
    rank = rank.reshape(1, -1)
    out = pl.pallas_call(
        partial(
            _kernel, phases=phases, row_phase=row_phase, scan_plan=scan_plan
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(rows.shape, lambda d: (0, 0, 0)),
            pl.BlockSpec(dels.shape, lambda d: (0, 0, 0)),
            pl.BlockSpec(rank.shape, lambda d: (0, 0)),
            pl.BlockSpec((NC, d_block, C), lambda d: (0, d, 0)),
            pl.BlockSpec((d_block, M_PAD), lambda d: (d, 0)),
        ],
        out_specs=[
            pl.BlockSpec((NC, d_block, C), lambda d: (0, d, 0)),
            pl.BlockSpec((d_block, M_PAD), lambda d: (d, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(cols.shape, I32),
            jax.ShapeDtypeStruct(meta.shape, I32),
        ],
        input_output_aliases={3: 0, 4: 1},
        interpret=interpret,
        # the doc tile ([NC, d_block, C] i32) plus the conflict-scan's
        # [d_block, C] temporaries are the VMEM tenants. With NC=26 (move
        # columns + the pass-through origin_slot plane) a d_block=128/
        # C=2048 tile is ~27MB + scan temporaries; the pre-move measured
        # sweet spot (d_block=128 at ~56MB total under NC=17) now lands
        # near the 64MB limit, so re-measure on hardware — d_block<=96 is
        # the safe default at C=2048 if allocation fails. The ISSUE-12
        # wide-tier unroll does NOT multiply the resident scan
        # temporaries (the before/conflicting sets and the per-step
        # gathers are reused across the unrolled sub-steps — program
        # text grows ~unroll×, live VMEM does not), but a raised
        # YTPU_SCAN_WIDE_UNROLL inflates compile time and instruction
        # footprint: re-bisect d_block if allocation regresses.
        compiler_params=None
        if interpret
        else pltpu.CompilerParams(
            # v5e VMEM is 128MB; the default guard stays conservative.
            # Big-capacity tiles (the fused full-B4 at C=65536 needs a
            # ~54MB state tile + scan temporaries) raise it via the
            # YTPU_FUSED_VMEM_MB env var, which the public entry points
            # re-read PER CALL and thread here as a STATIC argument — a
            # changed value forces a retrace instead of being silently
            # ignored for already-compiled (shape, d_block) keys
            # (ADVICE r5 #2: the old trace-time env read misled
            # VMEM-limit bisection).
            vmem_limit_bytes=vmem_limit_mb * 1024 * 1024
        ),
    )(rows, dels, rank, cols, meta)
    return out


# the standalone jitted entry (donated state); the async chunk program
# composes `_run_body` directly inside its own jit instead, so donation
# applies to the OUTER program's state operands. scan_plan rides as a
# STATIC (position 8) so a changed tier plan recompiles.
_run = partial(
    jax.jit, static_argnums=(3, 4, 5, 6, 7, 8), donate_argnums=(0, 1)
)(_run_body)


def apply_update_stream_fused(
    state: DocStateBatch,
    stream: UpdateBatch,
    client_rank: jax.Array,
    d_block: int = 32,
    interpret: bool = False,
    guard: bool = True,
    refresh_cache: bool = False,
    _debug_phases: int = 3,
    _debug_row_phase: int = 4,
) -> DocStateBatch:
    """Fused-replay drop-in for `apply_update_stream`: sequence rows, map
    rows (per-key LWW chains), nested-branch parents AND move ranges all
    integrate in-VMEM — move claims run as a fused end-of-step recompute
    pass (the claim walk / cycle check / ownership argmax of
    `batch_doc._recompute_moves`, parity: moving.rs:149-227).

    `guard` is kept for call-site compatibility; it no longer excludes
    anything.

    origin_slot cache (ADVICE r5 #1): the kernel passes the cache plane
    through without maintaining it, so a wholesale rebuild
    (`recompute_origin_slot`) is needed before anything READS it — and
    that rebuild is O(D·B²) compares with a multi-GB vmapped
    intermediate per doc at flagship capacities (C=65536, ~51k blocks:
    billions of compares). It therefore no longer runs eagerly on every
    fused apply. The default `refresh_cache=False` marks the returned
    state's cache STALE (`batch_doc.mark_origin_slot_stale`); the
    XLA-lane entry points (`apply_update_batch`/`apply_update_stream`)
    and checkpoint save — the cache's only readers — refresh lazily via
    `batch_doc.ensure_origin_slot`, so chained fused applies pay the
    rebuild at most once, at the boundary where the cache is actually
    consumed. Pass `refresh_cache=True` to opt back into the eager
    rebuild (callers that hand the state to out-of-tree cache readers).

    `_debug_phases` / `_debug_row_phase` truncate the kernel for
    hardware bisection only (see `_kernel`); never pass them in production
    — partial kernels corrupt state by design."""
    del guard
    # the fused program (especially interpret-mode on CPU) is the largest
    # in the process: evict under the resident-program budget BEFORE a
    # possible compile, not just on the periodic tick (the r5 no-crutch
    # suite segfaulted compiling exactly this program at ~73%)
    from ytpu.utils import progbudget
    from ytpu.utils.phases import (
        NULL_SPAN,
        phases as _phases,
        program_memory as _program_memory,
    )

    progbudget.enforce()
    cols, meta = pack_state(state)
    D = cols.shape[1]
    if D % d_block != 0:
        raise ValueError(f"n_docs {D} must be a multiple of d_block {d_block}")
    rows, dels = pack_stream(stream)
    vmem_mb = int(os.environ.get("YTPU_FUSED_VMEM_MB", "64"))
    # two-tier scan plan: re-read per call and threaded as a static, so
    # a changed knob retraces instead of silently reusing the old unroll
    scan_plan = scan_tier_plan()
    if _phases.enabled:
        _phases.transfer(
            "integrate.fused",
            rows.size * rows.dtype.itemsize + dels.size * dels.dtype.itemsize,
            "h2d",
        )
        span = _phases.span(
            "integrate.fused",
            (cols.shape, rows.shape, dels.shape, d_block, interpret,
             _debug_phases, _debug_row_phase, vmem_mb, scan_plan),
            axes=("state", "rows", "dels", "d_block", "interpret",
                  "debug_phases", "debug_row_phase", "vmem_mb",
                  "scan_plan"),
            memory=_program_memory(
                _run, cols, meta, (rows, dels, client_rank), d_block,
                interpret, _debug_phases, _debug_row_phase, vmem_mb,
                scan_plan,
            ),
        )
    else:
        span = NULL_SPAN
    with span:
        cols, meta = _run(
            cols, meta, (rows, dels, client_rank), d_block, interpret,
            _debug_phases, _debug_row_phase, vmem_mb, scan_plan,
        )
    out = unpack_state(cols, meta, state)
    if not refresh_cache:
        # lazy dirty-flag: the XLA apply wrappers / checkpoint save run
        # recompute_origin_slot on first read of a stale cache
        from ytpu.models.batch_doc import mark_origin_slot_stale

        mark_origin_slot_stale(out)
        return out
    # eager opt-in: rebuild so even out-of-tree readers see a valid cache
    from ytpu.models.batch_doc import recompute_origin_slot

    return recompute_origin_slot(out)


# --- chunked replay driver (ISSUE-4 tentpole) --------------------------------
# The fused kernel is byte-exact on silicon but a full-B4 tile needs more
# resident blocks than any legal VMEM shape holds (peak 51,555 at C=65536,
# which violates Pallas block limits; C=32768 overflows). The driver below
# gives the fused lane the XLA lane's survival trick — mid-replay
# compaction — without ever unpacking to host: chunks of the update stream
# run through `_run`, and between chunks `compact_packed` squashes the
# packed [NC, D, C] state in place whenever the shared CompactionPolicy's
# high-watermark trips or the next chunk's worst-case growth would
# overflow the tile.


_XLA_CHUNK_STEP = None


def xla_chunk_step(cols, meta, stream, rank, scan_plan=None):
    """One chunk of stream steps through the un-fused XLA integrate path,
    on the packed kernel state (unpack → apply_update_stream → repack, all
    inside one jit so XLA fuses the repacks away). The jitted step is a
    module singleton shared by every chunked driver instance — a per-call
    closure would retrace every chunk, and two singletons (this one and
    replay.py's old private copy) would hold duplicate unevictable
    executables. `scan_plan` (the ISSUE-12 two-tier static; None = the
    env-resolved `scan_tier_plan()`) rides as a static argnum so a
    changed tier plan recompiles the step."""
    global _XLA_CHUNK_STEP
    if scan_plan is None:
        scan_plan = scan_tier_plan()
    if _XLA_CHUNK_STEP is None:
        # the RAW body, not the instrumented wrapper: tracing through the
        # wrapper recorded a phantom `integrate.xla_stream` compile_s
        # entry in bench JSON (PR-4 review) — the only real dispatch here
        # is this chunk step, already attributed to `replay.chunk_xla`
        from ytpu.models.batch_doc import apply_update_stream_raw

        def step(cols, meta, stream, rank, scan_plan):
            # pack_state zeroes the meta padding, so the carried
            # scan record (ISSUE-11/12) is read out first and folded
            # back in with this chunk's contribution
            carried = meta[:, M_HIST0:M_SCAN_END]
            state = unpack_state(cols, meta, None)
            state, dhist = apply_update_stream_raw(
                state, stream, rank, scan_plan
            )
            cols, meta = pack_state(state)
            meta = _fold_scan_meta(meta, carried, dhist)
            return cols, meta

        # donate like the fused _run: the packed state updates in place
        # instead of holding two full copies at grown capacity
        _XLA_CHUNK_STEP = jax.jit(
            step, donate_argnums=(0, 1), static_argnums=(4,)
        )
    return _XLA_CHUNK_STEP(cols, meta, stream, rank, scan_plan)


def _fold_scan_meta(meta, carried, dhist):
    """Fold an XLA-lane chunk's scan record (``dhist``
    ``[D, SCAN_REC_WORDS]``) plus the pre-chunk carried meta columns
    back into a freshly packed meta (whose padding pack_state zeroed):
    every word adds except the max, which maxes (`merge_scan_records`,
    the one shared combine rule)."""
    return meta.at[:, M_HIST0:M_SCAN_END].set(
        merge_scan_records(carried, dhist)
    )


def _packed_commit_fold(cols, meta):
    """``[D]`` uint32 per-doc state commitments from the packed columns
    (ISSUE-13): `commit_fold_blocks` over every live block row — the
    same validity predicate `encode_diff_batch` uses.  Recomputed from
    the CURRENT state at each readout (a ~D·C vectorized reduction, free
    next to the integrate it rides), so compaction/GC/growth can never
    leave a stale accumulator behind."""
    B = cols.shape[-1]
    slots = jnp.arange(B, dtype=I32)
    valid = (slots[None, :] < meta[:, M_NBLOCKS][:, None]) & (cols[CL] >= 0)
    return commit_fold_blocks(cols[CL], cols[CK], cols[LN], valid)


@jax.jit
def packed_commitments(cols, meta):
    """Public on-demand pull of the ``[D]`` per-doc commitment words
    (i32 bit pattern of the uint32 fold).  NOT a hot-path call — the
    batch-aggregate word already rides the lazy readout; this exists
    for per-doc verification (tests, a quarantine postmortem)."""
    return jax.lax.bitcast_convert_type(
        _packed_commit_fold(cols, meta), I32
    )


@jax.jit
def packed_capacity_ledger(cols, meta):
    """Per-doc ``([D] occupied, [D] dead)`` i32 rows from the packed
    columns (ISSUE-18). NOT a hot-path call — the batch aggregates
    already ride the lazy readout; this is the per-tenant pull serving
    scrapes (`DeviceSyncServer` `/snapshot`) and tests materialize on
    demand. Free rows per doc are ``capacity - occupied - dead`` under
    the ledger convention (occupied counts LIVE rows, dead the
    tombstoned ones), so the three per-tenant gauges always sum to the
    column capacity."""
    occ = meta[:, M_NBLOCKS].astype(I32)
    dead = _packed_dead_rows(cols, meta)
    return occ - dead, dead


def _packed_dead_rows(cols, meta):
    """``[D]`` i32 per-doc dead-row counts: rows inside the occupied
    prefix (`n_blocks`) that are live allocations (`client >= 0`) but
    tombstoned (`DL > 0`) — the GC-able fragmentation `compact_packed`
    reclaims. Same validity predicate as `_packed_commit_fold`."""
    B = cols.shape[-1]
    slots = jnp.arange(B, dtype=I32)
    valid = (slots[None, :] < meta[:, M_NBLOCKS][:, None]) & (cols[CL] >= 0)
    return jnp.sum((valid & (cols[DL] > 0)).astype(I32), axis=1)


def _readout_words(cols, meta, err):
    """``[N_READOUT]`` i32: (max n_blocks, max sticky integrate error,
    sticky decode flags, scan-width bucket totals summed over docs, max
    scan width, the ISSUE-12 tier/trip totals summed over docs, the
    ISSUE-13 commitment word — wrap-sum over docs of the per-doc lattice
    digest — then the ISSUE-18 capacity-ledger words: Σ occupied rows,
    Σ dead rows, max per-doc dead) — everything the host learns per
    drain, one future."""
    hist = jnp.sum(meta[:, M_HIST0:M_SCANW_MAX], axis=0)
    tiers = jnp.sum(meta[:, M_TIER_CHEAP:M_SCAN_END], axis=0)
    commit = jax.lax.bitcast_convert_type(
        jnp.sum(_packed_commit_fold(cols, meta), dtype=jnp.uint32), I32
    )
    dead = _packed_dead_rows(cols, meta)
    ledger = jnp.stack(
        [
            jnp.sum(meta[:, M_NBLOCKS]),
            jnp.sum(dead),
            jnp.max(dead),
        ]
    )
    return jnp.concatenate(
        [
            jnp.stack(
                [jnp.max(meta[:, M_NBLOCKS]), jnp.max(meta[:, M_ERROR]), err]
            ),
            hist,
            jnp.max(meta[:, M_SCANW_MAX])[None],
            tiers,
            commit[None],
            ledger,
        ]
    )


@jax.jit
def _chunk_readout(cols, meta, err):
    """[N_READOUT] i32 (max n_blocks, max sticky integrate error, sticky
    decode flags, + the scan-width histogram words) — the per-chunk
    occupancy/error readout. Dispatched after every chunk but NOT
    materialized: the host keeps the device future and only blocks on it
    when its own optimistic occupancy bound trips the watermark, so
    steady-state chunks never pay a sync (the round-5 FusedReplay synced
    every chunk). Decode FLAG_ERRORS ride the same word (`err`,
    OR-reduced on device by `replay_chunk_program`), so the async lane's
    per-chunk `np.asarray(flags)` block is gone too. The ISSUE-11
    scan-width words (bucket totals + max) ride the SAME future — zero
    additional materializations."""
    return _readout_words(cols, meta, err)


@jax.jit
def _fold_subbatch_readouts(stacked):
    """Fold ``[n_sub, N_READOUT]`` per-sub-batch readouts into the ONE
    ``[N_READOUT]`` surface the drain already parses (ISSUE-20): each
    word folds with the same reduction `_readout_words` used to produce
    it over docs — max for the occupancy/error/scan-max words, sum for
    the histogram/tier/ledger totals, bitwise-OR for the sticky decode
    flags (threaded slice→slice, so the fold is also just the last
    word), uint32 wrap-sum for the ISSUE-13 commitment, and max for the
    per-doc dead peak. The result is byte-identical to the monolithic
    readout, so `_drain_readouts` (and the zero-sync invariant) never
    learns sub-batching happened."""
    mx = jnp.max(stacked, axis=0)
    sm = jnp.sum(stacked, axis=0)
    err = jax.lax.associative_scan(jnp.bitwise_or, stacked[:, 2])[-1]
    commit = jax.lax.bitcast_convert_type(
        jnp.sum(
            jax.lax.bitcast_convert_type(
                stacked[:, 3 + SCAN_REC_WORDS], jnp.uint32
            )
        ),
        I32,
    )
    base = 4 + SCAN_REC_WORDS  # first capacity-ledger word
    return jnp.concatenate(
        [
            jnp.stack([mx[0], mx[1], err]),
            sm[3 : 3 + SCAN_REC_MAX],  # scan-width bucket totals
            mx[3 + SCAN_REC_MAX][None],  # observed max scan width
            sm[3 + SCAN_REC_CHEAP : 3 + SCAN_REC_WORDS],  # tier/trip sums
            commit[None],
            jnp.stack([sm[base], sm[base + 1], mx[base + 2]]),
        ]
    )


def _chunk_core(
    cols,
    meta,
    err,
    buf,
    lens,
    refs,
    rank,
    *,
    lane: str,
    max_rows: int,
    max_dels: int,
    n_steps: int,
    max_sections: int,
    d_block: int,
    interpret: bool,
    vmem_mb: int,
    scan_plan: Tuple[int, int],
):
    """Traceable body shared by `replay_chunk_program` (host-packed
    ``[S, L]`` lanes) and `replay_chunk_program_raw` (device-gathered
    lanes): device decode (`decode_updates_v1` body) → global unit-ref
    rebase (`refs`, -1 = keep the decoded in-chunk ref) → integrate
    (fused Pallas tile or the packed-XLA scan, both under the ISSUE-12
    two-tier `scan_plan` static) → `[N_READOUT]` readout."""
    from ytpu.ops.decode_kernel import FLAG_ERRORS, _decode_updates_v1_impl

    stream, flags = _decode_updates_v1_impl(
        buf,
        lens,
        max_rows=max_rows,
        max_dels=max_dels,
        n_steps=n_steps,
        max_sections=max_sections,
    )
    stream = stream._replace(
        content_ref=jnp.where(refs >= 0, refs, stream.content_ref)
    )
    err = err | jax.lax.reduce(
        flags & FLAG_ERRORS, np.int32(0), jax.lax.bitwise_or, (0,)
    )
    if lane == "fused":
        rows, dels = pack_stream(stream)
        cols, meta = _run_body(
            cols, meta, (rows, dels, rank), d_block, interpret, 3, 4,
            vmem_mb, scan_plan,
        )
    else:
        from ytpu.models.batch_doc import apply_update_stream_raw

        carried = meta[:, M_HIST0:M_SCAN_END]
        state = unpack_state(cols, meta, None)
        state, dhist = apply_update_stream_raw(state, stream, rank, scan_plan)
        cols, meta = pack_state(state)
        meta = _fold_scan_meta(meta, carried, dhist)
    readout = _readout_words(cols, meta, err)
    return cols, meta, err, readout


@partial(
    jax.jit,
    static_argnames=(
        "lane",
        "max_rows",
        "max_dels",
        "n_steps",
        "max_sections",
        "d_block",
        "interpret",
        "vmem_mb",
        "scan_plan",
    ),
    donate_argnums=(0, 1, 2),
)
def replay_chunk_program(
    cols,
    meta,
    err,
    buf,
    lens,
    refs,
    rank,
    *,
    lane: str,
    max_rows: int,
    max_dels: int,
    n_steps: int,
    max_sections: int,
    d_block: int,
    interpret: bool,
    vmem_mb: int,
    scan_plan: Tuple[int, int],
):
    """One replay chunk straight from padded wire bytes, as ONE compiled
    dispatch: device decode (`decode_updates_v1` body) → global unit-ref
    rebase (`refs`, -1 = keep the decoded in-chunk ref) → integrate
    (fused Pallas tile or the packed-XLA scan) → `[3]` readout.

    Fusing the stages kills the two host hops the serial loop paid per
    chunk — the decoded-stream round trip between the decode and
    integrate programs, and the blocking `np.asarray(flags)` error check
    (replay.py:419/420 pre-PR5): per-lane decode FLAG_ERRORS are
    OR-reduced into the sticky `err` scalar on device, and flagged lanes
    already integrate as no-ops (the decoder zeroes their valid masks),
    so the host materializes nothing in steady state. `donate_argnums`
    on cols/meta lets XLA update the ~NC·D·C state in place instead of
    copying it every chunk.

    This is the HOST-PACKED lane: staging built the `[S, L]` matrix with
    `pack_updates_into` (per-update Python packing). The raw ingest lane
    (`replay_chunk_program_raw`) moves that packing on device too; this
    program stays as the fallback/checkpoint rung of the PR-6 ladder."""
    return _chunk_core(
        cols,
        meta,
        err,
        buf,
        lens,
        refs,
        rank,
        lane=lane,
        max_rows=max_rows,
        max_dels=max_dels,
        n_steps=n_steps,
        max_sections=max_sections,
        d_block=d_block,
        interpret=interpret,
        vmem_mb=vmem_mb,
        scan_plan=scan_plan,
    )


@partial(
    jax.jit,
    static_argnames=(
        "width",
        "lane",
        "max_rows",
        "max_dels",
        "n_steps",
        "max_sections",
        "d_block",
        "interpret",
        "vmem_mb",
        "scan_plan",
    ),
    donate_argnums=(0, 1, 2),
)
def replay_chunk_program_raw(
    cols,
    meta,
    err,
    raw,
    offs,
    lens,
    refs,
    rank,
    *,
    width: int,
    lane: str,
    max_rows: int,
    max_dels: int,
    n_steps: int,
    max_sections: int,
    d_block: int,
    interpret: bool,
    vmem_mb: int,
    scan_plan: Tuple[int, int],
):
    """One replay chunk straight from RAW CONCATENATED wire bytes plus a
    tiny per-update offsets table (ISSUE-7 tentpole): the device gathers
    each update's byte lane out of the flat arena
    (`decode_kernel.gather_raw_lanes` — the Stream-VByte control/data
    split: offsets are the control stream, the byte arena the data
    stream), then runs the same lane-parallel varint decode → unit-ref
    rebase → integrate → readout as `replay_chunk_program`.

    What this buys over the host-packed program: staging collapses to a
    memcpy (one slice copy + two vectorized table writes, no per-update
    Python), and the h2d transfer shrinks from ``S·L`` padded bytes to
    the actual wire bytes + ``2·S`` table words — so pipeline depth > 2
    is essentially free and `replay.overlap_ratio` → 1.0. The gather's
    zero mask makes the on-device lane matrix byte-identical to a
    host-packed one, so raw-vs-packed byte parity is structural."""
    from ytpu.ops.decode_kernel import gather_raw_lanes

    buf = gather_raw_lanes(raw, offs, lens, width)
    return _chunk_core(
        cols,
        meta,
        err,
        buf,
        lens,
        refs,
        rank,
        lane=lane,
        max_rows=max_rows,
        max_dels=max_dels,
        n_steps=n_steps,
        max_sections=max_sections,
        d_block=d_block,
        interpret=interpret,
        vmem_mb=vmem_mb,
        scan_plan=scan_plan,
    )


@lru_cache(maxsize=1)
def _transfer_aliases_host() -> bool:
    """True when `jnp.asarray` of a numpy array shares its memory instead
    of copying (the CPU PJRT client's zero-copy path). The async replay's
    staging-slot reuse gate assumes the h2d transfer made the input
    private; on an aliasing backend the bytes must be copied host-side
    first or a re-packed slot races the chunk program still reading it."""
    # the probe buffer must be 64-byte aligned: the zero-copy path only
    # engages on aligned host memory, so a small unaligned allocation
    # here would report "copies" while the page-aligned staging buffers
    # still alias — carve an aligned window out of a larger block
    raw = np.zeros(128, dtype=np.uint8)
    off = (-raw.ctypes.data) % 64
    probe = raw[off : off + 64]
    dev = jnp.asarray(probe)
    dev.block_until_ready()
    probe[0] = 1
    return bool(np.asarray(dev)[0] == 1)


@dataclass
class ReplayChunkStats:
    """Counters of one chunked replay (shared by both kernel lanes)."""

    chunks: int = 0
    compactions: int = 0
    growths: int = 0
    syncs: int = 0  # occupancy readouts actually materialized
    capacity: int = 0
    peak_blocks: int = 0  # max occupancy OBSERVED at readouts (lazy: the
    # true peak between syncs may be higher but is bounded by the margin)
    final_blocks: int = 0
    # resilience counters (ISSUE-6): lane demotions this driver performed,
    # in-place chunk retries that succeeded on a demoted lane, and decode
    # errors quarantined (skip-and-record) instead of aborting the replay
    demotions: int = 0
    recoveries: int = 0
    quarantined: int = 0
    # conflict-tail attribution (ISSUE-11): the scan-width record as of
    # the freshest materialized readout — pow2 bucket counts, observed
    # max, and the bucket-quantile p50/p99 (0s until the first drain)
    scan_hist: tuple = ()
    scan_max: int = 0
    scan_p50: int = 0
    scan_p99: int = 0
    # two-tier scan occupancy (ISSUE-12), same freshest-readout origin:
    # scans resolved entirely in the cheap tier vs escalated to the
    # vectorized wide tier, plus the exact dispatch-trip accounting —
    # `scan_trips_serial` is what the pre-ISSUE-12 one-candidate-per-trip
    # loop would have paid (Σ width), `scan_trips_two_tier` what the
    # tiered dispatch actually paid (Σ min(width, cheap) + wide blocks)
    scan_tier_cheap: int = 0
    scan_tier_wide: int = 0
    scan_trips_serial: int = 0
    scan_trips_two_tier: int = 0
    # incremental state commitment (ISSUE-13): the batch-aggregate
    # lattice-digest word as of the freshest materialized readout
    # (uint32 value; per-doc words via `packed_commitments` on demand)
    commit_word: int = 0
    # capacity observatory (ISSUE-18): occupancy/fragmentation ledger as
    # of the freshest materialized readout — Σ occupied rows over docs
    # (the n_blocks prefix, live + dead), Σ dead (tombstoned, GC-able)
    # rows inside it, and the worst per-doc dead count; plus compaction
    # efficacy — total rows reclaimed by `compact_packed` calls and the
    # chunk gap between the last two compactions (time-to-watermark).
    # All ride the SAME lazy readout future — zero new device syncs.
    occupied_rows: int = 0
    dead_rows: int = 0
    dead_max: int = 0
    reclaimed_rows: int = 0
    compact_gap_chunks: int = 0
    # doc-axis sub-batching (ISSUE-20): the active pow2 sub-batch width
    # (0 = monolithic dispatch) and how many times the driver narrowed
    # it — forecaster-driven or on a typed GrowOomError
    subbatch_width: int = 0
    subbatch_narrowed: int = 0


# --- lane-health ladder + typed replay faults (ISSUE-6 tentpole) -------------
# A hostile shape family (e.g. the 1024-doc integrate programs that kill
# the TPU worker, ROADMAP item 1) must not take the process down on every
# retry: the first dispatch/compile failure demotes the family one rung —
# fused Pallas → packed-XLA chunk step → (caller-level) serial host
# oracle — and the demotion is STICKY per shape family, so later drivers
# for the same family skip the known-bad lane entirely.

from ytpu.utils import metrics as _metrics
from ytpu.utils.faults import FaultError, faults

LANE_LADDER = ("fused", "xla", "host")

_DEMOTIONS = _metrics.counter("lane.demotions")
_DEMOTIONS_BY = _metrics.counter(
    "lane.demotions_by_lane", labelnames=("from_lane", "to_lane")
)
_RECOVERIES = _metrics.counter("replay.recoveries")
_QUARANTINED = _metrics.counter("replay.quarantined")
#: `grow.oom` denials (ISSUE-18): every typed GrowOomError raised at the
#: fault site — the chaos-side truth the `/capacity` forecaster is
#: scored against (forecast flagged BEFORE this counter moved?)
_GROW_DENIED = _metrics.counter("memory.grow_denied")
#: sub-batch width demotions (ISSUE-20): every halving of the doc-axis
#: sub-batch width — forecaster-driven (BEFORE a grow attempt) or in
#: response to a typed GrowOomError (instead of killing the chunk).
#: bench_compare regresses this on RISE: a healthy budget never narrows.
_SUBBATCH_NARROWED = _metrics.counter("capacity.subbatch_narrowed")


def packed_state_bytes(n_docs: int, capacity: int) -> int:
    """Analytic resident bytes of ONE packed state at a given capacity:
    the ``[NC, D, C]`` i32 column planes plus the ``[D, M_PAD]`` meta
    tile. The capacity observatory's model term — `grow_packed` doubles
    `capacity`, so the next grow attempt costs exactly this much at
    ``capacity * 2`` (plus the transient old+new overlap)."""
    return 4 * (NC * n_docs * capacity + n_docs * M_PAD)

# shape family -> lowest healthy rung (absent = full health)
_lane_floor: dict = {}
_lane_floor_lock = threading.Lock()


def lane_family(n_docs: int, d_block: int) -> Tuple[int, int]:
    """The sticky-health key: capacity grows mid-replay, so only the doc
    axis and kernel tiling identify a compiled shape family."""
    return (int(n_docs), int(d_block))


def effective_lane(family, requested: str) -> str:
    """`requested` demoted to the family's sticky floor, if any."""
    floor = _lane_floor.get(family)
    if floor is None:
        return requested
    if LANE_LADDER.index(floor) > LANE_LADDER.index(requested):
        return floor
    return requested


def demote_lane(family, from_lane: str) -> Optional[str]:
    """Record a sticky demotion one rung below `from_lane`; returns the
    new rung (``None`` when already at the ladder's end)."""
    idx = LANE_LADDER.index(from_lane)
    if idx + 1 >= len(LANE_LADDER):
        return None
    nxt = LANE_LADDER[idx + 1]
    with _lane_floor_lock:
        cur = _lane_floor.get(family)
        if cur is None or LANE_LADDER.index(nxt) > LANE_LADDER.index(cur):
            _lane_floor[family] = nxt
    _DEMOTIONS.inc()
    _DEMOTIONS_BY.labels(from_lane, nxt).inc()
    return nxt


def reset_lane_health() -> None:
    """Test/ops hook: forget every sticky demotion."""
    with _lane_floor_lock:
        _lane_floor.clear()


def lane_health() -> dict:
    """JSON-safe view of the sticky lane-demotion ladder: shape-family
    key (``"{n_docs}x{d_block}"``) → lowest healthy rung. Empty = full
    health. The telemetry plane's `/healthz` endpoint serves this."""
    with _lane_floor_lock:
        return {f"{fam[0]}x{fam[1]}": floor for fam, floor in _lane_floor.items()}


#: wall-clock of the most recent successful chunk dispatch, for the
#: telemetry `/healthz` last-dispatch age — a wedged device shows up as a
#: growing age while the HTTP plane stays serveable (its own thread)
_LAST_DISPATCH = _metrics.gauge("integrate.last_dispatch_unix")


class ReplayFault(RuntimeError):
    """A mid-replay device fault the driver could NOT absorb in place
    (state buffers lost to donation, simulated worker death, or the
    ladder exhausted).  `recoverable` callers (FusedReplay) restore the
    last chunk-boundary checkpoint — or the initial state — and re-run;
    the sticky lane floor already records any demotion."""

    def __init__(self, msg: str, *, chunk: int, lane: str,
                 cause: Optional[BaseException] = None):
        super().__init__(msg)
        self.chunk = chunk
        self.lane = lane
        self.cause = cause


class GrowOomError(FaultError):
    """The ``grow.oom`` fault site, typed (ISSUE-18): a denied
    `grow_packed` now reports WHAT it attempted against WHAT was
    available — attempted resident bytes at the doubled capacity vs
    the device budget — so chaos runs can score the `/capacity`
    forecaster against reality. Still a `FaultError` subclass: the
    lane ladder's `is_device_fault` and FusedReplay's checkpoint-resume
    recovery treat it exactly like the bare fault it replaces."""

    def __init__(
        self,
        spec,
        *,
        capacity: int,
        new_capacity: int,
        n_docs: int,
        attempted_bytes: int,
        available_bytes: int,
    ):
        RuntimeError.__init__(
            self,
            f"injected fault at site 'grow.oom': grow {capacity} -> "
            f"{new_capacity} slots for {n_docs} docs needs "
            f"~{attempted_bytes} resident bytes, budget "
            f"{available_bytes}",
        )
        self.site = "grow.oom"
        self.spec = spec
        self.capacity = int(capacity)
        self.new_capacity = int(new_capacity)
        self.n_docs = int(n_docs)
        self.attempted_bytes = int(attempted_bytes)
        self.available_bytes = int(available_bytes)


def is_device_fault(e: BaseException) -> bool:
    """True for failures that indict the DEVICE LANE (injected faults,
    XLA runtime/compile errors, Mosaic failures) — never for host-side
    programming errors, and never for the interpret-mode
    NotImplementedError that `tests/_fused_interpret` must see raw."""
    if isinstance(e, FaultError):
        return True
    if isinstance(e, (NotImplementedError, MemoryError, KeyboardInterrupt)):
        return False
    mod = type(e).__module__ or ""
    return (
        "jaxlib" in mod
        or "mosaic" in mod.lower()
        or type(e).__name__ == "XlaRuntimeError"
    )


def _buffers_alive(*arrays) -> bool:
    """True when every jax array still owns its buffer (donation marks
    consumed inputs deleted — a failed dispatch that already consumed the
    state cannot be retried in place)."""
    for a in arrays:
        try:
            if a.is_deleted():
                return False
        except AttributeError:
            pass
    return True


class PackedReplayDriver:
    """Chunked replay over a packed [NC, D, C] state with between-chunk
    device compaction under one shared `CompactionPolicy`.

    The occupancy protocol (no per-chunk sync): the host maintains an
    optimistic UPPER BOUND on the max per-doc block count — each chunk
    adds its worst-case growth (3 slots/row + 2/delete range, the same
    accounting as `ReplayPlan.adds` and `sharded_doc.flush`) — and each
    chunk dispatches a tiny `[2]` (occupancy, sticky-error) readout that
    stays an un-materialized device future. Only when the BOUND says the
    next chunk might not fit (or the high-watermark tripped) does the
    host block on the freshest readout; if the ACTUAL occupancy still
    trips the policy, `compact_packed` squashes in place and, when even
    that can't make room, `grow_packed` widens the tile (capacity change
    = one retrace, same as the round-5 XLA lane). Sticky error flags are
    checked at every materialized readout and once more at `finish()` —
    the device flags are sticky by design, so deferral never loses one.
    """

    def __init__(
        self,
        cols,
        meta,
        client_rank,
        *,
        d_block: int = 8,
        interpret: bool = False,
        lane: str = "fused",
        policy=None,
        unit_refs: bool = False,
        gc_ranges: bool = False,
        max_capacity: Optional[int] = None,
        sync_every_chunk: bool = False,
        initial_occupancy: int = 0,
        quarantine: bool = False,
        shard_docs: bool = False,
    ):
        from ytpu.models.batch_doc import DEFAULT_COMPACTION_POLICY

        if lane not in ("fused", "xla"):
            raise ValueError(f"lane must be 'fused' or 'xla', got {lane!r}")
        D = cols.shape[1]
        if lane == "fused" and D % d_block != 0:
            raise ValueError(
                f"n_docs {D} must be a multiple of d_block {d_block}"
            )
        # sticky lane health: a family demoted by an earlier driver (or an
        # earlier chunk of this replay) never re-tries the known-bad lane;
        # the "host" rung is the CALLER's (serial oracle) — the driver
        # itself bottoms out at the packed-XLA step
        self._family = lane_family(D, d_block)
        eff = effective_lane(self._family, lane)
        self.cols = cols
        self.meta = meta
        self.rank = client_rank
        lane = "xla" if eff == "host" else eff
        self.d_block = d_block
        self.interpret = interpret
        self.lane = lane
        self.policy = policy or DEFAULT_COMPACTION_POLICY
        self.unit_refs = unit_refs
        self.gc_ranges = gc_ranges
        self.max_capacity = max_capacity or cols.shape[2]
        self.sync_every_chunk = sync_every_chunk
        self.stats = ReplayChunkStats(capacity=cols.shape[2])
        self._hi_bound = int(initial_occupancy)
        self._pending = []  # un-materialized [3] readout futures
        # sticky decode-error scalar, kept ON DEVICE: replay_chunk_program
        # ORs each chunk's FLAG_ERRORS into it so the host never blocks on
        # per-chunk flags; materialized only at drains/finish
        self._err = jnp.zeros((), I32)
        # optional hook raised INSTEAD of the generic decode error: the
        # async replay loop re-identifies the offending chunk/update
        # indices host-side for the same message the sync lane raises
        self.on_decode_error = None
        # poison-update quarantine (opt-in): a tripped sticky decode
        # error is RECORDED and cleared instead of aborting the replay —
        # the decoder already integrates flagged lanes as no-ops, so the
        # stream's healthy updates are untouched. `on_quarantine(flags)`
        # (set by FusedReplay) re-identifies the offending update
        # indices host-side and returns the newly recorded ones.
        self.quarantine = quarantine
        self.on_quarantine = None
        # capacity observatory (ISSUE-18): optional HeadroomForecaster
        # fed at every materialized ledger readout (set by FusedReplay /
        # tests; None keeps the hot path untouched), plus the chunk
        # index of the latest compaction for the time-to-watermark gap
        self.forecaster = None
        self._last_compact_chunk = -1
        # doc-axis sub-batching (ISSUE-20): when enabled, every
        # one-dispatch chunk program (and compact/grow) runs per
        # pow2-width doc slice sized by `plan_subbatches` against the
        # forecaster's budget — the packed state never allocates (or
        # dispatches) as one monolith. `_sub_width` is the sticky active
        # width: planned lazily per capacity, only ever narrowed
        # (forecast or GrowOomError), never re-widened mid-replay.
        self.shard_docs = bool(shard_docs)
        self._sub_width: Optional[int] = None
        self._sub_cap = -1
        self.subbatch_journal: list = []

    @property
    def capacity(self) -> int:
        return self.cols.shape[2]

    # ------------------------------------------------------- sub-batching

    def _active_sub_width(self) -> Optional[int]:
        """The pow2 doc width each dispatch slices at, or None for the
        monolithic path (shard_docs off, or the whole doc axis fits one
        dispatch under the budget). Planned lazily per capacity via
        `plan_subbatches`; a sticky narrowing survives replanning (the
        min below) so a width demoted by `grow.oom` never re-widens."""
        if not self.shard_docs:
            return None
        D = self.cols.shape[1]
        if self._sub_cap != self.capacity:
            from ytpu.models.replay import plan_subbatches

            plan = plan_subbatches(
                D,
                self.capacity,
                d_block=self.d_block if self.lane == "fused" else 1,
                forecaster=self.forecaster,
            )
            width = plan.width
            if self._sub_width is not None:
                width = min(width, self._sub_width)
            self._sub_width = width
            self._sub_cap = self.capacity
            self.stats.subbatch_width = width if width < D else 0
        return self._sub_width if (self._sub_width or D) < D else None

    def _narrow_subbatch(self, reason: str) -> bool:
        """Demote the sub-batch width one pow2 rung (journaled + counted
        `capacity.subbatch_narrowed`); False at the floor (`d_block` on
        the fused lane, 1 otherwise) — the caller then surfaces the
        original failure instead of looping."""
        from ytpu.utils.phases import phases as _phases

        D = self.cols.shape[1]
        cur = self._sub_width if self._sub_width is not None else D
        floor = self.d_block if self.lane == "fused" else 1
        nxt = cur // 2
        if nxt < max(floor, 1):
            return False
        self._sub_width = nxt
        self._sub_cap = self.capacity
        self.stats.subbatch_width = nxt
        self.stats.subbatch_narrowed += 1
        _SUBBATCH_NARROWED.inc()
        self.subbatch_journal.append(
            {
                "chunk": self.stats.chunks,
                "capacity": self.capacity,
                "from_width": cur,
                "to_width": nxt,
                "reason": reason,
            }
        )
        if _phases.enabled:
            _phases.set_value("subbatch.width", nxt)
            _phases.add_value("capacity.subbatch_narrowed", 1)
        return True

    def _forecast_narrow(self, new_cap: int) -> None:
        """Satellite fix (ISSUE-20): consult the HeadroomForecaster
        BEFORE attempting `grow_packed` — while the MODELED grow
        transient at the active width busts the budget, narrow the
        width instead of letting the device (or the chaos site) deny
        the allocation."""
        if self.forecaster is None:
            return
        D = self.cols.shape[1]
        budget = self.forecaster.budget_bytes
        while True:
            w = self._active_sub_width() or D
            transient = self.forecaster.model_bytes(
                w, self.capacity
            ) + self.forecaster.model_bytes(w, new_cap)
            if transient <= budget or not self._narrow_subbatch("forecast"):
                return

    def _map_subbatches(self, fn, width: int):
        """Apply ``fn(cols_slice, meta_slice) -> (cols, meta)`` per
        doc-axis sub-batch and reassemble. Only one slice's transient
        (donated old + new buffers) is live at a time — the bounded
        working set that lets compact/grow clear shapes whose
        monolithic transient busts the budget."""
        D = self.cols.shape[1]
        outs_c, outs_m = [], []
        for lo in range(0, D, width):
            hi = min(lo + width, D)
            c = jax.lax.slice_in_dim(self.cols, lo, hi, axis=1)
            m = jax.lax.slice_in_dim(self.meta, lo, hi, axis=0)
            c, m = fn(c, m)
            outs_c.append(c)
            outs_m.append(m)
        if len(outs_c) == 1:
            return outs_c[0], outs_m[0]
        return (
            jnp.concatenate(outs_c, axis=1),
            jnp.concatenate(outs_m, axis=0),
        )

    def _dispatch_subbatched(
        self, lane, width, stage, span_tail, dev, vmem_mb, scan_plan,
        program, program_kw,
    ):
        """Run one chunk program per doc-axis sub-batch slice and
        reassemble (ISSUE-20 tentpole). Invariants:

        - every slice shares ONE `(width, capacity)` shape family, so
          the loop costs exactly one compile under the PR-17 sentinel
          (the per-slice span key carries no slice index);
        - slices are fresh `slice_in_dim` arrays, so the programs'
          donation frees only slice transients — `self.cols/meta/_err`
          stay alive and the PR-6 lane-ladder retry-in-place works
          unchanged;
        - the sticky decode-error scalar threads slice→slice (a copy of
          `self._err` seeds slice 0 — the original is never donated);
        - per-slice readouts fold on device into the ONE `[N_READOUT]`
          future the drain already parses: zero new syncs (PR-5);
        - on a multi-device host, slices round-robin across the batch
          mesh (`ytpu.parallel.mesh.subbatch_devices`); single-device
          placement is a no-op, keeping CPU dispatch byte-identical.
        """
        from ytpu.parallel.mesh import subbatch_devices
        from ytpu.utils.phases import (
            NULL_SPAN,
            phases as _phases,
            program_memory as _program_memory,
        )

        D = self.cols.shape[1]
        n_sub = (D + width - 1) // width
        placements = subbatch_devices(n_sub)
        err = jnp.bitwise_or(self._err, jnp.zeros((), I32))
        outs_c, outs_m, readouts = [], [], []
        for i, lo in enumerate(range(0, D, width)):
            hi = min(lo + width, D)
            sub_cols = jax.lax.slice_in_dim(self.cols, lo, hi, axis=1)
            sub_meta = jax.lax.slice_in_dim(self.meta, lo, hi, axis=0)
            dev_i = dev
            if placements is not None:
                tgt = placements[i]
                sub_cols = jax.device_put(sub_cols, tgt)
                sub_meta = jax.device_put(sub_meta, tgt)
                err = jax.device_put(err, tgt)
                dev_i = tuple(jax.device_put(a, tgt) for a in dev)
            span = (
                _phases.span(
                    "replay.subbatch",
                    (sub_cols.shape, stage, span_tail, lane,
                     self.d_block, vmem_mb, scan_plan),
                    axes=("state", "stage", "tail", "lane", "d_block",
                          "vmem_mb", "scan_plan"),
                    memory=_program_memory(
                        program, sub_cols, sub_meta, err, *dev_i,
                        self.rank, lane=lane, d_block=self.d_block,
                        interpret=self.interpret, vmem_mb=vmem_mb,
                        scan_plan=scan_plan, **program_kw,
                    ),
                )
                if _phases.enabled
                else NULL_SPAN
            )
            with span:
                sub_cols, sub_meta, err, ro = program(
                    sub_cols,
                    sub_meta,
                    err,
                    *dev_i,
                    self.rank,
                    lane=lane,
                    d_block=self.d_block,
                    interpret=self.interpret,
                    vmem_mb=vmem_mb,
                    scan_plan=scan_plan,
                    **program_kw,
                )
            outs_c.append(sub_cols)
            outs_m.append(sub_meta)
            readouts.append(ro)
        if placements is not None:
            # gather outputs onto one device before reassembly (the
            # follow-up NamedSharding-resident layout stays ROADMAP work)
            home = placements[0]
            outs_c = [jax.device_put(a, home) for a in outs_c]
            outs_m = [jax.device_put(a, home) for a in outs_m]
            readouts = [jax.device_put(a, home) for a in readouts]
            err = jax.device_put(err, home)
        cols = jnp.concatenate(outs_c, axis=1) if n_sub > 1 else outs_c[0]
        meta = jnp.concatenate(outs_m, axis=0) if n_sub > 1 else outs_m[0]
        readout = (
            _fold_subbatch_readouts(jnp.stack(readouts))
            if n_sub > 1
            else readouts[0]
        )
        self.stats.subbatch_width = width
        if _phases.enabled:
            _phases.set_value("subbatch.width", width)
            _phases.set_value("subbatch.n_sub", n_sub)
        return cols, meta, err, readout

    # ----------------------------------------------------------- readouts

    def _drain_readouts(self) -> int:
        """Materialize every pending readout; returns the freshest actual
        occupancy. Raises on a sticky device error flag."""
        from ytpu.utils.phases import phases as _phases

        hi = self._hi_bound
        if self._pending:
            if _phases.enabled:
                # the original [3]-word occupancy/error readout keeps its
                # historical 12-byte accounting (the zero-sync invariant
                # test pins it); the scan-width words riding the SAME
                # future attribute separately — one future, no new sync
                _phases.transfer(
                    "replay.readout", 12 * len(self._pending), "d2h"
                )
                _phases.transfer(
                    "integrate.scan_hist",
                    4 * SCAN_REC_WORDS * len(self._pending),
                    "d2h",
                )
                # the ISSUE-13 commitment word rides the same future:
                # its 4 bytes attribute separately, `replay.readout`
                # keeps its historical 12-byte accounting
                _phases.transfer(
                    "integrate.commit_word", 4 * len(self._pending), "d2h"
                )
                # the ISSUE-18 capacity-ledger words ride it too: their
                # bytes attribute under their own stage so every pinned
                # historical accounting above stays exact
                _phases.transfer(
                    "capacity.ledger",
                    4 * LEDGER_WORDS * len(self._pending),
                    "d2h",
                )
            sticky_derr = 0
            for fut in self._pending:
                try:
                    vals = np.asarray(fut)
                except Exception as e:
                    # an async dispatch whose EXECUTION died surfaces
                    # here, not at the dispatch call — the packed state
                    # downstream of it is unusable, so record the sticky
                    # demotion and hand the caller the resume path
                    if not is_device_fault(e):
                        raise
                    demote_lane(self._family, self.lane)
                    self.stats.demotions += 1
                    self._pending.clear()
                    raise ReplayFault(
                        f"deferred device fault at readout on lane "
                        f"{self.lane!r} ({type(e).__name__}: {e})",
                        chunk=self.stats.chunks,
                        lane=self.lane,
                        cause=e,
                    ) from e
                occ, kerr = int(vals[0]), int(vals[1])
                derr = int(vals[2]) if vals.shape[0] > 2 else 0
                if vals.shape[0] >= N_READOUT:
                    # meta carries the CUMULATIVE record, so the freshest
                    # readout supersedes earlier ones in the same drain
                    self._record_scan_width(
                        vals[3 : 3 + SCAN_WIDTH_BUCKETS],
                        int(vals[3 + SCAN_WIDTH_BUCKETS]),
                        vals[3 + SCAN_WIDTH_BUCKETS + 1 : 3 + SCAN_REC_WORDS],
                    )
                    # ISSUE-13 commitment word: recomputed from the
                    # state per readout, so the freshest one is THE
                    # current value (uint32 bit pattern of an i32 word)
                    self.stats.commit_word = (
                        int(vals[3 + SCAN_REC_WORDS]) & 0xFFFFFFFF
                    )
                    if _phases.enabled:
                        _phases.set_value(
                            "integrate.commit_word", self.stats.commit_word
                        )
                    # ISSUE-18 capacity ledger: same freshest-supersedes
                    # semantics — the words are recomputed from the
                    # CURRENT state at each readout
                    base = 4 + SCAN_REC_WORDS
                    self._record_capacity_ledger(
                        int(vals[base]),
                        int(vals[base + 1]),
                        int(vals[base + 2]),
                    )
                self.stats.peak_blocks = max(self.stats.peak_blocks, occ)
                if derr != 0:
                    if self.quarantine and self.on_quarantine is not None:
                        sticky_derr |= derr  # handled once after the loop
                    else:
                        self._raise_decode_error(derr)
                if kerr != 0:
                    self._raise_device_error()
                hi = occ
            self._pending.clear()
            self.stats.syncs += 1
            self._hi_bound = hi
            if sticky_derr:
                # skip-and-record: flagged lanes already integrated as
                # no-ops on device, so recording the offenders and
                # clearing the sticky scalar IS the recovery
                newly = self.on_quarantine(sticky_derr) or []
                self.stats.quarantined += len(newly)
                _QUARANTINED.inc(len(newly))
                self._err = jnp.zeros((), I32)
        return hi

    def _record_scan_width(self, buckets, observed_max: int, tiers=()) -> None:
        """Fold one materialized readout's scan words into the driver
        stats and the `integrate.scan_width_*` / `integrate.scan_tier_*`
        phase gauges (ISSUE-11/12). Called only from drains — the record
        arrives on the readout future the host was already blocking on,
        so this adds ZERO device syncs. Gauges land twice: the base key
        and a `.{lane}`-suffixed key, so fused- and packed-XLA-lane
        distributions stay separately regressable."""
        from ytpu.utils.phases import phases as _phases

        counts = [int(c) for c in buckets]
        mx = int(observed_max)
        st = self.stats
        st.scan_hist = tuple(counts)
        st.scan_max = mx
        st.scan_p50 = scan_width_quantile(counts, 0.50, mx)
        st.scan_p99 = scan_width_quantile(counts, 0.99, mx)
        tiers = [int(t) for t in tiers]
        if len(tiers) == SCAN_REC_WORDS - SCAN_WIDTH_BUCKETS - 1:
            cheap, wide, cheap_trips, wide_trips, width_sum = tiers
            st.scan_tier_cheap = cheap
            st.scan_tier_wide = wide
            st.scan_trips_serial = width_sum
            st.scan_trips_two_tier = cheap_trips + wide_trips
        if _phases.enabled and sum(counts):
            for name, v in (
                ("width_p50", st.scan_p50),
                ("width_p99", st.scan_p99),
                ("width_max", st.scan_max),
                ("tier_cheap", st.scan_tier_cheap),
                ("tier_wide", st.scan_tier_wide),
                ("trips_serial", st.scan_trips_serial),
                ("trips_two_tier", st.scan_trips_two_tier),
            ):
                _phases.set_value(f"integrate.scan_{name}", v)
                _phases.set_value(
                    f"integrate.scan_{name}.{self.lane}", v
                )

    def _record_capacity_ledger(
        self, occupied: int, dead: int, dead_max: int
    ) -> None:
        """Fold one materialized readout's capacity-ledger words into
        the driver stats, the `capacity.*` phase gauges, and (when set)
        the headroom forecaster (ISSUE-18). Called only from drains —
        the words arrive on the readout future the host was already
        blocking on, so this adds ZERO device syncs."""
        from ytpu.utils.phases import phases as _phases

        st = self.stats
        st.occupied_rows = int(occupied)
        st.dead_rows = int(dead)
        st.dead_max = int(dead_max)
        D = self.cols.shape[1]
        total = D * self.capacity
        if self.forecaster is not None:
            self.forecaster.observe(
                n_docs=D,
                capacity=self.capacity,
                occupied_rows=st.occupied_rows,
                dead_rows=st.dead_rows,
                chunks=st.chunks,
                max_capacity=self.max_capacity,
            )
        if _phases.enabled:
            for name, v in (
                ("occupied_rows", st.occupied_rows),
                ("dead_rows", st.dead_rows),
                ("dead_max", st.dead_max),
                ("free_rows", total - st.occupied_rows),
                (
                    "dead_fraction",
                    st.dead_rows / max(st.occupied_rows, 1),
                ),
                (
                    "occupancy_fraction",
                    st.occupied_rows / max(total, 1),
                ),
            ):
                _phases.set_value(f"capacity.{name}", v)

    def _raise_device_error(self):
        meta_np = np.asarray(self.meta)
        bad = meta_np[meta_np[:, M_ERROR] != 0][:4]
        raise RuntimeError(f"device error flags {bad}")

    def _raise_decode_error(self, flags_or: int):
        if self.on_decode_error is not None:
            self.on_decode_error(flags_or)  # expected to raise
        raise RuntimeError(
            f"device decode flagged errors in a deferred chunk (sticky "
            f"flags {flags_or}); replay with sync_every_chunk=True to "
            "localize the update"
        )

    # ------------------------------------------- lane ladder (ISSUE-6)

    def _refresh_origin_slot_packed(self) -> None:
        """Demotion repair: chunks run by the fused kernel leave the
        packed origin_slot cache plane stale, and the packed-XLA chunk
        step's conflict scan READS that plane — rebuild it before the
        first post-demotion XLA chunk (rare failure path; the O(D·B²)
        rebuild cost is irrelevant next to the fault it recovers from)."""
        from ytpu.models.batch_doc import recompute_origin_slot

        state = unpack_state(self.cols, self.meta, None)
        state = recompute_origin_slot(state)
        self.cols, self.meta = pack_state(state)

    def _absorb_lane_fault(self, e: BaseException) -> None:
        """Classify one dispatch failure: demote-and-return when the SAME
        chunk can retry in place on the next rung, else raise
        `ReplayFault` for the caller's checkpoint-resume path.  Host-side
        programming errors re-raise untouched."""
        if not is_device_fault(e):
            raise e
        kill = isinstance(e, FaultError) and bool(e.spec.args.get("kill"))
        alive = _buffers_alive(self.cols, self.meta, self._err)
        nxt = demote_lane(self._family, self.lane)
        if nxt is not None:
            self.stats.demotions += 1
        if kill or not alive or nxt is None or nxt == "host":
            raise ReplayFault(
                f"device dispatch failed on lane {self.lane!r} "
                f"({type(e).__name__}: {e})"
                + ("" if alive else " — state buffers lost to donation"),
                chunk=self.stats.chunks,
                lane=self.lane,
                cause=e,
            ) from e
        if self.lane == "fused":
            self._refresh_origin_slot_packed()
        self.lane = nxt
        self.stats.recoveries += 1
        _RECOVERIES.inc()

    def _dispatch(self, fn):
        """Run one chunk dispatch under the lane-health ladder: an
        injected or real dispatch/compile failure demotes the family one
        rung (sticky) and retries the SAME chunk in place while the state
        buffers survive; past the driver's rungs — or on simulated worker
        death (`replay.kill`) — it raises `ReplayFault` instead."""
        while True:
            try:
                faults.maybe_raise("dispatch.fail", lane=self.lane)
                out = fn(self.lane)
            except Exception as e:
                self._absorb_lane_fault(e)
                continue
            spec = faults.fire("replay.kill", lane=self.lane)
            if spec is not None:
                raise ReplayFault(
                    "injected mid-replay kill (state treated as lost)",
                    chunk=self.stats.chunks,
                    lane=self.lane,
                    cause=FaultError("replay.kill", spec),
                )
            _LAST_DISPATCH.set(time.time())
            return out

    # ------------------------------------------------------- compact/grow

    def compact(self) -> int:
        """Force a commit-style on-device compaction of the packed state;
        returns the actual high-water block count afterwards. Efficacy
        accounting (ISSUE-18): rows reclaimed vs the freshest
        pre-compaction ledger, and the chunk gap since the previous
        compaction (time-to-watermark) — both from readouts the call
        was already draining, zero new syncs."""
        from ytpu.ops.compaction import compact_packed
        from ytpu.utils.phases import phases as _phases

        occ_before = self.stats.occupied_rows
        sub_w = self._active_sub_width()
        if sub_w is None:
            self.cols, self.meta = compact_packed(
                self.cols, self.meta, self.unit_refs, self.gc_ranges
            )
        else:
            # compact_packed vmaps per doc, so per-slice compaction is
            # byte-identical — but its temp-heavy transient now peaks at
            # the sub width, not the monolith (ISSUE-20)
            self.cols, self.meta = self._map_subbatches(
                lambda c, m: compact_packed(
                    c, m, self.unit_refs, self.gc_ranges
                ),
                sub_w,
            )
        self.stats.compactions += 1
        if self._last_compact_chunk >= 0:
            self.stats.compact_gap_chunks = (
                self.stats.chunks - self._last_compact_chunk
            )
        self._last_compact_chunk = self.stats.chunks
        self._pending.append(_chunk_readout(self.cols, self.meta, self._err))
        hi = self._drain_readouts()
        reclaimed = max(0, occ_before - self.stats.occupied_rows)
        self.stats.reclaimed_rows += reclaimed
        if _phases.enabled:
            _phases.add_value("capacity.reclaimed_rows", reclaimed)
            _phases.set_value(
                "capacity.compact_gap_chunks", self.stats.compact_gap_chunks
            )
        return hi

    def ensure_room(self, margin: int) -> None:
        """Compact (and grow, when allowed) BEFORE a chunk whose worst-case
        growth is `margin`, so ERR_CAPACITY — which corrupts the tile —
        cannot fire mid-chunk."""
        if not self.policy.should_compact(self._hi_bound, margin, self.capacity):
            return
        hi = self._drain_readouts()
        if not self.policy.should_compact(hi, margin, self.capacity):
            return
        hi = self.compact()
        while hi + margin > self.capacity:
            new_cap = min(self.capacity * 2, self.max_capacity)
            if new_cap <= self.capacity:
                # `<=`, not `==`: a max_capacity BELOW the current
                # capacity used to fall through into grow_packed and
                # raise its misleading "cannot shrink" (PR-4 review) —
                # either way the real condition is capacity exhaustion
                raise RuntimeError(
                    f"state needs {hi + margin} block slots but replay "
                    f"is capacity-exhausted: max_capacity "
                    f"{self.max_capacity} (current capacity "
                    f"{self.capacity})"
                )
            from ytpu.ops.compaction import grow_packed

            # ISSUE-20 satellite: the forecaster is consulted BEFORE the
            # grow attempt — a modeled transient that busts the budget
            # narrows the sub-batch width instead of provoking the OOM
            if self.shard_docs:
                self._forecast_narrow(new_cap)
            try:
                spec = faults.fire("grow.oom")
                if spec is not None:
                    # typed denial (ISSUE-18): report attempted vs
                    # available bytes so chaos can score the /capacity
                    # forecaster against reality, and count it
                    from ytpu.utils.capacity import memory_budget_bytes

                    _GROW_DENIED.inc()
                    D = self.cols.shape[1]
                    raise GrowOomError(
                        spec,
                        capacity=self.capacity,
                        new_capacity=new_cap,
                        n_docs=D,
                        attempted_bytes=packed_state_bytes(D, new_cap),
                        available_bytes=int(
                            spec.args.get(
                                "budget", memory_budget_bytes()
                            )
                        ),
                    )
                sub_w = self._active_sub_width()
                if sub_w is None:
                    self.cols, self.meta = grow_packed(
                        self.cols, self.meta, new_cap
                    )
                else:
                    self.cols, self.meta = self._map_subbatches(
                        lambda c, m: grow_packed(c, m, new_cap), sub_w
                    )
            except Exception as e:
                if (
                    isinstance(e, GrowOomError)
                    and self.shard_docs
                    and self._narrow_subbatch("grow.oom")
                ):
                    # ISSUE-20: a denied grow demotes to a narrower
                    # sub-batch width and retries the SAME capacity step
                    # instead of killing the chunk (the armed fault was
                    # consumed firing, so the retry proceeds)
                    continue
                if not is_device_fault(e):
                    raise
                # a failed growth (device OOM) leaves the pre-grow state
                # valid but the next chunk unservable — checkpoint-resume
                # territory, not an in-place retry
                raise ReplayFault(
                    f"grow to capacity {new_cap} failed "
                    f"({type(e).__name__}: {e})",
                    chunk=self.stats.chunks,
                    lane=self.lane,
                    cause=e,
                ) from e
            self.stats.growths += 1
            self.stats.capacity = new_cap

    # --------------------------------------------------------------- step

    def step(self, stream, margin: Optional[int] = None) -> None:
        """Integrate one [S, ...] stream chunk (doc-free leading step axis,
        the `apply_update_stream` shape). `margin` is the chunk's worst-
        case slot growth; pass it when known host-side (e.g. from
        `ReplayPlan.adds`) to avoid touching the stream's valid masks."""
        from ytpu.models.batch_doc import stream_worst_case_adds
        from ytpu.utils.phases import (
            NULL_SPAN,
            phases as _phases,
            program_memory as _program_memory,
        )

        if margin is None:
            margin = int(stream_worst_case_adds(stream).sum()) + 8
        self.ensure_room(margin)

        # two-tier scan plan: env re-read per chunk, static through both
        # lanes' programs so a changed knob retraces (ADVICE r5 #2 shape)
        scan_plan = scan_tier_plan()

        def dispatch(lane):
            if lane == "fused":
                rows, dels = pack_stream(stream)
                # YTPU_FUSED_VMEM_MB rides `_run` as a STATIC arg (read
                # per chunk): a changed limit forces a retrace instead of
                # silently reusing the old compiled guard (ADVICE r5 #2)
                vmem_mb = int(os.environ.get("YTPU_FUSED_VMEM_MB", "64"))
                if _phases.enabled:
                    _phases.transfer(
                        "replay.chunk_fused",
                        rows.size * rows.dtype.itemsize
                        + dels.size * dels.dtype.itemsize,
                        "h2d",
                    )
                    span = _phases.span(
                        "replay.chunk_fused",
                        (self.cols.shape, rows.shape, dels.shape,
                         self.d_block, scan_plan),
                        axes=("state", "rows", "dels", "d_block",
                              "scan_plan"),
                        memory=_program_memory(
                            _run, self.cols, self.meta,
                            (rows, dels, self.rank), self.d_block,
                            self.interpret, 3, 4, vmem_mb, scan_plan,
                        ),
                    )
                else:
                    span = NULL_SPAN
                with span:
                    return _run(
                        self.cols,
                        self.meta,
                        (rows, dels, self.rank),
                        self.d_block,
                        self.interpret,
                        3,
                        4,
                        vmem_mb,
                        scan_plan,
                    )
            span = (
                _phases.span(
                    "replay.chunk_xla",
                    (self.cols.shape, stream.client.shape, scan_plan),
                    axes=("state", "stream", "scan_plan"),
                    # the jitted step is a lazily-built module singleton:
                    # resolve it at thunk-invoke time (the span body
                    # constructs it on the very first call)
                    memory=_program_memory(
                        lambda: _XLA_CHUNK_STEP, self.cols, self.meta,
                        stream, self.rank, scan_plan,
                    ),
                )
                if _phases.enabled
                else NULL_SPAN
            )
            with span:
                return xla_chunk_step(
                    self.cols, self.meta, stream, self.rank, scan_plan
                )

        self.cols, self.meta = self._dispatch(dispatch)
        self._pending.append(_chunk_readout(self.cols, self.meta, self._err))
        self._hi_bound += margin
        self.stats.chunks += 1
        if self.sync_every_chunk:
            self._drain_readouts()

    def _step_one_dispatch(self, stage, host_arrays, margin, span_tail,
                           program, span_axes=(), **program_kw):
        """Shared mechanics of the one-dispatch byte lanes (`step_bytes`
        / `step_raw`): progbudget tick, pre-chunk room check, the
        zero-copy-backend host copy, h2d accounting, the lane-laddered
        dispatch, and the readout/occupancy-bound epilogue — one copy,
        so a fix to any of them (e.g. the `_transfer_aliases_host` race
        guard) can never reach one lane and miss the other. The program
        is called as ``program(cols, meta, err, *device_arrays, rank,
        lane=..., ...program_kw..., d_block/interpret/vmem_mb)``;
        `span_tail` extends the phases span key with the lane-specific
        shape statics. Returns the device input arrays (the caller's
        slot-reuse gate)."""
        from ytpu.utils import progbudget
        from ytpu.utils.phases import (
            NULL_SPAN,
            phases as _phases,
            program_memory as _program_memory,
        )

        progbudget.tick()
        self.ensure_room(margin)
        vmem_mb = int(os.environ.get("YTPU_FUSED_VMEM_MB", "64"))
        # two-tier scan plan: env re-read per chunk, threaded as a static
        # of the one-dispatch programs — a changed knob retraces
        scan_plan = scan_tier_plan()
        if _transfer_aliases_host():
            host_arrays = tuple(a.copy() for a in host_arrays)
        dev = tuple(jnp.asarray(a) for a in host_arrays)
        if _phases.enabled:
            _phases.transfer(
                stage,
                sum(a.size * a.dtype.itemsize for a in dev),
                "h2d",
            )
        sub_w = self._active_sub_width()

        def dispatch(lane):
            if sub_w is not None:
                return self._dispatch_subbatched(
                    lane, sub_w, stage, span_tail, dev, vmem_mb,
                    scan_plan, program, program_kw,
                )
            span = (
                _phases.span(
                    stage,
                    (self.cols.shape, *span_tail, lane, self.d_block,
                     vmem_mb, scan_plan),
                    axes=("state", *span_axes, "lane", "d_block",
                          "vmem_mb", "scan_plan"),
                    memory=_program_memory(
                        program, self.cols, self.meta, self._err, *dev,
                        self.rank, lane=lane, d_block=self.d_block,
                        interpret=self.interpret, vmem_mb=vmem_mb,
                        scan_plan=scan_plan, **program_kw,
                    ),
                )
                if _phases.enabled
                else NULL_SPAN
            )
            with span:
                return program(
                    self.cols,
                    self.meta,
                    self._err,
                    *dev,
                    self.rank,
                    lane=lane,
                    d_block=self.d_block,
                    interpret=self.interpret,
                    vmem_mb=vmem_mb,
                    scan_plan=scan_plan,
                    **program_kw,
                )

        self.cols, self.meta, self._err, readout = self._dispatch(dispatch)
        self._pending.append(readout)
        self._hi_bound += margin
        self.stats.chunks += 1
        if self.sync_every_chunk:
            self._drain_readouts()
        return dev

    def step_bytes(self, buf, lens, refs, dims, margin: int):
        """Integrate one chunk straight from padded wire bytes: decode →
        unit-ref rebase → integrate → readout as ONE dispatch
        (`replay_chunk_program`, donated state) — the async replay
        loop's zero-sync steady state. `dims` is the decode-shape tuple
        ``(max_rows, max_dels, n_steps, max_sections)`` (from
        `ReplayPlan`); `refs` the chunk's ``[S, U]`` global unit-ref
        rows (-1 = keep the decoded ref); `margin` the chunk's
        worst-case slot growth. Decode errors fold into the sticky
        device scalar and surface at the next drain / `finish()`.

        Returns the device input arrays: the caller gates reuse of the
        numpy staging buffers on their transfer completing
        (`block_until_ready` on an INPUT waits for the h2d copy only —
        it is not a result materialization). On a backend whose
        "transfer" is zero-copy (CPU jax aliases the numpy buffer), the
        arrays are copied host-side first so a re-packed slot can never
        race the program still reading it."""
        max_rows, max_dels, n_steps, max_sections = dims
        return self._step_one_dispatch(
            "replay.chunk_async",
            (buf, lens, refs),
            margin,
            (buf.shape, refs.shape, tuple(dims)),
            replay_chunk_program,
            span_axes=("buf", "refs", "dims"),
            max_rows=max_rows,
            max_dels=max_dels,
            n_steps=n_steps,
            max_sections=max_sections,
        )

    def step_raw(self, raw, offs, lens, refs, dims, width: int, margin: int):
        """Integrate one chunk straight from RAW CONCATENATED wire bytes
        + a per-update offsets table: device lane-gather → decode →
        unit-ref rebase → integrate → readout as ONE dispatch
        (`replay_chunk_program_raw`, donated state) — the raw ingest
        lane whose host staging is a memcpy (ISSUE-7). ``width`` is the
        static per-lane window (the host-packed lane's ``pad_to``), the
        other arguments mirror `step_bytes`, including the returned
        device inputs for the caller's slot-reuse gate and the
        zero-copy-backend host copy."""
        max_rows, max_dels, n_steps, max_sections = dims
        return self._step_one_dispatch(
            "replay.chunk_raw",
            (raw, offs, lens, refs),
            margin,
            (raw.shape, refs.shape, tuple(dims), width),
            replay_chunk_program_raw,
            span_axes=("raw", "refs", "dims", "width"),
            width=width,
            max_rows=max_rows,
            max_dels=max_dels,
            n_steps=n_steps,
            max_sections=max_sections,
        )

    def finish(self):
        """Drain every pending readout (surfacing sticky errors) and
        return the packed (cols, meta)."""
        self._drain_readouts()
        self.stats.capacity = self.capacity
        self.stats.final_blocks = int(
            np.asarray(self.meta)[:, M_NBLOCKS].max()
        )
        return self.cols, self.meta


def replay_stream_fused(
    state: DocStateBatch,
    stream: UpdateBatch,
    client_rank: jax.Array,
    *,
    chunk_steps: int = 64,
    d_block: int = 8,
    interpret: bool = False,
    lane: str = "fused",
    policy=None,
    max_capacity: Optional[int] = None,
    refresh_cache: bool = False,
    shard_docs: bool = False,
    forecaster=None,
) -> Tuple[DocStateBatch, ReplayChunkStats]:
    """Chunked fused replay of a stacked [S, ...] update stream with
    between-chunk device compaction — `apply_update_stream_fused` for
    streams whose PEAK block count exceeds the tile capacity.

    The stream is cut into fixed `chunk_steps` windows (one compiled
    program serves every chunk; the tail pads with valid=False steps),
    each window runs through the fused kernel (`lane="fused"`) or the
    packed XLA chunk step (`lane="xla"`, the CPU-testable / Mosaic-
    fallback twin), and between windows the shared `CompactionPolicy`
    decides when the packed state squashes (`compact_packed`) or grows
    (`grow_packed`) — never unpacking to host mid-replay. Returns the
    final state plus `ReplayChunkStats`.

    origin_slot cache: the fused lane marks the returned state stale
    (same contract as `apply_update_stream_fused`; `refresh_cache=True`
    opts into the eager O(D·B²) rebuild); the XLA lane maintains the
    cache in-kernel, so the input is `ensure_origin_slot`'d up front and
    the output stays fresh — compaction's defrag remap preserves the
    containment contract either way.

    ``shard_docs=True`` (ISSUE-20) enables the driver's doc-axis
    sub-batch plan for this stream replay: the per-step integrate
    dispatch stays monolithic (the stacked-stream path carries no
    per-slice readout fold), but between-chunk `compact_packed` /
    `grow_packed` run per pow2-width doc slice under the budget
    (``forecaster`` optionally pins it) — the mixed-content twin of the
    byte-stream path's fully sliced dispatch."""
    from ytpu.models.batch_doc import stream_worst_case_adds

    if lane == "xla":
        from ytpu.models.batch_doc import ensure_origin_slot

        state = ensure_origin_slot(state)
    S = stream.valid.shape[0]
    if S == 0:
        return state, ReplayChunkStats(capacity=state.blocks.client.shape[-1])
    adds = stream_worst_case_adds(stream)
    initial = int(np.asarray(state.n_blocks).max())
    cols, meta = pack_state(state)
    driver = PackedReplayDriver(
        cols,
        meta,
        client_rank,
        d_block=d_block,
        interpret=interpret,
        lane=lane,
        policy=policy,
        max_capacity=max_capacity,
        initial_occupancy=initial,
        shard_docs=shard_docs,
    )
    driver.forecaster = forecaster
    for s in range(0, S, chunk_steps):
        e = min(S, s + chunk_steps)
        chunk = jax.tree_util.tree_map(lambda a: a[s:e], stream)
        if e - s < chunk_steps:
            # pad the tail to the compiled shape: replicate the last step,
            # then invalidate the padding rows/deletes
            pad = chunk_steps - (e - s)

            def _pad(a):
                tail = jnp.broadcast_to(a[-1:], (pad,) + a.shape[1:])
                return jnp.concatenate([a, tail], axis=0)

            chunk = jax.tree_util.tree_map(_pad, chunk)
            chunk = chunk._replace(
                valid=chunk.valid.at[e - s :].set(False),
                del_valid=chunk.del_valid.at[e - s :].set(False),
            )
        driver.step(chunk, margin=int(adds[s:e].sum()) + 8)
    cols, meta = driver.finish()
    out = unpack_state(cols, meta, state)
    if lane == "fused":
        if refresh_cache:
            from ytpu.models.batch_doc import recompute_origin_slot

            return recompute_origin_slot(out), driver.stats
        from ytpu.models.batch_doc import mark_origin_slot_stale

        mark_origin_slot_stale(out)
    return out, driver.stats


def _register_programs():
    from ytpu.utils import progbudget

    progbudget.register("fused_run", _run)
    # the chunk programs (fused decode+rebase+integrate, host-packed and
    # raw-gather variants) are the largest executables in the process —
    # one per (chunk, width, refs, state) shape family; they must ride
    # the same bounded-arena budget
    progbudget.register("replay_chunk_program", replay_chunk_program)
    progbudget.register("replay_chunk_program_raw", replay_chunk_program_raw)


_register_programs()
