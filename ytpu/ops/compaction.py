"""Device compaction: commit-time block squash + GC collapse, vmapped.

The reference compacts continuously at commit: `Item::try_squash` merges a
block into its clock-contiguous right neighbor (block.rs:775-799,
squash_left at block_store.rs:243), and the GC collector replaces deleted
non-kept items with content-free GC ranges (gc.rs:11-65). The device engine
appends rows forever, so long-lived docs fill their capacity with 1-element
blocks; this pass is the batched equivalent, run as one jitted program:

1. **GC conversion** — tombstoned value rows (string/any/binary/json/
   embed/format) drop their payload reference and become CONTENT_DELETED
   rows, exactly like the host oracle's collector: the item (with its
   origin/right-origin anchors) stays in the graph so wire encodes remain
   integrable by fresh replicas; only the payload is discarded. Structural
   rows (type/move/doc) are preserved.
2. **Squash** — a row merges into its sequence-right neighbor under the
   exact try_squash conditions (same client, contiguous clocks, the
   neighbor's origin is the row's last id, equal right-origins, equal
   deleted/moved/key/parent, mergeable content: same payload ref with
   contiguous offsets for string/any, unconditionally for GC/deleted).
   Chains collapse in one pass via pointer doubling + segment sums.
3. **Defragmentation** — surviving rows are packed to the front (slot
   order preserved), every index column (left/right/parent/head/moved,
   sequence starts) remapped, and n_blocks shrinks accordingly.

Semantics parity is testable: replay -> compact -> keep replaying must
match the host oracle exactly (tests/test_compaction.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ytpu.core.content import (
    BLOCK_GC,
    CONTENT_ANY,
    CONTENT_BINARY,
    CONTENT_DELETED,
    CONTENT_EMBED,
    CONTENT_FORMAT,
    CONTENT_JSON,
    CONTENT_STRING,
)
from ytpu.models.batch_doc import COL_DEFAULTS, BlockCols, DocStateBatch

__all__ = ["compact_state", "grow_state", "compact_packed", "grow_packed"]

I32 = jnp.int32

# kinds whose tombstones GC to content-free deleted rows (value content;
# the reference's ItemContent::gc drops these payloads outright)
_GCABLE = (
    CONTENT_JSON,
    CONTENT_BINARY,
    CONTENT_STRING,
    CONTENT_EMBED,
    CONTENT_FORMAT,
    CONTENT_ANY,
)
# content kinds mergeable under try_squash when payload refs are contiguous
_SPLICEABLE = (CONTENT_STRING, CONTENT_ANY)


def _compact_one(state: DocStateBatch) -> DocStateBatch:
    bl = state.blocks
    B = bl.client.shape[-1]
    slots = jnp.arange(B, dtype=I32)
    n = state.n_blocks
    active = slots < n

    # --- 1. GC conversion (gc.rs:11-65) ------------------------------------
    gcable = jnp.zeros((B,), bool)
    for k in _GCABLE:
        gcable = gcable | (bl.kind == k)
    convert = active & bl.deleted & gcable
    kind = jnp.where(convert, CONTENT_DELETED, bl.kind)
    content_ref = jnp.where(convert, -1, bl.content_ref)
    content_off = jnp.where(convert, 0, bl.content_off)
    bl = bl._replace(kind=kind, content_ref=content_ref, content_off=content_off)

    # --- 2. squash eligibility a -> b = right[a] (block.rs:775-799) --------
    b = bl.right
    sb = jnp.maximum(b, 0)

    def g(col):
        return col[sb]

    ror_eq = (bl.ror_client == g(bl.ror_client)) & (
        (bl.ror_client < 0) | (bl.ror_clock == g(bl.ror_clock))
    )
    origin_chain = (g(bl.origin_client) == bl.client) & (
        g(bl.origin_clock) == bl.clock + bl.length - 1
    )
    spliceable = jnp.zeros((B,), bool)
    for k in _SPLICEABLE:
        spliceable = spliceable | (bl.kind == k)
    content_ok = (bl.kind == g(bl.kind)) & (
        (bl.kind == BLOCK_GC)
        | (bl.kind == CONTENT_DELETED)
        | (
            spliceable
            & (bl.content_ref == g(bl.content_ref))
            & (g(bl.content_off) == bl.content_off + bl.length)
        )
    )
    elig = (
        active
        & (b >= 0)
        & (b < n)
        & (bl.client == g(bl.client))
        & (g(bl.clock) == bl.clock + bl.length)
        & origin_chain
        & ror_eq
        & (bl.deleted == g(bl.deleted))
        & (bl.moved == g(bl.moved))
        & (bl.key == g(bl.key))
        & (bl.parent == g(bl.parent))
        & (g(bl.left) == slots)  # well-formed adjacency both ways
        & content_ok
    )

    # a row is absorbed into its chain head iff its left neighbor merges
    # rightward into it
    sl = jnp.maximum(bl.left, 0)
    merged_away = active & (bl.left >= 0) & elig[sl]

    # chain representative via pointer doubling: parent = left when absorbed
    rep = jnp.where(merged_away, bl.left, slots)
    n_doubling = max(1, B.bit_length())
    for _ in range(n_doubling):
        rep = rep[jnp.maximum(rep, 0)]

    # per-chain aggregates (segment id = chain head slot)
    seg_len = jax.ops.segment_sum(
        jnp.where(active, bl.length, 0), jnp.maximum(rep, 0), num_segments=B
    )
    # the chain tail (the row that does NOT merge rightward) donates its
    # right pointer to the head
    tail = active & ~elig
    tail_w = jnp.where(tail, rep, B)
    chain_right = jnp.full((B,), -1, I32).at[tail_w].set(bl.right, mode="drop")

    keep = active & ~merged_away
    # heads take the aggregated length + the tail's right pointer
    length = jnp.where(keep, seg_len, bl.length)
    right = jnp.where(keep, chain_right, bl.right)
    bl = bl._replace(length=length, right=right)

    # --- 3. defragment: pack kept rows, remap index columns ----------------
    new_idx = jnp.cumsum(keep.astype(I32)) - 1
    # pointers into absorbed rows redirect to their chain head
    old2new = jnp.where(keep, new_idx, new_idx[jnp.maximum(rep, 0)])

    def remap(col):
        return jnp.where(col >= 0, old2new[jnp.maximum(col, 0)], -1)

    bl = bl._replace(
        left=remap(bl.left),
        right=remap(bl.right),
        parent=remap(bl.parent),
        head=remap(bl.head),
        moved=remap(bl.moved),
        # origin_slot: absorbed rows redirect to their chain head via
        # old2new; the head's widened clock range still contains the
        # origin id, so containment (the cache contract) is preserved
        origin_slot=remap(bl.origin_slot),
    )
    n_new = jnp.sum(keep.astype(I32))
    # kept rows first (slot order preserved), dropped rows after
    order = jnp.argsort(jnp.where(keep, slots, B + slots))
    blank = slots >= n_new

    packed = BlockCols(
        **{
            name: jnp.where(blank, fill, getattr(bl, name)[order])
            for name, fill in COL_DEFAULTS.items()
        }
    )
    start = jnp.where(
        state.start >= 0, old2new[jnp.maximum(state.start, 0)], -1
    )
    return DocStateBatch(
        blocks=packed, start=start, n_blocks=n_new, error=state.error
    )


@partial(jax.jit, donate_argnums=0)
def compact_state(state: DocStateBatch) -> DocStateBatch:
    """Squash + GC + defragment every doc in the batch (one compiled pass).

    The input state is donated: compaction runs exactly when the batch is
    near capacity, so holding two copies of the block columns would double
    HBM at the worst possible moment."""
    return jax.vmap(_compact_one)(state)


def _compact_packed_one(cols, meta, unit_refs: bool, gc_ranges: bool):
    """Squash + GC one doc in the fused kernel's packed domain.

    `cols` is the kernel's [NC, C] column stack, `meta` its [M_PAD] row.
    The full fused-lane schema is honored — map keys, nested parents,
    move ownership/range planes and the origin_slot cache plane all
    survive (slot-valued planes remap through the defrag permutation) —
    so this pass is safe to run at a CHUNK BOUNDARY of the chunked
    replay driver (`integrate_kernel.PackedReplayDriver`): rows the NEXT
    chunk will split (an origin landing mid-block of a squashed run) or
    claim (a live move whose range spans the boundary) keep every
    invariant the kernel's find_slot/claim walks rely on, because merges
    preserve clock-range containment and never cross a difference in
    deleted/moved/key/parent state.

    Two rules beyond `_compact_one`:
    - `gc_ranges`: tombstones become origin-free BLOCK_GC ranges and merge
      under clock contiguity + sequence adjacency alone — the reference's
      default-GC behavior (gc.rs:11-65 drops the item wholesale;
      squash_left_range_compaction block_store.rs:155-235 collapses runs),
      vs the softer skip_gc-style CONTENT_DELETED conversion. A
      tombstoned MOVE row converts like any other: its range planes clear
      with it (the reference drops the move item wholesale), so it can
      merge into adjacent GC runs instead of lingering as an unmergeable
      pseudo-move — safe because the end-of-chunk `recompute_moves` never
      leaves a live claim pointing at a tombstoned owner.
    - `unit_refs`: string content refs are absolute UTF-16-unit offsets
      into a content arena, so runs from *different* updates merge when
      `b.ref + b.off == a.ref + a.off + a.len` — the device equivalent of
      the reference's string concat in try_squash (block.rs:775-799).
    """
    from ytpu.ops.integrate_kernel import (
        CK,
        CL,
        CN,
        DL,
        HD,
        KD,
        KEY,
        LN,
        LT,
        M_NBLOCKS,
        M_START,
        MEA,
        MEC,
        MEK,
        MPR,
        MSA,
        MSC,
        MSK,
        MV,
        OC,
        OF,
        OK,
        OS,
        PA,
        RC,
        RF,
        RK,
        RT,
    )

    C = cols.shape[1]
    slots = jnp.arange(C, dtype=I32)
    n = meta[M_NBLOCKS]
    active = slots < n

    deleted = cols[DL] == 1
    if gc_ranges:
        convert = active & deleted & (cols[KD] != BLOCK_GC)
    else:
        gcable = jnp.zeros((C,), bool)
        for k in _GCABLE:
            gcable = gcable | (cols[KD] == k)
        convert = active & deleted & gcable
    new_kind = I32(BLOCK_GC) if gc_ranges else I32(CONTENT_DELETED)
    kind = jnp.where(convert, new_kind, cols[KD])
    rf = jnp.where(convert, -1, cols[RF])
    of = jnp.where(convert, 0, cols[OF])
    oc = jnp.where(convert & gc_ranges, -1, cols[OC])
    ok = jnp.where(convert & gc_ranges, 0, cols[OK])
    rc = jnp.where(convert & gc_ranges, -1, cols[RC])
    rk = jnp.where(convert & gc_ranges, 0, cols[RK])
    # origin cleared -> cached origin slot cleared with it (cache contract)
    os_c = jnp.where(convert & gc_ranges, -1, cols[OS])
    # converted dead moves drop their range planes (see docstring): the
    # MPR >= 0 squash veto below then no longer pins them apart from the
    # surrounding GC run
    msc = jnp.where(convert & gc_ranges, -1, cols[MSC])
    msk = jnp.where(convert & gc_ranges, 0, cols[MSK])
    msa = jnp.where(convert & gc_ranges, 0, cols[MSA])
    mec = jnp.where(convert & gc_ranges, -1, cols[MEC])
    mek = jnp.where(convert & gc_ranges, 0, cols[MEK])
    mea = jnp.where(convert & gc_ranges, 0, cols[MEA])
    mpr = jnp.where(convert & gc_ranges, -1, cols[MPR])

    cl, ck, ln, lt, rt = cols[CL], cols[CK], cols[LN], cols[LT], cols[RT]

    # --- squash eligibility a -> b = right[a] ------------------------------
    b = rt
    sb = jnp.maximum(b, 0)

    def g(col):
        return col[sb]

    key_c, pa_c = cols[KEY], cols[PA]
    base = (
        active
        & (b >= 0)
        & (b < n)
        & (cl == g(cl))
        & (g(ck) == ck + ln)
        & (g(lt) == slots)
        & (deleted == g(deleted))
        & (key_c == g(key_c))
        & (pa_c == g(pa_c))
        # try_squash parity (block.rs:775-799): `self.moved == other.moved`
        # — rows owned by different moves (or one owned, one not) never
        # merge, and move rows themselves (length-1 ranges) don't either
        & (cols[MV] == g(cols[MV]))
        & (mpr < 0)
        & (mpr[sb] < 0)
    )
    gcish = kind == BLOCK_GC
    # ContentType rows carry live child-sequence heads even when deleted;
    # never merge them away
    no_head = (cols[HD] < 0) & (g(cols[HD]) < 0)
    gc_merge = base & gcish & g(gcish) & no_head

    origin_chain = (g(oc) == cl) & (g(ok) == ck + ln - 1)
    ror_eq = (rc == g(rc)) & ((rc < 0) | (rk == g(rk)))
    if unit_refs:
        content_contig = (g(rf) >= 0) & (rf >= 0) & (
            g(rf) + g(of) == rf + of + ln
        )
    else:
        content_contig = (rf == g(rf)) & (g(of) == of + ln)
    spliceable = jnp.zeros((C,), bool)
    for k in _SPLICEABLE:
        spliceable = spliceable | (kind == k)
    live_merge = (
        base
        & ~deleted
        & spliceable
        & (kind == g(kind))
        & origin_chain
        & ror_eq
        & content_contig
    )
    dead_merge = (
        base
        & (kind == CONTENT_DELETED)
        & (g(kind) == CONTENT_DELETED)
        & origin_chain
        & ror_eq
    )
    elig = gc_merge | live_merge | dead_merge

    sl = jnp.maximum(lt, 0)
    merged_away = active & (lt >= 0) & elig[sl]

    rep = jnp.where(merged_away, lt, slots)
    for _ in range(max(1, C.bit_length())):
        rep = rep[jnp.maximum(rep, 0)]

    seg_len = jax.ops.segment_sum(
        jnp.where(active, ln, 0), jnp.maximum(rep, 0), num_segments=C
    )
    tail = active & ~elig
    tail_w = jnp.where(tail, rep, C)
    chain_right = jnp.full((C,), -1, I32).at[tail_w].set(rt, mode="drop")

    keep = active & ~merged_away
    length = jnp.where(keep, seg_len, ln)
    right = jnp.where(keep, chain_right, rt)

    # --- defragment --------------------------------------------------------
    new_idx = jnp.cumsum(keep.astype(I32)) - 1
    old2new = jnp.where(keep, new_idx, new_idx[jnp.maximum(rep, 0)])

    def remap(col):
        return jnp.where(col >= 0, old2new[jnp.maximum(col, 0)], -1)

    n_new = jnp.sum(keep.astype(I32))
    order = jnp.argsort(jnp.where(keep, slots, C + slots))
    blank = slots >= n_new

    def pack(col, fill):
        return jnp.where(blank, fill, col[order])

    out = jnp.stack(
        [
            pack(cl, -1),  # CL
            pack(ck, 0),  # CK
            pack(length, 0),  # LN
            pack(oc, -1),  # OC
            pack(ok, 0),  # OK
            pack(rc, -1),  # RC
            pack(rk, 0),  # RK
            pack(remap(lt), -1),  # LT
            pack(remap(right), -1),  # RT
            pack(cols[DL], 0),  # DL
            pack(jnp.where(convert, 0, cols[CN]), 0),  # CN
            pack(kind, 0),  # KD
            pack(rf, -1),  # RF
            pack(of, 0),  # OF
            pack(key_c, -1),  # KEY
            pack(remap(pa_c), -1),  # PA
            pack(remap(cols[HD]), -1),  # HD
            pack(remap(cols[MV]), -1),  # MV (slot index: defrag remap)
            pack(msc, -1),  # MSC
            pack(msk, 0),  # MSK
            pack(msa, 0),  # MSA
            pack(mec, -1),  # MEC
            pack(mek, 0),  # MEK
            pack(mea, 0),  # MEA
            pack(mpr, -1),  # MPR
            pack(remap(os_c), -1),  # OS (slot index: defrag remap)
        ]
    )
    start = meta[M_START]
    start = jnp.where(start >= 0, old2new[jnp.maximum(start, 0)], -1)
    meta = meta.at[M_START].set(start).at[M_NBLOCKS].set(n_new)
    return out, meta


@partial(jax.jit, static_argnums=(2, 3), donate_argnums=(0, 1))
def compact_packed(cols, meta, unit_refs: bool = False, gc_ranges: bool = False):
    """Squash + GC + defragment a packed [NC, D, C] state (fused-kernel
    domain, NC=26 incl. the origin_slot plane) without materializing the
    unpacked schema — the full-trace replay compacts at high-water marks
    where holding both layouts would double HBM."""
    f = partial(_compact_packed_one, unit_refs=unit_refs, gc_ranges=gc_ranges)
    return jax.vmap(f, in_axes=(1, 0), out_axes=(1, 0))(cols, meta)


def grow_packed(cols, meta, new_capacity: int):
    """Widen a packed state's capacity (slot indices survive unchanged)."""
    from ytpu.ops.integrate_kernel import (
        CL,
        HD,
        KEY,
        LT,
        MEC,
        MPR,
        MSC,
        MV,
        OC,
        OS,
        PA,
        RC,
        RF,
        RT,
    )

    NC_, D, C = cols.shape
    if new_capacity < C:
        raise ValueError(f"cannot shrink capacity {C} -> {new_capacity}")
    if new_capacity == C:
        return cols, meta
    pad = jnp.zeros((NC_, D, new_capacity - C), I32)
    # -1-filled columns: client/origin/ror clients, links, content ref,
    # move ownership/bound clients/priority (COL_DEFAULTS parity)
    neg = (
        jnp.zeros((NC_,), I32)
        .at[
            jnp.array(
                [CL, OC, RC, LT, RT, RF, KEY, PA, HD, MV, MSC, MEC, MPR, OS]
            )
        ]
        .set(-1)
    )
    pad = pad + neg[:, None, None]
    return jnp.concatenate([cols, pad], axis=2), meta


def grow_state(state: DocStateBatch, new_capacity: int) -> DocStateBatch:
    """Widen every doc's block capacity (host-side repad; index columns are
    slot-based so they survive unchanged). A stale origin_slot flag
    (identity-keyed) propagates to the repadded output."""
    B = state.blocks.client.shape[-1]
    if new_capacity < B:
        raise ValueError(f"cannot shrink capacity {B} -> {new_capacity}")
    if new_capacity == B:
        return state
    pad = new_capacity - B

    cols = {}
    for name, fill in COL_DEFAULTS.items():
        col = getattr(state.blocks, name)
        ext = jnp.full(col.shape[:-1] + (pad,), fill, dtype=col.dtype)
        cols[name] = jnp.concatenate([col, ext], axis=-1)
    out = state._replace(blocks=BlockCols(**cols))
    from ytpu.models.batch_doc import (
        mark_origin_slot_stale,
        origin_slot_is_stale,
    )

    if origin_slot_is_stale(state):
        mark_origin_slot_stale(out)
    return out


# --- phase-timer wrappers (observability layer) -----------------------------
# The jitted bodies stay module-level (progbudget needs the jit objects);
# the public names grow thin host wrappers that attribute first-call
# compile vs steady-state dispatch per compiled key. Disabled path: one
# attribute check, no allocation (SURVEY §5.5 hot-path rule).

_compact_state_jit = compact_state
_compact_packed_jit = compact_packed


def compact_state(state: DocStateBatch) -> DocStateBatch:
    from ytpu.models.batch_doc import (
        mark_origin_slot_stale,
        origin_slot_is_stale,
    )
    from ytpu.utils.phases import NULL_SPAN, phases, program_memory

    # staleness is identity-keyed on the cache array; the defragment
    # remap builds a NEW array, so a stale input must re-mark its output
    # or the unrefreshed cache would launder into a "clean" wrong one
    stale = origin_slot_is_stale(state)
    span = (
        phases.span(
            "compact.state", (state.blocks.client.shape,), axes=("state",),
            memory=program_memory(_compact_state_jit, state),
        )
        if phases.enabled
        else NULL_SPAN
    )
    with span:
        out = _compact_state_jit(state)
    if stale:
        mark_origin_slot_stale(out)
    return out


def compact_packed(cols, meta, unit_refs: bool = False, gc_ranges: bool = False):
    from ytpu.utils.phases import NULL_SPAN, phases, program_memory

    span = (
        phases.span(
            "compact.packed",
            (cols.shape, unit_refs, gc_ranges),
            axes=("cols", "unit_refs", "gc_ranges"),
            memory=program_memory(
                _compact_packed_jit, cols, meta, unit_refs, gc_ranges
            ),
        )
        if phases.enabled
        else NULL_SPAN
    )
    with span:
        return _compact_packed_jit(cols, meta, unit_refs, gc_ranges)


compact_state.__doc__ = _compact_state_jit.__doc__
compact_packed.__doc__ = _compact_packed_jit.__doc__


def _register_programs():
    from ytpu.utils import progbudget

    progbudget.register("compact_state", _compact_state_jit)
    progbudget.register("compact_packed", _compact_packed_jit)


_register_programs()
