"""Device compaction: commit-time block squash + GC collapse, vmapped.

The reference compacts continuously at commit: `Item::try_squash` merges a
block into its clock-contiguous right neighbor (block.rs:775-799,
squash_left at block_store.rs:243), and the GC collector replaces deleted
non-kept items with content-free GC ranges (gc.rs:11-65). The device engine
appends rows forever, so long-lived docs fill their capacity with 1-element
blocks; this pass is the batched equivalent, run as one jitted program:

1. **GC conversion** — tombstoned value rows (string/any/binary/json/
   embed/format) drop their payload reference and become CONTENT_DELETED
   rows, exactly like the host oracle's collector: the item (with its
   origin/right-origin anchors) stays in the graph so wire encodes remain
   integrable by fresh replicas; only the payload is discarded. Structural
   rows (type/move/doc) are preserved.
2. **Squash** — a row merges into its sequence-right neighbor under the
   exact try_squash conditions (same client, contiguous clocks, the
   neighbor's origin is the row's last id, equal right-origins, equal
   deleted/moved/key/parent, mergeable content: same payload ref with
   contiguous offsets for string/any, unconditionally for GC/deleted).
   Chains collapse in one pass via pointer doubling + segment sums.
3. **Defragmentation** — surviving rows are packed to the front (slot
   order preserved), every index column (left/right/parent/head/moved,
   sequence starts) remapped, and n_blocks shrinks accordingly.

Semantics parity is testable: replay -> compact -> keep replaying must
match the host oracle exactly (tests/test_compaction.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ytpu.core.content import (
    BLOCK_GC,
    CONTENT_ANY,
    CONTENT_BINARY,
    CONTENT_DELETED,
    CONTENT_EMBED,
    CONTENT_FORMAT,
    CONTENT_JSON,
    CONTENT_STRING,
)
from ytpu.models.batch_doc import COL_DEFAULTS, BlockCols, DocStateBatch

__all__ = ["compact_state", "grow_state"]

I32 = jnp.int32

# kinds whose tombstones GC to content-free deleted rows (value content;
# the reference's ItemContent::gc drops these payloads outright)
_GCABLE = (
    CONTENT_JSON,
    CONTENT_BINARY,
    CONTENT_STRING,
    CONTENT_EMBED,
    CONTENT_FORMAT,
    CONTENT_ANY,
)
# content kinds mergeable under try_squash when payload refs are contiguous
_SPLICEABLE = (CONTENT_STRING, CONTENT_ANY)


def _compact_one(state: DocStateBatch) -> DocStateBatch:
    bl = state.blocks
    B = bl.client.shape[-1]
    slots = jnp.arange(B, dtype=I32)
    n = state.n_blocks
    active = slots < n

    # --- 1. GC conversion (gc.rs:11-65) ------------------------------------
    gcable = jnp.zeros((B,), bool)
    for k in _GCABLE:
        gcable = gcable | (bl.kind == k)
    convert = active & bl.deleted & gcable
    kind = jnp.where(convert, CONTENT_DELETED, bl.kind)
    content_ref = jnp.where(convert, -1, bl.content_ref)
    content_off = jnp.where(convert, 0, bl.content_off)
    bl = bl._replace(kind=kind, content_ref=content_ref, content_off=content_off)

    # --- 2. squash eligibility a -> b = right[a] (block.rs:775-799) --------
    b = bl.right
    sb = jnp.maximum(b, 0)

    def g(col):
        return col[sb]

    ror_eq = (bl.ror_client == g(bl.ror_client)) & (
        (bl.ror_client < 0) | (bl.ror_clock == g(bl.ror_clock))
    )
    origin_chain = (g(bl.origin_client) == bl.client) & (
        g(bl.origin_clock) == bl.clock + bl.length - 1
    )
    spliceable = jnp.zeros((B,), bool)
    for k in _SPLICEABLE:
        spliceable = spliceable | (bl.kind == k)
    content_ok = (bl.kind == g(bl.kind)) & (
        (bl.kind == BLOCK_GC)
        | (bl.kind == CONTENT_DELETED)
        | (
            spliceable
            & (bl.content_ref == g(bl.content_ref))
            & (g(bl.content_off) == bl.content_off + bl.length)
        )
    )
    elig = (
        active
        & (b >= 0)
        & (b < n)
        & (bl.client == g(bl.client))
        & (g(bl.clock) == bl.clock + bl.length)
        & origin_chain
        & ror_eq
        & (bl.deleted == g(bl.deleted))
        & (bl.moved == g(bl.moved))
        & (bl.key == g(bl.key))
        & (bl.parent == g(bl.parent))
        & (g(bl.left) == slots)  # well-formed adjacency both ways
        & content_ok
    )

    # a row is absorbed into its chain head iff its left neighbor merges
    # rightward into it
    sl = jnp.maximum(bl.left, 0)
    merged_away = active & (bl.left >= 0) & elig[sl]

    # chain representative via pointer doubling: parent = left when absorbed
    rep = jnp.where(merged_away, bl.left, slots)
    n_doubling = max(1, B.bit_length())
    for _ in range(n_doubling):
        rep = rep[jnp.maximum(rep, 0)]

    # per-chain aggregates (segment id = chain head slot)
    seg_len = jax.ops.segment_sum(
        jnp.where(active, bl.length, 0), jnp.maximum(rep, 0), num_segments=B
    )
    # the chain tail (the row that does NOT merge rightward) donates its
    # right pointer to the head
    tail = active & ~elig
    tail_w = jnp.where(tail, rep, B)
    chain_right = jnp.full((B,), -1, I32).at[tail_w].set(bl.right, mode="drop")

    keep = active & ~merged_away
    # heads take the aggregated length + the tail's right pointer
    length = jnp.where(keep, seg_len, bl.length)
    right = jnp.where(keep, chain_right, bl.right)
    bl = bl._replace(length=length, right=right)

    # --- 3. defragment: pack kept rows, remap index columns ----------------
    new_idx = jnp.cumsum(keep.astype(I32)) - 1
    # pointers into absorbed rows redirect to their chain head
    old2new = jnp.where(keep, new_idx, new_idx[jnp.maximum(rep, 0)])

    def remap(col):
        return jnp.where(col >= 0, old2new[jnp.maximum(col, 0)], -1)

    bl = bl._replace(
        left=remap(bl.left),
        right=remap(bl.right),
        parent=remap(bl.parent),
        head=remap(bl.head),
        moved=remap(bl.moved),
    )
    n_new = jnp.sum(keep.astype(I32))
    # kept rows first (slot order preserved), dropped rows after
    order = jnp.argsort(jnp.where(keep, slots, B + slots))
    blank = slots >= n_new

    packed = BlockCols(
        **{
            name: jnp.where(blank, fill, getattr(bl, name)[order])
            for name, fill in COL_DEFAULTS.items()
        }
    )
    start = jnp.where(
        state.start >= 0, old2new[jnp.maximum(state.start, 0)], -1
    )
    return DocStateBatch(
        blocks=packed, start=start, n_blocks=n_new, error=state.error
    )


@partial(jax.jit, donate_argnums=0)
def compact_state(state: DocStateBatch) -> DocStateBatch:
    """Squash + GC + defragment every doc in the batch (one compiled pass).

    The input state is donated: compaction runs exactly when the batch is
    near capacity, so holding two copies of the block columns would double
    HBM at the worst possible moment."""
    return jax.vmap(_compact_one)(state)


def grow_state(state: DocStateBatch, new_capacity: int) -> DocStateBatch:
    """Widen every doc's block capacity (host-side repad; index columns are
    slot-based so they survive unchanged)."""
    B = state.blocks.client.shape[-1]
    if new_capacity < B:
        raise ValueError(f"cannot shrink capacity {B} -> {new_capacity}")
    if new_capacity == B:
        return state
    pad = new_capacity - B

    cols = {}
    for name, fill in COL_DEFAULTS.items():
        col = getattr(state.blocks, name)
        ext = jnp.full(col.shape[:-1] + (pad,), fill, dtype=col.dtype)
        cols[name] = jnp.concatenate([col, ext], axis=-1)
    return state._replace(blocks=BlockCols(**cols))
