"""Batched state-vector math on device.

Reference semantics: /root/reference/yrs/src/state_vector.rs (merge/set_max
:21-105) and the diff selection in store.rs:234-248 (`diff_state_vectors`).

Device layout: a batch of state vectors is a dense ``[n_docs, n_clients]``
i32 tensor over a host-interned client dictionary. All ops are elementwise /
reductions — they tile perfectly onto the VPU and shard over the doc axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "sv_merge",
    "sv_contains_all",
    "sv_diff_mask",
    "sv_from_blocks",
    "diff_start_clocks",
]


def sv_merge(a: jax.Array, b: jax.Array) -> jax.Array:
    """Pairwise max over [D, C] clock tensors."""
    return jnp.maximum(a, b)


def sv_contains_all(local: jax.Array, remote: jax.Array) -> jax.Array:
    """[D] bool: does `local` dominate `remote` per doc?"""
    return jnp.all(local >= remote, axis=-1)


def sv_diff_mask(local: jax.Array, remote: jax.Array) -> jax.Array:
    """[D, C] bool: clients for which local has blocks the remote lacks.

    This is the batched form of `diff_state_vectors` (store.rs:234-248).
    """
    return local > remote


def diff_start_clocks(local: jax.Array, remote: jax.Array) -> jax.Array:
    """[D, C] i32: first clock to ship per (doc, client); -1 if none needed."""
    need = local > remote
    return jnp.where(need, remote, -1)


def sv_from_blocks(
    blk_client: jax.Array,  # [D, B] i32 interned client (-1 unused)
    blk_clock: jax.Array,  # [D, B] i32
    blk_len: jax.Array,  # [D, B] i32
    n_clients: int,
) -> jax.Array:
    """[D, C] i32 state vectors from block columns (segment max of clock+len)."""
    end = blk_clock + blk_len
    valid = blk_client >= 0
    client = jnp.where(valid, blk_client, 0)
    contrib = jnp.where(valid, end, 0)
    # one-hot scatter-max over the client axis
    def per_doc(cl, co):
        return jax.ops.segment_max(
            co, cl, num_segments=n_clients, indices_are_sorted=False
        )

    out = jax.vmap(per_doc)(client, contrib)
    return jnp.maximum(out, 0)
