"""Device-side lib0/V1 update decoding — raw wire bytes in HBM → block rows.

The north-star fusion (SURVEY §2 #1, §7 step 8): hosts ship raw Yjs V1
update payloads to the device as a padded ``[S, L]`` byte matrix; the
device turns them into the columnar ``UpdateBatch`` stream the integrate
kernels consume. No host-side parsing, interning, or payload copying —
string payloads stay inside the wire buffer and are addressed by linear
byte offsets (``content_ref = s * L + byte_start``).

Algorithm: a vectorized field-at-a-time state machine. Every iteration
decodes one lib0 varint (or one info byte / one string skip) *in every
update lane simultaneously* — the per-lane parse is sequential (the wire
grammar is), but all S updates advance in lockstep as [S]-wide vector
ops, and UTF-16 lengths of string payloads come from prefix sums over
byte-class masks (the Stream-VByte-style trick: continuation-bit masks +
cumulative sums instead of byte loops).

Grammar decoded here (reference: update.rs:714-749 + :433-488,
block.rs:1786-1835, id_set.rs decode):

    update   := n_clients:var ( n_blocks:var client:var clock:var block* )*
                delete_set
    block    := info:u8
                [ origin:id ]       if info & 0x80
                [ r_origin:id ]     if info & 0x40
                [ parent ]          if info & 0xC0 == 0
                [ parent_sub:str ]  if info & 0xC0 == 0 and info & 0x20
                content
    content  := GC len:var | Skip len:var | Deleted len:var | String str
                | Any n:var value{token}* | Json n:var str* | Embed str
                | Binary buf | Format key:str value:str
                | Type tag:u8 [name:str]
                | Move flags:var start:id [end:id]
                (WeakRef types / Doc → host fallback, flagged)
    delete_set := n_clients:var ( client:var n_ranges:var (clock:var len:var)* )*

Supported on-device: GC / Skip / Deleted / String / Any (scalars,
arrays, depth-1 objects) / Json / Embed / Binary / Format / Type
(nested shared types; WeakRef branches excluded) / Move blocks with
root, ID, or nested parents, including map rows — parent_sub keys
resolve through a host-verified hash table (`key_table`), and client
ids beyond i32 (real 53-bit Yjs ids) through a varint-byte hash table
(`client_hash_table`). The remaining host-lane shapes: non-scalar
values nested inside object Any values, oversized keys, WeakRef types,
Doc. Flagged updates lose nothing — they take the exact host path they
take today.

Without tables, client ids are kept *raw*: YATA's tie-break is monotone
in the client id itself, so the rank table for the fused kernel is the
identity (`identity_rank`).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ytpu.core.content import (
    BLOCK_GC,
    BLOCK_SKIP,
    CONTENT_ANY,
    CONTENT_BINARY,
    CONTENT_DELETED,
    CONTENT_EMBED,
    CONTENT_FORMAT,
    CONTENT_JSON,
    CONTENT_MOVE,
    CONTENT_STRING,
    CONTENT_TYPE,
)
from ytpu.models.batch_doc import UpdateBatch

__all__ = [
    "pack_updates",
    "pack_updates_into",
    "pack_raw_updates_into",
    "gather_raw_lanes",
    "EMPTY_UPDATE",
    "decode_updates_v1",
    "default_steps",
    "exact_steps",
    "steps_for_columns",
    "identity_rank",
    "utf8_slice_u16",
    "RawPayloadView",
    "ChunkedWirePayloads",
    "FLAG_UNSUPPORTED",
    "FLAG_OVERFLOW",
    "FLAG_MALFORMED",
    "FLAG_BIG_CLIENT",
    "FLAG_MULTI_CLIENT",
    "FLAG_UNKNOWN_CLIENT",
]

I32 = jnp.int32
U32 = jnp.uint32

# --- per-update flag bits ----------------------------------------------------
FLAG_UNSUPPORTED = 1  # content kind / parent_sub the device cannot decode
FLAG_OVERFLOW = 2  # more blocks / delete ranges than the U/R buckets
FLAG_MALFORMED = 4  # ran past the buffer or did not reach DONE in T steps
FLAG_BIG_CLIENT = 8  # a client id >= 2^31 (needs host interning)
FLAG_MULTI_CLIENT = 16  # informational: >1 client section (wire order may
#                         not be a valid integration order for cross-client
#                         origins inside one update; single-client updates —
#                         the live-editing case — are always ordered)
FLAG_UNKNOWN_CLIENT = 32  # a client id absent from the supplied intern table
FLAG_UNKNOWN_KEY = 64  # a parent_sub hash absent from the supplied key table

FLAG_ERRORS = (
    FLAG_UNSUPPORTED
    | FLAG_OVERFLOW
    | FLAG_MALFORMED
    | FLAG_BIG_CLIENT
    | FLAG_UNKNOWN_CLIENT
    | FLAG_UNKNOWN_KEY
)

# --- parser states -----------------------------------------------------------
(
    ST_NCLIENTS,
    ST_NBLOCKS,
    ST_CLIENT,
    ST_CLOCK,
    ST_INFO,
    ST_ORIGIN_C,
    ST_ORIGIN_K,
    ST_ROR_C,
    ST_ROR_K,
    ST_PARENT_INFO,
    ST_PARENT_NAME,
    ST_PARENT_ID_C,
    ST_PARENT_ID_K,
    ST_PARENT_SUB,
    ST_DEL_LEN,
    ST_GC_LEN,
    ST_SKIP_LEN,
    ST_STR,
    ST_DS_NCLIENTS,
    ST_DS_CLIENT,
    ST_DS_NRANGES,
    ST_DS_CLOCK,
    ST_DS_LEN,
    ST_ANY_COUNT,  # ContentAny: value count
    ST_ANY_VAL,  # ContentAny: one scalar value per step
    ST_JSON_COUNT,  # ContentJson: string count
    ST_JSON_VAL,  # ContentJson: one length-prefixed string per step
    ST_SPAN1,  # ContentEmbed/Binary: one length-prefixed span, len 1
    ST_FMT_KEY,  # ContentFormat: key string
    ST_FMT_VAL,  # ContentFormat: one Any value
    ST_TYPE_TAG,  # ContentType: branch TypeRef tag byte
    ST_TYPE_NAME,  # ContentType: XmlElement/XmlHook name string
    ST_MV_FLAGS,  # ContentMove: collapsed/assoc/priority flags varint
    ST_MV_SC,  # ContentMove: range-start id client
    ST_MV_SK,  # ContentMove: range-start id clock
    ST_MV_EC,  # ContentMove: range-end id client (absent if collapsed)
    ST_MV_EK,  # ContentMove: range-end id clock
    ST_ANY_MKEY,  # ContentAny map value: one key string per step
    ST_ANY_MVAL,  # ContentAny map value: one scalar value per step
    ST_DONE,
    ST_ERR,
) = range(41)

# key-hash window: parent_sub keys longer than this take the host lane
KEY_HASH_BYTES = 32

_PAD = 16  # gather guard past the longest update


def pack_updates(
    payloads: List[bytes], pad_to: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Pad raw V1 update byte strings into an ``[S, L] uint8`` matrix.

    This is the *only* host work on the device-decode path — a memcpy.
    """
    lens = np.array([len(p) for p in payloads], dtype=np.int32)
    L = max(int(lens.max()) + _PAD if len(payloads) else _PAD, pad_to or 0)
    buf = np.zeros((len(payloads), L), dtype=np.uint8)
    for i, p in enumerate(payloads):
        buf[i, : len(p)] = np.frombuffer(p, dtype=np.uint8)
    return buf, lens


# the minimal well-formed V1 update (0 client sections, empty delete set):
# what staging pads short tail chunks with so every chunk keeps the one
# compiled [S, L] shape
EMPTY_UPDATE = b"\x00\x00"


def pack_updates_into(
    payloads: List[bytes], buf: np.ndarray, lens: np.ndarray
) -> None:
    """`pack_updates` into CALLER-PROVIDED staging buffers (in place).

    The async replay pipeline reuses a pair of preallocated ``[S, L]``
    u8 / ``[S]`` i32 staging buffers across chunks instead of allocating
    a fresh matrix per chunk; rows past ``len(payloads)`` are filled
    with `EMPTY_UPDATE` so a short tail chunk decodes as no-ops at the
    compiled shape. Each row's tail is zeroed only up to the previous
    occupant's length — the buffers never shrink, so stale bytes beyond
    `lens` can never alias into a later decode (the decoder's gather
    guard reads at most `_PAD` past `lens`, which stays zeroed)."""
    S, L = buf.shape
    if len(payloads) > S:
        raise ValueError(f"chunk of {len(payloads)} exceeds staging rows {S}")
    for i in range(S):
        p = payloads[i] if i < len(payloads) else EMPTY_UPDATE
        n = len(p)
        if n + _PAD > L:
            raise ValueError(f"payload of {n} bytes exceeds staging width {L}")
        prev = int(lens[i])
        buf[i, :n] = np.frombuffer(p, dtype=np.uint8)
        if prev + _PAD > n:
            buf[i, n : prev + _PAD] = 0
        lens[i] = n


_EMPTY_NP = np.frombuffer(EMPTY_UPDATE, dtype=np.uint8)


def pack_raw_updates_into(
    wire: np.ndarray,
    wire_offsets: np.ndarray,
    pos: int,
    end: int,
    raw: np.ndarray,
    offs: np.ndarray,
    lens: np.ndarray,
    width: Optional[int] = None,
) -> int:
    """Stage one chunk of the RAW ingest lane (ISSUE-7): a slice copy of
    the run's concatenated wire bytes plus vectorized offset/length
    tables — NO per-update Python work (the memcpy-staging invariant the
    bench dry-run asserts). ``wire`` is the whole stream's concatenated
    payload bytes, ``wire_offsets`` its ``[S+1]`` prefix table (update i
    occupies ``wire[wire_offsets[i]:wire_offsets[i+1]]``); the chunk
    ``[pos, end)`` lands in the reusable ``raw`` byte buffer with
    in-chunk ``offs``/``lens`` rows the device lane-gather consumes.
    Rows past ``end - pos`` point at a staged `EMPTY_UPDATE` tail so a
    short tail chunk decodes as no-ops at the compiled shape. Stale raw
    bytes from a previous occupant are harmless: the device gather
    (`gather_raw_lanes`) zero-masks every byte at or past each lane's
    length. Returns the staged byte count. ``width`` (the decode lane
    width) enables the same oversized-payload check `pack_updates_into`
    performs."""
    n = end - pos
    if n > offs.shape[0]:
        raise ValueError(f"chunk of {n} exceeds staging rows {offs.shape[0]}")
    b0 = int(wire_offsets[pos])
    b1 = int(wire_offsets[end])
    nb = b1 - b0
    if nb + len(EMPTY_UPDATE) > raw.shape[0]:
        raise ValueError(
            f"chunk of {nb} wire bytes exceeds staging capacity {raw.shape[0]}"
        )
    chunk_lens = wire_offsets[pos : end + 1]
    if width is not None and n:
        longest = int((chunk_lens[1:] - chunk_lens[:-1]).max())
        if longest + _PAD > width:
            raise ValueError(
                f"payload of {longest} bytes exceeds staging width {width}"
            )
    raw[:nb] = wire[b0:b1]
    raw[nb : nb + len(EMPTY_UPDATE)] = _EMPTY_NP
    offs[:n] = chunk_lens[:-1] - b0
    lens[:n] = chunk_lens[1:] - chunk_lens[:-1]
    offs[n:] = nb
    lens[n:] = len(EMPTY_UPDATE)
    return nb + len(EMPTY_UPDATE)


def gather_raw_lanes(raw, offs, lens, width: int):
    """``[RC]`` raw concatenated bytes + per-update offsets → the padded
    ``[S, L]`` lane matrix `pack_updates` builds on host, materialized ON
    DEVICE: one clamped lane-parallel gather + zero mask (the Stream-
    VByte-style control/data split — the offsets table is the control
    stream, the byte arena the data stream, and every update lane peels
    its window simultaneously). Bytes at ``j >= lens[s]`` are zeroed so
    the matrix is byte-identical to a freshly host-packed one — the
    varint state machine's prefix sums, gather guard, and key-hash
    windows read them, so the mask is what guarantees raw-vs-packed
    decode parity for every content kind (tests/test_async_raw_ingest).
    """
    iota = jnp.arange(width, dtype=I32)[None, :]
    idx = jnp.clip(offs[:, None].astype(I32) + iota, 0, raw.shape[0] - 1)
    lanes = jnp.take(raw, idx)
    return jnp.where(iota < lens[:, None].astype(I32), lanes, 0)


def identity_rank(k: int) -> jax.Array:
    """Rank table for raw-client-id streams: rank(c) = c."""
    return jnp.arange(k, dtype=I32)


def default_steps(max_rows: int, max_dels: int) -> int:
    """Safe iteration budget: fields per block ≤ 10 (+3/client header),
    2 per delete range (+2/ds client), +4 frame fields. Covers scalar
    content only — value-list content (Any/Json) costs one extra step per
    value; callers with a native pre-scan pass an exact ``n_steps``."""
    return 4 + 13 * max_rows + 4 * max_dels


def key_hash_host(key: bytes) -> int:
    """The device key hash, host side (must match the kernel's mixing)."""
    h = 0
    for i, byte in enumerate(key[:KEY_HASH_BYTES]):
        h = (h + byte * pow(31, i, 1 << 32)) & 0xFFFFFFFF
    h ^= (len(key) * 2654435761) & 0xFFFFFFFF
    return h & 0x7FFFFFFF


def client_hash_host(client: int) -> int:
    """Hash of a client id's varint wire bytes — how the device refers to
    ids beyond i32 (real Yjs clients are random 53-bit). Must match the
    kernel's in-window mixing; results live in [0, 2^30)."""
    h = 0
    i = 0
    v = client
    while True:
        byte = v & 0x7F
        v >>= 7
        if v:
            byte |= 0x80
        h = (h + byte * pow(31, i, 1 << 32)) & 0xFFFFFFFF
        i += 1
        if not v:
            break
    h ^= (i * 2654435761) & 0xFFFFFFFF
    return h & 0x3FFFFFFF


def exact_steps(
    n_client_sections: int,
    n_item_blocks: int,
    n_skip_gc_blocks: int,
    n_ds_sections: int,
    n_del_ranges: int,
    n_value_steps: int = 0,
) -> int:
    """Step budget for one update whose wire-section counts are known
    (native pre-scan): item blocks cost ≤ 10 fields, GC/Skip blocks 2,
    each client section 3 (n_blocks/client/clock), each ds section 2
    (client/n_ranges), each range 2 (clock/len), + 2 frame headers.
    ``n_value_steps`` covers value-list content: one step per Any/Json
    value plus one for a Format key."""
    return (
        2
        + 3 * n_client_sections
        + 10 * n_item_blocks
        + 2 * n_skip_gc_blocks
        + 2 * n_ds_sections
        + 2 * n_del_ranges
        + n_value_steps
    )


def steps_for_columns(cols) -> int:
    """Exact decode step budget for one update from its native pre-scan
    (`ytpu.native.NativeColumns`) — the single cost model shared by the
    ingest fast lane and the full-trace replay planner."""
    import numpy as np

    n_skip_gc = int(np.count_nonzero((cols.kind == 10) | (cols.kind == 0)))
    return exact_steps(
        cols.n_client_sections,
        cols.n_blocks - n_skip_gc + cols.n_zero_len_blocks,
        n_skip_gc,
        cols.n_ds_sections,
        cols.n_dels,
        getattr(cols, "n_value_steps", 0),
    )


def decode_updates_v1(
    buf: jax.Array,
    lens: jax.Array,
    max_rows: int,
    max_dels: int,
    n_steps: Optional[int] = None,
    client_table: Optional[Tuple[jax.Array, jax.Array]] = None,
    max_sections: Optional[int] = None,
    key_table: Optional[Tuple[jax.Array, jax.Array]] = None,
    client_hash_table: Optional[Tuple[jax.Array, jax.Array]] = None,
    primary_root_hash: Optional[jax.Array] = None,
) -> Tuple[UpdateBatch, jax.Array]:
    """Decode S updates into an ``[S, U] / [S, R]`` UpdateBatch stream.

    Returns ``(stream, flags)``; lanes with ``flags & FLAG_ERRORS`` decoded
    incompletely and must be re-decoded on host (their emitted rows are
    marked invalid so a mixed batch stays safe to apply).

    ``client_table=(sorted_ids, perm)`` maps raw client ids to interned
    indices on device (``perm[j]`` is the interned index of ``sorted_ids
    [j]``), so decoded streams can mix with host-encoded batches that use
    a `ClientInterner`. Lanes mentioning an id outside the table flag
    ``FLAG_UNKNOWN_CLIENT`` (host fallback interns it for the next step).

    ``key_table=(sorted_hashes, perm)`` maps parent_sub key hashes (see
    `key_hash_host`) to interned key indices, enabling map rows on
    device; the host pre-scan guarantees every key in the step is in the
    table and collision-free (collisions route to the host lane). Lanes
    with a map row but no table — or a hash miss — flag
    ``FLAG_UNKNOWN_KEY``.

    ``client_hash_table=(sorted_hashes, perm)`` resolves client ids
    beyond i32 (real Yjs ids are random 53-bit): the kernel hashes the
    id's varint bytes in-window (`client_hash_host`) and the table maps
    hash -> interned index. Without the table such lanes flag
    ``FLAG_BIG_CLIENT``; a miss flags ``FLAG_UNKNOWN_CLIENT``.

    ``max_sections`` bounds the client-section header (default ``max_rows
    + 1``). Wire-legal updates can carry more sections than emitted rows
    (e.g. sections holding only already-covered Skip runs); callers that
    pre-scan the wire (native columns) pass the real count so such
    updates don't trip the garbage-header guard. Pair it with an
    ``n_steps`` budget that covers the extra section fields
    (`exact_steps`).

    ``primary_root_hash`` ([S] i32, -1 = legacy single-root lane) enables
    multi-root decode (doc.rs:156-228): a named-root parent whose name
    hash equals the lane's primary maps to the implicit branch
    (``p_root == -1``); other names resolve through ``key_table`` to the
    anchor key id (miss -> FLAG_UNKNOWN_KEY, name beyond the hash
    window -> FLAG_UNSUPPORTED). Without it every named root aliases to
    the primary branch — the pre-multi-root behavior.
    """
    S, L = buf.shape
    U, R = max_rows, max_dels
    T = n_steps or default_steps(U, R)
    max_sec = max_sections if max_sections is not None else U + 1
    b = buf.astype(I32)
    lens = lens.astype(I32)

    # UTF-16 length prefix sums: a UTF-8 head byte (not 0b10xxxxxx) is one
    # code point; a 4-byte lead (>= 0xF0) is a surrogate pair, one extra.
    head = ((b & 0xC0) != 0x80).astype(I32)
    lead4 = (b >= 0xF0).astype(I32)
    zero = jnp.zeros((S, 1), I32)
    u16_psum = jnp.concatenate([zero, jnp.cumsum(head + lead4, axis=1)], axis=1)

    iota_u = jax.lax.broadcasted_iota(I32, (S, U), 1)
    iota_r = jax.lax.broadcasted_iota(I32, (S, R), 1)
    row_ids = jnp.arange(S, dtype=I32)

    def u16_span(a, bnd):
        """UTF-16 code units of bytes [a, b) per lane."""
        a = jnp.clip(a, 0, L)
        bnd = jnp.clip(bnd, 0, L)
        pa = jnp.take_along_axis(u16_psum, a[:, None], axis=1)[:, 0]
        pb = jnp.take_along_axis(u16_psum, bnd[:, None], axis=1)[:, 0]
        return pb - pa

    def init_carry():
        regs = dict(
            pos=jnp.zeros((S,), I32),
            st=jnp.full((S,), ST_NCLIENTS, I32),
            flags=jnp.zeros((S,), I32),
            clients_left=jnp.zeros((S,), I32),
            blocks_left=jnp.zeros((S,), I32),
            client=jnp.zeros((S,), I32),
            clock=jnp.zeros((S,), I32),
            info=jnp.zeros((S,), I32),
            oc=jnp.full((S,), -1, I32),
            ok=jnp.zeros((S,), I32),
            rc=jnp.full((S,), -1, I32),
            rk=jnp.zeros((S,), I32),
            ptag=jnp.zeros((S,), I32),
            pc=jnp.full((S,), -1, I32),
            pk=jnp.zeros((S,), I32),
            ds_clients_left=jnp.zeros((S,), I32),
            ds_ranges_left=jnp.zeros((S,), I32),
            ds_client=jnp.zeros((S,), I32),
            ds_clock=jnp.zeros((S,), I32),
            n_rows=jnp.zeros((S,), I32),
            n_dels=jnp.zeros((S,), I32),
            keyh=jnp.full((S,), -1, I32),  # parent_sub hash (-1 = none)
            rooth=jnp.full((S,), -1, I32),  # root parent name hash (-1 =
            # not a named-root parent; -2 = name beyond the hash window)
            vals_left=jnp.zeros((S,), I32),  # Any/Json values remaining
            vals_n=jnp.zeros((S,), I32),  # total value count (clock len)
            cref=jnp.full((S,), -1, I32),  # content span start byte
            mpairs=jnp.zeros((S,), I32),  # depth-1 object pairs remaining
            mvf=jnp.zeros((S,), I32),  # ContentMove flags
            msc=jnp.full((S,), -1, I32),
            msk=jnp.zeros((S,), I32),
            mec=jnp.full((S,), -1, I32),
        )
        rows = dict(
            client=jnp.zeros((S, U), I32),
            clock=jnp.zeros((S, U), I32),
            length=jnp.zeros((S, U), I32),
            oc=jnp.full((S, U), -1, I32),
            ok=jnp.zeros((S, U), I32),
            rc=jnp.full((S, U), -1, I32),
            rk=jnp.zeros((S, U), I32),
            kind=jnp.zeros((S, U), I32),
            ref=jnp.full((S, U), -1, I32),
            ptag=jnp.zeros((S, U), I32),
            pc=jnp.full((S, U), -1, I32),
            pk=jnp.zeros((S, U), I32),
            keyh=jnp.full((S, U), -1, I32),
            rooth=jnp.full((S, U), -1, I32),
            msc=jnp.full((S, U), -1, I32),
            msk=jnp.zeros((S, U), I32),
            msa=jnp.zeros((S, U), I32),
            mec=jnp.full((S, U), -1, I32),
            mek=jnp.zeros((S, U), I32),
            mea=jnp.zeros((S, U), I32),
            mprio=jnp.full((S, U), -1, I32),
            valid=jnp.zeros((S, U), bool),
        )
        dels = dict(
            client=jnp.zeros((S, R), I32),
            start=jnp.zeros((S, R), I32),
            end=jnp.zeros((S, R), I32),
            valid=jnp.zeros((S, R), bool),
        )
        return regs, rows, dels

    def step(_, carry):
        regs, rows, dels = carry
        pos, st = regs["pos"], regs["st"]
        active = (st != ST_DONE) & (st != ST_ERR)

        # --- one varint (or u8) at the cursor, all lanes at once ---------
        idx = jnp.clip(pos[:, None] + jnp.arange(10, dtype=I32)[None, :], 0, L - 1)
        in_buf = (pos[:, None] + jnp.arange(10, dtype=I32)[None, :]) < lens[:, None]
        bytes10 = jnp.where(in_buf, jnp.take_along_axis(b, idx, axis=1), 0)
        cont = bytes10 >= 0x80
        inb = jnp.concatenate(
            [jnp.ones((S, 1), I32), jnp.cumprod(cont[:, :9].astype(I32), axis=1)],
            axis=1,
        )  # inb[:, i] = byte i belongs to the varint
        nbytes = jnp.sum(inb, axis=1)
        shifts = (7 * jnp.arange(5, dtype=I32))[None, :]
        val = jnp.sum(
            jnp.where(
                inb[:, :5] == 1,
                (bytes10[:, :5].astype(U32) & 0x7F) << shifts.astype(U32),
                jnp.zeros((S, 5), U32),
            ),
            axis=1,
        ).astype(I32)
        ovf = (nbytes > 5) | ((nbytes == 5) & ((bytes10[:, 4] & 0x7F) >= 8))

        is_info = st == ST_INFO
        # the TypeRef tag is a raw u8 (EncoderV1.write_type_ref), like info
        is_u8 = is_info | (st == ST_TYPE_TAG)
        v = jnp.where(is_u8, bytes10[:, 0], val)
        consumed = jnp.where(is_u8, 1, nbytes)

        # string states consume the payload bytes too
        is_str_skip = (
            (st == ST_PARENT_NAME)
            | (st == ST_PARENT_SUB)
            | (st == ST_JSON_VAL)
            | (st == ST_FMT_KEY)
            | (st == ST_FMT_VAL)  # format values are JSON strings on wire
            | (st == ST_SPAN1)
            | (st == ST_TYPE_NAME)  # XmlElement/XmlHook branch name
            | (st == ST_ANY_MKEY)  # map-value keys: plain strings, no tag
        )
        is_str = st == ST_STR
        str_start = pos + nbytes
        consumed = consumed + jnp.where(is_str_skip | is_str, v, 0)

        # --- one lib0 Any value (ST_ANY_VAL / ST_FMT_VAL): tag byte at
        # pos, then a tag-dependent payload. A second varint extraction
        # over the window shifted by one covers int/string/buffer tags.
        is_any_val = st == ST_ANY_VAL
        is_any_mval = st == ST_ANY_MVAL
        tag = bytes10[:, 0]
        cont2 = bytes10[:, 1:] >= 0x80
        inb2 = jnp.concatenate(
            [jnp.ones((S, 1), I32), jnp.cumprod(cont2[:, :8].astype(I32), axis=1)],
            axis=1,
        )
        nb2 = jnp.sum(inb2, axis=1)
        val2 = jnp.sum(
            jnp.where(
                inb2[:, :5] == 1,
                (bytes10[:, 1:6].astype(U32) & 0x7F) << shifts.astype(U32),
                jnp.zeros((S, 5), U32),
            ),
            axis=1,
        ).astype(I32)
        any_extra = jnp.where(
            (tag == 127) | (tag == 126) | (tag == 121) | (tag == 120),
            0,
            jnp.where(
                tag == 125,  # integer: signed varint
                nb2,
                jnp.where(
                    tag == 124,  # float32
                    4,
                    jnp.where(
                        (tag == 123) | (tag == 122),  # float64 / bigint
                        8,
                        jnp.where(
                            (tag == 119) | (tag == 116),  # string / buffer
                            nb2 + val2,
                            jnp.where(
                                # array / depth-1 object header: tag + count
                                (tag == 117) | (tag == 118),
                                nb2,
                                0,
                            ),
                        ),
                    ),
                ),
            ),
        )
        # unknown tags — and non-scalar values INSIDE an object (depth-1
        # support) — fall back to the host lane; arrays and depth-1
        # objects are header tokens whose children step individually
        any_bad_tag = (is_any_val & (tag < 116)) | (
            is_any_mval & ((tag == 117) | (tag == 118) | (tag < 116))
        )
        consumed = jnp.where(is_any_val | is_any_mval, 1 + any_extra, consumed)

        # --- parent_sub key hash (device map rows): mix the key bytes so
        # the host-built (hash -> interned key) table resolves them
        kh_idx = jnp.clip(
            str_start[:, None] + jnp.arange(KEY_HASH_BYTES, dtype=I32)[None, :],
            0,
            L - 1,
        )
        kh_bytes = jnp.take_along_axis(b, kh_idx, axis=1).astype(U32)
        kh_mask = jnp.arange(KEY_HASH_BYTES, dtype=I32)[None, :] < v[:, None]
        pow31 = jnp.asarray(
            np.array(
                [pow(31, i, 1 << 32) for i in range(KEY_HASH_BYTES)],
                dtype=np.uint32,
            )
        )
        khash = jnp.sum(
            jnp.where(kh_mask, kh_bytes * pow31[None, :], 0).astype(U32), axis=1
        )
        khash = (
            (khash ^ (v.astype(U32) * jnp.uint32(2654435761)))
            & jnp.uint32(0x7FFFFFFF)
        ).astype(I32)
        key_too_long = (st == ST_PARENT_SUB) & (v > KEY_HASH_BYTES)

        pos_after = pos + consumed
        is_client_st = (
            (st == ST_CLIENT) | (st == ST_ORIGIN_C) | (st == ST_ROR_C)
            | (st == ST_PARENT_ID_C) | (st == ST_DS_CLIENT)
            | (st == ST_MV_SC) | (st == ST_MV_EC)
        )
        # client ids beyond i32 (ovf at a client state) are represented by
        # a hash of their varint bytes, encoded as -2 - hash (< -1); the
        # post-loop table lookup resolves them to interned indices
        cmask = jnp.arange(10, dtype=I32)[None, :] < nbytes[:, None]
        pow31_10 = jnp.asarray(
            np.array([pow(31, i, 1 << 32) for i in range(10)], dtype=np.uint32)
        )
        chash = jnp.sum(
            jnp.where(cmask, bytes10.astype(U32) * pow31_10[None, :], 0).astype(
                U32
            ),
            axis=1,
        )
        chash = (
            (chash ^ (nbytes.astype(U32) * jnp.uint32(2654435761)))
            & jnp.uint32(0x3FFFFFFF)
        ).astype(I32)
        vc = jnp.where(is_client_st & ovf, -2 - chash, v)
        bad = active & (
            (pos_after > lens)
            # a string length > L would wrap `pos + v` past int32 and slip
            # under the pos_after bound; no real payload exceeds its buffer
            | ((is_str_skip | is_str) & (v > L))
            | ((is_any_val | is_any_mval)
               & ((tag == 119) | (tag == 116))
               & (val2 > L))
            | (ovf & ~is_u8 & ~is_client_st & ~is_any_val & ~is_any_mval)
            | ((st == ST_NCLIENTS) & (v > max_sec))  # absurd header: garbage
        )
        act = active & ~bad

        def on(s):
            return act & (st == s)

        def upd(reg, cond, new):
            return jnp.where(cond, new, reg)

        # --- end-of-block / end-of-ds-range shared bookkeeping -----------
        # one token consumed per value step; an array header enqueues its
        # children onto the counter; a depth-1 object header suspends the
        # counter until its last pair's value lands (ST_ANY_MVAL)
        any_children = jnp.where((st == ST_ANY_VAL) & (tag == 117), val2, 0)
        map_open = on(ST_ANY_VAL) & (tag == 118) & (val2 > 0)
        mpairs2 = upd(regs["mpairs"], on(ST_ANY_MVAL), regs["mpairs"] - 1)
        map_done = on(ST_ANY_MVAL) & (mpairs2 == 0)
        vals_dec = (on(ST_ANY_VAL) & ~map_open) | on(ST_JSON_VAL) | map_done
        vals_left2 = upd(
            regs["vals_left"],
            vals_dec,
            regs["vals_left"] - 1 + any_children,
        )
        # states that finish a block this step (zero-count value lists
        # finish immediately and emit nothing)
        empty_list = (on(ST_ANY_COUNT) | on(ST_JSON_COUNT)) & (v == 0)
        list_done = vals_dec & (vals_left2 == 0)
        # TypeRef tags 3/5 (XmlElement/XmlHook) carry a name string; 7
        # (WeakRef: host-resolved link source) and unknown tags flag
        type_named = on(ST_TYPE_TAG) & ((v == 3) | (v == 5))
        type_done = (on(ST_TYPE_TAG) & ~type_named) | on(ST_TYPE_NAME)
        # a collapsed move (flags bit 0) ends at its start clock
        mv_collapsed = (regs["mvf"] & 1) != 0
        move_done = (on(ST_MV_SK) & mv_collapsed) | on(ST_MV_EK)
        emit_row_st = (
            on(ST_DEL_LEN)
            | on(ST_GC_LEN)
            | on(ST_SKIP_LEN)
            | on(ST_STR)
            | list_done
            | on(ST_SPAN1)
            | on(ST_FMT_VAL)
            | type_done
            | move_done
        )
        str_len16 = u16_span(str_start, str_start + v)
        is_list_done = list_done
        blk_len = jnp.where(
            is_str,
            str_len16,
            jnp.where(
                is_list_done,
                regs["vals_n"],
                jnp.where(
                    on(ST_SPAN1) | on(ST_FMT_VAL) | type_done | move_done,
                    1,
                    v,
                ),
            ),
        )
        block_end = emit_row_st | empty_list
        blocks_left2 = upd(regs["blocks_left"], block_end, regs["blocks_left"] - 1)
        # a client section with zero blocks (never produced by our encoders,
        # but legal wire) also closes at ST_CLOCK
        empty_client = on(ST_CLOCK) & (regs["blocks_left"] == 0)
        client_done = (block_end & (blocks_left2 == 0)) | empty_client
        clients_left2 = upd(regs["clients_left"], client_done, regs["clients_left"] - 1)
        after_block = jnp.where(
            blocks_left2 > 0,
            ST_INFO,
            jnp.where(clients_left2 > 0, ST_NBLOCKS, ST_DS_NCLIENTS),
        )

        ds_done_range = on(ST_DS_LEN)
        ds_ranges_left2 = upd(
            regs["ds_ranges_left"], ds_done_range, regs["ds_ranges_left"] - 1
        )
        # DS_NRANGES with 0 ranges also closes the ds-client section
        ds_client_done = (ds_done_range & (ds_ranges_left2 == 0)) | (
            on(ST_DS_NRANGES) & (v == 0)
        )
        ds_clients_left2 = upd(
            regs["ds_clients_left"], ds_client_done, regs["ds_clients_left"] - 1
        )
        after_ds_range = jnp.where(
            ds_ranges_left2 > 0,
            ST_DS_CLOCK,
            jnp.where(ds_clients_left2 > 0, ST_DS_CLIENT, ST_DONE),
        )

        # --- content dispatch after the last pre-content field -----------
        kind4 = regs["info"] & 0b1111
        content_st = jnp.where(
            kind4 == CONTENT_DELETED,
            ST_DEL_LEN,
            jnp.where(
                kind4 == CONTENT_STRING,
                ST_STR,
                jnp.where(
                    kind4 == CONTENT_ANY,
                    ST_ANY_COUNT,
                    jnp.where(
                        kind4 == CONTENT_JSON,
                        ST_JSON_COUNT,
                        jnp.where(
                            (kind4 == CONTENT_EMBED) | (kind4 == CONTENT_BINARY),
                            ST_SPAN1,
                            jnp.where(
                                kind4 == CONTENT_FORMAT,
                                ST_FMT_KEY,
                                jnp.where(
                                    kind4 == CONTENT_TYPE,
                                    ST_TYPE_TAG,
                                    jnp.where(
                                        kind4 == CONTENT_MOVE,
                                        ST_MV_FLAGS,
                                        ST_ERR,
                                    ),
                                ),
                            ),
                        ),
                    ),
                ),
            ),
        )
        content_unsupported = content_st == ST_ERR
        has_psub = ((regs["info"] & 0xC0) == 0) & ((regs["info"] & 0x20) != 0)
        after_parent = jnp.where(has_psub, ST_PARENT_SUB, content_st)

        # --- next state -----------------------------------------------------
        nclients_hdr = on(ST_NCLIENTS)
        info_gc = on(ST_INFO) & (v == BLOCK_GC)
        info_skip = on(ST_INFO) & (v == BLOCK_SKIP)
        info_item = on(ST_INFO) & ~info_gc & ~info_skip
        item_next = jnp.where(
            (v & 0x80) != 0,
            ST_ORIGIN_C,
            jnp.where((v & 0x40) != 0, ST_ROR_C, ST_PARENT_INFO),
        )

        st2 = st
        st2 = upd(st2, nclients_hdr, jnp.where(v > 0, ST_NBLOCKS, ST_DS_NCLIENTS))
        st2 = upd(st2, on(ST_NBLOCKS), ST_CLIENT)
        st2 = upd(st2, on(ST_CLIENT), ST_CLOCK)
        st2 = upd(
            st2,
            on(ST_CLOCK),
            jnp.where(
                regs["blocks_left"] > 0,
                ST_INFO,
                jnp.where(clients_left2 > 0, ST_NBLOCKS, ST_DS_NCLIENTS),
            ),
        )
        st2 = upd(st2, info_gc, ST_GC_LEN)
        st2 = upd(st2, info_skip, ST_SKIP_LEN)
        st2 = upd(st2, info_item, item_next)
        st2 = upd(st2, on(ST_ORIGIN_C), ST_ORIGIN_K)
        st2 = upd(
            st2,
            on(ST_ORIGIN_K),
            jnp.where((regs["info"] & 0x40) != 0, ST_ROR_C, content_st),
        )
        st2 = upd(st2, on(ST_ROR_C), ST_ROR_K)
        st2 = upd(st2, on(ST_ROR_K), content_st)
        st2 = upd(
            st2, on(ST_PARENT_INFO), jnp.where(v == 1, ST_PARENT_NAME, ST_PARENT_ID_C)
        )
        st2 = upd(st2, on(ST_PARENT_NAME), after_parent)
        st2 = upd(st2, on(ST_PARENT_ID_C), ST_PARENT_ID_K)
        st2 = upd(st2, on(ST_PARENT_ID_K), after_parent)
        st2 = upd(st2, on(ST_PARENT_SUB), content_st)
        st2 = upd(st2, on(ST_ANY_COUNT) & (v > 0), ST_ANY_VAL)
        st2 = upd(st2, map_open, ST_ANY_MKEY)
        st2 = upd(st2, on(ST_ANY_MKEY), ST_ANY_MVAL)
        st2 = upd(st2, on(ST_ANY_MVAL) & ~map_done, ST_ANY_MKEY)
        st2 = upd(
            st2, map_done & (vals_left2 > 0), ST_ANY_VAL
        )
        st2 = upd(st2, on(ST_JSON_COUNT) & (v > 0), ST_JSON_VAL)
        st2 = upd(st2, on(ST_FMT_KEY), ST_FMT_VAL)
        st2 = upd(st2, type_named, ST_TYPE_NAME)
        st2 = upd(st2, on(ST_MV_FLAGS), ST_MV_SC)
        st2 = upd(st2, on(ST_MV_SC), ST_MV_SK)
        st2 = upd(st2, on(ST_MV_SK) & ~mv_collapsed, ST_MV_EC)
        st2 = upd(st2, on(ST_MV_EC), ST_MV_EK)
        st2 = upd(st2, block_end, after_block)
        st2 = upd(st2, on(ST_DS_NCLIENTS), jnp.where(v > 0, ST_DS_CLIENT, ST_DONE))
        st2 = upd(st2, on(ST_DS_CLIENT), ST_DS_NRANGES)
        st2 = upd(
            st2,
            on(ST_DS_NRANGES),
            jnp.where(
                v > 0,
                ST_DS_CLOCK,
                jnp.where(ds_clients_left2 > 0, ST_DS_CLIENT, ST_DONE),
            ),
        )
        st2 = upd(st2, on(ST_DS_CLOCK), ST_DS_LEN)
        st2 = upd(st2, ds_done_range, after_ds_range)

        # unsupported content discovered at a dispatch point
        unsupported = (
            (on(ST_ORIGIN_K) & ((regs["info"] & 0x40) == 0) & content_unsupported)
            | (on(ST_ROR_K) & content_unsupported)
            | ((on(ST_PARENT_NAME) | on(ST_PARENT_ID_K)) & ~has_psub & content_unsupported)
            | (on(ST_PARENT_SUB) & content_unsupported)
            | (act & key_too_long)  # key exceeds the hash window
            | (act & any_bad_tag)  # recursive/unknown Any value
            # WeakRef branches (host-resolved link sources), Doc subtrees
            # and unknown TypeRef tags (valid device set: 0-6) stay on the
            # host lane
            | (on(ST_TYPE_TAG) & ((v == 7) | (v >= 8)))
        )
        # item with neither origin flag whose dispatch happens after parent
        st2 = upd(st2, unsupported, ST_ERR)
        st2 = upd(st2, bad, ST_ERR)

        # --- registers ------------------------------------------------------
        regs2 = dict(regs)
        regs2["pos"] = jnp.where(act, pos_after, pos)
        regs2["st"] = st2
        regs2["clients_left"] = upd(clients_left2, nclients_hdr, v)
        regs2["blocks_left"] = upd(blocks_left2, on(ST_NBLOCKS), v)
        regs2["client"] = upd(regs["client"], on(ST_CLIENT), vc)
        clock2 = upd(regs["clock"], on(ST_CLOCK), v)
        regs2["clock"] = upd(clock2, block_end, clock2 + blk_len)
        regs2["keyh"] = upd(
            upd(regs["keyh"], on(ST_INFO), -1), on(ST_PARENT_SUB), khash
        )
        # root-parent name hash (multi-root docs, doc.rs:156-228): khash is
        # computed from the CURRENT string's bytes, which at ST_PARENT_NAME
        # are the root name; names beyond the hash window mark -2 (resolved
        # lanes flag unsupported — legacy single-root callers ignore it)
        regs2["rooth"] = upd(
            upd(regs["rooth"], on(ST_INFO), -1),
            on(ST_PARENT_NAME),
            jnp.where(v <= KEY_HASH_BYTES, khash, -2),
        )
        count_st = on(ST_ANY_COUNT) | on(ST_JSON_COUNT)
        regs2["vals_n"] = upd(regs["vals_n"], count_st, v)
        regs2["vals_left"] = upd(vals_left2, count_st, v)
        regs2["cref"] = upd(
            regs["cref"], count_st | on(ST_FMT_KEY) | on(ST_TYPE_TAG), pos
        )
        regs2["info"] = upd(regs["info"], on(ST_INFO), v)
        # reset per-item registers when a new info byte arrives
        fresh = on(ST_INFO)
        regs2["oc"] = upd(upd(regs["oc"], fresh, -1), on(ST_ORIGIN_C), vc)
        regs2["ok"] = upd(upd(regs["ok"], fresh, 0), on(ST_ORIGIN_K), v)
        regs2["rc"] = upd(upd(regs["rc"], fresh, -1), on(ST_ROR_C), vc)
        regs2["rk"] = upd(upd(regs["rk"], fresh, 0), on(ST_ROR_K), v)
        ptag2 = upd(regs["ptag"], fresh, 0)
        regs2["ptag"] = upd(ptag2, on(ST_PARENT_INFO), jnp.where(v == 1, 1, 2))
        regs2["pc"] = upd(upd(regs["pc"], fresh, -1), on(ST_PARENT_ID_C), vc)
        regs2["pk"] = upd(upd(regs["pk"], fresh, 0), on(ST_PARENT_ID_K), v)
        regs2["ds_clients_left"] = upd(ds_clients_left2, on(ST_DS_NCLIENTS), v)
        regs2["ds_ranges_left"] = upd(ds_ranges_left2, on(ST_DS_NRANGES), v)
        regs2["ds_client"] = upd(regs["ds_client"], on(ST_DS_CLIENT), vc)
        regs2["ds_clock"] = upd(regs["ds_clock"], on(ST_DS_CLOCK), v)
        regs2["mpairs"] = upd(mpairs2, map_open, val2)
        regs2["mvf"] = upd(regs["mvf"], on(ST_MV_FLAGS), v)
        regs2["msc"] = upd(regs["msc"], on(ST_MV_SC), vc)
        regs2["msk"] = upd(regs["msk"], on(ST_MV_SK), v)
        regs2["mec"] = upd(regs["mec"], on(ST_MV_EC), vc)

        flags2 = (
            regs["flags"]
            | jnp.where(bad, FLAG_MALFORMED, 0)
            | jnp.where(unsupported, FLAG_UNSUPPORTED, 0)
            | jnp.where(nclients_hdr & (v > 1), FLAG_MULTI_CLIENT, 0)
        )

        # --- row / delete-range emission -----------------------------------
        emit = emit_row_st & ~on(ST_SKIP_LEN) & (blk_len > 0)
        row_ovf = emit & (regs["n_rows"] >= U)
        emit = emit & ~row_ovf
        oh = (iota_u == regs["n_rows"][:, None]) & emit[:, None]

        def put_row(name, vec):
            rows[name] = jnp.where(oh, vec[:, None], rows[name])

        is_gc_row = on(ST_GC_LEN)
        # the info register still holds the block's content kind for every
        # content-terminal state (Any/Json/Embed/Binary/Format/Deleted)
        row_kind = jnp.where(
            is_gc_row,
            BLOCK_GC,
            jnp.where(is_str, CONTENT_STRING, kind4),
        )
        row_ref = jnp.where(
            is_str,
            row_ids * L + str_start,
            jnp.where(
                is_list_done | on(ST_FMT_VAL) | on(ST_TYPE_NAME),
                row_ids * L + regs["cref"],
                jnp.where(
                    on(ST_SPAN1) | on(ST_TYPE_TAG),
                    row_ids * L + pos,
                    -1,
                ),
            ),
        )
        put_row("client", regs["client"])
        put_row("clock", regs["clock"])
        put_row("length", blk_len)
        put_row("oc", jnp.where(is_gc_row, -1, regs["oc"]))
        put_row("ok", jnp.where(is_gc_row, 0, regs["ok"]))
        put_row("rc", jnp.where(is_gc_row, -1, regs["rc"]))
        put_row("rk", jnp.where(is_gc_row, 0, regs["rk"]))
        put_row("kind", row_kind)
        put_row("ref", row_ref)
        put_row("ptag", jnp.where(is_gc_row, 0, regs["ptag"]))
        put_row("pc", jnp.where(is_gc_row, -1, regs["pc"]))
        put_row("pk", jnp.where(is_gc_row, 0, regs["pk"]))
        put_row("keyh", jnp.where(is_gc_row, -1, regs["keyh"]))
        put_row("rooth", jnp.where(is_gc_row, -1, regs["rooth"]))
        # ContentMove range fields (moving.rs:189-215 flag layout): assoc
        # columns use the engine convention 0 = After, -1 = Before; a
        # collapsed move's end is its start; end clock is the CURRENT
        # varint at ST_MV_EK (registers update after emission)
        is_move_emit = move_done
        mvf = regs["mvf"]
        msa = jnp.where((mvf & 2) != 0, 0, -1)
        mea = jnp.where((mvf & 4) != 0, 0, -1)
        # the CURRENT varint is the start clock when emitting collapsed at
        # ST_MV_SK, and the end clock at ST_MV_EK (registers update after
        # emission); the end id of a collapsed move is its start id
        msk_cur = jnp.where(on(ST_MV_SK), v, regs["msk"])
        mv_end_c = jnp.where(mv_collapsed, regs["msc"], regs["mec"])
        put_row("msc", jnp.where(is_move_emit, regs["msc"], -1))
        put_row("msk", jnp.where(is_move_emit, msk_cur, 0))
        put_row("msa", jnp.where(is_move_emit, msa, 0))
        put_row("mec", jnp.where(is_move_emit, mv_end_c, -1))
        put_row("mek", jnp.where(is_move_emit, v, 0))
        put_row("mea", jnp.where(is_move_emit, mea, 0))
        put_row("mprio", jnp.where(is_move_emit, mvf >> 6, -1))
        rows["valid"] = rows["valid"] | oh
        regs2["n_rows"] = regs["n_rows"] + emit.astype(I32)

        emit_d = ds_done_range & (v > 0)
        del_ovf = emit_d & (regs["n_dels"] >= R)
        emit_d = emit_d & ~del_ovf
        ohd = (iota_r == regs["n_dels"][:, None]) & emit_d[:, None]
        dels["client"] = jnp.where(ohd, regs["ds_client"][:, None], dels["client"])
        dels["start"] = jnp.where(ohd, regs["ds_clock"][:, None], dels["start"])
        dels["end"] = jnp.where(
            ohd, (regs["ds_clock"] + v)[:, None], dels["end"]
        )
        dels["valid"] = dels["valid"] | ohd
        regs2["n_dels"] = regs["n_dels"] + emit_d.astype(I32)

        regs2["flags"] = flags2 | jnp.where(row_ovf | del_ovf, FLAG_OVERFLOW, 0)
        return regs2, rows, dels

    regs, rows, dels = jax.lax.fori_loop(0, T, step, init_carry())
    flags = regs["flags"] | jnp.where(regs["st"] != ST_DONE, FLAG_MALFORMED, 0)

    return _resolve_and_pack(
        rows, dels, flags, client_table, key_table, client_hash_table,
        primary_root_hash,
    )


def _resolve_and_pack(
    rows, dels, flags, client_table, key_table, client_hash_table,
    primary_root_hash=None,
):
    """Shared post-decode pass for the V1 and V2 device lanes: raw client
    ids -> interned indices (`client_table`), big-client hash entries ->
    indices (`client_hash_table`), parent_sub hashes -> key indices
    (`key_table`), error-lane row invalidation, and UpdateBatch packing."""
    S, U = rows["client"].shape
    R = dels["client"].shape[1]
    if client_table is not None:
        sorted_ids, perm = client_table
        K = sorted_ids.shape[0]
        if K == 0:
            # empty raw table: only lanes using RAW (>= 0) ids are unknown
            # — hashed big-client entries (<= -2) resolve below
            raw_used = jnp.zeros((S,), bool)
            for name, used in (
                ("client", rows["valid"]),
                ("oc", rows["valid"]),
                ("rc", rows["valid"]),
                ("pc", rows["valid"]),
                ("msc", rows["valid"]),
                ("mec", rows["valid"]),
            ):
                if name not in rows:
                    continue
                raw_used = raw_used | jnp.any(used & (rows[name] >= 0), axis=1)
            raw_used = raw_used | jnp.any(
                dels["valid"] & (dels["client"] >= 0), axis=1
            )
            flags = flags | jnp.where(raw_used, FLAG_UNKNOWN_CLIENT, 0)
            client_table = None

    if client_table is not None:

        def map_ids(arr, used):
            j = jnp.clip(jnp.searchsorted(sorted_ids, arr), 0, max(K - 1, 0))
            hit = (sorted_ids[j] == arr) & (arr >= 0)
            unknown = used & (arr >= 0) & ~hit
            # hashed big-client entries (<= -2) pass through to the hash
            # resolution below
            out = jnp.where(hit, perm[j], jnp.where(arr <= -2, arr, -1))
            return out, jnp.any(unknown, axis=1)

        unk = jnp.zeros((S,), bool)
        for name, used in (
            ("client", rows["valid"]),
            ("oc", rows["valid"]),
            ("rc", rows["valid"]),
            ("pc", rows["valid"]),
            ("msc", rows["valid"]),
            ("mec", rows["valid"]),
        ):
            if name not in rows:
                continue
            rows[name], u = map_ids(rows[name], used)
            unk = unk | u
        dels["client"], u = map_ids(dels["client"], dels["valid"])
        unk = unk | u
        flags = flags | jnp.where(unk, FLAG_UNKNOWN_CLIENT, 0)

    # big-client hash entries -> interned indices (client_hash_table), or
    # FLAG_BIG_CLIENT when no table can resolve them
    cht = client_hash_table
    if cht is not None and cht[0].shape[0] == 0:
        cht = None

    def map_hashed(arr, used):
        hashed = arr <= -2
        if cht is None:
            return arr, jnp.any(used & hashed, axis=1), jnp.zeros((S,), bool)
        hh, hperm = cht
        KH = hh.shape[0]
        hv = -2 - arr
        j = jnp.clip(jnp.searchsorted(hh, hv), 0, KH - 1)
        hit = hashed & (hh[j] == hv)
        out = jnp.where(hit, hperm[j], arr)
        miss = jnp.any(used & hashed & ~hit, axis=1)
        return out, jnp.zeros((S,), bool), miss

    bigf = jnp.zeros((S,), bool)
    unkh = jnp.zeros((S,), bool)
    for name, used in (
        ("client", rows["valid"]),
        ("oc", rows["valid"]),
        ("rc", rows["valid"]),
        ("pc", rows["valid"]),
        ("msc", rows["valid"]),
        ("mec", rows["valid"]),
    ):
        if name not in rows:
            continue
        rows[name], b, m = map_hashed(rows[name], used)
        bigf = bigf | b
        unkh = unkh | m
    dels["client"], b, m = map_hashed(dels["client"], dels["valid"])
    bigf = bigf | b
    unkh = unkh | m
    flags = (
        flags
        | jnp.where(bigf, FLAG_BIG_CLIENT, 0)
        | jnp.where(unkh, FLAG_UNKNOWN_CLIENT, 0)
    )

    # parent_sub key hashes -> interned key indices (map rows on device)
    has_key = rows["valid"] & (rows["keyh"] >= 0)
    key_col = jnp.full((S, U), -1, I32)
    key_miss = has_key
    if key_table is not None:
        khashes, kperm = key_table
        K2 = khashes.shape[0]
        if K2 > 0:
            kj = jnp.clip(jnp.searchsorted(khashes, rows["keyh"]), 0, K2 - 1)
            khit = has_key & (khashes[kj] == rows["keyh"])
            key_col = jnp.where(khit, kperm[kj], -1)
            key_miss = has_key & ~khit
    flags = flags | jnp.where(
        jnp.any(key_miss, axis=1), FLAG_UNKNOWN_KEY, 0
    )

    # named-root parents (multi-root docs): the lane's primary root name
    # maps to the implicit branch (p_root -1); other names resolve through
    # the same key table to their anchor's key id
    rooth = rows.get("rooth")
    p_root_col = jnp.full((S, U), -1, I32)
    if rooth is not None and primary_root_hash is not None:
        prim = primary_root_hash[:, None]
        named = rows["valid"] & (rows["ptag"] == 1) & (prim >= 0)
        nonprim = named & (rooth >= 0) & (rooth != prim)
        root_long = named & (rooth == -2)
        root_miss = nonprim
        if key_table is not None and key_table[0].shape[0] > 0:
            rhashes, rperm = key_table
            rj = jnp.clip(
                jnp.searchsorted(rhashes, rooth), 0, rhashes.shape[0] - 1
            )
            rhit = nonprim & (rhashes[rj] == rooth)
            p_root_col = jnp.where(rhit, rperm[rj], -1)
            root_miss = nonprim & ~rhit
        flags = (
            flags
            | jnp.where(jnp.any(root_miss, axis=1), FLAG_UNKNOWN_KEY, 0)
            | jnp.where(jnp.any(root_long, axis=1), FLAG_UNSUPPORTED, 0)
        )

    # lanes that errored out must not contribute partial rows
    lane_ok = (flags & FLAG_ERRORS) == 0
    valid = rows["valid"] & lane_ok[:, None]
    dvalid = dels["valid"] & lane_ok[:, None]
    z_u = jnp.zeros((S, U), I32)
    neg_u = jnp.full((S, U), -1, I32)
    stream = UpdateBatch(
        client=rows["client"],
        clock=rows["clock"],
        length=rows["length"],
        origin_client=rows["oc"],
        origin_clock=rows["ok"],
        ror_client=rows["rc"],
        ror_clock=rows["rk"],
        kind=rows["kind"],
        content_ref=rows["ref"],
        content_off=z_u,
        key=key_col,
        p_tag=rows["ptag"],
        p_client=rows["pc"],
        p_clock=rows["pk"],
        p_root=p_root_col,
        mv_sc=rows.get("msc", neg_u),
        mv_sk=rows.get("msk", z_u),
        mv_sa=rows.get("msa", z_u),
        mv_ec=rows.get("mec", neg_u),
        mv_ek=rows.get("mek", z_u),
        mv_ea=rows.get("mea", z_u),
        mv_prio=rows.get("mprio", neg_u),
        valid=valid,
        del_client=dels["client"],
        del_start=dels["start"],
        del_end=dels["end"],
        del_valid=dvalid,
    )
    return stream, flags


def utf8_slice_u16(buf: np.ndarray, start: int, off: int, length: int) -> str:
    """Slice ``length`` UTF-16 units at unit-offset ``off`` from the UTF-8
    string starting at byte ``start`` of ``buf``.

    Offsets landing inside a surrogate pair render the severed half as
    U+FFFD — exact `split_str_utf16` / SplittableString parity
    (block.rs:1386-1502, :1852-1860).
    """
    i = int(start)

    def unit_at(i):
        b0 = buf[i]
        if b0 < 0x80:
            return 1, 1
        if b0 < 0xE0:
            return 2, 1
        if b0 < 0xF0:
            return 3, 1
        return 4, 2

    out = []
    u = 0
    while u < off:
        nb, nu = unit_at(i)
        i += nb
        u += nu
    need = length
    if u > off:
        # the slice starts inside a surrogate pair: its severed low
        # half renders as U+FFFD
        out.append("�")
        need -= u - off
    s = i
    while need > 0:
        nb, nu = unit_at(i)
        if nu > need:
            # ends inside a pair: severed high half renders as U+FFFD
            out.append(bytes(buf[s:i]).decode("utf-8", errors="surrogatepass"))
            out.append("�")
            return "".join(out)
        i += nb
        need -= nu
    out.append(bytes(buf[s:i]).decode("utf-8", errors="surrogatepass"))
    return "".join(out)


def _wire_any_values(flat: np.ndarray, start: int, off: int, length: int) -> list:
    """ContentAny at wire offset `start`: count varint then Any values."""
    from ytpu.encoding.lib0 import Cursor, read_any

    cur = Cursor(bytes(flat[start:]))
    n = cur.read_var_uint()
    out = []
    for i in range(min(n, off + length)):
        v = read_any(cur)
        if i >= off:
            out.append(v)
    return out


def _wire_any_values_countless(
    flat: np.ndarray, start: int, off: int, length: int
) -> list:
    """V2-lane ContentAny span: values start AT `start` (the count lives in
    the len column — the caller's `off + length` bounds the read)."""
    from ytpu.encoding.lib0 import Cursor, read_any

    cur = Cursor(bytes(flat[start:]))
    out = []
    for i in range(off + length):
        v = read_any(cur)
        if i >= off:
            out.append(v)
    return out


def _wire_json_values(flat: np.ndarray, start: int, off: int, length: int) -> list:
    """ContentJson at `start`: count then JSON strings (parsed, None on
    parse failure — ContentJSON.values parity)."""
    import json as _json

    from ytpu.encoding.lib0 import Cursor

    cur = Cursor(bytes(flat[start:]))
    n = cur.read_var_uint()
    out = []
    for i in range(min(n, off + length)):
        s = cur.read_string()
        if i >= off:
            try:
                out.append(_json.loads(s))
            except (ValueError, TypeError):
                out.append(None)
    return out


def _wire_json_raw(flat: np.ndarray, start: int, off: int, length: int) -> list:
    """ContentJson raw strings (re-encode path: byte-exact round trips)."""
    from ytpu.encoding.lib0 import Cursor

    cur = Cursor(bytes(flat[start:]))
    n = cur.read_var_uint()
    out = []
    for i in range(min(n, off + length)):
        s = cur.read_string()
        if i >= off:
            out.append(s)
    return out


def _wire_embed_value(flat: np.ndarray, start: int):
    from ytpu.encoding.lib0 import Cursor, any_from_json

    return any_from_json(Cursor(bytes(flat[start:])).read_string())


def _wire_binary_value(flat: np.ndarray, start: int) -> bytes:
    from ytpu.encoding.lib0 import Cursor

    return Cursor(bytes(flat[start:])).read_buf()


def _wire_format_kv(flat: np.ndarray, start: int):
    from ytpu.encoding.lib0 import Cursor, any_from_json

    cur = Cursor(bytes(flat[start:]))
    key = cur.read_string()
    return key, any_from_json(cur.read_string())


def _wire_type_branch(flat: np.ndarray, start: int):
    """ContentType at wire offset `start`: TypeRef tag byte (+ name for
    XmlElement/XmlHook) → a Branch carrying just the rendering-relevant
    fields (branch.rs decode_type_ref; WeakRef never reaches here — the
    decoder flags it to the host lane)."""
    from ytpu.core.branch import Branch
    from ytpu.encoding.lib0 import Cursor

    cur = Cursor(bytes(flat[start:]))
    tag = cur.read_u8()
    if tag in (3, 5):  # TYPE_XML_ELEMENT / TYPE_XML_HOOK
        return Branch(tag, type_name=cur.read_string())
    return Branch(tag)


def _wire_type_raw(flat: np.ndarray, start: int) -> bytes:
    """The exact wire bytes of a ContentType payload (for re-emission by
    the encode finisher)."""
    from ytpu.encoding.lib0 import Cursor

    cur = Cursor(bytes(flat[start:]))
    tag = cur.read_u8()
    if tag in (3, 5):
        cur.read_buf()  # name
    return bytes(flat[start : start + cur.pos])


class RawPayloadView:
    """PayloadStore-shaped reader over the raw wire-byte matrix.

    Device-decoded rows address content payloads by ``ref = s * L +
    byte_start``. String refs point at the UTF-8 bytes with ``(off, len)``
    in UTF-16 code units; Any/Json refs at their count varint with
    ``(off, len)`` in values; Embed/Binary/Format refs at their span
    start.
    """

    def __init__(self, buf: np.ndarray, v2_any: bool = False):
        self.buf = np.ascontiguousarray(buf, dtype=np.uint8).reshape(-1)
        # V2-lane states: ContentAny refs point at the FIRST value byte
        # (the V2 wire keeps the element count in the len COLUMN, so the
        # span is count-less; the row's length is the count)
        self.v2_any = v2_any

    def slice_text(self, ref: int, off: int, length: int) -> str:
        return utf8_slice_u16(self.buf, int(ref), off, length)

    def slice_values(self, ref: int, off: int, length: int) -> list:
        if self.v2_any:
            return _wire_any_values_countless(self.buf, int(ref), off, length)
        return _wire_any_values(self.buf, int(ref), off, length)

    def json_values(self, ref: int, off: int, length: int) -> list:
        return _wire_json_values(self.buf, int(ref), off, length)

    def json_raw(self, ref: int, off: int, length: int) -> list:
        return _wire_json_raw(self.buf, int(ref), off, length)

    def embed_value(self, ref: int):
        return _wire_embed_value(self.buf, int(ref))

    def binary_value(self, ref: int) -> bytes:
        return _wire_binary_value(self.buf, int(ref))

    def format_kv(self, ref: int):
        return _wire_format_kv(self.buf, int(ref))

    def type_branch(self, ref: int):
        return _wire_type_branch(self.buf, int(ref))

    def type_raw(self, ref: int) -> bytes:
        return _wire_type_raw(self.buf, int(ref))


class ChunkedWirePayloads:
    """PayloadStore-compatible resolver over a host `PayloadStore` PLUS
    retained wire-byte chunks from device-decoded steps.

    Ref space: ``ref >= 0`` → the PayloadStore (host-encoded rows);
    ``ref <= -2`` → wire chunk byte offset ``-(ref + 2)`` (device-decoded
    rows; the ingestor rebases each step's ``s * L + start`` refs by the
    running total of retained bytes). ``-1`` stays "no payload".
    """

    def __init__(self, store):
        self.store = store
        self._chunks: List[Tuple[int, np.ndarray]] = []  # (base, flat bytes)
        self.total_bytes = 0
        # bumped whenever a chunk is dropped, so incremental consumers
        # (the native finisher's wire-buffer cache) know to resync
        self.generation = 0

    @property
    def items(self):
        return self.store.items

    def add_chunk(self, buf: np.ndarray) -> int:
        """Retain a step's byte matrix; returns the base offset its
        ``s * L + start`` refs must be rebased by."""
        flat = np.ascontiguousarray(buf, dtype=np.uint8).reshape(-1)
        base = self.total_bytes
        self._chunks.append((base, flat))
        self.total_bytes += flat.size
        return base

    def drop_if_unreferenced(self, base: int) -> None:
        """Release the most recent chunk (it turned out to hold no string
        refs — e.g. a delete-only step); only the latest can be dropped."""
        if self._chunks and self._chunks[-1][0] == base:
            self._chunks.pop()
            self.total_bytes = base
            self.generation += 1

    def _locate(self, ref: int) -> Tuple[np.ndarray, int]:
        off = -(int(ref) + 2)
        import bisect

        k = bisect.bisect_right([b for b, _ in self._chunks], off) - 1
        base, flat = self._chunks[k]
        return flat, off - base

    def slice_text(self, ref: int, off: int, length: int) -> str:
        if int(ref) >= 0:
            return self.store.slice_text(ref, off, length)
        flat, start = self._locate(ref)
        return utf8_slice_u16(flat, start, off, length)

    def slice_values(self, ref: int, off: int, length: int) -> list:
        if int(ref) >= 0:
            return self.store.slice_values(ref, off, length)
        flat, start = self._locate(ref)
        return _wire_any_values(flat, start, off, length)

    def json_values(self, ref: int, off: int, length: int) -> list:
        if int(ref) >= 0:
            return self.store.json_values(ref, off, length)
        flat, start = self._locate(ref)
        return _wire_json_values(flat, start, off, length)

    def json_raw(self, ref: int, off: int, length: int) -> list:
        if int(ref) >= 0:
            return self.store.json_raw(ref, off, length)
        flat, start = self._locate(ref)
        return _wire_json_raw(flat, start, off, length)

    def embed_value(self, ref: int):
        if int(ref) >= 0:
            return self.store.embed_value(ref)
        flat, start = self._locate(ref)
        return _wire_embed_value(flat, start)

    def binary_value(self, ref: int) -> bytes:
        if int(ref) >= 0:
            return self.store.binary_value(ref)
        flat, start = self._locate(ref)
        return _wire_binary_value(flat, start)

    def format_kv(self, ref: int):
        if int(ref) >= 0:
            return self.store.format_kv(ref)
        flat, start = self._locate(ref)
        return _wire_format_kv(flat, start)

    def type_branch(self, ref: int):
        if int(ref) >= 0:
            return self.store.items[int(ref)][1].branch
        flat, start = self._locate(ref)
        return _wire_type_branch(flat, start)

    def type_raw(self, ref: int) -> bytes:
        flat, start = self._locate(ref)
        return _wire_type_raw(flat, start)


# --- bounded resident-program wrapper (VERDICT r4 #7) -----------------------
# The decode lane's program is one of the process's LARGEST; jitting it
# per entry (instead of eager op-by-op tracing, which strands its big
# fori_loop executables in caches nothing can evict selectively) makes
# its executables per-function evictable under the progbudget registry.

_decode_updates_v1_impl = decode_updates_v1
_decode_updates_v1_jit = partial(
    jax.jit,
    static_argnames=("max_rows", "max_dels", "n_steps", "max_sections"),
)(_decode_updates_v1_impl)


def decode_updates_v1(
    buf,
    lens,
    max_rows,
    max_dels,
    n_steps=None,
    client_table=None,
    max_sections=None,
    key_table=None,
    client_hash_table=None,
    primary_root_hash=None,
):
    from ytpu.utils.phases import NULL_SPAN, phases, program_memory
    from ytpu.utils.progbudget import tick

    tick()
    if phases.enabled:
        # wire bytes shipped to HBM this step (buf may already be a device
        # array — either way these bytes crossed or will cross the link).
        # size*itemsize, not .nbytes: callers sometimes wrap this entry in
        # an outer jax.jit (bench probes), and tracers carry shape/dtype
        # but not nbytes
        phases.transfer(
            "decode.v1",
            buf.size * buf.dtype.itemsize + lens.size * lens.dtype.itemsize,
            "h2d",
        )
        span = phases.span(
            "decode.v1",
            (buf.shape, max_rows, max_dels, n_steps, max_sections,
             client_table is not None, key_table is not None,
             client_hash_table is not None, primary_root_hash is not None),
            axes=("buf", "max_rows", "max_dels", "n_steps",
                  "max_sections", "client_table", "key_table",
                  "client_hash_table", "primary_root_hash"),
            memory=program_memory(
                _decode_updates_v1_jit,
                buf,
                lens,
                max_rows=max_rows,
                max_dels=max_dels,
                n_steps=n_steps,
                client_table=client_table,
                max_sections=max_sections,
                key_table=key_table,
                client_hash_table=client_hash_table,
                primary_root_hash=primary_root_hash,
            ),
        )
    else:
        span = NULL_SPAN
    with span:
        return _decode_updates_v1_jit(
            buf,
            lens,
            max_rows=max_rows,
            max_dels=max_dels,
            n_steps=n_steps,
            client_table=client_table,
            max_sections=max_sections,
            key_table=key_table,
            client_hash_table=client_hash_table,
            primary_root_hash=primary_root_hash,
        )


decode_updates_v1.__doc__ = _decode_updates_v1_impl.__doc__


def _register_programs():
    from ytpu.utils import progbudget

    progbudget.register("decode_updates_v1", _decode_updates_v1_jit)


_register_programs()
