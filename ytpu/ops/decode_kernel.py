"""Device-side lib0/V1 update decoding — raw wire bytes in HBM → block rows.

The north-star fusion (SURVEY §2 #1, §7 step 8): hosts ship raw Yjs V1
update payloads to the device as a padded ``[S, L]`` byte matrix; the
device turns them into the columnar ``UpdateBatch`` stream the integrate
kernels consume. No host-side parsing, interning, or payload copying —
string payloads stay inside the wire buffer and are addressed by linear
byte offsets (``content_ref = s * L + byte_start``).

Algorithm: a vectorized field-at-a-time state machine. Every iteration
decodes one lib0 varint (or one info byte / one string skip) *in every
update lane simultaneously* — the per-lane parse is sequential (the wire
grammar is), but all S updates advance in lockstep as [S]-wide vector
ops, and UTF-16 lengths of string payloads come from prefix sums over
byte-class masks (the Stream-VByte-style trick: continuation-bit masks +
cumulative sums instead of byte loops).

Grammar decoded here (reference: update.rs:714-749 + :433-488,
block.rs:1786-1835, id_set.rs decode):

    update   := n_clients:var ( n_blocks:var client:var clock:var block* )*
                delete_set
    block    := info:u8
                [ origin:id ]       if info & 0x80
                [ r_origin:id ]     if info & 0x40
                [ parent ]          if info & 0xC0 == 0
                [ parent_sub:str ]  if info & 0xC0 == 0 and info & 0x20
                content
    content  := GC len:var | Skip len:var | Deleted len:var | String str
                (other kinds → host fallback, flagged)
    delete_set := n_clients:var ( client:var n_ranges:var (clock:var len:var)* )*

Supported on-device: GC / Skip / Deleted / String blocks with root or
ID parents — i.e. the entire live text-editing data plane. Anything else
(map rows with parent_sub, embeds, Any payloads, moves, subdocs) flags
the update for the host decoder (`ytpu.core.Update.decode_v1`); flagged
updates lose nothing — they take the exact host path they take today.

Client ids are kept *raw* (no interning): YATA's tie-break is monotone
in the client id itself, so with raw ids the rank table for the fused
kernel is the identity (`identity_rank`). Ids ≥ 2^31 flag the update.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ytpu.core.content import (
    BLOCK_GC,
    BLOCK_SKIP,
    CONTENT_DELETED,
    CONTENT_STRING,
)
from ytpu.models.batch_doc import UpdateBatch

__all__ = [
    "pack_updates",
    "decode_updates_v1",
    "default_steps",
    "exact_steps",
    "steps_for_columns",
    "identity_rank",
    "utf8_slice_u16",
    "RawPayloadView",
    "ChunkedWirePayloads",
    "FLAG_UNSUPPORTED",
    "FLAG_OVERFLOW",
    "FLAG_MALFORMED",
    "FLAG_BIG_CLIENT",
    "FLAG_MULTI_CLIENT",
    "FLAG_UNKNOWN_CLIENT",
]

I32 = jnp.int32
U32 = jnp.uint32

# --- per-update flag bits ----------------------------------------------------
FLAG_UNSUPPORTED = 1  # content kind / parent_sub the device cannot decode
FLAG_OVERFLOW = 2  # more blocks / delete ranges than the U/R buckets
FLAG_MALFORMED = 4  # ran past the buffer or did not reach DONE in T steps
FLAG_BIG_CLIENT = 8  # a client id >= 2^31 (needs host interning)
FLAG_MULTI_CLIENT = 16  # informational: >1 client section (wire order may
#                         not be a valid integration order for cross-client
#                         origins inside one update; single-client updates —
#                         the live-editing case — are always ordered)
FLAG_UNKNOWN_CLIENT = 32  # a client id absent from the supplied intern table

FLAG_ERRORS = (
    FLAG_UNSUPPORTED
    | FLAG_OVERFLOW
    | FLAG_MALFORMED
    | FLAG_BIG_CLIENT
    | FLAG_UNKNOWN_CLIENT
)

# --- parser states -----------------------------------------------------------
(
    ST_NCLIENTS,
    ST_NBLOCKS,
    ST_CLIENT,
    ST_CLOCK,
    ST_INFO,
    ST_ORIGIN_C,
    ST_ORIGIN_K,
    ST_ROR_C,
    ST_ROR_K,
    ST_PARENT_INFO,
    ST_PARENT_NAME,
    ST_PARENT_ID_C,
    ST_PARENT_ID_K,
    ST_PARENT_SUB,
    ST_DEL_LEN,
    ST_GC_LEN,
    ST_SKIP_LEN,
    ST_STR,
    ST_DS_NCLIENTS,
    ST_DS_CLIENT,
    ST_DS_NRANGES,
    ST_DS_CLOCK,
    ST_DS_LEN,
    ST_DONE,
    ST_ERR,
) = range(25)

_PAD = 16  # gather guard past the longest update


def pack_updates(
    payloads: List[bytes], pad_to: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Pad raw V1 update byte strings into an ``[S, L] uint8`` matrix.

    This is the *only* host work on the device-decode path — a memcpy.
    """
    lens = np.array([len(p) for p in payloads], dtype=np.int32)
    L = max(int(lens.max()) + _PAD if len(payloads) else _PAD, pad_to or 0)
    buf = np.zeros((len(payloads), L), dtype=np.uint8)
    for i, p in enumerate(payloads):
        buf[i, : len(p)] = np.frombuffer(p, dtype=np.uint8)
    return buf, lens


def identity_rank(k: int) -> jax.Array:
    """Rank table for raw-client-id streams: rank(c) = c."""
    return jnp.arange(k, dtype=I32)


def default_steps(max_rows: int, max_dels: int) -> int:
    """Safe iteration budget: fields per block ≤ 10 (+3/client header),
    2 per delete range (+2/ds client), +4 frame fields."""
    return 4 + 13 * max_rows + 4 * max_dels


def exact_steps(
    n_client_sections: int,
    n_item_blocks: int,
    n_skip_gc_blocks: int,
    n_ds_sections: int,
    n_del_ranges: int,
) -> int:
    """Step budget for one update whose wire-section counts are known
    (native pre-scan): item blocks cost ≤ 10 fields, GC/Skip blocks 2,
    each client section 3 (n_blocks/client/clock), each ds section 2
    (client/n_ranges), each range 2 (clock/len), + 2 frame headers."""
    return (
        2
        + 3 * n_client_sections
        + 10 * n_item_blocks
        + 2 * n_skip_gc_blocks
        + 2 * n_ds_sections
        + 2 * n_del_ranges
    )


def steps_for_columns(cols) -> int:
    """Exact decode step budget for one update from its native pre-scan
    (`ytpu.native.NativeColumns`) — the single cost model shared by the
    ingest fast lane and the full-trace replay planner."""
    import numpy as np

    n_skip_gc = int(np.count_nonzero((cols.kind == 10) | (cols.kind == 0)))
    return exact_steps(
        cols.n_client_sections,
        cols.n_blocks - n_skip_gc + cols.n_zero_len_blocks,
        n_skip_gc,
        cols.n_ds_sections,
        cols.n_dels,
    )


def decode_updates_v1(
    buf: jax.Array,
    lens: jax.Array,
    max_rows: int,
    max_dels: int,
    n_steps: Optional[int] = None,
    client_table: Optional[Tuple[jax.Array, jax.Array]] = None,
    max_sections: Optional[int] = None,
) -> Tuple[UpdateBatch, jax.Array]:
    """Decode S updates into an ``[S, U] / [S, R]`` UpdateBatch stream.

    Returns ``(stream, flags)``; lanes with ``flags & FLAG_ERRORS`` decoded
    incompletely and must be re-decoded on host (their emitted rows are
    marked invalid so a mixed batch stays safe to apply).

    ``client_table=(sorted_ids, perm)`` maps raw client ids to interned
    indices on device (``perm[j]`` is the interned index of ``sorted_ids
    [j]``), so decoded streams can mix with host-encoded batches that use
    a `ClientInterner`. Lanes mentioning an id outside the table flag
    ``FLAG_UNKNOWN_CLIENT`` (host fallback interns it for the next step).

    ``max_sections`` bounds the client-section header (default ``max_rows
    + 1``). Wire-legal updates can carry more sections than emitted rows
    (e.g. sections holding only already-covered Skip runs); callers that
    pre-scan the wire (native columns) pass the real count so such
    updates don't trip the garbage-header guard. Pair it with an
    ``n_steps`` budget that covers the extra section fields
    (`exact_steps`).
    """
    S, L = buf.shape
    U, R = max_rows, max_dels
    T = n_steps or default_steps(U, R)
    max_sec = max_sections if max_sections is not None else U + 1
    b = buf.astype(I32)
    lens = lens.astype(I32)

    # UTF-16 length prefix sums: a UTF-8 head byte (not 0b10xxxxxx) is one
    # code point; a 4-byte lead (>= 0xF0) is a surrogate pair, one extra.
    head = ((b & 0xC0) != 0x80).astype(I32)
    lead4 = (b >= 0xF0).astype(I32)
    zero = jnp.zeros((S, 1), I32)
    u16_psum = jnp.concatenate([zero, jnp.cumsum(head + lead4, axis=1)], axis=1)

    iota_u = jax.lax.broadcasted_iota(I32, (S, U), 1)
    iota_r = jax.lax.broadcasted_iota(I32, (S, R), 1)
    row_ids = jnp.arange(S, dtype=I32)

    def u16_span(a, bnd):
        """UTF-16 code units of bytes [a, b) per lane."""
        a = jnp.clip(a, 0, L)
        bnd = jnp.clip(bnd, 0, L)
        pa = jnp.take_along_axis(u16_psum, a[:, None], axis=1)[:, 0]
        pb = jnp.take_along_axis(u16_psum, bnd[:, None], axis=1)[:, 0]
        return pb - pa

    def init_carry():
        regs = dict(
            pos=jnp.zeros((S,), I32),
            st=jnp.full((S,), ST_NCLIENTS, I32),
            flags=jnp.zeros((S,), I32),
            clients_left=jnp.zeros((S,), I32),
            blocks_left=jnp.zeros((S,), I32),
            client=jnp.zeros((S,), I32),
            clock=jnp.zeros((S,), I32),
            info=jnp.zeros((S,), I32),
            oc=jnp.full((S,), -1, I32),
            ok=jnp.zeros((S,), I32),
            rc=jnp.full((S,), -1, I32),
            rk=jnp.zeros((S,), I32),
            ptag=jnp.zeros((S,), I32),
            pc=jnp.full((S,), -1, I32),
            pk=jnp.zeros((S,), I32),
            ds_clients_left=jnp.zeros((S,), I32),
            ds_ranges_left=jnp.zeros((S,), I32),
            ds_client=jnp.zeros((S,), I32),
            ds_clock=jnp.zeros((S,), I32),
            n_rows=jnp.zeros((S,), I32),
            n_dels=jnp.zeros((S,), I32),
        )
        rows = dict(
            client=jnp.zeros((S, U), I32),
            clock=jnp.zeros((S, U), I32),
            length=jnp.zeros((S, U), I32),
            oc=jnp.full((S, U), -1, I32),
            ok=jnp.zeros((S, U), I32),
            rc=jnp.full((S, U), -1, I32),
            rk=jnp.zeros((S, U), I32),
            kind=jnp.zeros((S, U), I32),
            ref=jnp.full((S, U), -1, I32),
            ptag=jnp.zeros((S, U), I32),
            pc=jnp.full((S, U), -1, I32),
            pk=jnp.zeros((S, U), I32),
            valid=jnp.zeros((S, U), bool),
        )
        dels = dict(
            client=jnp.zeros((S, R), I32),
            start=jnp.zeros((S, R), I32),
            end=jnp.zeros((S, R), I32),
            valid=jnp.zeros((S, R), bool),
        )
        return regs, rows, dels

    def step(_, carry):
        regs, rows, dels = carry
        pos, st = regs["pos"], regs["st"]
        active = (st != ST_DONE) & (st != ST_ERR)

        # --- one varint (or u8) at the cursor, all lanes at once ---------
        idx = jnp.clip(pos[:, None] + jnp.arange(10, dtype=I32)[None, :], 0, L - 1)
        in_buf = (pos[:, None] + jnp.arange(10, dtype=I32)[None, :]) < lens[:, None]
        bytes10 = jnp.where(in_buf, jnp.take_along_axis(b, idx, axis=1), 0)
        cont = bytes10 >= 0x80
        inb = jnp.concatenate(
            [jnp.ones((S, 1), I32), jnp.cumprod(cont[:, :9].astype(I32), axis=1)],
            axis=1,
        )  # inb[:, i] = byte i belongs to the varint
        nbytes = jnp.sum(inb, axis=1)
        shifts = (7 * jnp.arange(5, dtype=I32))[None, :]
        val = jnp.sum(
            jnp.where(
                inb[:, :5] == 1,
                (bytes10[:, :5].astype(U32) & 0x7F) << shifts.astype(U32),
                jnp.zeros((S, 5), U32),
            ),
            axis=1,
        ).astype(I32)
        ovf = (nbytes > 5) | ((nbytes == 5) & ((bytes10[:, 4] & 0x7F) >= 8))

        is_info = st == ST_INFO
        v = jnp.where(is_info, bytes10[:, 0], val)
        consumed = jnp.where(is_info, 1, nbytes)

        # string states consume the payload bytes too
        is_str_skip = (st == ST_PARENT_NAME) | (st == ST_PARENT_SUB)
        is_str = st == ST_STR
        str_start = pos + nbytes
        consumed = consumed + jnp.where(is_str_skip | is_str, v, 0)

        pos_after = pos + consumed
        is_client_st = (
            (st == ST_CLIENT) | (st == ST_ORIGIN_C) | (st == ST_ROR_C)
            | (st == ST_PARENT_ID_C) | (st == ST_DS_CLIENT)
        )
        big_client = active & ovf & is_client_st
        bad = active & (
            (pos_after > lens)
            # a string length > L would wrap `pos + v` past int32 and slip
            # under the pos_after bound; no real payload exceeds its buffer
            | ((is_str_skip | is_str) & (v > L))
            | (ovf & ~is_info & ~is_client_st)
            | ((st == ST_NCLIENTS) & (v > max_sec))  # absurd header: garbage
        )
        act = active & ~bad & ~big_client

        def on(s):
            return act & (st == s)

        def upd(reg, cond, new):
            return jnp.where(cond, new, reg)

        # --- end-of-block / end-of-ds-range shared bookkeeping -----------
        emit_row_st = on(ST_DEL_LEN) | on(ST_GC_LEN) | on(ST_SKIP_LEN) | on(ST_STR)
        str_len16 = u16_span(str_start, str_start + v)
        blk_len = jnp.where(is_str, str_len16, v)
        blocks_left2 = upd(regs["blocks_left"], emit_row_st, regs["blocks_left"] - 1)
        # a client section with zero blocks (never produced by our encoders,
        # but legal wire) also closes at ST_CLOCK
        empty_client = on(ST_CLOCK) & (regs["blocks_left"] == 0)
        client_done = (emit_row_st & (blocks_left2 == 0)) | empty_client
        clients_left2 = upd(regs["clients_left"], client_done, regs["clients_left"] - 1)
        after_block = jnp.where(
            blocks_left2 > 0,
            ST_INFO,
            jnp.where(clients_left2 > 0, ST_NBLOCKS, ST_DS_NCLIENTS),
        )

        ds_done_range = on(ST_DS_LEN)
        ds_ranges_left2 = upd(
            regs["ds_ranges_left"], ds_done_range, regs["ds_ranges_left"] - 1
        )
        # DS_NRANGES with 0 ranges also closes the ds-client section
        ds_client_done = (ds_done_range & (ds_ranges_left2 == 0)) | (
            on(ST_DS_NRANGES) & (v == 0)
        )
        ds_clients_left2 = upd(
            regs["ds_clients_left"], ds_client_done, regs["ds_clients_left"] - 1
        )
        after_ds_range = jnp.where(
            ds_ranges_left2 > 0,
            ST_DS_CLOCK,
            jnp.where(ds_clients_left2 > 0, ST_DS_CLIENT, ST_DONE),
        )

        # --- content dispatch after the last pre-content field -----------
        kind4 = regs["info"] & 0b1111
        content_st = jnp.where(
            kind4 == CONTENT_DELETED,
            ST_DEL_LEN,
            jnp.where(kind4 == CONTENT_STRING, ST_STR, ST_ERR),
        )
        content_unsupported = content_st == ST_ERR
        has_psub = ((regs["info"] & 0xC0) == 0) & ((regs["info"] & 0x20) != 0)
        after_parent = jnp.where(has_psub, ST_PARENT_SUB, content_st)

        # --- next state -----------------------------------------------------
        nclients_hdr = on(ST_NCLIENTS)
        info_gc = on(ST_INFO) & (v == BLOCK_GC)
        info_skip = on(ST_INFO) & (v == BLOCK_SKIP)
        info_item = on(ST_INFO) & ~info_gc & ~info_skip
        item_next = jnp.where(
            (v & 0x80) != 0,
            ST_ORIGIN_C,
            jnp.where((v & 0x40) != 0, ST_ROR_C, ST_PARENT_INFO),
        )

        st2 = st
        st2 = upd(st2, nclients_hdr, jnp.where(v > 0, ST_NBLOCKS, ST_DS_NCLIENTS))
        st2 = upd(st2, on(ST_NBLOCKS), ST_CLIENT)
        st2 = upd(st2, on(ST_CLIENT), ST_CLOCK)
        st2 = upd(
            st2,
            on(ST_CLOCK),
            jnp.where(
                regs["blocks_left"] > 0,
                ST_INFO,
                jnp.where(clients_left2 > 0, ST_NBLOCKS, ST_DS_NCLIENTS),
            ),
        )
        st2 = upd(st2, info_gc, ST_GC_LEN)
        st2 = upd(st2, info_skip, ST_SKIP_LEN)
        st2 = upd(st2, info_item, item_next)
        st2 = upd(st2, on(ST_ORIGIN_C), ST_ORIGIN_K)
        st2 = upd(
            st2,
            on(ST_ORIGIN_K),
            jnp.where((regs["info"] & 0x40) != 0, ST_ROR_C, content_st),
        )
        st2 = upd(st2, on(ST_ROR_C), ST_ROR_K)
        st2 = upd(st2, on(ST_ROR_K), content_st)
        st2 = upd(
            st2, on(ST_PARENT_INFO), jnp.where(v == 1, ST_PARENT_NAME, ST_PARENT_ID_C)
        )
        st2 = upd(st2, on(ST_PARENT_NAME), after_parent)
        st2 = upd(st2, on(ST_PARENT_ID_C), ST_PARENT_ID_K)
        st2 = upd(st2, on(ST_PARENT_ID_K), after_parent)
        st2 = upd(st2, on(ST_PARENT_SUB), content_st)
        st2 = upd(st2, emit_row_st, after_block)
        st2 = upd(st2, on(ST_DS_NCLIENTS), jnp.where(v > 0, ST_DS_CLIENT, ST_DONE))
        st2 = upd(st2, on(ST_DS_CLIENT), ST_DS_NRANGES)
        st2 = upd(
            st2,
            on(ST_DS_NRANGES),
            jnp.where(
                v > 0,
                ST_DS_CLOCK,
                jnp.where(ds_clients_left2 > 0, ST_DS_CLIENT, ST_DONE),
            ),
        )
        st2 = upd(st2, on(ST_DS_CLOCK), ST_DS_LEN)
        st2 = upd(st2, ds_done_range, after_ds_range)

        # unsupported content discovered at a dispatch point
        unsupported = (
            (on(ST_ORIGIN_K) & ((regs["info"] & 0x40) == 0) & content_unsupported)
            | (on(ST_ROR_K) & content_unsupported)
            | ((on(ST_PARENT_NAME) | on(ST_PARENT_ID_K)) & ~has_psub & content_unsupported)
            | (on(ST_PARENT_SUB))  # map rows need host key interning
        )
        # item with neither origin flag whose dispatch happens after parent
        st2 = upd(st2, unsupported, ST_ERR)
        st2 = upd(st2, bad, ST_ERR)
        st2 = upd(st2, big_client, ST_ERR)

        # --- registers ------------------------------------------------------
        regs2 = dict(regs)
        regs2["pos"] = jnp.where(act, pos_after, pos)
        regs2["st"] = st2
        regs2["clients_left"] = upd(clients_left2, nclients_hdr, v)
        regs2["blocks_left"] = upd(blocks_left2, on(ST_NBLOCKS), v)
        regs2["client"] = upd(regs["client"], on(ST_CLIENT), v)
        clock2 = upd(regs["clock"], on(ST_CLOCK), v)
        regs2["clock"] = upd(clock2, emit_row_st, clock2 + blk_len)
        regs2["info"] = upd(regs["info"], on(ST_INFO), v)
        # reset per-item registers when a new info byte arrives
        fresh = on(ST_INFO)
        regs2["oc"] = upd(upd(regs["oc"], fresh, -1), on(ST_ORIGIN_C), v)
        regs2["ok"] = upd(upd(regs["ok"], fresh, 0), on(ST_ORIGIN_K), v)
        regs2["rc"] = upd(upd(regs["rc"], fresh, -1), on(ST_ROR_C), v)
        regs2["rk"] = upd(upd(regs["rk"], fresh, 0), on(ST_ROR_K), v)
        ptag2 = upd(regs["ptag"], fresh, 0)
        regs2["ptag"] = upd(ptag2, on(ST_PARENT_INFO), jnp.where(v == 1, 1, 2))
        regs2["pc"] = upd(upd(regs["pc"], fresh, -1), on(ST_PARENT_ID_C), v)
        regs2["pk"] = upd(upd(regs["pk"], fresh, 0), on(ST_PARENT_ID_K), v)
        regs2["ds_clients_left"] = upd(ds_clients_left2, on(ST_DS_NCLIENTS), v)
        regs2["ds_ranges_left"] = upd(ds_ranges_left2, on(ST_DS_NRANGES), v)
        regs2["ds_client"] = upd(regs["ds_client"], on(ST_DS_CLIENT), v)
        regs2["ds_clock"] = upd(regs["ds_clock"], on(ST_DS_CLOCK), v)

        flags2 = (
            regs["flags"]
            | jnp.where(bad, FLAG_MALFORMED, 0)
            | jnp.where(big_client, FLAG_BIG_CLIENT, 0)
            | jnp.where(unsupported, FLAG_UNSUPPORTED, 0)
            | jnp.where(nclients_hdr & (v > 1), FLAG_MULTI_CLIENT, 0)
        )

        # --- row / delete-range emission -----------------------------------
        emit = emit_row_st & ~on(ST_SKIP_LEN) & (blk_len > 0)
        row_ovf = emit & (regs["n_rows"] >= U)
        emit = emit & ~row_ovf
        oh = (iota_u == regs["n_rows"][:, None]) & emit[:, None]

        def put_row(name, vec):
            rows[name] = jnp.where(oh, vec[:, None], rows[name])

        is_gc_row = on(ST_GC_LEN)
        row_kind = jnp.where(
            is_gc_row,
            BLOCK_GC,
            jnp.where(is_str, CONTENT_STRING, CONTENT_DELETED),
        )
        put_row("client", regs["client"])
        put_row("clock", regs["clock"])
        put_row("length", blk_len)
        put_row("oc", jnp.where(is_gc_row, -1, regs["oc"]))
        put_row("ok", jnp.where(is_gc_row, 0, regs["ok"]))
        put_row("rc", jnp.where(is_gc_row, -1, regs["rc"]))
        put_row("rk", jnp.where(is_gc_row, 0, regs["rk"]))
        put_row("kind", row_kind)
        put_row("ref", jnp.where(is_str, row_ids * L + str_start, -1))
        put_row("ptag", jnp.where(is_gc_row, 0, regs["ptag"]))
        put_row("pc", jnp.where(is_gc_row, -1, regs["pc"]))
        put_row("pk", jnp.where(is_gc_row, 0, regs["pk"]))
        rows["valid"] = rows["valid"] | oh
        regs2["n_rows"] = regs["n_rows"] + emit.astype(I32)

        emit_d = ds_done_range & (v > 0)
        del_ovf = emit_d & (regs["n_dels"] >= R)
        emit_d = emit_d & ~del_ovf
        ohd = (iota_r == regs["n_dels"][:, None]) & emit_d[:, None]
        dels["client"] = jnp.where(ohd, regs["ds_client"][:, None], dels["client"])
        dels["start"] = jnp.where(ohd, regs["ds_clock"][:, None], dels["start"])
        dels["end"] = jnp.where(
            ohd, (regs["ds_clock"] + v)[:, None], dels["end"]
        )
        dels["valid"] = dels["valid"] | ohd
        regs2["n_dels"] = regs["n_dels"] + emit_d.astype(I32)

        regs2["flags"] = flags2 | jnp.where(row_ovf | del_ovf, FLAG_OVERFLOW, 0)
        return regs2, rows, dels

    regs, rows, dels = jax.lax.fori_loop(0, T, step, init_carry())
    flags = regs["flags"] | jnp.where(regs["st"] != ST_DONE, FLAG_MALFORMED, 0)

    if client_table is not None:
        sorted_ids, perm = client_table
        K = sorted_ids.shape[0]
        if K == 0:
            any_rows = jnp.any(rows["valid"], axis=1) | jnp.any(
                dels["valid"], axis=1
            )
            flags = flags | jnp.where(any_rows, FLAG_UNKNOWN_CLIENT, 0)
            client_table = None

    if client_table is not None:

        def map_ids(arr, used):
            j = jnp.clip(jnp.searchsorted(sorted_ids, arr), 0, max(K - 1, 0))
            hit = (sorted_ids[j] == arr) & (arr >= 0)
            unknown = used & (arr >= 0) & ~hit
            return jnp.where(hit, perm[j], -1), jnp.any(unknown, axis=1)

        unk = jnp.zeros((S,), bool)
        for name, used in (
            ("client", rows["valid"]),
            ("oc", rows["valid"]),
            ("rc", rows["valid"]),
            ("pc", rows["valid"]),
        ):
            rows[name], u = map_ids(rows[name], used)
            unk = unk | u
        dels["client"], u = map_ids(dels["client"], dels["valid"])
        unk = unk | u
        flags = flags | jnp.where(unk, FLAG_UNKNOWN_CLIENT, 0)

    # lanes that errored out must not contribute partial rows
    lane_ok = (flags & FLAG_ERRORS) == 0
    valid = rows["valid"] & lane_ok[:, None]
    dvalid = dels["valid"] & lane_ok[:, None]
    z_u = jnp.zeros((S, U), I32)
    neg_u = jnp.full((S, U), -1, I32)
    stream = UpdateBatch(
        client=rows["client"],
        clock=rows["clock"],
        length=rows["length"],
        origin_client=rows["oc"],
        origin_clock=rows["ok"],
        ror_client=rows["rc"],
        ror_clock=rows["rk"],
        kind=rows["kind"],
        content_ref=rows["ref"],
        content_off=z_u,
        key=neg_u,
        p_tag=rows["ptag"],
        p_client=rows["pc"],
        p_clock=rows["pk"],
        mv_sc=neg_u,
        mv_sk=z_u,
        mv_sa=z_u,
        mv_ec=neg_u,
        mv_ek=z_u,
        mv_ea=z_u,
        mv_prio=neg_u,
        valid=valid,
        del_client=dels["client"],
        del_start=dels["start"],
        del_end=dels["end"],
        del_valid=dvalid,
    )
    return stream, flags


def utf8_slice_u16(buf: np.ndarray, start: int, off: int, length: int) -> str:
    """Slice ``length`` UTF-16 units at unit-offset ``off`` from the UTF-8
    string starting at byte ``start`` of ``buf``.

    Offsets landing inside a surrogate pair render the severed half as
    U+FFFD — exact `split_str_utf16` / SplittableString parity
    (block.rs:1386-1502, :1852-1860).
    """
    i = int(start)

    def unit_at(i):
        b0 = buf[i]
        if b0 < 0x80:
            return 1, 1
        if b0 < 0xE0:
            return 2, 1
        if b0 < 0xF0:
            return 3, 1
        return 4, 2

    out = []
    u = 0
    while u < off:
        nb, nu = unit_at(i)
        i += nb
        u += nu
    need = length
    if u > off:
        # the slice starts inside a surrogate pair: its severed low
        # half renders as U+FFFD
        out.append("�")
        need -= u - off
    s = i
    while need > 0:
        nb, nu = unit_at(i)
        if nu > need:
            # ends inside a pair: severed high half renders as U+FFFD
            out.append(bytes(buf[s:i]).decode("utf-8", errors="surrogatepass"))
            out.append("�")
            return "".join(out)
        i += nb
        need -= nu
    out.append(bytes(buf[s:i]).decode("utf-8", errors="surrogatepass"))
    return "".join(out)


class RawPayloadView:
    """PayloadStore-shaped reader over the raw wire-byte matrix.

    Device-decoded rows address string payloads by ``ref = s * L +
    byte_start`` with ``(off, len)`` in UTF-16 code units; slicing decodes
    UTF-8 forward from the string start (splits keep offsets in units, so
    the walk is exact).
    """

    def __init__(self, buf: np.ndarray):
        self.buf = np.ascontiguousarray(buf, dtype=np.uint8).reshape(-1)

    def slice_text(self, ref: int, off: int, length: int) -> str:
        return utf8_slice_u16(self.buf, int(ref), off, length)

    def slice_values(self, ref: int, off: int, length: int) -> list:
        return list(self.slice_text(ref, off, length))


class ChunkedWirePayloads:
    """PayloadStore-compatible resolver over a host `PayloadStore` PLUS
    retained wire-byte chunks from device-decoded steps.

    Ref space: ``ref >= 0`` → the PayloadStore (host-encoded rows);
    ``ref <= -2`` → wire chunk byte offset ``-(ref + 2)`` (device-decoded
    rows; the ingestor rebases each step's ``s * L + start`` refs by the
    running total of retained bytes). ``-1`` stays "no payload".
    """

    def __init__(self, store):
        self.store = store
        self._chunks: List[Tuple[int, np.ndarray]] = []  # (base, flat bytes)
        self.total_bytes = 0

    @property
    def items(self):
        return self.store.items

    def add_chunk(self, buf: np.ndarray) -> int:
        """Retain a step's byte matrix; returns the base offset its
        ``s * L + start`` refs must be rebased by."""
        flat = np.ascontiguousarray(buf, dtype=np.uint8).reshape(-1)
        base = self.total_bytes
        self._chunks.append((base, flat))
        self.total_bytes += flat.size
        return base

    def drop_if_unreferenced(self, base: int) -> None:
        """Release the most recent chunk (it turned out to hold no string
        refs — e.g. a delete-only step); only the latest can be dropped."""
        if self._chunks and self._chunks[-1][0] == base:
            self._chunks.pop()
            self.total_bytes = base

    def _locate(self, ref: int) -> Tuple[np.ndarray, int]:
        off = -(int(ref) + 2)
        import bisect

        k = bisect.bisect_right([b for b, _ in self._chunks], off) - 1
        base, flat = self._chunks[k]
        return flat, off - base

    def slice_text(self, ref: int, off: int, length: int) -> str:
        if int(ref) >= 0:
            return self.store.slice_text(ref, off, length)
        flat, start = self._locate(ref)
        return utf8_slice_u16(flat, start, off, length)

    def slice_values(self, ref: int, off: int, length: int) -> list:
        if int(ref) >= 0:
            return self.store.slice_values(ref, off, length)
        return list(self.slice_text(ref, off, length))
