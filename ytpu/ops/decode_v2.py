"""Device-side V2 (columnar) update decoding — wire bytes → block rows.

The V2 format (reference: /root/reference/yrs/src/updates/encoder.rs:182-528,
decoder.rs:195-505) is struct-of-arrays on the wire: nine independently
RLE-compressed column buffers (key-clock, client, left/right clock, info,
string, parent-info, type-ref, len) followed by a `rest` stream holding the
structural varints (section headers, Skip lengths, the delete set). That
layout is exactly the device's own columnar model, so — unlike the V1 lane's
byte-at-a-time state machine (`decode_kernel.py`) — V2 decodes with NO
sequential pass over the wire bytes:

1. the 10 sub-buffer spans are split on host (one varint each — memcpy-level
   cost, like `pack_updates`);
2. each RLE column expands on device with an entry-sequential scan (one run
   per step, bulk run writes — runs, not bytes, bound the loop);
3. the `rest` stream is bulk-parsed in one shot: every lib0 varint ends at a
   byte < 0x80, so terminator positions come from a cumsum + searchsorted
   and all values extract in parallel;
4. everything else is pure tensor assembly — per-block column consumption
   counts are computed from the info bytes alone, prefix-summed into
   per-block column indices, and gathered.

Device-supported set (round 5): GC / Skip blocks and EVERY item content
kind except sub-documents — Deleted / String / Any / Binary / Move
decode fully on device (Any values via the rest WALKER, depth-1
lists/objects); Json / Embed / Format / Type structure-decodes on
device while their payload bytes resolve through a pack-time V1-form
sidecar (`_cold_sidecar` — the V2 wire scatters those payloads across
the len/string/type-ref/rest columns in forms the V1-shaped span
readers cannot address, so pack transcodes them once, host-side).
Root, ID, and nested parents, parent_sub map keys (hashed through the
same `key_table` as the V1 lane), multi client sections, and the delete
set all decode on device. Still host-routed (FLAG_UNSUPPORTED): Doc
content (subdoc lifecycle is host-level on both lanes), weak/unknown
type-ref tags, and Any maps nested beyond the walker's stacked scope
(W_DEPTH - 1 = 3 map levels; arrays nest arbitrarily).
Client ids beyond i32 resolve through the SAME
`client_hash_table` as the V1 lane: V2 client columns use *signed*
varints, so the expander reconstructs each big id's unsigned-varint byte
sequence from its 64-bit limbs and applies `client_hash_host`'s mixing
on device; without a table such lanes flag FLAG_BIG_CLIENT.

Output contract is identical to `decode_updates_v1`: ``(UpdateBatch,
flags)`` with per-lane error flags and rows invalidated on flagged lanes;
string content refs are byte offsets into the same packed ``[S, L]`` buffer
(`RawPayloadView` slices them out of the string-column blob exactly as it
does out of a V1 update body).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ytpu.core.content import (
    BLOCK_GC,
    BLOCK_SKIP,
    CONTENT_ANY,
    CONTENT_BINARY,
    CONTENT_DELETED,
    CONTENT_DOC,
    CONTENT_EMBED,
    CONTENT_FORMAT,
    CONTENT_JSON,
    CONTENT_MOVE,
    CONTENT_STRING,
    CONTENT_TYPE,
)
from ytpu.encoding.lib0 import Cursor

from .decode_kernel import (
    FLAG_BIG_CLIENT,
    FLAG_MALFORMED,
    FLAG_MULTI_CLIENT,
    FLAG_OVERFLOW,
    FLAG_UNSUPPORTED,
    KEY_HASH_BYTES,
    _resolve_and_pack,
    pack_updates,
)

__all__ = [
    "pack_updates_v2",
    "pack_updates_v2_raw",
    "decode_updates_v2",
    "decode_updates_v2_raw",
]

I32 = jnp.int32
U32 = jnp.uint32

# span indices into the host-split frame table
(
    SP_KEY_CLOCK,
    SP_CLIENT,
    SP_LEFT_CLOCK,
    SP_RIGHT_CLOCK,
    SP_INFO,
    SP_STRING,
    SP_PARENT_INFO,
    SP_TYPE_REF,
    SP_LEN,
    SP_REST,
    SP_STR_BLOB,
    SP_STR_LENS,
) = range(12)


# content kinds whose V2 payloads scatter across columns in forms the
# V1-shaped span readers cannot address; pack transcodes them into a
# V1-form SIDECAR appended after the update bytes (see pack_updates_v2)
_COLD_KINDS = (CONTENT_JSON, CONTENT_EMBED, CONTENT_FORMAT, 7)  # 7=Type


def _info_has_cold(p: bytes, start: int, length: int) -> bool:
    """Scan the info column's RLE runs for cold content kinds — O(runs)."""
    cur = Cursor(p[start : start + length])
    try:
        while cur.pos < length:
            v = cur.read_u8()
            if cur.pos < length:
                cur.read_var_uint()  # run count - 1
            if v not in (0, BLOCK_SKIP) and (v & 0x0F) in _COLD_KINDS:
                return True
    except Exception:
        pass
    return False


def _cold_sidecar(p: bytes) -> Optional[List[bytes]]:
    """V1-form payload bytes for every cold-kind block, in WIRE block
    order (sections as written, blocks within each section in order).

    The V2 wire splits Json / Embed / Format / Type payloads across the
    len / string / type-ref / rest columns (encoder.rs:253-260); the
    device lane decodes their STRUCTURE (ids, lengths, parents) from
    those columns, but the payload-byte readers (`RawPayloadView`,
    `ChunkedWirePayloads`, the native finisher arenas) all speak the V1
    inline form. `content.encode(EncoderV1)` is by construction exactly
    that form, so pack transcodes each cold payload once, host-side,
    into a sidecar span the row's ref points at. Returns None when the
    update cannot be walked (the device flags it malformed anyway)."""
    from ytpu.core.ids import ID
    from ytpu.core.update import _decode_block
    from ytpu.encoding.codec import DecoderV2, EncoderV1

    try:
        dec = DecoderV2(p)
        out: List[bytes] = []
        n_clients = dec.read_var()
        for _ in range(n_clients):
            n_blocks = dec.read_var()
            client = dec.read_client()
            clock = dec.read_var()
            for _ in range(n_blocks):
                carrier = _decode_block(ID(client, clock), dec)
                if carrier is None:
                    continue
                clock += carrier.len
                content = getattr(carrier, "content", None)
                if content is not None and content.kind in _COLD_KINDS:
                    enc = EncoderV1()
                    content.encode(enc)
                    out.append(enc.to_bytes())
        return out
    except Exception:
        return None


def pack_updates_v2(
    payloads: List[bytes], pad_to: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Optional[np.ndarray]]:
    """Pad raw V2 update byte strings into ``[S, L] uint8`` + frame spans.

    Host cost: eleven varint reads per update (the feature flag, nine
    column-buffer length prefixes, and the string column's inner blob
    length) — no value decoding, interning, or copying beyond the pad —
    UNLESS an update's info column holds cold content kinds (Json /
    Embed / Format / Type), in which case that update's cold payloads
    are transcoded into a V1-form sidecar appended after its bytes (the
    rows' content refs point there; structure still decodes on device).

    Returns ``(buf, lens, spans, sidecar)`` with ``spans[s, k] =
    (start, len)`` for the twelve regions (`SP_*`) and ``sidecar`` an
    ``[S, NCOLD] int32`` of per-cold-block byte offsets into the lane
    row (wire block order, -1 padded) — or None when no lane has cold
    content. A lane that fails frame splitting gets all-zero spans;
    `decode_updates_v2` flags it malformed.
    """
    S = len(payloads)
    spans = np.zeros((S, 12, 2), dtype=np.int32)
    side: List[Optional[List[bytes]]] = [None] * S
    side_failed = [False] * S
    for s, p in enumerate(payloads):
        try:
            cur = Cursor(p)
            cur.read_u8()  # feature flag
            for k in range(9):
                n = cur.read_var_uint()
                spans[s, k] = (cur.pos, n)
                cur.read_exact(n)
            spans[s, SP_REST] = (cur.pos, len(p) - cur.pos)
            # string column inner layout: [varint blob_len][blob][lens rle]
            st, sl = spans[s, SP_STRING]
            if sl > 0:
                scur = Cursor(p[st : st + sl])
                bn = scur.read_var_uint()
                spans[s, SP_STR_BLOB] = (st + scur.pos, bn)
                spans[s, SP_STR_LENS] = (
                    st + scur.pos + bn,
                    sl - scur.pos - bn,
                )
            ist, isl = spans[s, SP_INFO]
            if isl > 0 and _info_has_cold(p, int(ist), int(isl)):
                side[s] = _cold_sidecar(p)
                side_failed[s] = side[s] is None
        except Exception:
            spans[s] = 0  # malformed frame: flagged on device
    n_cold = max((len(c) for c in side if c), default=0)
    if n_cold == 0:
        need = max((len(p) for p in payloads), default=1)
        L = max(pad_to or 0, need, 1)
        buf = np.zeros((S, L), dtype=np.uint8)
        for s, p in enumerate(payloads):
            buf[s, : len(p)] = np.frombuffer(p, dtype=np.uint8)
        lens = np.asarray([len(p) for p in payloads], dtype=np.int32)
        return buf, lens, spans, None
    sidecar = np.full((S, n_cold), -1, dtype=np.int32)
    need = max(
        len(p) + sum(len(c) for c in (side[s] or []))
        for s, p in enumerate(payloads)
    )
    L = max(pad_to or 0, need, 1)
    buf = np.zeros((S, L), dtype=np.uint8)
    for s, p in enumerate(payloads):
        buf[s, : len(p)] = np.frombuffer(p, dtype=np.uint8)
        off = len(p)
        for k, cbytes in enumerate(side[s] or []):
            buf[s, off : off + len(cbytes)] = np.frombuffer(
                cbytes, dtype=np.uint8
            )
            sidecar[s, k] = off
            off += len(cbytes)
        if side_failed[s]:
            spans[s] = 0  # cold walk failed: flag the lane malformed
    lens = np.asarray([len(p) for p in payloads], dtype=np.int32)
    return buf, lens, spans, sidecar


def pack_updates_v2_raw(payloads: List[bytes]):
    """`pack_updates_v2` for the RAW ingest lane (ISSUE-7): the spans
    prescan (eleven varint reads per update — the control stream of the
    Stream-VByte-style split) runs unchanged, but the data stream ships
    as CONCATENATED wire bytes + a per-update offsets table instead of a
    host-padded ``[S, L]`` matrix — the lane matrix is materialized on
    device by `decode_kernel.gather_raw_lanes`, feeding the same
    bulk-varint expanders (`_bulk_uvarints`, `_expand_*`).

    Returns ``(wire, offsets, row_lens, lens, spans, sidecar, width)``:
    ``wire`` the flat u8 arena (each update's bytes followed by its
    V1-form cold sidecars, exactly the packed row layout), ``offsets``
    the ``[S]`` i32 arena starts, ``row_lens`` the ``[S]`` i32 staged
    extent per lane (payload + sidecars — the gather's zero-mask bound,
    which must NOT clip sidecar refs past the payload), ``lens`` the
    ``[S]`` payload lengths `decode_updates_v2` consumes, and ``width``
    the static per-lane window (== the packed ``L``)."""
    buf, lens, spans, sidecar = pack_updates_v2(payloads)
    S, L = buf.shape
    if sidecar is None:
        row_lens = lens.copy()
    else:
        # staged extent = payload + transcoded sidecars (the row tail of
        # the packed matrix past `lens`); derive it from the pack itself
        # so the two layouts cannot diverge. Only sidecar-carrying lanes
        # (cold content — rare) pay the per-row tail scan; plain lanes'
        # extent IS their payload length. A V2 end-to-end raw wiring
        # should fold the extent into the prescan instead (ROADMAP #2).
        row_lens = lens.copy()
        for s in np.nonzero(sidecar[:, 0] >= 0)[0]:
            nz = buf[s].nonzero()[0]
            last = int(nz[-1]) + 1 if nz.size else 0
            row_lens[s] = max(int(lens[s]), last)
    offsets = np.zeros(S, dtype=np.int32)
    if S > 1:
        offsets[1:] = np.cumsum(row_lens[:-1])
    total = int(row_lens.sum())
    wire = np.zeros(max(total, 1), dtype=np.uint8)
    for s in range(S):
        o, n = int(offsets[s]), int(row_lens[s])
        wire[o : o + n] = buf[s, :n]
    return wire, offsets, row_lens, lens, spans, sidecar, L


def decode_updates_v2_raw(
    wire,
    offsets,
    row_lens,
    lens,
    spans,
    width: int,
    **kw,
):
    """V2 decode over the raw concatenated arena: gather the ``[S, L]``
    lane matrix on device (`gather_raw_lanes`, zero-masked at each
    lane's STAGED extent so cold sidecars survive), then run the normal
    `decode_updates_v2` bulk expanders on it. Keyword args pass through
    (tables, sidecar, primary_root_hash)."""
    import jax.numpy as jnp

    from ytpu.ops.decode_kernel import gather_raw_lanes

    buf = gather_raw_lanes(
        jnp.asarray(wire), jnp.asarray(offsets), jnp.asarray(row_lens), width
    )
    return decode_updates_v2(buf, jnp.asarray(lens), spans, **kw)


# --- vectorized varint helpers ----------------------------------------------


def _window(b, pos, end, width):
    """[S, width] byte window at per-lane ``pos``, zero past ``end``."""
    S, L = b.shape
    idx = jnp.clip(pos[:, None] + jnp.arange(width, dtype=I32)[None, :], 0, L - 1)
    ok = (pos[:, None] + jnp.arange(width, dtype=I32)[None, :]) < end[:, None]
    return jnp.where(ok, jnp.take_along_axis(b, idx, axis=1), 0)


def _uvar_from(bytes10):
    """Unsigned lib0 varint from a [S, 10] window → (val, nbytes, ovf)."""
    S = bytes10.shape[0]
    cont = bytes10 >= 0x80
    inb = jnp.concatenate(
        [jnp.ones((S, 1), I32), jnp.cumprod(cont[:, :9].astype(I32), axis=1)],
        axis=1,
    )
    nbytes = jnp.sum(inb, axis=1)
    shifts = (7 * jnp.arange(5, dtype=I32))[None, :].astype(U32)
    val = jnp.sum(
        jnp.where(
            inb[:, :5] == 1,
            (bytes10[:, :5].astype(U32) & 0x7F) << shifts,
            jnp.zeros((S, 5), U32),
        ),
        axis=1,
    ).astype(I32)
    ovf = (nbytes > 5) | ((nbytes == 5) & ((bytes10[:, 4] & 0x7F) >= 8))
    return val, nbytes, ovf


def _svar_from(bytes10):
    """Signed lib0 varint (6 bits + sign in byte 0, then 7-bit groups) from
    a [S, 10] window → (magnitude, negative, nbytes, ovf)."""
    S = bytes10.shape[0]
    cont = bytes10 >= 0x80
    inb = jnp.concatenate(
        [jnp.ones((S, 1), I32), jnp.cumprod(cont[:, :9].astype(I32), axis=1)],
        axis=1,
    )
    nbytes = jnp.sum(inb, axis=1)
    neg = (bytes10[:, 0] & 0x40) != 0
    mag = (bytes10[:, 0].astype(U32) & 0x3F)
    shifts = (6 + 7 * jnp.arange(4, dtype=I32)).astype(U32)
    mag = mag + jnp.sum(
        jnp.where(
            inb[:, 1:5] == 1,
            (bytes10[:, 1:5].astype(U32) & 0x7F) << shifts[None, :],
            jnp.zeros((S, 4), U32),
        ),
        axis=1,
    )
    ovf = (nbytes > 5) | ((nbytes == 5) & ((bytes10[:, 4] & 0x7F) >= 16))
    return mag.astype(I32), neg, nbytes, ovf


def _bulk_uvarints(b, start, end, NV):
    """All unsigned varints of a flat region, in parallel.

    A lib0 varint ends at its first byte < 0x80, so terminator k of the
    region starts value k+1; positions come from a cumsum + searchsorted,
    values from 5-byte windows. Returns (vals [S, NV], n_varints [S],
    ovf [S, NV])."""
    S, L = b.shape
    iota = jnp.arange(L, dtype=I32)[None, :]
    in_region = (iota >= start[:, None]) & (iota < end[:, None])
    term = in_region & (b < 0x80)
    cum = jnp.cumsum(term.astype(I32), axis=1)
    n_varints = cum[:, -1]
    targets = jnp.arange(1, NV + 1, dtype=I32)
    term_pos = jax.vmap(lambda c: jnp.searchsorted(c, targets, side="left"))(cum)
    starts = jnp.concatenate(
        [start[:, None], (term_pos + 1)[:, :-1]], axis=1
    )  # [S, NV]
    idx = jnp.clip(
        starts[:, :, None] + jnp.arange(5, dtype=I32)[None, None, :], 0, L - 1
    )
    w = jnp.take_along_axis(b, idx.reshape(S, -1), axis=1).reshape(S, NV, 5)
    nb = jnp.clip(term_pos - starts + 1, 1, 10)
    inb = jnp.arange(5, dtype=I32)[None, None, :] < jnp.minimum(nb, 5)[:, :, None]
    shifts = (7 * jnp.arange(5, dtype=I32))[None, None, :].astype(U32)
    vals = jnp.sum(
        jnp.where(inb, (w.astype(U32) & 0x7F) << shifts, 0), axis=2
    ).astype(I32)
    ovf = (nb > 5) | ((nb == 5) & ((w[:, :, 4] & 0x7F) >= 8))
    return vals, n_varints, ovf, starts


# --- RLE column expanders ----------------------------------------------------


def _svar_limbs(bytes10):
    """64-bit magnitude of a signed lib0 varint as (lo, hi) u32 limbs.

    Byte 0 contributes 6 bits; byte k ≥ 1 contributes 7 bits at offset
    6 + 7(k-1). Groups straddling bit 32 split across the limbs."""
    S = bytes10.shape[0]
    cont = bytes10 >= 0x80
    inb = jnp.concatenate(
        [jnp.ones((S, 1), I32), jnp.cumprod(cont[:, :9].astype(I32), axis=1)],
        axis=1,
    )
    lo = bytes10[:, 0].astype(U32) & 0x3F
    hi = jnp.zeros((S,), U32)
    for k in range(1, 10):
        o = 6 + 7 * (k - 1)
        g = jnp.where(inb[:, k] == 1, bytes10[:, k].astype(U32) & 0x7F, 0)
        if o < 32:
            lo = lo + (g << o)
            if o > 25:  # straddles bit 32
                hi = hi + (g >> (32 - o))
        else:
            hi = hi + (g << (o - 32))
    return lo, hi


def _hash_u64_varint(lo, hi):
    """`client_hash_host` of the value's UNSIGNED-varint byte sequence,
    recomputed from (lo, hi) limbs — the bridge that lets V2's signed
    client varints resolve through the same host hash table as V1."""
    # 7-bit groups of the 64-bit value
    groups = []
    for k in range(10):
        o = 7 * k
        if o < 32:
            g = (lo >> o) & 0x7F
            if o > 25:
                g = g | ((hi << (32 - o)) & 0x7F)
        else:
            g = (hi >> (o - 32)) & 0x7F
        groups.append(g.astype(U32))
    gs = jnp.stack(groups, axis=-1)  # [S, 10]
    nonzero = gs != 0
    # index of the highest nonzero group (0 when value == 0)
    idx10 = jnp.arange(10, dtype=I32)
    last = jnp.max(jnp.where(nonzero, idx10[None, :], 0), axis=1)
    nbytes = last + 1
    in_seq = idx10[None, :] < nbytes[:, None]
    is_last = idx10[None, :] == last[:, None]
    byte_k = jnp.where(in_seq, gs | jnp.where(is_last, 0, 0x80), 0)
    pow31 = jnp.asarray(
        np.array([pow(31, i, 1 << 32) for i in range(10)], dtype=np.uint32)
    )
    h = jnp.sum(
        jnp.where(in_seq, byte_k.astype(U32) * pow31[None, :], 0).astype(U32),
        axis=1,
    )
    h = (h ^ (nbytes.astype(U32) * jnp.uint32(2654435761))) & jnp.uint32(
        0x3FFFFFFF
    )
    return h.astype(I32)


def _expand_uintoptrle(b, start, length, N, hash_big: bool = False):
    """UIntOptRle column → [S, N] values.

    Entry grammar (codec.py _UIntOptRleDecoder): signed varint; negative →
    run of |v| with count = next uvarint + 2; else single value. Returns
    ``(vals, produced)``. With ``hash_big``, positions whose value
    overflows i32 (real 53-bit client ids) carry
    ``-2 - client_hash`` instead of a truncated magnitude, so the shared
    `client_hash_table` resolution applies (V1-lane convention); other
    columns treat an i32 overflow as garbage-in (clamped value on a
    lane whose structural checks flag it)."""
    S = b.shape[0]
    end = start + length
    iota_n = jnp.arange(N, dtype=I32)[None, :]

    def step(_, carry):
        pos, oidx, vals = carry
        active = (pos < end) & (oidx < N)
        w = _window(b, pos, end, 10)
        mag, neg, nb, ovf = _svar_from(w)
        if hash_big:
            lo, hi = _svar_limbs(w)
            mag = jnp.where(ovf, -2 - _hash_u64_varint(lo, hi), mag)
        w2 = _window(b, pos + nb, end, 10)
        cnt, nb2, _ = _uvar_from(w2)
        count = jnp.where(neg, cnt + 2, 1)
        adv = nb + jnp.where(neg, nb2, 0)
        mask = (
            (iota_n >= oidx[:, None])
            & (iota_n < (oidx + count)[:, None])
            & active[:, None]
        )
        vals = jnp.where(mask, mag[:, None], vals)
        pos = jnp.where(active, pos + adv, pos)
        oidx = jnp.where(active, oidx + count, oidx)
        return pos, oidx, vals

    pos0 = jnp.where(length > 0, start, end)
    init = (pos0, jnp.zeros((S,), I32), jnp.zeros((S, N), I32))
    _, produced, vals = jax.lax.fori_loop(0, N, step, init)
    return vals, produced


def _expand_intdiffoptrle(b, start, length, N):
    """IntDiffOptRle column → [S, N] values (codec.py _IntDiffOptRleDecoder):
    signed varint `encoded` = (diff << 1) | has_count; run values are the
    arithmetic sequence last + diff, last + 2*diff, …"""
    S = b.shape[0]
    end = start + length
    iota_n = jnp.arange(N, dtype=I32)[None, :]

    def step(_, carry):
        pos, oidx, last, vals = carry
        active = (pos < end) & (oidx < N)
        w = _window(b, pos, end, 10)
        mag, neg, nb, _ = _svar_from(w)
        enc = jnp.where(neg, -mag, mag)
        has_count = (enc & 1) != 0
        diff = enc >> 1  # arithmetic shift: negative diffs survive
        w2 = _window(b, pos + nb, end, 10)
        cnt, nb2, _ = _uvar_from(w2)
        count = jnp.where(has_count, cnt + 2, 1)
        adv = nb + jnp.where(has_count, nb2, 0)
        k = iota_n - oidx[:, None] + 1  # 1-based position in the run
        mask = (k >= 1) & (k <= count[:, None]) & active[:, None]
        vals = jnp.where(mask, last[:, None] + diff[:, None] * k, vals)
        last = jnp.where(active, last + diff * count, last)
        pos = jnp.where(active, pos + adv, pos)
        oidx = jnp.where(active, oidx + count, oidx)
        return pos, oidx, last, vals

    pos0 = jnp.where(length > 0, start, end)
    init = (
        pos0,
        jnp.zeros((S,), I32),
        jnp.zeros((S,), I32),
        jnp.zeros((S, N), I32),
    )
    _, produced, _, vals = jax.lax.fori_loop(0, N, step, init)
    return vals, produced


def _expand_rle(b, start, length, N):
    """Rle column → [S, N] u8 values (codec.py _RleDecoder): u8 value, then
    count-1 as uvarint — omitted on the final entry ("repeat forever")."""
    S = b.shape[0]
    end = start + length
    iota_n = jnp.arange(N, dtype=I32)[None, :]

    def step(_, carry):
        pos, oidx, vals = carry
        active = (pos < end) & (oidx < N)
        value = _window(b, pos, end, 1)[:, 0]
        has_count = (pos + 1) < end
        w2 = _window(b, pos + 1, end, 10)
        cnt, nb2, _ = _uvar_from(w2)
        count = jnp.where(has_count, cnt + 1, N)  # tail entry fills out
        adv = 1 + jnp.where(has_count, nb2, 0)
        mask = (
            (iota_n >= oidx[:, None])
            & (iota_n < (oidx + count)[:, None])
            & active[:, None]
        )
        vals = jnp.where(mask, value[:, None], vals)
        pos = jnp.where(active, pos + adv, pos)
        oidx = jnp.where(active, oidx + count, oidx)
        return pos, oidx, vals

    pos0 = jnp.where(length > 0, start, end)
    init = (pos0, jnp.zeros((S,), I32), jnp.zeros((S, N), I32))
    _, produced, vals = jax.lax.fori_loop(0, N, step, init)
    return vals, produced


def _cumsum_excl(x):
    return jnp.cumsum(x, axis=1) - x


# rest-walker container-nesting stack depth: supports maps nested up to
# W_DEPTH - 1 levels (arrays nest arbitrarily at any level — they spend
# the level's own elems counter); deeper wire flags `deep` → host lane
W_DEPTH = 4

# rest-walker FSM states
(
    W_NC,
    W_SEC_N,
    W_SEC_CLK,
    W_BLK,
    W_SKIP,
    W_MVF,
    W_MSC,
    W_MSK,
    W_MEC,
    W_MEK,
    W_ANY,
    W_MKEY,
    W_MVAL,
    W_BUF,
    W_DS,
    W_DONE,
) = range(16)


def _rest_walker(
    b, start, end, NV: int, NB: int, is_skip, any_cnt, is_buf, is_move
):
    """Sequential rest-stream walker for lanes whose blocks put NON-VARINT
    bytes in the rest buffer (Any values, Binary bufs, Move payloads —
    encoder.rs:253-260 routes content through `rest` while ids/lens ride
    the RLE columns).

    Walks the stream with a per-lane FSM driven by the per-block content
    plan (`is_skip` / `any_cnt` / `is_buf` / `is_move`, all [S, NB] from
    the info/len columns): structural varints (section headers, skip
    lengths, the delete set) are decoded into output slots with the SAME
    numbering the flat bulk parse assigns to content-free lanes — so all
    downstream slot arithmetic is shared — while content regions are
    excised, their byte spans recorded per block (`c_start`), and Move
    payload fields parsed inline (they are plain varints). Any values
    step one token per iteration over a W_DEPTH-register container stack
    (arrays spend their level's elems counter, each open map tracks its
    pending pairs; maps nested beyond W_DEPTH - 1 levels set `deep`,
    routing the lane to the host). Client-id-sized move
    fields beyond i32 hash to ``-2 - client_hash`` exactly like `vat_id`.

    Returns dict(vv, vstart, vovf [S, NV], n_varints [S], c_start, mvf,
    msc, msk, mec, mek [S, NB], bad [S], deep [S]).
    """
    S, L = b.shape
    pow31_10 = jnp.asarray(
        np.array([pow(31, i, 1 << 32) for i in range(10)], dtype=np.uint32)
    )

    def win_hash(w10):
        """client_hash_host mixing over a varint's bytes ([S, 10] window)."""
        cont = w10 >= 0x80
        inb = jnp.concatenate(
            [jnp.ones((S, 1), I32), jnp.cumprod(cont[:, :9].astype(I32), axis=1)],
            axis=1,
        )
        nbytes = jnp.sum(inb, axis=1)
        h = jnp.sum(
            jnp.where(inb == 1, w10.astype(U32) * pow31_10[None, :], 0).astype(
                U32
            ),
            axis=1,
        )
        return (
            (h ^ (nbytes.astype(U32) * jnp.uint32(2654435761)))
            & jnp.uint32(0x3FFFFFFF)
        ).astype(I32)

    # per-iteration the FSM consumes a varint, an Any element (or object
    # key/value), a buf, or a zero-byte dispatch. Budget: all structural
    # varints + one dispatch per block + section plumbing + an 8-elements-
    # per-row allowance for Any lists; an Any-heavier lane runs out,
    # finishes != DONE, and flags malformed -> host lane (correct, slower)
    T_total = NV + 3 * NB + 8 * max(1, NB // 2) + 16

    def gat(arr, idx):
        return jnp.take_along_axis(arr, jnp.clip(idx, 0, NB - 1)[:, None], axis=1)[
            :, 0
        ]

    def step(_, carry):
        regs, out = carry
        (
            pos,
            st,
            vidx,
            blk,
            blocks_left,
            nc_left,
            elems,
            pairs,
            depth,
            collapsed,
        ) = regs
        active = (st != W_DONE) & (pos <= end)
        w = _window(b, pos, end, 10)
        val, nb, ovf = _uvar_from(w)
        tag = w[:, 0]

        is_var_state = (
            (st == W_NC)
            | (st == W_SEC_N)
            | (st == W_SEC_CLK)
            | (st == W_SKIP)
            | (st == W_MVF)
            | (st == W_MSC)
            | (st == W_MSK)
            | (st == W_MEC)
            | (st == W_MEK)
            | (st == W_DS)
        )
        # move id fields: values beyond i32 hash like vat_id
        hashed_val = jnp.where(ovf, -2 - win_hash(w), val)

        # --- Any value stepping (depth-1, mirrors the V1 machine) ---------
        in_any = st == W_ANY
        in_mkey = st == W_MKEY
        in_mval = st == W_MVAL
        # second varint in the window (value length/count after the tag)
        w2 = _window(b, pos + 1, end, 10)
        val2, nb2, _ = _uvar_from(w2)
        any_extra = jnp.where(
            (tag == 127) | (tag == 126) | (tag == 121) | (tag == 120),
            0,
            jnp.where(
                tag == 125,
                nb2,
                jnp.where(
                    tag == 124,
                    4,
                    jnp.where(
                        (tag == 123) | (tag == 122),
                        8,
                        jnp.where(
                            (tag == 119) | (tag == 116),
                            nb2 + val2,
                            jnp.where(
                                (tag == 117) | (tag == 118),
                                nb2,  # header: children step individually
                                0,
                            ),
                        ),
                    ),
                ),
            ),
        )
        # Depth-stacked container bookkeeping (r5; the r4 machine was
        # depth-1 — nested containers inside map values flagged `deep`).
        # At depth 0, `elems[:, 0]` counts pending top-level values; each
        # open map at depth d >= 1 tracks `pairs[:, d]` pending pairs and
        # `elems[:, d]` pending array-child value tokens of the CURRENT
        # pair's value. A push past W_DEPTH-1 (3 nested map levels) still
        # flags `deep` — bounded registers, unbounded wire.
        iota_s = jnp.arange(S)
        in_anyval = in_any | in_mval

        def sget(a, d):
            return a[iota_s, jnp.clip(d, 0, W_DEPTH - 1)]

        def sset(a, d, v, mask):
            dd_ = jnp.clip(d, 0, W_DEPTH - 1)
            return a.at[iota_s, dd_].set(jnp.where(mask, v, a[iota_s, dd_]))

        scalar_tag = (tag >= 116) & (tag != 117) & (tag != 118)
        bad_tag = tag < 116
        arr_tag = (tag == 117) & (val2 > 0)
        map_tag = (tag == 118) & (val2 > 0)
        # empty containers complete like scalars — an empty array as a
        # pair value (or last array child) must still fire pair_done
        scalar_like = (
            scalar_tag
            | ((tag == 118) & (val2 == 0))
            | ((tag == 117) & (val2 == 0))
        )
        push = active & in_anyval & map_tag
        deep_bad = (active & in_anyval & bad_tag) | (
            push & (depth >= W_DEPTH - 1)
        )
        push = push & ~deep_bad

        # value-token effects at the current depth (W_ANY tokens are
        # pre-counted in elems[d]; a W_MVAL token is implied by its pair)
        elems_delta = jnp.where(
            active & in_any & scalar_like,
            -1,
            jnp.where(
                active & in_any & arr_tag,
                val2 - 1,
                jnp.where(active & in_mval & arr_tag, val2, 0),
            ),
        )
        ed2 = sget(elems, depth) + elems_delta
        elems_n = sset(elems, depth, ed2, active & in_anyval)
        depth_n = jnp.where(push, depth + 1, depth)
        pairs_n = sset(pairs, depth_n, val2, push)
        elems_n = sset(elems_n, depth_n, 0, push)

        # completion cascade: a finished value at depth d >= 1 completes
        # its pair when no array children remain; a finished map pops and
        # completes one value at the depth below (unrolled W_DEPTH times
        # — a cascade can never be longer than the stack)
        pair_done = active & (
            (in_mval & scalar_like)
            | (in_any & scalar_like & (depth >= 1) & (ed2 == 0))
        )
        for _ in range(W_DEPTH):
            pd = sget(pairs_n, depth_n) - 1
            pairs_n = sset(pairs_n, depth_n, pd, pair_done)
            map_closed = pair_done & (pd <= 0)
            depth_n = jnp.where(map_closed, depth_n - 1, depth_n)
            # value completion at the popped-to depth
            e_at = sget(elems_n, depth_n)
            dec_nested = map_closed & (depth_n >= 1) & (e_at > 0)
            e_new = jnp.where(dec_nested, e_at - 1, e_at)
            elems_n = sset(elems_n, depth_n, e_new, dec_nested)
            dec_top = map_closed & (depth_n == 0)
            elems_n = sset(
                elems_n, jnp.zeros_like(depth_n), sget(elems_n, jnp.zeros_like(depth_n)) - 1, dec_top
            )
            pair_done = map_closed & (depth_n >= 1) & (e_new == 0)
        post_any = active & in_anyval & ~deep_bad
        e_top = sget(elems_n, depth_n)
        to_mkey = (post_any & (depth_n >= 1) & (e_top == 0)) | push
        to_any = post_any & (
            ((depth_n >= 1) & (e_top > 0))
            | ((depth_n == 0) & (elems_n[:, 0] > 0))
        )
        any_finished = (
            active & in_anyval & (depth_n == 0) & (elems_n[:, 0] <= 0)
        )

        # --- consumption / output ----------------------------------------
        consumed = jnp.where(
            is_var_state,
            nb,
            jnp.where(
                in_any | in_mval,
                1 + any_extra,
                jnp.where(
                    in_mkey,
                    nb + val,  # key string: len varint + bytes
                    jnp.where(st == W_BUF, nb + val, 0),  # [len][payload]
                ),
            ),
        )
        consumed = jnp.where(active, consumed, 0)
        # move payload varints are CONTENT: consumed and parsed into the
        # per-block arrays, but never assigned structural slots (the slot
        # numbering must match the content-free bulk parse)
        is_mv_state = (
            (st == W_MVF)
            | (st == W_MSC)
            | (st == W_MSK)
            | (st == W_MEC)
            | (st == W_MEK)
        )
        emit_slot = active & is_var_state & ~is_mv_state
        slot = jnp.clip(vidx, 0, NV - 1)
        stored = jnp.where(
            (st == W_MSC) | (st == W_MEC), hashed_val, val
        )
        vv = out["vv"].at[jnp.arange(S), slot].set(
            jnp.where(emit_slot, stored, out["vv"][jnp.arange(S), slot])
        )
        vstart = out["vstart"].at[jnp.arange(S), slot].set(
            jnp.where(emit_slot, pos, out["vstart"][jnp.arange(S), slot])
        )
        # id-field overflow is legal (hashed); others flag via vovf
        track_ovf = emit_slot & ovf
        vovf = out["vovf"].at[jnp.arange(S), slot].set(
            jnp.where(track_ovf, True, out["vovf"][jnp.arange(S), slot])
        )
        vidx2 = vidx + emit_slot.astype(I32)
        # move flag/clock overflow (clocks past i32) is malformed; id
        # fields (MSC/MEC) hash instead
        mv_num_ovf = (
            active
            & ovf
            & ((st == W_MVF) | (st == W_MSK) | (st == W_MEK))
        )

        # move field capture
        def put_blk(name, cond, value):
            cur = out[name]
            sblk = jnp.clip(blk, 0, NB - 1)
            return cur.at[jnp.arange(S), sblk].set(
                jnp.where(active & cond, value, cur[jnp.arange(S), sblk])
            )

        out2 = dict(out)
        out2["vv"], out2["vstart"], out2["vovf"] = vv, vstart, vovf
        out2["mvf"] = put_blk("mvf", st == W_MVF, val)
        out2["msc"] = put_blk("msc", st == W_MSC, hashed_val)
        out2["msk"] = put_blk("msk", st == W_MSK, val)
        out2["mec"] = put_blk("mec", st == W_MEC, hashed_val)
        out2["mek"] = put_blk("mek", st == W_MEK, val)
        out2["deep"] = out["deep"] | (active & deep_bad)
        # running past the region is malformed (checked at the end too)
        out2["bad"] = (
            out["bad"]
            | (active & (pos + consumed > end) & (consumed > 0))
            | mv_num_ovf
        )

        # --- state transitions --------------------------------------------
        collapsed2 = jnp.where(st == W_MVF, (val & 1) != 0, collapsed)
        blk_is_skip = gat(is_skip, blk)
        blk_any = gat(any_cnt, blk)
        blk_buf = gat(is_buf, blk)
        blk_move = gat(is_move, blk)
        has_content = (blk_any > 0) | blk_buf | blk_move

        # next state (default: stay)
        nst = st
        nst = jnp.where(st == W_NC, jnp.where(val > 0, W_SEC_N, W_DS), nst)
        nst = jnp.where(st == W_SEC_N, W_SEC_CLK, nst)
        nst = jnp.where(st == W_SEC_CLK, W_BLK, nst)
        # BLK dispatch (consumes nothing this step)
        sec_done = blocks_left == 0
        dispatch_skip = (st == W_BLK) & ~sec_done & blk_is_skip
        dispatch_any = (st == W_BLK) & ~sec_done & ~blk_is_skip & (blk_any > 0)
        dispatch_buf = (st == W_BLK) & ~sec_done & ~blk_is_skip & blk_buf
        dispatch_move = (st == W_BLK) & ~sec_done & ~blk_is_skip & blk_move
        dispatch_none = (st == W_BLK) & ~sec_done & ~blk_is_skip & ~has_content
        nst = jnp.where(dispatch_skip, W_SKIP, nst)
        nst = jnp.where(dispatch_any, W_ANY, nst)
        nst = jnp.where(dispatch_buf, W_BUF, nst)
        nst = jnp.where(dispatch_move, W_MVF, nst)
        # none-content blocks advance in place (stay W_BLK)
        nst = jnp.where(
            (st == W_BLK) & sec_done,
            jnp.where(nc_left > 1, W_SEC_N, W_DS),
            nst,
        )
        out2["c_start"] = put_blk(
            "c_start", dispatch_any | dispatch_buf | dispatch_move, pos
        )
        # content-finishing transitions -> back to block dispatch
        fin = (
            (st == W_SKIP)
            | any_finished
            | (st == W_BUF)
            | ((st == W_MSK) & collapsed2)
            | (st == W_MEK)
        )
        nst = jnp.where(st == W_MVF, W_MSC, nst)
        nst = jnp.where(st == W_MSC, W_MSK, nst)
        nst = jnp.where((st == W_MSK) & ~collapsed2, W_MEC, nst)
        nst = jnp.where(st == W_MEC, W_MEK, nst)
        nst = jnp.where(to_mkey, W_MKEY, nst)
        nst = jnp.where(to_any, W_ANY, nst)
        nst = jnp.where(in_mkey, W_MVAL, nst)
        nst = jnp.where(fin, W_BLK, nst)
        nst = jnp.where((st == W_DS) & (pos + consumed >= end), W_DONE, nst)
        nst = jnp.where(active, nst, st)

        adv_blk = (dispatch_none | fin).astype(I32)
        blk2 = blk + jnp.where(active, adv_blk, 0)
        blocks_left2 = blocks_left - jnp.where(active, adv_blk, 0)
        blocks_left2 = jnp.where(
            active & (st == W_SEC_N), val, blocks_left2
        )
        nc_left2 = jnp.where(active & (st == W_NC), val, nc_left)
        nc_left2 = nc_left2 - (active & (st == W_BLK) & sec_done).astype(I32)
        # entering a new Any block resets the whole container stack
        elems3 = jnp.where(
            dispatch_any[:, None],
            jnp.concatenate(
                [blk_any[:, None], jnp.zeros((S, W_DEPTH - 1), I32)], axis=1
            ),
            elems_n,
        )
        pairs3 = jnp.where(dispatch_any[:, None], 0, pairs_n)
        depth3 = jnp.where(dispatch_any, 0, depth_n)

        pos2 = pos + consumed
        regs2 = (
            jnp.where(active, pos2, pos),
            nst,
            vidx2,
            blk2,
            blocks_left2,
            nc_left2,
            elems3,
            pairs3,
            depth3,
            collapsed2,
        )
        return regs2, out2

    z_nv = jnp.zeros((S, NV), I32)
    out0 = dict(
        vv=z_nv,
        vstart=z_nv,
        vovf=jnp.zeros((S, NV), bool),
        c_start=jnp.zeros((S, NB), I32),
        mvf=jnp.zeros((S, NB), I32),
        msc=jnp.full((S, NB), -1, I32),
        msk=jnp.zeros((S, NB), I32),
        mec=jnp.full((S, NB), -1, I32),
        mek=jnp.zeros((S, NB), I32),
        bad=jnp.zeros((S,), bool),
        deep=jnp.zeros((S,), bool),
    )
    regs0 = (
        jnp.where(end > start, start, end),  # pos
        jnp.where(end > start, W_NC, W_DONE),  # empty rest: done
        jnp.zeros((S,), I32),  # vidx
        jnp.zeros((S,), I32),  # blk
        jnp.zeros((S,), I32),  # blocks_left
        jnp.zeros((S,), I32),  # nc_left
        jnp.zeros((S, W_DEPTH), I32),  # elems (container stack)
        jnp.zeros((S, W_DEPTH), I32),  # pairs (container stack)
        jnp.zeros((S,), I32),  # depth
        jnp.zeros((S,), bool),  # collapsed
    )
    regs, out = jax.lax.fori_loop(0, T_total, step, (regs0, out0))
    pos_f, st_f, vidx_f = regs[0], regs[1], regs[2]
    out["bad"] = out["bad"] | ((st_f != W_DONE) & (end > start))
    out["n_varints"] = vidx_f
    return out


def decode_updates_v2(
    buf: jax.Array,
    lens: jax.Array,
    spans: jax.Array,
    max_rows: int,
    max_dels: int,
    max_sections: Optional[int] = None,
    client_table: Optional[Tuple[jax.Array, jax.Array]] = None,
    key_table: Optional[Tuple[jax.Array, jax.Array]] = None,
    client_hash_table: Optional[Tuple[jax.Array, jax.Array]] = None,
    primary_root_hash: Optional[jax.Array] = None,
    sidecar: Optional[np.ndarray] = None,
):
    """Decode S V2 updates into an ``[S, U] / [S, R]`` UpdateBatch stream.

    Same contract as `decode_updates_v1` (see its docstring for the table
    semantics); `spans` and `sidecar` come from `pack_updates_v2` (the
    sidecar carries V1-form payload spans for Json / Embed / Format /
    Type content — see `_cold_sidecar`). Client ids beyond i32 hash to
    the same `client_hash_table` entries as the V1 lane: the expander
    reconstructs the id's UNSIGNED-varint bytes from its signed V2
    encoding and applies `client_hash_host`'s mixing on device.
    """
    S, L = buf.shape
    U, R = max_rows, max_dels
    SEC = max_sections if max_sections is not None else 4
    NB = U + 8  # blocks incl. Skip runs (emitted rows still cap at U)
    DSEC = R + 4
    NV = 2 + 2 * SEC + NB + 2 * DSEC + 2 * R
    NS = 2 * U + 4  # strings: root names + parent_subs + string contents
    NCLI = 3 * NB + SEC + 2
    b = buf.astype(I32)
    lens = lens.astype(I32)
    sp = spans.astype(I32)

    def span(k):
        return sp[:, k, 0], sp[:, k, 1]

    flags = jnp.zeros((S,), I32)
    # all-zero spans with a non-empty payload = host frame split failed
    frame_bad = (lens > 0) & (jnp.sum(jnp.abs(sp.reshape(S, -1)), axis=1) == 0)
    flags = flags | jnp.where(frame_bad, FLAG_MALFORMED, 0)

    # --- column expansions ---------------------------------------------------
    info_vals, info_n = _expand_rle(b, *span(SP_INFO), NB)
    pi_vals, pi_n = _expand_rle(b, *span(SP_PARENT_INFO), NB)
    cli_vals, cli_n = _expand_uintoptrle(
        b, *span(SP_CLIENT), NCLI, hash_big=True
    )
    lc_vals, lc_n = _expand_intdiffoptrle(b, *span(SP_LEFT_CLOCK), NB)
    rc_vals, rc_n = _expand_intdiffoptrle(b, *span(SP_RIGHT_CLOCK), NB)
    len_vals, len_n = _expand_uintoptrle(b, *span(SP_LEN), NB)
    tr_vals, tr_n = _expand_uintoptrle(b, *span(SP_TYPE_REF), NB)
    str16, str_n = _expand_uintoptrle(b, *span(SP_STR_LENS), NS)

    # string byte offsets: binary-search the buffer's UTF-16 prefix sums for
    # each string's cumulative unit target inside the blob
    head = ((b & 0xC0) != 0x80).astype(I32)
    lead4 = (b >= 0xF0).astype(I32)
    zero = jnp.zeros((S, 1), I32)
    u16_psum = jnp.concatenate([zero, jnp.cumsum(head + lead4, axis=1)], axis=1)
    blob_start, blob_len = span(SP_STR_BLOB)
    base16 = jnp.take_along_axis(u16_psum, blob_start[:, None], axis=1)
    tgt16 = base16 + _cumsum_excl(str16)  # [S, NS]
    lo = jnp.broadcast_to(blob_start[:, None], (S, NS))
    hi = jnp.broadcast_to((blob_start + blob_len)[:, None], (S, NS))
    for _ in range(18):  # L < 2^18: first byte index with psum >= target
        mid = (lo + hi) // 2
        pm = jnp.take_along_axis(u16_psum, jnp.clip(mid, 0, L), axis=1)
        go_right = pm < tgt16
        lo = jnp.where(go_right, mid + 1, lo)
        hi = jnp.where(go_right, hi, mid)
    str_start = lo  # [S, NS] byte offsets
    str_end = jnp.concatenate(
        [str_start[:, 1:], (blob_start + blob_len)[:, None]], axis=1
    )
    str_bytes = str_end - str_start

    # --- per-block column consumption (info bytes alone determine it) --------
    # (hoisted above the rest parse: the rest WALKER needs the per-block
    # content plan to excise non-varint regions — Any values, bufs, move
    # payloads — from the structural varint stream)
    iota_nb = jnp.arange(NB, dtype=I32)[None, :]
    info = info_vals
    is_gc = info == BLOCK_GC
    is_skip = info == BLOCK_SKIP
    is_item = ~is_gc & ~is_skip
    kind4 = info & 0x0F
    has_o = is_item & ((info & 0x80) != 0)
    has_r = is_item & ((info & 0x40) != 0)
    cant_copy = is_item & ~has_o & ~has_r
    has_psub = cant_copy & ((info & 0x20) != 0)
    # parent_info column index per block (consumed by parentful items only)
    pi_idx = _cumsum_excl(cant_copy.astype(I32))
    pi = jnp.take_along_axis(pi_vals, jnp.clip(pi_idx, 0, NB - 1), axis=1)
    is_root = cant_copy & (pi == 1)
    is_nested = cant_copy & (pi != 1)
    # client column: 1 per origin id, ror id, nested parent id
    c_cnt = has_o.astype(I32) + has_r.astype(I32) + is_nested.astype(I32)
    c_base = _cumsum_excl(c_cnt)
    # left-clock column: origin clock or nested-parent clock (≤ 1 per block)
    l_cnt = (has_o | is_nested).astype(I32)
    l_idx = _cumsum_excl(l_cnt)
    r_idx = _cumsum_excl(has_r.astype(I32))
    # content-kind masks (full set — every kind structure-decodes here;
    # only Doc and weak/unknown type tags still route to the host)
    is_str_content = is_item & (kind4 == CONTENT_STRING)
    is_del_content = is_item & (kind4 == CONTENT_DELETED)
    is_any_content = is_item & (kind4 == CONTENT_ANY)
    is_json_content = is_item & (kind4 == CONTENT_JSON)
    is_bin_content = is_item & (kind4 == CONTENT_BINARY)
    is_embed_content = is_item & (kind4 == CONTENT_EMBED)
    is_format_content = is_item & (kind4 == CONTENT_FORMAT)
    is_type_content = is_item & (kind4 == CONTENT_TYPE)
    is_doc_content = is_item & (kind4 == (CONTENT_DOC & 0x0F))
    is_move_content = is_item & ((info & 0x0F) == (CONTENT_MOVE & 0x0F))
    # one traversable Any value rides the rest stream for these kinds
    # (Embed value, Format value, Doc options) — the walker excises it
    # and, for Embed/Format, the sidecar carries its V1-form transcode
    is_one_any = is_item & (
        is_embed_content | is_format_content | is_doc_content
    )
    # len column: GC + Deleted lengths, plus Any/Json element counts
    # (ContentAny/ContentJson write their element count via write_len —
    # encoder.rs:253-260 — so they consume len-column entries too)
    n_cnt = (
        is_gc | is_del_content | is_any_content | is_json_content
    ).astype(I32)
    n_idx = _cumsum_excl(n_cnt)
    len_at_blk = jnp.take_along_axis(
        len_vals, jnp.clip(n_idx, 0, NB - 1), axis=1
    )
    w_any_cnt = jnp.where(
        is_any_content, len_at_blk, jnp.where(is_one_any, 1, 0)
    )
    # type-ref column: one entry per ContentType block; XmlElement /
    # XmlHook tags additionally consume a string (the node name)
    tr_idx = _cumsum_excl(is_type_content.astype(I32))
    tr_tag = jnp.take_along_axis(tr_vals, jnp.clip(tr_idx, 0, NB - 1), axis=1)
    is_type_named = is_type_content & ((tr_tag == 3) | (tr_tag == 5))
    type_weak_or_unknown = is_type_content & (tr_tag >= 7)
    # string column: root name, parent_sub, then content strings — in
    # that order per block (Json: N strings; Format: the key; XmlElement
    # / XmlHook type: the node name; String: the payload)
    s_cnt = (
        is_root.astype(I32)
        + has_psub.astype(I32)
        + is_str_content.astype(I32)
        + jnp.where(is_json_content, len_at_blk, 0)
        + is_format_content.astype(I32)
        + is_type_named.astype(I32)
    )
    s_base = _cumsum_excl(s_cnt)
    cum_skip = _cumsum_excl(is_skip.astype(I32))  # skips before block j
    cum_skip_incl = jnp.cumsum(is_skip.astype(I32), axis=1)

    def _skips_upto(n):
        """Skip blocks among blocks [0, n) per lane ([S] -> [S])."""
        at = jnp.take_along_axis(
            cum_skip_incl, jnp.clip(n - 1, 0, NB - 1)[:, None], axis=1
        )[:, 0]
        return jnp.where(n > 0, at, 0)

    # --- rest stream -----------------------------------------------------------
    # Content-free lanes (every block GC/Skip/Deleted/String/Json/Type):
    # the rest stream is flat varints and parses in ONE parallel pass.
    # Lanes whose blocks put bytes in rest (Any values, Binary bufs, Move
    # payloads, Embed/Format/Doc values) run the sequential WALKER below,
    # which excises those regions while assigning the SAME structural slot
    # numbering — downstream arithmetic is shared.
    rest_start, rest_len = span(SP_REST)
    v, n_varints, v_ovf, v_starts = _bulk_uvarints(
        b, rest_start, rest_start + rest_len, NV
    )
    lane_has_content = jnp.any(
        (w_any_cnt > 0) | is_bin_content | is_move_content, axis=1
    )

    def _run_walker(_):
        return _rest_walker(
            b,
            rest_start,
            rest_start + rest_len,
            NV,
            NB,
            is_skip,
            w_any_cnt,
            is_bin_content,
            is_move_content,
        )

    def _skip_walker(_):
        z_nv = jnp.zeros((S, NV), I32)
        z_nb = jnp.zeros((S, NB), I32)
        return dict(
            vv=z_nv,
            vstart=z_nv,
            vovf=jnp.zeros((S, NV), bool),
            c_start=z_nb,
            mvf=z_nb,
            msc=jnp.full((S, NB), -1, I32),
            msk=z_nb,
            mec=jnp.full((S, NB), -1, I32),
            mek=z_nb,
            bad=jnp.zeros((S,), bool),
            deep=jnp.zeros((S,), bool),
            n_varints=jnp.zeros((S,), I32),
        )

    # the sequential walker only runs when SOME lane actually put content
    # bytes in rest — the pure-text hot path (B4) stays bulk-only
    walker_out = jax.lax.cond(
        jnp.any(lane_has_content), _run_walker, _skip_walker, 0
    )
    sel = lane_has_content[:, None]
    v = jnp.where(sel, walker_out["vv"], v)
    v_starts = jnp.where(sel, walker_out["vstart"], v_starts)
    v_ovf = jnp.where(sel, walker_out["vovf"], v_ovf)
    n_varints = jnp.where(lane_has_content, walker_out["n_varints"], n_varints)
    walk_bad = lane_has_content & walker_out["bad"]
    deep_any = lane_has_content & walker_out["deep"]
    iota_nv = jnp.arange(NV, dtype=I32)[None, :]

    def vat(idx, used):
        """v[idx] with bounds+overflow accounting for consumed positions."""
        safe = jnp.clip(idx, 0, NV - 1)
        out = jnp.take_along_axis(v, safe, axis=1)
        bad = used & ((idx >= n_varints[:, None]) | (idx >= NV))
        ob = used & jnp.take_along_axis(v_ovf, safe, axis=1)
        return out, jnp.any(bad | ob, axis=1)

    pow31_10 = jnp.asarray(
        np.array([pow(31, i, 1 << 32) for i in range(10)], dtype=np.uint32)
    )

    def vat_id(idx, used):
        """Like `vat` for CLIENT-ID positions: a value beyond i32 is a real
        53-bit Yjs client — hash its wire bytes (`client_hash_host` mixing;
        rest varints are already the unsigned encoding) to ``-2 - h``
        instead of flagging malformed."""
        safe = jnp.clip(idx, 0, NV - 1)
        out = jnp.take_along_axis(v, safe, axis=1)
        bad = used & ((idx >= n_varints[:, None]) | (idx >= NV))
        ovf = jnp.take_along_axis(v_ovf, safe, axis=1)
        st = jnp.take_along_axis(v_starts, safe, axis=1)  # [S, K]
        K = st.shape[1]
        widx = jnp.clip(
            st[:, :, None] + jnp.arange(10, dtype=I32)[None, None, :], 0, L - 1
        )
        wb = jnp.take_along_axis(b, widx.reshape(S, -1), axis=1).reshape(
            S, K, 10
        )
        cont = wb >= 0x80
        inb = jnp.concatenate(
            [
                jnp.ones((S, K, 1), I32),
                jnp.cumprod(cont[:, :, :9].astype(I32), axis=2),
            ],
            axis=2,
        )
        nbytes = jnp.sum(inb, axis=2)
        h = jnp.sum(
            jnp.where(
                inb == 1, wb.astype(U32) * pow31_10[None, None, :], 0
            ).astype(U32),
            axis=2,
        )
        h = (
            (h ^ (nbytes.astype(U32) * jnp.uint32(2654435761)))
            & jnp.uint32(0x3FFFFFFF)
        ).astype(I32)
        out = jnp.where(ovf, -2 - h, out)
        return out, jnp.any(bad, axis=1)

    nc = v[:, 0]
    malformed = (lens > 0) & (n_varints < 1)
    flags = flags | jnp.where(nc > 1, FLAG_MULTI_CLIENT, 0)
    sec_ovf = nc > SEC

    # --- section walk (tiny: SEC iterations of [S]-vector work) --------------
    def sec_step(i, carry):
        vidx, base, sec_h, sec_base, sec_nb = carry
        active = i < nc
        nb_i, _ = vat(vidx[:, None], active[:, None])
        nb_i = nb_i[:, 0]
        sec_h = sec_h.at[:, i].set(jnp.where(active, vidx, -1))
        sec_base = sec_base.at[:, i].set(jnp.where(active, base, NB))
        sec_nb = sec_nb.at[:, i].set(jnp.where(active, nb_i, 0))
        nxt = jnp.clip(base + nb_i, 0, NB)
        skips_i = _skips_upto(nxt) - _skips_upto(base)
        vidx = jnp.where(active, vidx + 2 + skips_i, vidx)
        base = jnp.where(active, nxt, base)
        return vidx, base, sec_h, sec_base, sec_nb

    sec_h0 = jnp.full((S, SEC), -1, I32)
    sec_b0 = jnp.full((S, SEC), NB, I32)
    sec_n0 = jnp.zeros((S, SEC), I32)
    vidx_end, total_blocks, sec_h, sec_base, sec_nb = jax.lax.fori_loop(
        0, SEC, sec_step, (jnp.ones((S,), I32), jnp.zeros((S,), I32),
                           sec_h0, sec_b0, sec_n0)
    )
    blk_ovf = (total_blocks > NB) | (total_blocks > info_n) | sec_ovf

    valid_blk = iota_nb < total_blocks[:, None]
    # section id per block: number of section bases <= j, minus 1
    sec_id = (
        jnp.sum(
            (sec_base[:, None, :] <= iota_nb[:, :, None]).astype(I32), axis=2
        )
        - 1
    )
    sec_id = jnp.clip(sec_id, 0, SEC - 1)
    g = partial(jnp.take_along_axis, axis=1)
    blk_h = g(sec_h, sec_id)  # section header varint index
    blk_secbase = g(sec_base, sec_id)
    sec_clk, bad_v1 = vat(jnp.clip(blk_h, 0, NV - 1) + 1, valid_blk & (blk_h >= 0))
    sec_cli_idx = sec_id + g(c_base, jnp.clip(blk_secbase, 0, NB - 1))
    sec_client = g(cli_vals, jnp.clip(sec_cli_idx, 0, NCLI - 1))

    # skip lengths ride the rest stream between their section's blocks
    skip_rank_in_sec = cum_skip - g(cum_skip, jnp.clip(blk_secbase, 0, NB - 1))
    skip_vidx = blk_h + 2 + skip_rank_in_sec
    skip_len, bad_v2 = vat(jnp.clip(skip_vidx, 0, NV - 1), valid_blk & is_skip)

    # per-block fields from the expanded columns
    cli_at = lambda idx: g(cli_vals, jnp.clip(idx, 0, NCLI - 1))
    blk_cli_base = (sec_id + 1) + c_base
    oc = jnp.where(valid_blk & has_o, cli_at(blk_cli_base), -1)
    ok = jnp.where(
        valid_blk & has_o, g(lc_vals, jnp.clip(l_idx, 0, NB - 1)), 0
    )
    rc = jnp.where(valid_blk & has_r, cli_at(blk_cli_base + has_o), -1)
    rk = jnp.where(
        valid_blk & has_r, g(rc_vals, jnp.clip(r_idx, 0, NB - 1)), 0
    )
    pc = jnp.where(valid_blk & is_nested, cli_at(blk_cli_base), -1)
    pk = jnp.where(
        valid_blk & is_nested, g(lc_vals, jnp.clip(l_idx, 0, NB - 1)), 0
    )
    ptag = jnp.where(is_root, 1, jnp.where(is_nested, 2, 0))

    # string indices: root name at s_base, psub next, content last
    psub_idx = s_base + is_root
    content_sidx = psub_idx + has_psub
    str_at = lambda idx, arr: g(arr, jnp.clip(idx, 0, NS - 1))
    psub_start = str_at(psub_idx, str_start)
    psub_bytes = str_at(psub_idx, str_bytes)
    content_start = str_at(content_sidx, str_start)
    content_len16 = str_at(content_sidx, str16)

    # parent_sub / root-name hashes — identical mixing to the V1 lane's
    # key_hash_host (shared table resolution on both lanes)
    pow31 = jnp.asarray(
        np.array(
            [pow(31, i, 1 << 32) for i in range(KEY_HASH_BYTES)], dtype=np.uint32
        )
    )

    def name_hash(start, nbytes):
        """[S, NB] hash of the string column entry at byte `start`."""
        idx = jnp.clip(
            start[:, :, None]
            + jnp.arange(KEY_HASH_BYTES, dtype=I32)[None, None, :],
            0,
            L - 1,
        )
        w = jnp.take_along_axis(b, idx.reshape(S, -1), axis=1).reshape(
            S, NB, KEY_HASH_BYTES
        )
        m = (
            jnp.arange(KEY_HASH_BYTES, dtype=I32)[None, None, :]
            < nbytes[:, :, None]
        )
        h = jnp.sum(
            jnp.where(m, w.astype(U32) * pow31[None, None, :], 0).astype(U32),
            axis=2,
        )
        return (
            (h ^ (nbytes.astype(U32) * jnp.uint32(2654435761)))
            & jnp.uint32(0x7FFFFFFF)
        ).astype(I32)

    khash = name_hash(psub_start, psub_bytes)
    keyh = jnp.where(valid_blk & has_psub, khash, -1)
    key_too_long = valid_blk & has_psub & (psub_bytes > KEY_HASH_BYTES)
    # root-parent names (is_root rows consume the string at s_base)
    rname_start = str_at(s_base, str_start)
    rname_bytes = str_at(s_base, str_bytes)
    rhash = name_hash(rname_start, rname_bytes)
    rooth = jnp.where(
        valid_blk & is_root,
        jnp.where(rname_bytes <= KEY_HASH_BYTES, rhash, -2),
        -1,
    )

    # block lengths + clocks
    blk_len = jnp.where(
        is_str_content,
        content_len16,
        jnp.where(
            is_gc | is_del_content | is_any_content | is_json_content,
            len_at_blk,
            jnp.where(
                is_skip,
                skip_len,
                # Binary/Move/Embed/Format/Type/Doc occupy ONE clock unit
                jnp.where(is_item, 1, 0),
            ),
        ),
    )
    blk_len = jnp.where(valid_blk, blk_len, 0)
    len_psum = _cumsum_excl(blk_len)
    clock = sec_clk + len_psum - g(len_psum, jnp.clip(blk_secbase, 0, NB - 1))

    # --- unsupported / overflow / big-client flags ---------------------------
    # cold kinds (Json/Embed/Format/Type) structure-decode here and take
    # their payload refs from the pack-time V1-form sidecar; only Doc
    # content (subdoc lifecycle is host-level on BOTH lanes — decode_
    # kernel.py routes it to ST_ERR too) and weak/unknown type tags still
    # flag the lane
    cold_mask = valid_blk & (
        is_json_content
        | is_embed_content
        | is_format_content
        | (is_type_content & ~type_weak_or_unknown)
    )
    unsupported = (
        jnp.any(
            valid_blk
            & (is_doc_content | type_weak_or_unknown),
            axis=1,
        )
        | jnp.any(key_too_long, axis=1)
        | deep_any
    )
    if sidecar is None:
        # no pack-time sidecar: cold payload bytes are unaddressable
        unsupported = unsupported | jnp.any(cold_mask, axis=1)
    consumption_ovf = (
        (g(c_base, jnp.full((S, 1), NB - 1, I32))[:, 0] + 3 > NCLI)
        | (total_blocks > NB)
    )
    # truncated column buffers: the info bytes imply consumption counts
    # that each expansion must actually have produced (V1 parity: such
    # wire flags FLAG_MALFORMED and takes the host lane)
    vb = valid_blk.astype(I32)
    need_cli = jnp.minimum(nc, SEC) + jnp.sum(c_cnt * vb, axis=1)
    need_lc = jnp.sum(l_cnt * vb, axis=1)
    need_rc = jnp.sum(has_r.astype(I32) * vb, axis=1)
    need_len = jnp.sum(n_cnt * vb, axis=1)
    need_str = jnp.sum(s_cnt * vb, axis=1)
    need_pi = jnp.sum(cant_copy.astype(I32) * vb, axis=1)
    need_tr = jnp.sum(is_type_content.astype(I32) * vb, axis=1)
    truncated = (
        (need_cli > cli_n)
        | (need_lc > lc_n)
        | (need_rc > rc_n)
        | (need_len > len_n)
        | (need_str > str_n)
        | (need_pi > pi_n)
        | (need_tr > tr_n)
    )
    # string demand beyond the expansion cap (Json-heavy blocks) would
    # silently clip offsets — route to the host instead
    str_cap_ovf = need_str > NS

    # --- delete set ----------------------------------------------------------
    d0 = 1 + 2 * jnp.minimum(nc, SEC) + _skips_upto(total_blocks)
    ds_n, bad_v3 = vat(d0[:, None], (lens > 0)[:, None] & ~frame_bad[:, None])
    ds_n = ds_n[:, 0]
    iota_r = jnp.arange(R, dtype=I32)[None, :]

    dels = dict(
        client=jnp.zeros((S, R), I32),
        start=jnp.zeros((S, R), I32),
        end=jnp.zeros((S, R), I32),
        valid=jnp.zeros((S, R), bool),
    )

    def ds_step(k, carry):
        p, out_base, dels, bad, ovf = carry
        active = k < ds_n
        cli, b1 = vat_id(p[:, None], active[:, None])
        nr, b2 = vat(p[:, None] + 1, active[:, None])
        cli, nr = cli[:, 0], nr[:, 0]
        in_sec = active[:, None] & (iota_r < nr[:, None])
        dv, b3 = vat(p[:, None] + 2 + 2 * iota_r, in_sec)
        lv, b4 = vat(p[:, None] + 3 + 2 * iota_r, in_sec)
        lv = lv + 1  # write_ds_len stores length - 1
        # ds_curr_val accumulates diffs and lengths within the section
        dvm = jnp.where(in_sec, dv, 0)
        lvm = jnp.where(in_sec, lv, 0)
        clocks = jnp.cumsum(dvm, axis=1) + _cumsum_excl(lvm)
        # scatter range m of this section to output slot out_base + m
        tgt = out_base[:, None] + iota_r
        ohm = (iota_r[:, :, None] == tgt[:, None, :]) & in_sec[:, None, :]
        hit = jnp.any(ohm, axis=2)  # [S, R_out]

        def put(cur, val):
            return jnp.where(
                hit, jnp.einsum("som,sm->so", ohm.astype(I32), val), cur
            )

        dels = dict(
            client=put(dels["client"], jnp.broadcast_to(cli[:, None], (S, R))),
            start=put(dels["start"], clocks),
            end=put(dels["end"], clocks + lvm),
            valid=dels["valid"] | hit,
        )
        ovf = ovf | (active & (out_base + nr > R))
        bad = bad | b1 | b2 | b3 | b4
        p = jnp.where(active, p + 2 + 2 * nr, p)
        out_base = jnp.where(active, jnp.clip(out_base + nr, 0, R), out_base)
        return p, out_base, dels, bad, ovf

    p0 = d0 + 1
    _, _, dels, ds_bad, ds_ovf = jax.lax.fori_loop(
        0,
        DSEC,
        ds_step,
        (p0, jnp.zeros((S,), I32), dels, jnp.zeros((S,), bool), jnp.zeros((S,), bool)),
    )
    ds_sec_ovf = ds_n > DSEC

    # --- row emission (compact out the Skip blocks) --------------------------
    emit = valid_blk & ~is_skip & (blk_len > 0)
    emit_idx = _cumsum_excl(emit.astype(I32))
    row_ovf = jnp.any(emit & (emit_idx >= U), axis=1)
    iota_u = jnp.arange(U, dtype=I32)[None, :]
    oh = (
        (iota_u[:, None, :] == emit_idx[:, :, None])
        & emit[:, :, None]
        & (emit_idx < U)[:, :, None]
    )  # [S, NB, U]

    def scatter(vec, fill):
        out = jnp.einsum("sbu,sb->su", oh.astype(I32), vec)
        hit = jnp.any(oh, axis=1)
        return jnp.where(hit, out, fill)

    row_ids = jnp.arange(S, dtype=I32)[:, None]
    c_start = walker_out["c_start"]
    # content refs: strings point into the string blob; Any values point at
    # their FIRST value byte (count-less — the row length is the count; the
    # reader must be in V2/count-less mode, see RawPayloadView(v2_any=...));
    # Binary and Move spans are byte-identical to their V1 wire forms
    has_span = is_any_content | is_bin_content | is_move_content
    # cold kinds: refs point at the pack-time V1-form sidecar spans,
    # matched by cold-block rank in wire block order
    side_bad = jnp.zeros((S,), bool)
    ref_cold = jnp.full((S, NB), -1, I32)
    if sidecar is not None:
        side_j = jnp.asarray(sidecar, dtype=I32)
        NC2 = side_j.shape[1]
        cold_rank = _cumsum_excl(cold_mask.astype(I32))
        cold_off = jnp.take_along_axis(
            side_j, jnp.clip(cold_rank, 0, max(NC2 - 1, 0)), axis=1
        )
        side_bad = jnp.any(
            cold_mask & ((cold_rank >= NC2) | (cold_off < 0)), axis=1
        )
        ref_cold = row_ids * L + cold_off
    ref_col = jnp.where(
        is_str_content,
        row_ids * L + content_start,
        jnp.where(
            has_span,
            row_ids * L + c_start,
            jnp.where(cold_mask, ref_cold, -1),
        ),
    )
    mvf = walker_out["mvf"]
    mv_collapsed = (mvf & 1) != 0
    msa_col = jnp.where((mvf & 2) != 0, 0, -1)
    mea_col = jnp.where((mvf & 4) != 0, 0, -1)
    mec_raw = jnp.where(mv_collapsed, walker_out["msc"], walker_out["mec"])
    mek_raw = jnp.where(mv_collapsed, walker_out["msk"], walker_out["mek"])
    mv_on = is_move_content & valid_blk
    rows = dict(
        client=scatter(jnp.broadcast_to(sec_client, (S, NB)), 0),
        clock=scatter(clock, 0),
        length=scatter(blk_len, 0),
        oc=scatter(oc, -1),
        ok=scatter(ok, 0),
        rc=scatter(rc, -1),
        rk=scatter(rk, 0),
        kind=scatter(jnp.where(is_gc, BLOCK_GC, kind4), 0),
        ref=scatter(ref_col, -1),
        ptag=scatter(ptag, 0),
        pc=scatter(pc, -1),
        pk=scatter(pk, 0),
        keyh=scatter(keyh, -1),
        rooth=scatter(rooth, -1),
        msc=scatter(jnp.where(mv_on, walker_out["msc"], -1), -1),
        msk=scatter(jnp.where(mv_on, walker_out["msk"], 0), 0),
        msa=scatter(jnp.where(mv_on, msa_col, 0), 0),
        mec=scatter(jnp.where(mv_on, mec_raw, -1), -1),
        mek=scatter(jnp.where(mv_on, mek_raw, 0), 0),
        mea=scatter(jnp.where(mv_on, mea_col, 0), 0),
        mprio=scatter(jnp.where(mv_on, mvf >> 6, -1), -1),
        valid=jnp.any(oh, axis=1),
    )

    malformed = (
        malformed
        | frame_bad
        | bad_v1
        | bad_v2
        | bad_v3
        | ds_bad
        | truncated
        | walk_bad
        | side_bad
        | (valid_blk & (blk_len < 0)).any(axis=1)
    )
    flags = (
        flags
        | jnp.where(malformed, FLAG_MALFORMED, 0)
        | jnp.where(unsupported, FLAG_UNSUPPORTED, 0)
        | jnp.where(
            blk_ovf | row_ovf | consumption_ovf | ds_ovf | ds_sec_ovf
            | str_cap_ovf,
            FLAG_OVERFLOW,
            0,
        )
    )

    return _resolve_and_pack(
        rows, dels, flags, client_table, key_table, client_hash_table,
        primary_root_hash,
    )


# --- bounded resident-program wrapper (VERDICT r4 #7) -----------------------
# Same policy as the V1 lane: the columnar decode compiles as ONE
# per-function-evictable program under the progbudget registry.

_decode_updates_v2_impl = decode_updates_v2
_decode_updates_v2_jit = partial(
    jax.jit, static_argnames=("max_rows", "max_dels", "max_sections")
)(_decode_updates_v2_impl)


def decode_updates_v2(
    buf,
    lens,
    spans,
    max_rows,
    max_dels,
    max_sections=None,
    client_table=None,
    key_table=None,
    client_hash_table=None,
    primary_root_hash=None,
    sidecar=None,
):
    from ytpu.utils.progbudget import tick

    tick()
    return _decode_updates_v2_jit(
        jnp.asarray(buf),
        jnp.asarray(lens),
        jnp.asarray(spans),
        max_rows=max_rows,
        max_dels=max_dels,
        max_sections=max_sections,
        client_table=client_table,
        key_table=key_table,
        client_hash_table=client_hash_table,
        primary_root_hash=primary_root_hash,
        sidecar=None if sidecar is None else jnp.asarray(sidecar),
    )


decode_updates_v2.__doc__ = _decode_updates_v2_impl.__doc__


def _register_programs():
    from ytpu.utils import progbudget

    progbudget.register("decode_updates_v2", _decode_updates_v2_jit)


_register_programs()
