// ytpu/native/engine.cpp — scalar single-doc YATA engine in C++.
//
// The native-speed performance baseline (VERDICT r1 #3, extended r5 #3):
// a from-scratch C++ implementation of the YATA integration algorithm
// over the columnar decode (lib0_codec.cpp), semantics matching the
// reference's hot path — integrate (yrs/src/block.rs:482-769, conflict
// scan :537-602), apply_delete (yrs/src/transaction.rs:472-575), map
// key chains with last-write-wins shadowing (block.rs:614-659), nested
// branch parents (block.rs:1287-1343 repair) — for every content kind
// the B-series benches exercise: String / Deleted / Any / JSON / Binary
// / Embed / Format / Type (nested branches: YArray, YMap, YText,
// XmlElement, XmlFragment). It is NOT a port: storage is an index-based
// arena (no pointers), per-client lookup is an ordered clock map, and
// each parent (root or nested branch) owns an intrusive doubly-linked
// sequence over item indices plus a key->live-entry map.
//
// Scope guard: updates containing features outside this engine's scope
// (GC ranges, move ranges, sub-documents) set `unsupported` and the
// Python wrapper falls back to the host oracle.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <string>
#include <unordered_map>
#include <vector>

// columnar V1 decoder (lib0_codec.cpp, linked into the same .so)
extern "C" {
void* ytpu_decode_update_v1(const uint8_t* data, size_t len);
int ytpu_columns_error(void* h);
size_t ytpu_columns_n_blocks(void* h);
size_t ytpu_columns_n_dels(void* h);
const int64_t* ytpu_col_client(void* h);
const int64_t* ytpu_col_clock(void* h);
const int64_t* ytpu_col_length(void* h);
const int64_t* ytpu_col_kind(void* h);
const int64_t* ytpu_col_origin_client(void* h);
const int64_t* ytpu_col_origin_clock(void* h);
const int64_t* ytpu_col_ror_client(void* h);
const int64_t* ytpu_col_ror_clock(void* h);
const int64_t* ytpu_col_parent_kind(void* h);
const int64_t* ytpu_col_parent_name_start(void* h);
const int64_t* ytpu_col_parent_name_len(void* h);
const int64_t* ytpu_col_parent_id_client(void* h);
const int64_t* ytpu_col_parent_id_clock(void* h);
const int64_t* ytpu_col_parent_sub_start(void* h);
const int64_t* ytpu_col_parent_sub_len(void* h);
const int64_t* ytpu_col_content_start(void* h);
const int64_t* ytpu_col_content_len_bytes(void* h);
const int64_t* ytpu_col_del_client(void* h);
const int64_t* ytpu_col_del_start(void* h);
const int64_t* ytpu_col_del_end(void* h);
void ytpu_columns_free(void* h);
}

namespace {

constexpr int64_t KIND_GC = 0;
constexpr int64_t KIND_DELETED = 1;
constexpr int64_t KIND_JSON = 2;
constexpr int64_t KIND_BINARY = 3;
constexpr int64_t KIND_STRING = 4;
constexpr int64_t KIND_EMBED = 5;
constexpr int64_t KIND_FORMAT = 6;
constexpr int64_t KIND_TYPE = 7;
constexpr int64_t KIND_ANY = 8;
constexpr int64_t KIND_DOC = 9;
constexpr int64_t KIND_SKIP = 10;
constexpr int64_t KIND_MOVE = 11;

// shared-type tags inside ContentType payloads (branch type refs)
constexpr uint8_t TYPE_ARRAY = 0;
constexpr uint8_t TYPE_MAP = 1;
constexpr uint8_t TYPE_TEXT = 2;
constexpr uint8_t TYPE_XML_ELEMENT = 3;
constexpr uint8_t TYPE_XML_FRAGMENT = 4;
constexpr uint8_t TYPE_XML_HOOK = 5;
constexpr uint8_t TYPE_XML_TEXT = 6;

// Byte offset of the k-th UTF-16 unit within s[0..n). If the cut lands
// inside a surrogate pair (astral char = 4-byte UTF-8 = 2 units), sets
// *midpair and returns the char's start — the caller substitutes U+FFFD
// halves, matching the host's split_str_utf16 (and the workaround
// documented at reference block.rs:1852-1860).
size_t utf16_to_byte(const uint8_t* s, size_t n, int64_t units,
                     bool* midpair = nullptr) {
  size_t i = 0;
  int64_t u = 0;
  if (midpair) *midpair = false;
  while (i < n && u < units) {
    uint8_t b = s[i];
    if (b < 0x80) {
      i += 1;
      u += 1;
    } else if (b < 0xE0) {
      i += 2;
      u += 1;
    } else if (b < 0xF0) {
      i += 3;
      u += 1;
    } else {
      if (u + 2 > units) {  // cut splits this pair
        if (midpair) *midpair = true;
        return i;
      }
      i += 4;
      u += 2;  // surrogate pair
    }
  }
  return i;
}

constexpr const char* kReplacement = "\xEF\xBF\xBD";  // U+FFFD

// ---- lib0 Any byte-span scanning (element boundaries for splits) ----

bool read_var_uint(const uint8_t* p, size_t n, size_t& pos, uint64_t* out) {
  uint64_t num = 0;
  int shift = 0;
  while (pos < n) {
    uint8_t b = p[pos++];
    num |= (uint64_t)(b & 0x7F) << shift;
    shift += 7;
    if (b < 0x80) {
      if (out) *out = num;
      return true;
    }
    if (shift >= 70) return false;  // 10-byte cap: shift 70 would be UB
  }
  return false;
}

// overflow-safe "pos + k <= n" for attacker-controlled k
bool fits(size_t pos, uint64_t k, size_t n) {
  return pos <= n && k <= (uint64_t)(n - pos);
}

bool skip_var_int(const uint8_t* p, size_t n, size_t& pos) {
  if (pos >= n) return false;
  uint8_t b = p[pos++];
  if ((b & 0x80) == 0) return true;
  while (pos < n) {
    b = p[pos++];
    if (b < 0x80) return true;
  }
  return false;
}

// skip one Any value (parity: any.rs:37-83)
bool skip_any_bytes(const uint8_t* p, size_t n, size_t& pos) {
  if (pos >= n) return false;
  uint8_t tag = p[pos++];
  switch (tag) {
    case 127:  // undefined
    case 126:  // null
    case 121:  // false
    case 120:  // true
      return true;
    case 125:  // integer (signed varint)
      return skip_var_int(p, n, pos);
    case 124:  // f32
      pos += 4;
      return pos <= n;
    case 123:  // f64
    case 122:  // bigint
      pos += 8;
      return pos <= n;
    case 119:
    case 116: {  // string / buffer
      uint64_t k = 0;
      if (!read_var_uint(p, n, pos, &k)) return false;
      if (!fits(pos, k, n)) return false;
      pos += (size_t)k;
      return true;
    }
    case 118: {  // map
      uint64_t cnt = 0;
      if (!read_var_uint(p, n, pos, &cnt)) return false;
      for (uint64_t i = 0; i < cnt; i++) {
        uint64_t k = 0;
        if (!read_var_uint(p, n, pos, &k)) return false;
        if (!fits(pos, k, n)) return false;
        pos += (size_t)k;
        if (!skip_any_bytes(p, n, pos)) return false;
      }
      return true;
    }
    case 117: {  // array
      uint64_t cnt = 0;
      if (!read_var_uint(p, n, pos, &cnt)) return false;
      for (uint64_t i = 0; i < cnt; i++)
        if (!skip_any_bytes(p, n, pos)) return false;
      return true;
    }
    default:
      return false;
  }
}

// byte offset after `k` Any elements
bool any_elems_to_byte(const uint8_t* p, size_t n, int64_t k, size_t* cut) {
  size_t pos = 0;
  for (int64_t i = 0; i < k; i++)
    if (!skip_any_bytes(p, n, pos)) return false;
  *cut = pos;
  return true;
}

// byte offset after `k` length-prefixed strings (ContentJSON elements)
bool json_elems_to_byte(const uint8_t* p, size_t n, int64_t k, size_t* cut) {
  size_t pos = 0;
  for (int64_t i = 0; i < k; i++) {
    uint64_t len = 0;
    if (!read_var_uint(p, n, pos, &len)) return false;
    if (!fits(pos, len, n)) return false;
    pos += (size_t)len;
  }
  *cut = pos;
  return true;
}

// ---- JSON emission (visible-state oracle output) ----

void json_escape(const uint8_t* p, size_t n, std::string& out) {
  out.push_back('"');
  for (size_t i = 0; i < n; i++) {
    uint8_t c = p[i];
    switch (c) {
      case '"':
        out += "\\\"";
        break;
      case '\\':
        out += "\\\\";
        break;
      case '\n':
        out += "\\n";
        break;
      case '\r':
        out += "\\r";
        break;
      case '\t':
        out += "\\t";
        break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out.push_back((char)c);
        }
    }
  }
  out.push_back('"');
}

bool read_f_be(const uint8_t* p, size_t n, size_t& pos, int width,
               double* out) {
  if (pos + (size_t)width > n) return false;
  if (width == 4) {
    uint32_t bits = 0;
    for (int i = 0; i < 4; i++) bits = (bits << 8) | p[pos++];
    float f;
    memcpy(&f, &bits, 4);
    *out = (double)f;
  } else {
    uint64_t bits = 0;
    for (int i = 0; i < 8; i++) bits = (bits << 8) | p[pos++];
    memcpy(out, &bits, 8);
  }
  return true;
}

// emit one Any value as JSON; returns false on error/unsupported
bool any_json(const uint8_t* p, size_t n, size_t& pos, std::string& out) {
  if (pos >= n) return false;
  uint8_t tag = p[pos++];
  switch (tag) {
    case 127:  // undefined (host any_to_json: null)
    case 126:
      out += "null";
      return true;
    case 121:
      out += "false";
      return true;
    case 120:
      out += "true";
      return true;
    case 125: {  // signed varint
      if (pos >= n) return false;
      uint8_t b = p[pos++];
      bool neg = (b & 0x40) != 0;
      uint64_t num = b & 0x3F;
      int shift = 6;
      while (b & 0x80) {
        if (pos >= n || shift >= 64) return false;
        b = p[pos++];
        num |= (uint64_t)(b & 0x7F) << shift;
        shift += 7;
      }
      char buf[32];
      snprintf(buf, sizeof(buf), "%s%llu", neg ? "-" : "",
               (unsigned long long)num);
      out += buf;
      return true;
    }
    case 124:
    case 123: {  // f32 / f64 (big-endian)
      double v = 0;
      if (!read_f_be(p, n, pos, tag == 124 ? 4 : 8, &v)) return false;
      if (!(v == v) || v - v != 0)  // NaN / ±inf: not valid JSON
        return false;
      char buf[40];
      snprintf(buf, sizeof(buf), "%.17g", v);
      out += buf;
      return true;
    }
    case 122: {  // bigint i64 big-endian
      if (pos + 8 > n) return false;
      uint64_t bits = 0;
      for (int i = 0; i < 8; i++) bits = (bits << 8) | p[pos++];
      char buf[32];
      snprintf(buf, sizeof(buf), "%lld", (long long)(int64_t)bits);
      out += buf;
      return true;
    }
    case 119: {  // string
      uint64_t k = 0;
      if (!read_var_uint(p, n, pos, &k)) return false;
      if (!fits(pos, k, n)) return false;
      json_escape(p + pos, (size_t)k, out);
      pos += (size_t)k;
      return true;
    }
    case 118: {  // map
      uint64_t cnt = 0;
      if (!read_var_uint(p, n, pos, &cnt)) return false;
      out.push_back('{');
      for (uint64_t i = 0; i < cnt; i++) {
        if (i) out.push_back(',');
        uint64_t k = 0;
        if (!read_var_uint(p, n, pos, &k)) return false;
        if (!fits(pos, k, n)) return false;
        json_escape(p + pos, (size_t)k, out);
        pos += (size_t)k;
        out.push_back(':');
        if (!any_json(p, n, pos, out)) return false;
      }
      out.push_back('}');
      return true;
    }
    case 117: {  // array
      uint64_t cnt = 0;
      if (!read_var_uint(p, n, pos, &cnt)) return false;
      out.push_back('[');
      for (uint64_t i = 0; i < cnt; i++) {
        if (i) out.push_back(',');
        if (!any_json(p, n, pos, out)) return false;
      }
      out.push_back(']');
      return true;
    }
    default:
      return false;  // binary / unknown: no JSON projection
  }
}

struct Item {
  uint64_t client = 0;
  uint64_t clock = 0;
  int64_t len = 0;  // CRDT length (UTF-16 units / element count)
  int64_t oc = -1;  // origin (client, clock); -1 client = none
  int64_t ok = 0;
  int64_t rc = -1;  // right origin
  int64_t rk = 0;
  int32_t left = -1;   // sequence neighbors (indices into items)
  int32_t right = -1;
  int32_t parent = -1;  // parents index; -2 = inherit from neighbors
  int32_t sub = -1;     // interned map key; -1 = sequence item
  int32_t branch = -1;  // parents index when ContentType
  uint8_t kind = (uint8_t)KIND_STRING;
  bool deleted = false;
  bool countable = true;
  bool detached = false;  // integrated without a live parent (GC-like)
  size_t c_off = 0;  // content bytes in the arena
  size_t c_len = 0;  // (strings: UTF-8; Any/JSON: element bytes, no
                     // count prefix; others: raw wire payload span)
};

// One sequence scope: a root type or a nested branch (reference Branch,
// types/mod.rs). `entries` maps interned keys to the LIVE (right-most)
// chain entry, mirroring parent.map in block.rs:614-659.
struct ParentSeq {
  int32_t head = -1;
  int32_t item = -1;  // backing ContentType item (-1 for roots)
  std::string name;   // root name (empty for nested branches)
  std::unordered_map<int32_t, int32_t> entries;
};

// V1 wire writer (lib0 varint framing)
struct Wr {
  std::string buf;
  void u8(uint8_t b) { buf.push_back((char)b); }
  void vu(uint64_t v) {
    while (v >= 0x80) {
      buf.push_back((char)(0x80 | (v & 0x7F)));
      v >>= 7;
    }
    buf.push_back((char)v);
  }
  void bytes(const char* p, size_t n) { buf.append(p, n); }
  void str(const std::string& s) {
    vu(s.size());
    buf.append(s);
  }
};

struct Engine {
  std::vector<Item> items;
  std::vector<ParentSeq> parents;
  std::string arena;  // content bytes
  std::unordered_map<std::string, int32_t> roots;  // root name -> parent
  std::unordered_map<std::string, int32_t> key_ids;
  std::vector<std::string> key_names;
  // per-client: start clock -> item index, ordered (O(log n) find/split)
  std::unordered_map<uint64_t, std::map<uint64_t, int32_t>> by_client;
  std::unordered_map<uint64_t, uint64_t> sv;  // next expected clock
  bool unsupported = false;
  bool error = false;

  int32_t root_key(const std::string& name) {
    auto it = roots.find(name);
    if (it != roots.end()) return it->second;
    int32_t k = (int32_t)parents.size();
    ParentSeq ps;
    ps.name = name;
    parents.push_back(std::move(ps));
    roots.emplace(name, k);
    return k;
  }

  int32_t intern_key(const uint8_t* p, size_t n) {
    std::string s((const char*)p, n);
    auto it = key_ids.find(s);
    if (it != key_ids.end()) return it->second;
    int32_t k = (int32_t)key_names.size();
    key_names.push_back(s);
    key_ids.emplace(std::move(s), k);
    return k;
  }

  uint64_t cov(uint64_t client) const {
    auto it = sv.find(client);
    return it == sv.end() ? 0 : it->second;
  }

  // item whose span contains `clock`, or -1
  int32_t find(uint64_t client, uint64_t clock) {
    auto bc = by_client.find(client);
    if (bc == by_client.end() || bc->second.empty()) return -1;
    auto it = bc->second.upper_bound(clock);
    if (it == bc->second.begin()) return -1;
    --it;
    int32_t idx = it->second;
    const Item& b = items[idx];
    if (clock >= b.clock + (uint64_t)b.len) return -1;
    return idx;
  }

  // split `idx` at absolute clock `at` (strictly inside); returns the
  // right half's index. Mirrors ItemSlice materialization
  // (yrs/src/store.rs:284-331) on the flat store.
  int32_t split(int32_t idx, uint64_t at) {
    Item& b = items[idx];
    int64_t left_units = (int64_t)(at - b.clock);
    Item r;
    r.client = b.client;
    r.clock = at;
    r.len = b.len - left_units;
    r.oc = (int64_t)b.client;  // right half originates from the left half
    r.ok = (int64_t)(at - 1);
    r.rc = b.rc;
    r.rk = b.rk;
    r.parent = b.parent;
    r.sub = b.sub;
    r.kind = b.kind;
    r.deleted = b.deleted;
    r.countable = b.countable;
    r.detached = b.detached;
    if (b.kind == KIND_STRING) {
      const uint8_t* s = (const uint8_t*)arena.data() + b.c_off;
      bool mid = false;
      size_t cut = utf16_to_byte(s, b.c_len, left_units, &mid);
      if (!mid) {
        r.c_off = b.c_off + cut;
        r.c_len = b.c_len - cut;
        b.c_len = cut;
      } else {
        // surrogate-pair split: each half gets a U+FFFD stand-in (1 unit
        // each, keeping content length == clock length on both sides).
        // Spans can't express the substitution in place, so both halves
        // move to fresh arena regions (rare; bounded by astral splits).
        std::string lbytes(arena, b.c_off, cut);
        std::string rbytes(arena, b.c_off + cut + 4, b.c_len - cut - 4);
        size_t loff = arena.size();
        arena.append(lbytes);
        arena.append(kReplacement);
        size_t roff = arena.size();
        arena.append(kReplacement);
        arena.append(rbytes);
        b.c_off = loff;
        b.c_len = cut + 3;
        r.c_off = roff;
        r.c_len = 3 + rbytes.size();
      }
    } else if (b.kind == KIND_ANY || b.kind == KIND_JSON) {
      const uint8_t* s = (const uint8_t*)arena.data() + b.c_off;
      size_t cut = 0;
      bool ok2 = (b.kind == KIND_ANY)
                     ? any_elems_to_byte(s, b.c_len, left_units, &cut)
                     : json_elems_to_byte(s, b.c_len, left_units, &cut);
      if (!ok2) {
        error = true;
        cut = b.c_len;
      }
      r.c_off = b.c_off + cut;
      r.c_len = b.c_len - cut;
      b.c_len = cut;
    }
    b.len = left_units;
    int32_t ridx = (int32_t)items.size();
    // sequence splice: b <-> r <-> old right
    r.left = idx;
    r.right = b.right;
    items.push_back(r);
    Item& b2 = items[idx];  // re-borrow (push_back may reallocate)
    if (b2.right >= 0) items[b2.right].left = ridx;
    b2.right = ridx;
    by_client[r.client][at] = ridx;
    // the live map entry moves to the right half (it ends the chain)
    if (r.sub >= 0 && r.parent >= 0 && r.right < 0) {
      auto f = parents[r.parent].entries.find(r.sub);
      if (f != parents[r.parent].entries.end() && f->second == idx)
        f->second = ridx;
    }
    return ridx;
  }

  // left neighbor for (client, clock): the item ending exactly at clock,
  // split if needed (get_item_clean_end, yrs/src/block_store.rs:402)
  int32_t clean_end(uint64_t client, uint64_t clock) {
    int32_t idx = find(client, clock);
    if (idx < 0) return -1;
    const Item& b = items[idx];
    if (clock + 1 < b.clock + (uint64_t)b.len) split(idx, clock + 1);
    return idx;
  }

  // item starting exactly at clock, split if needed (get_item_clean_start)
  int32_t clean_start(uint64_t client, uint64_t clock) {
    int32_t idx = find(client, clock);
    if (idx < 0) return -1;
    if (items[idx].clock < clock) return split(idx, clock);
    return idx;
  }

  // first entry of the map-key chain that ends at `live`
  int32_t chain_start(int32_t live) {
    while (live >= 0 && items[live].left >= 0) live = items[live].left;
    return live;
  }

  // YATA conflict resolution (reference: block.rs:482-769; the conflict
  // scan :537-602 with the client-id tie-break :571-580; map binding and
  // last-write-wins shadowing :614-659).
  void integrate(Item it) {
    // repair: resolve origin → left neighbor (clean end) and right origin
    // → scan bound (clean start), independently (block.rs:1287-1343)
    int32_t left = -1, right = -1;
    if (it.oc >= 0) {
      left = clean_end((uint64_t)it.oc, (uint64_t)it.ok);
      if (left < 0) {
        error = true;  // missing dependency (caller checked coverage)
        return;
      }
      if (items[left].detached) {
        unsupported = true;
        return;
      }
    }
    if (it.rc >= 0) {
      right = clean_start((uint64_t)it.rc, (uint64_t)it.rk);
      if (right < 0) {
        error = true;
        return;
      }
      if (items[right].detached) {
        unsupported = true;
        return;
      }
    }

    // parent inheritance from resolved neighbors (store.rs repair /
    // block.rs:604-612 first half)
    if (it.parent == -2) {
      if (left >= 0) {
        it.parent = items[left].parent;
        it.sub = items[left].sub;
      } else if (right >= 0) {
        it.parent = items[right].parent;
        it.sub = items[right].sub;
      } else {
        unsupported = true;  // no anchor to inherit from
        return;
      }
    }
    if (it.parent < 0) {
      // unresolvable parent (deleted nested type): the reference turns
      // the block into a GC range. Register coverage, keep no sequence
      // position; origins resolving into it escalate to the host.
      it.detached = true;
      it.deleted = true;
      int32_t idx = (int32_t)items.size();
      items.push_back(it);
      by_client[it.client][it.clock] = idx;
      uint64_t end = it.clock + (uint64_t)it.len;
      if (end > cov(it.client)) sv[it.client] = end;
      return;
    }
    const int32_t pidx = it.parent;

    // conflict scan: walk candidates in (left, right_origin_bound)
    int32_t o;
    if (left >= 0) {
      o = items[left].right;
    } else if (it.sub >= 0) {
      auto f = parents[pidx].entries.find(it.sub);
      o = chain_start(f == parents[pidx].entries.end() ? -1 : f->second);
    } else {
      o = parents[pidx].head;
    }
    if (o >= 0 && o != right) {
      // item-index sets; small in practice (concurrent-insert width)
      std::vector<int32_t> conflicting, before_origin;
      auto contains = [](const std::vector<int32_t>& v, int32_t x) {
        return std::find(v.begin(), v.end(), x) != v.end();
      };
      while (o >= 0 && o != right) {
        before_origin.push_back(o);
        conflicting.push_back(o);
        const Item& ob = items[o];
        bool same_origin = (ob.oc == it.oc) && (ob.oc < 0 || ob.ok == it.ok);
        if (same_origin) {
          if (ob.client < it.client) {
            left = o;
            conflicting.clear();
          } else if (ob.rc == it.rc && (ob.rc < 0 || ob.rk == it.rk)) {
            break;  // same origin + same right origin: order settled
          }
        } else {
          int32_t oo = (ob.oc >= 0)
                           ? find((uint64_t)ob.oc, (uint64_t)ob.ok)
                           : -1;
          if (ob.oc >= 0 && oo >= 0 && contains(before_origin, oo)) {
            if (!contains(conflicting, oo)) {
              left = o;
              conflicting.clear();
            }
          } else {
            break;
          }
        }
        o = ob.right;
      }
    }

    // inherit parent_sub from the settled left neighbor (block.rs:604-612)
    if (it.sub < 0 && left >= 0) {
      if (items[left].sub >= 0)
        it.sub = items[left].sub;
      else if (right >= 0 && items[right].sub >= 0)
        it.sub = items[right].sub;
    }

    // splice into the sequence / key chain (block.rs:614-659)
    int32_t idx = (int32_t)items.size();
    it.left = left;
    if (left >= 0) {
      it.right = items[left].right;
    } else if (it.sub >= 0) {
      auto f = parents[pidx].entries.find(it.sub);
      it.right = chain_start(f == parents[pidx].entries.end() ? -1 : f->second);
    } else {
      it.right = parents[pidx].head;
      parents[pidx].head = idx;
    }
    items.push_back(it);
    Item& nb = items[idx];
    if (nb.left >= 0) items[nb.left].right = idx;
    if (nb.right >= 0) {
      items[nb.right].left = idx;
    } else if (nb.sub >= 0) {
      // became the live value of a map entry; shadow the previous chain
      parents[pidx].entries[nb.sub] = idx;
      if (nb.left >= 0) items[nb.left].deleted = true;
    }
    by_client[nb.client][nb.clock] = idx;
    uint64_t end = nb.clock + (uint64_t)nb.len;
    if (end > cov(nb.client)) sv[nb.client] = end;

    // content side effects (block.rs:704-741)
    if (nb.kind == KIND_DELETED) nb.deleted = true;
    if (nb.kind == KIND_TYPE) {
      nb.branch = (int32_t)parents.size();
      ParentSeq br;
      br.item = idx;
      parents.push_back(br);
    }
    // late arrivals behind a newer map value, or a deleted parent, are
    // integrated directly as tombstones (integrate_block's return True)
    Item& nb2 = items[idx];  // parents.push_back does not move items
    bool parent_deleted =
        parents[pidx].item >= 0 && items[parents[pidx].item].deleted;
    if (parent_deleted || (nb2.sub >= 0 && nb2.right >= 0))
      nb2.deleted = true;
  }

  // tombstone [start, end) of `client` (apply_delete semantics:
  // transaction.rs:472-575 — split boundaries, mark deleted)
  void apply_delete(uint64_t client, uint64_t start, uint64_t end) {
    uint64_t covered = cov(client);
    if (end > covered) end = covered;  // clip (host lane stashes the rest)
    uint64_t c = start;
    while (c < end) {
      int32_t idx = find(client, c);
      if (idx < 0) {
        // gap (already GC'd or range hole): advance to next block start
        auto& m = by_client[client];
        auto it = m.upper_bound(c);
        if (it == m.end() || it->first >= end) return;
        c = it->first;
        continue;
      }
      if (items[idx].clock < c) idx = split(idx, c);
      Item& b = items[idx];
      uint64_t bend = b.clock + (uint64_t)b.len;
      if (bend > end) {
        split(idx, end);
      }
      items[idx].deleted = true;
      c = items[idx].clock + (uint64_t)items[idx].len;
    }
  }

  void apply(const uint8_t* data, size_t n) {
    void* h = ytpu_decode_update_v1(data, n);
    size_t nb = ytpu_columns_n_blocks(h);
    size_t nd = ytpu_columns_n_dels(h);
    if (ytpu_columns_error(h)) error = true;
    const int64_t* client = ytpu_col_client(h);
    const int64_t* clock = ytpu_col_clock(h);
    const int64_t* length = ytpu_col_length(h);
    const int64_t* kind = ytpu_col_kind(h);
    const int64_t* oc = ytpu_col_origin_client(h);
    const int64_t* ok = ytpu_col_origin_clock(h);
    const int64_t* rc = ytpu_col_ror_client(h);
    const int64_t* rk = ytpu_col_ror_clock(h);
    const int64_t* pk = ytpu_col_parent_kind(h);
    const int64_t* pns = ytpu_col_parent_name_start(h);
    const int64_t* pnl = ytpu_col_parent_name_len(h);
    const int64_t* pic = ytpu_col_parent_id_client(h);
    const int64_t* pik = ytpu_col_parent_id_clock(h);
    const int64_t* pss = ytpu_col_parent_sub_start(h);
    const int64_t* psl = ytpu_col_parent_sub_len(h);
    const int64_t* cs = ytpu_col_content_start(h);
    const int64_t* cl = ytpu_col_content_len_bytes(h);
    const int64_t* dc = ytpu_col_del_client(h);
    const int64_t* ds = ytpu_col_del_start(h);
    const int64_t* de = ytpu_col_del_end(h);
    // Dependency-driven ordering: the host Update driver integrates
    // carriers as their origins/parents become available (update.rs
    // stack machine). Here rows not yet ready are deferred and retried
    // in passes; a pass with no progress means a genuinely missing
    // dependency (the host lane stashes those as pending — this engine
    // reports an error and the caller falls back to the oracle).
    std::vector<size_t> work(nb), next;
    for (size_t i = 0; i < nb; i++) {
      work[i] = i;
      // register roots in wire order regardless of integration order so
      // parents[0] (the `text()` default) is deterministic under deferral
      if (kind[i] != KIND_SKIP && kind[i] != KIND_GC && pk[i] == 1)
        root_key(std::string((const char*)data + pns[i], (size_t)pnl[i]));
    }
    bool progress = true;
    bool forward = true;
    while (!work.empty() && progress && !error && !unsupported) {
      progress = false;
      next.clear();
      // alternate scan direction between passes: a dependency chain laid
      // out against the scan order then settles in 2 passes, not O(n)
      if (!forward) std::reverse(work.begin(), work.end());
      forward = !forward;
      for (size_t wi = 0; wi < work.size() && !error && !unsupported;
           wi++) {
        size_t i = work[wi];
      if (kind[i] == KIND_SKIP) continue;
      if (kind[i] == KIND_GC || kind[i] == KIND_MOVE ||
          kind[i] == KIND_DOC) {
        // GC ranges are position-less (BlockRange); moves and subdocs
        // carry transaction machinery this engine does not model — fall
        // back to the host oracle for such streams.
        unsupported = true;
        break;
      }
      uint64_t cend = (uint64_t)clock[i] + (uint64_t)length[i];
      uint64_t have = cov((uint64_t)client[i]);
      if (cend <= have) {
        progress = true;
        continue;  // duplicate delivery
      }
      bool ready = (uint64_t)clock[i] <= have;
      if (ready && oc[i] >= 0 && ok[i] >= 0 &&
          (uint64_t)ok[i] >= cov((uint64_t)oc[i]))
        ready = false;
      if (ready && rc[i] >= 0 && rk[i] >= 0 &&
          (uint64_t)rk[i] >= cov((uint64_t)rc[i]))
        ready = false;
      if (ready && pk[i] == 2 &&
          (uint64_t)pik[i] >= cov((uint64_t)pic[i]))
        ready = false;
      if (!ready) {
        next.push_back(i);
        continue;
      }
      Item it;
      it.client = (uint64_t)client[i];
      it.clock = (uint64_t)clock[i];
      it.len = length[i];
      it.kind = (uint8_t)kind[i];
      it.oc = oc[i] >= 0 && ok[i] >= 0 ? oc[i] : -1;
      it.ok = ok[i];
      it.rc = rc[i] >= 0 && rk[i] >= 0 ? rc[i] : -1;
      it.rk = rk[i];
      it.countable =
          !(kind[i] == KIND_DELETED || kind[i] == KIND_FORMAT);
      // parent columns: 1 = root name, 2 = branch id, 3 = inherit
      if (pk[i] == 1) {
        it.parent =
            root_key(std::string((const char*)data + pns[i], (size_t)pnl[i]));
      } else if (pk[i] == 2) {
        int32_t tgt = find((uint64_t)pic[i], (uint64_t)pik[i]);
        if (tgt < 0) {
          error = true;  // parent not integrated yet (host lane stashes)
          break;
        }
        if (items[tgt].branch >= 0) {
          it.parent = items[tgt].branch;
        } else if (items[tgt].kind == KIND_DELETED) {
          it.parent = -1;  // reference: parent resolves to None → GC
        } else {
          error = true;  // defect: parent is not a shared type
          break;
        }
      } else {
        it.parent = -2;  // inherit from origin neighbors at integrate
      }
      if (pss[i] >= 0)
        it.sub = intern_key(data + pss[i], (size_t)psl[i]);
      int64_t offset = (int64_t)(have - it.clock);  // partial redelivery
      // content payload → arena
      const uint8_t* p = data + cs[i];
      size_t pn = (size_t)cl[i];
      if (kind[i] == KIND_STRING || kind[i] == KIND_ANY ||
          kind[i] == KIND_JSON) {
        // strip the count/byte-length prefix; keep element bytes so
        // splits can cut on element boundaries
        size_t vi = 0;
        if (!read_var_uint(p, pn, vi, nullptr)) {
          error = true;
          break;
        }
        it.c_off = arena.size();
        it.c_len = pn - vi;
        arena.append((const char*)p + vi, pn - vi);
      } else if (kind[i] != KIND_DELETED) {
        // Binary / Embed / Format / Type: raw payload span
        it.c_off = arena.size();
        it.c_len = pn;
        arena.append((const char*)p, pn);
      }
      if (offset > 0) {
        // drop the already-integrated prefix (integrate(txn, offset))
        it.clock += (uint64_t)offset;
        if (it.kind == KIND_STRING) {
          const uint8_t* s = (const uint8_t*)arena.data() + it.c_off;
          bool mid = false;
          size_t cut = utf16_to_byte(s, it.c_len, offset, &mid);
          if (!mid) {
            it.c_off += cut;
            it.c_len -= cut;
          } else {
            std::string rest(arena, it.c_off + cut + 4, it.c_len - cut - 4);
            it.c_off = arena.size();
            arena.append(kReplacement);
            arena.append(rest);
            it.c_len = 3 + rest.size();
          }
        } else if (it.kind == KIND_ANY || it.kind == KIND_JSON) {
          const uint8_t* s = (const uint8_t*)arena.data() + it.c_off;
          size_t cut = 0;
          bool ok2 = (it.kind == KIND_ANY)
                         ? any_elems_to_byte(s, it.c_len, offset, &cut)
                         : json_elems_to_byte(s, it.c_len, offset, &cut);
          if (!ok2) {
            error = true;
            break;
          }
          it.c_off += cut;
          it.c_len -= cut;
        } else if (it.kind != KIND_DELETED) {
          // length-1 content cannot be partially redelivered
          error = true;
          break;
        }
        it.len -= offset;
        it.oc = (int64_t)it.client;
        it.ok = (int64_t)(it.clock - 1);
      }
      integrate(it);
      progress = true;
      }
      work.swap(next);
    }
    if (!work.empty() && !error && !unsupported)
      error = true;  // missing dependency: host lane stashes as pending
    for (size_t i = 0; i < nd && !error && !unsupported; i++) {
      apply_delete((uint64_t)dc[i], (uint64_t)ds[i], (uint64_t)de[i]);
    }
    ytpu_columns_free(h);
  }

  std::string text_of(int32_t pidx) const {
    std::string out;
    if (pidx < 0) return out;
    for (int32_t i = parents[pidx].head; i >= 0; i = items[i].right) {
      const Item& b = items[i];
      if (!b.deleted && b.kind == KIND_STRING)
        out.append(arena, b.c_off, b.c_len);
    }
    return out;
  }

  std::string text() const { return text_of(parents.empty() ? -1 : 0); }

  // ---- visible-state JSON (validation oracle for benches/tests) ----
  // shapes: 0 = sequence (YArray / XmlFragment children), 1 = map,
  // 2 = type (infer from the backing ContentType payload)

  bool type_json(int32_t item_idx, std::string& out) const {
    const Item& b = items[item_idx];
    if (b.branch < 0) return false;
    const uint8_t* p = (const uint8_t*)arena.data() + b.c_off;
    size_t n = b.c_len;
    if (n < 1) return false;
    uint8_t tag = p[0];
    switch (tag) {
      case TYPE_ARRAY:
        return seq_json(b.branch, out);
      case TYPE_MAP:
        return map_json(b.branch, out);
      case TYPE_TEXT:
      case TYPE_XML_TEXT: {
        std::string t = text_of(b.branch);
        json_escape((const uint8_t*)t.data(), t.size(), out);
        return true;
      }
      case TYPE_XML_ELEMENT: {
        size_t pos = 1;
        uint64_t k = 0;
        if (!read_var_uint(p, n, pos, &k)) return false;
        if (!fits(pos, k, n)) return false;
        out += "{\"name\":";
        json_escape(p + pos, (size_t)k, out);
        out += ",\"attrs\":";
        if (!map_json(b.branch, out)) return false;
        out += ",\"children\":";
        if (!seq_json(b.branch, out)) return false;
        out.push_back('}');
        return true;
      }
      case TYPE_XML_FRAGMENT:
        return seq_json(b.branch, out);
      default:
        return false;  // hooks / weak links: host-side projection only
    }
  }

  bool value_json(int32_t idx, bool last_only, std::string& out) const {
    const Item& b = items[idx];
    switch (b.kind) {
      case KIND_ANY: {
        const uint8_t* p = (const uint8_t*)arena.data() + b.c_off;
        size_t pos = 0;
        for (int64_t e = 0; e < b.len; e++) {
          std::string one;
          if (!any_json(p, b.c_len, pos, one)) return false;
          if (last_only) {
            if (e == b.len - 1) out += one;
          } else {
            if (e) out.push_back(',');
            out += one;
          }
        }
        return true;
      }
      case KIND_JSON: {
        const uint8_t* p = (const uint8_t*)arena.data() + b.c_off;
        size_t pos = 0;
        for (int64_t e = 0; e < b.len; e++) {
          uint64_t k = 0;
          if (!read_var_uint(p, b.c_len, pos, &k)) return false;
          if (!fits(pos, k, b.c_len)) return false;
          if (!last_only && e) out.push_back(',');
          if (!last_only || e == b.len - 1)
            out.append((const char*)p + pos, (size_t)k);
          pos += (size_t)k;
        }
        return true;
      }
      case KIND_STRING:
        json_escape((const uint8_t*)arena.data() + b.c_off, b.c_len, out);
        return true;
      case KIND_EMBED: {
        // v1 embed payload = length-prefixed JSON text
        const uint8_t* p = (const uint8_t*)arena.data() + b.c_off;
        size_t pos = 0;
        uint64_t k = 0;
        if (!read_var_uint(p, b.c_len, pos, &k)) return false;
        if (!fits(pos, k, b.c_len)) return false;
        out.append((const char*)p + pos, (size_t)k);
        return true;
      }
      case KIND_TYPE:
        return type_json(idx, out);
      default:
        return false;  // binary / doc: no JSON projection
    }
  }

  bool seq_json(int32_t pidx, std::string& out) const {
    out.push_back('[');
    bool first = true;
    for (int32_t i = parents[pidx].head; i >= 0; i = items[i].right) {
      const Item& b = items[i];
      if (b.deleted || !b.countable) continue;
      if (!first) out.push_back(',');
      first = false;
      if (!value_json(i, false, out)) return false;
    }
    out.push_back(']');
    return true;
  }

  bool map_json(int32_t pidx, std::string& out) const {
    out.push_back('{');
    bool first = true;
    for (const auto& kv : parents[pidx].entries) {
      int32_t idx = kv.second;
      if (idx < 0 || items[idx].deleted) continue;
      if (!first) out.push_back(',');
      first = false;
      const std::string& key = key_names[kv.first];
      json_escape((const uint8_t*)key.data(), key.size(), out);
      out.push_back(':');
      if (!value_json(idx, true, out)) return false;
    }
    out.push_back('}');
    return true;
  }

  // JSON of a root's visible state; empty string on unsupported content
  std::string root_json(const std::string& name, int shape) const {
    std::string out;
    auto it = roots.find(name);
    if (it == roots.end()) {
      out = (shape == 1) ? "{}" : "[]";
      return out;
    }
    bool ok2 = (shape == 1) ? map_json(it->second, out)
                            : seq_json(it->second, out);
    if (!ok2) return std::string();
    return out;
  }

  // ---- V1 diff encoder (reference: store.rs:204-248 write_blocks_from
  // + block.rs:868-908 item encode; host parity: ytpu/core/store.py
  // write_blocks_from / block.py Item.encode) ----

  // encode one item with the first `offset` clock units dropped
  bool encode_item(Wr& w, const Item& b, int64_t offset) const {
    if (b.detached) return false;
    constexpr uint8_t HAS_ORIGIN = 0x80, HAS_RIGHT = 0x40, HAS_SUB = 0x20;
    bool has_origin = offset > 0 || b.oc >= 0;
    uint8_t info = (uint8_t)b.kind;
    if (has_origin) info |= HAS_ORIGIN;
    if (b.rc >= 0) info |= HAS_RIGHT;
    if (b.sub >= 0) info |= HAS_SUB;
    w.u8(info);
    if (has_origin) {
      // with offset > 0 the origin is rewritten to the preceding unit
      uint64_t oc2 = offset > 0 ? b.client : (uint64_t)b.oc;
      uint64_t ok2 = offset > 0 ? b.clock + (uint64_t)offset - 1
                                : (uint64_t)b.ok;
      w.vu(oc2);
      w.vu(ok2);
    }
    if (b.rc >= 0) {
      w.vu((uint64_t)b.rc);
      w.vu((uint64_t)b.rk);
    }
    if (!has_origin && b.rc < 0) {
      if (b.parent < 0) return false;
      const ParentSeq& P = parents[b.parent];
      if (P.item >= 0) {
        w.vu(0);  // parent by branch id
        w.vu(items[P.item].client);
        w.vu(items[P.item].clock);
      } else {
        w.vu(1);  // parent by root name
        w.str(P.name);
      }
      if (b.sub >= 0) w.str(key_names[b.sub]);
    }
    // content
    switch (b.kind) {
      case KIND_DELETED:
        w.vu((uint64_t)(b.len - offset));
        return true;
      case KIND_STRING: {
        const uint8_t* s = (const uint8_t*)arena.data() + b.c_off;
        size_t cut = 0;
        if (offset > 0) {
          bool mid = false;
          cut = utf16_to_byte(s, b.c_len, offset, &mid);
          if (mid) return false;  // astral-split re-encode: host lane
        }
        w.vu((uint64_t)(b.c_len - cut));
        w.bytes((const char*)s + cut, b.c_len - cut);
        return true;
      }
      case KIND_ANY:
      case KIND_JSON: {
        const uint8_t* s = (const uint8_t*)arena.data() + b.c_off;
        size_t cut = 0;
        if (offset > 0) {
          bool ok3 = (b.kind == KIND_ANY)
                         ? any_elems_to_byte(s, b.c_len, offset, &cut)
                         : json_elems_to_byte(s, b.c_len, offset, &cut);
          if (!ok3) return false;
        }
        w.vu((uint64_t)(b.len - offset));
        w.bytes((const char*)s + cut, b.c_len - cut);
        return true;
      }
      case KIND_BINARY:
      case KIND_EMBED:
      case KIND_FORMAT:
      case KIND_TYPE:
        if (offset != 0) return false;  // length-1 content cannot slice
        w.bytes(arena.data() + b.c_off, b.c_len);
        return true;
      default:
        return false;
    }
  }

  // full diff vs a remote state vector; empty result = unsupported
  std::string encode_diff(const std::vector<std::pair<uint64_t, uint64_t>>&
                              remote) const {
    Wr w;
    std::unordered_map<uint64_t, uint64_t> rsv;
    for (const auto& kv : remote) rsv[kv.first] = kv.second;
    // clients whose local clock is ahead, higher ids first
    std::vector<std::pair<uint64_t, uint64_t>> diff;  // (client, remote)
    for (const auto& kv : sv) {
      auto f = rsv.find(kv.first);
      uint64_t rc2 = f == rsv.end() ? 0 : f->second;
      if (kv.second > rc2) diff.emplace_back(kv.first, rc2);
    }
    std::sort(diff.begin(), diff.end(),
              [](const auto& a, const auto& b2) { return a.first > b2.first; });
    w.vu(diff.size());
    for (const auto& [client, rclock] : diff) {
      const auto& m = by_client.at(client);
      // pivot: block containing rclock (or the first block)
      auto it = m.begin();
      int64_t offset = 0;
      if (rclock > 0) {
        auto ub = m.upper_bound(rclock);
        if (ub != m.begin()) {
          auto prev = std::prev(ub);
          const Item& pb = items[prev->second];
          if (rclock < pb.clock + (uint64_t)pb.len) {
            it = prev;
            offset = (int64_t)(rclock - pb.clock);
          } else {
            it = ub;
          }
        }
      }
      size_t count = 0;
      for (auto c = it; c != m.end(); ++c) count++;
      w.vu(count);
      w.vu(client);
      w.vu(items[it->second].clock + (uint64_t)offset);
      bool first = true;
      for (; it != m.end(); ++it) {
        if (!encode_item(w, items[it->second], first ? offset : 0))
          return std::string();
        first = false;
      }
    }
    // delete set: merged deleted ranges per client, higher ids first
    std::vector<std::pair<uint64_t, std::vector<std::pair<uint64_t, uint64_t>>>>
        dels;
    for (const auto& kv : by_client) {
      std::vector<std::pair<uint64_t, uint64_t>> rs;
      for (const auto& ci : kv.second) {
        const Item& b = items[ci.second];
        if (!b.deleted) continue;
        uint64_t s = b.clock, e = b.clock + (uint64_t)b.len;
        if (!rs.empty() && rs.back().second == s)
          rs.back().second = e;
        else
          rs.emplace_back(s, e);
      }
      if (!rs.empty()) dels.emplace_back(kv.first, std::move(rs));
    }
    std::sort(dels.begin(), dels.end(),
              [](const auto& a, const auto& b2) { return a.first > b2.first; });
    w.vu(dels.size());
    for (const auto& [client, rs] : dels) {
      w.vu(client);
      w.vu(rs.size());
      for (const auto& [s, e] : rs) {
        w.vu(s);
        w.vu(e - s);
      }
    }
    return w.buf;
  }
};

char* dup_cstr(const std::string& s) {
  char* out = (char*)malloc(s.size() + 1);
  if (!out) return nullptr;
  memcpy(out, s.data(), s.size());
  out[s.size()] = 0;
  return out;
}

}  // namespace

extern "C" {

void* ytpu_engine_new(void) { return new Engine(); }

void ytpu_engine_free(void* h) { delete static_cast<Engine*>(h); }

// 0 = ok, 1 = decode/order error, 2 = unsupported feature
int ytpu_engine_apply(void* h, const uint8_t* data, size_t len) {
  Engine* e = static_cast<Engine*>(h);
  e->apply(data, len);
  if (e->error) return 1;
  if (e->unsupported) return 2;
  return 0;
}

// UTF-8 text of the first root sequence; caller frees with
// ytpu_engine_str_free
char* ytpu_engine_text(void* h) {
  std::string s = static_cast<Engine*>(h)->text();
  return dup_cstr(s);
}

// UTF-8 text of the named root
char* ytpu_engine_text_root(void* h, const char* name) {
  Engine* e = static_cast<Engine*>(h);
  auto it = e->roots.find(name);
  std::string s = it == e->roots.end() ? "" : e->text_of(it->second);
  return dup_cstr(s);
}

// JSON of a named root's visible state. shape: 0 = sequence (array /
// xml-fragment children), 1 = map. Returns NULL when the root holds
// content with no JSON projection (binary, subdocs, hooks) — callers
// fall back to the host oracle.
char* ytpu_engine_root_json(void* h, const char* name, int shape) {
  std::string s = static_cast<Engine*>(h)->root_json(name, shape);
  if (s.empty()) return nullptr;
  return dup_cstr(s);
}

// V1 update bytes for the diff vs a remote state vector (parallel
// client/clock arrays). Returns a malloc'd buffer (length in *out_len),
// or NULL when the state holds content this encoder cannot re-emit —
// callers fall back to the host oracle. Free with ytpu_engine_str_free.
char* ytpu_engine_encode_diff(void* h, const uint64_t* sv_clients,
                              const uint64_t* sv_clocks, size_t n_sv,
                              size_t* out_len) {
  Engine* e = static_cast<Engine*>(h);
  std::vector<std::pair<uint64_t, uint64_t>> remote;
  remote.reserve(n_sv);
  for (size_t i = 0; i < n_sv; i++)
    remote.emplace_back(sv_clients[i], sv_clocks[i]);
  std::string s = e->encode_diff(remote);
  if (s.empty()) {
    *out_len = 0;
    return nullptr;
  }
  char* out = (char*)malloc(s.size());
  if (!out) {
    *out_len = 0;
    return nullptr;
  }
  memcpy(out, s.data(), s.size());
  *out_len = s.size();
  return out;
}

void ytpu_engine_str_free(char* s) { free(s); }

size_t ytpu_engine_n_items(void* h) {
  return static_cast<Engine*>(h)->items.size();
}
}
