// ytpu/native/engine.cpp — scalar single-doc YATA engine in C++.
//
// The native-speed performance baseline (VERDICT r1 #3): a from-scratch
// C++ implementation of the YATA integration algorithm over the columnar
// decode (lib0_codec.cpp), semantics matching the reference's hot path —
// integrate (yrs/src/block.rs:482-769, conflict scan :537-602),
// apply_delete (yrs/src/transaction.rs:472-575), squash
// (yrs/src/block.rs:775-799) — for the block kinds the B-series benches
// exercise (String / Deleted content + delete-set ranges, root text
// parent). It is NOT a port: storage is an index-based arena (no
// pointers), per-client lookup is an ordered clock map, and the sequence
// is an intrusive doubly-linked list over indices.
//
// Scope guard: updates containing features outside this engine's scope
// (map keys, nested parents, moves, non-text content) set `unsupported`
// and the Python wrapper falls back to the host oracle.

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <string>
#include <unordered_map>
#include <vector>

// columnar V1 decoder (lib0_codec.cpp, linked into the same .so)
extern "C" {
void* ytpu_decode_update_v1(const uint8_t* data, size_t len);
int ytpu_columns_error(void* h);
size_t ytpu_columns_n_blocks(void* h);
size_t ytpu_columns_n_dels(void* h);
const int64_t* ytpu_col_client(void* h);
const int64_t* ytpu_col_clock(void* h);
const int64_t* ytpu_col_length(void* h);
const int64_t* ytpu_col_kind(void* h);
const int64_t* ytpu_col_origin_client(void* h);
const int64_t* ytpu_col_origin_clock(void* h);
const int64_t* ytpu_col_ror_client(void* h);
const int64_t* ytpu_col_ror_clock(void* h);
const int64_t* ytpu_col_parent_kind(void* h);
const int64_t* ytpu_col_parent_sub_start(void* h);
const int64_t* ytpu_col_content_start(void* h);
const int64_t* ytpu_col_content_len_bytes(void* h);
const int64_t* ytpu_col_del_client(void* h);
const int64_t* ytpu_col_del_start(void* h);
const int64_t* ytpu_col_del_end(void* h);
void ytpu_columns_free(void* h);
}

namespace {

constexpr int64_t KIND_GC = 0;
constexpr int64_t KIND_DELETED = 1;
constexpr int64_t KIND_STRING = 4;
constexpr int64_t KIND_SKIP = 10;

struct Item {
  uint64_t client = 0;
  uint64_t clock = 0;
  int64_t len = 0;  // CRDT length (UTF-16 units for strings)
  int64_t oc = -1;  // origin (client, clock); -1 client = none
  int64_t ok = 0;
  int64_t rc = -1;  // right origin
  int64_t rk = 0;
  int32_t left = -1;   // sequence neighbors (indices into items)
  int32_t right = -1;
  bool deleted = false;
  bool is_string = false;
  size_t str_off = 0;  // UTF-8 bytes in the arena (strings only)
  size_t str_len = 0;
};

// Byte offset of the k-th UTF-16 unit within s[0..n). If the cut lands
// inside a surrogate pair (astral char = 4-byte UTF-8 = 2 units), sets
// *midpair and returns the char's start — the caller substitutes U+FFFD
// halves, matching the host's split_str_utf16 (and the workaround
// documented at reference block.rs:1852-1860).
size_t utf16_to_byte(const uint8_t* s, size_t n, int64_t units,
                     bool* midpair = nullptr) {
  size_t i = 0;
  int64_t u = 0;
  if (midpair) *midpair = false;
  while (i < n && u < units) {
    uint8_t b = s[i];
    if (b < 0x80) {
      i += 1;
      u += 1;
    } else if (b < 0xE0) {
      i += 2;
      u += 1;
    } else if (b < 0xF0) {
      i += 3;
      u += 1;
    } else {
      if (u + 2 > units) {  // cut splits this pair
        if (midpair) *midpair = true;
        return i;
      }
      i += 4;
      u += 2;  // surrogate pair
    }
  }
  return i;
}

constexpr const char* kReplacement = "\xEF\xBF\xBD";  // U+FFFD

struct Engine {
  std::vector<Item> items;
  std::string arena;  // string content bytes
  // per-client: start clock -> item index, ordered (O(log n) find/split)
  std::unordered_map<uint64_t, std::map<uint64_t, int32_t>> by_client;
  std::unordered_map<uint64_t, uint64_t> sv;  // next expected clock
  int32_t head = -1;  // first item of the root sequence
  bool unsupported = false;
  bool error = false;

  uint64_t cov(uint64_t client) const {
    auto it = sv.find(client);
    return it == sv.end() ? 0 : it->second;
  }

  // item whose span contains `clock`, or -1
  int32_t find(uint64_t client, uint64_t clock) {
    auto bc = by_client.find(client);
    if (bc == by_client.end() || bc->second.empty()) return -1;
    auto it = bc->second.upper_bound(clock);
    if (it == bc->second.begin()) return -1;
    --it;
    int32_t idx = it->second;
    const Item& b = items[idx];
    if (clock >= b.clock + (uint64_t)b.len) return -1;
    return idx;
  }

  // split `idx` at absolute clock `at` (strictly inside); returns the
  // right half's index. Mirrors ItemSlice materialization
  // (yrs/src/store.rs:284-331) on the flat store.
  int32_t split(int32_t idx, uint64_t at) {
    Item& b = items[idx];
    int64_t left_units = (int64_t)(at - b.clock);
    Item r;
    r.client = b.client;
    r.clock = at;
    r.len = b.len - left_units;
    r.oc = (int64_t)b.client;  // right half originates from the left half
    r.ok = (int64_t)(at - 1);
    r.rc = b.rc;
    r.rk = b.rk;
    r.deleted = b.deleted;
    r.is_string = b.is_string;
    if (b.is_string) {
      const uint8_t* s = (const uint8_t*)arena.data() + b.str_off;
      bool mid = false;
      size_t cut = utf16_to_byte(s, b.str_len, left_units, &mid);
      if (!mid) {
        r.str_off = b.str_off + cut;
        r.str_len = b.str_len - cut;
        b.str_len = cut;
      } else {
        // surrogate-pair split: each half gets a U+FFFD stand-in (1 unit
        // each, keeping content length == clock length on both sides).
        // Spans can't express the substitution in place, so both halves
        // move to fresh arena regions (rare; bounded by astral splits).
        std::string lbytes(arena, b.str_off, cut);
        std::string rbytes(arena, b.str_off + cut + 4,
                           b.str_len - cut - 4);
        size_t loff = arena.size();
        arena.append(lbytes);
        arena.append(kReplacement);
        size_t roff = arena.size();
        arena.append(kReplacement);
        arena.append(rbytes);
        b.str_off = loff;
        b.str_len = cut + 3;
        r.str_off = roff;
        r.str_len = 3 + rbytes.size();
      }
    }
    b.len = left_units;
    int32_t ridx = (int32_t)items.size();
    // sequence splice: b <-> r <-> old right
    r.left = idx;
    r.right = b.right;
    items.push_back(r);
    Item& b2 = items[idx];  // re-borrow (push_back may reallocate)
    if (b2.right >= 0) items[b2.right].left = ridx;
    b2.right = ridx;
    by_client[r.client][at] = ridx;
    return ridx;
  }

  // left neighbor for (client, clock): the item ending exactly at clock,
  // split if needed (get_item_clean_end, yrs/src/block_store.rs:402)
  int32_t clean_end(uint64_t client, uint64_t clock) {
    int32_t idx = find(client, clock);
    if (idx < 0) return -1;
    const Item& b = items[idx];
    if (clock + 1 < b.clock + (uint64_t)b.len) split(idx, clock + 1);
    return idx;
  }

  // item starting exactly at clock, split if needed (get_item_clean_start)
  int32_t clean_start(uint64_t client, uint64_t clock) {
    int32_t idx = find(client, clock);
    if (idx < 0) return -1;
    if (items[idx].clock < clock) return split(idx, clock);
    return idx;
  }

  // YATA conflict resolution (reference: block.rs:482-769; the conflict
  // scan :537-602 with the client-id tie-break :571-580).
  void integrate(Item it) {
    // repair: resolve origin → left neighbor (clean end) and right origin
    // → scan bound (clean start), independently (block.rs:1287-1343)
    int32_t left = -1, right = -1;
    if (it.oc >= 0) {
      left = clean_end((uint64_t)it.oc, (uint64_t)it.ok);
      if (left < 0) {
        error = true;  // missing dependency (caller checked coverage)
        return;
      }
    }
    if (it.rc >= 0) {
      right = clean_start((uint64_t)it.rc, (uint64_t)it.rk);
      if (right < 0) {
        error = true;
        return;
      }
    }

    // conflict scan: walk candidates in (left, right_origin_bound)
    int32_t o = (left >= 0) ? items[left].right : head;
    if (o >= 0 && o != right) {
      // item-index sets; small in practice (concurrent-insert width)
      std::vector<int32_t> conflicting, before_origin;
      auto contains = [](const std::vector<int32_t>& v, int32_t x) {
        return std::find(v.begin(), v.end(), x) != v.end();
      };
      while (o >= 0 && o != right) {
        before_origin.push_back(o);
        conflicting.push_back(o);
        const Item& ob = items[o];
        bool same_origin = (ob.oc == it.oc) && (ob.oc < 0 || ob.ok == it.ok);
        if (same_origin) {
          if (ob.client < it.client) {
            left = o;
            conflicting.clear();
          } else if (ob.rc == it.rc && (ob.rc < 0 || ob.rk == it.rk)) {
            break;  // same origin + same right origin: order settled
          }
        } else {
          int32_t oo = (ob.oc >= 0)
                           ? find((uint64_t)ob.oc, (uint64_t)ob.ok)
                           : -1;
          if (ob.oc >= 0 && oo >= 0 && contains(before_origin, oo)) {
            if (!contains(conflicting, oo)) {
              left = o;
              conflicting.clear();
            }
          } else {
            break;
          }
        }
        o = ob.right;
      }
    }

    // splice into the sequence
    int32_t idx = (int32_t)items.size();
    it.left = left;
    it.right = (left >= 0) ? items[left].right : head;
    items.push_back(it);
    Item& nb = items[idx];
    if (nb.left >= 0)
      items[nb.left].right = idx;
    else
      head = idx;
    if (nb.right >= 0) items[nb.right].left = idx;
    by_client[nb.client][nb.clock] = idx;
    uint64_t end = nb.clock + (uint64_t)nb.len;
    if (end > cov(nb.client)) sv[nb.client] = end;
  }

  // tombstone [start, end) of `client` (apply_delete semantics:
  // transaction.rs:472-575 — split boundaries, mark deleted)
  void apply_delete(uint64_t client, uint64_t start, uint64_t end) {
    uint64_t covered = cov(client);
    if (end > covered) end = covered;  // clip (host lane stashes the rest)
    uint64_t c = start;
    while (c < end) {
      int32_t idx = find(client, c);
      if (idx < 0) {
        // gap (already GC'd or range hole): advance to next block start
        auto& m = by_client[client];
        auto it = m.upper_bound(c);
        if (it == m.end() || it->first >= end) return;
        c = it->first;
        continue;
      }
      if (items[idx].clock < c) idx = split(idx, c);
      Item& b = items[idx];
      uint64_t bend = b.clock + (uint64_t)b.len;
      if (bend > end) {
        split(idx, end);
      }
      items[idx].deleted = true;
      c = items[idx].clock + (uint64_t)items[idx].len;
    }
  }

  void apply(const uint8_t* data, size_t n) {
    void* h = ytpu_decode_update_v1(data, n);
    size_t nb = ytpu_columns_n_blocks(h);
    size_t nd = ytpu_columns_n_dels(h);
    if (ytpu_columns_error(h)) error = true;
    const int64_t* client = ytpu_col_client(h);
    const int64_t* clock = ytpu_col_clock(h);
    const int64_t* length = ytpu_col_length(h);
    const int64_t* kind = ytpu_col_kind(h);
    const int64_t* oc = ytpu_col_origin_client(h);
    const int64_t* ok = ytpu_col_origin_clock(h);
    const int64_t* rc = ytpu_col_ror_client(h);
    const int64_t* rk = ytpu_col_ror_clock(h);
    const int64_t* pk = ytpu_col_parent_kind(h);
    const int64_t* pss = ytpu_col_parent_sub_start(h);
    const int64_t* cs = ytpu_col_content_start(h);
    const int64_t* cl = ytpu_col_content_len_bytes(h);
    const int64_t* dc = ytpu_col_del_client(h);
    const int64_t* ds = ytpu_col_del_start(h);
    const int64_t* de = ytpu_col_del_end(h);
    for (size_t i = 0; i < nb && !error && !unsupported; i++) {
      if (kind[i] == KIND_SKIP) continue;
      if (pk[i] == 2 || pss[i] >= 0) {  // branch-id parent / map row
        unsupported = true;
        break;
      }
      uint64_t cend = (uint64_t)clock[i] + (uint64_t)length[i];
      uint64_t have = cov((uint64_t)client[i]);
      if (cend <= have) continue;  // duplicate delivery
      if ((uint64_t)clock[i] > have) {
        error = true;  // out-of-order (bench streams are in-order)
        break;
      }
      Item it;
      it.client = (uint64_t)client[i];
      it.clock = (uint64_t)clock[i];
      it.len = length[i];
      it.oc = oc[i] >= 0 && ok[i] >= 0 ? oc[i] : -1;
      it.ok = ok[i];
      it.rc = rc[i] >= 0 && rk[i] >= 0 ? rc[i] : -1;
      it.rk = rk[i];
      int64_t offset = (int64_t)(have - it.clock);  // partial redelivery
      if (kind[i] == KIND_STRING) {
        it.is_string = true;
        // content span = varint byte-length prefix + UTF-8 payload
        const uint8_t* p = data + cs[i];
        size_t pn = (size_t)cl[i];
        size_t vi = 0;
        uint64_t blen = 0;
        int shift = 0;
        while (vi < pn) {
          uint8_t b = p[vi++];
          blen |= (uint64_t)(b & 0x7F) << shift;
          shift += 7;
          if (b < 0x80) break;
        }
        it.str_off = arena.size();
        it.str_len = (size_t)blen;
        arena.append((const char*)p + vi, (size_t)blen);
      } else if (kind[i] == KIND_DELETED) {
        it.deleted = true;
      } else {
        // GC ranges are position-less (BlockRange, not a sequence item);
        // integrating one here would corrupt origin resolution — fall
        // back to the host oracle for such streams.
        unsupported = true;
        break;
      }
      if (offset > 0) {
        // drop the already-integrated prefix (integrate(txn, offset))
        it.clock += (uint64_t)offset;
        if (it.is_string) {
          const uint8_t* s = (const uint8_t*)arena.data() + it.str_off;
          bool mid = false;
          size_t cut = utf16_to_byte(s, it.str_len, offset, &mid);
          if (!mid) {
            it.str_off += cut;
            it.str_len -= cut;
          } else {
            std::string rest(arena, it.str_off + cut + 4,
                             it.str_len - cut - 4);
            it.str_off = arena.size();
            arena.append(kReplacement);
            arena.append(rest);
            it.str_len = 3 + rest.size();
          }
        }
        it.len -= offset;
        it.oc = (int64_t)it.client;
        it.ok = (int64_t)(it.clock - 1);
      }
      integrate(it);
    }
    for (size_t i = 0; i < nd && !error && !unsupported; i++) {
      apply_delete((uint64_t)dc[i], (uint64_t)ds[i], (uint64_t)de[i]);
    }
    ytpu_columns_free(h);
  }

  std::string text() const {
    std::string out;
    out.reserve(arena.size());
    for (int32_t i = head; i >= 0; i = items[i].right) {
      const Item& b = items[i];
      if (!b.deleted && b.is_string)
        out.append(arena, b.str_off, b.str_len);
    }
    return out;
  }
};

}  // namespace

extern "C" {

void* ytpu_engine_new(void) { return new Engine(); }

void ytpu_engine_free(void* h) { delete static_cast<Engine*>(h); }

// 0 = ok, 1 = decode/order error, 2 = unsupported feature
int ytpu_engine_apply(void* h, const uint8_t* data, size_t len) {
  Engine* e = static_cast<Engine*>(h);
  e->apply(data, len);
  if (e->error) return 1;
  if (e->unsupported) return 2;
  return 0;
}

// UTF-8 text of the root sequence; caller frees with ytpu_engine_str_free
char* ytpu_engine_text(void* h) {
  std::string s = static_cast<Engine*>(h)->text();
  char* out = (char*)malloc(s.size() + 1);
  if (!out) return nullptr;
  memcpy(out, s.data(), s.size());
  out[s.size()] = 0;
  return out;
}

void ytpu_engine_str_free(char* s) { free(s); }

size_t ytpu_engine_n_items(void* h) {
  return static_cast<Engine*>(h)->items.size();
}
}
