"""Native (C++) host runtime pieces, loaded via ctypes.

The shared library is built on demand with g++ (see `_build`). Everything
here degrades gracefully: if no compiler is available the Python
implementations in `ytpu.encoding` / `ytpu.core` are used instead —
`available()` reports which path is active.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

__all__ = [
    "load",
    "available",
    "NativeColumns",
    "decode_update_columns",
    "build_capi",
    "NativeEngine",
    "NativeUnsupported",
    "engine_available",
    "native_replay_v1",
]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "lib0_codec.cpp")
_ENGINE_SRC = os.path.join(_HERE, "engine.cpp")
_FINISHER_SRC = os.path.join(_HERE, "encode_finisher.cpp")
_LIB = os.path.join(_HERE, "_libytpu.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_COLUMNS = [
    "client",
    "clock",
    "length",
    "kind",
    "origin_client",
    "origin_clock",
    "ror_client",
    "ror_clock",
    "parent_kind",
    "parent_name_start",
    "parent_name_len",
    "parent_id_client",
    "parent_id_clock",
    "parent_sub_start",
    "parent_sub_len",
    "content_start",
    "content_len_bytes",
]
_DEL_COLUMNS = ["del_client", "del_start", "del_end"]


def _build() -> bool:
    try:
        subprocess.run(
            [
                "g++",
                "-O2",
                "-shared",
                "-fPIC",
                "-pthread",
                "-std=c++17",
                _SRC,
                _ENGINE_SRC,
                _FINISHER_SRC,
                "-o",
                _LIB,
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except Exception:
        return False


_CAPI_SRC = os.path.join(_HERE, "capi.cpp")
_CAPI_LIB = os.path.join(_HERE, "libytpu_capi.so")


def build_capi(force: bool = False) -> Optional[str]:
    """Build the yffi-parity C ABI library (`libytpu_capi.so`).

    Embeds CPython: links against the running interpreter's libpython so
    arbitrary C programs can drive the engine (see include/ytpu.h).
    Returns the library path, or None if the toolchain is unavailable.
    """
    import sysconfig

    header = os.path.join(_HERE, "include", "ytpu.h")
    support = os.path.join(_HERE, "support.py")
    inputs = [p for p in (_CAPI_SRC, header, support) if os.path.exists(p)]
    if (
        not force
        and os.path.exists(_CAPI_LIB)
        and os.path.getmtime(_CAPI_LIB) >= max(os.path.getmtime(p) for p in inputs)
    ):
        return _CAPI_LIB
    include = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR") or "/usr/local/lib"
    version = sysconfig.get_config_var("LDVERSION") or sysconfig.get_config_var(
        "VERSION"
    )
    try:
        subprocess.run(
            [
                "g++",
                "-O2",
                "-shared",
                "-fPIC",
                "-std=c++17",
                _CAPI_SRC,
                f"-I{include}",
                f"-L{libdir}",
                f"-lpython{version}",
                f"-Wl,-rpath,{libdir}",
                "-o",
                _CAPI_LIB,
            ],
            check=True,
            capture_output=True,
            timeout=180,
        )
        return _CAPI_LIB
    except Exception:
        return None


class FinishIn(ctypes.Structure):
    """Mirror of `FinishIn` in encode_finisher.cpp (field order must match)."""

    _fields_ = [
        ("n_docs_total", ctypes.c_int32),
        ("n_blocks_cap", ctypes.c_int32),
        ("client", ctypes.POINTER(ctypes.c_int32)),
        ("clock", ctypes.POINTER(ctypes.c_int32)),
        ("length", ctypes.POINTER(ctypes.c_int32)),
        ("origin_client", ctypes.POINTER(ctypes.c_int32)),
        ("origin_clock", ctypes.POINTER(ctypes.c_int32)),
        ("ror_client", ctypes.POINTER(ctypes.c_int32)),
        ("ror_clock", ctypes.POINTER(ctypes.c_int32)),
        ("kind", ctypes.POINTER(ctypes.c_int32)),
        ("content_ref", ctypes.POINTER(ctypes.c_int32)),
        ("content_off", ctypes.POINTER(ctypes.c_int32)),
        ("key", ctypes.POINTER(ctypes.c_int32)),
        ("parent", ctypes.POINTER(ctypes.c_int32)),
        ("ship", ctypes.POINTER(ctypes.c_uint8)),
        ("offsets", ctypes.POINTER(ctypes.c_int32)),
        ("deleted", ctypes.POINTER(ctypes.c_uint8)),
        ("sel", ctypes.POINTER(ctypes.c_int32)),
        ("n_sel", ctypes.c_int32),
        ("from_idx", ctypes.POINTER(ctypes.c_int64)),
        ("n_interned", ctypes.c_int32),
        ("key_blob", ctypes.POINTER(ctypes.c_uint8)),
        ("key_off", ctypes.POINTER(ctypes.c_int64)),
        ("n_keys", ctypes.c_int32),
        ("root_name", ctypes.POINTER(ctypes.c_uint8)),
        ("root_name_len", ctypes.c_int32),
        ("text_arena", ctypes.POINTER(ctypes.c_uint8)),
        ("text_arena_len", ctypes.c_int64),
        ("item_text_off", ctypes.POINTER(ctypes.c_int64)),
        ("item_text_units", ctypes.POINTER(ctypes.c_int64)),
        ("blob_arena", ctypes.POINTER(ctypes.c_uint8)),
        ("blob_arena_len", ctypes.c_int64),
        ("item_blob_off", ctypes.POINTER(ctypes.c_int64)),
        ("item_blob_len", ctypes.POINTER(ctypes.c_int64)),
        ("item_elem_base", ctypes.POINTER(ctypes.c_int64)),
        ("item_elem_count", ctypes.POINTER(ctypes.c_int64)),
        ("elem_off", ctypes.POINTER(ctypes.c_int64)),
        ("elem_arena", ctypes.POINTER(ctypes.c_uint8)),
        ("elem_arena_len", ctypes.c_int64),
        ("n_items", ctypes.c_int64),
        ("wire", ctypes.POINTER(ctypes.c_uint8)),
        ("wire_len", ctypes.c_int64),
    ]


def load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        newest_src = max(
            os.path.getmtime(_SRC),
            os.path.getmtime(_ENGINE_SRC),
            os.path.getmtime(_FINISHER_SRC),
        )
        if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < newest_src:
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        lib.ytpu_decode_update_v1.restype = ctypes.c_void_p
        lib.ytpu_decode_update_v1.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        lib.ytpu_columns_error.restype = ctypes.c_int
        lib.ytpu_columns_error.argtypes = [ctypes.c_void_p]
        lib.ytpu_columns_n_blocks.restype = ctypes.c_size_t
        lib.ytpu_columns_n_blocks.argtypes = [ctypes.c_void_p]
        lib.ytpu_columns_n_dels.restype = ctypes.c_size_t
        lib.ytpu_columns_n_dels.argtypes = [ctypes.c_void_p]
        lib.ytpu_columns_n_client_sections.restype = ctypes.c_size_t
        lib.ytpu_columns_n_client_sections.argtypes = [ctypes.c_void_p]
        lib.ytpu_columns_n_ds_sections.restype = ctypes.c_size_t
        lib.ytpu_columns_n_ds_sections.argtypes = [ctypes.c_void_p]
        lib.ytpu_columns_n_zero_len_blocks.restype = ctypes.c_size_t
        lib.ytpu_columns_n_zero_len_blocks.argtypes = [ctypes.c_void_p]
        lib.ytpu_columns_n_value_steps.restype = ctypes.c_size_t
        lib.ytpu_columns_n_value_steps.argtypes = [ctypes.c_void_p]
        lib.ytpu_columns_n_complex_any.restype = ctypes.c_size_t
        lib.ytpu_columns_n_complex_any.argtypes = [ctypes.c_void_p]
        lib.ytpu_columns_free.argtypes = [ctypes.c_void_p]
        for name in _COLUMNS + _DEL_COLUMNS:
            fn = getattr(lib, f"ytpu_col_{name}")
            fn.restype = ctypes.POINTER(ctypes.c_int64)
            fn.argtypes = [ctypes.c_void_p]
        lib.ytpu_decode_var_uints.restype = ctypes.c_size_t
        lib.ytpu_decode_var_uints.argtypes = [
            ctypes.c_char_p,
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_size_t,
        ]
        lib.ytpu_engine_new.restype = ctypes.c_void_p
        lib.ytpu_engine_free.argtypes = [ctypes.c_void_p]
        lib.ytpu_engine_apply.restype = ctypes.c_int
        lib.ytpu_engine_apply.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_size_t,
        ]
        lib.ytpu_engine_text.restype = ctypes.c_void_p  # freed manually
        lib.ytpu_engine_text.argtypes = [ctypes.c_void_p]
        lib.ytpu_engine_text_root.restype = ctypes.c_void_p
        lib.ytpu_engine_text_root.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ytpu_engine_root_json.restype = ctypes.c_void_p
        lib.ytpu_engine_root_json.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_int,
        ]
        lib.ytpu_engine_encode_diff.restype = ctypes.c_void_p
        lib.ytpu_engine_encode_diff.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.ytpu_engine_str_free.argtypes = [ctypes.c_void_p]
        lib.ytpu_engine_n_items.restype = ctypes.c_size_t
        lib.ytpu_engine_n_items.argtypes = [ctypes.c_void_p]
        # the finisher passes a 40+ field struct by pointer; refuse to bind
        # unless the C++ and ctypes layouts agree byte-for-byte (a field
        # added/reordered on one side would otherwise corrupt memory)
        lib.ytpu_finish_in_sizeof.restype = ctypes.c_int64
        lib.finisher_ok = (
            int(lib.ytpu_finish_in_sizeof()) == ctypes.sizeof(FinishIn)
        )
        if lib.finisher_ok:
            lib.ytpu_finish_batch.restype = ctypes.c_void_p
            lib.ytpu_finish_batch.argtypes = [ctypes.POINTER(FinishIn)]
            lib.ytpu_finish_batch_mt.restype = ctypes.c_void_p
            lib.ytpu_finish_batch_mt.argtypes = [
                ctypes.POINTER(FinishIn),
                ctypes.c_int32,
            ]
            lib.ytpu_finish_status.restype = ctypes.c_int32
            lib.ytpu_finish_status.argtypes = [ctypes.c_void_p, ctypes.c_int32]
            lib.ytpu_finish_data.restype = ctypes.POINTER(ctypes.c_uint8)
            lib.ytpu_finish_data.argtypes = [ctypes.c_void_p]
            lib.ytpu_finish_span.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.ytpu_finish_free.argtypes = [ctypes.c_void_p]
            # ISSUE-10 additions: the strided packed-arena entry (one
            # host tensor, zero per-plane copies) and the vectorized
            # span/status readout. A stale .so that predates them (no
            # compiler to rebuild) degrades to the classic per-column /
            # per-doc path — `finisher_strided_ok` gates the callers.
            try:
                lib.ytpu_finish_batch_strided.restype = ctypes.c_void_p
                lib.ytpu_finish_batch_strided.argtypes = [
                    ctypes.POINTER(FinishIn),
                    ctypes.c_int64,
                    ctypes.c_int32,
                ]
                lib.ytpu_finish_total_len.restype = ctypes.c_int64
                lib.ytpu_finish_total_len.argtypes = [ctypes.c_void_p]
                lib.ytpu_finish_spans.argtypes = [
                    ctypes.c_void_p,
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.POINTER(ctypes.c_int32),
                ]
                lib.finisher_strided_ok = True
            except AttributeError:
                lib.finisher_strided_ok = False
        else:
            lib.finisher_strided_ok = False
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


class NativeColumns:
    """Columnar view over one decoded update (owns the native handle)."""

    def __init__(self, lib: ctypes.CDLL, handle: int, payload: bytes):
        self._lib = lib
        self._handle = handle
        self.payload = payload  # original wire bytes; spans index into this
        self.error = bool(lib.ytpu_columns_error(handle))
        self.n_blocks = int(lib.ytpu_columns_n_blocks(handle))
        self.n_dels = int(lib.ytpu_columns_n_dels(handle))
        self.n_client_sections = int(lib.ytpu_columns_n_client_sections(handle))
        self.n_ds_sections = int(lib.ytpu_columns_n_ds_sections(handle))
        self.n_zero_len_blocks = int(lib.ytpu_columns_n_zero_len_blocks(handle))
        self.n_value_steps = int(lib.ytpu_columns_n_value_steps(handle))
        self.n_complex_any = int(lib.ytpu_columns_n_complex_any(handle))
        import numpy as np

        def grab(name: str, count: int):
            if count == 0:
                return np.empty(0, dtype=np.int64)
            ptr = getattr(lib, f"ytpu_col_{name}")(handle)
            return np.ctypeslib.as_array(ptr, shape=(count,)).copy()

        for name in _COLUMNS:
            setattr(self, name, grab(name, self.n_blocks))
        for name in _DEL_COLUMNS:
            setattr(self, name, grab(name, self.n_dels))
        lib.ytpu_columns_free(handle)
        self._handle = None

    def span(self, start: int, length: int) -> bytes:
        return self.payload[start : start + length]

    def parent_name(self, i: int) -> str:
        s, n = int(self.parent_name_start[i]), int(self.parent_name_len[i])
        return self.span(s, n).decode("utf-8")

    def parent_sub(self, i: int):
        s, n = int(self.parent_sub_start[i]), int(self.parent_sub_len[i])
        if s < 0:
            return None
        return self.span(s, n).decode("utf-8")

    def content_bytes(self, i: int) -> bytes:
        return self.span(int(self.content_start[i]), int(self.content_len_bytes[i]))


def decode_update_columns(payload: bytes) -> Optional[NativeColumns]:
    """Decode a v1 update into block columns via the native codec.

    Returns None if the native library is unavailable.
    """
    lib = load()
    if lib is None:
        return None
    handle = lib.ytpu_decode_update_v1(payload, len(payload))
    return NativeColumns(lib, handle, payload)


class NativeUnsupported(RuntimeError):
    """The C++ engine hit a feature outside its scope (GC ranges, move
    ranges, sub-documents) — use the host oracle."""


class NativeEngine:
    """Scalar single-doc YATA engine in C++ (`engine.cpp`).

    The native-speed performance baseline: reference-equivalent integrate
    / apply_delete semantics for text, array, map and nested-XML update
    streams (String / Deleted / Any / JSON / Binary / Embed / Format /
    Type content, root-name and branch-id parents, map key chains with
    last-write-wins shadowing). Raises `NativeUnsupported` for
    out-of-scope features (GC ranges, moves, subdocs).
    """

    def __init__(self):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = lib.ytpu_engine_new()

    def apply_update_v1(self, payload: bytes) -> None:
        rc = self._lib.ytpu_engine_apply(self._handle, payload, len(payload))
        if rc == 2:
            raise NativeUnsupported("update outside native engine scope")
        if rc != 0:
            raise RuntimeError(f"native engine apply failed (rc={rc})")

    def text(self) -> str:
        ptr = self._lib.ytpu_engine_text(self._handle)
        if not ptr:
            raise MemoryError("ytpu_engine_text")
        try:
            return ctypes.string_at(ptr).decode("utf-8")
        finally:
            self._lib.ytpu_engine_str_free(ptr)

    def text_root(self, name: str) -> str:
        ptr = self._lib.ytpu_engine_text_root(self._handle, name.encode())
        if not ptr:
            raise MemoryError("ytpu_engine_text_root")
        try:
            return ctypes.string_at(ptr).decode("utf-8")
        finally:
            self._lib.ytpu_engine_str_free(ptr)

    def encode_diff_v1(self, sv: dict) -> bytes:
        """V1 update bytes for the diff vs a remote state vector (mapping
        client-id -> clock). Semantics parity with the host's
        `encode_state_as_update_v1` (reference store.rs:204-248); block
        granularity may differ (the engine splits but never squashes), so
        validate by applying to a fresh doc, not by byte compare. Raises
        `NativeUnsupported` when the state cannot be re-encoded natively."""
        n = len(sv)
        clients = (ctypes.c_uint64 * n)(*sv.keys())
        clocks = (ctypes.c_uint64 * n)(*sv.values())
        out_len = ctypes.c_size_t(0)
        ptr = self._lib.ytpu_engine_encode_diff(
            self._handle, clients, clocks, n, ctypes.byref(out_len)
        )
        if not ptr:
            raise NativeUnsupported("state has no native diff encoding")
        try:
            return ctypes.string_at(ptr, out_len.value)
        finally:
            self._lib.ytpu_engine_str_free(ptr)

    def root_json(self, name: str, shape: str = "seq"):
        """Parsed visible state of a named root ("seq" = array / xml
        children order, "map" = key/value object). Raises
        `NativeUnsupported` when the root holds content with no native
        JSON projection (binary, subdocs, hooks)."""
        import json as _json

        shapes = {"seq": 0, "map": 1}
        ptr = self._lib.ytpu_engine_root_json(
            self._handle, name.encode(), shapes[shape]
        )
        if not ptr:
            raise NativeUnsupported(f"no native JSON projection for {name!r}")
        try:
            return _json.loads(ctypes.string_at(ptr).decode("utf-8"))
        finally:
            self._lib.ytpu_engine_str_free(ptr)

    @property
    def n_items(self) -> int:
        return int(self._lib.ytpu_engine_n_items(self._handle))

    def close(self) -> None:
        if self._handle:
            self._lib.ytpu_engine_free(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass


def engine_available() -> bool:
    return available()


def native_replay_v1(payloads) -> str:
    """Replay a V1 update stream through the C++ engine; returns the final
    root text. Raises `NativeUnsupported` when the stream needs features
    beyond the engine's scope (caller falls back to the host oracle)."""
    eng = NativeEngine()
    try:
        for p in payloads:
            eng.apply_update_v1(p)
        return eng.text()
    finally:
        eng.close()
