// lib0 v1 update decoder — native host ingestion path.
//
// Behavioral parity: the v1 wire grammar of /root/reference/yrs/src/
// updates/decoder.rs:76-190 and update.rs:433-488 (block framing), plus
// Any skipping per any.rs:37-83.
//
// Where the reference implements its codec in Rust inside the same process
// as the CRDT store, ytpu's runtime splits the plane: this C++ decoder
// turns raw update bytes into struct-of-arrays block columns (the exact
// UpdateBatch layout of ytpu/models/batch_doc.py) so Python never walks the
// byte stream on the hot path; payload bytes stay in place and are
// referenced by (offset, length) spans.
//
// Exposed as a C ABI consumed via ctypes (ytpu/native/__init__.py).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr uint8_t BLOCK_GC = 0;
constexpr uint8_t CONTENT_DELETED = 1;
constexpr uint8_t CONTENT_JSON = 2;
constexpr uint8_t CONTENT_BINARY = 3;
constexpr uint8_t CONTENT_STRING = 4;
constexpr uint8_t CONTENT_EMBED = 5;
constexpr uint8_t CONTENT_FORMAT = 6;
constexpr uint8_t CONTENT_TYPE = 7;
constexpr uint8_t CONTENT_ANY = 8;
constexpr uint8_t CONTENT_DOC = 9;
constexpr uint8_t BLOCK_SKIP = 10;
constexpr uint8_t CONTENT_MOVE = 11;

constexpr uint8_t HAS_ORIGIN = 0x80;
constexpr uint8_t HAS_RIGHT_ORIGIN = 0x40;
constexpr uint8_t HAS_PARENT_SUB = 0x20;

constexpr uint8_t TYPE_XML_ELEMENT = 3;
constexpr uint8_t TYPE_XML_HOOK = 5;
constexpr uint8_t TYPE_WEAK = 7;

struct Cursor {
  const uint8_t* buf;
  size_t len;
  size_t pos;
  bool error;

  uint8_t u8() {
    if (pos >= len) {
      error = true;
      return 0;
    }
    return buf[pos++];
  }

  uint64_t var_uint() {
    uint64_t num = 0;
    int shift = 0;
    while (true) {
      uint8_t b = u8();
      if (error) return 0;
      num |= (uint64_t)(b & 0x7F) << shift;
      shift += 7;
      if (b < 0x80) return num;
      if (shift >= 70) {  // 10-byte cap: an 11th byte would shift ≥64 (UB)
        error = true;
        return 0;
      }
    }
  }

  void skip(size_t n) {
    if (pos > len || n > len - pos) {  // overflow-safe bound
      error = true;
      return;
    }
    pos += n;
  }

  // span helpers: record [start, end) of a length-prefixed buffer
  void buf_span(int64_t* start, int64_t* length) {
    uint64_t n = var_uint();
    *start = (int64_t)pos;
    *length = (int64_t)n;
    skip((size_t)n);
  }

  void skip_var_int() {  // signed varint (6-bit head)
    uint8_t b = u8();
    if (error || (b & 0x80) == 0) return;
    while (true) {
      b = u8();
      if (error || b < 0x80) return;
    }
  }

  void skip_f(int n) { skip(n); }

  void skip_any() {  // parity: any.rs:37-83
    uint8_t tag = u8();
    if (error) return;
    switch (tag) {
      case 127:  // undefined
      case 126:  // null
      case 121:  // false
      case 120:  // true
        return;
      case 125:  // integer (signed varint)
        skip_var_int();
        return;
      case 124:  // f32
        skip_f(4);
        return;
      case 123:  // f64
      case 122:  // bigint
        skip_f(8);
        return;
      case 119: {  // string
        uint64_t n = var_uint();
        skip((size_t)n);
        return;
      }
      case 118: {  // map
        uint64_t n = var_uint();
        for (uint64_t i = 0; i < n && !error; i++) {
          uint64_t k = var_uint();
          skip((size_t)k);
          skip_any();
        }
        return;
      }
      case 117: {  // array
        uint64_t n = var_uint();
        for (uint64_t i = 0; i < n && !error; i++) skip_any();
        return;
      }
      case 116: {  // buffer
        uint64_t n = var_uint();
        skip((size_t)n);
        return;
      }
      default:
        error = true;
        return;
    }
  }

  // skip one Any value counting device decode tokens (one step per
  // scalar or array header; maps/unknown tags report as complex)
  void skip_any_tokens(int64_t* tokens, int64_t* complex_vals) {
    if (pos < len) {
      uint8_t tag = buf[pos];
      if (tag < 116) {
        (*complex_vals)++;
      } else if (tag == 118) {
        // depth-1 object: header token + one token per key + one per
        // scalar value; nested arrays/objects inside stay host-lane
        pos++;  // tag
        uint64_t n = var_uint();
        (*tokens)++;
        for (uint64_t i = 0; i < n && !error; i++) {
          uint64_t klen = var_uint();
          skip((size_t)klen);
          (*tokens)++;
          if (pos < len) {
            uint8_t vt = buf[pos];
            if (vt == 117 || vt == 118 || vt < 116) (*complex_vals)++;
          }
          (*tokens)++;
          skip_any();
        }
        return;
      } else if (tag == 117) {
        // array header consumes one token; children count themselves
        size_t save = pos;
        pos++;  // tag
        uint64_t n = var_uint();
        (*tokens)++;
        for (uint64_t i = 0; i < n && !error; i++)
          skip_any_tokens(tokens, complex_vals);
        (void)save;
        return;
      }
    }
    (*tokens)++;
    skip_any();
  }
};

// UTF-16 code-unit length of a UTF-8 byte span (the Yjs clock unit).
int64_t utf16_units(const uint8_t* p, int64_t n) {
  int64_t units = 0;
  for (int64_t i = 0; i < n;) {
    uint8_t b = p[i];
    if (b < 0x80) {
      units += 1;
      i += 1;
    } else if ((b >> 5) == 0x6) {
      units += 1;
      i += 2;
    } else if ((b >> 4) == 0xE) {
      units += 1;
      i += 3;
    } else if ((b >> 3) == 0x1E) {
      units += 2;  // astral char: surrogate pair
      i += 4;
    } else {
      i += 1;  // invalid byte: resynchronize
    }
  }
  return units;
}

struct Columns {
  // one row per block carrier
  std::vector<int64_t> client, clock, length, kind;
  std::vector<int64_t> origin_client, origin_clock;       // -1 clock if none
  std::vector<int64_t> ror_client, ror_clock;             // -1 if none
  std::vector<int64_t> parent_kind;  // 0=none,1=name,2=id,3=inherit(unset)
  std::vector<int64_t> parent_name_start, parent_name_len;
  std::vector<int64_t> parent_id_client, parent_id_clock;
  std::vector<int64_t> parent_sub_start, parent_sub_len;  // -1 if none
  std::vector<int64_t> content_start, content_len_bytes;  // payload span
  // delete set rows
  std::vector<int64_t> del_client, del_start, del_end;
  // wire-section counts (header values, not emitted-row counts): the
  // device decoder's step budget and header guard must cover sections
  // that emit zero rows (covered Skip runs, empty ds-client sections)
  int64_t n_client_sections = 0;
  int64_t n_ds_sections = 0;
  // item blocks with zero CRDT length, dropped from the columns
  // (update.rs:737-742) but still present on the wire: the device
  // decoder spends parse steps on them, so budgets must count them
  int64_t n_zero_len_blocks = 0;
  // extra device decode steps for value-list content (one per Any/Json
  // value, one per Format key) and the count of Any values the device
  // cannot parse (recursive map/array tags)
  int64_t n_value_steps = 0;
  int64_t n_complex_any = 0;
  int error = 0;
};

// skip one content payload, recording its byte span and returning its
// CRDT length (clock units)
int64_t read_content(Cursor& c, uint8_t info, Columns& out) {
  uint8_t ref = info & 0x0F;
  int64_t span_start = (int64_t)c.pos;
  int64_t crdt_len = 1;
  switch (ref) {
    case CONTENT_DELETED:
      crdt_len = (int64_t)c.var_uint();
      break;
    case CONTENT_JSON: {
      uint64_t n = c.var_uint();
      for (uint64_t i = 0; i < n && !c.error; i++) {
        uint64_t k = c.var_uint();
        c.skip((size_t)k);
      }
      crdt_len = (int64_t)n;
      out.n_value_steps += (int64_t)n;
      break;
    }
    case CONTENT_BINARY: {
      uint64_t n = c.var_uint();
      c.skip((size_t)n);
      crdt_len = 1;
      break;
    }
    case CONTENT_STRING: {
      uint64_t n = c.var_uint();
      const uint8_t* p = c.buf + c.pos;
      c.skip((size_t)n);
      if (!c.error) crdt_len = utf16_units(p, (int64_t)n);
      break;
    }
    case CONTENT_EMBED: {
      uint64_t n = c.var_uint();
      c.skip((size_t)n);
      break;
    }
    case CONTENT_FORMAT: {
      uint64_t k = c.var_uint();
      c.skip((size_t)k);
      uint64_t v = c.var_uint();
      c.skip((size_t)v);
      out.n_value_steps += 1;  // device: key step + value step
      break;
    }
    case CONTENT_TYPE: {
      uint8_t tag = c.u8();
      if (tag == TYPE_XML_ELEMENT || tag == TYPE_XML_HOOK) {
        uint64_t n = c.var_uint();
        c.skip((size_t)n);
      } else if (tag == TYPE_WEAK) {
        uint8_t flags = c.u8();
        c.var_uint();
        c.var_uint();
        if (flags & 1) {
          c.var_uint();
          c.var_uint();
        }
      }
      break;
    }
    case CONTENT_ANY: {
      uint64_t n = c.var_uint();
      int64_t tokens = 0;
      for (uint64_t i = 0; i < n && !c.error; i++) {
        // one device step per scalar/array-header/object-header/key
        // token; non-scalar values INSIDE an object and unknown tags
        // exceed the device model (complex -> host lane)
        c.skip_any_tokens(&tokens, &out.n_complex_any);
      }
      crdt_len = (int64_t)n;
      out.n_value_steps += tokens;
      break;
    }
    case CONTENT_DOC: {
      uint64_t n = c.var_uint();  // guid string
      c.skip((size_t)n);
      c.skip_any();
      break;
    }
    case CONTENT_MOVE: {
      uint64_t flags = c.var_uint();
      c.var_uint();
      c.var_uint();
      if (!(flags & 1)) {
        c.var_uint();
        c.var_uint();
      }
      break;
    }
    default:
      c.error = true;
      break;
  }
  out.content_start.push_back(span_start);
  out.content_len_bytes.push_back((int64_t)c.pos - span_start);
  return crdt_len;
}

Columns* decode_update(const uint8_t* data, size_t n) {
  auto* out = new Columns();
  Cursor c{data, n, 0, false};
  uint64_t n_clients = c.var_uint();
  out->n_client_sections = (int64_t)n_clients;
  for (uint64_t ci = 0; ci < n_clients && !c.error; ci++) {
    uint64_t n_blocks = c.var_uint();
    uint64_t client = c.var_uint();
    uint64_t clock = c.var_uint();
    for (uint64_t bi = 0; bi < n_blocks && !c.error; bi++) {
      uint8_t info = c.u8();
      if (c.error) break;
      if (info == BLOCK_SKIP || info == BLOCK_GC) {
        uint64_t len = c.var_uint();
        out->client.push_back((int64_t)client);
        out->clock.push_back((int64_t)clock);
        out->length.push_back((int64_t)len);
        out->kind.push_back(info == BLOCK_SKIP ? BLOCK_SKIP : BLOCK_GC);
        out->origin_client.push_back(-1);
        out->origin_clock.push_back(-1);
        out->ror_client.push_back(-1);
        out->ror_clock.push_back(-1);
        out->parent_kind.push_back(0);
        out->parent_name_start.push_back(-1);
        out->parent_name_len.push_back(-1);
        out->parent_id_client.push_back(-1);
        out->parent_id_clock.push_back(-1);
        out->parent_sub_start.push_back(-1);
        out->parent_sub_len.push_back(-1);
        out->content_start.push_back(-1);
        out->content_len_bytes.push_back(0);
        clock += len;
        continue;
      }
      bool cant_copy_parent = (info & (HAS_ORIGIN | HAS_RIGHT_ORIGIN)) == 0;
      int64_t oc = -1, ok = -1, rc = -1, rk = -1;
      if (info & HAS_ORIGIN) {
        oc = (int64_t)c.var_uint();
        ok = (int64_t)c.var_uint();
      }
      if (info & HAS_RIGHT_ORIGIN) {
        rc = (int64_t)c.var_uint();
        rk = (int64_t)c.var_uint();
      }
      int64_t pk = 3, pns = -1, pnl = -1, pic = -1, pik = -1, pss = -1,
              psl = -1;
      if (cant_copy_parent) {
        if (c.var_uint() == 1) {
          pk = 1;
          uint64_t len2 = c.var_uint();
          pns = (int64_t)c.pos;
          pnl = (int64_t)len2;
          c.skip((size_t)len2);
        } else {
          pk = 2;
          pic = (int64_t)c.var_uint();
          pik = (int64_t)c.var_uint();
        }
        if (info & HAS_PARENT_SUB) {
          uint64_t len2 = c.var_uint();
          pss = (int64_t)c.pos;
          psl = (int64_t)len2;
          c.skip((size_t)len2);
        }
      }
      out->client.push_back((int64_t)client);
      out->clock.push_back((int64_t)clock);
      out->kind.push_back(info & 0x0F);
      out->origin_client.push_back(oc);
      out->origin_clock.push_back(ok);
      out->ror_client.push_back(rc);
      out->ror_clock.push_back(rk);
      out->parent_kind.push_back(pk);
      out->parent_name_start.push_back(pns);
      out->parent_name_len.push_back(pnl);
      out->parent_id_client.push_back(pic);
      out->parent_id_clock.push_back(pik);
      out->parent_sub_start.push_back(pss);
      out->parent_sub_len.push_back(psl);
      int64_t crdt_len = read_content(c, info, *out);
      if (crdt_len == 0) {
        // historical empty blocks have no effect (parity: update.rs:737-742)
        out->n_zero_len_blocks++;
        out->client.pop_back();
        out->clock.pop_back();
        out->kind.pop_back();
        out->origin_client.pop_back();
        out->origin_clock.pop_back();
        out->ror_client.pop_back();
        out->ror_clock.pop_back();
        out->parent_kind.pop_back();
        out->parent_name_start.pop_back();
        out->parent_name_len.pop_back();
        out->parent_id_client.pop_back();
        out->parent_id_clock.pop_back();
        out->parent_sub_start.pop_back();
        out->parent_sub_len.pop_back();
        out->content_start.pop_back();
        out->content_len_bytes.pop_back();
        continue;
      }
      out->length.push_back(crdt_len);
      clock += (uint64_t)crdt_len;
    }
  }
  // delete set
  if (!c.error) {
    uint64_t ds_clients = c.var_uint();
    out->n_ds_sections = (int64_t)ds_clients;
    for (uint64_t i = 0; i < ds_clients && !c.error; i++) {
      uint64_t client = c.var_uint();
      uint64_t n_ranges = c.var_uint();
      for (uint64_t r = 0; r < n_ranges && !c.error; r++) {
        uint64_t start = c.var_uint();
        uint64_t len2 = c.var_uint();
        out->del_client.push_back((int64_t)client);
        out->del_start.push_back((int64_t)start);
        out->del_end.push_back((int64_t)(start + len2));
      }
    }
  }
  out->error = c.error ? 1 : 0;
  return out;
}

}  // namespace

extern "C" {

void* ytpu_decode_update_v1(const uint8_t* data, size_t len) {
  return decode_update(data, len);
}

int ytpu_columns_error(void* handle) {
  return static_cast<Columns*>(handle)->error;
}

size_t ytpu_columns_n_blocks(void* handle) {
  return static_cast<Columns*>(handle)->client.size();
}

size_t ytpu_columns_n_dels(void* handle) {
  return static_cast<Columns*>(handle)->del_client.size();
}

size_t ytpu_columns_n_client_sections(void* handle) {
  return (size_t)static_cast<Columns*>(handle)->n_client_sections;
}

size_t ytpu_columns_n_ds_sections(void* handle) {
  return (size_t)static_cast<Columns*>(handle)->n_ds_sections;
}

size_t ytpu_columns_n_zero_len_blocks(void* handle) {
  return (size_t)static_cast<Columns*>(handle)->n_zero_len_blocks;
}

size_t ytpu_columns_n_value_steps(void* handle) {
  return (size_t)static_cast<Columns*>(handle)->n_value_steps;
}

size_t ytpu_columns_n_complex_any(void* handle) {
  return (size_t)static_cast<Columns*>(handle)->n_complex_any;
}

// column accessors: return pointers into the Columns arrays
#define COLUMN_ACCESSOR(name)                              \
  const int64_t* ytpu_col_##name(void* handle) {           \
    return static_cast<Columns*>(handle)->name.data();     \
  }

COLUMN_ACCESSOR(client)
COLUMN_ACCESSOR(clock)
COLUMN_ACCESSOR(length)
COLUMN_ACCESSOR(kind)
COLUMN_ACCESSOR(origin_client)
COLUMN_ACCESSOR(origin_clock)
COLUMN_ACCESSOR(ror_client)
COLUMN_ACCESSOR(ror_clock)
COLUMN_ACCESSOR(parent_kind)
COLUMN_ACCESSOR(parent_name_start)
COLUMN_ACCESSOR(parent_name_len)
COLUMN_ACCESSOR(parent_id_client)
COLUMN_ACCESSOR(parent_id_clock)
COLUMN_ACCESSOR(parent_sub_start)
COLUMN_ACCESSOR(parent_sub_len)
COLUMN_ACCESSOR(content_start)
COLUMN_ACCESSOR(content_len_bytes)
COLUMN_ACCESSOR(del_client)
COLUMN_ACCESSOR(del_start)
COLUMN_ACCESSOR(del_end)

void ytpu_columns_free(void* handle) { delete static_cast<Columns*>(handle); }

// standalone batch varint decode (microbenchmark / utility)
size_t ytpu_decode_var_uints(const uint8_t* data, size_t len, uint64_t* out,
                             size_t max_out) {
  Cursor c{data, len, 0, false};
  size_t n = 0;
  while (c.pos < c.len && n < max_out) {
    out[n++] = c.var_uint();
    if (c.error) return n - 1;
  }
  return n;
}
}
