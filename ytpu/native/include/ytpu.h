/* libytpu — C ABI for the ytpu CRDT framework.
 *
 * Function-shape parity target: the reference's C FFI layer
 * (/root/reference/yffi/src/lib.rs, 192 extern "C" fns; generated header
 * tests-ffi/include/libyrs.h). Same names and call shapes wherever the
 * engine supports the feature, so the reference's tests-ffi doctest suite
 * ports mechanically. Tag constants match yffi/src/lib.rs:32-100.
 *
 * Differences from libyrs.h (documented, deliberate):
 *  - YInput supports yffi's recursive form (value.values / value.map with
 *    a top-level len, built by yinput_json_array/yinput_json_map/
 *    yinput_yarray/yinput_ymap) plus `*_str` extension constructors that
 *    take JSON strings for convenience. MIGRATION NOTE: the `*_str` forms
 *    mark themselves with len = UINT32_MAX; a hand-built array/map YInput
 *    with len = 0 and a non-NULL payload pointer is rejected as ambiguous
 *    (it could be either an empty recursive array or a mis-built
 *    JSON-string form). Pass NULL for empty arrays/maps, or build
 *    string-form inputs with yinput_json_array_str / yinput_json_map_str /
 *    yinput_yarray_str / yinput_ymap_str.
 *  - YOutput is an opaque handle with youtput_* accessors instead of a
 *    by-value tagged union.
 *  - Binary results come back as YBinary {data,len} released with
 *    ybinary_destroy; strings via ystring_destroy.
 *  - On error, fallible functions return 0/NULL and ytpu_last_error()
 *    carries a message (thread-local, describing the most recent call).
 *  - Read transactions may coexist (any number per doc) but reject writes;
 *    write transactions are exclusive, like the engine's.
 */
#ifndef YTPU_H
#define YTPU_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- opaque handles ---------------------------------------------------- */
typedef struct YDoc YDoc;
typedef struct Branch Branch;
typedef struct YTransaction YTransaction;
typedef struct YOutput YOutput;
typedef struct YUndoManager YUndoManager;
typedef struct YStickyIndex YStickyIndex;
typedef struct YSubscription YSubscription;
typedef struct YArrayIter YArrayIter;
typedef struct YMapIter YMapIter;
typedef struct YXmlTreeWalker YXmlTreeWalker;

/* ---- value tags (yffi/src/lib.rs:32-100) -------------------------------- */
#define Y_JSON_BOOL (-8)
#define Y_JSON_NUM (-7)
#define Y_JSON_INT (-6)
#define Y_JSON_STR (-5)
#define Y_JSON_BUF (-4)
#define Y_JSON_ARR (-3)
#define Y_JSON_MAP (-2)
#define Y_JSON_NULL (-1)
#define Y_JSON_UNDEF 0
#define Y_ARRAY 1
#define Y_MAP 2
#define Y_TEXT 3
#define Y_XML_ELEM 4
#define Y_XML_TEXT 5
#define Y_XML_FRAG 6
#define Y_DOC 7
#define Y_WEAK_LINK 8

#define Y_OFFSET_BYTES 0
#define Y_OFFSET_UTF16 1

#define Y_ASSOC_BEFORE (-1)
#define Y_ASSOC_AFTER 0

/* ---- event tags (libyrs.h: Y_KIND_* / Y_EVENT_*) ------------------------ */
#define Y_KIND_UNDO 0
#define Y_KIND_REDO 1
#define Y_EVENT_PATH_KEY 1
#define Y_EVENT_PATH_INDEX 2
#define Y_EVENT_CHANGE_ADD 1
#define Y_EVENT_CHANGE_DELETE 2
#define Y_EVENT_CHANGE_RETAIN 3
#define Y_EVENT_KEY_CHANGE_ADD 4
#define Y_EVENT_KEY_CHANGE_DELETE 5
#define Y_EVENT_KEY_CHANGE_UPDATE 6

/* ---- plain data -------------------------------------------------------- */
typedef struct YOptions {
  uint64_t id;               /* 0 = random client id */
  const char *guid;          /* NULL = random v4 uuid */
  const char *collection_id; /* NULL = none */
  uint8_t encoding;          /* Y_OFFSET_BYTES | Y_OFFSET_UTF16 */
  uint8_t skip_gc;
  uint8_t auto_load;
  uint8_t should_load;
} YOptions;

typedef struct YBinary {
  uint8_t *data; /* NULL on error */
  uint64_t len;
} YBinary;

typedef struct YInput {
  int8_t tag; /* Y_JSON_* scalar, or Y_TEXT/Y_ARRAY/Y_MAP/Y_XML_* prelim */
  /* element count for recursive ARR/MAP forms; 1 for scalars;
   * UINT32_MAX marks the `*_str` JSON-string forms */
  uint32_t len;
  union {
    uint8_t flag;    /* Y_JSON_BOOL */
    double num;      /* Y_JSON_NUM */
    int64_t integer; /* Y_JSON_INT */
    const char *str; /* Y_JSON_STR; JSON/init payload for `*_str` forms */
    struct {
      const uint8_t *data;
      uint64_t len;
    } buf;                       /* Y_JSON_BUF */
    struct YInput *values;       /* Y_JSON_ARR / Y_ARRAY (recursive, `len`
                                    elements; yffi contract: borrowed) */
    struct {
      char **keys;               /* `len` keys... */
      struct YInput *values;     /* ...paired with `len` nested inputs */
    } map;                       /* Y_JSON_MAP / Y_MAP (recursive) */
    struct YDoc *doc;            /* Y_DOC (nested subdocument) */
    const struct YWeak *weak;    /* Y_WEAK_LINK (ytext_quote/ymap_link) */
  } value;
} YInput;

typedef struct YMapEntry {
  char *key;      /* released with the entry */
  YOutput *value; /* released with the entry */
} YMapEntry;

/* ---- events (yffi: YEvent family) ----------------------------------------
 * An event handle is valid ONLY for the duration of the observer callback
 * (same contract as yffi). All typed event aliases share one opaque struct;
 * accessors check nothing — calling a map accessor on a text event simply
 * yields an empty result. */
typedef struct YEvent YEvent;
typedef YEvent YTextEvent;
typedef YEvent YArrayEvent;
typedef YEvent YMapEvent;
typedef YEvent YXmlEvent;
typedef YEvent YXmlTextEvent;
typedef YEvent YWeakLinkEvent;

typedef struct YPathSegment {
  char tag; /* Y_EVENT_PATH_KEY | Y_EVENT_PATH_INDEX */
  union {
    char *key;      /* owned by the segment array */
    uint32_t index;
  } value;
} YPathSegment;

/* Sequence change (yffi YEventChange). Unlike libyrs.h, `values` is an
 * array of YOutput handles (our YOutput is opaque), released with the
 * delta. */
typedef struct YEventChange {
  char tag; /* Y_EVENT_CHANGE_* */
  uint32_t len;
  YOutput **values; /* ADD only; len entries */
} YEventChange;

/* Text delta (yffi YDelta). `insert` is a single YOutput (string run or
 * one embed); attribute values ride as JSON strings. */
typedef struct YDeltaAttr {
  char *key;
  char *value_json;
} YDeltaAttr;

typedef struct YDelta {
  char tag; /* Y_EVENT_CHANGE_* */
  uint32_t len;
  YOutput *insert; /* ADD only */
  uint32_t attributes_len;
  YDeltaAttr *attributes;
} YDelta;

/* Map / attribute change (yffi YEventKeyChange). */
typedef struct YEventKeyChange {
  char *key;
  char tag; /* Y_EVENT_KEY_CHANGE_* */
  YOutput *old_value; /* NULL for ADD */
  YOutput *new_value; /* NULL for DELETE */
} YEventKeyChange;

/* ---- weak links (yffi: Weak / YWeakIter) -------------------------------- */
typedef struct YWeak YWeak; /* a prelim link, input for yinput_weak */
typedef struct YWeakIter YWeakIter;

/* ---- xml attributes (yffi: YXmlAttr / YXmlAttrIter) --------------------- */
typedef struct YXmlAttr {
  char *name;
  char *value;
} YXmlAttr;
typedef struct YXmlAttrIter YXmlAttrIter;

/* ---- text chunks (yffi: YChunk) ----------------------------------------- */
typedef struct YChunk {
  YOutput *data; /* string run, embed or nested type */
  uint32_t fmt_len;
  YMapEntry *fmt; /* formatting attributes */
} YChunk;

/* ---- delete sets / pending updates (yffi shapes) ------------------------ */
typedef struct YIdRange {
  uint32_t start;
  uint32_t len;
} YIdRange;

typedef struct YIdRangeSeq {
  uint32_t len;
  YIdRange *seq;
} YIdRangeSeq;

typedef struct YDeleteSet {
  uint32_t entries_len;
  uint64_t *client_ids;
  YIdRangeSeq *ranges;
} YDeleteSet;

/* Unapplied (stashed) update data. `missing` is a lib0-v1 state vector
 * describing the clocks the stash is waiting for (yffi YPendingUpdate,
 * which carries the same two payloads). */
typedef struct YPendingUpdate {
  YBinary missing;
  YBinary update_v1;
} YPendingUpdate;

/* ---- subdocs event (yffi YSubdocsEvent) --------------------------------- */
typedef struct YSubdocsEvent {
  uint32_t added_len;
  uint32_t removed_len;
  uint32_t loaded_len;
  YDoc **added;   /* handles valid only during the callback */
  YDoc **removed;
  YDoc **loaded;
} YSubdocsEvent;

/* ---- undo event (yffi YUndoEvent) --------------------------------------- */
typedef struct YUndoEvent {
  char kind; /* Y_KIND_UNDO | Y_KIND_REDO */
  const char *origin; /* valid during callback */
  uint32_t origin_len;
  /* Round-trips between observe_added and observe_popped callbacks for the
   * same stack item; starts NULL, user-managed (yffi contract). */
  void *meta;
} YUndoEvent;

/* ---- logical branch id (yffi YBranchId) --------------------------------- */
typedef struct YBranchId {
  /* >= 0: nested type, value is the client id (use .clock);
   * < 0: root type, -value is the name length (use .name). */
  int64_t client_or_len;
  union {
    uint32_t clock;
    const uint8_t *name; /* NOT nul-terminated; length = -client_or_len */
  } variant;
} YBranchId;

/* ---- runtime / errors --------------------------------------------------- */
/* Last error message for this thread, or NULL. Owned by the library. */
const char *ytpu_last_error(void);
void ystring_destroy(char *str);
void ybinary_destroy(YBinary bin);

/* ---- document lifecycle (yffi: ydoc_*) ---------------------------------- */
YDoc *ydoc_new(void);
YDoc *ydoc_new_with_options(YOptions options);
YDoc *ydoc_clone(YDoc *doc);
void ydoc_destroy(YDoc *doc);
uint64_t ydoc_id(YDoc *doc);
char *ydoc_guid(YDoc *doc);
char *ydoc_collection_id(YDoc *doc); /* NULL if unset */
uint8_t ydoc_should_load(YDoc *doc);
uint8_t ydoc_auto_load(YDoc *doc);
void ydoc_load(YDoc *doc);

/* ---- transactions (yffi: ydoc_*_transaction / ytransaction_*) ----------- */
YTransaction *ydoc_read_transaction(YDoc *doc);
YTransaction *ydoc_write_transaction(YDoc *doc, uint32_t origin_len,
                                     const char *origin);
void ytransaction_commit(YTransaction *txn);
uint8_t ytransaction_writeable(YTransaction *txn);

YBinary ytransaction_state_vector_v1(YTransaction *txn);
YBinary ytransaction_state_diff_v1(YTransaction *txn, const uint8_t *sv,
                                   uint32_t sv_len);
YBinary ytransaction_state_diff_v2(YTransaction *txn, const uint8_t *sv,
                                   uint32_t sv_len);
/* 0 on success, nonzero error code otherwise */
uint8_t ytransaction_apply(YTransaction *txn, const uint8_t *diff,
                           uint32_t diff_len);
uint8_t ytransaction_apply_v2(YTransaction *txn, const uint8_t *diff,
                              uint32_t diff_len);
YBinary ytransaction_snapshot(YTransaction *txn);
YBinary ytransaction_encode_state_from_snapshot_v1(YTransaction *txn,
                                                   const uint8_t *snapshot,
                                                   uint32_t snapshot_len);
YBinary ytransaction_encode_state_from_snapshot_v2(YTransaction *txn,
                                                   const uint8_t *snapshot,
                                                   uint32_t snapshot_len);
char *yupdate_debug_v1(const uint8_t *update, uint32_t update_len);
char *yupdate_debug_v2(const uint8_t *update, uint32_t update_len);

/* ---- root types --------------------------------------------------------- */
Branch *ytext(YDoc *doc, const char *name);
Branch *yarray(YDoc *doc, const char *name);
Branch *ymap(YDoc *doc, const char *name);
Branch *yxmlfragment(YDoc *doc, const char *name);
Branch *yxmltext(YDoc *doc, const char *name);
int8_t ytype_kind(Branch *branch);
uint8_t ybranch_alive(Branch *branch);
void ybranch_destroy(Branch *branch); /* releases the handle, not the type */

/* ---- YOutput ------------------------------------------------------------ */
int8_t youtput_tag(const YOutput *val);
char *youtput_read_string(const YOutput *val); /* NULL if not a string */
uint8_t youtput_read_bool(const YOutput *val);
double youtput_read_float(const YOutput *val);
int64_t youtput_read_long(const YOutput *val);
YBinary youtput_read_binary(const YOutput *val);
char *youtput_json(const YOutput *val); /* any value as JSON */
Branch *youtput_read_yarray(YOutput *val);
Branch *youtput_read_ymap(YOutput *val);
Branch *youtput_read_ytext(YOutput *val);
Branch *youtput_read_yxmlelem(YOutput *val);
Branch *youtput_read_yxmltext(YOutput *val);
YDoc *youtput_read_ydoc(YOutput *val);
void youtput_destroy(YOutput *val);

/* ---- by-value YOutput (yffi ABI-shape parity) ---------------------------
 * The opaque-handle accessors above are the primary surface; this by-value
 * form mirrors libyrs.h's `YOutput` tagged union (tag / len /
 * YOutputContent) for consumers written against that shape.
 * `youtput_unwrap` materializes a handle into the union — deep: array and
 * map contents become malloc'd element buffers of further by-value cells —
 * and `youtput_value_destroy` releases the whole tree. Shared-type / doc
 * leaves come back as the same opaque Branch* / YDoc* handles used by the
 * rest of this API (release with ybranch_destroy / ydoc_destroy; the
 * destroy helper does this for untouched leaves).
 * `len` semantics match libyrs.h: buffer byte length for Y_JSON_BUF,
 * element count for Y_JSON_ARR / Y_JSON_MAP, 0 for null/undefined,
 * otherwise 1. */
typedef struct YMapEntryValue YMapEntryValue;
typedef struct YOutputValue {
  int8_t tag;
  uint32_t len;
  union YOutputValueContent {
    uint8_t flag;
    double num;
    int64_t integer;
    char *str;          /* malloc'd, NUL-terminated */
    uint8_t *buf;       /* malloc'd, len bytes */
    struct YOutputValue *array;
    YMapEntryValue *map;
    Branch *y_type;
    YDoc *y_doc;
  } value;
} YOutputValue;
struct YMapEntryValue {
  char *key; /* malloc'd, NUL-terminated */
  YOutputValue value;
};
YOutputValue youtput_unwrap(const YOutput *val);
void youtput_value_destroy(YOutputValue val);

/* ---- YText (yffi: ytext_*) ---------------------------------------------- */
uint32_t ytext_len(Branch *txt, YTransaction *txn);
char *ytext_string(Branch *txt, YTransaction *txn);
void ytext_insert(Branch *txt, YTransaction *txn, uint32_t index,
                  const char *value, const char *attrs_json);
void ytext_insert_embed(Branch *txt, YTransaction *txn, uint32_t index,
                        const YInput *content, const char *attrs_json);
void ytext_format(Branch *txt, YTransaction *txn, uint32_t index,
                  uint32_t len, const char *attrs_json);
void ytext_remove_range(Branch *txt, YTransaction *txn, uint32_t index,
                        uint32_t len);

/* ---- YArray (yffi: yarray_*) -------------------------------------------- */
uint32_t yarray_len(Branch *array);
YOutput *yarray_get(Branch *array, YTransaction *txn, uint32_t index);
void yarray_insert_range(Branch *array, YTransaction *txn, uint32_t index,
                         const YInput *items, uint32_t items_len);
void yarray_remove_range(Branch *array, YTransaction *txn, uint32_t index,
                         uint32_t len);
void yarray_move(Branch *array, YTransaction *txn, uint32_t source,
                 uint32_t target);
YArrayIter *yarray_iter(Branch *array, YTransaction *txn);
YOutput *yarray_iter_next(YArrayIter *iter); /* NULL at end */
void yarray_iter_destroy(YArrayIter *iter);

/* ---- YMap (yffi: ymap_*) ------------------------------------------------ */
uint32_t ymap_len(Branch *map, YTransaction *txn);
void ymap_insert(Branch *map, YTransaction *txn, const char *key,
                 const YInput *value);
uint8_t ymap_remove(Branch *map, YTransaction *txn, const char *key);
YOutput *ymap_get(Branch *map, YTransaction *txn, const char *key);
void ymap_remove_all(Branch *map, YTransaction *txn);
YMapIter *ymap_iter(Branch *map, YTransaction *txn);
YMapEntry *ymap_iter_next(YMapIter *iter); /* NULL at end */
void ymap_entry_destroy(YMapEntry *entry);
void ymap_iter_destroy(YMapIter *iter);

/* ---- YXml (yffi: yxmlelem_* / yxmltext_* / yxml_*) ---------------------- */
char *yxmlelem_tag(Branch *xml);
char *yxmlelem_string(Branch *xml, YTransaction *txn);
void yxmlelem_insert_attr(Branch *xml, YTransaction *txn,
                          const char *attr_name, const char *attr_value);
void yxmlelem_remove_attr(Branch *xml, YTransaction *txn,
                          const char *attr_name);
char *yxmlelem_get_attr(Branch *xml, YTransaction *txn,
                        const char *attr_name); /* NULL if missing */
uint32_t yxmlelem_child_len(Branch *xml, YTransaction *txn);
Branch *yxmlelem_insert_elem(Branch *xml, YTransaction *txn, uint32_t index,
                             const char *name);
Branch *yxmlelem_insert_text(Branch *xml, YTransaction *txn, uint32_t index);
void yxmlelem_remove_range(Branch *xml, YTransaction *txn, uint32_t index,
                           uint32_t len);
YOutput *yxmlelem_get(Branch *xml, YTransaction *txn, uint32_t index);
YOutput *yxmlelem_first_child(Branch *xml);
YOutput *yxml_next_sibling(Branch *xml, YTransaction *txn);
YOutput *yxml_prev_sibling(Branch *xml, YTransaction *txn);
YXmlTreeWalker *yxmlelem_tree_walker(Branch *xml, YTransaction *txn);
YOutput *yxmlelem_tree_walker_next(YXmlTreeWalker *walker);
void yxmlelem_tree_walker_destroy(YXmlTreeWalker *walker);

uint32_t yxmltext_len(Branch *xml, YTransaction *txn);
char *yxmltext_string(Branch *xml, YTransaction *txn);
void yxmltext_insert(Branch *xml, YTransaction *txn, uint32_t index,
                     const char *str, const char *attrs_json);
void yxmltext_remove_range(Branch *xml, YTransaction *txn, uint32_t index,
                           uint32_t len);
void yxmltext_format(Branch *xml, YTransaction *txn, uint32_t index,
                     uint32_t len, const char *attrs_json);
void yxmltext_insert_attr(Branch *xml, YTransaction *txn,
                          const char *attr_name, const char *attr_value);
char *yxmltext_get_attr(Branch *xml, YTransaction *txn,
                        const char *attr_name);

/* ---- UndoManager (yffi: yundo_manager_*) -------------------------------- */
typedef struct YUndoManagerOptions {
  int32_t capture_timeout_millis;
} YUndoManagerOptions;
YUndoManager *yundo_manager(YDoc *doc, const YUndoManagerOptions *options);
void yundo_manager_destroy(YUndoManager *mgr);
void yundo_manager_add_scope(YUndoManager *mgr, Branch *ytype);
void yundo_manager_add_origin(YUndoManager *mgr, uint32_t origin_len,
                              const char *origin);
void yundo_manager_remove_origin(YUndoManager *mgr, uint32_t origin_len,
                                 const char *origin);
uint8_t yundo_manager_undo(YUndoManager *mgr);
uint8_t yundo_manager_redo(YUndoManager *mgr);
uint8_t yundo_manager_can_undo(YUndoManager *mgr);
uint8_t yundo_manager_can_redo(YUndoManager *mgr);
void yundo_manager_clear(YUndoManager *mgr);
void yundo_manager_stop(YUndoManager *mgr);

/* ---- StickyIndex (yffi: ysticky_index_*) -------------------------------- */
YStickyIndex *ysticky_index_from_index(Branch *ytype, YTransaction *txn,
                                       uint32_t index, int8_t assoc);
void ysticky_index_destroy(YStickyIndex *pos);
int8_t ysticky_index_assoc(YStickyIndex *pos);
YBinary ysticky_index_encode(YStickyIndex *pos);
YStickyIndex *ysticky_index_decode(const uint8_t *bin, uint32_t len);
/* writes the resolved index to *out_index; 0 if position vanished */
uint8_t ysticky_index_read(YStickyIndex *pos, YTransaction *txn,
                           uint32_t *out_index);

/* ---- observers (yffi: ydoc_observe_*) ----------------------------------- */
typedef void (*ytpu_observe_cb)(void *state, uint32_t len,
                                const uint8_t *bytes);
YSubscription *ydoc_observe_updates_v1(YDoc *doc, void *state,
                                       ytpu_observe_cb cb);
YSubscription *ydoc_observe_updates_v2(YDoc *doc, void *state,
                                       ytpu_observe_cb cb);
/* after-transaction: cb invoked with len=0 */
YSubscription *ydoc_observe_after_transaction(YDoc *doc, void *state,
                                              ytpu_observe_cb cb);
void yunobserve(YSubscription *subscription);

/* ---- default options (yffi: yoptions) ----------------------------------- */
YOptions yoptions(void);

/* ---- YInput constructors (yffi: yinput_*) --------------------------------
 * Pure struct builders; no allocation, no ownership taken (yffi contract).
 * The array/map constructors take recursive YInput element arrays (borrowed
 * for the duration of the call that consumes them), exactly like yffi; the
 * `*_str` extensions accept JSON strings instead. */
YInput yinput_null(void);
YInput yinput_undefined(void);
YInput yinput_bool(uint8_t flag);
YInput yinput_float(double num);
YInput yinput_long(int64_t integer);
YInput yinput_string(const char *str);
YInput yinput_binary(const uint8_t *buf, uint32_t len);
YInput yinput_json_array(YInput *values, uint32_t len);
YInput yinput_json_map(char **keys, YInput *values, uint32_t len);
YInput yinput_ytext(const char *init);
YInput yinput_yarray(YInput *values, uint32_t len);
YInput yinput_ymap(char **keys, YInput *values, uint32_t len);
YInput yinput_yxmlelem(const char *name);
YInput yinput_yxmltext(const char *init);
YInput yinput_ydoc(YDoc *doc);
YInput yinput_weak(const YWeak *weak);
/* extensions: JSON-string forms of the four constructors above */
YInput yinput_json_array_str(const char *json);
YInput yinput_json_map_str(const char *json);
YInput yinput_yarray_str(const char *init_json);
YInput yinput_ymap_str(const char *init_json);

/* ---- YOutput collection readers ------------------------------------------
 * For a Y_JSON_ARR output: array of new YOutput handles (each released with
 * youtput_destroy; the array itself with free()). For a Y_JSON_MAP output:
 * array of YMapEntry pointers (each released with ymap_entry_destroy; the
 * array with free()). */
YOutput **youtput_read_json_array(YOutput *val, uint32_t *len);
YMapEntry **youtput_read_json_map(YOutput *val, uint32_t *len);
Branch *youtput_read_yweak(YOutput *val);

/* ---- doc clear / subdocs (yffi: ydoc_clear / ytransaction_subdocs) ------- */
/* Destroys the document's observer state, firing clear observers. The txn
 * parameter mirrors yffi's shape and may be NULL. */
void ydoc_clear(YDoc *doc, YTransaction *parent_txn);
YSubscription *ydoc_observe_clear(YDoc *doc, void *state,
                                  void (*cb)(void *, YDoc *));
YSubscription *ydoc_observe_subdocs(YDoc *doc, void *state,
                                    void (*cb)(void *,
                                               const YSubdocsEvent *));
/* Array of subdoc handles; each must be ydoc_destroy'd, array free()'d. */
YDoc **ytransaction_subdocs(YTransaction *txn, uint32_t *len);

/* ---- pending introspection (yffi: ytransaction_pending_*) ---------------- */
YPendingUpdate *ytransaction_pending_update(YTransaction *txn);
void ypending_update_destroy(YPendingUpdate *update);
YDeleteSet *ytransaction_pending_ds(YTransaction *txn);
void ydelete_set_destroy(YDeleteSet *ds);

/* ---- logical branch ids (yffi: ybranch_id / ybranch_get / ytype_get) -----
 * For root types, id.variant.name is an owned nul-terminated copy —
 * release with ystring_destroy((char *)id.variant.name). */
YBranchId ybranch_id(Branch *branch);
Branch *ybranch_get(const YBranchId *branch_id, YTransaction *txn);
/* Root-type lookup WITHOUT creating; NULL if the name was never defined. */
Branch *ytype_get(YTransaction *txn, const char *name);

/* ---- per-type event observers (yffi: y*_observe / yobserve_deep) --------- */
YSubscription *ytext_observe(Branch *txt, void *state,
                             void (*cb)(void *, const YTextEvent *));
YSubscription *yarray_observe(Branch *array, void *state,
                              void (*cb)(void *, const YArrayEvent *));
YSubscription *ymap_observe(Branch *map, void *state,
                            void (*cb)(void *, const YMapEvent *));
YSubscription *yxmlelem_observe(Branch *xml, void *state,
                                void (*cb)(void *, const YXmlEvent *));
YSubscription *yxmltext_observe(Branch *xml, void *state,
                                void (*cb)(void *, const YXmlTextEvent *));
YSubscription *yweak_observe(Branch *weak, void *state,
                             void (*cb)(void *, const YWeakLinkEvent *));
/* Deep observer: events arrive as an array of YEvent pointers (libyrs.h
 * passes YEvent structs by value; ours are opaque, hence the indirection). */
YSubscription *yobserve_deep(Branch *ytype, void *state,
                             void (*cb)(void *, uint32_t,
                                        const YEvent *const *));
/* Which shared type emitted this event: Y_TEXT/Y_ARRAY/Y_MAP/Y_XML_*. */
int8_t yevent_kind(const YEvent *e);

/* ---- event accessors (valid only inside the observer callback) ----------- */
Branch *ytext_event_target(const YTextEvent *e);
Branch *yarray_event_target(const YArrayEvent *e);
Branch *ymap_event_target(const YMapEvent *e);
Branch *yxmlelem_event_target(const YXmlEvent *e);
Branch *yxmltext_event_target(const YXmlTextEvent *e);

YPathSegment *ytext_event_path(const YTextEvent *e, uint32_t *len);
YPathSegment *yarray_event_path(const YArrayEvent *e, uint32_t *len);
YPathSegment *ymap_event_path(const YMapEvent *e, uint32_t *len);
YPathSegment *yxmlelem_event_path(const YXmlEvent *e, uint32_t *len);
YPathSegment *yxmltext_event_path(const YXmlTextEvent *e, uint32_t *len);
void ypath_destroy(YPathSegment *path, uint32_t len);

YDelta *ytext_event_delta(const YTextEvent *e, uint32_t *len);
YDelta *yxmltext_event_delta(const YXmlTextEvent *e, uint32_t *len);
void ytext_delta_destroy(YDelta *delta, uint32_t len);

YEventChange *yarray_event_delta(const YArrayEvent *e, uint32_t *len);
YEventChange *yxmlelem_event_delta(const YXmlEvent *e, uint32_t *len);
void yevent_delta_destroy(YEventChange *delta, uint32_t len);

YEventKeyChange *ymap_event_keys(const YMapEvent *e, uint32_t *len);
YEventKeyChange *yxmlelem_event_keys(const YXmlEvent *e, uint32_t *len);
YEventKeyChange *yxmltext_event_keys(const YXmlTextEvent *e, uint32_t *len);
void yevent_keys_destroy(YEventKeyChange *keys, uint32_t len);

/* ---- weak links / quotations (yffi: y*_quote / ymap_link / yweak_*) ------ */
YWeak *ytext_quote(Branch *text, YTransaction *txn, uint32_t start_index,
                   uint32_t end_index, int8_t start_exclusive,
                   int8_t end_exclusive);
YWeak *yarray_quote(Branch *array, YTransaction *txn, uint32_t start_index,
                    uint32_t end_index, int8_t start_exclusive,
                    int8_t end_exclusive);
YWeak *ymap_link(Branch *map, YTransaction *txn, const char *key);
void yweak_destroy(YWeak *weak);
YOutput *yweak_deref(Branch *map_link, YTransaction *txn);
YWeakIter *yweak_iter(Branch *array_link, YTransaction *txn);
YOutput *yweak_iter_next(YWeakIter *iter); /* NULL at end */
void yweak_iter_destroy(YWeakIter *iter);
char *yweak_string(Branch *text_link, YTransaction *txn);
char *yweak_xml_string(Branch *xml_text_link, YTransaction *txn);

/* ---- text chunks (yffi: ytext_chunks) ------------------------------------ */
YChunk *ytext_chunks(Branch *txt, YTransaction *txn, uint32_t *chunks_len);
void ychunks_destroy(YChunk *chunks, uint32_t len);

/* ---- xml attribute iteration / tree (yffi: yxml*_attr_iter &c.) ---------- */
YXmlAttrIter *yxmlelem_attr_iter(Branch *xml, YTransaction *txn);
YXmlAttrIter *yxmltext_attr_iter(Branch *xml, YTransaction *txn);
YXmlAttr *yxmlattr_iter_next(YXmlAttrIter *iterator); /* NULL at end */
void yxmlattr_destroy(YXmlAttr *attr);
void yxmlattr_iter_destroy(YXmlAttrIter *iterator);
Branch *yxmlelem_parent(Branch *xml); /* NULL at root fragment */
void yxmltext_remove_attr(Branch *xml, YTransaction *txn,
                          const char *attr_name);
void yxmltext_insert_embed(Branch *xml, YTransaction *txn, uint32_t index,
                           const YInput *content, const char *attrs_json);

/* ---- undo observers (yffi: yundo_manager_observe_*) ----------------------
 * The event's `meta` pointer round-trips between added/popped callbacks of
 * the same stack item (yffi contract): write it in one callback, read it in
 * the other. */
YSubscription *yundo_manager_observe_added(YUndoManager *mgr, void *state,
                                           void (*cb)(void *, YUndoEvent *));
YSubscription *yundo_manager_observe_popped(YUndoManager *mgr, void *state,
                                            void (*cb)(void *, YUndoEvent *));

#ifdef __cplusplus
} /* extern "C" */
#endif
#endif /* YTPU_H */
