/* libytpu — C ABI for the ytpu CRDT framework.
 *
 * Function-shape parity target: the reference's C FFI layer
 * (/root/reference/yffi/src/lib.rs, 192 extern "C" fns; generated header
 * tests-ffi/include/libyrs.h). Same names and call shapes wherever the
 * engine supports the feature, so the reference's tests-ffi doctest suite
 * ports mechanically. Tag constants match yffi/src/lib.rs:32-100.
 *
 * Differences from libyrs.h (documented, deliberate):
 *  - YInput is a flat tagged scalar; JSON arrays/maps and nested-type
 *    initializers are passed as JSON strings instead of recursive YInput
 *    arrays (value.str).
 *  - YOutput is an opaque handle with youtput_* accessors instead of a
 *    by-value tagged union.
 *  - Binary results come back as YBinary {data,len} released with
 *    ybinary_destroy; strings via ystring_destroy.
 *  - On error, fallible functions return 0/NULL and ytpu_last_error()
 *    carries a message (thread-local, describing the most recent call).
 *  - Read transactions may coexist (any number per doc) but reject writes;
 *    write transactions are exclusive, like the engine's.
 */
#ifndef YTPU_H
#define YTPU_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---- opaque handles ---------------------------------------------------- */
typedef struct YDoc YDoc;
typedef struct Branch Branch;
typedef struct YTransaction YTransaction;
typedef struct YOutput YOutput;
typedef struct YUndoManager YUndoManager;
typedef struct YStickyIndex YStickyIndex;
typedef struct YSubscription YSubscription;
typedef struct YArrayIter YArrayIter;
typedef struct YMapIter YMapIter;
typedef struct YXmlTreeWalker YXmlTreeWalker;

/* ---- value tags (yffi/src/lib.rs:32-100) -------------------------------- */
#define Y_JSON_BOOL (-8)
#define Y_JSON_NUM (-7)
#define Y_JSON_INT (-6)
#define Y_JSON_STR (-5)
#define Y_JSON_BUF (-4)
#define Y_JSON_ARR (-3)
#define Y_JSON_MAP (-2)
#define Y_JSON_NULL (-1)
#define Y_JSON_UNDEF 0
#define Y_ARRAY 1
#define Y_MAP 2
#define Y_TEXT 3
#define Y_XML_ELEM 4
#define Y_XML_TEXT 5
#define Y_XML_FRAG 6
#define Y_DOC 7
#define Y_WEAK_LINK 8

#define Y_OFFSET_BYTES 0
#define Y_OFFSET_UTF16 1

#define Y_ASSOC_BEFORE (-1)
#define Y_ASSOC_AFTER 0

/* ---- plain data -------------------------------------------------------- */
typedef struct YOptions {
  uint64_t id;               /* 0 = random client id */
  const char *guid;          /* NULL = random v4 uuid */
  const char *collection_id; /* NULL = none */
  uint8_t encoding;          /* Y_OFFSET_BYTES | Y_OFFSET_UTF16 */
  uint8_t skip_gc;
  uint8_t auto_load;
  uint8_t should_load;
} YOptions;

typedef struct YBinary {
  uint8_t *data; /* NULL on error */
  uint64_t len;
} YBinary;

typedef struct YInput {
  int8_t tag; /* Y_JSON_* scalar, or Y_TEXT/Y_ARRAY/Y_MAP/Y_XML_* prelim */
  union {
    uint8_t flag;    /* Y_JSON_BOOL */
    double num;      /* Y_JSON_NUM */
    int64_t integer; /* Y_JSON_INT */
    const char *str; /* Y_JSON_STR; JSON for ARR/MAP; init for prelims */
    struct {
      const uint8_t *data;
      uint64_t len;
    } buf; /* Y_JSON_BUF */
  } value;
} YInput;

typedef struct YMapEntry {
  char *key;      /* released with the entry */
  YOutput *value; /* released with the entry */
} YMapEntry;

/* ---- runtime / errors --------------------------------------------------- */
/* Last error message for this thread, or NULL. Owned by the library. */
const char *ytpu_last_error(void);
void ystring_destroy(char *str);
void ybinary_destroy(YBinary bin);

/* ---- document lifecycle (yffi: ydoc_*) ---------------------------------- */
YDoc *ydoc_new(void);
YDoc *ydoc_new_with_options(YOptions options);
YDoc *ydoc_clone(YDoc *doc);
void ydoc_destroy(YDoc *doc);
uint64_t ydoc_id(YDoc *doc);
char *ydoc_guid(YDoc *doc);
char *ydoc_collection_id(YDoc *doc); /* NULL if unset */
uint8_t ydoc_should_load(YDoc *doc);
uint8_t ydoc_auto_load(YDoc *doc);
void ydoc_load(YDoc *doc);

/* ---- transactions (yffi: ydoc_*_transaction / ytransaction_*) ----------- */
YTransaction *ydoc_read_transaction(YDoc *doc);
YTransaction *ydoc_write_transaction(YDoc *doc, uint32_t origin_len,
                                     const char *origin);
void ytransaction_commit(YTransaction *txn);
uint8_t ytransaction_writeable(YTransaction *txn);

YBinary ytransaction_state_vector_v1(YTransaction *txn);
YBinary ytransaction_state_diff_v1(YTransaction *txn, const uint8_t *sv,
                                   uint32_t sv_len);
YBinary ytransaction_state_diff_v2(YTransaction *txn, const uint8_t *sv,
                                   uint32_t sv_len);
/* 0 on success, nonzero error code otherwise */
uint8_t ytransaction_apply(YTransaction *txn, const uint8_t *diff,
                           uint32_t diff_len);
uint8_t ytransaction_apply_v2(YTransaction *txn, const uint8_t *diff,
                              uint32_t diff_len);
YBinary ytransaction_snapshot(YTransaction *txn);
YBinary ytransaction_encode_state_from_snapshot_v1(YTransaction *txn,
                                                   const uint8_t *snapshot,
                                                   uint32_t snapshot_len);
YBinary ytransaction_encode_state_from_snapshot_v2(YTransaction *txn,
                                                   const uint8_t *snapshot,
                                                   uint32_t snapshot_len);
char *yupdate_debug_v1(const uint8_t *update, uint32_t update_len);
char *yupdate_debug_v2(const uint8_t *update, uint32_t update_len);

/* ---- root types --------------------------------------------------------- */
Branch *ytext(YDoc *doc, const char *name);
Branch *yarray(YDoc *doc, const char *name);
Branch *ymap(YDoc *doc, const char *name);
Branch *yxmlfragment(YDoc *doc, const char *name);
Branch *yxmltext(YDoc *doc, const char *name);
int8_t ytype_kind(Branch *branch);
uint8_t ybranch_alive(Branch *branch);
void ybranch_destroy(Branch *branch); /* releases the handle, not the type */

/* ---- YOutput ------------------------------------------------------------ */
int8_t youtput_tag(const YOutput *val);
char *youtput_read_string(const YOutput *val); /* NULL if not a string */
uint8_t youtput_read_bool(const YOutput *val);
double youtput_read_float(const YOutput *val);
int64_t youtput_read_long(const YOutput *val);
YBinary youtput_read_binary(const YOutput *val);
char *youtput_json(const YOutput *val); /* any value as JSON */
Branch *youtput_read_yarray(YOutput *val);
Branch *youtput_read_ymap(YOutput *val);
Branch *youtput_read_ytext(YOutput *val);
Branch *youtput_read_yxmlelem(YOutput *val);
Branch *youtput_read_yxmltext(YOutput *val);
YDoc *youtput_read_ydoc(YOutput *val);
void youtput_destroy(YOutput *val);

/* ---- YText (yffi: ytext_*) ---------------------------------------------- */
uint32_t ytext_len(Branch *txt, YTransaction *txn);
char *ytext_string(Branch *txt, YTransaction *txn);
void ytext_insert(Branch *txt, YTransaction *txn, uint32_t index,
                  const char *value, const char *attrs_json);
void ytext_insert_embed(Branch *txt, YTransaction *txn, uint32_t index,
                        const YInput *content, const char *attrs_json);
void ytext_format(Branch *txt, YTransaction *txn, uint32_t index,
                  uint32_t len, const char *attrs_json);
void ytext_remove_range(Branch *txt, YTransaction *txn, uint32_t index,
                        uint32_t len);

/* ---- YArray (yffi: yarray_*) -------------------------------------------- */
uint32_t yarray_len(Branch *array);
YOutput *yarray_get(Branch *array, YTransaction *txn, uint32_t index);
void yarray_insert_range(Branch *array, YTransaction *txn, uint32_t index,
                         const YInput *items, uint32_t items_len);
void yarray_remove_range(Branch *array, YTransaction *txn, uint32_t index,
                         uint32_t len);
void yarray_move(Branch *array, YTransaction *txn, uint32_t source,
                 uint32_t target);
YArrayIter *yarray_iter(Branch *array, YTransaction *txn);
YOutput *yarray_iter_next(YArrayIter *iter); /* NULL at end */
void yarray_iter_destroy(YArrayIter *iter);

/* ---- YMap (yffi: ymap_*) ------------------------------------------------ */
uint32_t ymap_len(Branch *map, YTransaction *txn);
void ymap_insert(Branch *map, YTransaction *txn, const char *key,
                 const YInput *value);
uint8_t ymap_remove(Branch *map, YTransaction *txn, const char *key);
YOutput *ymap_get(Branch *map, YTransaction *txn, const char *key);
void ymap_remove_all(Branch *map, YTransaction *txn);
YMapIter *ymap_iter(Branch *map, YTransaction *txn);
YMapEntry *ymap_iter_next(YMapIter *iter); /* NULL at end */
void ymap_entry_destroy(YMapEntry *entry);
void ymap_iter_destroy(YMapIter *iter);

/* ---- YXml (yffi: yxmlelem_* / yxmltext_* / yxml_*) ---------------------- */
char *yxmlelem_tag(Branch *xml);
char *yxmlelem_string(Branch *xml, YTransaction *txn);
void yxmlelem_insert_attr(Branch *xml, YTransaction *txn,
                          const char *attr_name, const char *attr_value);
void yxmlelem_remove_attr(Branch *xml, YTransaction *txn,
                          const char *attr_name);
char *yxmlelem_get_attr(Branch *xml, YTransaction *txn,
                        const char *attr_name); /* NULL if missing */
uint32_t yxmlelem_child_len(Branch *xml, YTransaction *txn);
Branch *yxmlelem_insert_elem(Branch *xml, YTransaction *txn, uint32_t index,
                             const char *name);
Branch *yxmlelem_insert_text(Branch *xml, YTransaction *txn, uint32_t index);
void yxmlelem_remove_range(Branch *xml, YTransaction *txn, uint32_t index,
                           uint32_t len);
YOutput *yxmlelem_get(Branch *xml, YTransaction *txn, uint32_t index);
YOutput *yxmlelem_first_child(Branch *xml);
YOutput *yxml_next_sibling(Branch *xml, YTransaction *txn);
YOutput *yxml_prev_sibling(Branch *xml, YTransaction *txn);
YXmlTreeWalker *yxmlelem_tree_walker(Branch *xml, YTransaction *txn);
YOutput *yxmlelem_tree_walker_next(YXmlTreeWalker *walker);
void yxmlelem_tree_walker_destroy(YXmlTreeWalker *walker);

uint32_t yxmltext_len(Branch *xml, YTransaction *txn);
char *yxmltext_string(Branch *xml, YTransaction *txn);
void yxmltext_insert(Branch *xml, YTransaction *txn, uint32_t index,
                     const char *str, const char *attrs_json);
void yxmltext_remove_range(Branch *xml, YTransaction *txn, uint32_t index,
                           uint32_t len);
void yxmltext_format(Branch *xml, YTransaction *txn, uint32_t index,
                     uint32_t len, const char *attrs_json);
void yxmltext_insert_attr(Branch *xml, YTransaction *txn,
                          const char *attr_name, const char *attr_value);
char *yxmltext_get_attr(Branch *xml, YTransaction *txn,
                        const char *attr_name);

/* ---- UndoManager (yffi: yundo_manager_*) -------------------------------- */
typedef struct YUndoManagerOptions {
  int32_t capture_timeout_millis;
} YUndoManagerOptions;
YUndoManager *yundo_manager(YDoc *doc, const YUndoManagerOptions *options);
void yundo_manager_destroy(YUndoManager *mgr);
void yundo_manager_add_scope(YUndoManager *mgr, Branch *ytype);
void yundo_manager_add_origin(YUndoManager *mgr, uint32_t origin_len,
                              const char *origin);
void yundo_manager_remove_origin(YUndoManager *mgr, uint32_t origin_len,
                                 const char *origin);
uint8_t yundo_manager_undo(YUndoManager *mgr);
uint8_t yundo_manager_redo(YUndoManager *mgr);
uint8_t yundo_manager_can_undo(YUndoManager *mgr);
uint8_t yundo_manager_can_redo(YUndoManager *mgr);
void yundo_manager_clear(YUndoManager *mgr);
void yundo_manager_stop(YUndoManager *mgr);

/* ---- StickyIndex (yffi: ysticky_index_*) -------------------------------- */
YStickyIndex *ysticky_index_from_index(Branch *ytype, YTransaction *txn,
                                       uint32_t index, int8_t assoc);
void ysticky_index_destroy(YStickyIndex *pos);
int8_t ysticky_index_assoc(YStickyIndex *pos);
YBinary ysticky_index_encode(YStickyIndex *pos);
YStickyIndex *ysticky_index_decode(const uint8_t *bin, uint32_t len);
/* writes the resolved index to *out_index; 0 if position vanished */
uint8_t ysticky_index_read(YStickyIndex *pos, YTransaction *txn,
                           uint32_t *out_index);

/* ---- observers (yffi: ydoc_observe_*) ----------------------------------- */
typedef void (*ytpu_observe_cb)(void *state, uint32_t len,
                                const uint8_t *bytes);
YSubscription *ydoc_observe_updates_v1(YDoc *doc, void *state,
                                       ytpu_observe_cb cb);
YSubscription *ydoc_observe_updates_v2(YDoc *doc, void *state,
                                       ytpu_observe_cb cb);
/* after-transaction: cb invoked with len=0 */
YSubscription *ydoc_observe_after_transaction(YDoc *doc, void *state,
                                              ytpu_observe_cb cb);
void yunobserve(YSubscription *subscription);

#ifdef __cplusplus
} /* extern "C" */
#endif
#endif /* YTPU_H */
