/* libytpu C ABI implementation.
 *
 * Native host-runtime layer: embeds CPython, drives the ytpu engine
 * (JAX/XLA data plane + Python host semantics) through
 * ytpu/native/support.py, and exposes the yffi-shaped C surface declared
 * in include/ytpu.h (parity: /root/reference/yffi/src/lib.rs).
 *
 * Responsibilities handled here (not in Python):
 *  - interpreter lifecycle + sys.path bootstrap (locates the repo relative
 *    to this shared object via dladdr)
 *  - GIL acquisition around every entry point (callable from any thread)
 *  - handle management: every opaque pointer owns one Python reference
 *  - YInput/YOutput conversion and malloc'd result buffers
 *  - C function-pointer observer trampolines (PyCFunction over a capsule)
 *  - thread-local error capture (ytpu_last_error)
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <dlfcn.h>
#include <limits.h>

#include <cstdarg>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>

#include "include/ytpu.h"

/* ---- opaque handle definitions ------------------------------------------ */
struct YDoc {
  PyObject *obj;
};
struct Branch {
  PyObject *obj;
};
struct YTransaction {
  PyObject *obj;
  bool writeable;
};
struct YOutput {
  PyObject *obj;
};
struct YUndoManager {
  PyObject *obj;
};
struct YStickyIndex {
  PyObject *obj;
};
struct YSubscription {
  PyObject *unobserve;
  PyObject *callback;
};
struct YArrayIter {
  PyObject *iter;
};
struct YMapIter {
  PyObject *iter;
};
struct YXmlTreeWalker {
  PyObject *iter;
};
struct YEvent {
  PyObject *obj; /* borrowed; valid only during the observer callback */
};
struct YWeak {
  PyObject *obj; /* WeakPrelim */
};
struct YWeakIter {
  PyObject *iter;
};
struct YXmlAttrIter {
  PyObject *iter;
};

/* ---- interpreter bootstrap ---------------------------------------------- */
static PyObject *g_support = nullptr; /* ytpu.native.support module */
static std::once_flag g_init_once;
static std::string g_boot_error; /* sticky bootstrap failure, if any */
static thread_local std::string g_last_error;

static void set_err(const std::string &msg) { g_last_error = msg; }

static void set_err_py() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c) msg = c;
      Py_DECREF(s);
    }
  }
  if (type) {
    PyObject *n = PyObject_GetAttrString(type, "__name__");
    if (n) {
      const char *c = PyUnicode_AsUTF8(n);
      if (c) msg = std::string(c) + ": " + msg;
      Py_DECREF(n);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_err(msg);
}

static void bootstrap() {
  bool started_here = !Py_IsInitialized();
  if (started_here) {
    Py_InitializeEx(0);
  }
  PyGILState_STATE st = PyGILState_Ensure();
  /* Make the repo importable: this .so lives at <root>/ytpu/native/. */
  Dl_info info;
  if (dladdr((void *)&bootstrap, &info) && info.dli_fname) {
    std::string path(info.dli_fname);
    /* dladdr reports the path as given at link time; canonicalize so a
     * relative -l path still resolves to the repo root */
    char resolved[PATH_MAX];
    if (realpath(path.c_str(), resolved)) path = resolved;
    for (int up = 0; up < 3; ++up) {
      size_t slash = path.find_last_of('/');
      if (slash == std::string::npos) break;
      path.resize(slash);
    }
    PyObject *sys_path = PySys_GetObject("path"); /* borrowed */
    if (sys_path && !path.empty()) {
      PyObject *dir = PyUnicode_FromString(path.c_str());
      if (dir) {
        PyList_Insert(sys_path, 0, dir);
        Py_DECREF(dir);
      }
    }
  }
  g_support = PyImport_ImportModule("ytpu.native.support");
  if (!g_support) {
    set_err_py();
    g_boot_error = "ytpu bootstrap failed: " + g_last_error;
  }
  PyGILState_Release(st);
  if (started_here) {
    /* Release the GIL acquired by Py_Initialize so any thread can enter. */
    PyEval_SaveThread();
  }
}

static bool ensure_init() {
  std::call_once(g_init_once, bootstrap);
  return g_support != nullptr;
}

/* RAII GIL guard; every extern "C" entry point opens one. The last-error
 * slot always describes the most recent entry point, so a NULL/0 result
 * from a call that left no message is a legitimate "absent" answer. */
struct Gil {
  PyGILState_STATE st;
  bool ok;
  Gil() {
    g_last_error.clear();
    ok = ensure_init();
    if (ok) {
      st = PyGILState_Ensure();
    } else {
      g_last_error = g_boot_error; /* init failures stay diagnosable */
    }
  }
  ~Gil() {
    if (ok) PyGILState_Release(st);
  }
};

/* Call `target.<name>(args…)`; returns a new reference or NULL with the
 * error captured. */
static PyObject *vcall(PyObject *target, const char *name, const char *fmt,
                       va_list args) {
  PyObject *fn = PyObject_GetAttrString(target, name);
  if (!fn) {
    set_err_py();
    return nullptr;
  }
  PyObject *tuple = fmt ? Py_VaBuildValue(fmt, args) : PyTuple_New(0);
  if (!tuple) {
    set_err_py();
    Py_DECREF(fn);
    return nullptr;
  }
  if (!PyTuple_Check(tuple)) {
    PyObject *wrapped = PyTuple_Pack(1, tuple);
    Py_DECREF(tuple);
    tuple = wrapped;
  }
  PyObject *res = PyObject_CallObject(fn, tuple);
  Py_DECREF(fn);
  Py_DECREF(tuple);
  if (!res) set_err_py();
  return res;
}

/* Call a function in ytpu.native.support. */
static PyObject *support_call(const char *name, const char *fmt, ...) {
  va_list args;
  va_start(args, fmt);
  PyObject *res = vcall(g_support, name, fmt, args);
  va_end(args);
  return res;
}

/* Call a method on an engine object. */
static PyObject *method_call(PyObject *obj, const char *name, const char *fmt,
                             ...) {
  va_list args;
  va_start(args, fmt);
  PyObject *res = vcall(obj, name, fmt, args);
  va_end(args);
  return res;
}

/* ---- conversions --------------------------------------------------------- */
static char *dup_str(const char *s) {
  if (!s) return nullptr;
  size_t n = strlen(s) + 1;
  char *out = (char *)malloc(n);
  if (out) memcpy(out, s, n);
  return out;
}

static char *py_to_cstr(PyObject *obj) { /* consumes obj */
  if (!obj) return nullptr;
  char *out = nullptr;
  if (obj != Py_None) {
    const char *c = PyUnicode_AsUTF8(obj);
    if (c) out = dup_str(c);
  }
  Py_DECREF(obj);
  return out;
}

static YBinary py_to_binary(PyObject *obj) { /* consumes obj */
  YBinary bin{nullptr, 0};
  if (!obj) return bin;
  char *buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(obj, &buf, &len) == 0) {
    bin.data = (uint8_t *)malloc(len > 0 ? (size_t)len : 1);
    if (bin.data) {
      memcpy(bin.data, buf, (size_t)len);
      bin.len = (uint64_t)len;
    }
  } else {
    set_err_py();
  }
  Py_DECREF(obj);
  return bin;
}

/* (tag, payload) pair for support.input_to_value. Returns new ref payload. */
/* YInput.len sentinel marking the `*_str` JSON-string constructor forms */
#define YINPUT_STR_FORM UINT32_MAX

static PyObject *input_to_value(const YInput *input);

static PyObject *input_payload(const YInput *input) {
  if (!input) Py_RETURN_NONE;
  switch (input->tag) {
    case Y_JSON_BOOL:
      return PyBool_FromLong(input->value.flag);
    case Y_JSON_NUM:
      return PyFloat_FromDouble(input->value.num);
    case Y_JSON_INT:
      return PyLong_FromLongLong(input->value.integer);
    case Y_JSON_ARR:
    case Y_ARRAY:
      if (input->len != YINPUT_STR_FORM) {
        /* Migration guard: a hand-built `{tag, value.str = json}` with
         * len left 0 is indistinguishable from an empty recursive array
         * that passes a non-null (unused) pointer; reading the pointee to
         * disambiguate would be out-of-bounds for a one-past-end pointer.
         * Reject the ambiguous shape outright: empty arrays pass
         * values=NULL (what yinput_json_array(NULL, 0) builds); JSON
         * strings use yinput_json_array_str (len = YINPUT_STR_FORM). */
        if (input->len == 0 && input->value.values) {
          PyErr_SetString(
              PyExc_ValueError,
              "ambiguous YInput: len==0 with a non-NULL payload pointer; "
              "pass values=NULL for an empty array, or use "
              "yinput_json_array_str / len=YINPUT_STR_FORM for the "
              "JSON-string form");
          return nullptr;
        }
        /* yffi recursive form: convert each element (prelims included) */
        PyObject *list = PyList_New((Py_ssize_t)input->len);
        if (!list) return nullptr;
        for (uint32_t k = 0; k < input->len; k++) {
          PyObject *v = input_to_value(&input->value.values[k]);
          if (!v) {
            Py_DECREF(list);
            return nullptr;
          }
          PyList_SET_ITEM(list, (Py_ssize_t)k, v);
        }
        return list;
      }
      if (input->value.str) return PyUnicode_FromString(input->value.str);
      Py_RETURN_NONE;
    case Y_JSON_MAP:
    case Y_MAP:
      if (input->len != YINPUT_STR_FORM) {
        /* same migration guard as the array case above */
        if (input->len == 0 && input->value.map.keys) {
          PyErr_SetString(
              PyExc_ValueError,
              "ambiguous YInput: len==0 with a non-NULL payload pointer; "
              "pass keys=NULL for an empty map, or use "
              "yinput_json_map_str / len=YINPUT_STR_FORM for the "
              "JSON-string form");
          return nullptr;
        }
        PyObject *dict = PyDict_New();
        if (!dict) return nullptr;
        for (uint32_t k = 0; k < input->len; k++) {
          PyObject *v = input_to_value(&input->value.map.values[k]);
          if (!v || PyDict_SetItemString(dict, input->value.map.keys[k], v) < 0) {
            Py_XDECREF(v);
            Py_DECREF(dict);
            return nullptr;
          }
          Py_DECREF(v);
        }
        return dict;
      }
      if (input->value.str) return PyUnicode_FromString(input->value.str);
      Py_RETURN_NONE;
    case Y_JSON_STR:
    case Y_TEXT:
    case Y_XML_TEXT:
    case Y_XML_ELEM:
      if (input->value.str) return PyUnicode_FromString(input->value.str);
      Py_RETURN_NONE;
    case Y_JSON_BUF:
      return PyBytes_FromStringAndSize((const char *)input->value.buf.data,
                                       (Py_ssize_t)input->value.buf.len);
    case Y_DOC:
      if (input->value.doc) {
        Py_INCREF(input->value.doc->obj);
        return input->value.doc->obj;
      }
      Py_RETURN_NONE;
    case Y_WEAK_LINK:
      if (input->value.weak) {
        Py_INCREF(input->value.weak->obj);
        return input->value.weak->obj;
      }
      Py_RETURN_NONE;
    default:
      Py_RETURN_NONE;
  }
}

static PyObject *input_to_value(const YInput *input) {
  int tag = input ? input->tag : Y_JSON_NULL;
  PyObject *payload = input_payload(input);
  if (!payload) {
    set_err_py();
    return nullptr;
  }
  PyObject *res = support_call("input_to_value", "(iN)", tag, payload);
  return res;
}

static YOutput *wrap_output(PyObject *obj) { /* takes ownership */
  if (!obj) return nullptr;
  if (obj == Py_None) {
    Py_DECREF(obj);
    return nullptr;
  }
  YOutput *out = new YOutput{obj};
  return out;
}

static Branch *wrap_branch(PyObject *obj) { /* takes ownership */
  if (!obj || obj == Py_None) {
    Py_XDECREF(obj);
    return nullptr;
  }
  return new Branch{obj};
}

/* ---- runtime / errors ---------------------------------------------------- */
extern "C" const char *ytpu_last_error(void) {
  return g_last_error.empty() ? nullptr : g_last_error.c_str();
}

extern "C" void ystring_destroy(char *str) { free(str); }

extern "C" void ybinary_destroy(YBinary bin) { free(bin.data); }

/* ---- document lifecycle -------------------------------------------------- */
static YDoc *doc_from_options(const YOptions *o) {
  Gil gil;
  if (!gil.ok) return nullptr;
  PyObject *obj = support_call(
      "doc_new", "(KzziiiI)", (unsigned long long)(o ? o->id : 0),
      o ? o->guid : nullptr, o ? o->collection_id : nullptr,
      o ? (int)o->skip_gc : 0, o ? (int)o->auto_load : 0,
      o ? (int)o->should_load : 1,
      (o == nullptr || o->encoding == Y_OFFSET_UTF16) ? 1u : 0u);
  if (!obj) return nullptr;
  return new YDoc{obj};
}

extern "C" YDoc *ydoc_new(void) { return doc_from_options(nullptr); }

extern "C" YDoc *ydoc_new_with_options(YOptions options) {
  return doc_from_options(&options);
}

extern "C" YDoc *ydoc_clone(YDoc *doc) {
  /* yffi contract (lib.rs:398-407): the clone is the SAME document
   * instance — a second handle, not a replica. */
  Gil gil;
  if (!gil.ok || !doc) return nullptr;
  Py_INCREF(doc->obj);
  return new YDoc{doc->obj};
}

extern "C" void ydoc_destroy(YDoc *doc) {
  if (!doc) return;
  Gil gil;
  if (gil.ok) Py_DECREF(doc->obj);
  delete doc;
}

extern "C" uint64_t ydoc_id(YDoc *doc) {
  Gil gil;
  if (!gil.ok || !doc) return 0;
  PyObject *v = PyObject_GetAttrString(doc->obj, "client_id");
  if (!v) {
    set_err_py();
    return 0;
  }
  uint64_t id = PyLong_AsUnsignedLongLong(v);
  Py_DECREF(v);
  return id;
}

extern "C" char *ydoc_guid(YDoc *doc) {
  Gil gil;
  if (!gil.ok || !doc) return nullptr;
  return py_to_cstr(PyObject_GetAttrString(doc->obj, "guid"));
}

extern "C" char *ydoc_collection_id(YDoc *doc) {
  Gil gil;
  if (!gil.ok || !doc) return nullptr;
  PyObject *opts = PyObject_GetAttrString(doc->obj, "options");
  if (!opts) return nullptr;
  char *out = py_to_cstr(PyObject_GetAttrString(opts, "collection_id"));
  Py_DECREF(opts);
  return out;
}

static uint8_t doc_option_flag(YDoc *doc, const char *name) {
  Gil gil;
  if (!gil.ok || !doc) return 0;
  PyObject *opts = PyObject_GetAttrString(doc->obj, "options");
  if (!opts) return 0;
  PyObject *v = PyObject_GetAttrString(opts, name);
  Py_DECREF(opts);
  if (!v) return 0;
  uint8_t out = PyObject_IsTrue(v) == 1 ? 1 : 0;
  Py_DECREF(v);
  return out;
}

extern "C" uint8_t ydoc_should_load(YDoc *doc) {
  return doc_option_flag(doc, "should_load");
}

extern "C" uint8_t ydoc_auto_load(YDoc *doc) {
  return doc_option_flag(doc, "auto_load");
}

extern "C" void ydoc_load(YDoc *doc) {
  Gil gil;
  if (!gil.ok || !doc) return;
  PyObject *r = method_call(doc->obj, "load", nullptr);
  Py_XDECREF(r);
}

/* ---- transactions -------------------------------------------------------- */
static YTransaction *txn_new(YDoc *doc, const char *origin,
                             uint32_t origin_len, bool writeable) {
  Gil gil;
  if (!gil.ok || !doc) return nullptr;
  PyObject *obj =
      origin ? support_call("txn_new", "(Oy#i)", doc->obj, origin,
                            (Py_ssize_t)origin_len, (int)writeable)
             : support_call("txn_new", "(Ozi)", doc->obj, nullptr,
                            (int)writeable);
  if (!obj) return nullptr;
  return new YTransaction{obj, writeable};
}

extern "C" YTransaction *ydoc_read_transaction(YDoc *doc) {
  return txn_new(doc, nullptr, 0, false);
}

extern "C" YTransaction *ydoc_write_transaction(YDoc *doc,
                                                uint32_t origin_len,
                                                const char *origin) {
  return txn_new(doc, origin, origin_len, true);
}

extern "C" void ytransaction_commit(YTransaction *txn) {
  if (!txn) return;
  Gil gil;
  if (gil.ok) {
    PyObject *r = support_call("txn_commit", "(O)", txn->obj);
    Py_XDECREF(r);
    Py_DECREF(txn->obj);
  }
  delete txn;
}

extern "C" uint8_t ytransaction_writeable(YTransaction *txn) {
  return txn && txn->writeable ? 1 : 0;
}

extern "C" YBinary ytransaction_state_vector_v1(YTransaction *txn) {
  Gil gil;
  if (!gil.ok || !txn) return YBinary{nullptr, 0};
  return py_to_binary(support_call("txn_state_vector_v1", "(O)", txn->obj));
}

static YBinary state_diff(YTransaction *txn, const uint8_t *sv,
                          uint32_t sv_len, const char *fn) {
  Gil gil;
  if (!gil.ok || !txn) return YBinary{nullptr, 0};
  PyObject *res = sv ? support_call(fn, "(Oy#)", txn->obj, (const char *)sv,
                                    (Py_ssize_t)sv_len)
                     : support_call(fn, "(Oz)", txn->obj, nullptr);
  return py_to_binary(res);
}

extern "C" YBinary ytransaction_state_diff_v1(YTransaction *txn,
                                              const uint8_t *sv,
                                              uint32_t sv_len) {
  return state_diff(txn, sv, sv_len, "txn_state_diff_v1");
}

extern "C" YBinary ytransaction_state_diff_v2(YTransaction *txn,
                                              const uint8_t *sv,
                                              uint32_t sv_len) {
  return state_diff(txn, sv, sv_len, "txn_state_diff_v2");
}

static uint8_t txn_apply(YTransaction *txn, const uint8_t *diff,
                         uint32_t diff_len, int v2) {
  Gil gil;
  if (!gil.ok || !txn || !diff) return 1;
  PyObject *r = support_call("txn_apply", "(Oy#i)", txn->obj,
                             (const char *)diff, (Py_ssize_t)diff_len, v2);
  if (!r) return 2;
  Py_DECREF(r);
  return 0;
}

extern "C" uint8_t ytransaction_apply(YTransaction *txn, const uint8_t *diff,
                                      uint32_t diff_len) {
  return txn_apply(txn, diff, diff_len, 0);
}

extern "C" uint8_t ytransaction_apply_v2(YTransaction *txn,
                                         const uint8_t *diff,
                                         uint32_t diff_len) {
  return txn_apply(txn, diff, diff_len, 1);
}

extern "C" YBinary ytransaction_snapshot(YTransaction *txn) {
  Gil gil;
  if (!gil.ok || !txn) return YBinary{nullptr, 0};
  return py_to_binary(support_call("txn_snapshot", "(O)", txn->obj));
}

static YBinary encode_from_snapshot(YTransaction *txn, const uint8_t *snap,
                                    uint32_t len, int v2) {
  Gil gil;
  if (!gil.ok || !txn || !snap) return YBinary{nullptr, 0};
  return py_to_binary(support_call("txn_encode_from_snapshot", "(Oy#i)",
                                   txn->obj, (const char *)snap,
                                   (Py_ssize_t)len, v2));
}

extern "C" YBinary ytransaction_encode_state_from_snapshot_v1(
    YTransaction *txn, const uint8_t *snapshot, uint32_t snapshot_len) {
  return encode_from_snapshot(txn, snapshot, snapshot_len, 0);
}

extern "C" YBinary ytransaction_encode_state_from_snapshot_v2(
    YTransaction *txn, const uint8_t *snapshot, uint32_t snapshot_len) {
  return encode_from_snapshot(txn, snapshot, snapshot_len, 1);
}

static char *update_debug(const uint8_t *update, uint32_t len, int v2) {
  Gil gil;
  if (!gil.ok || !update) return nullptr;
  return py_to_cstr(support_call("update_debug", "(y#i)",
                                 (const char *)update, (Py_ssize_t)len, v2));
}

extern "C" char *yupdate_debug_v1(const uint8_t *update, uint32_t update_len) {
  return update_debug(update, update_len, 0);
}

extern "C" char *yupdate_debug_v2(const uint8_t *update, uint32_t update_len) {
  return update_debug(update, update_len, 1);
}

/* ---- root types ----------------------------------------------------------- */
static Branch *root_type(YDoc *doc, int kind, const char *name) {
  Gil gil;
  if (!gil.ok || !doc || !name) return nullptr;
  return wrap_branch(support_call("doc_root", "(Ois)", doc->obj, kind, name));
}

extern "C" Branch *ytext(YDoc *doc, const char *name) {
  return root_type(doc, Y_TEXT, name);
}
extern "C" Branch *yarray(YDoc *doc, const char *name) {
  return root_type(doc, Y_ARRAY, name);
}
extern "C" Branch *ymap(YDoc *doc, const char *name) {
  return root_type(doc, Y_MAP, name);
}
extern "C" Branch *yxmlfragment(YDoc *doc, const char *name) {
  return root_type(doc, Y_XML_FRAG, name);
}
extern "C" Branch *yxmltext(YDoc *doc, const char *name) {
  return root_type(doc, Y_XML_TEXT, name);
}

extern "C" int8_t ytype_kind(Branch *branch) {
  Gil gil;
  if (!gil.ok || !branch) return Y_JSON_UNDEF;
  PyObject *r = support_call("branch_kind", "(O)", branch->obj);
  if (!r) return Y_JSON_UNDEF;
  int8_t kind = (int8_t)PyLong_AsLong(r);
  Py_DECREF(r);
  return kind;
}

extern "C" uint8_t ybranch_alive(Branch *branch) {
  return branch && branch->obj ? 1 : 0;
}

extern "C" void ybranch_destroy(Branch *branch) {
  if (!branch) return;
  Gil gil;
  if (gil.ok) Py_DECREF(branch->obj);
  delete branch;
}

/* ---- YOutput --------------------------------------------------------------- */
extern "C" int8_t youtput_tag(const YOutput *val) {
  Gil gil;
  if (!gil.ok || !val) return Y_JSON_UNDEF;
  PyObject *r = support_call("output_tag", "(O)", val->obj);
  if (!r) return Y_JSON_UNDEF;
  int8_t tag = (int8_t)PyLong_AsLong(r);
  Py_DECREF(r);
  return tag;
}

extern "C" char *youtput_read_string(const YOutput *val) {
  Gil gil;
  if (!gil.ok || !val || !PyUnicode_Check(val->obj)) return nullptr;
  Py_INCREF(val->obj);
  return py_to_cstr(val->obj);
}

extern "C" uint8_t youtput_read_bool(const YOutput *val) {
  Gil gil;
  if (!gil.ok || !val) return 0;
  return PyObject_IsTrue(val->obj) == 1 ? 1 : 0;
}

extern "C" double youtput_read_float(const YOutput *val) {
  Gil gil;
  if (!gil.ok || !val) return 0.0;
  double d = PyFloat_AsDouble(val->obj);
  if (PyErr_Occurred()) {
    set_err_py();
    return 0.0;
  }
  return d;
}

extern "C" int64_t youtput_read_long(const YOutput *val) {
  Gil gil;
  if (!gil.ok || !val) return 0;
  int64_t v = PyLong_AsLongLong(val->obj);
  if (PyErr_Occurred()) {
    set_err_py();
    return 0;
  }
  return v;
}

extern "C" YBinary youtput_read_binary(const YOutput *val) {
  Gil gil;
  if (!gil.ok || !val || !PyBytes_Check(val->obj)) return YBinary{nullptr, 0};
  Py_INCREF(val->obj);
  return py_to_binary(val->obj);
}

extern "C" char *youtput_json(const YOutput *val) {
  Gil gil;
  if (!gil.ok || !val) return nullptr;
  return py_to_cstr(support_call("output_json", "(O)", val->obj));
}

static Branch *output_branch(YOutput *val, int8_t expect) {
  Gil gil;
  if (!gil.ok || !val) return nullptr;
  PyObject *r = support_call("output_tag", "(O)", val->obj);
  if (!r) return nullptr;
  int8_t tag = (int8_t)PyLong_AsLong(r);
  Py_DECREF(r);
  if (tag != expect) return nullptr;
  Py_INCREF(val->obj);
  return new Branch{val->obj};
}

extern "C" Branch *youtput_read_yarray(YOutput *val) {
  return output_branch(val, Y_ARRAY);
}
extern "C" Branch *youtput_read_ymap(YOutput *val) {
  return output_branch(val, Y_MAP);
}
extern "C" Branch *youtput_read_ytext(YOutput *val) {
  return output_branch(val, Y_TEXT);
}
extern "C" Branch *youtput_read_yxmlelem(YOutput *val) {
  return output_branch(val, Y_XML_ELEM);
}
extern "C" Branch *youtput_read_yxmltext(YOutput *val) {
  return output_branch(val, Y_XML_TEXT);
}

extern "C" YDoc *youtput_read_ydoc(YOutput *val) {
  Gil gil;
  if (!gil.ok || !val) return nullptr;
  PyObject *r = support_call("output_tag", "(O)", val->obj);
  if (!r) return nullptr;
  int8_t tag = (int8_t)PyLong_AsLong(r);
  Py_DECREF(r);
  if (tag != Y_DOC) return nullptr;
  Py_INCREF(val->obj);
  return new YDoc{val->obj};
}

extern "C" void youtput_destroy(YOutput *val) {
  if (!val) return;
  Gil gil;
  if (gil.ok) Py_DECREF(val->obj);
  delete val;
}

/* ---- by-value YOutput (yffi ABI-shape parity) ------------------------------ */

static YOutputValue py_to_output_value(PyObject *obj);

static YOutputValue output_value_tagged(int8_t tag) {
  YOutputValue v;
  memset(&v, 0, sizeof(v));
  v.tag = tag;
  v.len = 0;
  return v;
}

static YOutputValue py_to_output_value(PyObject *obj) {
  if (!obj || obj == Py_None) return output_value_tagged(Y_JSON_NULL);
  YOutputValue v = output_value_tagged(Y_JSON_UNDEF);
  if (PyBool_Check(obj)) {
    v.tag = Y_JSON_BOOL;
    v.len = 1;
    v.value.flag = obj == Py_True ? 1 : 0;
    return v;
  }
  if (PyLong_Check(obj)) {
    v.tag = Y_JSON_INT;
    v.len = 1;
    v.value.integer = PyLong_AsLongLong(obj);
    return v;
  }
  if (PyFloat_Check(obj)) {
    v.tag = Y_JSON_NUM;
    v.len = 1;
    v.value.num = PyFloat_AsDouble(obj);
    return v;
  }
  if (PyUnicode_Check(obj)) {
    v.tag = Y_JSON_STR;
    v.len = 1;
    const char *c = PyUnicode_AsUTF8(obj);
    v.value.str = dup_str(c ? c : "");
    return v;
  }
  if (PyBytes_Check(obj)) {
    v.tag = Y_JSON_BUF;
    Py_ssize_t n = PyBytes_GET_SIZE(obj);
    v.len = (uint32_t)n;
    v.value.buf = (uint8_t *)malloc(n ? (size_t)n : 1);
    if (v.value.buf && n) memcpy(v.value.buf, PyBytes_AS_STRING(obj), (size_t)n);
    return v;
  }
  if (PyList_Check(obj)) {
    v.tag = Y_JSON_ARR;
    Py_ssize_t n = PyList_GET_SIZE(obj);
    v.len = (uint32_t)n;
    v.value.array =
        (YOutputValue *)calloc(n ? (size_t)n : 1, sizeof(YOutputValue));
    for (Py_ssize_t i = 0; i < n && v.value.array; i++)
      v.value.array[i] = py_to_output_value(PyList_GET_ITEM(obj, i));
    return v;
  }
  if (PyDict_Check(obj)) {
    v.tag = Y_JSON_MAP;
    Py_ssize_t n = PyDict_Size(obj);
    v.len = (uint32_t)n;
    v.value.map =
        (YMapEntryValue *)calloc(n ? (size_t)n : 1, sizeof(YMapEntryValue));
    PyObject *key, *value;
    Py_ssize_t pos = 0, i = 0;
    while (v.value.map && PyDict_Next(obj, &pos, &key, &value) && i < n) {
      const char *k = PyUnicode_Check(key) ? PyUnicode_AsUTF8(key) : nullptr;
      v.value.map[i].key = dup_str(k ? k : "");
      v.value.map[i].value = py_to_output_value(value);
      i++;
    }
    return v;
  }
  /* shared types / nested docs: wrap the same opaque handles the rest of
   * the API uses */
  PyObject *r = support_call("output_tag", "(O)", obj);
  int8_t tag = Y_JSON_UNDEF;
  if (r) {
    tag = (int8_t)PyLong_AsLong(r);
    Py_DECREF(r);
  }
  v.tag = tag;
  if (tag == Y_DOC) {
    Py_INCREF(obj);
    v.len = 1;
    v.value.y_doc = new YDoc{obj};
  } else if (tag > 0) {  /* Y_ARRAY..Y_WEAK_LINK: a Branch view */
    Py_INCREF(obj);
    v.len = 1;
    v.value.y_type = new Branch{obj};
  }
  return v;
}

extern "C" YOutputValue youtput_unwrap(const YOutput *val) {
  Gil gil;
  if (!gil.ok || !val) return output_value_tagged(Y_JSON_UNDEF);
  return py_to_output_value(val->obj);
}

extern "C" void youtput_value_destroy(YOutputValue val) {
  switch (val.tag) {
    case Y_JSON_STR:
      free(val.value.str);
      return;
    case Y_JSON_BUF:
      free(val.value.buf);
      return;
    case Y_JSON_ARR:
      if (val.value.array) {
        for (uint32_t i = 0; i < val.len; i++)
          youtput_value_destroy(val.value.array[i]);
        free(val.value.array);
      }
      return;
    case Y_JSON_MAP:
      if (val.value.map) {
        for (uint32_t i = 0; i < val.len; i++) {
          free(val.value.map[i].key);
          youtput_value_destroy(val.value.map[i].value);
        }
        free(val.value.map);
      }
      return;
    case Y_DOC:
      ydoc_destroy(val.value.y_doc);
      return;
    default:
      if (val.tag > 0 && val.value.y_type) ybranch_destroy(val.value.y_type);
      return;
  }
}

/* ---- YText ------------------------------------------------------------------ */
extern "C" uint32_t ytext_len(Branch *txt, YTransaction *txn) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !txt) return 0;
  PyObject *r = support_call("type_len", "(O)", txt->obj);
  if (!r) return 0;
  uint32_t n = (uint32_t)PyLong_AsUnsignedLong(r);
  Py_DECREF(r);
  return n;
}

extern "C" char *ytext_string(Branch *txt, YTransaction *txn) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !txt) return nullptr;
  return py_to_cstr(method_call(txt->obj, "get_string", nullptr));
}

extern "C" void ytext_insert(Branch *txt, YTransaction *txn, uint32_t index,
                             const char *value, const char *attrs_json) {
  Gil gil;
  if (!gil.ok || !txt || !txn || !value) return;
  PyObject *r = support_call("text_insert", "(OOIsz)", txn->obj, txt->obj,
                             (unsigned)index, value, attrs_json);
  Py_XDECREF(r);
}

extern "C" void ytext_insert_embed(Branch *txt, YTransaction *txn,
                                   uint32_t index, const YInput *content,
                                   const char *attrs_json) {
  Gil gil;
  if (!gil.ok || !txt || !txn || !content) return;
  /* embed payload rides as JSON (same simplification as YInput) */
  PyObject *payload = input_payload(content);
  if (!payload) return;
  PyObject *json_str = nullptr;
  if (content->tag == Y_JSON_ARR || content->tag == Y_JSON_MAP) {
    json_str = payload;
  } else {
    PyObject *json_mod = PyImport_ImportModule("json");
    if (json_mod) {
      json_str = method_call(json_mod, "dumps", "(N)", payload);
      Py_DECREF(json_mod);
    } else {
      Py_DECREF(payload);
    }
  }
  if (!json_str) return;
  PyObject *r = support_call("text_insert_embed", "(OOINz)", txn->obj,
                             txt->obj, (unsigned)index, json_str, attrs_json);
  Py_XDECREF(r);
}

extern "C" void ytext_format(Branch *txt, YTransaction *txn, uint32_t index,
                             uint32_t len, const char *attrs_json) {
  Gil gil;
  if (!gil.ok || !txt || !txn || !attrs_json) return;
  PyObject *r = support_call("text_format", "(OOIIs)", txn->obj, txt->obj,
                             (unsigned)index, (unsigned)len, attrs_json);
  Py_XDECREF(r);
}

extern "C" void ytext_remove_range(Branch *txt, YTransaction *txn,
                                   uint32_t index, uint32_t len) {
  Gil gil;
  if (!gil.ok || !txt || !txn) return;
  PyObject *r = method_call(txt->obj, "remove_range", "(OII)", txn->obj,
                            (unsigned)index, (unsigned)len);
  Py_XDECREF(r);
}

/* ---- YArray ----------------------------------------------------------------- */
extern "C" uint32_t yarray_len(Branch *array) {
  Gil gil;
  if (!gil.ok || !array) return 0;
  PyObject *r = support_call("type_len", "(O)", array->obj);
  if (!r) return 0;
  uint32_t n = (uint32_t)PyLong_AsUnsignedLong(r);
  Py_DECREF(r);
  return n;
}

extern "C" YOutput *yarray_get(Branch *array, YTransaction *txn,
                               uint32_t index) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !array) return nullptr;
  return wrap_output(method_call(array->obj, "get", "(I)", (unsigned)index));
}

extern "C" void yarray_insert_range(Branch *array, YTransaction *txn,
                                    uint32_t index, const YInput *items,
                                    uint32_t items_len) {
  Gil gil;
  if (!gil.ok || !array || !txn || (!items && items_len)) return;
  PyObject *pairs = PyList_New((Py_ssize_t)items_len);
  if (!pairs) return;
  for (uint32_t i = 0; i < items_len; ++i) {
    PyObject *payload = input_payload(&items[i]);
    PyObject *pair = payload ? Py_BuildValue("(iN)", (int)items[i].tag, payload)
                             : nullptr;
    if (!pair) {
      Py_DECREF(pairs);
      set_err_py();
      return;
    }
    PyList_SET_ITEM(pairs, (Py_ssize_t)i, pair);
  }
  PyObject *r = support_call("array_insert_range", "(OOIN)", txn->obj,
                             array->obj, (unsigned)index, pairs);
  Py_XDECREF(r);
}

extern "C" void yarray_remove_range(Branch *array, YTransaction *txn,
                                    uint32_t index, uint32_t len) {
  Gil gil;
  if (!gil.ok || !array || !txn) return;
  PyObject *r = method_call(array->obj, "remove_range", "(OII)", txn->obj,
                            (unsigned)index, (unsigned)len);
  Py_XDECREF(r);
}

extern "C" void yarray_move(Branch *array, YTransaction *txn, uint32_t source,
                            uint32_t target) {
  Gil gil;
  if (!gil.ok || !array || !txn) return;
  PyObject *r = method_call(array->obj, "move_to", "(OII)", txn->obj,
                            (unsigned)source, (unsigned)target);
  Py_XDECREF(r);
}

extern "C" YArrayIter *yarray_iter(Branch *array, YTransaction *txn) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !array) return nullptr;
  PyObject *lst = method_call(array->obj, "to_list", nullptr);
  if (!lst) return nullptr;
  PyObject *it = PyObject_GetIter(lst);
  Py_DECREF(lst);
  if (!it) {
    set_err_py();
    return nullptr;
  }
  return new YArrayIter{it};
}

extern "C" YOutput *yarray_iter_next(YArrayIter *iter) {
  Gil gil;
  if (!gil.ok || !iter) return nullptr;
  PyObject *v = PyIter_Next(iter->iter);
  if (!v) {
    if (PyErr_Occurred()) set_err_py();
    return nullptr;
  }
  return wrap_output(v);
}

extern "C" void yarray_iter_destroy(YArrayIter *iter) {
  if (!iter) return;
  Gil gil;
  if (gil.ok) Py_DECREF(iter->iter);
  delete iter;
}

/* ---- YMap ------------------------------------------------------------------- */
extern "C" uint32_t ymap_len(Branch *map, YTransaction *txn) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !map) return 0;
  PyObject *r = support_call("type_len", "(O)", map->obj);
  if (!r) return 0;
  uint32_t n = (uint32_t)PyLong_AsUnsignedLong(r);
  Py_DECREF(r);
  return n;
}

extern "C" void ymap_insert(Branch *map, YTransaction *txn, const char *key,
                            const YInput *value) {
  Gil gil;
  if (!gil.ok || !map || !txn || !key) return;
  PyObject *v = input_to_value(value);
  if (!v) return;
  PyObject *r = method_call(map->obj, "insert", "(OsN)", txn->obj, key, v);
  Py_XDECREF(r);
}

extern "C" uint8_t ymap_remove(Branch *map, YTransaction *txn,
                               const char *key) {
  Gil gil;
  if (!gil.ok || !map || !txn || !key) return 0;
  PyObject *r = method_call(map->obj, "remove", "(Os)", txn->obj, key);
  if (!r) return 0;
  uint8_t removed = PyObject_IsTrue(r) == 1 ? 1 : 0;
  Py_DECREF(r);
  return removed;
}

extern "C" YOutput *ymap_get(Branch *map, YTransaction *txn, const char *key) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !map || !key) return nullptr;
  return wrap_output(method_call(map->obj, "get", "(s)", key));
}

extern "C" void ymap_remove_all(Branch *map, YTransaction *txn) {
  Gil gil;
  if (!gil.ok || !map || !txn) return;
  PyObject *r = method_call(map->obj, "clear", "(O)", txn->obj);
  Py_XDECREF(r);
}

extern "C" YMapIter *ymap_iter(Branch *map, YTransaction *txn) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !map) return nullptr;
  PyObject *items = support_call("map_iter_items", "(O)", map->obj);
  if (!items) return nullptr;
  PyObject *it = PyObject_GetIter(items);
  Py_DECREF(items);
  if (!it) {
    set_err_py();
    return nullptr;
  }
  return new YMapIter{it};
}

extern "C" YMapEntry *ymap_iter_next(YMapIter *iter) {
  Gil gil;
  if (!gil.ok || !iter) return nullptr;
  PyObject *pair = PyIter_Next(iter->iter);
  if (!pair) {
    if (PyErr_Occurred()) set_err_py();
    return nullptr;
  }
  PyObject *key = PyTuple_GetItem(pair, 0);   /* borrowed */
  PyObject *value = PyTuple_GetItem(pair, 1); /* borrowed */
  if (!key || !value) {
    Py_DECREF(pair);
    set_err_py();
    return nullptr;
  }
  const char *k = PyUnicode_AsUTF8(key);
  YMapEntry *entry = new YMapEntry{dup_str(k ? k : ""), nullptr};
  Py_INCREF(value);
  entry->value = wrap_output(value);
  Py_DECREF(pair);
  return entry;
}

extern "C" void ymap_entry_destroy(YMapEntry *entry) {
  if (!entry) return;
  free(entry->key);
  youtput_destroy(entry->value);
  delete entry;
}

extern "C" void ymap_iter_destroy(YMapIter *iter) {
  if (!iter) return;
  Gil gil;
  if (gil.ok) Py_DECREF(iter->iter);
  delete iter;
}

/* ---- YXml ------------------------------------------------------------------- */
extern "C" char *yxmlelem_tag(Branch *xml) {
  Gil gil;
  if (!gil.ok || !xml) return nullptr;
  return py_to_cstr(PyObject_GetAttrString(xml->obj, "tag"));
}

extern "C" char *yxmlelem_string(Branch *xml, YTransaction *txn) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !xml) return nullptr;
  return py_to_cstr(method_call(xml->obj, "get_string", nullptr));
}

extern "C" void yxmlelem_insert_attr(Branch *xml, YTransaction *txn,
                                     const char *attr_name,
                                     const char *attr_value) {
  Gil gil;
  if (!gil.ok || !xml || !txn || !attr_name || !attr_value) return;
  PyObject *r = method_call(xml->obj, "insert_attribute", "(Oss)", txn->obj,
                            attr_name, attr_value);
  Py_XDECREF(r);
}

extern "C" void yxmlelem_remove_attr(Branch *xml, YTransaction *txn,
                                     const char *attr_name) {
  Gil gil;
  if (!gil.ok || !xml || !txn || !attr_name) return;
  PyObject *r =
      method_call(xml->obj, "remove_attribute", "(Os)", txn->obj, attr_name);
  Py_XDECREF(r);
}

extern "C" char *yxmlelem_get_attr(Branch *xml, YTransaction *txn,
                                   const char *attr_name) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !xml || !attr_name) return nullptr;
  return py_to_cstr(method_call(xml->obj, "get_attribute", "(s)", attr_name));
}

extern "C" uint32_t yxmlelem_child_len(Branch *xml, YTransaction *txn) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !xml) return 0;
  Py_ssize_t n = PyObject_Length(xml->obj);
  if (n < 0) {
    set_err_py();
    return 0;
  }
  return (uint32_t)n;
}

extern "C" Branch *yxmlelem_insert_elem(Branch *xml, YTransaction *txn,
                                        uint32_t index, const char *name) {
  Gil gil;
  if (!gil.ok || !xml || !txn || !name) return nullptr;
  return wrap_branch(support_call("xml_insert_elem", "(OOIs)", txn->obj,
                                  xml->obj, (unsigned)index, name));
}

extern "C" Branch *yxmlelem_insert_text(Branch *xml, YTransaction *txn,
                                        uint32_t index) {
  Gil gil;
  if (!gil.ok || !xml || !txn) return nullptr;
  return wrap_branch(support_call("xml_insert_text", "(OOI)", txn->obj,
                                  xml->obj, (unsigned)index));
}

extern "C" void yxmlelem_remove_range(Branch *xml, YTransaction *txn,
                                      uint32_t index, uint32_t len) {
  Gil gil;
  if (!gil.ok || !xml || !txn) return;
  PyObject *r = method_call(xml->obj, "remove_range", "(OII)", txn->obj,
                            (unsigned)index, (unsigned)len);
  Py_XDECREF(r);
}

extern "C" YOutput *yxmlelem_get(Branch *xml, YTransaction *txn,
                                 uint32_t index) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !xml) return nullptr;
  return wrap_output(method_call(xml->obj, "get", "(I)", (unsigned)index));
}

extern "C" YOutput *yxmlelem_first_child(Branch *xml) {
  Gil gil;
  if (!gil.ok || !xml) return nullptr;
  return wrap_output(method_call(xml->obj, "first_child", nullptr));
}

extern "C" YOutput *yxml_next_sibling(Branch *xml, YTransaction *txn) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !xml) return nullptr;
  return wrap_output(method_call(xml->obj, "next_sibling", nullptr));
}

extern "C" YOutput *yxml_prev_sibling(Branch *xml, YTransaction *txn) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !xml) return nullptr;
  return wrap_output(method_call(xml->obj, "prev_sibling", nullptr));
}

extern "C" YXmlTreeWalker *yxmlelem_tree_walker(Branch *xml,
                                                YTransaction *txn) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !xml) return nullptr;
  PyObject *walker = method_call(xml->obj, "successors", nullptr);
  if (!walker) return nullptr;
  PyObject *it = PyObject_GetIter(walker);
  Py_DECREF(walker);
  if (!it) {
    set_err_py();
    return nullptr;
  }
  return new YXmlTreeWalker{it};
}

extern "C" YOutput *yxmlelem_tree_walker_next(YXmlTreeWalker *walker) {
  Gil gil;
  if (!gil.ok || !walker) return nullptr;
  PyObject *v = PyIter_Next(walker->iter);
  if (!v) {
    if (PyErr_Occurred()) set_err_py();
    return nullptr;
  }
  return wrap_output(v);
}

extern "C" void yxmlelem_tree_walker_destroy(YXmlTreeWalker *walker) {
  if (!walker) return;
  Gil gil;
  if (gil.ok) Py_DECREF(walker->iter);
  delete walker;
}

extern "C" uint32_t yxmltext_len(Branch *xml, YTransaction *txn) {
  return ytext_len(xml, txn);
}

extern "C" char *yxmltext_string(Branch *xml, YTransaction *txn) {
  return ytext_string(xml, txn);
}

extern "C" void yxmltext_insert(Branch *xml, YTransaction *txn, uint32_t index,
                                const char *str, const char *attrs_json) {
  ytext_insert(xml, txn, index, str, attrs_json);
}

extern "C" void yxmltext_remove_range(Branch *xml, YTransaction *txn,
                                      uint32_t index, uint32_t len) {
  ytext_remove_range(xml, txn, index, len);
}

extern "C" void yxmltext_format(Branch *xml, YTransaction *txn, uint32_t index,
                                uint32_t len, const char *attrs_json) {
  ytext_format(xml, txn, index, len, attrs_json);
}

extern "C" void yxmltext_insert_attr(Branch *xml, YTransaction *txn,
                                     const char *attr_name,
                                     const char *attr_value) {
  yxmlelem_insert_attr(xml, txn, attr_name, attr_value);
}

extern "C" char *yxmltext_get_attr(Branch *xml, YTransaction *txn,
                                   const char *attr_name) {
  return yxmlelem_get_attr(xml, txn, attr_name);
}

/* ---- UndoManager ------------------------------------------------------------ */
extern "C" YUndoManager *yundo_manager(YDoc *doc,
                                       const YUndoManagerOptions *options) {
  Gil gil;
  if (!gil.ok || !doc) return nullptr;
  int timeout = options ? options->capture_timeout_millis : 500;
  PyObject *obj = support_call("undo_manager_new", "(Oi)", doc->obj, timeout);
  if (!obj) return nullptr;
  return new YUndoManager{obj};
}

extern "C" void yundo_manager_destroy(YUndoManager *mgr) {
  if (!mgr) return;
  Gil gil;
  if (gil.ok) Py_DECREF(mgr->obj);
  delete mgr;
}

extern "C" void yundo_manager_add_scope(YUndoManager *mgr, Branch *ytype) {
  Gil gil;
  if (!gil.ok || !mgr || !ytype) return;
  PyObject *r = method_call(mgr->obj, "expand_scope", "(O)", ytype->obj);
  Py_XDECREF(r);
}

static void undo_origin(YUndoManager *mgr, const char *origin, uint32_t len,
                        const char *fn) {
  Gil gil;
  if (!gil.ok || !mgr || !origin) return;
  PyObject *r =
      method_call(mgr->obj, fn, "(y#)", origin, (Py_ssize_t)len);
  Py_XDECREF(r);
}

extern "C" void yundo_manager_add_origin(YUndoManager *mgr,
                                         uint32_t origin_len,
                                         const char *origin) {
  undo_origin(mgr, origin, origin_len, "include_origin");
}

extern "C" void yundo_manager_remove_origin(YUndoManager *mgr,
                                            uint32_t origin_len,
                                            const char *origin) {
  undo_origin(mgr, origin, origin_len, "exclude_origin");
}

static uint8_t undo_flag(YUndoManager *mgr, const char *name) {
  Gil gil;
  if (!gil.ok || !mgr) return 0;
  PyObject *r = method_call(mgr->obj, name, nullptr);
  if (!r) return 0;
  uint8_t out = PyObject_IsTrue(r) == 1 ? 1 : 0;
  Py_DECREF(r);
  return out;
}

extern "C" uint8_t yundo_manager_undo(YUndoManager *mgr) {
  return undo_flag(mgr, "undo");
}
extern "C" uint8_t yundo_manager_redo(YUndoManager *mgr) {
  return undo_flag(mgr, "redo");
}
extern "C" uint8_t yundo_manager_can_undo(YUndoManager *mgr) {
  return undo_flag(mgr, "can_undo");
}
extern "C" uint8_t yundo_manager_can_redo(YUndoManager *mgr) {
  return undo_flag(mgr, "can_redo");
}
extern "C" void yundo_manager_clear(YUndoManager *mgr) {
  undo_flag(mgr, "clear");
}
extern "C" void yundo_manager_stop(YUndoManager *mgr) {
  undo_flag(mgr, "reset");
}

/* ---- StickyIndex ------------------------------------------------------------ */
extern "C" YStickyIndex *ysticky_index_from_index(Branch *ytype,
                                                  YTransaction *txn,
                                                  uint32_t index,
                                                  int8_t assoc) {
  Gil gil;
  if (!gil.ok || !ytype || !txn) return nullptr;
  PyObject *obj = support_call("sticky_from_index", "(OOIi)", txn->obj,
                               ytype->obj, (unsigned)index, (int)assoc);
  if (!obj) return nullptr;
  return new YStickyIndex{obj};
}

extern "C" void ysticky_index_destroy(YStickyIndex *pos) {
  if (!pos) return;
  Gil gil;
  if (gil.ok) Py_DECREF(pos->obj);
  delete pos;
}

extern "C" int8_t ysticky_index_assoc(YStickyIndex *pos) {
  Gil gil;
  if (!gil.ok || !pos) return Y_ASSOC_AFTER;
  PyObject *r = support_call("sticky_assoc", "(O)", pos->obj);
  if (!r) return Y_ASSOC_AFTER;
  int8_t assoc = (int8_t)PyLong_AsLong(r);
  Py_DECREF(r);
  return assoc;
}

extern "C" YBinary ysticky_index_encode(YStickyIndex *pos) {
  Gil gil;
  if (!gil.ok || !pos) return YBinary{nullptr, 0};
  return py_to_binary(support_call("sticky_encode", "(O)", pos->obj));
}

extern "C" YStickyIndex *ysticky_index_decode(const uint8_t *bin,
                                              uint32_t len) {
  Gil gil;
  if (!gil.ok || !bin) return nullptr;
  PyObject *obj = support_call("sticky_decode", "(y#)", (const char *)bin,
                               (Py_ssize_t)len);
  if (!obj) return nullptr;
  return new YStickyIndex{obj};
}

extern "C" uint8_t ysticky_index_read(YStickyIndex *pos, YTransaction *txn,
                                      uint32_t *out_index) {
  Gil gil;
  if (!gil.ok || !pos || !txn || !out_index) return 0;
  PyObject *r = support_call("sticky_read", "(OO)", pos->obj, txn->obj);
  if (!r) return 0;
  if (r == Py_None) {
    Py_DECREF(r);
    return 0;
  }
  *out_index = (uint32_t)PyLong_AsUnsignedLong(r);
  Py_DECREF(r);
  return 1;
}

/* ---- observers -------------------------------------------------------------- */
struct CallbackData {
  void *state;
  ytpu_observe_cb cb;
};

static void capsule_free(PyObject *capsule) {
  CallbackData *cd =
      (CallbackData *)PyCapsule_GetPointer(capsule, "ytpu.callback");
  delete cd;
}

static PyObject *observer_trampoline(PyObject *self, PyObject *args) {
  CallbackData *cd =
      (CallbackData *)PyCapsule_GetPointer(self, "ytpu.callback");
  if (!cd) return nullptr;
  PyObject *payload = nullptr;
  if (!PyArg_ParseTuple(args, "O", &payload)) return nullptr;
  const uint8_t *data = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_Check(payload)) {
    char *buf = nullptr;
    PyBytes_AsStringAndSize(payload, &buf, &len);
    data = (const uint8_t *)buf;
  }
  /* user C callback runs with the GIL held; it must not re-enter Python */
  cd->cb(cd->state, (uint32_t)len, data);
  Py_RETURN_NONE;
}

static PyMethodDef g_trampoline_def = {"_ytpu_observer", observer_trampoline,
                                       METH_VARARGS, nullptr};

static YSubscription *observe(YDoc *doc, int kind, void *state,
                              ytpu_observe_cb cb) {
  Gil gil;
  if (!gil.ok || !doc || !cb) return nullptr;
  CallbackData *cd = new CallbackData{state, cb};
  PyObject *capsule = PyCapsule_New(cd, "ytpu.callback", capsule_free);
  if (!capsule) {
    delete cd;
    set_err_py();
    return nullptr;
  }
  PyObject *fn = PyCFunction_New(&g_trampoline_def, capsule);
  Py_DECREF(capsule); /* fn owns it now */
  if (!fn) {
    set_err_py();
    return nullptr;
  }
  PyObject *unobserve = support_call("observe", "(OiO)", doc->obj, kind, fn);
  if (!unobserve) {
    Py_DECREF(fn);
    return nullptr;
  }
  return new YSubscription{unobserve, fn};
}

extern "C" YSubscription *ydoc_observe_updates_v1(YDoc *doc, void *state,
                                                  ytpu_observe_cb cb) {
  return observe(doc, 0, state, cb);
}

extern "C" YSubscription *ydoc_observe_updates_v2(YDoc *doc, void *state,
                                                  ytpu_observe_cb cb) {
  return observe(doc, 1, state, cb);
}

extern "C" YSubscription *ydoc_observe_after_transaction(YDoc *doc,
                                                         void *state,
                                                         ytpu_observe_cb cb) {
  return observe(doc, 2, state, cb);
}

/* ---- typed event observers --------------------------------------------- */
/* One trampoline family for every callback that delivers a structured
 * event. The capsule carries the user's state+fn plus a kind selector so
 * a single PyCFunction body can unpack the support-layer payload. */
enum TypedCbKind {
  CB_EVENT = 0,    /* args: (event,)                         */
  CB_DEEP = 1,     /* args: (events_list,)                   */
  CB_SUBDOCS = 2,  /* args: (added, removed, loaded) lists   */
  CB_CLEAR = 3,    /* args: (doc,)                           */
  CB_UNDO = 4,     /* args: (kind, origin|None, stack_item)  */
};

struct TypedCbData {
  void *state;
  void *cb;
  int kind;
};

static void typed_capsule_free(PyObject *capsule) {
  TypedCbData *cd =
      (TypedCbData *)PyCapsule_GetPointer(capsule, "ytpu.typed_callback");
  delete cd;
}

static PyObject *typed_trampoline(PyObject *self, PyObject *args) {
  TypedCbData *cd =
      (TypedCbData *)PyCapsule_GetPointer(self, "ytpu.typed_callback");
  if (!cd) return nullptr;
  switch (cd->kind) {
    case CB_EVENT: {
      PyObject *ev = nullptr;
      if (!PyArg_ParseTuple(args, "O", &ev)) return nullptr;
      YEvent e{ev};
      ((void (*)(void *, const YEvent *))cd->cb)(cd->state, &e);
      break;
    }
    case CB_DEEP: {
      PyObject *list = nullptr;
      if (!PyArg_ParseTuple(args, "O", &list)) return nullptr;
      Py_ssize_t n = PySequence_Length(list);
      if (n < 0) return nullptr;
      YEvent *events = new YEvent[n > 0 ? n : 1];
      const YEvent **ptrs = new const YEvent *[n > 0 ? n : 1];
      bool ok = true;
      for (Py_ssize_t i = 0; i < n; ++i) {
        PyObject *item = PySequence_GetItem(list, i); /* new ref */
        if (!item) {
          ok = false;
          break;
        }
        events[i].obj = item;
        ptrs[i] = &events[i];
      }
      if (ok) {
        ((void (*)(void *, uint32_t, const YEvent *const *))cd->cb)(
            cd->state, (uint32_t)n, ptrs);
      }
      for (Py_ssize_t i = 0; i < n; ++i) {
        if (events[i].obj) Py_DECREF(events[i].obj);
      }
      delete[] events;
      delete[] ptrs;
      if (!ok) return nullptr;
      break;
    }
    case CB_SUBDOCS: {
      PyObject *added = nullptr, *removed = nullptr, *loaded = nullptr;
      if (!PyArg_ParseTuple(args, "OOO", &added, &removed, &loaded))
        return nullptr;
      YSubdocsEvent ev{};
      PyObject *lists[3] = {added, removed, loaded};
      YDoc **arrays[3] = {nullptr, nullptr, nullptr};
      uint32_t lens[3] = {0, 0, 0};
      for (int k = 0; k < 3; ++k) {
        Py_ssize_t n = PySequence_Length(lists[k]);
        lens[k] = n > 0 ? (uint32_t)n : 0;
        arrays[k] = new YDoc *[lens[k] ? lens[k] : 1];
        for (uint32_t i = 0; i < lens[k]; ++i) {
          PyObject *d = PySequence_GetItem(lists[k], (Py_ssize_t)i);
          arrays[k][i] = d ? new YDoc{d} : nullptr; /* owns the new ref */
        }
      }
      ev.added_len = lens[0];
      ev.removed_len = lens[1];
      ev.loaded_len = lens[2];
      ev.added = arrays[0];
      ev.removed = arrays[1];
      ev.loaded = arrays[2];
      ((void (*)(void *, const YSubdocsEvent *))cd->cb)(cd->state, &ev);
      for (int k = 0; k < 3; ++k) {
        for (uint32_t i = 0; i < lens[k]; ++i) {
          if (arrays[k][i]) {
            Py_DECREF(arrays[k][i]->obj);
            delete arrays[k][i];
          }
        }
        delete[] arrays[k];
      }
      break;
    }
    case CB_CLEAR: {
      PyObject *doc = nullptr;
      if (!PyArg_ParseTuple(args, "O", &doc)) return nullptr;
      YDoc handle{doc};
      ((void (*)(void *, YDoc *))cd->cb)(cd->state, &handle);
      break;
    }
    case CB_UNDO: {
      int kind = 0;
      PyObject *origin = nullptr, *item = nullptr;
      if (!PyArg_ParseTuple(args, "iOO", &kind, &origin, &item))
        return nullptr;
      YUndoEvent ev{};
      ev.kind = (char)kind;
      const char *obuf = nullptr;
      Py_ssize_t olen = 0;
      if (origin != Py_None && PyBytes_Check(origin)) {
        PyBytes_AsStringAndSize(origin, (char **)&obuf, &olen);
      }
      ev.origin = obuf;
      ev.origin_len = (uint32_t)olen;
      PyObject *meta = support_call("undo_item_meta", "(O)", item);
      ev.meta = meta ? (void *)(intptr_t)PyLong_AsLongLong(meta) : nullptr;
      Py_XDECREF(meta);
      ((void (*)(void *, YUndoEvent *))cd->cb)(cd->state, &ev);
      PyObject *r = support_call("undo_item_set_meta", "(OL)", item,
                                 (long long)(intptr_t)ev.meta);
      Py_XDECREF(r);
      break;
    }
  }
  Py_RETURN_NONE;
}

static PyMethodDef g_typed_trampoline_def = {
    "_ytpu_typed_observer", typed_trampoline, METH_VARARGS, nullptr};

/* Register through a support-module function whose last arg is the python
 * callback; `fmt_head` describes the leading args. */
static YSubscription *typed_observe(int kind, void *state, void *cb,
                                    const char *support_fn, PyObject *target,
                                    int extra_int, bool has_extra) {
  Gil gil;
  if (!gil.ok || !target || !cb) return nullptr;
  TypedCbData *cd = new TypedCbData{state, cb, kind};
  PyObject *capsule = PyCapsule_New(cd, "ytpu.typed_callback",
                                    typed_capsule_free);
  if (!capsule) {
    delete cd;
    set_err_py();
    return nullptr;
  }
  PyObject *fn = PyCFunction_New(&g_typed_trampoline_def, capsule);
  Py_DECREF(capsule);
  if (!fn) {
    set_err_py();
    return nullptr;
  }
  PyObject *unobserve =
      has_extra ? support_call(support_fn, "(OiO)", target, extra_int, fn)
                : support_call(support_fn, "(OO)", target, fn);
  if (!unobserve) {
    Py_DECREF(fn);
    return nullptr;
  }
  return new YSubscription{unobserve, fn};
}

extern "C" YSubscription *ytext_observe(Branch *txt, void *state,
                                        void (*cb)(void *,
                                                   const YEvent *)) {
  return typed_observe(CB_EVENT, state, (void *)cb, "observe_type",
                       txt ? txt->obj : nullptr, 0, false);
}
extern "C" YSubscription *yarray_observe(Branch *array, void *state,
                                         void (*cb)(void *,
                                                    const YEvent *)) {
  return typed_observe(CB_EVENT, state, (void *)cb, "observe_type",
                       array ? array->obj : nullptr, 0, false);
}
extern "C" YSubscription *ymap_observe(Branch *map, void *state,
                                       void (*cb)(void *, const YEvent *)) {
  return typed_observe(CB_EVENT, state, (void *)cb, "observe_type",
                       map ? map->obj : nullptr, 0, false);
}
extern "C" YSubscription *yxmlelem_observe(Branch *xml, void *state,
                                           void (*cb)(void *,
                                                      const YEvent *)) {
  return typed_observe(CB_EVENT, state, (void *)cb, "observe_type",
                       xml ? xml->obj : nullptr, 0, false);
}
extern "C" YSubscription *yxmltext_observe(Branch *xml, void *state,
                                           void (*cb)(void *,
                                                      const YEvent *)) {
  return typed_observe(CB_EVENT, state, (void *)cb, "observe_type",
                       xml ? xml->obj : nullptr, 0, false);
}
extern "C" YSubscription *yweak_observe(Branch *weak, void *state,
                                        void (*cb)(void *,
                                                   const YEvent *)) {
  return typed_observe(CB_EVENT, state, (void *)cb, "observe_type",
                       weak ? weak->obj : nullptr, 0, false);
}
extern "C" YSubscription *yobserve_deep(Branch *ytype, void *state,
                                        void (*cb)(void *, uint32_t,
                                                   const YEvent *const *)) {
  return typed_observe(CB_DEEP, state, (void *)cb, "observe_deep_type",
                       ytype ? ytype->obj : nullptr, 0, false);
}
extern "C" YSubscription *ydoc_observe_subdocs(
    YDoc *doc, void *state, void (*cb)(void *, const YSubdocsEvent *)) {
  return typed_observe(CB_SUBDOCS, state, (void *)cb, "observe_subdocs",
                       doc ? doc->obj : nullptr, 0, false);
}
extern "C" YSubscription *ydoc_observe_clear(YDoc *doc, void *state,
                                             void (*cb)(void *, YDoc *)) {
  return typed_observe(CB_CLEAR, state, (void *)cb, "observe_clear",
                       doc ? doc->obj : nullptr, 0, false);
}
extern "C" YSubscription *yundo_manager_observe_added(
    YUndoManager *mgr, void *state, void (*cb)(void *, YUndoEvent *)) {
  return typed_observe(CB_UNDO, state, (void *)cb, "undo_observe",
                       mgr ? mgr->obj : nullptr, 0, true);
}
extern "C" YSubscription *yundo_manager_observe_popped(
    YUndoManager *mgr, void *state, void (*cb)(void *, YUndoEvent *)) {
  return typed_observe(CB_UNDO, state, (void *)cb, "undo_observe",
                       mgr ? mgr->obj : nullptr, 1, true);
}

/* ---- event accessors ----------------------------------------------------- */
extern "C" int8_t yevent_kind(const YEvent *e) {
  Gil gil;
  if (!gil.ok || !e) return Y_JSON_UNDEF;
  PyObject *r = support_call("event_kind", "(O)", e->obj);
  if (!r) return Y_JSON_UNDEF;
  int8_t kind = (int8_t)PyLong_AsLong(r);
  Py_DECREF(r);
  return kind;
}

static Branch *event_target(const YEvent *e) {
  Gil gil;
  if (!gil.ok || !e) return nullptr;
  return wrap_branch(support_call("event_target", "(O)", e->obj));
}
extern "C" Branch *ytext_event_target(const YEvent *e) {
  return event_target(e);
}
extern "C" Branch *yarray_event_target(const YEvent *e) {
  return event_target(e);
}
extern "C" Branch *ymap_event_target(const YEvent *e) {
  return event_target(e);
}
extern "C" Branch *yxmlelem_event_target(const YEvent *e) {
  return event_target(e);
}
extern "C" Branch *yxmltext_event_target(const YEvent *e) {
  return event_target(e);
}

static YPathSegment *event_path(const YEvent *e, uint32_t *len) {
  if (len) *len = 0;
  Gil gil;
  if (!gil.ok || !e || !len) return nullptr;
  PyObject *path = support_call("event_path", "(O)", e->obj);
  if (!path) return nullptr;
  Py_ssize_t n = PySequence_Length(path);
  YPathSegment *out =
      (YPathSegment *)calloc(n > 0 ? (size_t)n : 1, sizeof(YPathSegment));
  for (Py_ssize_t i = 0; i < n && out; ++i) {
    PyObject *seg = PySequence_GetItem(path, i);
    if (!seg) break;
    if (PyUnicode_Check(seg)) {
      out[i].tag = Y_EVENT_PATH_KEY;
      const char *s = PyUnicode_AsUTF8(seg);
      out[i].value.key = dup_str(s ? s : "");
    } else {
      out[i].tag = Y_EVENT_PATH_INDEX;
      out[i].value.index = (uint32_t)PyLong_AsUnsignedLong(seg);
    }
    Py_DECREF(seg);
  }
  Py_DECREF(path);
  *len = (uint32_t)(n > 0 ? n : 0);
  return out;
}
extern "C" YPathSegment *ytext_event_path(const YEvent *e, uint32_t *len) {
  return event_path(e, len);
}
extern "C" YPathSegment *yarray_event_path(const YEvent *e, uint32_t *len) {
  return event_path(e, len);
}
extern "C" YPathSegment *ymap_event_path(const YEvent *e, uint32_t *len) {
  return event_path(e, len);
}
extern "C" YPathSegment *yxmlelem_event_path(const YEvent *e, uint32_t *len) {
  return event_path(e, len);
}
extern "C" YPathSegment *yxmltext_event_path(const YEvent *e, uint32_t *len) {
  return event_path(e, len);
}
extern "C" void ypath_destroy(YPathSegment *path, uint32_t len) {
  if (!path) return;
  for (uint32_t i = 0; i < len; ++i) {
    if (path[i].tag == Y_EVENT_PATH_KEY) free(path[i].value.key);
  }
  free(path);
}

static YDelta *event_delta_text(const YEvent *e, uint32_t *len) {
  if (len) *len = 0;
  Gil gil;
  if (!gil.ok || !e || !len) return nullptr;
  PyObject *rows = support_call("event_delta_text", "(O)", e->obj);
  if (!rows) return nullptr;
  Py_ssize_t n = PySequence_Length(rows);
  YDelta *out = (YDelta *)calloc(n > 0 ? (size_t)n : 1, sizeof(YDelta));
  for (Py_ssize_t i = 0; i < n && out; ++i) {
    PyObject *row = PySequence_GetItem(rows, i); /* (tag,len,ins,attrs) */
    if (!row) break;
    int tag = 0;
    unsigned length = 0;
    PyObject *insert = nullptr, *attrs = nullptr;
    if (PyArg_ParseTuple(row, "iIOO", &tag, &length, &insert, &attrs)) {
      out[i].tag = (char)tag;
      out[i].len = length;
      if (insert != Py_None) {
        Py_INCREF(insert);
        out[i].insert = wrap_output(insert);
      }
      if (attrs != Py_None) {
        Py_ssize_t an = PySequence_Length(attrs);
        out[i].attributes =
            (YDeltaAttr *)calloc(an > 0 ? (size_t)an : 1, sizeof(YDeltaAttr));
        out[i].attributes_len = (uint32_t)(an > 0 ? an : 0);
        for (Py_ssize_t a = 0; a < an && out[i].attributes; ++a) {
          PyObject *pair = PySequence_GetItem(attrs, a);
          const char *k = nullptr;
          PyObject *v = nullptr;
          if (pair && PyArg_ParseTuple(pair, "sO", &k, &v)) {
            out[i].attributes[a].key = dup_str(k);
            Py_INCREF(v);
            out[i].attributes[a].value_json =
                py_to_cstr(support_call("output_json", "(N)", v));
          }
          Py_XDECREF(pair);
        }
      }
    }
    Py_DECREF(row);
  }
  Py_DECREF(rows);
  *len = (uint32_t)(n > 0 ? n : 0);
  return out;
}
extern "C" YDelta *ytext_event_delta(const YEvent *e, uint32_t *len) {
  return event_delta_text(e, len);
}
extern "C" YDelta *yxmltext_event_delta(const YEvent *e, uint32_t *len) {
  return event_delta_text(e, len);
}
extern "C" void ytext_delta_destroy(YDelta *delta, uint32_t len) {
  if (!delta) return;
  for (uint32_t i = 0; i < len; ++i) {
    if (delta[i].insert) youtput_destroy(delta[i].insert);
    for (uint32_t a = 0; a < delta[i].attributes_len; ++a) {
      free(delta[i].attributes[a].key);
      free(delta[i].attributes[a].value_json);
    }
    free(delta[i].attributes);
  }
  free(delta);
}

static YEventChange *event_delta_seq(const YEvent *e, uint32_t *len) {
  if (len) *len = 0;
  Gil gil;
  if (!gil.ok || !e || !len) return nullptr;
  PyObject *rows = support_call("event_delta_seq", "(O)", e->obj);
  if (!rows) return nullptr;
  Py_ssize_t n = PySequence_Length(rows);
  YEventChange *out =
      (YEventChange *)calloc(n > 0 ? (size_t)n : 1, sizeof(YEventChange));
  for (Py_ssize_t i = 0; i < n && out; ++i) {
    PyObject *row = PySequence_GetItem(rows, i); /* (tag, len, values) */
    if (!row) break;
    int tag = 0;
    unsigned length = 0;
    PyObject *values = nullptr;
    if (PyArg_ParseTuple(row, "iIO", &tag, &length, &values)) {
      out[i].tag = (char)tag;
      out[i].len = length;
      if (values != Py_None) {
        Py_ssize_t vn = PySequence_Length(values);
        out[i].values =
            (YOutput **)calloc(vn > 0 ? (size_t)vn : 1, sizeof(YOutput *));
        for (Py_ssize_t v = 0; v < vn && out[i].values; ++v) {
          PyObject *item = PySequence_GetItem(values, v);
          out[i].values[v] = item ? new YOutput{item} : nullptr;
        }
      }
    }
    Py_DECREF(row);
  }
  Py_DECREF(rows);
  *len = (uint32_t)(n > 0 ? n : 0);
  return out;
}
extern "C" YEventChange *yarray_event_delta(const YEvent *e, uint32_t *len) {
  return event_delta_seq(e, len);
}
extern "C" YEventChange *yxmlelem_event_delta(const YEvent *e,
                                              uint32_t *len) {
  return event_delta_seq(e, len);
}
extern "C" void yevent_delta_destroy(YEventChange *delta, uint32_t len) {
  if (!delta) return;
  for (uint32_t i = 0; i < len; ++i) {
    if (delta[i].values) {
      for (uint32_t v = 0; v < delta[i].len; ++v) {
        if (delta[i].values[v]) youtput_destroy(delta[i].values[v]);
      }
      free(delta[i].values);
    }
  }
  free(delta);
}

static YEventKeyChange *event_keys(const YEvent *e, uint32_t *len) {
  if (len) *len = 0;
  Gil gil;
  if (!gil.ok || !e || !len) return nullptr;
  PyObject *rows = support_call("event_keys", "(O)", e->obj);
  if (!rows) return nullptr;
  Py_ssize_t n = PySequence_Length(rows);
  YEventKeyChange *out = (YEventKeyChange *)calloc(
      n > 0 ? (size_t)n : 1, sizeof(YEventKeyChange));
  for (Py_ssize_t i = 0; i < n && out; ++i) {
    PyObject *row = PySequence_GetItem(rows, i); /* (key, tag, old, new) */
    if (!row) break;
    const char *key = nullptr;
    int tag = 0;
    PyObject *oldv = nullptr, *newv = nullptr;
    if (PyArg_ParseTuple(row, "siOO", &key, &tag, &oldv, &newv)) {
      out[i].key = dup_str(key);
      out[i].tag = (char)tag;
      if (oldv != Py_None) {
        Py_INCREF(oldv);
        out[i].old_value = wrap_output(oldv);
      }
      if (newv != Py_None) {
        Py_INCREF(newv);
        out[i].new_value = wrap_output(newv);
      }
    }
    Py_DECREF(row);
  }
  Py_DECREF(rows);
  *len = (uint32_t)(n > 0 ? n : 0);
  return out;
}
extern "C" YEventKeyChange *ymap_event_keys(const YEvent *e, uint32_t *len) {
  return event_keys(e, len);
}
extern "C" YEventKeyChange *yxmlelem_event_keys(const YEvent *e,
                                                uint32_t *len) {
  return event_keys(e, len);
}
extern "C" YEventKeyChange *yxmltext_event_keys(const YEvent *e,
                                                uint32_t *len) {
  return event_keys(e, len);
}
extern "C" void yevent_keys_destroy(YEventKeyChange *keys, uint32_t len) {
  if (!keys) return;
  for (uint32_t i = 0; i < len; ++i) {
    free(keys[i].key);
    if (keys[i].old_value) youtput_destroy(keys[i].old_value);
    if (keys[i].new_value) youtput_destroy(keys[i].new_value);
  }
  free(keys);
}

extern "C" void yunobserve(YSubscription *subscription) {
  if (!subscription) return;
  Gil gil;
  if (gil.ok) {
    PyObject *r = PyObject_CallObject(subscription->unobserve, nullptr);
    if (!r) {
      set_err_py();
    } else {
      Py_DECREF(r);
    }
    Py_DECREF(subscription->unobserve);
    Py_DECREF(subscription->callback);
  }
  delete subscription;
}

/* ---- default options (yffi: yoptions) ------------------------------------ */
extern "C" YOptions yoptions(void) {
  YOptions o{};
  o.id = 0;
  o.guid = nullptr;
  o.collection_id = nullptr;
  o.encoding = Y_OFFSET_UTF16;
  o.skip_gc = 0;
  o.auto_load = 0;
  o.should_load = 1;
  return o;
}

/* ---- YInput constructors (yffi: yinput_*) -------------------------------- */
extern "C" YInput yinput_null(void) {
  YInput i{};
  i.len = 1;
  i.tag = Y_JSON_NULL;
  return i;
}
extern "C" YInput yinput_undefined(void) {
  YInput i{};
  i.len = 1;
  i.tag = Y_JSON_UNDEF;
  return i;
}
extern "C" YInput yinput_bool(uint8_t flag) {
  YInput i{};
  i.len = 1;
  i.tag = Y_JSON_BOOL;
  i.value.flag = flag;
  return i;
}
extern "C" YInput yinput_float(double num) {
  YInput i{};
  i.len = 1;
  i.tag = Y_JSON_NUM;
  i.value.num = num;
  return i;
}
extern "C" YInput yinput_long(int64_t integer) {
  YInput i{};
  i.len = 1;
  i.tag = Y_JSON_INT;
  i.value.integer = integer;
  return i;
}
extern "C" YInput yinput_string(const char *str) {
  YInput i{};
  i.len = 1;
  i.tag = Y_JSON_STR;
  i.value.str = str;
  return i;
}
extern "C" YInput yinput_binary(const uint8_t *buf, uint32_t len) {
  YInput i{};
  i.len = 1;
  i.tag = Y_JSON_BUF;
  i.value.buf.data = buf;
  i.value.buf.len = len;
  return i;
}
extern "C" YInput yinput_json_array(YInput *values, uint32_t len) {
  YInput i{};
  i.tag = Y_JSON_ARR;
  i.len = len;
  i.value.values = values;
  return i;
}
extern "C" YInput yinput_json_map(char **keys, YInput *values, uint32_t len) {
  YInput i{};
  i.tag = Y_JSON_MAP;
  i.len = len;
  i.value.map.keys = keys;
  i.value.map.values = values;
  return i;
}
extern "C" YInput yinput_json_array_str(const char *json) {
  YInput i{};
  i.tag = Y_JSON_ARR;
  i.len = YINPUT_STR_FORM;
  i.value.str = json;
  return i;
}
extern "C" YInput yinput_json_map_str(const char *json) {
  YInput i{};
  i.tag = Y_JSON_MAP;
  i.len = YINPUT_STR_FORM;
  i.value.str = json;
  return i;
}
extern "C" YInput yinput_ytext(const char *init) {
  YInput i{};
  i.tag = Y_TEXT;
  i.len = YINPUT_STR_FORM;
  i.value.str = init;
  return i;
}
extern "C" YInput yinput_yarray(YInput *values, uint32_t len) {
  YInput i{};
  i.tag = Y_ARRAY;
  i.len = len;
  i.value.values = values;
  return i;
}
extern "C" YInput yinput_ymap(char **keys, YInput *values, uint32_t len) {
  YInput i{};
  i.tag = Y_MAP;
  i.len = len;
  i.value.map.keys = keys;
  i.value.map.values = values;
  return i;
}
extern "C" YInput yinput_yarray_str(const char *init_json) {
  YInput i{};
  i.tag = Y_ARRAY;
  i.len = YINPUT_STR_FORM;
  i.value.str = init_json;
  return i;
}
extern "C" YInput yinput_ymap_str(const char *init_json) {
  YInput i{};
  i.tag = Y_MAP;
  i.len = YINPUT_STR_FORM;
  i.value.str = init_json;
  return i;
}
extern "C" YInput yinput_yxmlelem(const char *name) {
  YInput i{};
  i.tag = Y_XML_ELEM;
  i.value.str = name;
  return i;
}
extern "C" YInput yinput_yxmltext(const char *init) {
  YInput i{};
  i.tag = Y_XML_TEXT;
  i.value.str = init;
  return i;
}
extern "C" YInput yinput_ydoc(YDoc *doc) {
  YInput i{};
  i.len = 1;
  i.tag = Y_DOC;
  i.value.doc = doc;
  return i;
}
extern "C" YInput yinput_weak(const YWeak *weak) {
  YInput i{};
  i.len = 1;
  i.tag = Y_WEAK_LINK;
  i.value.weak = weak;
  return i;
}

/* ---- YOutput collection readers ------------------------------------------ */
extern "C" YOutput **youtput_read_json_array(YOutput *val, uint32_t *len) {
  if (len) *len = 0;
  Gil gil;
  if (!gil.ok || !val || !len || !PyList_Check(val->obj)) return nullptr;
  Py_ssize_t n = PyList_Size(val->obj);
  YOutput **out =
      (YOutput **)calloc(n > 0 ? (size_t)n : 1, sizeof(YOutput *));
  for (Py_ssize_t i = 0; i < n && out; ++i) {
    PyObject *item = PyList_GetItem(val->obj, i); /* borrowed */
    if (item) {
      Py_INCREF(item);
      out[i] = new YOutput{item};
    }
  }
  *len = (uint32_t)(n > 0 ? n : 0);
  return out;
}

extern "C" YMapEntry **youtput_read_json_map(YOutput *val, uint32_t *len) {
  if (len) *len = 0;
  Gil gil;
  if (!gil.ok || !val || !len || !PyDict_Check(val->obj)) return nullptr;
  Py_ssize_t n = PyDict_Size(val->obj);
  YMapEntry **out =
      (YMapEntry **)calloc(n > 0 ? (size_t)n : 1, sizeof(YMapEntry *));
  Py_ssize_t pos = 0, i = 0;
  PyObject *key = nullptr, *value = nullptr;
  while (out && PyDict_Next(val->obj, &pos, &key, &value) && i < n) {
    const char *k = PyUnicode_Check(key) ? PyUnicode_AsUTF8(key) : nullptr;
    Py_INCREF(value);
    out[i] = new YMapEntry{dup_str(k ? k : ""), wrap_output(value)};
    ++i;
  }
  *len = (uint32_t)(n > 0 ? n : 0);
  return out;
}

extern "C" Branch *youtput_read_yweak(YOutput *val) {
  Gil gil;
  if (!gil.ok || !val) return nullptr;
  PyObject *r = support_call("output_tag", "(O)", val->obj);
  if (!r) return nullptr;
  int8_t tag = (int8_t)PyLong_AsLong(r);
  Py_DECREF(r);
  if (tag != Y_WEAK_LINK) return nullptr;
  Py_INCREF(val->obj);
  return new Branch{val->obj};
}

/* ---- doc clear / subdocs -------------------------------------------------- */
extern "C" void ydoc_clear(YDoc *doc, YTransaction *parent_txn) {
  (void)parent_txn;
  Gil gil;
  if (!gil.ok || !doc) return;
  PyObject *r = support_call("doc_clear", "(O)", doc->obj);
  Py_XDECREF(r);
}

extern "C" YDoc **ytransaction_subdocs(YTransaction *txn, uint32_t *len) {
  if (len) *len = 0;
  Gil gil;
  if (!gil.ok || !txn || !len) return nullptr;
  PyObject *docs = support_call("txn_subdocs", "(O)", txn->obj);
  if (!docs) return nullptr;
  Py_ssize_t n = PySequence_Length(docs);
  YDoc **out = (YDoc **)calloc(n > 0 ? (size_t)n : 1, sizeof(YDoc *));
  for (Py_ssize_t i = 0; i < n && out; ++i) {
    PyObject *d = PySequence_GetItem(docs, i); /* new ref */
    out[i] = d ? new YDoc{d} : nullptr;
  }
  Py_DECREF(docs);
  *len = (uint32_t)(n > 0 ? n : 0);
  return out;
}

/* ---- pending introspection ------------------------------------------------ */
extern "C" YPendingUpdate *ytransaction_pending_update(YTransaction *txn) {
  Gil gil;
  if (!gil.ok || !txn) return nullptr;
  PyObject *r = support_call("txn_pending_update", "(O)", txn->obj);
  if (!r) return nullptr;
  if (r == Py_None) {
    Py_DECREF(r);
    return nullptr;
  }
  PyObject *missing = PyTuple_GetItem(r, 0); /* borrowed */
  PyObject *update = PyTuple_GetItem(r, 1);  /* borrowed */
  if (!missing || !update) {
    Py_DECREF(r);
    set_err_py();
    return nullptr;
  }
  YPendingUpdate *out = new YPendingUpdate{};
  Py_INCREF(missing);
  out->missing = py_to_binary(missing);
  Py_INCREF(update);
  out->update_v1 = py_to_binary(update);
  Py_DECREF(r);
  return out;
}

extern "C" void ypending_update_destroy(YPendingUpdate *update) {
  if (!update) return;
  free(update->missing.data);
  free(update->update_v1.data);
  delete update;
}

extern "C" YDeleteSet *ytransaction_pending_ds(YTransaction *txn) {
  Gil gil;
  if (!gil.ok || !txn) return nullptr;
  PyObject *r = support_call("txn_pending_ds", "(O)", txn->obj);
  if (!r) return nullptr;
  if (r == Py_None) {
    Py_DECREF(r);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Length(r);
  YDeleteSet *ds = new YDeleteSet{};
  ds->entries_len = (uint32_t)(n > 0 ? n : 0);
  ds->client_ids =
      (uint64_t *)calloc(n > 0 ? (size_t)n : 1, sizeof(uint64_t));
  ds->ranges =
      (YIdRangeSeq *)calloc(n > 0 ? (size_t)n : 1, sizeof(YIdRangeSeq));
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *entry = PySequence_GetItem(r, i); /* (client, [(s,l)...]) */
    unsigned long long client = 0;
    PyObject *ranges = nullptr;
    if (entry && PyArg_ParseTuple(entry, "KO", &client, &ranges)) {
      ds->client_ids[i] = client;
      Py_ssize_t rn = PySequence_Length(ranges);
      ds->ranges[i].len = (uint32_t)(rn > 0 ? rn : 0);
      ds->ranges[i].seq =
          (YIdRange *)calloc(rn > 0 ? (size_t)rn : 1, sizeof(YIdRange));
      for (Py_ssize_t j = 0; j < rn; ++j) {
        PyObject *pair = PySequence_GetItem(ranges, j);
        unsigned start = 0, rlen = 0;
        if (pair && PyArg_ParseTuple(pair, "II", &start, &rlen)) {
          ds->ranges[i].seq[j].start = start;
          ds->ranges[i].seq[j].len = rlen;
        }
        Py_XDECREF(pair);
      }
    }
    Py_XDECREF(entry);
  }
  Py_DECREF(r);
  return ds;
}

extern "C" void ydelete_set_destroy(YDeleteSet *ds) {
  if (!ds) return;
  for (uint32_t i = 0; i < ds->entries_len; ++i) free(ds->ranges[i].seq);
  free(ds->ranges);
  free(ds->client_ids);
  delete ds;
}

/* ---- logical branch ids --------------------------------------------------- */
extern "C" YBranchId ybranch_id(Branch *branch) {
  YBranchId id{};
  id.client_or_len = 0;
  Gil gil;
  if (!gil.ok || !branch) return id;
  PyObject *r = support_call("branch_id", "(O)", branch->obj);
  if (!r) return id;
  int nested = 0;
  if (PyTuple_Size(r) == 3) {
    unsigned long long client = 0;
    unsigned clock = 0;
    if (PyArg_ParseTuple(r, "iKI", &nested, &client, &clock)) {
      id.client_or_len = (int64_t)client;
      id.variant.clock = clock;
    }
  } else {
    PyObject *name = nullptr;
    if (PyArg_ParseTuple(r, "iO", &nested, &name) && name != Py_None) {
      const char *s = PyUnicode_AsUTF8(name);
      if (s) {
        id.client_or_len = -(int64_t)strlen(s);
        id.variant.name = (const uint8_t *)dup_str(s);
      }
    }
  }
  Py_DECREF(r);
  return id;
}

extern "C" Branch *ybranch_get(const YBranchId *branch_id,
                               YTransaction *txn) {
  Gil gil;
  if (!gil.ok || !branch_id || !txn) return nullptr;
  if (branch_id->client_or_len >= 0) {
    return wrap_branch(support_call(
        "branch_get", "(OiKIz)", txn->obj, 1,
        (unsigned long long)branch_id->client_or_len,
        (unsigned)branch_id->variant.clock, (const char *)nullptr));
  }
  size_t nlen = (size_t)(-branch_id->client_or_len);
  std::string name((const char *)branch_id->variant.name, nlen);
  return wrap_branch(support_call("branch_get", "(OiKIs)", txn->obj, 0, 0ULL,
                                  0u, name.c_str()));
}

extern "C" Branch *ytype_get(YTransaction *txn, const char *name) {
  Gil gil;
  if (!gil.ok || !txn || !name) return nullptr;
  return wrap_branch(support_call("type_get", "(Os)", txn->obj, name));
}

/* ---- weak links / quotations ---------------------------------------------- */
static YWeak *quote_common(Branch *seq, YTransaction *txn, uint32_t start,
                           uint32_t end, int8_t start_excl, int8_t end_excl) {
  Gil gil;
  if (!gil.ok || !seq || !txn) return nullptr;
  PyObject *obj =
      support_call("quote", "(OOIIii)", txn->obj, seq->obj, (unsigned)start,
                   (unsigned)end, (int)start_excl, (int)end_excl);
  if (!obj) return nullptr;
  return new YWeak{obj};
}

extern "C" YWeak *ytext_quote(Branch *text, YTransaction *txn,
                              uint32_t start_index, uint32_t end_index,
                              int8_t start_exclusive, int8_t end_exclusive) {
  return quote_common(text, txn, start_index, end_index, start_exclusive,
                      end_exclusive);
}

extern "C" YWeak *yarray_quote(Branch *array, YTransaction *txn,
                               uint32_t start_index, uint32_t end_index,
                               int8_t start_exclusive, int8_t end_exclusive) {
  return quote_common(array, txn, start_index, end_index, start_exclusive,
                      end_exclusive);
}

extern "C" YWeak *ymap_link(Branch *map, YTransaction *txn, const char *key) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !map || !key) return nullptr;
  PyObject *obj = support_call("map_link", "(Os)", map->obj, key);
  if (!obj || obj == Py_None) {
    Py_XDECREF(obj);
    return nullptr;
  }
  return new YWeak{obj};
}

extern "C" void yweak_destroy(YWeak *weak) {
  if (!weak) return;
  Gil gil;
  if (gil.ok) Py_DECREF(weak->obj);
  delete weak;
}

extern "C" YOutput *yweak_deref(Branch *map_link, YTransaction *txn) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !map_link) return nullptr;
  return wrap_output(support_call("weak_deref", "(O)", map_link->obj));
}

extern "C" YWeakIter *yweak_iter(Branch *array_link, YTransaction *txn) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !array_link) return nullptr;
  PyObject *values = support_call("weak_unquote", "(O)", array_link->obj);
  if (!values) return nullptr;
  PyObject *it = PyObject_GetIter(values);
  Py_DECREF(values);
  if (!it) {
    set_err_py();
    return nullptr;
  }
  return new YWeakIter{it};
}

extern "C" YOutput *yweak_iter_next(YWeakIter *iter) {
  Gil gil;
  if (!gil.ok || !iter) return nullptr;
  PyObject *v = PyIter_Next(iter->iter);
  if (!v) {
    if (PyErr_Occurred()) set_err_py();
    return nullptr;
  }
  return wrap_output(v);
}

extern "C" void yweak_iter_destroy(YWeakIter *iter) {
  if (!iter) return;
  Gil gil;
  if (gil.ok) Py_DECREF(iter->iter);
  delete iter;
}

extern "C" char *yweak_string(Branch *text_link, YTransaction *txn) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !text_link) return nullptr;
  return py_to_cstr(support_call("weak_string", "(O)", text_link->obj));
}

extern "C" char *yweak_xml_string(Branch *xml_text_link, YTransaction *txn) {
  (void)txn;
  Gil gil;
  if (!gil.ok || !xml_text_link) return nullptr;
  return py_to_cstr(
      support_call("weak_xml_string", "(O)", xml_text_link->obj));
}

/* ---- text chunks ----------------------------------------------------------- */
extern "C" YChunk *ytext_chunks(Branch *txt, YTransaction *txn,
                                uint32_t *chunks_len) {
  (void)txn;
  if (chunks_len) *chunks_len = 0;
  Gil gil;
  if (!gil.ok || !txt || !chunks_len) return nullptr;
  PyObject *rows = support_call("text_chunks", "(O)", txt->obj);
  if (!rows) return nullptr;
  Py_ssize_t n = PySequence_Length(rows);
  YChunk *out = (YChunk *)calloc(n > 0 ? (size_t)n : 1, sizeof(YChunk));
  for (Py_ssize_t i = 0; i < n && out; ++i) {
    PyObject *row = PySequence_GetItem(rows, i); /* (value, attrs_items) */
    PyObject *value = nullptr, *attrs = nullptr;
    if (row && PyArg_ParseTuple(row, "OO", &value, &attrs)) {
      Py_INCREF(value);
      out[i].data = wrap_output(value);
      Py_ssize_t an = PySequence_Length(attrs);
      out[i].fmt_len = (uint32_t)(an > 0 ? an : 0);
      out[i].fmt =
          (YMapEntry *)calloc(an > 0 ? (size_t)an : 1, sizeof(YMapEntry));
      for (Py_ssize_t a = 0; a < an && out[i].fmt; ++a) {
        PyObject *pair = PySequence_GetItem(attrs, a);
        const char *k = nullptr;
        PyObject *v = nullptr;
        if (pair && PyArg_ParseTuple(pair, "sO", &k, &v)) {
          out[i].fmt[a].key = dup_str(k);
          Py_INCREF(v);
          out[i].fmt[a].value = wrap_output(v);
        }
        Py_XDECREF(pair);
      }
    }
    Py_XDECREF(row);
  }
  Py_DECREF(rows);
  *chunks_len = (uint32_t)(n > 0 ? n : 0);
  return out;
}

extern "C" void ychunks_destroy(YChunk *chunks, uint32_t len) {
  if (!chunks) return;
  for (uint32_t i = 0; i < len; ++i) {
    if (chunks[i].data) youtput_destroy(chunks[i].data);
    for (uint32_t a = 0; a < chunks[i].fmt_len; ++a) {
      free(chunks[i].fmt[a].key);
      if (chunks[i].fmt[a].value) youtput_destroy(chunks[i].fmt[a].value);
    }
    free(chunks[i].fmt);
  }
  free(chunks);
}

/* ---- xml attribute iteration / tree ---------------------------------------- */
static YXmlAttrIter *attr_iter_common(Branch *xml) {
  Gil gil;
  if (!gil.ok || !xml) return nullptr;
  PyObject *pairs = support_call("xml_attrs", "(O)", xml->obj);
  if (!pairs) return nullptr;
  PyObject *it = PyObject_GetIter(pairs);
  Py_DECREF(pairs);
  if (!it) {
    set_err_py();
    return nullptr;
  }
  return new YXmlAttrIter{it};
}

extern "C" YXmlAttrIter *yxmlelem_attr_iter(Branch *xml, YTransaction *txn) {
  (void)txn;
  return attr_iter_common(xml);
}

extern "C" YXmlAttrIter *yxmltext_attr_iter(Branch *xml, YTransaction *txn) {
  (void)txn;
  return attr_iter_common(xml);
}

extern "C" YXmlAttr *yxmlattr_iter_next(YXmlAttrIter *iterator) {
  Gil gil;
  if (!gil.ok || !iterator) return nullptr;
  PyObject *pair = PyIter_Next(iterator->iter);
  if (!pair) {
    if (PyErr_Occurred()) set_err_py();
    return nullptr;
  }
  const char *name = nullptr, *value = nullptr;
  YXmlAttr *attr = nullptr;
  if (PyArg_ParseTuple(pair, "ss", &name, &value)) {
    attr = new YXmlAttr{dup_str(name), dup_str(value)};
  } else {
    set_err_py();
  }
  Py_DECREF(pair);
  return attr;
}

extern "C" void yxmlattr_destroy(YXmlAttr *attr) {
  if (!attr) return;
  free(attr->name);
  free(attr->value);
  delete attr;
}

extern "C" void yxmlattr_iter_destroy(YXmlAttrIter *iterator) {
  if (!iterator) return;
  Gil gil;
  if (gil.ok) Py_DECREF(iterator->iter);
  delete iterator;
}

extern "C" Branch *yxmlelem_parent(Branch *xml) {
  Gil gil;
  if (!gil.ok || !xml) return nullptr;
  return wrap_branch(support_call("xml_parent", "(O)", xml->obj));
}

extern "C" void yxmltext_remove_attr(Branch *xml, YTransaction *txn,
                                     const char *attr_name) {
  yxmlelem_remove_attr(xml, txn, attr_name);
}

extern "C" void yxmltext_insert_embed(Branch *xml, YTransaction *txn,
                                      uint32_t index, const YInput *content,
                                      const char *attrs_json) {
  ytext_insert_embed(xml, txn, index, content, attrs_json);
}
