"""Glue functions for the C ABI (`ytpu/native/capi.cpp`).

The native `libytpu` shared library embeds CPython and calls into this
module; every function here takes/returns only types the C layer can
convert cheaply (ints, bytes, str, tuples, opaque engine objects).

Parity target: the reference's C FFI crate (/root/reference/yffi/src/lib.rs,
192 `extern "C"` functions; header tests-ffi/include/libyrs.h). Tag
constants mirror yffi/src/lib.rs:32-100 so ported FFI tests keep their
switch statements.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from ytpu.core import Doc, ID, Snapshot, StateVector
from ytpu.core.doc import OFFSET_BYTES, OFFSET_UTF16, Options
from ytpu.core.update import Update
from ytpu.core.moving import ASSOC_AFTER, ASSOC_BEFORE, StickyIndex
from ytpu.encoding.lib0 import Cursor, Writer
from ytpu.types.array import Array
from ytpu.types.map import Map
from ytpu.types.shared import (
    ArrayPrelim,
    MapPrelim,
    SharedType,
    TextPrelim,
    XmlElementPrelim,
    XmlFragmentPrelim,
    XmlTextPrelim,
)
from ytpu.types.text import Text
from ytpu.types.xml import XmlElement, XmlFragment, XmlText
from ytpu.undo import UndoManager, UndoOptions

# --- yffi tag constants (yffi/src/lib.rs:32-100) ---------------------------
Y_JSON_BOOL = -8
Y_JSON_NUM = -7
Y_JSON_INT = -6
Y_JSON_STR = -5
Y_JSON_BUF = -4
Y_JSON_ARR = -3
Y_JSON_MAP = -2
Y_JSON_NULL = -1
Y_JSON_UNDEF = 0
Y_ARRAY = 1
Y_MAP = 2
Y_TEXT = 3
Y_XML_ELEM = 4
Y_XML_TEXT = 5
Y_XML_FRAG = 6
Y_DOC = 7
Y_WEAK_LINK = 8


# --- doc lifecycle ---------------------------------------------------------

def doc_new(
    client_id: int,
    guid: Optional[str],
    collection_id: Optional[str],
    skip_gc: bool,
    auto_load: bool,
    should_load: bool,
    offset_utf16: bool,
) -> Doc:
    opts = Options(
        client_id=client_id if client_id != 0 else None,
        guid=guid,
        collection_id=collection_id,
        skip_gc=skip_gc,
        auto_load=auto_load,
        should_load=should_load,
        offset_kind=OFFSET_UTF16 if offset_utf16 else OFFSET_BYTES,
    )
    return Doc(options=opts)


class ReadTxn:
    """Read-only transaction shim (yffi: many ydoc_read_transaction handles
    may coexist; writes through them are rejected). The engine's exclusive
    `Transaction` is only taken for writes."""

    __slots__ = ("doc",)

    def __init__(self, doc: Doc):
        self.doc = doc

    def state_vector(self) -> StateVector:
        return self.doc.state_vector()

    def snapshot(self) -> Snapshot:
        return self.doc.snapshot()

    def encode_diff_v1(self, remote_sv: StateVector) -> bytes:
        return self.doc.encode_state_as_update_v1(remote_sv)

    def encode_diff_v2(self, remote_sv: StateVector) -> bytes:
        return self.doc.encode_state_as_update_v2(remote_sv)

    def apply_update(self, update) -> None:
        raise RuntimeError("cannot apply an update through a read-only transaction")


def doc_root(doc: Doc, kind: int, name: str) -> SharedType:
    if kind == Y_TEXT:
        return doc.get_text(name)
    if kind == Y_ARRAY:
        return doc.get_array(name)
    if kind == Y_MAP:
        return doc.get_map(name)
    if kind == Y_XML_FRAG:
        return doc.get_xml_fragment(name)
    if kind == Y_XML_TEXT:
        return doc.get_xml_text(name)
    raise ValueError(f"unsupported root kind {kind}")


def txn_new(doc: Doc, origin: Optional[bytes], writeable: bool):
    if not writeable:
        return ReadTxn(doc)
    txn = doc.transact(origin=origin)
    txn.__enter__()
    return txn


def txn_commit(txn) -> None:
    if isinstance(txn, ReadTxn):
        return
    try:
        txn.__exit__(None, None, None)
    finally:
        # a commit-time exception (e.g. an observer raising) must not leave
        # the doc's exclusive write slot held forever
        if getattr(txn.doc, "_txn", None) is txn:
            txn.doc._txn = None


# --- sync / encoding -------------------------------------------------------

def txn_state_vector_v1(txn) -> bytes:
    return txn.state_vector().encode_v1()


def txn_state_diff_v1(txn, sv: Optional[bytes]) -> bytes:
    remote = StateVector.decode_v1(sv) if sv else StateVector()
    return txn.encode_diff_v1(remote)


def txn_state_diff_v2(txn, sv: Optional[bytes]) -> bytes:
    remote = StateVector.decode_v1(sv) if sv else StateVector()
    return txn.encode_diff_v2(remote)


def txn_apply(txn, update: bytes, v2: bool) -> None:
    txn.apply_update(Update.decode_v2(update) if v2 else Update.decode_v1(update))


def txn_snapshot(txn) -> bytes:
    return txn.snapshot().encode_v1()


def txn_encode_from_snapshot(txn, snapshot: bytes, v2: bool) -> bytes:
    snap = Snapshot.decode_v1(snapshot)
    data = txn.doc.encode_state_from_snapshot(snap)
    if v2:
        return Update.decode_v1(data).encode_v2()
    return data


def update_debug(update: bytes, v2: bool) -> str:
    u = Update.decode_v2(update) if v2 else Update.decode_v1(update)
    return repr(u)


# --- values (YInput / YOutput) ---------------------------------------------

def input_to_value(tag: int, payload: Any) -> Any:
    """Convert a (tag, payload) pair from the C layer to an engine value.

    Payloads arrive either already structured (list/dict built by the C
    layer from recursive YInput arrays — the yffi-parity path; elements
    are themselves converted values, so nested prelims pass through) or
    as JSON strings (the `yinput_*_str` extension constructors).
    """
    if tag == Y_JSON_NULL:
        return None
    if tag == Y_JSON_UNDEF:
        return None
    if tag in (Y_JSON_BOOL, Y_JSON_NUM, Y_JSON_INT, Y_JSON_STR, Y_JSON_BUF):
        return payload
    if tag == Y_JSON_ARR:
        return payload if isinstance(payload, list) else json.loads(payload)
    if tag == Y_JSON_MAP:
        return payload if isinstance(payload, dict) else json.loads(payload)
    if tag == Y_TEXT:
        return TextPrelim(payload or "")
    if tag == Y_XML_TEXT:
        return XmlTextPrelim(payload or "")
    if tag == Y_ARRAY:
        if isinstance(payload, list):
            return ArrayPrelim(payload)
        return ArrayPrelim(json.loads(payload) if payload else [])
    if tag == Y_MAP:
        if isinstance(payload, dict):
            return MapPrelim(payload)
        return MapPrelim(json.loads(payload) if payload else {})
    if tag == Y_XML_ELEM:
        return XmlElementPrelim(payload or "UNDEFINED")
    if tag == Y_XML_FRAG:
        return XmlFragmentPrelim(payload or [])
    if tag == Y_DOC:
        return payload  # a Doc instance → ContentDoc on insertion
    if tag == Y_WEAK_LINK:
        return payload  # a WeakPrelim from quote()/map_link()
    raise ValueError(f"unsupported YInput tag {tag}")


def output_tag(value: Any) -> int:
    if value is None:
        return Y_JSON_NULL
    if isinstance(value, bool):
        return Y_JSON_BOOL
    if isinstance(value, int):
        return Y_JSON_INT
    if isinstance(value, float):
        return Y_JSON_NUM
    if isinstance(value, str):
        return Y_JSON_STR
    if isinstance(value, (bytes, bytearray)):
        return Y_JSON_BUF
    if isinstance(value, list):
        return Y_JSON_ARR
    if isinstance(value, dict):
        return Y_JSON_MAP
    if isinstance(value, XmlElement):
        return Y_XML_ELEM
    if isinstance(value, XmlText):
        return Y_XML_TEXT
    if isinstance(value, XmlFragment):
        return Y_XML_FRAG
    if isinstance(value, Text):
        return Y_TEXT
    if isinstance(value, Array):
        return Y_ARRAY
    if isinstance(value, Map):
        return Y_MAP
    if isinstance(value, Doc):
        return Y_DOC
    from ytpu.types.weak import WeakRef

    if isinstance(value, WeakRef):
        return Y_WEAK_LINK
    return Y_JSON_UNDEF


def output_json(value: Any) -> str:
    if isinstance(value, SharedType):
        return json.dumps(value.to_json())
    if isinstance(value, (bytes, bytearray)):
        return json.dumps(list(value))
    return json.dumps(value)


def branch_kind(branch: Any) -> int:
    return output_tag(branch)


# --- type operations -------------------------------------------------------

def type_len(t) -> int:
    if isinstance(t, Map):
        return sum(1 for _ in t.keys())
    return t.branch.content_len


def xml_insert_elem(txn, xml, index: int, name: str):
    xml.insert(txn, index, XmlElementPrelim(name))
    return xml.get(index)


def xml_insert_text(txn, xml, index: int):
    xml.insert(txn, index, XmlTextPrelim(""))
    return xml.get(index)


def text_insert(txn, text, index: int, chunk: str, attrs: Optional[str]) -> None:
    if attrs:
        text.insert_with_attributes(txn, index, chunk, json.loads(attrs))
    else:
        text.insert(txn, index, chunk)


def text_insert_embed(txn, text, index: int, content_json: str, attrs: Optional[str]) -> None:
    text.insert_embed(txn, index, json.loads(content_json))
    if attrs:
        text.format(txn, index, 1, json.loads(attrs))


def text_format(txn, text, index: int, length: int, attrs: str) -> None:
    text.format(txn, index, length, json.loads(attrs))


def array_insert_range(txn, arr, index: int, tags_payloads: list) -> None:
    values = [input_to_value(t, p) for (t, p) in tags_payloads]
    arr.insert_range(txn, index, values)


def map_iter_items(m) -> list:
    return list(m.items())


def xml_attrs(x) -> list:
    return [(k, v) for k, v in x.attributes()]


def xml_kind_children(x) -> list:
    return list(x.children())


# --- sticky index -----------------------------------------------------------

def sticky_from_index(txn, branch, index: int, assoc: int) -> StickyIndex:
    return StickyIndex.from_type_index(
        branch.branch if isinstance(branch, SharedType) else branch,
        index,
        ASSOC_AFTER if assoc >= 0 else ASSOC_BEFORE,
    )


def sticky_read(si: StickyIndex, txn):
    """(index,) or None if the position is gone."""
    out = si.get_offset(txn.doc.store)
    if out is None:
        return None
    branch, index = out
    return index


def sticky_assoc(si: StickyIndex) -> int:
    return 0 if si.assoc == ASSOC_AFTER else -1


def sticky_encode(si: StickyIndex) -> bytes:
    return si.encode_v1()


def sticky_decode(data: bytes) -> StickyIndex:
    return StickyIndex.decode_v1(data)


# --- undo -------------------------------------------------------------------

def undo_manager_new(doc: Doc, capture_timeout_ms: int) -> UndoManager:
    return UndoManager(doc, [], UndoOptions(capture_timeout_ms=capture_timeout_ms))


# --- observers --------------------------------------------------------------

def observe(doc: Doc, kind: int, cb) -> Any:
    """kind: 0=update_v1 1=update_v2 2=after_transaction. Returns unobserve."""
    if kind == 0:
        return doc.observe_update_v1(lambda payload, origin, txn: cb(payload))
    if kind == 1:
        return doc.observe_update_v2(lambda payload, origin, txn: cb(payload))
    if kind == 2:
        return doc.observe_after_transaction(lambda txn: cb(b""))
    raise ValueError(f"unsupported observer kind {kind}")


def observe_clear(doc: Doc, cb) -> Any:
    """yffi ydoc_observe_clear: fired when the doc is destroyed."""
    return doc.observe_destroy(lambda d: cb(d))


def observe_subdocs(doc: Doc, cb) -> Any:
    """yffi ydoc_observe_subdocs: cb(added_docs, removed_docs, loaded_docs)."""

    def fire(txn, added, removed, loaded):
        cb(list(added.values()), list(removed.values()), list(loaded.values()))

    return doc.observe_subdocs(fire)


def doc_clear(doc: Doc) -> None:
    doc.destroy()


# --- branch handles / logical ids (yffi: ybranch_id / ybranch_get) ----------

def shared_from_branch(branch) -> SharedType:
    from ytpu.types import wrap_branch

    return wrap_branch(branch)


def type_get(txn, name: str) -> Optional[SharedType]:
    """Root type lookup WITHOUT creating (yffi ytype_get, lib.rs ytype_get)."""
    branch = txn.doc.store.types.get(name)
    return shared_from_branch(branch) if branch is not None else None


def branch_id(shared: SharedType):
    """(1, client, clock) for nested branches; (0, root_name) for roots
    (parity: branch.rs BranchID :926)."""
    branch = shared.branch
    if branch.item is not None:
        return (1, branch.item.id.client, branch.item.id.clock)
    store = branch.store
    name = branch.type_name if branch.type_name else None
    if store is not None:
        for root_name, root in store.types.items():
            if root is branch:
                name = root_name
                break
    return (0, name)


def branch_get(txn, nested: int, client: int, clock: int, name: Optional[str]):
    store = txn.doc.store
    if nested:
        item = store.blocks.get_item(ID(client, clock))
        if item is None:
            return None
        from ytpu.core.content import ContentType

        if not isinstance(item.content, ContentType):
            return None
        return shared_from_branch(item.content.branch)
    branch = store.types.get(name) if name is not None else None
    return shared_from_branch(branch) if branch is not None else None


# --- pending introspection (yffi: ytransaction_pending_update/_ds) ----------

def txn_pending_update(txn):
    """(missing_sv_v1, update_v1) or None (parity: store.rs:42-50)."""
    pending = txn.doc.store.pending
    if pending is None:
        return None
    return (pending.missing.encode_v1(), pending.update.encode_v1())


def txn_pending_ds(txn):
    """[(client, [(start, len), ...]), ...] or None."""
    ds = txn.doc.store.pending_ds
    if ds is None or not ds.clients:
        return None
    out = []
    for client in sorted(ds.clients, reverse=True):
        ranges = [(r.start, r.end - r.start) for r in ds.clients[client]]
        out.append((client, ranges))
    return out


# --- subdocuments ------------------------------------------------------------

def txn_subdocs(txn) -> list:
    return list(txn.doc.store.subdocs.values())


# --- per-type event observers (yffi: ytext_observe & co.) --------------------

def observe_type(shared: SharedType, fn) -> Any:
    """fn receives the engine Event; valid only during the callback."""
    return shared.observe(lambda txn, event: fn(event))


def observe_deep_type(shared: SharedType, fn) -> Any:
    """fn receives the list of bubbled Events (yffi yobserve_deep)."""
    return shared.observe_deep(lambda txn, events: fn(list(events)))


def event_target(event) -> SharedType:
    return shared_from_branch(event.target)


def event_kind(event) -> int:
    return output_tag(shared_from_branch(event.target))


def event_path(event) -> list:
    return event.path()


def event_delta_seq(event) -> list:
    """Sequence delta as (tag, len, values|None) rows; tags mirror
    Y_EVENT_CHANGE_ADD/DELETE/RETAIN = 1/2/3 (yffi YEventChange)."""
    rows = []
    for ch in event.delta():
        if ch.kind == "insert":
            rows.append((1, ch.len, list(ch.values or [])))
        elif ch.kind == "delete":
            rows.append((2, ch.len, None))
        else:
            rows.append((3, ch.len, None))
    return rows


def event_delta_text(event) -> list:
    """Text delta as (tag, len, insert|None, attrs_items|None) rows; string
    runs are joined; an embed/branch insert stays a single value
    (yffi YDelta; parity: types/text.rs:1213-1305)."""
    rows = []
    for ch in event.delta():
        attrs = list(ch.attributes.items()) if ch.attributes else None
        if ch.kind == "insert":
            # group consecutive string values into one run; embeds/branches
            # stay single-value rows (yffi YDelta: one string run OR one embed)
            run: list = []
            for v in ch.values or []:
                if isinstance(v, str):
                    run.append(v)
                    continue
                if run:
                    text = "".join(run)
                    rows.append((1, len(text), text, attrs))
                    run = []
                rows.append((1, 1, v, attrs))
            if run:
                text = "".join(run)
                rows.append((1, len(text), text, attrs))
        elif ch.kind == "delete":
            rows.append((2, ch.len, None, None))
        else:
            rows.append((3, ch.len, None, attrs))
    return rows


def event_keys(event) -> list:
    """Map/attribute delta as (key, tag, old, new) rows; tags mirror
    Y_EVENT_KEY_CHANGE_ADD/DELETE/UPDATE = 4/5/6 (yffi YEventKeyChange)."""
    tag_of = {"add": 4, "remove": 5, "update": 6}
    rows = []
    for key, change in event.keys().items():
        rows.append((key, tag_of[change.action], change.old_value, change.new_value))
    return rows


# --- weak links / quotations (yffi: ytext_quote / yarray_quote / ymap_link) --

def quote(txn, shared: SharedType, start: int, end: int,
          start_exclusive: int, end_exclusive: int):
    """Quote [start..end] (inclusive bounds, yffi shape) as a weak prelim."""
    from ytpu.types.weak import quote_range

    lo = start + (1 if start_exclusive else 0)
    hi = end - (1 if end_exclusive else 0)
    return quote_range(shared, txn, lo, hi - lo + 1)


def map_link(m, key: str):
    from ytpu.types.weak import map_link as _map_link

    return _map_link(m, key)


def weak_deref(weak: SharedType):
    return weak.try_deref()


def weak_unquote(weak: SharedType) -> list:
    return weak.unquote()


def weak_string(weak: SharedType) -> str:
    return "".join(v for v in weak.unquote() if isinstance(v, str))


def weak_xml_string(weak: SharedType) -> str:
    """Quoted range rendered with formatting markup, the same XML-ish tag
    scheme as XmlText::get_string (yffi yweak_xml_string)."""
    from ytpu.core.content import ContentFormat, ContentString

    store = weak.branch.store
    src = weak.source
    if store is None or src is None or src.quote_start.id is None:
        return ""
    item = store.blocks.get_item(src.quote_start.id)
    end_id = src.quote_end.id
    out, open_tags = [], []
    while item is not None:
        if not item.deleted:
            content = item.content
            if isinstance(content, ContentString):
                out.append(content.text)
            elif isinstance(content, ContentFormat):
                if content.value is None:
                    if content.key in open_tags:
                        open_tags.remove(content.key)
                        out.append(f"</{content.key}>")
                else:
                    open_tags.append(content.key)
                    out.append(f"<{content.key}>")
        if end_id is not None and (
            item.contains(end_id)
            or (item.id.client == end_id.client and item.id.clock >= end_id.clock)
        ):
            break
        item = item.right
    for tag in reversed(open_tags):
        out.append(f"</{tag}>")
    return "".join(out)


# --- text chunks (yffi: ytext_chunks) ----------------------------------------

def text_chunks(text) -> list:
    """[(value, attrs_items), ...] — formatted runs (yffi YChunk)."""
    return [
        (d.insert, list(d.attributes.items()) if d.attributes else [])
        for d in text.diff()
    ]


# --- xml helpers -------------------------------------------------------------

def xml_parent(x):
    node = x.parent()
    return node if node is not None else None


# --- undo observers (yffi: yundo_manager_observe_added/_popped) --------------

def undo_observe(mgr: UndoManager, which: int, fn) -> Any:
    """which: 0=added 1=popped. fn(kind_int, origin_bytes_or_None, stack_item);
    kind mirrors Y_KIND_UNDO=0 / Y_KIND_REDO=1."""
    if which == 0:

        def on_added(txn, item, kind):
            origin = txn.origin
            if origin is not None and not isinstance(origin, (bytes, bytearray)):
                origin = str(origin).encode()
            # Parity: undo.rs:229-233 — the added-event kind is Undo only
            # when captured DURING an undo (item lands on the redo stack);
            # a normal edit fires Redo. `kind` here names the target stack.
            fn(1 if kind == "undo" else 0, origin, item)

        mgr.on_added_subs.append(on_added)
        return lambda: mgr.on_added_subs.remove(on_added)

    def on_popped(item, kind):
        fn(0 if kind == "undo" else 1, None, item)

    mgr.on_popped_subs.append(on_popped)
    return lambda: mgr.on_popped_subs.remove(on_popped)


def undo_item_meta(item) -> int:
    meta = getattr(item, "meta", None)
    return int(meta) if isinstance(meta, int) else 0


def undo_item_set_meta(item, ptr: int) -> None:
    item.meta = ptr if ptr else None
