"""Glue functions for the C ABI (`ytpu/native/capi.cpp`).

The native `libytpu` shared library embeds CPython and calls into this
module; every function here takes/returns only types the C layer can
convert cheaply (ints, bytes, str, tuples, opaque engine objects).

Parity target: the reference's C FFI crate (/root/reference/yffi/src/lib.rs,
192 `extern "C"` functions; header tests-ffi/include/libyrs.h). Tag
constants mirror yffi/src/lib.rs:32-100 so ported FFI tests keep their
switch statements.
"""

from __future__ import annotations

import json
from typing import Any, Optional

from ytpu.core import Doc, ID, Snapshot, StateVector
from ytpu.core.doc import OFFSET_BYTES, OFFSET_UTF16, Options
from ytpu.core.update import Update
from ytpu.core.moving import ASSOC_AFTER, ASSOC_BEFORE, StickyIndex
from ytpu.encoding.lib0 import Cursor, Writer
from ytpu.types.array import Array
from ytpu.types.map import Map
from ytpu.types.shared import (
    ArrayPrelim,
    MapPrelim,
    SharedType,
    TextPrelim,
    XmlElementPrelim,
    XmlTextPrelim,
)
from ytpu.types.text import Text
from ytpu.types.xml import XmlElement, XmlFragment, XmlText
from ytpu.undo import UndoManager, UndoOptions

# --- yffi tag constants (yffi/src/lib.rs:32-100) ---------------------------
Y_JSON_BOOL = -8
Y_JSON_NUM = -7
Y_JSON_INT = -6
Y_JSON_STR = -5
Y_JSON_BUF = -4
Y_JSON_ARR = -3
Y_JSON_MAP = -2
Y_JSON_NULL = -1
Y_JSON_UNDEF = 0
Y_ARRAY = 1
Y_MAP = 2
Y_TEXT = 3
Y_XML_ELEM = 4
Y_XML_TEXT = 5
Y_XML_FRAG = 6
Y_DOC = 7
Y_WEAK_LINK = 8


# --- doc lifecycle ---------------------------------------------------------

def doc_new(
    client_id: int,
    guid: Optional[str],
    collection_id: Optional[str],
    skip_gc: bool,
    auto_load: bool,
    should_load: bool,
    offset_utf16: bool,
) -> Doc:
    opts = Options(
        client_id=client_id if client_id != 0 else None,
        guid=guid,
        collection_id=collection_id,
        skip_gc=skip_gc,
        auto_load=auto_load,
        should_load=should_load,
        offset_kind=OFFSET_UTF16 if offset_utf16 else OFFSET_BYTES,
    )
    return Doc(options=opts)


class ReadTxn:
    """Read-only transaction shim (yffi: many ydoc_read_transaction handles
    may coexist; writes through them are rejected). The engine's exclusive
    `Transaction` is only taken for writes."""

    __slots__ = ("doc",)

    def __init__(self, doc: Doc):
        self.doc = doc

    def state_vector(self) -> StateVector:
        return self.doc.state_vector()

    def snapshot(self) -> Snapshot:
        return self.doc.snapshot()

    def encode_diff_v1(self, remote_sv: StateVector) -> bytes:
        return self.doc.encode_state_as_update_v1(remote_sv)

    def encode_diff_v2(self, remote_sv: StateVector) -> bytes:
        return self.doc.encode_state_as_update_v2(remote_sv)

    def apply_update(self, update) -> None:
        raise RuntimeError("cannot apply an update through a read-only transaction")


def doc_root(doc: Doc, kind: int, name: str) -> SharedType:
    if kind == Y_TEXT:
        return doc.get_text(name)
    if kind == Y_ARRAY:
        return doc.get_array(name)
    if kind == Y_MAP:
        return doc.get_map(name)
    if kind == Y_XML_FRAG:
        return doc.get_xml_fragment(name)
    if kind == Y_XML_TEXT:
        return doc.get_xml_text(name)
    raise ValueError(f"unsupported root kind {kind}")


def txn_new(doc: Doc, origin: Optional[bytes], writeable: bool):
    if not writeable:
        return ReadTxn(doc)
    txn = doc.transact(origin=origin)
    txn.__enter__()
    return txn


def txn_commit(txn) -> None:
    if isinstance(txn, ReadTxn):
        return
    try:
        txn.__exit__(None, None, None)
    finally:
        # a commit-time exception (e.g. an observer raising) must not leave
        # the doc's exclusive write slot held forever
        if getattr(txn.doc, "_txn", None) is txn:
            txn.doc._txn = None


# --- sync / encoding -------------------------------------------------------

def txn_state_vector_v1(txn) -> bytes:
    return txn.state_vector().encode_v1()


def txn_state_diff_v1(txn, sv: Optional[bytes]) -> bytes:
    remote = StateVector.decode_v1(sv) if sv else StateVector()
    return txn.encode_diff_v1(remote)


def txn_state_diff_v2(txn, sv: Optional[bytes]) -> bytes:
    remote = StateVector.decode_v1(sv) if sv else StateVector()
    return txn.encode_diff_v2(remote)


def txn_apply(txn, update: bytes, v2: bool) -> None:
    txn.apply_update(Update.decode_v2(update) if v2 else Update.decode_v1(update))


def txn_snapshot(txn) -> bytes:
    return txn.snapshot().encode_v1()


def txn_encode_from_snapshot(txn, snapshot: bytes, v2: bool) -> bytes:
    snap = Snapshot.decode_v1(snapshot)
    data = txn.doc.encode_state_from_snapshot(snap)
    if v2:
        return Update.decode_v1(data).encode_v2()
    return data


def update_debug(update: bytes, v2: bool) -> str:
    u = Update.decode_v2(update) if v2 else Update.decode_v1(update)
    return repr(u)


# --- values (YInput / YOutput) ---------------------------------------------

def input_to_value(tag: int, payload: Any) -> Any:
    """Convert a (tag, scalar-payload) pair from the C layer to an engine value.

    For Y_JSON_ARR/Y_JSON_MAP the payload is a JSON string (the C API's
    simplification of yffi's recursive YInput arrays); for nested shared
    types it is a JSON string used as the prelim's initial content.
    """
    if tag == Y_JSON_NULL:
        return None
    if tag == Y_JSON_UNDEF:
        return None
    if tag in (Y_JSON_BOOL, Y_JSON_NUM, Y_JSON_INT, Y_JSON_STR, Y_JSON_BUF):
        return payload
    if tag == Y_JSON_ARR:
        return json.loads(payload)
    if tag == Y_JSON_MAP:
        return json.loads(payload)
    if tag == Y_TEXT:
        return TextPrelim(payload or "")
    if tag == Y_XML_TEXT:
        return XmlTextPrelim(payload or "")
    if tag == Y_ARRAY:
        return ArrayPrelim(json.loads(payload) if payload else [])
    if tag == Y_MAP:
        return MapPrelim(json.loads(payload) if payload else {})
    if tag == Y_XML_ELEM:
        return XmlElementPrelim(payload or "UNDEFINED")
    raise ValueError(f"unsupported YInput tag {tag}")


def output_tag(value: Any) -> int:
    if value is None:
        return Y_JSON_NULL
    if isinstance(value, bool):
        return Y_JSON_BOOL
    if isinstance(value, int):
        return Y_JSON_INT
    if isinstance(value, float):
        return Y_JSON_NUM
    if isinstance(value, str):
        return Y_JSON_STR
    if isinstance(value, (bytes, bytearray)):
        return Y_JSON_BUF
    if isinstance(value, list):
        return Y_JSON_ARR
    if isinstance(value, dict):
        return Y_JSON_MAP
    if isinstance(value, XmlElement):
        return Y_XML_ELEM
    if isinstance(value, XmlText):
        return Y_XML_TEXT
    if isinstance(value, XmlFragment):
        return Y_XML_FRAG
    if isinstance(value, Text):
        return Y_TEXT
    if isinstance(value, Array):
        return Y_ARRAY
    if isinstance(value, Map):
        return Y_MAP
    if isinstance(value, Doc):
        return Y_DOC
    from ytpu.types.weak import WeakRef

    if isinstance(value, WeakRef):
        return Y_WEAK_LINK
    return Y_JSON_UNDEF


def output_json(value: Any) -> str:
    if isinstance(value, SharedType):
        return json.dumps(value.to_json())
    if isinstance(value, (bytes, bytearray)):
        return json.dumps(list(value))
    return json.dumps(value)


def branch_kind(branch: Any) -> int:
    return output_tag(branch)


# --- type operations -------------------------------------------------------

def type_len(t) -> int:
    if isinstance(t, Map):
        return sum(1 for _ in t.keys())
    return t.branch.content_len


def xml_insert_elem(txn, xml, index: int, name: str):
    xml.insert(txn, index, XmlElementPrelim(name))
    return xml.get(index)


def xml_insert_text(txn, xml, index: int):
    xml.insert(txn, index, XmlTextPrelim(""))
    return xml.get(index)


def text_insert(txn, text, index: int, chunk: str, attrs: Optional[str]) -> None:
    if attrs:
        text.insert_with_attributes(txn, index, chunk, json.loads(attrs))
    else:
        text.insert(txn, index, chunk)


def text_insert_embed(txn, text, index: int, content_json: str, attrs: Optional[str]) -> None:
    text.insert_embed(txn, index, json.loads(content_json))
    if attrs:
        text.format(txn, index, 1, json.loads(attrs))


def text_format(txn, text, index: int, length: int, attrs: str) -> None:
    text.format(txn, index, length, json.loads(attrs))


def array_insert_range(txn, arr, index: int, tags_payloads: list) -> None:
    values = [input_to_value(t, p) for (t, p) in tags_payloads]
    arr.insert_range(txn, index, values)


def map_iter_items(m) -> list:
    return list(m.items())


def xml_attrs(x) -> list:
    return [(k, v) for k, v in x.attributes()]


def xml_kind_children(x) -> list:
    return list(x.children())


# --- sticky index -----------------------------------------------------------

def sticky_from_index(txn, branch, index: int, assoc: int) -> StickyIndex:
    return StickyIndex.from_type_index(
        branch.branch if isinstance(branch, SharedType) else branch,
        index,
        ASSOC_AFTER if assoc >= 0 else ASSOC_BEFORE,
    )


def sticky_read(si: StickyIndex, txn):
    """(index,) or None if the position is gone."""
    out = si.get_offset(txn.doc.store)
    if out is None:
        return None
    branch, index = out
    return index


def sticky_assoc(si: StickyIndex) -> int:
    return 0 if si.assoc == ASSOC_AFTER else -1


def sticky_encode(si: StickyIndex) -> bytes:
    return si.encode_v1()


def sticky_decode(data: bytes) -> StickyIndex:
    return StickyIndex.decode_v1(data)


# --- undo -------------------------------------------------------------------

def undo_manager_new(doc: Doc, capture_timeout_ms: int) -> UndoManager:
    return UndoManager(doc, [], UndoOptions(capture_timeout_ms=capture_timeout_ms))


# --- observers --------------------------------------------------------------

def observe(doc: Doc, kind: int, cb) -> Any:
    """kind: 0=update_v1 1=update_v2 2=after_transaction. Returns unobserve."""
    if kind == 0:
        return doc.observe_update_v1(lambda payload, origin, txn: cb(payload))
    if kind == 1:
        return doc.observe_update_v2(lambda payload, origin, txn: cb(payload))
    if kind == 2:
        return doc.observe_after_transaction(lambda txn: cb(b""))
    raise ValueError(f"unsupported observer kind {kind}")
