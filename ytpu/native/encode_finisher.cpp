// ytpu/native/encode_finisher.cpp — batched native wire-encode finisher.
//
// The native half of `encode_diff_batch` (VERDICT r2 #6): the device kernel
// selects which block rows ship to a remote (ship mask + first-block clock
// offsets, ytpu/models/batch_doc.py:encode_diff_batch); this module turns
// the selected rows of MANY docs into v1 update payloads in one call,
// replacing the per-row Python loop of `finish_encode_diff`
// (batch_doc.py). Reference equivalent: `Store::write_blocks_from` /
// `DeleteSet::encode` compiled in yrs (yrs/src/store.rs:204-248,
// id_set.rs:440-).
//
// Byte parity contract: output is identical to the Python finisher for
// every supported row. Variable-length content is resolved through two
// ref spaces (the same spaces the Python `ChunkedWirePayloads` resolves):
//   ref >= 0  → host PayloadStore item; the Python side pre-bakes three
//               arenas: UTF-16LE text, pre-encoded content blobs, and
//               per-element pre-encoded Any values.
//   ref <= -2 → byte offset -(ref+2) into the retained wire chunks; spans
//               are re-emitted by walking the original update bytes.
// Rows that would need a host JSON round-trip (wire Format/Embed refs) or
// an unknown content kind mark the whole doc STATUS_FALLBACK and the
// Python finisher handles that doc alone.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr int32_t KIND_GC = 0;
constexpr int32_t KIND_DELETED = 1;
constexpr int32_t KIND_JSON = 2;
constexpr int32_t KIND_BINARY = 3;
constexpr int32_t KIND_STRING = 4;
constexpr int32_t KIND_EMBED = 5;
constexpr int32_t KIND_FORMAT = 6;
constexpr int32_t KIND_TYPE = 7;
constexpr int32_t KIND_ANY = 8;
// engine sentinel, not a wire ref: a synthetic per-doc row anchoring a
// non-primary named root (content.py BLOCK_ROOT_ANCHOR); rows parented
// to one re-emit the root-name wire form with the anchor's key name
constexpr int32_t KIND_ROOT_ANCHOR = 12;

constexpr int32_t STATUS_OK = 0;
constexpr int32_t STATUS_FALLBACK = 1;

struct Buf {
  std::string b;

  void u8(uint8_t v) { b.push_back(static_cast<char>(v)); }

  void var(uint64_t v) {
    while (v >= 0x80) {
      b.push_back(static_cast<char>(0x80 | (v & 0x7F)));
      v >>= 7;
    }
    b.push_back(static_cast<char>(v));
  }

  void raw(const uint8_t* p, size_t n) {
    b.append(reinterpret_cast<const char*>(p), n);
  }

  // write_string for an already-UTF-8 byte span (varint byte len + bytes)
  void str(const uint8_t* p, size_t n) {
    var(n);
    raw(p, n);
  }
};

// UTF-16LE → UTF-8 with lone surrogate halves replaced by U+FFFD —
// parity with Python's bytes.decode("utf-16-le", errors="replace")
// feeding Writer.write_string (ytpu/models/batch_doc.py slice_text).
void utf16le_to_utf8(const uint8_t* p, size_t units, std::string& out) {
  size_t i = 0;
  while (i < units) {
    uint32_t u = static_cast<uint32_t>(p[2 * i]) |
                 (static_cast<uint32_t>(p[2 * i + 1]) << 8);
    uint32_t cp;
    if (u >= 0xD800 && u < 0xDC00) {
      if (i + 1 < units) {
        uint32_t lo = static_cast<uint32_t>(p[2 * i + 2]) |
                      (static_cast<uint32_t>(p[2 * i + 3]) << 8);
        if (lo >= 0xDC00 && lo < 0xE000) {
          cp = 0x10000 + ((u - 0xD800) << 10) + (lo - 0xDC00);
          i += 2;
        } else {
          cp = 0xFFFD;
          i += 1;
        }
      } else {
        cp = 0xFFFD;
        i += 1;
      }
    } else if (u >= 0xDC00 && u < 0xE000) {
      cp = 0xFFFD;
      i += 1;
    } else {
      cp = u;
      i += 1;
    }
    if (cp < 0x80) {
      out.push_back(static_cast<char>(cp));
    } else if (cp < 0x800) {
      out.push_back(static_cast<char>(0xC0 | (cp >> 6)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      out.push_back(static_cast<char>(0xE0 | (cp >> 12)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    } else {
      out.push_back(static_cast<char>(0xF0 | (cp >> 18)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
      out.push_back(static_cast<char>(0x80 | (cp & 0x3F)));
    }
  }
}

// one UTF-8 lead byte → (bytes, utf-16 units); matches the Python
// unit_at in decode_kernel.utf8_slice_u16 (WTF-8 surrogate sequences are
// 3-byte / 1-unit and round-trip as raw bytes, like surrogatepass).
inline void unit_at(uint8_t b0, int& nb, int& nu) {
  if (b0 < 0x80) {
    nb = 1;
    nu = 1;
  } else if (b0 < 0xE0) {
    nb = 2;
    nu = 1;
  } else if (b0 < 0xF0) {
    nb = 3;
    nu = 1;
  } else {
    nb = 4;
    nu = 2;
  }
}

// Slice `length` UTF-16 units at unit-offset `off` from the UTF-8 bytes
// at wire[start..]; severed surrogate halves render as U+FFFD. Exact
// parity with decode_kernel.utf8_slice_u16. Returns false on overrun.
bool utf8_slice_u16(const uint8_t* wire, int64_t wire_len, int64_t start,
                    int64_t off, int64_t length, std::string& out) {
  static const char kFFFD[] = "\xEF\xBF\xBD";
  int64_t i = start;
  int64_t u = 0;
  int nb, nu;
  while (u < off) {
    if (i >= wire_len) return false;
    unit_at(wire[i], nb, nu);
    i += nb;
    u += nu;
  }
  int64_t need = length;
  if (u > off) {
    out.append(kFFFD, 3);
    need -= u - off;
  }
  int64_t s = i;
  while (need > 0) {
    if (i >= wire_len) return false;
    unit_at(wire[i], nb, nu);
    if (nu > need) {
      out.append(reinterpret_cast<const char*>(wire + s),
                 static_cast<size_t>(i - s));
      out.append(kFFFD, 3);
      return true;
    }
    i += nb;
    need -= nu;
  }
  if (i > wire_len) return false;
  out.append(reinterpret_cast<const char*>(wire + s),
             static_cast<size_t>(i - s));
  return true;
}

// varint reader over the wire buffer; returns false on overrun
bool read_var(const uint8_t* p, int64_t len, int64_t& pos, uint64_t& out) {
  out = 0;
  int shift = 0;
  while (pos < len) {
    uint8_t b = p[pos++];
    out |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) return true;
    shift += 7;
    if (shift > 63) return false;
  }
  return false;
}

constexpr int64_t kMaxSafeInt = (int64_t{1} << 53) - 1;  // F64_MAX_SAFE_INTEGER

// write_var_int — Writer.write_var_int parity (sign bit 0x40 in first byte)
void put_var_int(Buf& out, int64_t v) {
  bool neg = v < 0;
  uint64_t m = neg ? static_cast<uint64_t>(-v) : static_cast<uint64_t>(v);
  uint8_t first = static_cast<uint8_t>((m & 0x3F) | (neg ? 0x40 : 0));
  m >>= 6;
  if (m > 0) first |= 0x80;
  out.u8(first);
  while (m > 0) {
    uint8_t b = static_cast<uint8_t>(m & 0x7F);
    m >>= 7;
    if (m > 0) b |= 0x80;
    out.u8(b);
  }
}

// write_any's integer canonicalization: INTEGER inside the f64-safe range,
// BIGINT outside (lib0.py:301-307)
void put_canonical_int(Buf& out, int64_t v) {
  if (v >= -kMaxSafeInt && v <= kMaxSafeInt) {
    out.u8(125);
    put_var_int(out, v);
  } else {
    out.u8(122);
    for (int i = 7; i >= 0; i--)
      out.u8(static_cast<uint8_t>((static_cast<uint64_t>(v) >> (8 * i)) & 0xFF));
  }
}

// write_any's float canonicalization: integral-and-safe → INTEGER, exact
// f32 round-trip → FLOAT32, else FLOAT64 (lib0.py:308-321)
void put_canonical_float(Buf& out, double v) {
  if (std::isfinite(v) && std::trunc(v) == v &&
      v >= static_cast<double>(-kMaxSafeInt) &&
      v <= static_cast<double>(kMaxSafeInt)) {
    put_canonical_int(out, static_cast<int64_t>(v));
    return;
  }
  if (!std::isnan(v) && std::fabs(v) <= 3.4028234663852886e38 &&
      static_cast<double>(static_cast<float>(v)) == v) {
    out.u8(124);
    float f = static_cast<float>(v);
    uint32_t bits;
    std::memcpy(&bits, &f, 4);
    for (int i = 3; i >= 0; i--)
      out.u8(static_cast<uint8_t>((bits >> (8 * i)) & 0xFF));
    return;
  }
  out.u8(123);
  uint64_t bits;
  std::memcpy(&bits, &v, 8);
  for (int i = 7; i >= 0; i--)
    out.u8(static_cast<uint8_t>((bits >> (8 * i)) & 0xFF));
}

// Re-emit one wire Any value exactly as the Python finisher's
// read_any → write_any round trip would (which canonicalizes: non-minimal
// varints re-encode minimal, BIGINTs inside the safe range become
// INTEGERs, whole-number floats become INTEGERs, f32-exact doubles become
// FLOAT32s). Returns false (→ per-doc Python fallback) on malformed input
// or a map with duplicate keys (dict dedup changes the count).
bool reencode_any(const uint8_t* p, int64_t len, int64_t& pos, Buf& out,
                  int depth = 0) {
  // untrusted wire data: bound recursion so deeply nested arrays/maps
  // degrade to the Python fallback instead of smashing the C stack
  if (depth > 100) return false;
  if (pos >= len) return false;
  uint8_t tag = p[pos++];
  uint64_t n;
  switch (tag) {
    case 127:  // undefined
    case 126:  // null
    case 121:  // false
    case 120:  // true
      out.u8(tag);
      return true;
    case 125: {  // integer (signed varint)
      if (pos >= len) return false;
      uint8_t b = p[pos++];
      uint64_t m = b & 0x3F;
      const bool neg = (b & 0x40) != 0;
      int shift = 6;
      while (b & 0x80) {
        if (pos >= len || shift > 70) return false;
        b = p[pos++];
        m |= static_cast<uint64_t>(b & 0x7F) << shift;
        shift += 7;
      }
      if (m > static_cast<uint64_t>(INT64_MAX)) return false;
      put_canonical_int(out, neg ? -static_cast<int64_t>(m)
                                 : static_cast<int64_t>(m));
      return true;
    }
    case 124: {  // float32 (big-endian)
      if (pos + 4 > len) return false;
      uint32_t bits = 0;
      for (int i = 0; i < 4; i++) bits = (bits << 8) | p[pos++];
      float f;
      std::memcpy(&f, &bits, 4);
      put_canonical_float(out, static_cast<double>(f));
      return true;
    }
    case 123: {  // float64 (big-endian)
      if (pos + 8 > len) return false;
      uint64_t bits = 0;
      for (int i = 0; i < 8; i++) bits = (bits << 8) | p[pos++];
      double v;
      std::memcpy(&v, &bits, 8);
      put_canonical_float(out, v);
      return true;
    }
    case 122: {  // bigint (big-endian i64; read_any returns a plain int)
      if (pos + 8 > len) return false;
      uint64_t bits = 0;
      for (int i = 0; i < 8; i++) bits = (bits << 8) | p[pos++];
      put_canonical_int(out, static_cast<int64_t>(bits));
      return true;
    }
    case 119:    // string (UTF-8 round-trips byte-exact via surrogatepass)
    case 116: {  // buffer
      if (!read_var(p, len, pos, n)) return false;
      // n is an untrusted 64-bit varint: compare against the remaining
      // bytes unsigned, never via pos + (int64)n (which can wrap)
      if (n > static_cast<uint64_t>(len - pos)) return false;
      out.u8(tag);
      out.var(n);
      out.raw(p + pos, static_cast<size_t>(n));
      pos += static_cast<int64_t>(n);
      return true;
    }
    case 118: {  // map: count, then (string key, any value)*
      if (!read_var(p, len, pos, n)) return false;
      out.u8(tag);
      out.var(n);
      std::vector<std::pair<int64_t, int64_t>> seen;  // key spans
      for (uint64_t i = 0; i < n; i++) {
        uint64_t klen;
        if (!read_var(p, len, pos, klen)) return false;
        if (klen > static_cast<uint64_t>(len - pos)) return false;
        for (const auto& s : seen)
          if (s.second == static_cast<int64_t>(klen) &&
              std::memcmp(p + s.first, p + pos, klen) == 0)
            return false;  // duplicate key: dict dedup changes the count
        seen.emplace_back(pos, static_cast<int64_t>(klen));
        out.var(klen);
        out.raw(p + pos, static_cast<size_t>(klen));
        pos += static_cast<int64_t>(klen);
        if (!reencode_any(p, len, pos, out, depth + 1)) return false;
      }
      return true;
    }
    case 117: {  // array
      if (!read_var(p, len, pos, n)) return false;
      out.u8(tag);
      out.var(n);
      for (uint64_t i = 0; i < n; i++)
        if (!reencode_any(p, len, pos, out, depth + 1)) return false;
      return true;
    }
    default:
      return false;
  }
}

// skip one lib0 Any value (tags descend from 127; ytpu/encoding/lib0.py
// read_any / reference any.rs:93-184)
bool skip_any(const uint8_t* p, int64_t len, int64_t& pos, int depth = 0) {
  if (depth > 100) return false;
  if (pos >= len) return false;
  uint8_t tag = p[pos++];
  uint64_t n;
  switch (tag) {
    case 127:  // undefined
    case 126:  // null
    case 121:  // false
    case 120:  // true
      return true;
    case 125: {  // integer (var_int: first byte 0x40 sign, 0x80 cont)
      if (pos >= len) return false;
      uint8_t b = p[pos++];
      while (b & 0x80) {
        if (pos >= len) return false;
        b = p[pos++];
      }
      return true;
    }
    case 124:  // float32
      pos += 4;
      return pos <= len;
    case 123:  // float64
    case 122:  // bigint
      pos += 8;
      return pos <= len;
    case 119:  // string
    case 116:  // buffer
      if (!read_var(p, len, pos, n)) return false;
      if (n > static_cast<uint64_t>(len - pos)) return false;
      pos += static_cast<int64_t>(n);
      return true;
    case 118: {  // map: count, then (string key, any value)*
      if (!read_var(p, len, pos, n)) return false;
      for (uint64_t i = 0; i < n; i++) {
        uint64_t klen;
        if (!read_var(p, len, pos, klen)) return false;
        if (klen > static_cast<uint64_t>(len - pos)) return false;
        pos += static_cast<int64_t>(klen);
        if (!skip_any(p, len, pos, depth + 1)) return false;
      }
      return true;
    }
    case 117: {  // array
      if (!read_var(p, len, pos, n)) return false;
      for (uint64_t i = 0; i < n; i++)
        if (!skip_any(p, len, pos, depth + 1)) return false;
      return true;
    }
    default:
      return false;
  }
}

struct FinishIn {
  int32_t n_docs_total;
  int32_t n_blocks_cap;
  const int32_t* client;
  const int32_t* clock;
  const int32_t* length;
  const int32_t* origin_client;
  const int32_t* origin_clock;
  const int32_t* ror_client;
  const int32_t* ror_clock;
  const int32_t* kind;
  const int32_t* content_ref;
  const int32_t* content_off;
  const int32_t* key;
  const int32_t* parent;
  const uint8_t* ship;
  const int32_t* offsets;
  const uint8_t* deleted;
  const int32_t* sel;
  int32_t n_sel;
  const int64_t* from_idx;
  int32_t n_interned;
  const uint8_t* key_blob;
  const int64_t* key_off;  // [n_keys + 1]
  int32_t n_keys;
  const uint8_t* root_name;
  int32_t root_name_len;
  const uint8_t* text_arena;
  int64_t text_arena_len;
  const int64_t* item_text_off;    // [n_items], -1 = not a string payload
  const int64_t* item_text_units;  // [n_items] payload size in UTF-16 units
  const uint8_t* blob_arena;
  int64_t blob_arena_len;
  const int64_t* item_blob_off;  // [n_items], -1 = no pre-encoded blob
  const int64_t* item_blob_len;
  const int64_t* item_elem_base;   // [n_items], -1 = not an Any payload
  const int64_t* item_elem_count;  // [n_items] element count
  const int64_t* elem_off;         // [n_elems + 1] spans into elem_arena
  const uint8_t* elem_arena;
  int64_t elem_arena_len;
  int64_t n_items;
  const uint8_t* wire;
  int64_t wire_len;
};

struct FinishOut {
  std::string data;
  std::vector<int64_t> span_off;
  std::vector<int64_t> span_len;
  std::vector<int32_t> status;
};

class DocEncoder {
 public:
  // doc_stride < 0: classic column layout — every column is a dense
  // [n_docs, n_blocks_cap] array, ship/deleted are u8.  doc_stride >= 0:
  // STRIDED packed-arena layout (ISSUE-10) — the column pointers all
  // point into ONE host copy of the device's packed [D, 15, R] i32
  // tensor (pointer for plane k = arena + k*R), consecutive docs are
  // doc_stride (= 15*R) apart, and the ship/offsets/deleted planes are
  // i32 like everything else (no per-plane u8 conversion copies).
  DocEncoder(const FinishIn& in, int32_t doc, int64_t doc_stride)
      : in_(in),
        base_(static_cast<int64_t>(doc) *
              (doc_stride < 0 ? in.n_blocks_cap : doc_stride)),
        ship32_(doc_stride < 0
                    ? nullptr
                    : reinterpret_cast<const int32_t*>(in.ship)),
        del32_(doc_stride < 0
                   ? nullptr
                   : reinterpret_cast<const int32_t*>(in.deleted)) {}

  bool ship_at(int32_t r) const {
    return ship32_ ? ship32_[base_ + r] != 0 : in_.ship[base_ + r] != 0;
  }

  bool deleted_at(int32_t r) const {
    return del32_ ? del32_[base_ + r] != 0 : in_.deleted[base_ + r] != 0;
  }

  // returns false → caller must fall back to the Python finisher
  bool run(Buf& out) {
    const int32_t B = in_.n_blocks_cap;
    // group shipped rows by interned client
    std::vector<int32_t> rows;
    rows.reserve(64);
    for (int32_t r = 0; r < B; r++)
      if (ship_at(r)) rows.push_back(r);
    // client set, ordered by real id descending
    std::vector<int32_t> clients;
    for (int32_t r : rows) {
      int32_t c = in_.client[base_ + r];
      if (c < 0 || c >= in_.n_interned) return false;
      if (std::find(clients.begin(), clients.end(), c) == clients.end())
        clients.push_back(c);
    }
    std::sort(clients.begin(), clients.end(), [&](int32_t a, int32_t b) {
      return in_.from_idx[a] > in_.from_idx[b];
    });
    out.var(clients.size());
    for (int32_t c : clients) {
      std::vector<int32_t> slots;
      for (int32_t r : rows)
        if (in_.client[base_ + r] == c) slots.push_back(r);
      std::sort(slots.begin(), slots.end(), [&](int32_t a, int32_t b) {
        return in_.clock[base_ + a] < in_.clock[base_ + b];
      });
      out.var(slots.size());
      out.var(static_cast<uint64_t>(in_.from_idx[c]));
      int32_t first_off = in_.offsets[base_ + slots[0]];
      out.var(static_cast<uint64_t>(in_.clock[base_ + slots[0]] + first_off));
      for (size_t pos = 0; pos < slots.size(); pos++) {
        int32_t off = (pos == 0) ? first_off : 0;
        if (!encode_row(out, slots[pos], off)) return false;
      }
    }
    return encode_delete_set(out);
  }

 private:
  bool encode_row(Buf& out, int32_t r, int32_t off) {
    const int64_t i = base_ + r;
    const int32_t kind = in_.kind[i];
    if (kind == KIND_GC) {
      out.u8(KIND_GC);
      out.var(static_cast<uint64_t>(in_.length[i] - off));
      return true;
    }
    int32_t oc = in_.origin_client[i], ok = in_.origin_clock[i];
    int32_t rc = in_.ror_client[i], rk = in_.ror_clock[i];
    const int32_t clock = in_.clock[i];
    if (off > 0) {
      oc = in_.client[i];
      ok = clock + off - 1;
    }
    const bool has_o = oc >= 0, has_r = rc >= 0;
    const int32_t key = in_.key[i];
    const bool has_sub = key >= 0;
    out.u8(static_cast<uint8_t>(kind | (has_o ? 0x80 : 0) |
                                (has_r ? 0x40 : 0) | (has_sub ? 0x20 : 0)));
    if (has_o) {
      if (oc >= in_.n_interned) return false;
      out.var(static_cast<uint64_t>(in_.from_idx[oc]));
      out.var(static_cast<uint64_t>(ok));
    }
    if (has_r) {
      if (rc >= in_.n_interned) return false;
      out.var(static_cast<uint64_t>(in_.from_idx[rc]));
      out.var(static_cast<uint64_t>(rk));
    }
    if (!has_o && !has_r) {
      const int32_t parent_row = in_.parent[i];
      if (parent_row >= 0) {
        if (parent_row >= in_.n_blocks_cap) return false;
        const int64_t p = base_ + parent_row;
        if (in_.kind[p] == KIND_ROOT_ANCHOR) {
          // non-primary named root: emit the root-name form with the
          // anchor's interned key name
          const int32_t rkey = in_.key[p];
          if (rkey < 0 || rkey >= in_.n_keys) return false;
          const int64_t ks = in_.key_off[rkey], ke = in_.key_off[rkey + 1];
          out.var(1);
          out.str(in_.key_blob + ks, static_cast<size_t>(ke - ks));
        } else {
          const int32_t pc = in_.client[p];
          if (pc < 0 || pc >= in_.n_interned) return false;
          out.var(0);  // parent_info: nested (not a root name)
          out.var(static_cast<uint64_t>(in_.from_idx[pc]));
          out.var(static_cast<uint64_t>(in_.clock[p]));
        }
      } else {
        out.var(1);  // parent_info: root name
        out.str(in_.root_name, static_cast<size_t>(in_.root_name_len));
      }
      if (has_sub) {
        if (key >= in_.n_keys) return false;
        const int64_t ks = in_.key_off[key], ke = in_.key_off[key + 1];
        out.str(in_.key_blob + ks, static_cast<size_t>(ke - ks));
      }
    }
    const int32_t ref = in_.content_ref[i];
    const int64_t c_off = static_cast<int64_t>(in_.content_off[i]) + off;
    const int64_t length = in_.length[i] - off;
    return encode_content(out, kind, ref, c_off, length);
  }

  bool encode_content(Buf& out, int32_t kind, int32_t ref, int64_t c_off,
                      int64_t length) {
    if (kind == KIND_DELETED) {
      out.var(static_cast<uint64_t>(length));
      return true;
    }
    if (ref >= 0) return encode_host_content(out, kind, ref, c_off, length);
    if (ref <= -2) {
      const int64_t w = -(static_cast<int64_t>(ref) + 2);
      return encode_wire_content(out, kind, w, c_off, length);
    }
    return false;  // ref == -1 with payload-bearing kind
  }

  bool encode_host_content(Buf& out, int32_t kind, int32_t ref, int64_t c_off,
                           int64_t length) {
    if (ref >= in_.n_items) return false;
    if (kind == KIND_STRING) {
      const int64_t toff = in_.item_text_off[ref];
      if (toff < 0 || c_off < 0 || length < 0) return false;
      // slice must stay inside this item's payload AND the arena
      // (inconsistent content_off/length columns → Python fallback, which
      // slices safely, instead of an out-of-bounds native read)
      if (c_off + length > in_.item_text_units[ref]) return false;
      if (toff + 2 * (c_off + length) > in_.text_arena_len) return false;
      scratch_.clear();
      utf16le_to_utf8(in_.text_arena + toff + 2 * c_off,
                      static_cast<size_t>(length), scratch_);
      out.str(reinterpret_cast<const uint8_t*>(scratch_.data()),
              scratch_.size());
      return true;
    }
    if (kind == KIND_ANY) {
      const int64_t eb = in_.item_elem_base[ref];
      if (eb < 0 || c_off < 0 || length < 0) return false;
      if (c_off + length > in_.item_elem_count[ref]) return false;
      out.var(static_cast<uint64_t>(length));
      const int64_t s = in_.elem_off[eb + c_off];
      const int64_t e = in_.elem_off[eb + c_off + length];
      if (s < 0 || e < s || e > in_.elem_arena_len) return false;
      out.raw(in_.elem_arena + s, static_cast<size_t>(e - s));
      return true;
    }
    // every other host payload pre-encodes its full content bytes
    // (ContentFormat/Embed/Binary/Json/Type/Doc/Move .encode — the Python
    // finisher's else-branch, batch_doc.py _encode_device_row)
    const int64_t boff = in_.item_blob_off[ref];
    const int64_t blen = in_.item_blob_len[ref];
    if (boff < 0 || blen < 0 || boff + blen > in_.blob_arena_len) return false;
    out.raw(in_.blob_arena + boff, static_cast<size_t>(blen));
    return true;
  }

  bool encode_wire_content(Buf& out, int32_t kind, int64_t w, int64_t c_off,
                           int64_t length) {
    const uint8_t* p = in_.wire;
    const int64_t L = in_.wire_len;
    if (w < 0 || w >= L) return false;
    if (kind == KIND_STRING) {
      scratch_.clear();
      if (!utf8_slice_u16(p, L, w, c_off, length, scratch_)) return false;
      out.str(reinterpret_cast<const uint8_t*>(scratch_.data()),
              scratch_.size());
      return true;
    }
    if (kind == KIND_ANY) {
      int64_t pos = w;
      uint64_t n;
      if (!read_var(p, L, pos, n)) return false;
      const int64_t avail =
          (n > static_cast<uint64_t>(INT64_MAX))
              ? c_off + length
              : std::min<int64_t>(static_cast<int64_t>(n), c_off + length);
      for (int64_t k = 0; k < c_off && k < avail; k++)
        if (!skip_any(p, L, pos)) return false;
      // Python emits write_len(length) then re-encodes each value through
      // read_any → write_any; reencode_any reproduces that canonicalization
      out.var(static_cast<uint64_t>(length));
      for (int64_t k = c_off; k < avail; k++)
        if (!reencode_any(p, L, pos, out)) return false;
      return true;
    }
    if (kind == KIND_JSON) {
      int64_t pos = w;
      uint64_t n;
      if (!read_var(p, L, pos, n)) return false;
      const int64_t avail =
          (n > static_cast<uint64_t>(INT64_MAX))
              ? c_off + length
              : std::min<int64_t>(static_cast<int64_t>(n), c_off + length);
      for (int64_t k = 0; k < c_off && k < avail; k++) {
        uint64_t slen;
        if (!read_var(p, L, pos, slen)) return false;
        if (slen > static_cast<uint64_t>(L - pos)) return false;
        pos += static_cast<int64_t>(slen);
      }
      const int64_t s = pos;
      int64_t count = 0;
      for (int64_t k = c_off; k < avail; k++) {
        uint64_t slen;
        if (!read_var(p, L, pos, slen)) return false;
        if (slen > static_cast<uint64_t>(L - pos)) return false;
        pos += static_cast<int64_t>(slen);
        count++;
      }
      out.var(static_cast<uint64_t>(count));
      out.raw(p + s, static_cast<size_t>(pos - s));
      return true;
    }
    if (kind == KIND_BINARY) {
      // read_buf → write_buf round-trips bytes exactly: copy the span
      int64_t pos = w;
      uint64_t n;
      if (!read_var(p, L, pos, n)) return false;
      if (n > static_cast<uint64_t>(L - pos)) return false;
      pos += static_cast<int64_t>(n);
      out.raw(p + w, static_cast<size_t>(pos - w));
      return true;
    }
    if (kind == KIND_TYPE) {
      // device-retained ContentType span: verbatim copy of the TypeRef
      // tag byte (+ XmlElement/XmlHook name buf) — no re-serialization
      int64_t pos = w;
      if (pos >= L) return false;
      const uint8_t tag = p[pos++];
      if (tag == 3 || tag == 5) {
        uint64_t n;
        if (!read_var(p, L, pos, n)) return false;
        if (n > static_cast<uint64_t>(L - pos)) return false;
        pos += static_cast<int64_t>(n);
      }
      out.raw(p + w, static_cast<size_t>(pos - w));
      return true;
    }
    // wire Format/Embed refs re-serialize JSON through Python (json value
    // round-trip — not byte-stable from C++); other kinds are out of the
    // device decoder's raw-wire scope anyway. Fall back.
    return false;
  }

  bool encode_delete_set(Buf& out) {
    const int32_t B = in_.n_blocks_cap;
    // collect (real_client, start, end), squash per client, clients desc
    struct Entry {
      int64_t client;
      std::vector<std::pair<int64_t, int64_t>> ranges;
    };
    std::vector<Entry> entries;
    for (int32_t r = 0; r < B; r++) {
      if (!deleted_at(r)) continue;
      const int32_t c = in_.client[base_ + r];
      if (c < 0 || c >= in_.n_interned) return false;
      const int64_t real = in_.from_idx[c];
      const int64_t s = in_.clock[base_ + r];
      const int64_t e = s + in_.length[base_ + r];
      if (e <= s) continue;
      auto it = std::find_if(entries.begin(), entries.end(),
                             [&](const Entry& x) { return x.client == real; });
      if (it == entries.end()) {
        entries.push_back({real, {{s, e}}});
      } else {
        it->ranges.emplace_back(s, e);
      }
    }
    for (auto& e : entries) {
      std::sort(e.ranges.begin(), e.ranges.end());
      std::vector<std::pair<int64_t, int64_t>> sq;
      for (auto& r : e.ranges) {
        if (!sq.empty() && r.first <= sq.back().second) {
          if (r.second > sq.back().second) sq.back().second = r.second;
        } else {
          sq.push_back(r);
        }
      }
      e.ranges.swap(sq);
    }
    std::sort(entries.begin(), entries.end(),
              [](const Entry& a, const Entry& b) { return a.client > b.client; });
    out.var(entries.size());
    for (const auto& e : entries) {
      out.var(static_cast<uint64_t>(e.client));
      out.var(e.ranges.size());
      for (const auto& r : e.ranges) {
        out.var(static_cast<uint64_t>(r.first));
        out.var(static_cast<uint64_t>(r.second - r.first));
      }
    }
    return true;
  }

  const FinishIn& in_;
  const int64_t base_;
  const int32_t* ship32_;  // strided mode only (else null → u8 masks)
  const int32_t* del32_;
  std::string scratch_;
};

// One worker's output: spans are relative to this shard's `data` and get
// rebased during the merge.
struct Shard {
  std::string data;
  std::vector<int64_t> off;
  std::vector<int64_t> len;
  std::vector<int32_t> status;
};

void encode_range(const FinishIn& in, int64_t doc_stride, int32_t lo,
                  int32_t hi, Shard& sh) {
  const int32_t n = hi - lo;
  sh.off.assign(n, 0);
  sh.len.assign(n, 0);
  sh.status.assign(n, STATUS_FALLBACK);
  Buf buf;
  for (int32_t i = lo; i < hi; i++) {
    const int32_t doc = in.sel[i];
    const size_t start = buf.b.size();
    DocEncoder enc(in, doc, doc_stride);
    if (doc < 0 || doc >= in.n_docs_total || !enc.run(buf)) {
      buf.b.resize(start);  // drop partial output
      continue;
    }
    sh.status[i - lo] = STATUS_OK;
    sh.off[i - lo] = static_cast<int64_t>(start);
    sh.len[i - lo] = static_cast<int64_t>(buf.b.size() - start);
  }
  sh.data.swap(buf.b);
}

}  // namespace

extern "C" {

// layout guard: the Python ctypes mirror asserts this equals
// ctypes.sizeof(FinishIn) before binding (catches field drift between
// the two hand-maintained struct definitions)
int64_t ytpu_finish_in_sizeof() { return static_cast<int64_t>(sizeof(FinishIn)); }

// Docs encode independently (FinishIn is read-only; each DocEncoder owns
// its scratch), so the batch splits into contiguous chunks of `sel`, one
// per worker. n_threads <= 0 means hardware concurrency — the Python
// caller decides whether a pool is worth spawning (it thresholds on
// TOTAL selected rows, not doc count, so a few huge docs still fan out);
// this side only caps workers at one doc per chunk. Called with the GIL
// released (ctypes drops it around foreign calls).
void* finish_batch_impl(const FinishIn* in, int64_t doc_stride,
                        int32_t n_threads) {
  auto* out = new FinishOut();
  const int32_t n = in->n_sel;
  out->span_off.resize(n);
  out->span_len.resize(n);
  out->status.resize(n);
  if (n == 0) return out;
  int32_t hw = static_cast<int32_t>(std::thread::hardware_concurrency());
  if (hw <= 0) hw = 1;
  // one doc per chunk at minimum granularity; the max() keeps a direct
  // ABI caller with a degenerate n_sel from ever sizing zero shards
  int32_t t = n_threads <= 0 ? hw : std::min(n_threads, hw);
  t = std::max(int32_t{1}, std::min(t, n));
  std::vector<Shard> shards(t);
  if (t <= 1) {
    encode_range(*in, doc_stride, 0, n, shards[0]);
  } else {
    std::vector<std::thread> pool;
    pool.reserve(t);
    for (int32_t k = 0; k < t; k++) {
      const int32_t lo = static_cast<int32_t>(static_cast<int64_t>(n) * k / t);
      const int32_t hi =
          static_cast<int32_t>(static_cast<int64_t>(n) * (k + 1) / t);
      pool.emplace_back(encode_range, std::cref(*in), doc_stride, lo, hi,
                        std::ref(shards[k]));
    }
    for (auto& th : pool) th.join();
  }
  size_t total = 0;
  for (const auto& sh : shards) total += sh.data.size();
  out->data.reserve(total);
  int32_t i = 0;
  for (const auto& sh : shards) {
    const int64_t base = static_cast<int64_t>(out->data.size());
    out->data.append(sh.data);
    for (size_t j = 0; j < sh.status.size(); j++, i++) {
      out->status[i] = sh.status[j];
      out->span_off[i] = sh.status[j] == STATUS_OK ? base + sh.off[j] : 0;
      out->span_len[i] = sh.len[j];
    }
  }
  return out;
}

void* ytpu_finish_batch_mt(const FinishIn* in, int32_t n_threads) {
  return finish_batch_impl(in, -1, n_threads);
}

// ISSUE-10: the packed-arena entry — the column pointers in `in` point
// into one contiguous host copy of the device's packed [D, 15, R] i32
// tensor (plane k's pointer = arena + k*R) and consecutive docs sit
// `doc_stride` (= 15*R) int32s apart.  Saves the 15 per-plane
// `ascontiguousarray` copies the classic entry needs; the ship/offsets/
// deleted planes are read as i32.
void* ytpu_finish_batch_strided(const FinishIn* in, int64_t doc_stride,
                                int32_t n_threads) {
  return finish_batch_impl(in, doc_stride, n_threads);
}

void* ytpu_finish_batch(const FinishIn* in) {
  return ytpu_finish_batch_mt(in, 1);
}

int32_t ytpu_finish_status(void* h, int32_t i) {
  return static_cast<FinishOut*>(h)->status[i];
}

const uint8_t* ytpu_finish_data(void* h) {
  return reinterpret_cast<const uint8_t*>(
      static_cast<FinishOut*>(h)->data.data());
}

void ytpu_finish_span(void* h, int32_t i, int64_t* off, int64_t* len) {
  auto* o = static_cast<FinishOut*>(h);
  *off = o->span_off[i];
  *len = o->span_len[i];
}

int64_t ytpu_finish_total_len(void* h) {
  return static_cast<int64_t>(static_cast<FinishOut*>(h)->data.size());
}

// ISSUE-10: vectorized span/status readout — one call fills the caller's
// offset/length/status tables for the whole batch, replacing the 3
// ctypes round-trips PER DOC of the span/status getters (the "per-doc
// Python glue" half of the old finisher handoff).
void ytpu_finish_spans(void* h, int64_t* off, int64_t* len, int32_t* status) {
  auto* o = static_cast<FinishOut*>(h);
  const size_t n = o->status.size();
  std::memcpy(off, o->span_off.data(), n * sizeof(int64_t));
  std::memcpy(len, o->span_len.data(), n * sizeof(int64_t));
  std::memcpy(status, o->status.data(), n * sizeof(int32_t));
}

void ytpu_finish_free(void* h) { delete static_cast<FinishOut*>(h); }

}  // extern "C"
