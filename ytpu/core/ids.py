"""Block identifiers.

Semantics follow the reference model (/root/reference/yrs/src/block.rs:75-93):
a block is addressed by a Lamport-style ``(client, clock)`` pair; a block of
length ``len`` covers clocks ``clock .. clock+len-1``.

In the device path these become two i32/i64 columns of the block tensor
(`ytpu.models.batch_doc`); here they are a tiny value type for the host engine.
"""

from __future__ import annotations

from typing import NamedTuple

__all__ = ["ID", "ClientID"]

ClientID = int


class ID(NamedTuple):
    client: int
    clock: int

    def __repr__(self) -> str:
        return f"<{self.client}#{self.clock}>"
