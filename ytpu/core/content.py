"""Item content variants.

Behavioral parity target: `ItemContent` in /root/reference/yrs/src/block.rs:1507-1928
(10 variants; wire ref-numbers at block.rs:28-61). Each content kind knows its
CRDT length (measured in UTF-16 code units for strings, element count for
sequences — this is what advances the Lamport clock), whether it is countable
(contributes to the visible length of a sequence), how to split at an offset,
how to merge with a right neighbor, and its v1 wire encoding.

Device mapping: content payloads never live in the block tensor itself — the
tensor carries ``(content_kind, content_ref, len)`` columns and the payloads
stay in host-side side buffers (see `ytpu.models.batch_doc`).
"""

from __future__ import annotations

import json
from typing import Any as PyAny, List, Optional, Tuple

from ytpu.encoding.lib0 import Cursor, Writer

__all__ = [
    "BLOCK_GC",
    "BLOCK_SKIP",
    "CONTENT_DELETED",
    "CONTENT_JSON",
    "CONTENT_BINARY",
    "CONTENT_STRING",
    "CONTENT_EMBED",
    "CONTENT_FORMAT",
    "CONTENT_TYPE",
    "CONTENT_ANY",
    "CONTENT_DOC",
    "CONTENT_MOVE",
    "utf16_len",
    "utf16_index",
    "split_str_utf16",
    "Content",
    "ContentDeleted",
    "ContentJSON",
    "ContentBinary",
    "ContentString",
    "ContentEmbed",
    "ContentFormat",
    "ContentType",
    "ContentAny",
    "ContentDoc",
    "ContentMove",
    "decode_content",
]

# Wire ref-numbers (low bits of the item info byte); parity: block.rs:28-61.
BLOCK_GC = 0
CONTENT_DELETED = 1
CONTENT_JSON = 2
CONTENT_BINARY = 3
CONTENT_STRING = 4
CONTENT_EMBED = 5
CONTENT_FORMAT = 6
CONTENT_TYPE = 7
CONTENT_ANY = 8
CONTENT_DOC = 9
BLOCK_SKIP = 10
CONTENT_MOVE = 11
# Device-engine sentinel (NOT a wire ref): a synthetic per-doc block row
# anchoring a non-primary named root branch (doc.rs:156-228 multi-root
# shape). Anchor rows have client == -1 (no wire identity, never ship);
# blocks parented to one re-emit the root-name wire form at encode time.
BLOCK_ROOT_ANCHOR = 12


def utf16_len(s: str) -> int:
    """Length of `s` in UTF-16 code units (the Yjs clock unit for text)."""
    n = len(s)
    # Astral characters (> U+FFFF) take two code units.
    for ch in s:
        if ord(ch) > 0xFFFF:
            n += 1
    return n


def utf16_index(s: str, offset: int) -> int:
    """Convert a UTF-16 code-unit offset into a Python string index."""
    if offset <= 0:
        return 0
    units = 0
    for i, ch in enumerate(s):
        if units >= offset:
            return i
        units += 2 if ord(ch) > 0xFFFF else 1
    return len(s)


def split_str_utf16(s: str, offset: int) -> Tuple[str, str]:
    """Split at a UTF-16 code-unit offset.

    If the offset lands inside a surrogate pair (astral char), both halves
    get a U+FFFD replacement for their severed half so the UTF-16 lengths
    stay consistent with the clock split (the workaround documented at
    reference block.rs:1852-1860).
    """
    if offset <= 0:
        return "", s
    units = 0
    for i, ch in enumerate(s):
        if units == offset:
            return s[:i], s[i:]
        width = 2 if ord(ch) > 0xFFFF else 1
        if units + width > offset:
            # offset splits this astral char
            return s[:i] + "�", "�" + s[i + 1 :]
        units += width
    return s, ""


class Content:
    """Base class for item content."""

    kind: int = -1
    countable: bool = False

    def length(self) -> int:
        raise NotImplementedError

    def splice(self, offset: int) -> "Content":
        """Split in place at `offset` (clock units); returns the right part."""
        raise NotImplementedError(f"{type(self).__name__} is not splittable")

    def merge(self, other: "Content") -> bool:
        """Try to append `other` (right neighbor's content). True on success."""
        return False

    def encode(self, enc) -> None:
        raise NotImplementedError

    def values(self) -> List[PyAny]:
        """User-facing element values (for countable sequence content)."""
        return []

    def copy(self) -> "Content":
        raise NotImplementedError


class ContentDeleted(Content):
    kind = CONTENT_DELETED
    countable = False
    __slots__ = ("len",)

    def __init__(self, length: int):
        self.len = length

    def length(self) -> int:
        return self.len

    def splice(self, offset: int) -> "ContentDeleted":
        right = ContentDeleted(self.len - offset)
        self.len = offset
        return right

    def merge(self, other: Content) -> bool:
        if isinstance(other, ContentDeleted):
            self.len += other.len
            return True
        return False

    def encode(self, enc) -> None:
        enc.write_len(self.len)

    def copy(self) -> "ContentDeleted":
        return ContentDeleted(self.len)

    def __repr__(self) -> str:
        return f"Deleted({self.len})"


class ContentJSON(Content):
    """Legacy JSON content: a list of raw JSON strings (one clock unit each)."""

    kind = CONTENT_JSON
    countable = True
    __slots__ = ("raw",)

    def __init__(self, raw: List[str]):
        self.raw = raw

    def length(self) -> int:
        return len(self.raw)

    def splice(self, offset: int) -> "ContentJSON":
        right = ContentJSON(self.raw[offset:])
        self.raw = self.raw[:offset]
        return right

    def merge(self, other: Content) -> bool:
        if isinstance(other, ContentJSON):
            self.raw.extend(other.raw)
            return True
        return False

    def encode(self, enc) -> None:
        enc.write_len(len(self.raw))
        for s in self.raw:
            enc.write_string(s)

    def values(self) -> List[PyAny]:
        out = []
        for s in self.raw:
            try:
                out.append(json.loads(s))
            except (ValueError, TypeError):
                out.append(None)
        return out

    def copy(self) -> "ContentJSON":
        return ContentJSON(list(self.raw))

    def __repr__(self) -> str:
        return f"JSON({self.raw!r})"


class ContentBinary(Content):
    kind = CONTENT_BINARY
    countable = True
    __slots__ = ("data",)

    def __init__(self, data: bytes):
        self.data = data

    def length(self) -> int:
        return 1

    def encode(self, enc) -> None:
        enc.write_buf(self.data)

    def values(self) -> List[PyAny]:
        return [self.data]

    def copy(self) -> "ContentBinary":
        return ContentBinary(self.data)

    def __repr__(self) -> str:
        return f"Binary({len(self.data)}b)"


class ContentString(Content):
    kind = CONTENT_STRING
    countable = True
    __slots__ = ("text", "_u16len")

    def __init__(self, text: str):
        self.text = text
        self._u16len = utf16_len(text)

    def length(self) -> int:
        return self._u16len

    def splice(self, offset: int) -> "ContentString":
        left, right = split_str_utf16(self.text, offset)
        self.text = left
        self._u16len = offset
        return ContentString(right)

    def merge(self, other: Content) -> bool:
        if isinstance(other, ContentString):
            self.text += other.text
            self._u16len += other._u16len
            return True
        return False

    def encode(self, enc) -> None:
        enc.write_string(self.text)

    def values(self) -> List[PyAny]:
        return list(self.text)

    def copy(self) -> "ContentString":
        return ContentString(self.text)

    def __repr__(self) -> str:
        return f"Str({self.text!r})"


class ContentEmbed(Content):
    kind = CONTENT_EMBED
    countable = True
    __slots__ = ("value",)

    def __init__(self, value: PyAny):
        self.value = value

    def length(self) -> int:
        return 1

    def encode(self, enc) -> None:
        enc.write_json(self.value)

    def values(self) -> List[PyAny]:
        return [self.value]

    def copy(self) -> "ContentEmbed":
        return ContentEmbed(self.value)

    def __repr__(self) -> str:
        return f"Embed({self.value!r})"


class ContentFormat(Content):
    kind = CONTENT_FORMAT
    countable = False
    __slots__ = ("key", "value")

    def __init__(self, key: str, value: PyAny):
        self.key = key
        self.value = value

    def length(self) -> int:
        return 1

    def encode(self, enc) -> None:
        enc.write_key(self.key)
        enc.write_json(self.value)

    def copy(self) -> "ContentFormat":
        return ContentFormat(self.key, self.value)

    def __repr__(self) -> str:
        return f"Format({self.key}={self.value!r})"


class ContentType(Content):
    """An embedded shared type; holds the `Branch` node (ytpu.core.branch)."""

    kind = CONTENT_TYPE
    countable = True
    __slots__ = ("branch",)

    def __init__(self, branch):
        self.branch = branch

    def length(self) -> int:
        return 1

    def encode(self, enc) -> None:
        self.branch.encode_type_ref(enc)

    def values(self) -> List[PyAny]:
        return [self.branch]

    def copy(self) -> "ContentType":
        # Branch copy only makes sense for carriers that were never integrated.
        return ContentType(self.branch)

    def __repr__(self) -> str:
        return f"Type({self.branch.type_ref})"


class ContentAny(Content):
    kind = CONTENT_ANY
    countable = True
    __slots__ = ("items",)

    def __init__(self, items: List[PyAny]):
        self.items = items

    def length(self) -> int:
        return len(self.items)

    def splice(self, offset: int) -> "ContentAny":
        right = ContentAny(self.items[offset:])
        self.items = self.items[:offset]
        return right

    def merge(self, other: Content) -> bool:
        if isinstance(other, ContentAny):
            self.items.extend(other.items)
            return True
        return False

    def encode(self, enc) -> None:
        enc.write_len(len(self.items))
        for v in self.items:
            enc.write_any(v)

    def values(self) -> List[PyAny]:
        return list(self.items)

    def copy(self) -> "ContentAny":
        return ContentAny(list(self.items))

    def __repr__(self) -> str:
        return f"Any({self.items!r})"


class ContentDoc(Content):
    """A nested sub-document (reference: block.rs:1518, doc.rs:840-872)."""

    kind = CONTENT_DOC
    countable = True
    __slots__ = ("doc",)

    def __init__(self, doc):
        self.doc = doc

    def length(self) -> int:
        return 1

    def encode(self, enc) -> None:
        self.doc.options.encode(enc)

    def values(self) -> List[PyAny]:
        return [self.doc]

    def copy(self) -> "ContentDoc":
        return ContentDoc(self.doc)

    def __repr__(self) -> str:
        return f"Doc({self.doc.guid})"


class ContentMove(Content):
    """A move-range marker (reference: moving.rs:16)."""

    kind = CONTENT_MOVE
    countable = False
    __slots__ = ("move",)

    def __init__(self, move):
        self.move = move

    def length(self) -> int:
        return 1

    def encode(self, enc) -> None:
        self.move.encode(enc)

    def copy(self) -> "ContentMove":
        return ContentMove(self.move.copy())

    def __repr__(self) -> str:
        return f"Move({self.move})"


def decode_content(dec, info: int, decode_branch, decode_doc, decode_move) -> Content:
    """Decode an item's content given its info byte and a v1/v2 decoder.

    `decode_branch(dec)` / `decode_doc(dec)` / `decode_move(dec)` are injected
    to avoid circular imports with the branch/doc/move modules.
    Parity: block.rs:1786-1835 (note: the reference masks with 0b1111).
    """
    ref = info & 0b1111
    if ref == CONTENT_DELETED:
        return ContentDeleted(dec.read_len())
    if ref == CONTENT_JSON:
        # Note: Yjs writes n then n JSON strings; yrs's decoder (block.rs:1790-1797)
        # reads n+1 which is asymmetric with its own encoder — we follow Yjs.
        n = dec.read_len()
        return ContentJSON([dec.read_string() for _ in range(n)])
    if ref == CONTENT_BINARY:
        return ContentBinary(dec.read_buf())
    if ref == CONTENT_STRING:
        return ContentString(dec.read_string())
    if ref == CONTENT_EMBED:
        return ContentEmbed(dec.read_json())
    if ref == CONTENT_FORMAT:
        key = dec.read_key()
        return ContentFormat(key, dec.read_json())
    if ref == CONTENT_TYPE:
        return ContentType(decode_branch(dec))
    if ref == CONTENT_ANY:
        n = dec.read_len()
        return ContentAny([dec.read_any() for _ in range(n)])
    if ref == CONTENT_DOC:
        return ContentDoc(decode_doc(dec))
    if ref == CONTENT_MOVE:
        return ContentMove(decode_move(dec))
    raise ValueError(f"unexpected content ref {ref}")
