"""DocStore — per-document state + the YATA integration algorithm.

Behavioral parity targets:
- `Store` (/root/reference/yrs/src/store.rs:27-62, encode_diff :194-248)
- `ItemPtr::integrate` — the YATA conflict-resolution algorithm
  (/root/reference/yrs/src/block.rs:482-769) and `Item::repair`
  (block.rs:1287-1343)
- `GCCollector` (/root/reference/yrs/src/gc.rs)

The store owns the columnar block lists (`ytpu.core.block_store.BlockStore`),
the root-type registry, the pending-update stash, and sub-document links. The
device path (`ytpu.models.batch_doc`) holds N of these as one struct-of-arrays
pytree; this host form is the per-tenant oracle and the ragged boundary.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ytpu.encoding.codec import DecoderV1, DecoderV2, EncoderV1, EncoderV2
from ytpu.encoding.lib0 import Writer

from .block import GCRange, Item, SkipRange
from .block_store import BlockStore
from .branch import Branch, TYPE_UNDEFINED
from .content import (
    ContentDeleted,
    ContentDoc,
    ContentMove,
    ContentType,
)
from .id_set import DeleteSet
from .ids import ID, ClientID
from .state_vector import Snapshot, StateVector
from .update import PendingUpdate, Update

__all__ = ["DocStore"]

# Optional perf probe (benches/device.py config #3 diagnostic): when set
# to a list, every YATA conflict scan appends its candidate-walk length.
# The device engine runs the SAME scan as a while_loop whose iteration
# count this distribution bounds — the p99 here explains conflict-heavy
# workloads' device step cost.
SCAN_WIDTH_PROBE: Optional[list] = None


class DocStore:
    __slots__ = (
        "doc",
        "types",
        "blocks",
        "pending",
        "pending_ds",
        "subdocs",
        "linked_by",
        "node_registry",
    )

    def __init__(self, doc):
        self.doc = doc
        self.types: Dict[str, Branch] = {}
        self.blocks = BlockStore()
        self.pending: Optional[PendingUpdate] = None
        self.pending_ds: Optional[DeleteSet] = None
        self.subdocs: Dict[str, object] = {}
        self.linked_by: Dict[Item, Set[Branch]] = {}
        self.node_registry: Set[int] = set()  # ids of live nested branches

    # --- root types ------------------------------------------------------------

    def get_or_create_type(self, name: str, type_ref: int) -> Branch:
        """Parity: store.rs:114 (+ repair_type_ref upgrade on Undefined)."""
        branch = self.types.get(name)
        if branch is None:
            branch = Branch(type_ref)
            branch.name = name
            branch.store = self
            self.types[name] = branch
        elif branch.type_ref == TYPE_UNDEFINED and type_ref != TYPE_UNDEFINED:
            branch.type_ref = type_ref
        return branch

    def get_local_state(self) -> int:
        return self.blocks.get_clock(self.doc.client_id)

    def register(self, branch: Branch) -> Branch:
        branch.store = self
        self.node_registry.add(id(branch))
        return branch

    def deregister(self, branch: Branch) -> None:
        self.node_registry.discard(id(branch))

    # --- repair: resolve wire-level references to live objects -----------------

    def repair(self, item: Item) -> None:
        """Resolve origin/right-origin IDs to split block pointers and the
        parent reference to a live Branch. Parity: block.rs:1287-1343."""
        if item.origin is not None:
            item.left = self.blocks.get_item_clean_end(item.origin)
        if item.right_origin is not None:
            item.right = self.blocks.get_item_clean_start(item.right_origin)

        parent = item.parent
        if isinstance(parent, Branch):
            pass
        elif parent is None:
            # infer from a resolved neighbor
            if item.left is not None and item.left.parent is not None:
                item.parent_sub = item.left.parent_sub
                item.parent = item.left.parent
            elif item.right is not None and item.right.parent is not None:
                item.parent_sub = item.right.parent_sub
                item.parent = item.right.parent
        elif isinstance(parent, ID):
            target = self.blocks.get_item(parent)
            if target is not None:
                content = target.content
                if isinstance(content, ContentType):
                    item.parent = content.branch
                elif isinstance(content, ContentDeleted):
                    item.parent = None
                else:
                    raise ValueError(
                        f"defect: parent {parent} is not a shared type"
                    )
            else:
                item.parent = None
        elif isinstance(parent, str):
            item.parent = self.get_or_create_type(parent, TYPE_UNDEFINED)

    # --- YATA integrate --------------------------------------------------------

    def integrate_block(self, txn, block, offset: int) -> bool:
        """Integrate one carrier. Returns True if the block must be deleted
        right after integration. Parity: block.rs:482-769."""
        if isinstance(block, SkipRange):
            return False
        if isinstance(block, GCRange):
            if offset > 0:
                block.id = ID(block.id.client, block.id.clock + offset)
                block.len -= offset
            return False
        item: Item = block
        if offset > 0:
            item.id = ID(item.id.client, item.id.clock + offset)
            left = self.blocks.get_item_clean_end(ID(item.id.client, item.id.clock - 1))
            item.left = left
            item.origin = left.last_id if left is not None else None
            item.content = item.content.splice(offset)
            item.len -= offset

        # resolve parent (local inserts arrive with a Branch already)
        parent = item.parent
        if isinstance(parent, str):
            parent = self.get_or_create_type(parent, TYPE_UNDEFINED)
            item.parent = parent
        elif isinstance(parent, ID):
            target = self.blocks.get_item(parent)
            if target is not None and isinstance(target.content, ContentType):
                parent = target.content.branch
                item.parent = parent
            else:
                parent = None  # leave item.parent as the dangling ID
        elif parent is None:
            return True  # unknown parent: caller turns the block into GC

        if parent is None:
            return True

        left = item.left
        right = item.right
        right_is_null_or_has_left = right is None or right.left is not None
        left_has_other_right_than_self = left is not None and left.right is not right

        if (left is None and right_is_null_or_has_left) or left_has_other_right_than_self:
            # --- the YATA conflict scan (block.rs:537-602) ---
            if left is not None:
                o = left.right
            elif item.parent_sub is not None:
                o = parent.map.get(item.parent_sub)
                while o is not None and o.left is not None:
                    o = o.left
            else:
                o = parent.start

            conflicting: Set[int] = set()
            before_origin: Set[int] = set()
            _scan_steps = 0
            while o is not None and o is not item.right:
                _scan_steps += 1
                before_origin.add(id(o))
                conflicting.add(id(o))
                if item.origin == o.origin:
                    # case 1: same insertion point — client id breaks the tie
                    if o.id.client < item.id.client:
                        left = o
                        conflicting.clear()
                    elif item.right_origin == o.right_origin:
                        # equivalent right anchors: `item` sorts before `o`
                        break
                else:
                    o_origin = (
                        self.blocks.get_item(o.origin) if o.origin is not None else None
                    )
                    if o_origin is not None and id(o_origin) in before_origin:
                        # case 2: `o` anchors inside the scanned region
                        if id(o_origin) not in conflicting:
                            left = o
                            conflicting.clear()
                    else:
                        break
                o = o.right
            if SCAN_WIDTH_PROBE is not None:
                SCAN_WIDTH_PROBE.append(_scan_steps)
            item.left = left

        # inherit parent_sub from neighbors (block.rs:604-612)
        if item.parent_sub is None and item.left is not None:
            if item.left.parent_sub is not None:
                item.parent_sub = item.left.parent_sub
            elif item.right is not None and item.right.parent_sub is not None:
                item.parent_sub = item.right.parent_sub

        # reconnect left/right (block.rs:614-659)
        if item.left is not None:
            item.right = item.left.right
            item.left.right = item
        else:
            if item.parent_sub is not None:
                r = parent.map.get(item.parent_sub)
                while r is not None and r.left is not None:
                    r = r.left
            else:
                r = parent.start
                parent.start = item
            item.right = r

        if item.right is not None:
            item.right.left = item
        elif item.parent_sub is not None:
            # became the live value of a map entry; shadow the previous chain
            parent.map[item.parent_sub] = item
            if item.left is not None:
                if item.left.linked:
                    # inherit links from the entry we're overriding
                    # (parity: block.rs:642-655)
                    links = self.linked_by.pop(item.left, None)
                    item.left.linked = False
                    if links:
                        item.linked = True
                        self.linked_by.setdefault(item, set()).update(links)
                        for link in links:
                            if link.link_source is not None:
                                link.link_source.first_item = item
                txn.delete(item.left)

        # parent length bookkeeping (block.rs:661-675)
        if item.parent_sub is None and not item.deleted:
            if item.countable:
                parent.block_len += item.len
                parent.content_len += item.len

        # moved-range inheritance / reconciliation (block.rs:677-702)
        left_moved = item.left.moved if item.left is not None else None
        right_moved = item.right.moved if item.right is not None else None
        if left_moved is not None or right_moved is not None:
            if left_moved is right_moved:
                item.moved = left_moved
            else:
                for mover in (left_moved, right_moved):
                    if mover is not None and isinstance(mover.content, ContentMove):
                        m = mover.content.move
                        if not m.is_collapsed():
                            m.integrate_block(txn, mover)

        # content side effects (block.rs:704-741)
        content = item.content
        if isinstance(content, ContentDeleted):
            txn.delete_set.insert(item.id, content.len)
            item.mark_deleted()
        elif isinstance(content, ContentDoc):
            subdoc = content.doc
            subdoc.parent_doc = txn.doc
            subdoc.parent_item = item
            txn.subdocs_added[subdoc.guid] = subdoc
            if subdoc.options.should_load:
                txn.subdocs_loaded[subdoc.guid] = subdoc
        elif isinstance(content, ContentMove):
            content.move.integrate_block(txn, item)
        elif isinstance(content, ContentType):
            if not item.deleted:
                self.register(content.branch)
            if content.branch.link_source is not None:
                from ytpu.types.weak import materialize_link

                materialize_link(self, content.branch)

        txn.add_changed_type(parent, item.parent_sub)

        # notify weak links covering this position (parity: block.rs:743-750)
        if item.linked:
            for link in self.linked_by.get(item, ()):  # pragma: no branch
                txn.add_changed_type(link, item.parent_sub)

        parent_deleted = (
            isinstance(item.parent, Branch)
            and item.parent.item is not None
            and item.parent.item.deleted
        )
        return parent_deleted or (item.parent_sub is not None and item.right is not None)

    def follow_redone(self, id_: ID) -> Optional[Item]:
        """Follow the `redone` chain from `id_` to the live replacement item.

        Parity: store.rs:344.
        """
        next_id = id_
        diff = 0
        item = None
        while True:
            if diff > 0:
                next_id = ID(next_id.client, next_id.clock + diff)
            item = self.blocks.get_item(next_id)
            if item is None:
                return None
            diff = next_id.clock - item.id.clock
            if item.redone is None:
                break
            next_id = item.redone
        if diff > 0:
            return self.blocks.get_item_clean_start(
                ID(item.id.client, item.id.clock + diff)
            )
        return item

    # --- delete-set view over the whole store ---------------------------------

    def delete_set(self) -> DeleteSet:
        """DeleteSet of everything tombstoned or GC'd (parity: DeleteSet::from)."""
        ds = DeleteSet()
        for client, lst in self.blocks.clients.items():
            for b in lst:
                if (b.is_item and b.deleted) or isinstance(b, GCRange):
                    ds.insert_range(client, b.id.clock, b.id.clock + b.len)
        ds.squash()
        return ds

    def snapshot(self) -> Snapshot:
        return Snapshot(self.blocks.get_state_vector(), self.delete_set())

    # --- diff encoding (parity: store.rs:194-248) ------------------------------

    def write_blocks_from(self, remote_sv: StateVector, enc) -> None:
        local_sv = self.blocks.get_state_vector()
        # clients whose local clock is ahead of the remote's view
        diff: List[Tuple[ClientID, int]] = []
        for client, local_clock in local_sv.clocks.items():
            remote_clock = remote_sv.get(client)
            if local_clock > remote_clock:
                diff.append((client, remote_clock))
        # higher client ids first — "heavily improves the conflict algorithm"
        diff.sort(key=lambda e: -e[0])
        enc.write_var(len(diff))
        for client, remote_clock in diff:
            lst = self.blocks.clients[client]
            pivot = lst.find_pivot(remote_clock) if remote_clock > 0 else 0
            if pivot is None:
                pivot = 0
            count = len(lst) - pivot
            first = lst[pivot]
            offset = max(0, remote_clock - first.id.clock)
            enc.write_var(count)
            enc.write_client(client)
            enc.write_var(first.id.clock + offset)
            first.encode(enc, offset)
            for i in range(pivot + 1, len(lst)):
                lst[i].encode(enc, 0)

    def encode_diff(self, remote_sv: StateVector, enc) -> None:
        self.write_blocks_from(remote_sv, enc)
        self.delete_set().encode(enc)

    def encode_diff_v1(self, remote_sv: StateVector) -> bytes:
        enc = EncoderV1()
        self.encode_diff(remote_sv, enc)
        return enc.to_bytes()

    def encode_diff_v2(self, remote_sv: StateVector) -> bytes:
        enc = EncoderV2()
        self.encode_diff(remote_sv, enc)
        return enc.to_bytes()

    def write_blocks_to(self, sv: StateVector, enc) -> None:
        """Encode all blocks *up to* `sv` (snapshot prefix encode).

        Parity: store.rs:153-184.
        """
        local_sv = self.blocks.get_state_vector()
        diff = [
            (client, min(clock, local_sv.get(client)))
            for client, clock in sv.clocks.items()
            if client in local_sv.clocks
        ]
        diff.sort(key=lambda e: -e[0])
        enc.write_var(len(diff))
        for client, clock in diff:
            blocks = self.blocks.clients[client]
            clock = min(clock, blocks.clock() + 1)
            last_idx = blocks.find_pivot(clock - 1)
            if last_idx is None:
                continue
            enc.write_var(last_idx + 1)
            enc.write_client(client)
            enc.write_var(0)
            for i in range(last_idx):
                blocks[i].encode(enc, 0)
            last = blocks[last_idx]
            # encode the last block trimmed to end exactly at `clock`
            end_trim = (last.id.clock + last.len) - clock
            if end_trim > 0 and last.is_item:
                head = last.content.copy()
                head.splice(last.len - end_trim)
                trimmed = Item(
                    last.id,
                    None,
                    last.origin,
                    None,
                    last.right_origin,
                    last.parent,
                    last.parent_sub,
                    head,
                )
                trimmed.encode(enc, 0)
            elif end_trim > 0:
                enc.write_info(0)  # GC
                enc.write_len(last.len - end_trim)
            else:
                last.encode(enc, 0)

    def encode_state_from_snapshot(self, snapshot: Snapshot) -> bytes:
        """Historical state encode (time travel). Requires `skip_gc`.

        Parity: store.rs:139-151.
        """
        if not self.doc.options.skip_gc:
            raise RuntimeError(
                "encode_state_from_snapshot requires a Doc with skip_gc=True"
            )
        enc = EncoderV1()
        self.write_blocks_to(snapshot.state_vector, enc)
        snapshot.delete_set.encode(enc)
        return enc.to_bytes()

    def _encode_state_as_update(self, remote_sv: StateVector, v2: bool) -> bytes:
        """Full diff vs `remote_sv`, folding in any pending stashed data.

        Parity: transaction.rs:73-93 + merge_pending_v1/v2 :247-281.
        """
        base = self.encode_diff_v2(remote_sv) if v2 else self.encode_diff_v1(remote_sv)
        decode = Update.decode_v2 if v2 else Update.decode_v1
        to_merge: List[Update] = []
        if self.pending is not None:
            # round-trip for a deep copy: merge() splits carriers in place
            to_merge.append(Update.decode_v1(self.pending.update.encode_v1()))
        if self.pending_ds is not None:
            to_merge.append(Update(None, DeleteSet(dict(self.pending_ds.clients))))
        if not to_merge:
            return base
        to_merge.insert(0, decode(base))
        merged = Update.merge(to_merge)
        return merged.encode_v2() if v2 else merged.encode_v1()

    def encode_state_as_update_v1(self, remote_sv: StateVector) -> bytes:
        return self._encode_state_as_update(remote_sv, v2=False)

    def encode_state_as_update_v2(self, remote_sv: StateVector) -> bytes:
        return self._encode_state_as_update(remote_sv, v2=True)
