"""Host CRDT core: the per-document semantic oracle.

Layer map (mirrors SURVEY.md §1): ids/state_vector/id_set (L2), block/
block_store (L3), store/transaction/doc/update (L4), with the shared types
in `ytpu.types` (L5) on top.
"""

from .block import GCRange, Item, SkipRange
from .block_store import BlockStore, ClientBlockList
from .branch import Branch
from .doc import Doc, Options
from .id_set import DeleteSet, IdSet
from .ids import ID, ClientID
from .state_vector import Snapshot, StateVector
from .transaction import Transaction
from .update import (
    PendingUpdate,
    Update,
    decode_update_v1,
    diff_updates_v1,
    encode_state_vector_from_update_v1,
    merge_updates_v1,
)

__all__ = [
    "ID",
    "ClientID",
    "StateVector",
    "Snapshot",
    "IdSet",
    "DeleteSet",
    "Item",
    "GCRange",
    "SkipRange",
    "BlockStore",
    "ClientBlockList",
    "Branch",
    "Doc",
    "Options",
    "Transaction",
    "Update",
    "PendingUpdate",
    "decode_update_v1",
    "merge_updates_v1",
    "encode_state_vector_from_update_v1",
    "diff_updates_v1",
]
