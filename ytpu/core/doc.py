"""Doc — the document handle and its options.

Behavioral parity target: /root/reference/yrs/src/doc.rs (`Doc` :57, ctors
:77-123, root-type getters :156-228, observers :230-621, subdocs :625-678,
`Options` :754-838, wire form :840-872) and the `Transact` trait :886-965.

In the batched TPU engine a `Doc` is a tenant slot: `ytpu.models.batch_doc`
hosts N doc states as one pytree and mirrors this exact API per slot.
"""

from __future__ import annotations

import random
import uuid
from typing import Callable, Dict, List, Optional

from ytpu.encoding.lib0 import Cursor, Writer, read_any, write_any

from .branch import (
    Branch,
    TYPE_ARRAY,
    TYPE_MAP,
    TYPE_TEXT,
    TYPE_XML_ELEMENT,
    TYPE_XML_FRAGMENT,
    TYPE_XML_TEXT,
)
from .state_vector import Snapshot, StateVector
from .store import DocStore
from .transaction import Transaction
from .update import Update

__all__ = ["Doc", "Options", "OFFSET_UTF16", "OFFSET_BYTES"]

OFFSET_UTF16 = 0
OFFSET_BYTES = 1


class Options:
    __slots__ = (
        "client_id",
        "guid",
        "collection_id",
        "offset_kind",
        "skip_gc",
        "auto_load",
        "should_load",
    )

    def __init__(
        self,
        client_id: Optional[int] = None,
        guid: Optional[str] = None,
        collection_id: Optional[str] = None,
        offset_kind: int = OFFSET_UTF16,
        skip_gc: bool = False,
        auto_load: bool = False,
        should_load: bool = True,
    ):
        if client_id is None:
            client_id = random.getrandbits(32)
        if guid is None:
            guid = str(uuid.uuid4())
        self.client_id = client_id
        self.guid = guid
        self.collection_id = collection_id
        self.offset_kind = offset_kind
        self.skip_gc = skip_gc
        self.auto_load = auto_load
        self.should_load = should_load

    def encode(self, enc) -> None:
        """Parity: doc.rs:814-845."""
        from ytpu.encoding.lib0 import BigInt

        enc.write_string(self.guid)
        m: Dict[str, object] = {"gc": not self.skip_gc}
        if self.collection_id is not None:
            m["collectionId"] = self.collection_id
        m["encoding"] = BigInt(1 if self.offset_kind == OFFSET_BYTES else 0)
        m["autoLoad"] = self.auto_load
        m["shouldLoad"] = self.should_load
        enc.write_any(m)

    @classmethod
    def decode(cls, dec) -> "Options":
        guid = dec.read_string()
        opts = cls(guid=guid, should_load=False)
        m = dec.read_any()
        if isinstance(m, dict):
            if isinstance(m.get("gc"), bool):
                opts.skip_gc = not m["gc"]
            if isinstance(m.get("autoLoad"), bool):
                opts.auto_load = m["autoLoad"]
            if isinstance(m.get("collectionId"), str):
                opts.collection_id = m["collectionId"]
            if m.get("encoding") == 1:
                opts.offset_kind = OFFSET_BYTES
        opts.should_load = opts.should_load or opts.auto_load
        return opts


class Doc:
    """A CRDT document: a set of root shared types over one block store."""

    def __init__(self, client_id: Optional[int] = None, options: Optional[Options] = None, **kw):
        if options is None:
            options = Options(client_id=client_id, **kw)
        self.options = options
        self.store = DocStore(self)
        self.parent_doc: Optional["Doc"] = None
        self.parent_item = None
        self.destroyed = False
        self.loaded = False
        self._txn: Optional[Transaction] = None
        # observers
        self.update_v1_subs: List[Callable] = []
        self.update_v2_subs: List[Callable] = []
        self.after_transaction_subs: List[Callable] = []
        self.transaction_cleanup_subs: List[Callable] = []
        self.subdocs_subs: List[Callable] = []
        self.destroy_subs: List[Callable] = []

    # --- identity --------------------------------------------------------------

    @property
    def client_id(self) -> int:
        return self.options.client_id

    @client_id.setter
    def client_id(self, value: int) -> None:
        self.options.client_id = value

    @property
    def guid(self) -> str:
        return self.options.guid

    # --- transactions ----------------------------------------------------------

    def transact(self, origin=None) -> Transaction:
        if self._txn is not None:
            raise RuntimeError("a transaction is already active on this Doc")
        txn = Transaction(self, origin)
        self._txn = txn
        return txn

    # --- root types ------------------------------------------------------------

    def get_text(self, name: str):
        from ytpu.types.text import Text

        return Text(self.store.get_or_create_type(name, TYPE_TEXT))

    def get_array(self, name: str):
        from ytpu.types.array import Array

        return Array(self.store.get_or_create_type(name, TYPE_ARRAY))

    def get_map(self, name: str):
        from ytpu.types.map import Map

        return Map(self.store.get_or_create_type(name, TYPE_MAP))

    def get_xml_fragment(self, name: str):
        from ytpu.types.xml import XmlFragment

        return XmlFragment(self.store.get_or_create_type(name, TYPE_XML_FRAGMENT))

    def get_xml_text(self, name: str):
        from ytpu.types.xml import XmlText

        return XmlText(self.store.get_or_create_type(name, TYPE_XML_TEXT))

    # --- convenience -----------------------------------------------------------

    def apply_update_v1(self, data: bytes, origin=None) -> None:
        with self.transact(origin) as txn:
            txn.apply_update(Update.decode_v1(data))

    def apply_update_v2(self, data: bytes, origin=None) -> None:
        with self.transact(origin) as txn:
            txn.apply_update(Update.decode_v2(data))

    def encode_state_as_update_v1(self, remote_sv: Optional[StateVector] = None) -> bytes:
        return self.store.encode_state_as_update_v1(remote_sv or StateVector())

    def encode_state_as_update_v2(self, remote_sv: Optional[StateVector] = None) -> bytes:
        return self.store.encode_state_as_update_v2(remote_sv or StateVector())

    def state_vector(self) -> StateVector:
        return self.store.blocks.get_state_vector()

    def snapshot(self) -> Snapshot:
        return self.store.snapshot()

    def encode_state_from_snapshot(self, snapshot: Snapshot) -> bytes:
        """Encode the document as it looked at `snapshot` (requires skip_gc)."""
        return self.store.encode_state_from_snapshot(snapshot)

    def to_json(self) -> dict:
        from ytpu.types import wrap_branch

        out = {}
        for name, branch in self.store.types.items():
            out[name] = wrap_branch(branch).to_json()
        return out

    # --- observers -------------------------------------------------------------

    def observe_update_v1(self, cb: Callable) -> Callable[[], None]:
        self.update_v1_subs.append(cb)
        return lambda: self.update_v1_subs.remove(cb)

    def observe_update_v2(self, cb: Callable) -> Callable[[], None]:
        self.update_v2_subs.append(cb)
        return lambda: self.update_v2_subs.remove(cb)

    def observe_after_transaction(self, cb: Callable) -> Callable[[], None]:
        self.after_transaction_subs.append(cb)
        return lambda: self.after_transaction_subs.remove(cb)

    def observe_transaction_cleanup(self, cb: Callable) -> Callable[[], None]:
        self.transaction_cleanup_subs.append(cb)
        return lambda: self.transaction_cleanup_subs.remove(cb)

    def observe_subdocs(self, cb: Callable) -> Callable[[], None]:
        self.subdocs_subs.append(cb)
        return lambda: self.subdocs_subs.remove(cb)

    def observe_destroy(self, cb: Callable) -> Callable[[], None]:
        self.destroy_subs.append(cb)
        return lambda: self.destroy_subs.remove(cb)

    # --- subdoc lifecycle ------------------------------------------------------

    def load(self, parent_txn=None) -> None:
        """Request loading of a sub-document (parity: doc.rs:625-648)."""
        if self.loaded or self.parent_doc is None:
            self.loaded = True
            return
        self.loaded = True
        item = self.parent_item
        if item is not None and not item.deleted:
            self.options.should_load = True
            if parent_txn is not None:
                parent_txn.subdocs_loaded[self.guid] = self

    def destroy(self) -> None:
        if self.destroyed:
            return
        self.destroyed = True
        for cb in self.destroy_subs:
            cb(self)
        self.update_v1_subs.clear()
        self.after_transaction_subs.clear()
        self.transaction_cleanup_subs.clear()
        self.subdocs_subs.clear()
        self.destroy_subs.clear()

    def __repr__(self) -> str:
        return f"Doc(client_id={self.client_id}, guid={self.guid!r})"
