"""The block layer: Item / GC / Skip.

Behavioral parity target: /root/reference/yrs/src/block.rs — `Item` :1088-1133,
flags :967-1071, encode :868-908, `BlockRange` :1137, split semantics
(`splice`) :435-478, squash :775-799, YATA `integrate` :482-769 and `repair`
:1287-1343 (the latter two live in `ytpu.core.store` next to the block store).

Host representation: Python objects with direct left/right references (the
ragged boundary form). The device path re-expresses the same schema as SoA
index arrays — see `ytpu.models.batch_doc` for the column layout.
"""

from __future__ import annotations

from typing import Optional, Union

from .branch import Branch
from .content import (
    BLOCK_GC,
    BLOCK_SKIP,
    Content,
    ContentDeleted,
    ContentString,
    ContentType,
    utf16_len,
)
from .ids import ID

__all__ = ["Item", "GCRange", "SkipRange", "Parent", "UNKNOWN_PARENT"]

HAS_ORIGIN = 0x80
HAS_RIGHT_ORIGIN = 0x40
HAS_PARENT_SUB = 0x20

# Item.parent is one of: Branch (resolved), str (unresolved root name),
# ID (unresolved nested-type anchor), or None (unknown).
Parent = Union[Branch, str, ID, None]
UNKNOWN_PARENT = None


class GCRange:
    """A garbage-collected block range (reference: BlockCell::GC, block.rs:101)."""

    __slots__ = ("id", "len")
    is_item = False
    is_skip = False

    def __init__(self, id_: ID, length: int):
        self.id = id_
        self.len = length

    @property
    def last_id(self) -> ID:
        return ID(self.id.client, self.id.clock + self.len - 1)

    def encode(self, enc, offset: int = 0) -> None:
        enc.write_info(BLOCK_GC)
        enc.write_len(self.len - offset)

    def __repr__(self) -> str:
        return f"GC{self.id}+{self.len}"


class SkipRange:
    """A hole marker inside an update stream (never stored in a doc)."""

    __slots__ = ("id", "len")
    is_item = False
    is_skip = True

    def __init__(self, id_: ID, length: int):
        self.id = id_
        self.len = length

    def encode(self, enc, offset: int = 0) -> None:
        enc.write_info(BLOCK_SKIP)
        # skip lengths ride the main stream, not the len column (update.rs:437)
        enc.write_var(self.len - offset)

    def __repr__(self) -> str:
        return f"Skip{self.id}+{self.len}"


class Item:
    __slots__ = (
        "id",
        "len",
        "left",
        "right",
        "origin",
        "right_origin",
        "parent",
        "parent_sub",
        "content",
        "deleted",
        "keep",
        "moved",
        "redone",
        "linked",
    )
    is_item = True
    is_skip = False

    def __init__(
        self,
        id_: ID,
        left: Optional["Item"],
        origin: Optional[ID],
        right: Optional["Item"],
        right_origin: Optional[ID],
        parent: Parent,
        parent_sub: Optional[str],
        content: Content,
    ):
        self.id = id_
        self.len = content.length()
        self.left = left
        self.right = right
        self.origin = origin
        self.right_origin = right_origin
        self.parent = parent
        self.parent_sub = parent_sub
        self.content = content
        self.deleted = False
        self.keep = False
        self.moved: Optional["Item"] = None
        self.redone: Optional[ID] = None
        self.linked = False
        if isinstance(content, ContentType):
            content.branch.item = self
            if content.branch.name is None and isinstance(parent, str):
                content.branch.name = parent

    @property
    def countable(self) -> bool:
        return self.content.countable

    @property
    def last_id(self) -> ID:
        return ID(self.id.client, self.id.clock + self.len - 1)

    def contains(self, id_: ID) -> bool:
        return (
            self.id.client == id_.client
            and self.id.clock <= id_.clock < self.id.clock + self.len
        )

    def mark_deleted(self) -> None:
        self.deleted = True

    def visible_len(self) -> int:
        return 0 if self.deleted or not self.countable else self.len

    # --- wire (v1) ---

    def encode(self, enc, offset: int = 0) -> None:
        """Encode, optionally skipping the first `offset` clock units.

        Parity: block.rs:868-908 (plain) and the partial-block slice encode
        at slice.rs:101-199; with offset > 0 the origin is rewritten to point
        at the preceding unit of this same block.
        """
        origin = (
            ID(self.id.client, self.id.clock + offset - 1) if offset > 0 else self.origin
        )
        info = (
            self.content.kind
            | (HAS_ORIGIN if origin is not None else 0)
            | (HAS_RIGHT_ORIGIN if self.right_origin is not None else 0)
            | (HAS_PARENT_SUB if self.parent_sub is not None else 0)
        )
        enc.write_info(info)
        if origin is not None:
            enc.write_left_id(origin)
        if self.right_origin is not None:
            enc.write_right_id(self.right_origin)
        if origin is None and self.right_origin is None:
            parent = self.parent
            if isinstance(parent, Branch):
                if parent.item is not None:
                    enc.write_parent_info(False)
                    enc.write_left_id(parent.item.id)
                else:
                    enc.write_parent_info(True)
                    enc.write_string(parent.name or "")
            elif isinstance(parent, ID):
                enc.write_parent_info(False)
                enc.write_left_id(parent)
            elif isinstance(parent, str):
                enc.write_parent_info(True)
                enc.write_string(parent)
            else:
                raise ValueError(f"cannot encode item {self.id}: unknown parent")
            if self.parent_sub is not None:
                enc.write_string(self.parent_sub)
        if offset > 0:
            head = self.content.copy()
            tail = head.splice(offset)  # splice keeps the head, returns the tail
            tail.encode(enc)
        else:
            self.content.encode(enc)

    # --- splitting & squashing ---

    def split(self, offset: int) -> "Item":
        """Split at `offset` clock units; returns the new right item.

        Caller is responsible for inserting the new item into the client block
        list and (if needed) parent map. Parity: splitItem semantics
        (reference: block_store.rs:456, store.rs:284-331).
        """
        right_content = self.content.splice(offset)
        right = Item(
            ID(self.id.client, self.id.clock + offset),
            self,
            ID(self.id.client, self.id.clock + offset - 1),
            self.right,
            self.right_origin,
            self.parent,
            self.parent_sub,
            right_content,
        )
        right.len = self.len - offset
        if self.deleted:
            right.deleted = True
        if self.keep:
            right.keep = True
        if self.moved is not None:
            right.moved = self.moved
        if self.redone is not None:
            right.redone = ID(self.redone.client, self.redone.clock + offset)
        self.len = offset
        if self.right is not None:
            self.right.left = right
        self.right = right
        return right

    def try_squash(self, other: "Item") -> bool:
        """Merge `other` (immediate right neighbor block) into self if compatible.

        Parity: block.rs:775-799.
        """
        if (
            self.id.client == other.id.client
            and self.id.clock + self.len == other.id.clock
            and other.origin == self.last_id
            and self.right_origin == other.right_origin
            and self.right is other
            and self.deleted == other.deleted
            and self.redone is None
            and other.redone is None
            and self.moved is other.moved
            and not self.linked
            and not other.linked
            and type(self.content) is type(other.content)
            and self.content.merge(other.content)
        ):
            if other.keep:
                self.keep = True
            self.right = other.right
            if self.right is not None:
                self.right.left = self
            self.len += other.len
            return True
        return False

    def __repr__(self) -> str:
        flags = "D" if self.deleted else ""
        return f"Item{self.id}+{self.len}{flags}:{self.content!r}"
