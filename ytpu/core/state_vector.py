"""State vectors — per-client version clocks used for delta sync.

Behavioral parity target: /root/reference/yrs/src/state_vector.rs:19-154.
A state vector maps ``client -> next expected clock`` (i.e. number of
operations observed from that client). Diff sync sends a state vector
(SyncStep1) and receives blocks above those clocks (SyncStep2).

TPU mapping: a batch of state vectors is a dense ``[n_docs, n_clients]`` i32
tensor over a client dictionary; merge = elementwise max, comparison =
elementwise less-than (see `ytpu.ops.state_vector`). This host class is the
ragged boundary representation.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional, Tuple

from ytpu.encoding.lib0 import Cursor, Writer

from .ids import ID, ClientID

__all__ = ["StateVector", "Snapshot"]


class StateVector:
    __slots__ = ("clocks",)

    def __init__(self, clocks: Optional[Dict[ClientID, int]] = None):
        self.clocks: Dict[ClientID, int] = dict(clocks) if clocks else {}

    def get(self, client: ClientID) -> int:
        return self.clocks.get(client, 0)

    def set_min(self, client: ClientID, clock: int) -> None:
        if client in self.clocks:
            self.clocks[client] = min(self.clocks[client], clock)
        else:
            self.clocks[client] = clock

    def set_max(self, client: ClientID, clock: int) -> None:
        if clock > self.clocks.get(client, 0):
            self.clocks[client] = clock

    def inc_by(self, client: ClientID, delta: int) -> None:
        if delta:
            self.clocks[client] = self.clocks.get(client, 0) + delta

    def contains(self, id_: ID) -> bool:
        """True if a block starting at `id_` can be applied without a gap
        (parity: state_vector.rs — `id.clock <= get(client)`)."""
        return id_.clock <= self.get(id_.client)

    def contains_all(self, other: "StateVector") -> bool:
        return all(self.get(c) >= k for c, k in other.clocks.items())

    def merge(self, other: "StateVector") -> None:
        for client, clock in other.clocks.items():
            self.set_max(client, clock)

    def copy(self) -> "StateVector":
        return StateVector(self.clocks)

    def __iter__(self) -> Iterator[Tuple[ClientID, int]]:
        return iter(self.clocks.items())

    def __len__(self) -> int:
        return len(self.clocks)

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, StateVector):
            return NotImplemented
        a = {c: k for c, k in self.clocks.items() if k}
        b = {c: k for c, k in other.clocks.items() if k}
        return a == b

    def __repr__(self) -> str:
        inner = ", ".join(f"{c}:{k}" for c, k in sorted(self.clocks.items()))
        return f"StateVector({{{inner}}})"

    # --- wire format (v1) ---

    def encode(self, w: Optional[Writer] = None) -> Writer:
        w = w if w is not None else Writer()
        entries = [(c, k) for c, k in self.clocks.items() if k > 0]
        # Deterministic order: higher clients first, mirroring update encoding
        # conventions (reference sorts updates by descending client id).
        entries.sort(key=lambda e: -e[0])
        w.write_var_uint(len(entries))
        for client, clock in entries:
            w.write_var_uint(client)
            w.write_var_uint(clock)
        return w

    def encode_v1(self) -> bytes:
        return self.encode().to_bytes()

    @classmethod
    def decode(cls, cur: Cursor) -> "StateVector":
        n = cur.read_var_uint()
        clocks: Dict[ClientID, int] = {}
        for _ in range(n):
            client = cur.read_var_uint()
            clock = cur.read_var_uint()
            if clock:
                clocks[client] = max(clocks.get(client, 0), clock)
        return cls(clocks)

    @classmethod
    def decode_v1(cls, data: bytes) -> "StateVector":
        return cls.decode(Cursor(data))


class Snapshot:
    """A point-in-time document version: state vector + accumulated deletions.

    Parity: /root/reference/yrs/src/state_vector.rs:135-154.
    """

    __slots__ = ("state_vector", "delete_set")

    def __init__(self, state_vector: StateVector, delete_set) -> None:
        self.state_vector = state_vector
        self.delete_set = delete_set

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Snapshot):
            return NotImplemented
        return (
            self.state_vector == other.state_vector
            and self.delete_set == other.delete_set
        )

    def encode_v1(self) -> bytes:
        from ytpu.encoding.codec import EncoderV1

        enc = EncoderV1()
        self.delete_set.encode(enc)
        self.state_vector.encode(enc.w)
        return enc.to_bytes()

    @classmethod
    def decode_v1(cls, data: bytes) -> "Snapshot":
        from ytpu.encoding.codec import DecoderV1

        from .id_set import DeleteSet

        dec = DecoderV1(data)
        ds = DeleteSet.decode(dec)
        sv = StateVector.decode(dec.cur)
        return cls(sv, ds)

    def encode_v2(self) -> bytes:
        """Same layout through the v2 columnar codec (parity:
        Snapshot::encode_v2, state_vector.rs)."""
        from ytpu.encoding.codec import EncoderV2

        enc = EncoderV2()
        self.delete_set.encode(enc)
        self.state_vector.encode(enc.rest)
        return enc.to_bytes()

    @classmethod
    def decode_v2(cls, data: bytes) -> "Snapshot":
        from ytpu.encoding.codec import DecoderV2

        from .id_set import DeleteSet

        dec = DecoderV2(data)
        ds = DeleteSet.decode(dec)
        sv = StateVector.decode(dec.rest)
        return cls(sv, ds)
