"""BlockStore — per-client sorted block lists.

Behavioral parity target: /root/reference/yrs/src/block_store.rs
(`ClientBlockList` + interpolation-seeded `find_pivot` :70-96, `BlockStore`
:300-475, `split_block` :456, clean-start/clean-end :402-417, `squash_left`
:243). Blocks for one client are stored sorted by clock and are contiguous
(no gaps) — so `find_pivot` can seed a binary search with the interpolated
index `clock * n_blocks / client_clock`.

Device mapping: per-doc block tensors sorted by (client, clock);
`find_pivot` becomes `jnp.searchsorted` over the clock column.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Union

from .block import GCRange, Item
from .ids import ID, ClientID
from .state_vector import StateVector

__all__ = ["ClientBlockList", "BlockStore"]

Block = Union[Item, GCRange]


class ClientBlockList:
    __slots__ = ("blocks",)

    def __init__(self):
        self.blocks: List[Block] = []

    def __len__(self) -> int:
        return len(self.blocks)

    def __getitem__(self, i: int) -> Block:
        return self.blocks[i]

    def __iter__(self) -> Iterator[Block]:
        return iter(self.blocks)

    def clock(self) -> int:
        """Next expected clock = end of the last block."""
        if not self.blocks:
            return 0
        last = self.blocks[-1]
        return last.id.clock + last.len

    def find_pivot(self, clock: int) -> Optional[int]:
        """Index of the block whose clock range covers `clock`.

        Interpolation-seeded binary search (parity: block_store.rs:70-96).
        """
        blocks = self.blocks
        if not blocks:
            return None
        left = 0
        right = len(blocks) - 1
        last = blocks[right]
        total = last.id.clock + last.len
        if clock >= total:
            return None
        # interpolation seed — exact when blocks are uniform length-1 runs
        mid = min((clock * len(blocks)) // total, right)
        while left <= right:
            b = blocks[mid]
            start = b.id.clock
            if start <= clock:
                if clock < start + b.len:
                    return mid
                left = mid + 1
            else:
                right = mid - 1
            mid = (left + right) // 2
        return None

    def insert_at(self, index: int, block: Block) -> None:
        self.blocks.insert(index, block)

    def push(self, block: Block) -> None:
        self.blocks.append(block)

    def squash_left(self, index: int) -> bool:
        """Try to merge blocks[index] into blocks[index-1].

        Parity: block_store.rs:243 + the map fixup from the Yjs algorithm
        (if the squashed right block was a map entry, repoint the entry).
        """
        if index <= 0 or index >= len(self.blocks):
            return False
        left = self.blocks[index - 1]
        right = self.blocks[index]
        if not (left.is_item and right.is_item):
            return False
        if left.try_squash(right):
            from .branch import Branch

            if right.parent_sub is not None and isinstance(right.parent, Branch):
                if right.parent.map.get(right.parent_sub) is right:
                    right.parent.map[right.parent_sub] = left
            del self.blocks[index]
            return True
        return False


class BlockStore:
    __slots__ = ("clients",)

    def __init__(self):
        self.clients: Dict[ClientID, ClientBlockList] = {}

    def get_client(self, client: ClientID) -> Optional[ClientBlockList]:
        return self.clients.get(client)

    def get_client_or_create(self, client: ClientID) -> ClientBlockList:
        lst = self.clients.get(client)
        if lst is None:
            lst = ClientBlockList()
            self.clients[client] = lst
        return lst

    def get_clock(self, client: ClientID) -> int:
        lst = self.clients.get(client)
        return lst.clock() if lst else 0

    def get_state_vector(self) -> StateVector:
        return StateVector({c: lst.clock() for c, lst in self.clients.items() if len(lst)})

    def push_block(self, block: Block) -> None:
        self.get_client_or_create(block.id.client).push(block)

    def get_block(self, id_: ID) -> Optional[Block]:
        lst = self.clients.get(id_.client)
        if lst is None:
            return None
        idx = lst.find_pivot(id_.clock)
        if idx is None:
            return None
        return lst[idx]

    def get_item(self, id_: ID) -> Optional[Item]:
        b = self.get_block(id_)
        return b if isinstance(b, Item) else None

    def split_at(self, item: Item, offset: int) -> Item:
        """Physically split `item` at `offset`, registering the right half."""
        right = item.split(offset)
        lst = self.clients[item.id.client]
        idx = lst.find_pivot(item.id.clock)
        # right half sits immediately after the left half
        lst.insert_at(idx + 1, right)
        return right

    def get_item_clean_start(self, id_: ID) -> Optional[Item]:
        """Item starting exactly at `id_` (splitting a covering block if needed).

        Parity: block_store.rs:402-417 + store.rs:284-331 (materialize).
        """
        item = self.get_item(id_)
        if item is None:
            return None
        if item.id.clock == id_.clock:
            return item
        return self.split_at(item, id_.clock - item.id.clock)

    def get_item_clean_end(self, id_: ID) -> Optional[Item]:
        """Item ending exactly at `id_` (splitting a covering block if needed)."""
        item = self.get_item(id_)
        if item is None:
            return None
        if id_.clock == item.id.clock + item.len - 1:
            return item
        self.split_at(item, id_.clock - item.id.clock + 1)
        return item

    def __iter__(self) -> Iterator:
        return iter(self.clients.items())

    def __repr__(self) -> str:
        lines = []
        for client, lst in sorted(self.clients.items()):
            lines.append(f"  {client}: " + " ".join(repr(b) for b in lst))
        return "BlockStore{\n" + "\n".join(lines) + "\n}"
