"""Clock-range sets: IdSet / DeleteSet.

Behavioral parity target: /root/reference/yrs/src/id_set.rs (IdRange :36-248,
IdSet :324-439, DeleteSet :440-652). An IdSet maps each client to a set of
half-open clock ranges ``[start, end)``; a DeleteSet is the IdSet of tombstoned
blocks carried by every update and snapshot.

Representation here: ``client -> list[(start, end)]`` kept squash-lazy like
the reference (ranges are sorted+merged on demand). On device, a batch of
delete sets becomes a ragged ``[n_docs, n_ranges, 3]`` (client, start, end)
tensor; interval membership is a searchsorted over the flattened ranges
(see `ytpu.ops.delete_set`).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

from ytpu.encoding.lib0 import Cursor, Writer

from .ids import ID, ClientID

__all__ = ["IdSet", "DeleteSet"]

Range = Tuple[int, int]  # half-open [start, end)


def _squash_ranges(ranges: List[Range]) -> List[Range]:
    """Sort and merge overlapping/adjacent ranges."""
    if len(ranges) <= 1:
        return ranges
    ranges = sorted(ranges)
    out = [ranges[0]]
    for start, end in ranges[1:]:
        last_start, last_end = out[-1]
        if start <= last_end:  # overlap or adjacency joins
            if end > last_end:
                out[-1] = (last_start, end)
        else:
            out.append((start, end))
    return out


class IdSet:
    __slots__ = ("clients",)

    def __init__(self, clients: Optional[Dict[ClientID, List[Range]]] = None):
        self.clients: Dict[ClientID, List[Range]] = clients if clients is not None else {}

    def is_empty(self) -> bool:
        return all(not rs for rs in self.clients.values())

    def insert(self, id_: ID, length: int) -> None:
        if length <= 0:
            return
        self.clients.setdefault(id_.client, []).append((id_.clock, id_.clock + length))

    def insert_range(self, client: ClientID, start: int, end: int) -> None:
        if end > start:
            self.clients.setdefault(client, []).append((start, end))

    def squash(self) -> None:
        for client in list(self.clients):
            rs = _squash_ranges(self.clients[client])
            if rs:
                self.clients[client] = rs
            else:
                del self.clients[client]

    def contains(self, id_: ID) -> bool:
        rs = self.clients.get(id_.client)
        if not rs:
            return False
        return any(start <= id_.clock < end for start, end in rs)

    def ranges(self, client: ClientID) -> List[Range]:
        return _squash_ranges(self.clients.get(client, []))

    def merge(self, other: "IdSet") -> None:
        for client, rs in other.clients.items():
            self.clients.setdefault(client, []).extend(rs)
        self.squash()

    def invert(self) -> "IdSet":
        """Ranges *not* covered, from clock 0 up to each client's max covered clock."""
        out = IdSet()
        for client, rs in self.clients.items():
            rs = _squash_ranges(rs)
            prev = 0
            holes: List[Range] = []
            for start, end in rs:
                if start > prev:
                    holes.append((prev, start))
                prev = end
            if holes:
                out.clients[client] = holes
        return out

    def copy(self) -> "IdSet":
        return IdSet({c: list(rs) for c, rs in self.clients.items()})

    def __iter__(self) -> Iterator[Tuple[ClientID, List[Range]]]:
        return iter(self.clients.items())

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, IdSet):
            return NotImplemented
        a = {c: _squash_ranges(rs) for c, rs in self.clients.items() if rs}
        b = {c: _squash_ranges(rs) for c, rs in other.clients.items() if rs}
        return a == b

    def __repr__(self) -> str:
        parts = []
        for client, rs in sorted(self.clients.items()):
            rr = ",".join(f"[{s}..{e})" for s, e in _squash_ranges(rs))
            parts.append(f"{client}:{rr}")
        return f"{type(self).__name__}({'; '.join(parts)})"

    # --- wire format: clients count, then per client: id, range count,
    # (clock, len) pairs (v2 delta-encodes clocks via the ds channel) ---

    def encode(self, enc) -> None:
        entries = [(c, _squash_ranges(rs)) for c, rs in self.clients.items() if rs]
        entries.sort(key=lambda e: -e[0])
        enc.write_var(len(entries))
        for client, rs in entries:
            enc.reset_ds_cur_val()
            enc.write_var(client)
            enc.write_var(len(rs))
            for start, end in rs:
                enc.write_ds_clock(start)
                enc.write_ds_len(end - start)

    def encode_v1(self) -> bytes:
        from ytpu.encoding.codec import EncoderV1

        enc = EncoderV1()
        self.encode(enc)
        return enc.to_bytes()

    @classmethod
    def decode(cls, dec) -> "IdSet":
        n_clients = dec.read_var()
        out = cls()
        for _ in range(n_clients):
            dec.reset_ds_cur_val()
            client = dec.read_var()
            n_ranges = dec.read_var()
            rs = out.clients.setdefault(client, [])
            for _ in range(n_ranges):
                clock = dec.read_ds_clock()
                length = dec.read_ds_len()
                if length:
                    rs.append((clock, clock + length))
        return out

    @classmethod
    def decode_v1(cls, data: bytes) -> "IdSet":
        from ytpu.encoding.codec import DecoderV1

        return cls.decode(DecoderV1(data))


class DeleteSet(IdSet):
    """IdSet of deleted block ranges (reference: id_set.rs:440)."""

    __slots__ = ()

    @classmethod
    def from_id_set(cls, ids: IdSet) -> "DeleteSet":
        return cls({c: list(rs) for c, rs in ids.clients.items()})
