"""Update — a decoded-but-not-integrated set of foreign blocks.

Behavioral parity target: /root/reference/yrs/src/update.rs (`Update` :91,
lazy decode :433-488, `integrate` stack machine :169-308, `missing` :310-385,
`merge_updates` :537-704, `encode_diff` :490-535) and the doc-less utilities
in alt.rs:15-95.

An update carries, per client, a clock-contiguous run of block carriers
(Item / GC / Skip) plus a delete set. Integration applies blocks in causal
waves: a block whose origin/right-origin/parent clocks aren't locally known
is stashed (with the rest of its client queue) into a pending update.

Device mapping: `decode_update` is the host half of the ingestion pipeline —
its output columns feed `ytpu.models.batch_doc.UpdateBatch`; the wave
scheduling mirrors the device kernel's dependency-satisfied wave loop.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Optional, Tuple, Union

from ytpu.encoding.codec import DecoderV1, DecoderV2, EncoderV1, EncoderV2
from ytpu.encoding.lib0 import Cursor, Writer

from .block import GCRange, Item, SkipRange
from .branch import Branch
from .content import BLOCK_GC, BLOCK_SKIP, decode_content
from .id_set import DeleteSet
from .ids import ID, ClientID
from .moving import Move
from .state_vector import StateVector

__all__ = [
    "Update",
    "PendingUpdate",
    "decode_update_v1",
    "merge_updates_v1",
    "merge_updates_v2",
    "encode_state_vector_from_update_v1",
    "encode_state_vector_from_update_v2",
    "diff_updates_v1",
    "diff_updates_v2",
]

Carrier = Union[Item, GCRange, SkipRange]

HAS_ORIGIN = 0x80
HAS_RIGHT_ORIGIN = 0x40
HAS_PARENT_SUB = 0x20


class PendingUpdate:
    """Blocks that couldn't be integrated + the clocks they're waiting for.

    Parity: update.rs:289-299, store.rs:42-50.
    """

    __slots__ = ("update", "missing")

    def __init__(self, update: "Update", missing: StateVector):
        self.update = update
        self.missing = missing


class Update:
    __slots__ = ("blocks", "delete_set")

    def __init__(
        self,
        blocks: Optional[Dict[ClientID, Deque[Carrier]]] = None,
        delete_set: Optional[DeleteSet] = None,
    ):
        self.blocks: Dict[ClientID, Deque[Carrier]] = blocks if blocks is not None else {}
        self.delete_set = delete_set if delete_set is not None else DeleteSet()

    def is_empty(self) -> bool:
        return not self.blocks and self.delete_set.is_empty()

    def state_vector(self) -> StateVector:
        """Highest contiguous clock per client described by this update."""
        sv = StateVector()
        for client, blocks in self.blocks.items():
            if blocks:
                last = blocks[-1]
                sv.set_max(client, last.id.clock + last.len)
        return sv

    # --- decoding ---

    @classmethod
    def decode(cls, dec) -> "Update":
        n_clients = dec.read_var()
        blocks: Dict[ClientID, Deque[Carrier]] = {}
        for _ in range(n_clients):
            n_blocks = dec.read_var()
            client = dec.read_client()
            clock = dec.read_var()
            dq = blocks.setdefault(client, deque())
            for _ in range(n_blocks):
                carrier = _decode_block(ID(client, clock), dec)
                if carrier is not None:
                    clock += carrier.len
                    dq.append(carrier)
        delete_set = DeleteSet.decode(dec)
        return cls(blocks, delete_set)

    @classmethod
    def decode_v1(cls, data: bytes) -> "Update":
        return cls.decode(DecoderV1(data))

    @classmethod
    def decode_v2(cls, data: bytes) -> "Update":
        return cls.decode(DecoderV2(data))

    # --- encoding ---

    def encode(self, enc) -> None:
        self.encode_diff(StateVector(), enc)

    def encode_v1(self) -> bytes:
        enc = EncoderV1()
        self.encode(enc)
        return enc.to_bytes()

    def encode_v2(self) -> bytes:
        enc = EncoderV2()
        self.encode(enc)
        return enc.to_bytes()

    def encode_diff(self, remote_sv: StateVector, enc) -> None:
        """Encode only what `remote_sv` is missing (parity: update.rs:490-535)."""
        per_client: List[Tuple[ClientID, int, List[Carrier]]] = []
        for client, blocks in self.blocks.items():
            remote_clock = remote_sv.get(client)
            out: List[Carrier] = []
            offset = 0
            it = iter(blocks)
            for block in it:
                if block.is_skip:
                    continue
                if block.id.clock + block.len > remote_clock:
                    offset = max(0, remote_clock - block.id.clock)
                    out.append(block)
                    out.extend(it)  # everything after the first match
                    break
            if out:
                per_client.append((client, offset, out))
        per_client.sort(key=lambda e: -e[0])  # higher clients first
        enc.write_var(len(per_client))
        for client, offset, out in per_client:
            enc.write_var(len(out))
            enc.write_client(client)
            enc.write_var(out[0].id.clock + offset)
            out[0].encode(enc, offset)
            for block in out[1:]:
                block.encode(enc, 0)
        self.delete_set.encode(enc)

    def encode_diff_v1(self, remote_sv: StateVector) -> bytes:
        enc = EncoderV1()
        self.encode_diff(remote_sv, enc)
        return enc.to_bytes()

    # --- integration driver (parity: update.rs:169-308) ---

    def integrate(self, txn) -> Tuple[Optional[PendingUpdate], Optional[DeleteSet]]:
        """Integrate this update into the doc behind `txn`.

        Returns (pending blocks or None, unapplied delete-set or None).
        """
        store = txn.store
        pending: Optional[PendingUpdate] = None
        if self.blocks:
            client_ids = sorted(self.blocks.keys())  # popped from the end: descending
            current_client = client_ids.pop()
            current_target: Optional[Deque[Carrier]] = self.blocks.get(current_client)
            stack_head: Optional[Carrier] = (
                current_target.popleft() if current_target else None
            )
            local_sv = store.blocks.get_state_vector()
            missing_sv = StateVector()
            remaining: Dict[ClientID, Deque[Carrier]] = {}
            stack: List[Carrier] = []

            while stack_head is not None:
                block = stack_head
                if not block.is_skip:
                    id_ = block.id
                    local_clock = local_sv.get(id_.client)
                    if local_clock >= id_.clock:
                        offset = local_clock - id_.clock
                        dep = _missing_dep(block, local_sv)
                        if dep is not None:
                            stack.append(block)
                            dep_queue = self.blocks.get(dep)
                            if dep_queue:
                                # dependency may be satisfied later in this update
                                stack_head = dep_queue.popleft()
                                current_target = self.blocks.get(current_client)
                                continue
                            # causally depends on updates we don't have
                            missing_sv.set_min(dep, local_sv.get(dep))
                            _return_stack(stack, self.blocks, remaining)
                            current_target = self.blocks.get(current_client)
                            stack = []
                        elif offset == 0 or offset < block.len:
                            local_sv.set_max(id_.client, id_.clock + block.len)
                            if block.is_item:
                                store.repair(block)
                            should_delete = store.integrate_block(txn, block, offset)
                            delete_ptr = block if (should_delete and block.is_item) else None
                            if block.is_item:
                                if block.parent is not None:
                                    store.blocks.push_block(block)
                                else:
                                    # unresolvable parent: degrade to GC range
                                    store.blocks.push_block(GCRange(block.id, block.len))
                                    delete_ptr = None
                            elif isinstance(block, GCRange):
                                store.blocks.push_block(block)
                            if delete_ptr is not None:
                                txn.delete(delete_ptr)
                    else:
                        # gap in this client's own sequence
                        missing_sv.set_min(id_.client, id_.clock - 1)
                        stack.append(block)
                        _return_stack(stack, self.blocks, remaining)
                        current_target = self.blocks.get(current_client)
                        stack = []

                # pick next head
                if stack:
                    stack_head = stack.pop()
                elif current_target:
                    stack_head = current_target.popleft()
                else:
                    stack_head = None
                    while client_ids:
                        cid = client_ids.pop()
                        dq = self.blocks.get(cid)
                        if dq:
                            current_client = cid
                            current_target = dq
                            stack_head = dq.popleft()
                            break

            if any(remaining.values()):
                pending = PendingUpdate(Update(remaining), missing_sv)

        remaining_ds = txn.apply_delete(self.delete_set)
        return pending, remaining_ds

    # --- merge (parity: update.rs:537-704, fresh algorithm) ---

    @classmethod
    def merge(cls, updates: List["Update"]) -> "Update":
        """Merge updates into one, synthesizing Skip markers over gaps.

        Fresh design (not the reference's k-way lazy merge): per client,
        carriers are sorted by clock; overlaps are resolved by preferring the
        carrier that extends furthest (splitting off already-covered
        prefixes), and clock gaps become explicit Skip carriers so the result
        remains a valid contiguous run.
        """
        all_blocks: Dict[ClientID, List[Carrier]] = {}
        delete_set = DeleteSet()
        for u in updates:
            for client, dq in u.blocks.items():
                all_blocks.setdefault(client, []).extend(dq)
            delete_set.merge(u.delete_set)

        merged: Dict[ClientID, Deque[Carrier]] = {}
        for client, carriers in all_blocks.items():
            # stable order: by clock; prefer Items over Skips on ties
            carriers.sort(key=lambda c: (c.id.clock, c.is_skip))
            out: Deque[Carrier] = deque()
            current_end: Optional[int] = None  # clock after last emitted carrier
            for c in carriers:
                start, length = c.id.clock, c.len
                if current_end is None:
                    out.append(c)
                    current_end = start + length
                    continue
                if start >= current_end:
                    if start > current_end:
                        # hole: synthesize a skip
                        out.append(
                            SkipRange(ID(client, current_end), start - current_end)
                        )
                    # contiguous (or after the skip): emit the carrier whole —
                    # splitting at offset 0 would rewrite its origin to
                    # (client, clock-1), which only coincides with the true
                    # origin for append-only streams
                    out.append(c)
                    current_end = start + length
                elif start + length <= current_end:
                    continue  # fully covered
                else:
                    # partial overlap: emit only the uncovered suffix
                    overlap = current_end - start  # > 0 here
                    if c.is_skip:
                        out.append(SkipRange(ID(client, current_end), length - overlap))
                    elif isinstance(c, GCRange):
                        out.append(GCRange(ID(client, current_end), length - overlap))
                    else:
                        # split a detached clone — merge() must never mutate
                        # its input updates (their carriers stay re-encodable)
                        clone = Item(
                            c.id, None, c.origin, None, c.right_origin,
                            c.parent, c.parent_sub, c.content.copy(),
                        )
                        clone.deleted = c.deleted
                        clone.keep = c.keep
                        clone.moved = c.moved
                        clone.redone = c.redone
                        right = clone.split(overlap)
                        right.left = None
                        out.append(right)
                    current_end = start + length
            # drop trailing skips: they carry no information
            while out and out[-1].is_skip:
                out.pop()
            if out:
                merged[client] = out
        return cls(merged, delete_set)


# --- block decode helper -------------------------------------------------------


def _decode_branch(dec) -> Branch:
    return Branch.decode_type_ref(dec)


def _decode_doc(dec):
    from .doc import Doc, Options

    opts = Options.decode(dec)
    return Doc(options=opts)


def _decode_block(id_: ID, dec) -> Optional[Carrier]:
    """Parity: update.rs:433-488."""
    info = dec.read_info()
    if info == BLOCK_SKIP:
        return SkipRange(id_, dec.read_var())
    if info == BLOCK_GC:
        return GCRange(id_, dec.read_len())
    cant_copy_parent = info & (HAS_ORIGIN | HAS_RIGHT_ORIGIN) == 0
    origin = None
    right_origin = None
    if info & HAS_ORIGIN:
        origin = ID(*dec.read_left_id())
    if info & HAS_RIGHT_ORIGIN:
        right_origin = ID(*dec.read_right_id())
    parent = None
    parent_sub = None
    if cant_copy_parent:
        if dec.read_parent_info():
            parent = dec.read_string()
        else:
            parent = ID(*dec.read_left_id())
        if info & HAS_PARENT_SUB:
            parent_sub = dec.read_string()
    content = decode_content(dec, info, _decode_branch, _decode_doc, Move.decode)
    if content.length() == 0:
        return None  # historical empty blocks have no effect
    return Item(id_, None, origin, None, right_origin, parent, parent_sub, content)


def _missing_dep(block: Carrier, local_sv: StateVector) -> Optional[ClientID]:
    """First unmet causal dependency of `block` (parity: update.rs:310-385)."""
    if not block.is_item:
        return None
    item: Item = block
    origin = item.origin
    if origin is not None and origin.client != item.id.client:
        if origin.clock >= local_sv.get(origin.client):
            return origin.client
    right_origin = item.right_origin
    if right_origin is not None and right_origin.client != item.id.client:
        if right_origin.clock >= local_sv.get(right_origin.client):
            return right_origin.client
    parent = item.parent
    if isinstance(parent, Branch):
        anchor = parent.item
        if anchor is not None and anchor.id.client != item.id.client:
            if anchor.id.clock >= local_sv.get(anchor.id.client):
                return anchor.id.client
    elif isinstance(parent, ID):
        if parent.client != item.id.client and parent.clock >= local_sv.get(parent.client):
            return parent.client
    content = item.content
    from .content import ContentMove, ContentType

    if isinstance(content, ContentMove):
        m = content.move
        start = m.start.id
        if start is not None and start.clock >= local_sv.get(start.client):
            return start.client
        if not m.is_collapsed():
            end = m.end.id
            if end is not None and end.clock >= local_sv.get(end.client):
                return end.client
    elif isinstance(content, ContentType):
        src = content.branch.link_source
        if src is not None:
            start = src.quote_start.id
            end = src.quote_end.id
            if start is not None and start.clock >= local_sv.get(start.client):
                return start.client
            if start != end and end is not None and end.clock >= local_sv.get(end.client):
                return end.client
    return None


def _return_stack(
    stack: List[Carrier],
    refs: Dict[ClientID, Deque[Carrier]],
    remaining: Dict[ClientID, Deque[Carrier]],
) -> None:
    """Move stacked carriers (plus the rest of their client queues) aside.

    Parity: update.rs:411-431 (with the same-client collision handled by
    appending instead of overwriting).
    """
    for item in stack:
        client = item.id.client
        rest = refs.pop(client, None)
        if rest is not None:
            rest.appendleft(item)
            if client in remaining:
                remaining[client].extend(rest)
            else:
                remaining[client] = rest
        else:
            if client in remaining:
                remaining[client].appendleft(item)
            else:
                remaining[client] = deque([item])
    stack.clear()


# --- doc-less binary utilities (parity: alt.rs:15-95) -------------------------


def decode_update_v1(data: bytes) -> Update:
    return Update.decode_v1(data)


def merge_updates_v1(updates: List[bytes]) -> bytes:
    return Update.merge([Update.decode_v1(u) for u in updates]).encode_v1()


def merge_updates_v2(updates: List[bytes]) -> bytes:
    return Update.merge([Update.decode_v2(u) for u in updates]).encode_v2()


def encode_state_vector_from_update_v1(update: bytes) -> bytes:
    return Update.decode_v1(update).state_vector().encode_v1()


def encode_state_vector_from_update_v2(update: bytes) -> bytes:
    return Update.decode_v2(update).state_vector().encode_v1()


def diff_updates_v1(update: bytes, state_vector: bytes) -> bytes:
    sv = StateVector.decode_v1(state_vector)
    return Update.decode_v1(update).encode_diff_v1(sv)


def diff_updates_v2(update: bytes, state_vector: bytes) -> bytes:
    sv = StateVector.decode_v1(state_vector)
    u = Update.decode_v2(update)
    enc = EncoderV2()
    u.encode_diff(sv, enc)
    return enc.to_bytes()
