"""Move ranges and sticky indices.

Behavioral parity target: /root/reference/yrs/src/moving.rs (Move :16,
StickyIndex :403, Assoc :723). Round-1 scope: full wire format + data model;
`Move.integrate_block` / move-aware iteration land with the move/undo service
layer. Sticky indices resolve through `ytpu.core.store.DocStore`.
"""

from __future__ import annotations

from typing import Optional

from ytpu.encoding.lib0 import Cursor, Writer

from .ids import ID

__all__ = ["ASSOC_BEFORE", "ASSOC_AFTER", "StickyIndex", "Move"]

ASSOC_BEFORE = -1
ASSOC_AFTER = 0


class StickyIndex:
    """A position that sticks to its neighborhood across concurrent edits.

    Scope is either an item ID (relative), or a root-type name / branch id
    (start or end of a sequence).
    """

    __slots__ = ("id", "name", "branch_id", "assoc")

    def __init__(
        self,
        id_: Optional[ID] = None,
        name: Optional[str] = None,
        branch_id: Optional[ID] = None,
        assoc: int = ASSOC_AFTER,
    ):
        self.id = id_
        self.name = name
        self.branch_id = branch_id
        self.assoc = assoc

    @classmethod
    def from_id(cls, id_: ID, assoc: int) -> "StickyIndex":
        return cls(id_=id_, assoc=assoc)

    @classmethod
    def from_type_index(cls, branch, index: int, assoc: int = ASSOC_AFTER) -> "StickyIndex":
        """Sticky position at `index` of a sequence (parity: moving.rs:809 /
        IndexedSequence::sticky_index)."""
        if assoc == ASSOC_BEFORE:
            if index == 0:
                return cls._from_branch(branch, assoc)
            index -= 1
        # the walk is MOVE-AWARE: `index` is a VISIBLE position, and after
        # a move the raw link order no longer matches document order
        # (parity: moving.rs:809 via the move-aware block iterator — a
        # raw walk would anchor a second move on the wrong element)
        from ytpu.types.shared import visible_items

        for item in visible_items(branch):
            if not item.deleted and item.countable:
                if item.len > index:
                    return cls(
                        id_=ID(item.id.client, item.id.clock + index), assoc=assoc
                    )
                index -= item.len
        return cls._from_branch(branch, assoc)

    @classmethod
    def _from_branch(cls, branch, assoc: int) -> "StickyIndex":
        if branch.item is not None:
            return cls(branch_id=branch.item.id, assoc=assoc)
        return cls(name=branch.name, assoc=assoc)

    def get_offset(self, store) -> Optional[tuple]:
        """Resolve back to (branch, index) against the current doc state
        (parity: moving.rs:483 / Yjs createAbsolutePositionFromRelativePosition).
        """
        from ytpu.core.content import ContentType

        if self.id is not None:
            if store.blocks.get_clock(self.id.client) <= self.id.clock:
                return None
            right = store.follow_redone(self.id)
            if right is None:
                return None
            diff = self.id.clock - right.id.clock if right.contains(self.id) else 0
            branch = right.parent
            from ytpu.core.branch import Branch

            if not isinstance(branch, Branch):
                return None
            index = 0
            if branch.item is None or not branch.item.deleted:
                if not right.deleted and right.countable:
                    index = diff + (0 if self.assoc >= 0 else 1)
                node = right.left
                while node is not None:
                    if not node.deleted and node.countable:
                        index += node.len
                    node = node.left
            return branch, index
        if self.name is not None:
            branch = store.types.get(self.name)
        elif self.branch_id is not None:
            anchor = store.blocks.get_item(self.branch_id)
            branch = (
                anchor.content.branch
                if anchor is not None and isinstance(anchor.content, ContentType)
                else None
            )
        else:
            return None
        if branch is None:
            return None
        return branch, (branch.content_len if self.assoc >= 0 else 0)

    def encode_v1(self) -> bytes:
        """Wire form: IndexScope tag + payload, then assoc as a signed varint
        (parity: moving.rs:610-614, IndexScope :672-691, Assoc :786-793)."""
        w = Writer()
        if self.id is not None:
            w.write_var_uint(0)
            w.write_var_uint(self.id.client)
            w.write_var_uint(self.id.clock)
        elif self.branch_id is not None:
            w.write_var_uint(2)
            w.write_var_uint(self.branch_id.client)
            w.write_var_uint(self.branch_id.clock)
        else:
            w.write_var_uint(1)
            w.write_string(self.name or "")
        w.write_var_int(self.assoc)
        return w.to_bytes()

    @classmethod
    def decode_v1(cls, data: bytes) -> "StickyIndex":
        """Parity: moving.rs:617-623, :693-710, :795-801 (assoc optional for
        pre-assoc payloads, defaulting to After)."""
        cur = Cursor(data)
        tag = cur.read_var_uint()
        id_ = name = branch_id = None
        if tag == 0:
            id_ = ID(cur.read_var_uint(), cur.read_var_uint())
        elif tag == 1:
            name = cur.read_string()
        elif tag == 2:
            branch_id = ID(cur.read_var_uint(), cur.read_var_uint())
        else:
            raise ValueError(f"unknown sticky-index scope tag {tag}")
        assoc = ASSOC_AFTER
        if cur.has_content():
            assoc = ASSOC_BEFORE if cur.read_var_int() < 0 else ASSOC_AFTER
        return cls(id_=id_, name=name, branch_id=branch_id, assoc=assoc)

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, StickyIndex):
            return NotImplemented
        return (
            self.id == other.id
            and self.name == other.name
            and self.branch_id == other.branch_id
            and self.assoc == other.assoc
        )

    def __repr__(self) -> str:
        where = self.id or self.name or self.branch_id
        arrow = "<" if self.assoc == ASSOC_BEFORE else ">"
        return f"Sticky({where}{arrow})"


class Move:
    """A moved range ``[start, end]`` with a conflict-resolution priority."""

    __slots__ = ("start", "end", "priority", "overrides", "origin")

    def __init__(self, start: StickyIndex, end: StickyIndex, priority: int):
        self.start = start
        self.end = end
        self.priority = priority
        # runtime state (set during integration):
        self.overrides = None  # set[Item] of moves this one shadows
        self.origin = None  # previous `moved` markers

    def is_collapsed(self) -> bool:
        return self.start.id == self.end.id

    def copy(self) -> "Move":
        return Move(self.start, self.end, self.priority)

    def encode(self, enc) -> None:
        collapsed = self.is_collapsed()
        flags = 0
        if collapsed:
            flags |= 0b001
        if self.start.assoc == ASSOC_AFTER:
            flags |= 0b010
        if self.end.assoc == ASSOC_AFTER:
            flags |= 0b100
        flags |= self.priority << 6
        enc.write_var(flags)
        enc.write_var(self.start.id.client)
        enc.write_var(self.start.id.clock)
        if not collapsed:
            enc.write_var(self.end.id.client)
            enc.write_var(self.end.id.clock)

    @classmethod
    def decode(cls, dec) -> "Move":
        flags = dec.read_var()
        collapsed = flags & 0b001 != 0
        start_assoc = ASSOC_AFTER if flags & 0b010 else ASSOC_BEFORE
        end_assoc = ASSOC_AFTER if flags & 0b100 else ASSOC_BEFORE
        priority = flags >> 6
        start_id = ID(dec.read_var(), dec.read_var())
        end_id = start_id if collapsed else ID(dec.read_var(), dec.read_var())
        return cls(
            StickyIndex.from_id(start_id, start_assoc),
            StickyIndex.from_id(end_id, end_assoc),
            priority,
        )

    # --- integration (parity: moving.rs:100-265) -------------------------------

    @staticmethod
    def _item_ptr(store, sticky: StickyIndex):
        """Range coordinate resolution (parity: moving.rs:100-111):
        assoc After → the item starting at id (in-range); assoc Before →
        the item *after* the one ending at id (exclusive bound)."""
        if sticky.id is None:
            return None
        if sticky.assoc == ASSOC_AFTER:
            return store.blocks.get_item_clean_start(sticky.id)
        item = store.blocks.get_item_clean_end(sticky.id)
        return item.right if item is not None else None

    def get_coords(self, store):
        return self._item_ptr(store, self.start), self._item_ptr(store, self.end)

    def push_override(self, item) -> None:
        if self.overrides is None:
            self.overrides = set()
        self.overrides.add(item)

    def find_move_loop(self, store, moved_item, tracked) -> bool:
        """Cycle detection across nested moves (parity: moving.rs:113-141)."""
        if moved_item in tracked:
            return True
        tracked.add(moved_item)
        from ytpu.core.content import ContentMove

        start, end = self.get_coords(store)
        cur = start
        while cur is not None and cur is not end:
            if not cur.deleted and cur.moved is moved_item:
                if isinstance(cur.content, ContentMove):
                    if cur.content.move.find_move_loop(store, cur, tracked):
                        return True
            cur = cur.right
        return False

    def integrate_block(self, txn, item) -> None:
        """Claim the moved range, reconciling concurrent moves by priority
        (parity: moving.rs:149-227). `item` is the ContentMove item."""
        from ytpu.core.content import ContentMove

        store = txn.store
        start, end = self.get_coords(store)
        max_priority = 0
        adapt = self.priority < 0
        cur = start
        while cur is not None and cur is not end:
            prev_move = cur.moved
            if prev_move is not None and isinstance(prev_move.content, ContentMove):
                next_prio = prev_move.content.move.priority
            else:
                next_prio = -1
            takes = (
                adapt
                or next_prio < self.priority
                or (
                    prev_move is not None
                    and next_prio == self.priority
                    and (prev_move.id.client, prev_move.id.clock)
                    < (item.id.client, item.id.clock)
                )
            )
            if takes:
                if prev_move is not None:
                    if (
                        isinstance(prev_move.content, ContentMove)
                        and prev_move.content.move.is_collapsed()
                    ):
                        self._delete_as_cleanup(txn, prev_move, adapt)
                    self.push_override(prev_move)
                    if cur is not start:
                        txn.merge_blocks.append(cur.id)
                    max_priority = max(max_priority, next_prio)
                    # remember who moved this item before (for event diffing),
                    # unless the previous move was created in this very txn
                    if cur not in txn.prev_moved and not txn.has_added(prev_move.id):
                        txn.prev_moved[cur] = prev_move
                cur.moved = item
                if not cur.deleted and isinstance(cur.content, ContentMove):
                    if cur.content.move.find_move_loop(store, cur, {item}):
                        if adapt:
                            # the tombstoned move still re-encodes: its
                            # priority must leave the adapt sentinel (-1)
                            # before the early return, or a later
                            # encode_state_as_update writes a negative
                            # varint and throws
                            self.priority = max_priority + 1
                        self._delete_as_cleanup(txn, item, adapt)
                        return
            else:
                if prev_move is not None and isinstance(prev_move.content, ContentMove):
                    prev_move.content.move.push_override(item)
            cur = cur.right
        if adapt:
            self.priority = max_priority + 1

    def delete(self, txn, item) -> None:
        """Release the moved range and reintegrate overridden moves
        (parity: moving.rs:229-280)."""
        from ytpu.core.content import ContentMove

        store = txn.store
        start, end = self.get_coords(store)
        cur = start
        while cur is not None and cur is not end:
            if cur.moved is item:
                if cur in txn.prev_moved:
                    if txn.has_added(item.id) and txn.prev_moved[cur] is item:
                        del txn.prev_moved[cur]
                else:
                    txn.prev_moved[cur] = item
                cur.moved = None
            cur = cur.right

        def reintegrate(it):
            if isinstance(it.content, ContentMove):
                if it.deleted:
                    inner_overrides = it.content.move.overrides
                    if inner_overrides:
                        for inner in list(inner_overrides):
                            reintegrate(inner)
                else:
                    it.content.move.integrate_block(txn, it)

        if self.overrides:
            for inner in list(self.overrides):
                reintegrate(inner)

    @staticmethod
    def _delete_as_cleanup(txn, item, adapt_priority: bool) -> None:
        txn.delete(item)
        if adapt_priority:
            # losing move markers created concurrently clean up silently
            txn.merge_blocks.append(item.id)

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Move):
            return NotImplemented
        return (
            self.start == other.start
            and self.end == other.end
            and self.priority == other.priority
        )

    def __repr__(self) -> str:
        return f"Move({self.start}..{self.end}, prio={self.priority})"
