"""Move ranges and sticky indices.

Behavioral parity target: /root/reference/yrs/src/moving.rs (Move :16,
StickyIndex :403, Assoc :723). Round-1 scope: full wire format + data model;
`Move.integrate_block` / move-aware iteration land with the move/undo service
layer. Sticky indices resolve through `ytpu.core.store.DocStore`.
"""

from __future__ import annotations

from typing import Optional

from ytpu.encoding.lib0 import Cursor, Writer

from .ids import ID

__all__ = ["ASSOC_BEFORE", "ASSOC_AFTER", "StickyIndex", "Move"]

ASSOC_BEFORE = -1
ASSOC_AFTER = 0


class StickyIndex:
    """A position that sticks to its neighborhood across concurrent edits.

    Scope is either an item ID (relative), or a root-type name / branch id
    (start or end of a sequence).
    """

    __slots__ = ("id", "name", "branch_id", "assoc")

    def __init__(
        self,
        id_: Optional[ID] = None,
        name: Optional[str] = None,
        branch_id: Optional[ID] = None,
        assoc: int = ASSOC_AFTER,
    ):
        self.id = id_
        self.name = name
        self.branch_id = branch_id
        self.assoc = assoc

    @classmethod
    def from_id(cls, id_: ID, assoc: int) -> "StickyIndex":
        return cls(id_=id_, assoc=assoc)

    @classmethod
    def from_type_index(cls, branch, index: int, assoc: int = ASSOC_AFTER) -> "StickyIndex":
        """Sticky position at `index` of a sequence (parity: moving.rs:809 /
        IndexedSequence::sticky_index)."""
        if assoc == ASSOC_BEFORE:
            if index == 0:
                return cls._from_branch(branch, assoc)
            index -= 1
        item = branch.start
        while item is not None:
            if not item.deleted and item.countable:
                if item.len > index:
                    return cls(
                        id_=ID(item.id.client, item.id.clock + index), assoc=assoc
                    )
                index -= item.len
            item = item.right
        return cls._from_branch(branch, assoc)

    @classmethod
    def _from_branch(cls, branch, assoc: int) -> "StickyIndex":
        if branch.item is not None:
            return cls(branch_id=branch.item.id, assoc=assoc)
        return cls(name=branch.name, assoc=assoc)

    def get_offset(self, store) -> Optional[tuple]:
        """Resolve back to (branch, index) against the current doc state
        (parity: moving.rs:483 / Yjs createAbsolutePositionFromRelativePosition).
        """
        from ytpu.core.content import ContentType

        if self.id is not None:
            if store.blocks.get_clock(self.id.client) <= self.id.clock:
                return None
            right = store.follow_redone(self.id)
            if right is None:
                return None
            diff = self.id.clock - right.id.clock if right.contains(self.id) else 0
            branch = right.parent
            from ytpu.core.branch import Branch

            if not isinstance(branch, Branch):
                return None
            index = 0
            if branch.item is None or not branch.item.deleted:
                if not right.deleted and right.countable:
                    index = diff + (0 if self.assoc >= 0 else 1)
                node = right.left
                while node is not None:
                    if not node.deleted and node.countable:
                        index += node.len
                    node = node.left
            return branch, index
        if self.name is not None:
            branch = store.types.get(self.name)
        elif self.branch_id is not None:
            anchor = store.blocks.get_item(self.branch_id)
            branch = (
                anchor.content.branch
                if anchor is not None and isinstance(anchor.content, ContentType)
                else None
            )
        else:
            return None
        if branch is None:
            return None
        return branch, (branch.content_len if self.assoc >= 0 else 0)

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, StickyIndex):
            return NotImplemented
        return (
            self.id == other.id
            and self.name == other.name
            and self.branch_id == other.branch_id
            and self.assoc == other.assoc
        )

    def __repr__(self) -> str:
        where = self.id or self.name or self.branch_id
        arrow = "<" if self.assoc == ASSOC_BEFORE else ">"
        return f"Sticky({where}{arrow})"


class Move:
    """A moved range ``[start, end]`` with a conflict-resolution priority."""

    __slots__ = ("start", "end", "priority", "overrides", "origin")

    def __init__(self, start: StickyIndex, end: StickyIndex, priority: int):
        self.start = start
        self.end = end
        self.priority = priority
        # runtime state (set during integration):
        self.overrides = None  # set[Item] of moves this one shadows
        self.origin = None  # previous `moved` markers

    def is_collapsed(self) -> bool:
        return self.start.id == self.end.id

    def copy(self) -> "Move":
        return Move(self.start, self.end, self.priority)

    def encode(self, enc) -> None:
        collapsed = self.is_collapsed()
        flags = 0
        if collapsed:
            flags |= 0b001
        if self.start.assoc == ASSOC_AFTER:
            flags |= 0b010
        if self.end.assoc == ASSOC_AFTER:
            flags |= 0b100
        flags |= self.priority << 6
        enc.write_var(flags)
        enc.write_var(self.start.id.client)
        enc.write_var(self.start.id.clock)
        if not collapsed:
            enc.write_var(self.end.id.client)
            enc.write_var(self.end.id.clock)

    @classmethod
    def decode(cls, dec) -> "Move":
        flags = dec.read_var()
        collapsed = flags & 0b001 != 0
        start_assoc = ASSOC_AFTER if flags & 0b010 else ASSOC_BEFORE
        end_assoc = ASSOC_AFTER if flags & 0b100 else ASSOC_BEFORE
        priority = flags >> 6
        start_id = ID(dec.read_var(), dec.read_var())
        end_id = start_id if collapsed else ID(dec.read_var(), dec.read_var())
        return cls(
            StickyIndex.from_id(start_id, start_assoc),
            StickyIndex.from_id(end_id, end_assoc),
            priority,
        )

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Move):
            return NotImplemented
        return (
            self.start == other.start
            and self.end == other.end
            and self.priority == other.priority
        )

    def __repr__(self) -> str:
        return f"Move({self.start}..{self.end}, prio={self.priority})"
