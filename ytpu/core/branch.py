"""Branch — the universal shared-type node.

Behavioral parity target: /root/reference/yrs/src/branch.rs:173-215 and
`TypeRef` in /root/reference/yrs/src/types/mod.rs:36-199. Every shared type
(Text, Array, Map, XmlElement, …) is a projection over a `Branch`: a sequence
component (`start` linked chain) plus a map component (`map` per-key chains),
tagged with a runtime `type_ref`.

Device mapping: the batched engine keeps a branch table per doc — columns
(type_ref, start_idx, item_idx, block_len, content_len) plus a host dict for
root names and map keys (`ytpu.models.batch_doc`).
"""

from __future__ import annotations

from typing import Dict, List, Optional, TYPE_CHECKING

from ytpu.encoding.lib0 import Cursor, Writer

from .ids import ID
from .moving import ASSOC_AFTER, ASSOC_BEFORE, StickyIndex

if TYPE_CHECKING:
    from .block import Item

__all__ = [
    "TYPE_ARRAY",
    "TYPE_MAP",
    "TYPE_TEXT",
    "TYPE_XML_ELEMENT",
    "TYPE_XML_FRAGMENT",
    "TYPE_XML_HOOK",
    "TYPE_XML_TEXT",
    "TYPE_WEAK",
    "TYPE_DOC",
    "TYPE_UNDEFINED",
    "Branch",
    "LinkSource",
]

# Wire tags; parity: types/mod.rs:36-64.
TYPE_ARRAY = 0
TYPE_MAP = 1
TYPE_TEXT = 2
TYPE_XML_ELEMENT = 3
TYPE_XML_FRAGMENT = 4
TYPE_XML_HOOK = 5
TYPE_XML_TEXT = 6
TYPE_WEAK = 7
TYPE_DOC = 9
TYPE_UNDEFINED = 15


class LinkSource:
    """Quoted range backing a WeakRef (reference: types/weak.rs:487)."""

    __slots__ = ("quote_start", "quote_end", "first_item")

    def __init__(self, quote_start: StickyIndex, quote_end: StickyIndex):
        self.quote_start = quote_start
        self.quote_end = quote_end
        self.first_item = None

    def is_single(self) -> bool:
        return self.quote_start.id == self.quote_end.id


class Branch:
    __slots__ = (
        "item",
        "name",
        "type_ref",
        "type_name",
        "link_source",
        "start",
        "map",
        "block_len",
        "content_len",
        "observers",
        "deep_observers",
        "store",
    )

    def __init__(
        self,
        type_ref: int,
        type_name: Optional[str] = None,
        link_source: Optional[LinkSource] = None,
    ):
        self.item: Optional["Item"] = None  # integration anchor (None for roots)
        self.name: Optional[str] = None  # root-type name
        self.type_ref = type_ref
        self.type_name = type_name  # XmlElement tag / XmlHook key
        self.link_source = link_source
        self.start: Optional["Item"] = None
        self.map: Dict[str, "Item"] = {}
        self.block_len = 0  # total clock length of alive sequence items
        self.content_len = 0  # user-visible length
        self.observers: List = []
        self.deep_observers: List = []
        self.store = None  # back-ref set when registered

    def is_deleted(self) -> bool:
        return self.item is not None and self.item.deleted

    # --- wire ---

    def encode_type_ref(self, enc) -> None:
        """Parity: types/mod.rs:118-158."""
        enc.write_type_ref(self.type_ref)
        if self.type_ref in (TYPE_XML_ELEMENT, TYPE_XML_HOOK):
            enc.write_key(self.type_name or "")
        elif self.type_ref == TYPE_WEAK:
            src = self.link_source
            info = 0 if src.is_single() else 1
            if src.quote_start.assoc == ASSOC_AFTER:
                info |= 2
            if src.quote_end.assoc == ASSOC_AFTER:
                info |= 4
            enc.write_u8(info)
            enc.write_var(src.quote_start.id.client)
            enc.write_var(src.quote_start.id.clock)
            if not src.is_single():
                enc.write_var(src.quote_end.id.client)
                enc.write_var(src.quote_end.id.clock)

    @classmethod
    def decode_type_ref(cls, dec) -> "Branch":
        tag = dec.read_type_ref()
        if tag in (TYPE_XML_ELEMENT, TYPE_XML_HOOK):
            return cls(tag, type_name=dec.read_key())
        if tag == TYPE_WEAK:
            flags = dec.read_u8()
            single = flags & 1 == 0
            start_assoc = ASSOC_AFTER if flags & 2 else ASSOC_BEFORE
            end_assoc = ASSOC_AFTER if flags & 4 else ASSOC_BEFORE
            start_id = ID(dec.read_var(), dec.read_var())
            end_id = start_id if single else ID(dec.read_var(), dec.read_var())
            src = LinkSource(
                StickyIndex.from_id(start_id, start_assoc),
                StickyIndex.from_id(end_id, end_assoc),
            )
            return cls(tag, link_source=src)
        return cls(tag)

    # --- traversal helpers used by the shared types ---

    def first(self) -> Optional["Item"]:
        item = self.start
        while item is not None and item.deleted:
            item = item.right
        return item

    def __repr__(self) -> str:
        tag = self.name or (f"@{self.item.id}" if self.item else "?")
        return f"Branch[{self.type_ref}]({tag})"
