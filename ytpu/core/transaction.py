"""Transactions — the unit of change over a document.

Behavioral parity target: /root/reference/yrs/src/transaction.rs
(`TransactionMut` fields :317-338, `apply_delete` :472-575, recursive
`delete` :579-663, `apply_update` + pending retry :675-727, `create_item`
:729-776, the 11-step `commit` pipeline :828-962) and `GCCollector`
(/root/reference/yrs/src/gc.rs).

A transaction corresponds to one batched device step in the TPU engine: the
commit pipeline's squash/GC phases map onto the post-step compaction kernels,
and its event flush onto the host-side event materialization.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ytpu.encoding.lib0 import Writer

from .block import GCRange, Item
from .branch import Branch
from .content import ContentDeleted, ContentDoc, ContentMove, ContentType
from .id_set import DeleteSet
from .ids import ID
from .state_vector import Snapshot, StateVector
from .update import PendingUpdate, Update

__all__ = ["Transaction", "ItemPosition"]


class ItemPosition:
    """Insertion cursor (parity: block.rs:916-925)."""

    __slots__ = ("parent", "left", "right", "index", "current_attrs")

    def __init__(self, parent: Branch, left=None, right=None, index=0, current_attrs=None):
        self.parent = parent
        self.left = left
        self.right = right
        self.index = index
        self.current_attrs = current_attrs

    def forward(self) -> bool:
        right = self.right
        if right is None:
            return False
        if not right.deleted:
            from .content import ContentFormat, ContentString, ContentEmbed

            if isinstance(right.content, (ContentString, ContentEmbed)):
                self.index += right.len
            elif isinstance(right.content, ContentFormat):
                if self.current_attrs is None:
                    self.current_attrs = {}
                _update_attrs(self.current_attrs, right.content.key, right.content.value)
        self.left = right
        self.right = right.right
        return True


def _update_attrs(attrs: dict, key: str, value) -> None:
    if value is None:
        attrs.pop(key, None)
    else:
        attrs[key] = value


class Transaction:
    """A read/write transaction; writes are committed on `__exit__`/commit()."""

    __slots__ = (
        "doc",
        "store",
        "origin",
        "before_state",
        "after_state",
        "delete_set",
        "merge_blocks",
        "changed",
        "changed_parent_types",
        "subdocs_added",
        "subdocs_removed",
        "subdocs_loaded",
        "committed",
        "prev_moved",
        "_events",
    )

    def __init__(self, doc, origin=None):
        self.doc = doc
        self.store = doc.store
        self.origin = origin
        self.before_state: StateVector = self.store.blocks.get_state_vector()
        self.after_state: Optional[StateVector] = None
        self.delete_set = DeleteSet()
        self.merge_blocks: List[ID] = []
        self.changed: Dict[Branch, Set[Optional[str]]] = {}
        self.changed_parent_types: List[Branch] = []
        self.subdocs_added: Dict[str, object] = {}
        self.subdocs_removed: Dict[str, object] = {}
        self.subdocs_loaded: Dict[str, object] = {}
        self.committed = False
        self.prev_moved: Dict[Item, Item] = {}  # item -> move that owned it
        self._events = []

    # --- context manager -------------------------------------------------------

    def __enter__(self) -> "Transaction":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.commit()
        self.doc._txn = None

    # --- reads -----------------------------------------------------------------

    def state_vector(self) -> StateVector:
        return self.store.blocks.get_state_vector()

    def snapshot(self) -> Snapshot:
        return self.store.snapshot()

    def encode_state_as_update_v1(self, remote_sv: Optional[StateVector] = None) -> bytes:
        return self.store.encode_state_as_update_v1(remote_sv or StateVector())

    def encode_diff_v1(self, remote_sv: StateVector) -> bytes:
        return self.store.encode_diff_v1(remote_sv)

    def encode_diff_v2(self, remote_sv: StateVector) -> bytes:
        return self.store.encode_diff_v2(remote_sv)

    def encode_update_v1(self) -> bytes:
        """This transaction's own delta (the update-event payload).

        Parity: transaction.rs:464-468.
        """
        from ytpu.encoding.codec import EncoderV1

        enc = EncoderV1()
        self.store.write_blocks_from(self.before_state, enc)
        self.delete_set.encode(enc)
        return enc.to_bytes()

    def encode_update_v2(self) -> bytes:
        from ytpu.encoding.codec import EncoderV2

        enc = EncoderV2()
        self.store.write_blocks_from(self.before_state, enc)
        self.delete_set.encode(enc)
        return enc.to_bytes()

    def has_added(self, id_: ID) -> bool:
        """Was the block at `id_` created inside this transaction?"""
        return id_.clock >= self.before_state.get(id_.client)

    # --- change tracking -------------------------------------------------------

    def add_changed_type(self, parent: Branch, parent_sub: Optional[str]) -> None:
        """Parity: transaction.rs:964-984."""
        anchor = parent.item
        if anchor is not None:
            trigger = (
                anchor.id.clock < self.before_state.get(anchor.id.client)
                and not anchor.deleted
            )
        else:
            trigger = True
        if trigger:
            self.changed.setdefault(parent, set()).add(parent_sub)

    # --- deletion --------------------------------------------------------------

    def delete(self, item: Item) -> bool:
        """Tombstone `item` (recursively for nested types).

        Parity: transaction.rs:579-663.
        """
        recurse: List[Item] = []
        result = False
        if not item.deleted:
            if item.parent_sub is None and item.countable:
                if isinstance(item.parent, Branch):
                    item.parent.block_len -= item.len
                    item.parent.content_len -= item.len
            item.mark_deleted()
            self.delete_set.insert(item.id, item.len)
            if isinstance(item.parent, Branch):
                self.add_changed_type(item.parent, item.parent_sub)
            content = item.content
            if isinstance(content, ContentDoc):
                guid = content.doc.guid
                if guid in self.subdocs_added:
                    del self.subdocs_added[guid]
                else:
                    self.subdocs_removed[guid] = content.doc
            elif isinstance(content, ContentType):
                branch = content.branch
                self.store.deregister(branch)
                self.changed.pop(branch, None)
                if branch.link_source is not None:
                    # deleting a weak link unlinks its quoted items
                    # (parity: weak.rs:509-517 LinkSource::unlink)
                    from ytpu.types.weak import unlink_all

                    unlink_all(self.store, branch)
                node = branch.start
                while node is not None:
                    if not node.deleted:
                        recurse.append(node)
                    node = node.right
                for node in branch.map.values():
                    while node is not None:
                        if not node.deleted:
                            recurse.append(node)
                        node = node.left
            elif isinstance(content, ContentMove):
                content.move.delete(self, item)
            if item.linked:
                # notify links that the element was removed
                # (parity: transaction.rs:634-647)
                links = self.store.linked_by.pop(item, None)
                if links:
                    for link in links:
                        self.add_changed_type(link, item.parent_sub)
                        src = link.link_source
                        if src is not None and src.is_single():
                            src.first_item = None
            result = True

        for node in recurse:
            if not self.delete(node):
                self.merge_blocks.append(node.id)
        return result

    def apply_delete(self, ds: DeleteSet) -> Optional[DeleteSet]:
        """Apply a remote delete-set; returns ranges that couldn't be applied.

        Parity: transaction.rs:472-575.
        """
        unapplied = DeleteSet()
        for client, ranges in list(ds.clients.items()):
            blocks = self.store.blocks.get_client(client)
            if blocks is None:
                for start, end in ranges:
                    unapplied.insert_range(client, start, end)
                continue
            state = blocks.clock()
            for start, end in sorted(ranges):
                if start >= state:
                    unapplied.insert_range(client, start, end)
                    continue
                if state < end:
                    unapplied.insert_range(client, state, end)
                index = blocks.find_pivot(start)
                if index is None:
                    continue
                b = blocks[index]
                if b.is_item and not b.deleted and b.id.clock < start:
                    # split off the unaffected prefix
                    self.store.blocks.split_at(b, start - b.id.clock)
                    index += 1
                    self.merge_blocks.append(blocks[index].id)
                while index < len(blocks):
                    b = blocks[index]
                    if b.id.clock >= end:
                        break
                    if b.is_item and not b.deleted:
                        if b.id.clock + b.len > end:
                            self.store.blocks.split_at(b, end - b.id.clock)
                            self.merge_blocks.append(blocks[index + 1].id)
                        self.delete(b)
                    index += 1
        if unapplied.is_empty():
            return None
        return unapplied

    # --- update application ----------------------------------------------------

    def apply_update(self, update: Update) -> None:
        """Parity: transaction.rs:675-727 (pending stash & retry loop)."""
        remaining, remaining_ds = update.integrate(self)
        store = self.store
        retry = False
        if store.pending is not None:
            pending = store.pending
            for client, clock in pending.missing.clocks.items():
                if clock < store.blocks.get_clock(client):
                    retry = True
                    break
            if remaining is not None:
                for client, clock in remaining.missing.clocks.items():
                    pending.missing.set_min(client, clock)
                pending.update = Update.merge([pending.update, remaining.update])
            store.pending = pending
        else:
            store.pending = remaining

        if store.pending_ds is not None:
            pending_ds = store.pending_ds
            store.pending_ds = None
            ds2 = self.apply_delete(pending_ds)
            if remaining_ds is not None and ds2 is not None:
                remaining_ds.merge(ds2)
                store.pending_ds = remaining_ds
            else:
                store.pending_ds = remaining_ds or ds2
        else:
            store.pending_ds = remaining_ds

        if retry:
            pending = store.pending
            store.pending = None
            ds = store.pending_ds
            store.pending_ds = None
            self.apply_update(pending.update)
            ds_update = Update()
            if ds is not None:
                ds_update.delete_set = ds
            self.apply_update(ds_update)

    def apply_update_v1(self, data: bytes) -> None:
        self.apply_update(Update.decode_v1(data))

    def split_by_snapshot(self, snapshot: Snapshot) -> None:
        """Split blocks at snapshot boundaries so historical visibility
        checks are block-aligned (parity: transaction.rs:986-1018)."""
        store = self.store
        for client, clock in snapshot.state_vector.clocks.items():
            item = store.blocks.get_item(ID(client, clock))
            if item is not None and item.id.clock < clock:
                store.blocks.split_at(item, clock - item.id.clock)
                self.merge_blocks.append(ID(client, clock))
        for client, ranges in snapshot.delete_set.clients.items():
            for start, end in ranges:
                for edge in (start, end):
                    item = store.blocks.get_item(ID(client, edge))
                    if item is not None and item.id.clock < edge:
                        store.blocks.split_at(item, edge - item.id.clock)
                        self.merge_blocks.append(ID(client, edge))

    # --- local inserts ---------------------------------------------------------

    def create_item(self, pos: ItemPosition, content, parent_sub: Optional[str]) -> Optional[Item]:
        """Parity: transaction.rs:729-776."""
        left = pos.left
        right = pos.right
        origin = left.last_id if left is not None else None
        store = self.store
        id_ = ID(self.doc.client_id, store.get_local_state())
        if content.length() == 0:
            return None
        item = Item(
            id_,
            left,
            origin,
            right,
            right.id if right is not None else None,
            pos.parent,
            parent_sub,
            content,
        )
        store.integrate_block(self, item, 0)
        store.blocks.push_block(item)
        return item

    # --- commit pipeline -------------------------------------------------------

    def commit(self) -> None:
        """Parity: transaction.rs:828-962 (steps numbered as in the reference)."""
        if self.committed:
            return
        self.committed = True
        store = self.store
        doc = self.doc

        # 1. squash delete set
        self.delete_set.squash()
        self.after_state = store.blocks.get_state_vector()

        # changed branches + their ancestors (used by undo scope filtering;
        # parity: txn.changed_parent_types)
        seen = set()
        for branch in self.changed:
            node = branch
            while node is not None and id(node) not in seen:
                seen.add(id(node))
                self.changed_parent_types.append(node)
                node = (
                    node.item.parent
                    if node.item is not None and isinstance(node.item.parent, Branch)
                    else None
                )

        # 2-3. per-type observers + deep observers
        if self.changed:
            from ytpu.types.events import fire_type_events

            fire_type_events(self)

        for cb in doc.after_transaction_subs:
            cb(self)

        # 4. GC delete set (unless disabled)
        if not doc.options.skip_gc:
            self._gc_collect()

        # 5-6. squash new blocks to the left
        for client, clock in self.after_state.clocks.items():
            before_clock = self.before_state.get(client)
            if before_clock != clock:
                blocks = store.blocks.get_client(client)
                pivot = blocks.find_pivot(before_clock)
                first_change = max(1, pivot if pivot is not None else 1)
                i = len(blocks) - 1
                while i >= first_change:
                    if blocks.squash_left(i):
                        pass
                    i -= 1

        # 7. squash explicitly queued merge candidates
        for bid in self.merge_blocks:
            blocks = store.blocks.get_client(bid.client)
            if blocks is None:
                continue
            pos = blocks.find_pivot(bid.clock)
            if pos is None:
                continue
            if pos + 1 < len(blocks):
                blocks.squash_left(pos + 1)
            elif pos > 0:
                blocks.squash_left(pos)

        # 8-10. cleanup + update events
        for cb in doc.transaction_cleanup_subs:
            cb(self)
        if doc.update_v1_subs:
            payload = self.encode_update_v1()
            if payload != b"\x00\x00":  # skip no-op transactions
                for cb in doc.update_v1_subs:
                    cb(payload, self.origin, self)
        if doc.update_v2_subs:
            payload = self.encode_update_v2()
            for cb in doc.update_v2_subs:
                cb(payload, self.origin, self)

        # 11. subdoc bookkeeping
        if self.subdocs_added or self.subdocs_removed or self.subdocs_loaded:
            for guid, subdoc in self.subdocs_added.items():
                subdoc.client_id = doc.client_id
                if subdoc.options.collection_id is None:
                    subdoc.options.collection_id = doc.options.collection_id
                store.subdocs[guid] = subdoc
            for guid in self.subdocs_removed:
                store.subdocs.pop(guid, None)
            for cb in doc.subdocs_subs:
                cb(self, self.subdocs_added, self.subdocs_removed, self.subdocs_loaded)
            for subdoc in self.subdocs_removed.values():
                subdoc.destroy()

    def _gc_collect(self) -> None:
        """Parity: gc.rs:11-65 + block.rs:1371-1382,1907-1928."""
        marked: List[Tuple[int, int]] = []

        def gc_item(item: Item, parent_gc: bool) -> None:
            if item.deleted and not item.keep:
                content = item.content
                if isinstance(content, ContentType):
                    branch = content.branch
                    node = branch.start
                    branch.start = None
                    while node is not None:
                        nxt = node.right
                        gc_item(node, True)
                        node = nxt
                    for node in branch.map.values():
                        while node is not None:
                            prev = node.left
                            gc_item(node, True)
                            node = prev
                    branch.map.clear()
                if parent_gc:
                    marked.append((item.id.client, item.id.clock))
                else:
                    item.content = ContentDeleted(item.len)

        for client, ranges in self.delete_set.clients.items():
            blocks = self.store.blocks.get_client(client)
            if blocks is None:
                continue
            for start, end in reversed(sorted(ranges)):
                idx = blocks.find_pivot(start)
                if idx is None:
                    continue
                clock = start
                while idx < len(blocks):
                    b = blocks[idx]
                    clock = b.id.clock + b.len
                    if clock > end:
                        break
                    if b.is_item:
                        gc_item(b, False)
                    idx += 1

        for client, clock in marked:
            blocks = self.store.blocks.get_client(client)
            if blocks is None:
                continue
            idx = blocks.find_pivot(clock)
            if idx is None:
                continue
            b = blocks[idx]
            if b.is_item and b.deleted and not b.keep:
                blocks.blocks[idx] = GCRange(b.id, b.len)
