"""In-process multi-peer test network + scenario fuzzer.

Behavioral parity target: /root/reference/yrs/src/test_utils.rs —
`exchange_updates` :17, seeded `run_scenario` :38-77, `TestConnector`
in-process peer network with disconnect/reconnect/partial flush :79-435 and
the final convergence assertion :402-429.

This harness is the primary conformance oracle for both the host engine and
the batched device engine ("distributed" testing is always simulated
in-process; the same approach drives the multi-host TPU tests with a fake
transport).
"""

from __future__ import annotations

import random
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ytpu.core import Doc, StateVector, Update

__all__ = ["TestPeer", "TestConnector", "exchange_updates", "run_scenario"]


def exchange_updates(docs: List[Doc]) -> None:
    """Full pairwise sync until fixpoint (parity: test_utils.rs:17)."""
    for _ in range(len(docs)):
        changed = False
        for a in docs:
            for b in docs:
                if a is b:
                    continue
                diff = a.encode_state_as_update_v1(b.state_vector())
                before = b.state_vector().clocks.copy()
                b.apply_update_v1(diff)
                if b.state_vector().clocks != before:
                    changed = True
        if not changed:
            break


class TestPeer:
    __slots__ = ("doc", "receiving", "online", "connector")

    def __init__(self, connector: "TestConnector", client_id: int):
        self.doc = Doc(client_id=client_id)
        self.receiving: Dict[int, Deque[bytes]] = {}
        self.online = True
        self.connector = connector
        self.doc.observe_update_v1(self._broadcast)

    def _broadcast(self, payload: bytes, origin, txn) -> None:
        for other in self.connector.peers:
            if other is not self:
                other.receiving.setdefault(self.doc.client_id, deque()).append(payload)

    def receive(self, sender: int, n: Optional[int] = None) -> int:
        """Apply up to `n` queued messages from `sender` (None = all)."""
        q = self.receiving.get(sender)
        if not q:
            return 0
        count = 0
        while q and (n is None or count < n):
            payload = q.popleft()
            self.doc.apply_update_v1(payload)
            count += 1
        return count


class TestConnector:
    """A fake network of peers with lossless but delayable message queues."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        self.peers: List[TestPeer] = []

    def create_peer(self, client_id: int) -> TestPeer:
        peer = TestPeer(self, client_id)
        self.peers.append(peer)
        return peer

    # --- message pumping -------------------------------------------------------

    def flush_random_message(self) -> bool:
        """Deliver one random queued message (parity: test_utils.rs flush)."""
        candidates: List[Tuple[TestPeer, int]] = []
        for peer in self.peers:
            if not peer.online:
                continue
            for sender, q in peer.receiving.items():
                if q:
                    candidates.append((peer, sender))
        if not candidates:
            return False
        peer, sender = self.rng.choice(candidates)
        peer.receive(sender, 1)
        return True

    def flush_all(self) -> bool:
        any_ = False
        while self.flush_random_message():
            any_ = True
        return any_

    def disconnect_random(self) -> bool:
        online = [p for p in self.peers if p.online]
        if not online:
            return False
        self.rng.choice(online).online = False
        return True

    def reconnect_random(self) -> bool:
        offline = [p for p in self.peers if not p.online]
        if not offline:
            return False
        peer = self.rng.choice(offline)
        peer.online = True
        # on reconnect, run a full sync-step exchange with everyone
        for other in self.peers:
            if other is not peer:
                peer.doc.apply_update_v1(
                    other.doc.encode_state_as_update_v1(peer.doc.state_vector())
                )
                other.doc.apply_update_v1(
                    peer.doc.encode_state_as_update_v1(other.doc.state_vector())
                )
        return True

    def assert_converged(self) -> None:
        """Reconnect + flush everything, then require identical stores
        (parity: test_utils.rs:402-429)."""
        for peer in self.peers:
            peer.online = True
        self.flush_all()
        exchange_updates([p.doc for p in self.peers])
        first = self.peers[0].doc
        ref_json = first.to_json()
        ref_sv = first.state_vector()
        for peer in self.peers[1:]:
            assert peer.doc.state_vector() == ref_sv, (
                f"state vectors diverged:\n{ref_sv}\n{peer.doc.state_vector()}"
            )
            got = peer.doc.to_json()
            assert got == ref_json, f"doc content diverged:\n{ref_json}\n{got}"


def run_scenario(
    seed: int,
    mutators: List[Callable],
    n_peers: int,
    n_iterations: int,
) -> TestConnector:
    """Seeded random op/network interleaving (parity: test_utils.rs:38-77).

    `mutators` are callables (doc, rng) -> None applying one random local op.
    Mix per iteration mirrors the reference: 2% disconnect, 1% reconnect,
    50% flush one message, 47% random local edit.
    """
    tc = TestConnector(seed)
    for i in range(n_peers):
        tc.create_peer(i + 1)
    rng = tc.rng
    for _ in range(n_iterations):
        roll = rng.random()
        if roll < 0.02:
            tc.disconnect_random()
        elif roll < 0.03:
            tc.reconnect_random()
        elif roll < 0.53:
            tc.flush_random_message()
        else:
            peer = rng.choice(tc.peers)
            mutator = rng.choice(mutators)
            mutator(peer.doc, rng)
    tc.assert_converged()
    return tc
