"""Cross-server (pod-to-pod) replication over the y-sync protocol.

Behavioral parity target: /root/reference/yrs/src/sync/protocol.rs — the
handshake contract (:8-31) and default handlers (:42-135) are symmetric
peer-to-peer; a "server" is just a peer that happens to fan updates out to
its own sessions. This module applies that symmetry *between two server
processes*: each pod holds authoritative tenant state (host docs or device
batch slots) and a `ReplicaLink` makes one pod a session of the other.

Design: the link bridges a local in-process `Session` (obtained from
`SyncServer.connect_frames`, so the local server speaks its own greeting —
SyncStep1(sv) + awareness snapshot) to the remote pod's TCP endpoint
(`ytpu.sync.net.serve`). Frames flow both ways untouched:

- local greeting / replies / outbox broadcasts  → written to the socket;
- remote frames → `server.receive_frames(session, frame)`; the local
  server applies them with the link's session as origin, so its own
  broadcast fan-out delivers to every *other* local session but never
  echoes back over the link.

Because only `connect_frames` / `receive_frames` / `drain` are used, the
same link replicates a plain host `SyncServer` and a device-authoritative
`DeviceSyncServer` (whose overrides answer SyncStep1 from device state and
queue inbound updates straight to batch slots) without special cases.

One link per tenant per peer pair is fully bidirectional; duplicate
delivery through redundant links is harmless (CRDT updates are idempotent,
exactly the reference's at-least-once stance). Anti-entropy: `gossip()`
re-sends SyncStep1 with the current local state vector so a peer that
missed live updates (e.g. reconnect) ships the SV-diff — the
reference's read-your-state handshake used as a repair round.
"""

from __future__ import annotations

import asyncio
from typing import List, Optional

from ytpu.sync.net import read_frame, write_frame
from ytpu.sync.protocol import Message, SyncMessage
from ytpu.sync.server import Session, SyncServer

__all__ = ["ReplicaLink", "Replicator"]


def _step1_frame(server: SyncServer, tenant: str) -> bytes:
    """A SyncStep1 frame carrying the server's CURRENT state vector for
    `tenant` — device state when the server is device-authoritative
    (`tenant_state_vector` dispatches, including host-demoted tenants)."""
    if getattr(server, "device_authoritative", False):
        server.flush_device()
    return Message.sync(
        SyncMessage.step1(server.tenant_state_vector(tenant))
    ).encode_v1()


class ReplicaLink:
    """Replicate one tenant between a local server and a remote pod."""

    def __init__(self, server: SyncServer, tenant: str):
        self.server = server
        self.tenant = tenant
        self.session: Optional[Session] = None
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None

    async def connect(self, host: str, port: int) -> None:
        """Dial the peer pod and run the symmetric greeting."""
        self.reader, self.writer = await asyncio.open_connection(host, port)
        write_frame(self.writer, self.tenant.encode("utf-8"))
        # local server's own greeting (SyncStep1 + awareness) goes first —
        # both sides open with step1, per the protocol.rs header contract
        self.session, greeting = self.server.connect_frames(self.tenant)
        for frame in greeting:
            write_frame(self.writer, frame)
        await self.writer.drain()

    async def pump(self, max_frames: int = 64, timeout: float = 0.2) -> int:
        """Process up to `max_frames` inbound frames, then flush outbox.

        Returns the number of frames read. A `timeout` bounds the wait for
        each frame's first byte, so a quiet peer never blocks the loop.
        Raises ConnectionError when the peer closed (EOF) or when this
        link's session was evicted as a slow consumer — a silent return
        in either case would leave `run()` busy-spinning / the pods
        silently diverging."""
        n = 0
        while n < max_frames:
            frame = await read_frame(self.reader, first_byte_timeout=timeout)
            if frame is None:
                if self.reader.at_eof():
                    raise ConnectionError("replica peer closed the link")
                break
            for reply in self.server.receive_frames(self.session, frame):
                write_frame(self.writer, reply)
            n += 1
        if self.session is not None and self.session.dead:
            raise ConnectionError(
                "replica link session evicted (outbox overflow); "
                "reconnect and resync via the SyncStep1 greeting"
            )
        await self.flush()
        return n

    async def flush(self) -> None:
        """Ship local broadcasts (other sessions' applies) to the peer."""
        if self.writer is None:
            return
        for payload in self.server.drain(self.session):
            write_frame(self.writer, payload)
        await self.writer.drain()

    async def gossip(self) -> None:
        """Anti-entropy round: advertise the current local SV; the peer
        answers with the SV-diff update (protocol.rs:60-68 semantics)."""
        if self.writer is None:
            return
        write_frame(self.writer, _step1_frame(self.server, self.tenant))
        await self.writer.drain()

    async def run(self, interval: float = 0.05, gossip_every: int = 0) -> None:
        """Continuous replication loop (cancel the task to stop)."""
        rounds = 0
        while True:
            await self.pump(timeout=interval)
            rounds += 1
            if gossip_every and rounds % gossip_every == 0:
                await self.gossip()

    async def close(self) -> None:
        if self.session is not None:
            self.server.disconnect(self.session)
            self.session = None
        if self.writer is not None:
            self.writer.close()
            try:
                await self.writer.wait_closed()
            except Exception:
                pass
            self.writer = None


class Replicator:
    """All of one pod's links to one peer pod (one link per tenant)."""

    def __init__(self, server: SyncServer, host: str, port: int):
        self.server = server
        self.host = host
        self.port = port
        self.links: List[ReplicaLink] = []

    async def add_tenant(self, tenant: str) -> ReplicaLink:
        link = ReplicaLink(self.server, tenant)
        await link.connect(self.host, self.port)
        self.links.append(link)
        return link

    async def pump(self, rounds: int = 1, timeout: float = 0.2) -> int:
        total = 0
        for _ in range(rounds):
            for link in self.links:
                total += await link.pump(timeout=timeout)
        return total

    async def gossip(self) -> None:
        for link in self.links:
            await link.gossip()

    async def close(self) -> None:
        for link in self.links:
            await link.close()
        self.links = []
