"""Multi-replica federation: peer sync mesh, incremental commitments,
partition/heal chaos (ISSUE-13 tentpole).

Everything before this layer was one server owning every tenant — a
single process, single device, single failure domain.  The y-sync
protocol is symmetric (reference: yrs sync/protocol.rs:8-31 — a "server"
is just a peer that fans updates out to its own sessions), so scale-OUT
is running N `SyncServer` / `DeviceSyncServer` replicas that peer with
each other as clients: server↔server SyncStep1/2 over the same frames
tenants speak.  Two layers live here:

- **`ReplicaLink` / `Replicator`** (the original pod-to-pod bridge,
  folded onto the PR-6 hardened transport): one asyncio link makes a
  local server a session of a remote pod over TCP — connect retry with
  exponential backoff + full jitter (`net.connect_retries`), the
  whole-frame read deadline, and `reconnect()`-with-SV-resync
  (`net.reconnects`).  This remains the CROSS-PROCESS transport; new
  code composing several replicas in one process should use the mesh.

- **`ReplicaMesh`** (ISSUE-13): the federation control plane.  It owns
  one `_PeerLink` per (replica pair, tenant) — a bidirectional in-proc
  link whose two ends are ordinary server `Session`s, pumped
  deterministically (tier-1-testable; the wire-frame path, byte for
  byte, minus the socket) — plus:

  * **tenant-sharded ownership** with typed, epoch-guarded
    `OwnershipHandoff` frames (`protocol.MSG_OWNERSHIP`):
    `assign_owner` shards tenants across replicas, `migrate_tenant`
    promotes PR-9's `rebalance_tenant` into LIVE cross-replica
    migration (drain → handoff broadcast → optional source device-slot
    release via `DeviceSyncServer.release_tenant`), and `kill_replica`
    is the forced failover — the dead replica's sessions drop with
    `net.sessions_dropped{reason="failover"}` and its tenants' ownership
    hands off to a survivor.

  * **O(1) anti-entropy** (`anti_entropy_round`): replicas exchange
    per-tenant incremental commitments (`ytpu.sync.commitment`,
    `protocol.MSG_COMMIT` frames over the links) and pull an SV-diff
    ONLY on mismatch.  A commitment that still disagrees after a
    converged sync (equal state vectors) is a typed `DivergenceFault`:
    the tenant quarantines, `replica.divergences` counts it, and a
    telemetry `/healthz` probe sees ``status: "degraded"``
    (`mesh.attach_health`).  `recover_tenant` rebuilds the trackers
    from scratch and unquarantines when replicas agree again.

  * **first-class chaos**: `partition`/`heal`/`lag` are mesh APIs AND
    `YTPU_FAULTS=` sites (`replica.partition`, `replica.heal`,
    `replica.lag`, `replica.kill`, plus `commit.corrupt` inside the
    commitment fold) fired at `sync_round` entry, so a federated soak
    scripts its whole failure schedule through the PR-6 grammar.

Delivery semantics: links are at-least-once (CRDT updates are
idempotent), and the mesh dedupes *delivered* update/step2 payloads per
receiving replica — device-authoritative servers rebroadcast
unconditionally (they never touch a host doc, so no no-op-apply
suppression exists there), and without the dedup a ≥3-replica cycle
would circulate one update forever.  Partitioned links DROP frames
(that is the fault being modeled; `replica.frames_dropped`); healing
queues an SV gossip both ways, and the next anti-entropy round pulls
whatever the drop lost.  In-proc, all replicas share one ownership map;
the handoff frames still cross the links so the epoch guard and codecs
run exactly as a cross-process mesh would pump them.
"""

from __future__ import annotations

import asyncio
import hashlib
import time
from collections import deque
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from ytpu.sync.commitment import TenantCommitments
from ytpu.sync.net import (
    FRAME_DEADLINE,
    _RECONNECTS,
    connect_with_backoff,
    read_frame,
    write_frame,
)
from ytpu.sync.protocol import (
    MSG_BUSY,
    MSG_COMMIT,
    MSG_OWNERSHIP,
    MSG_TRACE,
    Message,
    OwnershipHandoff,
    SyncMessage,
    commit_message,
    decode_commit,
    decode_ownership,
    decode_trace,
    message_reader,
    ownership_message,
)
from ytpu.sync.server import Session, SyncServer
from ytpu.utils import metrics
from ytpu.utils.faults import faults
from ytpu.utils.trace import resume_trace, tracer

__all__ = [
    "DivergenceFault",
    "MeshReplica",
    "ReplicaLink",
    "ReplicaMesh",
    "Replicator",
]

# federation series (module-cached; docs/observability.md §Metric name
# index). `replica.anti_entropy_bytes` is the scale headline: commitment
# agreement makes a round cost O(tenants · links) tiny frames instead of
# O(state) — bench_compare regresses it on RISE.
_LINKS = metrics.gauge("replica.links")
_SYNC_ROUNDS = metrics.counter("replica.sync_rounds")
_AE_ROUNDS = metrics.counter("replica.anti_entropy_rounds")
_AE_BYTES = metrics.counter("replica.anti_entropy_bytes")
_MISMATCHES = metrics.counter("replica.commit_mismatches")
_DIVERGENCES = metrics.counter("replica.divergences")
_QUARANTINED = metrics.gauge("replica.quarantined_tenants")
_RECOVERIES = metrics.counter("replica.recoveries")
_PARTITIONS = metrics.counter("replica.partitions")
_HEALS = metrics.counter("replica.heals")
_LAGS = metrics.counter("replica.lags")
_FAILOVERS = metrics.counter("replica.failovers")
_MIGRATIONS = metrics.counter("replica.migrations")
_FRAMES_DROPPED = metrics.counter(
    "replica.frames_dropped", labelnames=("reason",)
)
_FRAMES_DEDUPED = metrics.counter("replica.frames_deduped")
_LINK_RESYNCS = metrics.counter("replica.link_resyncs")
# fleet-observability round gauges (ISSUE-15): the most recent top-level
# sync round's duration/volume, the most recent anti-entropy round's
# mismatch count, and the per-tenant convergence lag (anti-entropy rounds
# since the tenant's last CLEAN pass — 0 is steady state)
_ROUND_MS = metrics.gauge("replica.round_ms")
_ROUND_FRAMES = metrics.gauge("replica.round_frames")
_ROUND_MISMATCHES = metrics.gauge("replica.round_mismatches")
_CONV_LAG = metrics.gauge("replica.convergence_lag", labelnames=("tenant",))

#: event-timeline ring capacity (`ReplicaMesh.timeline`): enough to hold
#: every ownership/migration/quarantine event of a long chaos soak while
#: keeping the `/snapshot` section bounded
TIMELINE_CAP = 512


class DivergenceFault(RuntimeError):
    """Two replicas' commitments for one tenant disagree AFTER a sync
    round converged their state vectors: the op lattices agree but a
    commitment tracker (or the state behind it) silently diverged —
    the failure mode the incremental commitment exists to catch
    (`commit.corrupt` injects it deterministically).  The tenant is
    quarantined on raise/record; `ReplicaMesh.recover_tenant` is the
    operator path back."""

    def __init__(
        self, tenant: str, a: str, b: str, commit_a: int, commit_b: int
    ):
        super().__init__(
            f"tenant {tenant!r} commitments diverge between replicas "
            f"{a!r} ({commit_a:#018x}) and {b!r} ({commit_b:#018x}) "
            "despite equal state vectors — tenant quarantined"
        )
        self.tenant = tenant
        self.replica_a = a
        self.replica_b = b
        self.commit_a = commit_a
        self.commit_b = commit_b


def _step1_frame(server: SyncServer, tenant: str) -> bytes:
    """A SyncStep1 frame carrying the server's CURRENT state vector for
    `tenant` — device state when the server is device-authoritative
    (`tenant_state_vector` dispatches, including host-demoted tenants)."""
    if getattr(server, "device_authoritative", False):
        server.flush_device()
    return Message.sync(
        SyncMessage.step1(server.tenant_state_vector(tenant))
    ).encode_v1()


# --------------------------------------------------------------------------
# the in-process federation mesh (ISSUE-13)
# --------------------------------------------------------------------------


class MeshReplica:
    """One replica in a `ReplicaMesh`: an id, a server, liveness, its
    per-tenant commitment trackers, and the delivered-payload dedup set
    (the at-least-once mesh's cycle breaker — see module docstring)."""

    __slots__ = ("id", "server", "alive", "commitments", "_seen")

    #: dedup-set bound (FIFO eviction).  Rebroadcast cycles re-deliver a
    #: payload within a handful of flow passes, so a recency window this
    #: wide breaks every cycle while keeping steady-state memory flat; an
    #: evicted key's payload recirculating later is an idempotent no-op.
    SEEN_CAP = 65536

    def __init__(self, rid: str, server: SyncServer):
        self.id = rid
        self.server = server
        self.alive = True
        self.commitments = TenantCommitments()
        self._seen: Dict[bytes, None] = {}  # insertion-ordered set

    @staticmethod
    def payload_key(frame: bytes, tenant: str) -> Optional[bytes]:
        """Dedup key of a SyncStep2/Update frame (same payload in either
        wrapping keys identically — frame[2:] skips kind+tag) or an
        Awareness frame (servers rebroadcast awareness unconditionally,
        so a ≥3-replica cycle would otherwise circulate one snapshot
        forever and `sync_round` could never quiesce; byte-identical
        awareness payloads are idempotent no-ops, a bumped presence
        clock changes the bytes and passes).  None for every other
        frame kind.  The TENANT is part of the key: the same client
        writing byte-identical first ops into two tenants is two
        distinct deliveries, not a duplicate."""
        if len(frame) < 2:
            return None
        salt = tenant.encode() + b"\x00"
        if frame[0] == 0 and frame[1] in (1, 2):
            return hashlib.blake2b(
                salt + frame[2:], digest_size=8
            ).digest()
        if frame[0] == 1:  # Awareness
            return hashlib.blake2b(salt + frame, digest_size=8).digest()
        return None

    def seen_payload(self, key: Optional[bytes]) -> bool:
        """True when this replica already had the payload behind `key`
        DELIVERED (marked via `mark_payload` only after a successful
        apply).  Re-applying would be an idempotent no-op; the dedup
        prevents device-authoritative rebroadcast cycles."""
        if key is not None and key in self._seen:
            _FRAMES_DEDUPED.inc()
            return True
        return False

    def mark_payload(self, key: Optional[bytes]) -> None:
        if key is None:
            return
        self._seen[key] = None
        if len(self._seen) > self.SEEN_CAP:
            del self._seen[next(iter(self._seen))]

    def commitment(self, tenant: str) -> int:
        """The replica's current commitment for `tenant` (incremental
        fold of the authoritative state vector's delta)."""
        return self.commitments.refresh(
            tenant, self.server.tenant_state_vector(tenant)
        )


class _PeerLink:
    """One tenant's bidirectional in-proc link between two mesh
    replicas.  Each end is an ordinary `Session` on the OTHER replica's
    server (exactly the `ReplicaLink` bridge shape, minus the socket):
    frames queue toward a destination and `flow()` delivers one batch
    each way, returning (frames, bytes) moved.  Partition drops,
    lag defers, a slow-consumer-evicted end reopens with a fresh
    greeting (SV resync)."""

    __slots__ = (
        "mesh", "a", "b", "tenant", "partitioned", "lag_rounds",
        "sess_a", "sess_b", "_to_a", "_to_b",
    )

    def __init__(self, mesh: "ReplicaMesh", a: MeshReplica, b: MeshReplica,
                 tenant: str):
        self.mesh = mesh
        self.a = a
        self.b = b
        self.tenant = tenant
        self.partitioned = False
        self.lag_rounds = 0
        self._to_a: List[bytes] = []
        self._to_b: List[bytes] = []
        # each replica's greeting (SyncStep1 + awareness) crosses to the
        # peer — both sides open with step1, per the protocol contract
        self.sess_a, greet_a = a.server.connect_frames(tenant)
        self.sess_b, greet_b = b.server.connect_frames(tenant)
        # peer replication is mesh-internal: admission must not refuse it
        self.sess_a.mesh_link = True
        self.sess_b.mesh_link = True
        self._to_b.extend(greet_a)
        self._to_a.extend(greet_b)

    def covers(self, rid: str) -> bool:
        return rid in (self.a.id, self.b.id)

    def post(self, frame: bytes, dst: MeshReplica) -> None:
        (self._to_a if dst is self.a else self._to_b).append(frame)

    def gossip(self) -> None:
        """Queue an SV advertisement both ways — the repair round a heal
        schedules (the peer answers with the SV-diff, protocol.rs:60-68
        semantics)."""
        self._to_b.append(_step1_frame(self.a.server, self.tenant))
        self._to_a.append(_step1_frame(self.b.server, self.tenant))

    def _resync(self, end: str) -> Session:
        """Reopen one evicted end (outbox overflow marked it dead): a
        fresh session whose greeting resyncs the peer via the
        state-vector handshake — the PR-6 reconnect discipline."""
        _LINK_RESYNCS.inc()
        if end == "b":
            self.b.server.disconnect(self.sess_b)
            self.sess_b, greet = self.b.server.connect_frames(self.tenant)
            self.sess_b.mesh_link = True
            self._to_a.extend(greet)
            return self.sess_b
        self.a.server.disconnect(self.sess_a)
        self.sess_a, greet = self.a.server.connect_frames(self.tenant)
        self.sess_a.mesh_link = True
        self._to_b.extend(greet)
        return self.sess_a

    def _deliver(
        self, frames: List[bytes], src: MeshReplica, dst: MeshReplica,
        end: str,
    ) -> Tuple[int, int]:
        n = nb = 0
        back = self._to_a if end == "b" else self._to_b
        sess = self.sess_b if end == "b" else self.sess_a
        pending = None  # decoded trace context riding ahead of one frame
        for frame in frames:
            n += 1
            nb += len(frame)
            tr, pending = pending, None
            if frame and frame[0] == MSG_TRACE:
                # wire trace-context extension (ISSUE-15): applies to
                # the IMMEDIATELY FOLLOWING frame only — consumed at the
                # link layer, never forwarded to the server (and dropped
                # when the next frame dedups away, so it can never leak
                # onto an unrelated frame)
                if tracer.enabled:
                    try:
                        _v, tid, origin = decode_trace(
                            next(message_reader(frame)).body
                        )
                        pending = (tid, origin)
                    except Exception:
                        pass
                continue
            if self.mesh._handle_mesh_frame(frame, src, dst):
                continue  # commit/ownership: the mesh's, not the server's
            if frame and frame[0] == MSG_BUSY:
                # a peer's admission refusal crossing back over the
                # link: servers don't speak MSG_BUSY (only SyncClient
                # does) — swallow it; the refused update was never
                # marked delivered, so SV-resync gossip retransmits it
                continue
            key = dst.payload_key(frame, self.tenant)
            if dst.seen_payload(key):
                continue  # at-least-once dedup (idempotent anyway)
            if sess.dead:
                sess = self._resync(end)
            # mark delivered only on SUCCESS: a refused apply must stay
            # repairable by the SV-resync retransmission path — marking
            # up front would blacklist the payload forever.  An update
            # frame only counts as applied when the server's applied
            # counter moved (catches Busy replies AND the silent
            # admission policy="drop" refusal, which sends nothing);
            # awareness frames have no admission gate.
            is_update = key is not None and frame[0] == 0
            before = dst.server._applied.value if is_update else 0
            if tr is not None and tracer.enabled:
                # re-enter the sender's trace around the delivery: the
                # receiver's apply span AND any onward rebroadcast
                # (which re-emits the trace frame with THIS replica as
                # origin) join the id the client frame started
                with resume_trace(
                    tr[0], tr[1], replica=dst.id, tenant=self.tenant
                ), tracer.span(
                    "replica.deliver", replica=dst.id, peer=src.id
                ):
                    back.extend(dst.server.receive_frames(sess, frame))
            else:
                back.extend(dst.server.receive_frames(sess, frame))
            if key is not None and not sess.dead:
                if not is_update or dst.server._applied.value > before:
                    dst.mark_payload(key)
        return n, nb

    def flow(self) -> Tuple[int, int]:
        """Drain both ends' outboxes into the pending queues, then
        deliver one batch each way.  Returns (frames, bytes) delivered
        — 0 under partition (frames DROP), lag (frames defer), or a
        dead replica (frames discard)."""
        if not (self.a.alive and self.b.alive):
            self._to_a.clear()
            self._to_b.clear()
            return 0, 0
        self._to_b.extend(self.a.server.drain(self.sess_a))
        self._to_a.extend(self.b.server.drain(self.sess_b))
        if self.partitioned:
            n = len(self._to_a) + len(self._to_b)
            if n:
                _FRAMES_DROPPED.labels("partition").inc(n)
            self._to_a.clear()
            self._to_b.clear()
            return 0, 0
        if self.lag_rounds > 0:
            self.lag_rounds -= 1
            return 0, 0
        out_b, self._to_b = self._to_b, []
        out_a, self._to_a = self._to_a, []
        n1, b1 = self._deliver(out_b, self.a, self.b, "b")
        n2, b2 = self._deliver(out_a, self.b, self.a, "a")
        return n1 + n2, b1 + b2


class ReplicaMesh:
    """N replicas fully meshed per tenant, with sharded ownership,
    commitment-verified anti-entropy, and scripted chaos (see module
    docstring).  ``replicas`` is an iterable of ``(id, server)`` pairs;
    tenants join via `ensure_tenant` / `assign_owner` (or lazily on
    `route`)."""

    def __init__(
        self,
        replicas: Iterable[Tuple[str, SyncServer]],
        tenants: Iterable[str] = (),
    ):
        self.replicas: Dict[str, MeshReplica] = {}
        for rid, server in replicas:
            if rid in self.replicas:
                raise ValueError(f"duplicate replica id {rid!r}")
            self.replicas[rid] = MeshReplica(rid, server)
        if len(self.replicas) < 2:
            raise ValueError("a mesh needs at least two replicas")
        self._links: Dict[Tuple[str, str, str], _PeerLink] = {}
        #: tenant -> its links (maintained at link create/delete so the
        #: per-event route() and per-tenant anti-entropy stay O(links of
        #: that tenant), never a scan of the whole mesh)
        self._links_by_tenant: Dict[str, List[_PeerLink]] = {}
        #: tenant -> (owner replica id, ownership epoch)
        self.owner: Dict[str, Tuple[str, int]] = {}
        #: tenant -> the DivergenceFault that quarantined it
        self.quarantined: Dict[str, DivergenceFault] = {}
        #: every divergence ever caught (the chaos-soak assertion surface)
        self.divergences: List[DivergenceFault] = []
        #: (receiver, sender, tenant) -> (ae round, value): probes carry
        #: the anti-entropy round they were sent in, so one deferred by
        #: `replica.lag` and delivered rounds later can never alias as
        #: the current round's answer
        self._commit_inbox: Dict[Tuple[str, str, str], Tuple[int, int]] = {}
        #: replica pairs currently partitioned — the fault is per PAIR,
        #: not per existing link: a link lazily created between a
        #: severed pair (ensure_tenant for a new tenant mid-partition)
        #: must be born partitioned, or frames would cross the split
        self._partitioned_pairs: Set[FrozenSet[str]] = set()
        self._ae_seq = 0
        #: bounded ownership/migration/quarantine event timeline
        #: (ISSUE-15): each entry is {"seq", "t", "kind", "tenant"?, ...}
        #: — the `/snapshot` section `fleet_timeline` and the operator's
        #: "what happened to this tenant" answer
        self.timeline: deque = deque(maxlen=TIMELINE_CAP)
        self._timeline_seq = 0
        #: tenant -> anti-entropy rounds since its last CLEAN pass
        self._conv_lag: Dict[str, int] = {}
        #: replicas cleanly drained for maintenance (ISSUE-16): their
        #: remaining sessions closed with ``reason="drain"`` and the
        #: canary stops scoring them — a subsequent kill is planned
        #: decommissioning, not a failure
        self.decommissioned: Set[str] = set()
        for t in tenants:
            self.ensure_tenant(t)

    # ------------------------------------------------------------ topology

    def _record_event(self, kind: str, tenant: Optional[str] = None,
                      **detail) -> None:
        """Append one control-plane event to the bounded timeline ring."""
        self._timeline_seq += 1
        ev: Dict = {"seq": self._timeline_seq, "t": time.time(), "kind": kind}
        if tenant is not None:
            ev["tenant"] = tenant
        ev.update(detail)
        self.timeline.append(ev)

    def alive(self) -> List[MeshReplica]:
        return [r for r in self.replicas.values() if r.alive]

    def ensure_tenant(self, tenant: str, owner: Optional[str] = None) -> None:
        """Create the tenant's links between every alive replica pair
        and register ownership (default: the first ALIVE replica — a
        tenant created after a failover must not default to the dead
        one, which no handoff would ever correct).  Known tenants
        return in O(1) — replicas never join a live mesh, so a tenant's
        link set only ever shrinks (deaths), never needs re-probing.
        For a KNOWN tenant the ``owner`` argument is ignored —
        `assign_owner` is the ownership-mutation API."""
        if owner is not None and owner not in self.replicas:
            raise KeyError(f"unknown replica {owner!r}")
        if tenant in self.owner:
            return
        if owner is None:
            alive = self.alive()
            owner = alive[0].id if alive else next(iter(self.replicas))
        self.owner[tenant] = (owner, 0)
        ids = [r.id for r in self.alive()]
        by_tenant = self._links_by_tenant.setdefault(tenant, [])
        for i in range(len(ids)):
            for j in range(i + 1, len(ids)):
                key = (ids[i], ids[j], tenant)
                if key not in self._links:
                    link = _PeerLink(
                        self, self.replicas[ids[i]], self.replicas[ids[j]],
                        tenant,
                    )
                    if frozenset((ids[i], ids[j])) in self._partitioned_pairs:
                        link.partitioned = True
                    self._links[key] = link
                    by_tenant.append(link)
        _LINKS.set(len(self._links))

    def _tenant_links(self, tenant: str) -> List[_PeerLink]:
        return [
            link
            for link in self._links_by_tenant.get(tenant, ())
            if link.a.alive and link.b.alive
        ]

    def route(self, tenant: str) -> MeshReplica:
        """The replica that should serve `tenant` right now: its owner,
        or — between a death and the failover handoff — any survivor."""
        self.ensure_tenant(tenant)
        rid, _ = self.owner[tenant]
        rep = self.replicas[rid]
        if rep.alive:
            return rep
        return self.alive()[0]

    def flush_devices(self) -> None:
        for rep in self.alive():
            flush = getattr(rep.server, "flush_device", None)
            if flush is not None:
                flush()

    def preregister_clients(self, client_ids: Iterable[int]) -> None:
        """Intern expected writer ids on every device-backed replica up
        front (the decode/integrate programs specialize on client-table
        SIZE — same rationale as `SoakDriver._preregister_clients`)."""
        ids = list(client_ids)
        for rep in self.alive():
            ing = getattr(rep.server, "ingestor", None)
            if ing is not None:
                for cid in ids:
                    ing.enc.interner.intern(cid)

    # --------------------------------------------------------- frame plane

    def _handle_mesh_frame(
        self, frame: bytes, src: MeshReplica, dst: MeshReplica
    ) -> bool:
        """Intercept mesh-level frames (commit probes, ownership
        handoffs) at the link layer — they never reach a tenant's
        protocol handler."""
        if not frame or frame[0] not in (MSG_COMMIT, MSG_OWNERSHIP):
            return False
        msg = next(message_reader(frame))
        if msg.kind == MSG_COMMIT:
            tenant, value, rnd = decode_commit(msg.body)
            self._commit_inbox[(dst.id, src.id, tenant)] = (rnd, value)
            return True
        if msg.kind == MSG_OWNERSHIP:
            self._apply_handoff(decode_ownership(msg.body))
            return True
        return False

    def _apply_handoff(self, h: OwnershipHandoff) -> bool:
        """Epoch-guarded ownership application: stale (≤ known epoch)
        handoffs are ignored, so replayed or reordered frames can never
        regress the owner map."""
        cur = self.owner.get(h.tenant)
        if cur is not None and h.epoch <= cur[1]:
            return False
        self.owner[h.tenant] = (h.owner, h.epoch)
        self._record_event(
            "ownership", h.tenant, owner=h.owner, epoch=h.epoch
        )
        return True

    def _handoff(self, h: OwnershipHandoff, broadcast: bool = True) -> None:
        with tracer.span(
            "replica.handoff", tenant=h.tenant, owner=h.owner, epoch=h.epoch
        ):
            self._apply_handoff(h)
            if broadcast:
                frame = ownership_message(h).encode_v1()
                for link in self._tenant_links(h.tenant):
                    link.post(frame, link.a)
                    link.post(frame, link.b)

    def assign_owner(self, tenant: str, rid: str) -> int:
        """Shard one tenant onto a replica (typed epoch-bumping handoff,
        broadcast over its links); returns the new epoch."""
        if rid not in self.replicas:
            raise KeyError(f"unknown replica {rid!r}")
        self.ensure_tenant(tenant)
        cur, epoch = self.owner[tenant]
        if cur == rid:
            return epoch
        h = OwnershipHandoff(tenant, rid, epoch + 1)
        self._handoff(h)
        return h.epoch

    # -------------------------------------------------------- chaos faults

    def _fire_fault_sites(self) -> None:
        """The ISSUE-13 `YTPU_FAULTS` sites, fired once per (top-level)
        sync round: `replica.partition` (args ``a=``/``b=``, default
        the first alive pair), `replica.heal` (heal everything),
        `replica.lag` (args ``a=``/``b=``/``rounds=``, default 2), and
        `replica.kill` (args ``replica=``, default the LAST alive;
        ``drain=0`` skips the pre-kill drain → the un-replicated tail is
        lost, for loss-scenario tests)."""
        if not faults.active:
            return
        ids = [r.id for r in self.alive()]
        spec = faults.fire("replica.partition")
        if spec is not None and len(ids) >= 2:
            self.partition(
                str(spec.args.get("a", ids[0])),
                str(spec.args.get("b", ids[1])),
            )
        if faults.fire("replica.heal") is not None:
            self.heal()
        spec = faults.fire("replica.lag")
        if spec is not None and len(ids) >= 2:
            self.lag(
                str(spec.args.get("a", ids[0])),
                str(spec.args.get("b", ids[1])),
                rounds=int(spec.args.get("rounds", 2)),
            )
        spec = faults.fire("replica.kill")
        if spec is not None and len(ids) >= 2:
            victim = str(spec.args.get("replica", ids[-1]))
            self.kill_replica(victim, drain=bool(spec.args.get("drain", 1)))

    def partition(self, a: str, b: str) -> int:
        """Partition the `a`↔`b` replica pair: every existing link drops
        frames until `heal`, and links created DURING the partition
        (new tenants) are born partitioned too.  Returns the link count
        partitioned."""
        for rid in (a, b):
            if rid not in self.replicas:
                raise KeyError(f"unknown replica {rid!r}")
        pair = frozenset((a, b))
        newly = pair not in self._partitioned_pairs
        self._partitioned_pairs.add(pair)
        n = 0
        for link in self._links.values():
            if link.covers(a) and link.covers(b) and not link.partitioned:
                link.partitioned = True
                n += 1
        if n or newly:
            _PARTITIONS.inc()
            self._record_event("partition", a=a, b=b, links=n)
        return n

    def heal(self, a: Optional[str] = None, b: Optional[str] = None) -> int:
        """Heal partitioned links (all of them, or just the `a`↔`b`
        pair), queueing an SV-resync gossip on each so the next sync
        round repairs what the partition dropped."""
        if a is None and b is not None:
            a, b = b, None  # heal(b=x) means heal(x), not heal-everything
        n = 0
        for link in self._links.values():
            if not link.partitioned:
                continue
            if a is not None and not (link.covers(a) and link.covers(b or a)):
                continue
            link.partitioned = False
            link.gossip()
            n += 1
        if a is None:
            cleared = len(self._partitioned_pairs)
            self._partitioned_pairs.clear()
        else:
            cleared = 0
            for pair in list(self._partitioned_pairs):
                if a in pair and (b is None or b in pair):
                    self._partitioned_pairs.discard(pair)
                    cleared += 1
        if n or cleared:
            _HEALS.inc()
            self._record_event("heal", a=a, b=b, links=n)
        return n

    def lag(self, a: str, b: str, rounds: int = 2) -> int:
        """Defer delivery on the `a`↔`b` links for `rounds` flow passes
        (frames queue, nothing is lost) — transit latency, not loss."""
        for rid in (a, b):
            if rid not in self.replicas:
                raise KeyError(f"unknown replica {rid!r}")
        n = 0
        for link in self._links.values():
            if link.covers(a) and link.covers(b):
                link.lag_rounds = max(link.lag_rounds, int(rounds))
                n += 1
        if n:
            _LAGS.inc()
        return n

    # ----------------------------------------------------------- sync plane

    def sync_round(self, max_passes: int = 32, fire_faults: bool = True) -> Dict:
        """Pump every link until quiescent (bounded by `max_passes`),
        flushing device queues between passes so diffs reflect delivered
        updates.  Top-level rounds fire the armed `replica.*` fault
        sites first; internal rounds (drain-before-kill, migration)
        pass ``fire_faults=False``."""
        if fire_faults:
            self._fire_fault_sites()
        _SYNC_ROUNDS.inc()
        t0 = time.perf_counter()
        frames = nbytes = passes = 0
        with tracer.span("replica.sync_round", replicas=len(self.alive())):
            self.flush_devices()
            while passes < max_passes:
                moved = mbytes = 0
                for link in list(self._links.values()):
                    n, nb = link.flow()
                    moved += n
                    mbytes += nb
                passes += 1
                frames += moved
                nbytes += mbytes
                if moved == 0:
                    break
                self.flush_devices()
        _ROUND_MS.set(round((time.perf_counter() - t0) * 1e3, 3))
        _ROUND_FRAMES.set(frames)
        return {"frames": frames, "bytes": nbytes, "passes": passes}

    def _pump_link(self, link: _PeerLink, max_passes: int = 16) -> Tuple[int, int]:
        frames = nbytes = 0
        for _ in range(max_passes):
            n, nb = link.flow()
            if n == 0:
                break
            frames += n
            nbytes += nb
            self.flush_devices()
        return frames, nbytes

    # ---------------------------------------------------------- anti-entropy

    def anti_entropy_round(self, strict: bool = False) -> Dict:
        """One commitment-verified anti-entropy round: per healthy
        (tenant, link), exchange `MSG_COMMIT` probes; on agreement the
        round cost ends there (O(1) per tenant per link — no state is
        flushed or rendered).  On mismatch, pull the SV-diff (gossip +
        pump) and re-compare; a mismatch that SURVIVES equal state
        vectors is a `DivergenceFault`: recorded in `self.divergences`,
        the tenant quarantined (skipped by later rounds until
        `recover_tenant`), surfaced via `health()` — and raised when
        ``strict=True``."""
        _AE_ROUNDS.inc()
        self.flush_devices()
        rep = {
            "tenants": 0, "compared": 0, "mismatches": 0, "pulled": 0,
            "divergences": 0, "unconverged": 0, "bytes": 0,
        }
        self._ae_seq += 1
        rnd = self._ae_seq
        for tenant in sorted(self.owner):
            if tenant in self.quarantined:
                # quarantined tenants are unverifiable by definition —
                # their convergence lag keeps growing until recovery
                self._bump_convergence_lag(tenant, clean=False)
                continue
            rep["tenants"] += 1
            clean = True
            for link in self._tenant_links(tenant):
                if link.partitioned:
                    clean = False
                    continue  # cannot anti-entropy across a partition
                a, b = link.a, link.b
                with tracer.span(
                    "replica.anti_entropy",
                    tenant=tenant, a=a.id, b=b.id, round=rnd,
                ):
                    ca = a.commitment(tenant)
                    cb = b.commitment(tenant)
                    fa = commit_message(tenant, ca, round_=rnd).encode_v1()
                    fb = commit_message(tenant, cb, round_=rnd).encode_v1()
                    link.post(fa, b)
                    link.post(fb, a)
                    _, nb = self._pump_link(link)
                    rep["bytes"] += nb
                    got_b = self._commit_inbox.pop((b.id, a.id, tenant), None)
                    got_a = self._commit_inbox.pop((a.id, b.id, tenant), None)
                    if (
                        got_b is None or got_b[0] != rnd
                        or got_a is None or got_a[0] != rnd
                    ):
                        # probe lost, or a STALE one surfaced (deferred by
                        # replica.lag and delivered rounds late)
                        rep["unconverged"] += 1
                        clean = False
                        continue
                    rep["compared"] += 1
                    if got_b[1] == cb and got_a[1] == ca:
                        continue  # agreement: O(1), done
                    _MISMATCHES.inc()
                    rep["mismatches"] += 1
                    clean = False
                    link.gossip()
                    _, nb = self._pump_link(link)
                    rep["bytes"] += nb
                    rep["pulled"] += 1
                    ca2 = a.commitment(tenant)
                    cb2 = b.commitment(tenant)
                    if ca2 == cb2:
                        continue  # the pull repaired it
                    sva = sorted(a.server.tenant_state_vector(tenant))
                    svb = sorted(b.server.tenant_state_vector(tenant))
                    if sva != svb:
                        rep["unconverged"] += 1  # sync gap, not divergence
                        continue
                    fault = DivergenceFault(tenant, a.id, b.id, ca2, cb2)
                    self.quarantined[tenant] = fault
                    self.divergences.append(fault)
                    _DIVERGENCES.inc()
                    _QUARANTINED.set(len(self.quarantined))
                    self._record_event("quarantine", tenant, a=a.id, b=b.id)
                    rep["divergences"] += 1
                    if strict:
                        raise fault
                    break  # tenant quarantined: skip its remaining links
            self._bump_convergence_lag(tenant, clean=clean)
        _ROUND_MISMATCHES.set(rep["mismatches"])
        _AE_BYTES.inc(rep["bytes"])
        return rep

    def _bump_convergence_lag(self, tenant: str, clean: bool) -> None:
        """Track convergence-lag = anti-entropy rounds since the
        tenant's last CLEAN pass (every healthy link compared and
        agreed).  0 is steady state; a growing value is a tenant the
        mesh cannot currently verify — partition, probe loss, or
        quarantine (`replica.convergence_lag{tenant=}`)."""
        lag = 0 if clean else self._conv_lag.get(tenant, 0) + 1
        self._conv_lag[tenant] = lag
        _CONV_LAG.labels(tenant).set(lag)

    def recover_tenant(self, tenant: str) -> bool:
        """Recovery for a quarantined tenant: authoritative commitment
        rebuild on every alive replica (discarding poisoned incremental
        state), one sync round, then unquarantine iff the rebuilt
        commitments agree (`replica.recoveries`).  Returns success."""
        fault = self.quarantined.pop(tenant, None)
        _QUARANTINED.set(len(self.quarantined))
        self.flush_devices()
        for rep in self.alive():
            rep.commitments.recompute(
                tenant, rep.server.tenant_state_vector(tenant)
            )
        self.sync_round(fire_faults=False)
        vals = {rep.commitment(tenant) for rep in self.alive()}
        ok = len(vals) <= 1
        if not ok:
            if fault is not None:
                self.quarantined[tenant] = fault
                _QUARANTINED.set(len(self.quarantined))
        elif fault is not None:
            _RECOVERIES.inc()
            self._record_event("recovery", tenant)
        return ok

    # -------------------------------------------------- migration / failover

    def migrate_tenant(
        self, tenant: str, to_id: str, free_source_slot: bool = False
    ) -> int:
        """LIVE cross-replica tenant migration (`rebalance_tenant`
        promoted across the mesh): drain so the destination is current,
        broadcast a typed epoch-bumped `OwnershipHandoff`, and — with
        ``free_source_slot=True`` on a device-backed source — release
        the old owner's device slot (`DeviceSyncServer.release_tenant`;
        the tenant stays servable there, host-resident).  Sessions are
        re-routed by whoever routes them (`route`); returns the new
        ownership epoch."""
        dst = self.replicas[to_id]
        if not dst.alive:
            raise ValueError(f"cannot migrate {tenant!r} to dead replica {to_id!r}")
        self.ensure_tenant(tenant)
        src_id, epoch = self.owner[tenant]
        if src_id == to_id:
            return epoch
        with tracer.span(
            "replica.migrate", tenant=tenant, src=src_id, dst=to_id
        ):
            self.sync_round(fire_faults=False)
            h = OwnershipHandoff(tenant, to_id, epoch + 1)
            self._handoff(h)
            self.sync_round(fire_faults=False)
            if free_source_slot:
                src = self.replicas[src_id]
                release = getattr(src.server, "release_tenant", None)
                if src.alive and release is not None:
                    release(tenant)
        _MIGRATIONS.inc()
        self._record_event(
            "migration", tenant, src=src_id, dst=to_id, epoch=h.epoch
        )
        return h.epoch

    def decommission(self, rid: str) -> int:
        """Mark ``rid`` as cleanly drained for maintenance (ISSUE-16):
        one final drain sync round ships its tail, any remaining client
        sessions close with ``net.sessions_dropped{reason="drain"}``
        (clients reconnect to the tenants' new owners — every owned
        tenant should already have been migrated away), and the canary
        prober stops probing it.  After this, `kill_replica` finds zero
        sessions to drop — a drained kill must never count as a
        failover failure.  Returns the sessions closed."""
        if rid not in self.replicas:
            raise KeyError(f"unknown replica {rid!r}")
        rep = self.replicas[rid]
        self.decommissioned.add(rid)
        closed = 0
        if rep.alive:
            self.sync_round(fire_faults=False)
            drop = getattr(rep.server, "drop_sessions", None)
            if drop is not None:
                closed = drop("drain")
        self._record_event("decommission", replica=rid, closed=closed)
        return closed

    def kill_replica(self, rid: str, drain: bool = True) -> int:
        """Forced failover: (optionally) drain the mesh so the victim
        holds nothing unique, mark it dead, drop its sessions with
        `net.sessions_dropped{reason="failover"}`, hand its tenants'
        ownership to the first survivor (typed, epoch-bumped), and close
        the peers' ends of its links.  Returns the sessions dropped.
        ``drain=False`` models an abrupt crash — updates the victim had
        not yet replicated are LOST (CRDT convergence still holds among
        survivors; the soak oracle will show the gap)."""
        if rid not in self.replicas:
            raise KeyError(f"unknown replica {rid!r}")
        rep = self.replicas[rid]
        if not rep.alive:
            return 0
        if len(self.alive()) <= 1:
            raise ValueError(
                f"cannot kill {rid!r}: it is the last alive replica"
            )
        with tracer.span("replica.failover", replica=rid, drain=int(drain)):
            return self._kill_replica(rid, drain)

    def _kill_replica(self, rid: str, drain: bool) -> int:
        rep = self.replicas[rid]
        if drain:
            self.sync_round(fire_faults=False)
        rep.alive = False
        # close BOTH ends of the victim's links first — the victim-side
        # sessions are mesh plumbing, not client sessions, so they must
        # not count as failover drops (the metric's contract is "real
        # sessions that must reconnect to a survivor"); the peer-side
        # ends close so their outboxes don't grow until slow-consumer
        # eviction
        for key, link in list(self._links.items()):
            if not link.covers(rid):
                continue
            if link.a.id == rid:
                mine, other, osess = link.sess_a, link.b, link.sess_b
            else:
                mine, other, osess = link.sess_b, link.a, link.sess_a
            rep.server.disconnect(mine)
            other.server.disconnect(osess)
            del self._links[key]
            self._links_by_tenant[link.tenant].remove(link)
        _LINKS.set(len(self._links))
        dropped = 0
        drop = getattr(rep.server, "drop_sessions", None)
        if drop is not None:
            dropped = drop("failover")
        heirs = [r.id for r in self.alive()]
        for tenant, (owner, epoch) in sorted(self.owner.items()):
            if owner == rid and heirs:
                self._handoff(OwnershipHandoff(tenant, heirs[0], epoch + 1))
        _FAILOVERS.inc()
        self._record_event(
            "failover", replica=rid, dropped=dropped,
            heir=heirs[0] if heirs else None,
        )
        self.sync_round(fire_faults=False)
        return dropped

    # ----------------------------------------------------------- health plane

    def health(self) -> Dict:
        """`/healthz` section (ISSUE-13): quarantined tenants flip the
        probe to degraded (`TelemetryServer.add_health_provider`)."""
        return {
            "replicas": {r.id: r.alive for r in self.replicas.values()},
            "owners": {t: o for t, (o, _e) in sorted(self.owner.items())},
            "quarantined_tenants": sorted(self.quarantined),
            "degraded": bool(self.quarantined),
        }

    def attach_health(self, telemetry) -> None:
        """Register this mesh on a `TelemetryServer`'s `/healthz` (and
        `/snapshot`, same section name)."""
        telemetry.add_health_provider("replica", self.health)
        telemetry.add_provider("replica", self.health)

    def timeline_events(self) -> List[Dict]:
        """The ownership/migration/quarantine event timeline (bounded
        ring, oldest first) — the `/snapshot` section `fleet_timeline`."""
        return list(self.timeline)

    def replica_snapshot(self, rid: str) -> Dict[str, float]:
        """One replica's numeric state for the merged `/fleet`
        exposition.  Registry counters are process-global (in-proc
        replicas share them), so everything here reads SERVER-LOCAL
        state — the per-instance tally `SyncServer.applied_local`, the
        replica's own tenant/session maps, and the shared ownership map
        filtered to this replica."""
        from ytpu.utils.profile import profile_fractions

        rep = self.replicas[rid]
        owned = [t for t, (o, _e) in self.owner.items() if o == rid]
        out = {
            "replica.alive": 1.0 if rep.alive else 0.0,
            "replica.tenants": float(len(rep.server.tenants)),
            "replica.sessions": float(
                sum(len(t.sessions) for t in rep.server.tenants.values())
            ),
            "replica.owned_tenants": float(len(owned)),
            "replica.updates_applied": float(
                getattr(rep.server, "applied_local", 0)
            ),
            "replica.quarantined_owned": float(
                sum(1 for t in owned if t in self.quarantined)
            ),
        }
        # unified wall-time budget per replica (ISSUE-17): in-proc
        # replicas share the process recorder, so the fractions are the
        # process-lifetime window — still the right scrape shape for the
        # merged exposition (one `profile_*_fraction{replica=}` series
        # per bucket), and a cross-process pod reports its own
        out.update(profile_fractions())
        # occupancy/fragmentation aggregates (ISSUE-18): device-backed
        # replicas fold their slot ledger into `/fleet` so one merged
        # scrape ranks replicas by fragmentation; host-only replicas
        # (no ingestor) skip the section rather than report zeros
        ing = getattr(rep.server, "ingestor", None)
        if ing is not None:
            try:
                live, dead, free = ing.capacity_ledger()
                out["capacity.live_rows"] = float(sum(int(x) for x in live))
                out["capacity.dead_rows"] = float(sum(int(x) for x in dead))
                out["capacity.free_rows"] = float(sum(int(x) for x in free))
            except Exception:
                pass  # a mid-teardown device pull must not kill the scrape
        return out

    def attach_telemetry(self, telemetry) -> None:
        """Full fleet-observability attach (ISSUE-15): `/healthz` +
        `/snapshot` health sections (`attach_health`), the event
        timeline as `/snapshot` section ``fleet_timeline``, and one
        `/fleet` source per replica — the merged exposition labels every
        family with ``replica="<id>"``."""
        self.attach_health(telemetry)
        telemetry.add_provider("fleet_timeline", self.timeline_events)
        for rid in self.replicas:
            telemetry.add_fleet_source(
                rid, lambda rid=rid: self.replica_snapshot(rid)
            )


# --------------------------------------------------------------------------
# the original cross-process pod-to-pod bridge, on the hardened transport
# --------------------------------------------------------------------------


class ReplicaLink:
    """Replicate one tenant between a local server and a remote pod over
    TCP.  The link bridges a local in-process `Session` (obtained from
    `SyncServer.connect_frames`, so the local server speaks its own
    greeting — SyncStep1(sv) + awareness snapshot) to the remote pod's
    endpoint (`ytpu.sync.net.serve`); frames flow both ways untouched.
    Because only `connect_frames` / `receive_frames` / `drain` are used,
    the same link replicates a plain host `SyncServer` and a
    device-authoritative `DeviceSyncServer` without special cases.

    Hardened-transport defaults (ISSUE-13 satellite — this path predated
    the PR-6 net work): `connect()` dials with exponential backoff +
    full jitter (`net.connect_retries`), every read runs under the
    whole-frame deadline, and `reconnect()` redials the remembered
    endpoint with a FRESH session whose greeting resyncs via the
    state-vector handshake (`net.reconnects`).  For several replicas in
    one process, prefer `ReplicaMesh` — it adds ownership, commitment
    anti-entropy, and chaos scripting on top of the same frame flow."""

    def __init__(self, server: SyncServer, tenant: str):
        self.server = server
        self.tenant = tenant
        self.session: Optional[Session] = None
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self._endpoint: Optional[Tuple[str, int]] = None

    async def connect(
        self,
        host: str,
        port: int,
        retries: int = 4,
        backoff: float = 0.05,
        backoff_max: float = 2.0,
    ) -> None:
        """Dial the peer pod (retry with backoff + jitter on refusal —
        `net.connect_with_backoff`) and run the symmetric greeting."""
        self.reader, self.writer = await connect_with_backoff(
            host, port, retries=retries, backoff=backoff,
            backoff_max=backoff_max,
        )
        self._endpoint = (host, port)
        write_frame(self.writer, self.tenant.encode("utf-8"))
        # local server's own greeting (SyncStep1 + awareness) goes first —
        # both sides open with step1, per the protocol.rs header contract
        self.session, greeting = self.server.connect_frames(self.tenant)
        for frame in greeting:
            write_frame(self.writer, frame)
        await self.writer.drain()

    async def reconnect(self, **connect_kw) -> None:
        """Reconnect-with-resync after a dropped link (peer death,
        eviction, `FrameTimeout`): tear down transport AND session, then
        redial the remembered endpoint — the fresh greeting's SyncStep1
        carries the local server's CURRENT state vector, so the peer's
        SyncStep2 fills exactly the gap (`net.reconnects`)."""
        if self._endpoint is None:
            raise RuntimeError("reconnect before a successful connect")
        host, port = self._endpoint
        await self.close()
        await self.connect(host, port, **connect_kw)
        # net.py's cached child, NOT a fresh registry lookup: after a
        # test-time metrics.reset() the two would be different objects
        # and the reconnect series would tear across paths
        _RECONNECTS.inc()

    async def pump(
        self,
        max_frames: int = 64,
        timeout: float = 0.2,
        frame_timeout: Optional[float] = FRAME_DEADLINE,
    ) -> int:
        """Process up to `max_frames` inbound frames, then flush outbox.

        Returns the number of frames read. `timeout` bounds the wait for
        each frame's FIRST byte (a quiet peer never blocks the loop);
        `frame_timeout` is the PR-6 whole-frame deadline — a peer that
        stalls mid-frame raises `FrameTimeout` instead of hanging the
        link (`reconnect()` is the recovery).  Raises ConnectionError
        when the peer closed (EOF) or when this link's session was
        evicted as a slow consumer — a silent return in either case
        would leave `run()` busy-spinning / the pods silently
        diverging."""
        n = 0
        pending = None  # wire trace context riding ahead of one frame
        while n < max_frames:
            frame = await read_frame(
                self.reader,
                first_byte_timeout=timeout,
                frame_timeout=frame_timeout,
            )
            if frame is None:
                if self.reader.at_eof():
                    raise ConnectionError("replica peer closed the link")
                break
            tr, pending = pending, None
            if frame and frame[0] == MSG_TRACE:
                # trace-context extension (ISSUE-15): consumed here,
                # applies to the next frame only
                if tracer.enabled:
                    try:
                        _v, tid, origin = decode_trace(
                            next(message_reader(frame)).body
                        )
                        pending = (tid, origin)
                    except Exception:
                        pass
                n += 1
                continue
            if tr is not None and tracer.enabled:
                with resume_trace(tr[0], tr[1], tenant=self.tenant):
                    replies = self.server.receive_frames(self.session, frame)
            else:
                replies = self.server.receive_frames(self.session, frame)
            for reply in replies:
                write_frame(self.writer, reply)
            n += 1
        if self.session is not None and self.session.dead:
            raise ConnectionError(
                "replica link session evicted (outbox overflow); "
                "reconnect and resync via the SyncStep1 greeting"
            )
        await self.flush()
        return n

    async def flush(self) -> None:
        """Ship local broadcasts (other sessions' applies) to the peer."""
        if self.writer is None:
            return
        for payload in self.server.drain(self.session):
            write_frame(self.writer, payload)
        await self.writer.drain()

    async def gossip(self) -> None:
        """Anti-entropy round: advertise the current local SV; the peer
        answers with the SV-diff update (protocol.rs:60-68 semantics)."""
        if self.writer is None:
            return
        write_frame(self.writer, _step1_frame(self.server, self.tenant))
        await self.writer.drain()

    async def run(self, interval: float = 0.05, gossip_every: int = 0) -> None:
        """Continuous replication loop (cancel the task to stop)."""
        rounds = 0
        while True:
            await self.pump(timeout=interval)
            rounds += 1
            if gossip_every and rounds % gossip_every == 0:
                await self.gossip()

    async def close(self) -> None:
        if self.session is not None:
            self.server.disconnect(self.session)
            self.session = None
        if self.writer is not None:
            self.writer.close()
            try:
                await self.writer.wait_closed()
            except Exception:
                pass
            self.writer = None


class Replicator:
    """All of one pod's links to one peer pod (one link per tenant)."""

    def __init__(self, server: SyncServer, host: str, port: int):
        self.server = server
        self.host = host
        self.port = port
        self.links: List[ReplicaLink] = []

    async def add_tenant(self, tenant: str) -> ReplicaLink:
        link = ReplicaLink(self.server, tenant)
        await link.connect(self.host, self.port)
        self.links.append(link)
        return link

    async def pump(self, rounds: int = 1, timeout: float = 0.2) -> int:
        total = 0
        for _ in range(rounds):
            for link in self.links:
                total += await link.pump(timeout=timeout)
        return total

    async def gossip(self) -> None:
        for link in self.links:
            await link.gossip()

    async def close(self) -> None:
        for link in self.links:
            await link.close()
        self.links = []
