"""y-sync protocol: the transport-agnostic sync state machine.

Behavioral parity target: /root/reference/yrs/src/sync/protocol.rs
(`Protocol` trait with default handlers :42-135, message tags :138-147 and
:219-224, `Message`/`SyncMessage` codecs :158-272, `MessageReader` :312-330).

Handshake (protocol.rs header comment): on connect each side sends
SyncStep1(its state vector) + its Awareness snapshot; a SyncStep1 is answered
with SyncStep2(missing update); live changes flow as Update messages.

The batched server loop in `ytpu.sync.server` replaces the reference's
per-connection state machine with per-tenant queues feeding
`apply_update_batch` — the protocol bytes stay identical.
"""

from __future__ import annotations

from typing import Iterator, List, NamedTuple, Optional, Tuple, Union

from ytpu.core import StateVector, Update
from ytpu.encoding.lib0 import Cursor, Writer

from .awareness import Awareness, AwarenessUpdate

__all__ = [
    "MSG_SYNC",
    "MSG_AWARENESS",
    "MSG_AUTH",
    "MSG_QUERY_AWARENESS",
    "MSG_BUSY",
    "MSG_COMMIT",
    "MSG_OWNERSHIP",
    "MSG_TRACE",
    "PROTOCOL_VERSION",
    "TRACE_WIRE_VERSION",
    "busy_message",
    "decode_busy",
    "commit_message",
    "decode_commit",
    "OwnershipHandoff",
    "ownership_message",
    "decode_ownership",
    "trace_message",
    "decode_trace",
    "MSG_SYNC_STEP_1",
    "MSG_SYNC_STEP_2",
    "MSG_SYNC_UPDATE",
    "Message",
    "SyncMessage",
    "message_reader",
    "Protocol",
    "PermissionDenied",
    "UnsupportedMessage",
]

MSG_SYNC = 0
MSG_AWARENESS = 1
MSG_AUTH = 2
MSG_QUERY_AWARENESS = 3
# ytpu extension (ISSUE-9 admission control): a server under overload
# answers an Update with a Busy message instead of silently killing the
# session — body is lib0 [var_uint retry_after_ms][string reason].  Rides
# the generic custom-tag encode/decode path, so peers that predate it see
# an unknown-tag Message they may ignore (SyncClient.pump skips non-sync
# kinds by design).
MSG_BUSY = 4
# ytpu federation extensions (ISSUE-13, server↔server only — the replica
# mesh intercepts these at the link layer; they never reach a tenant's
# protocol handler):
# - Commit: one tenant's incrementally-maintained state commitment
#   (ytpu/sync/commitment.py), the O(1)-per-tenant anti-entropy probe a
#   peer compares against its own before deciding whether to pull a
#   diff. Body: lib0 [string tenant][var_uint lo32][var_uint hi32]
#   [var_uint round].
# - Ownership: a typed tenant-ownership handoff (live cross-replica
#   migration / failover), epoch-guarded so a stale handoff replayed out
#   of order can never regress the owner map. Body: lib0 [string tenant]
#   [string owner replica id][var_uint epoch].
# Both ride the generic custom-tag path, so pre-federation peers see an
# unknown-tag Message they may ignore.
MSG_COMMIT = 5
MSG_OWNERSHIP = 6
# ytpu fleet-observability extension (ISSUE-15): an optional trace-context
# frame carrying the ambient trace id across replica links and real
# sockets.  A trace frame stands alone and applies to the IMMEDIATELY
# FOLLOWING frame only — transports that understand it re-enter the
# originating `trace_context()` around that next frame, so one Chrome
# trace shows a single update's id from the client frame through the
# owner replica to every peer rebroadcast.  Body: lib0
# [var_uint ext_version][string trace id][string origin replica id].
# Backward compatible on both sides: emission is gated on the peer
# protocol's `version` (old peers are never sent one), and
# `Protocol.handle_message` ignores the tag unconditionally (a stray
# trace frame reaching an old-style handler is dropped, never fatal).
MSG_TRACE = 7

#: current wire-protocol version of this build; `Protocol(version=1)`
#: models a pre-fleet peer (tolerates trace frames, never emits them)
PROTOCOL_VERSION = 2
#: first protocol version whose peers may be sent MSG_TRACE frames
TRACE_WIRE_VERSION = 2
#: version field inside the trace-frame body (room for richer context —
#: baggage, sampling flags — without a new message tag)
TRACE_EXT_VERSION = 1

PERMISSION_DENIED = 0
PERMISSION_GRANTED = 1

MSG_SYNC_STEP_1 = 0
MSG_SYNC_STEP_2 = 1
MSG_SYNC_UPDATE = 2


class PermissionDenied(Exception):
    pass


class UnsupportedMessage(Exception):
    pass


class SyncMessage:
    """One of SyncStep1(sv) / SyncStep2(update bytes) / Update(update bytes)."""

    __slots__ = ("tag", "payload")

    def __init__(self, tag: int, payload):
        self.tag = tag
        self.payload = payload

    @classmethod
    def step1(cls, sv: StateVector) -> "SyncMessage":
        return cls(MSG_SYNC_STEP_1, sv)

    @classmethod
    def step2(cls, update: bytes) -> "SyncMessage":
        return cls(MSG_SYNC_STEP_2, update)

    @classmethod
    def update(cls, update: bytes) -> "SyncMessage":
        return cls(MSG_SYNC_UPDATE, update)

    def encode(self, w: Writer) -> None:
        w.write_var_uint(self.tag)
        if self.tag == MSG_SYNC_STEP_1:
            w.write_buf(self.payload.encode_v1())
        else:
            w.write_buf(self.payload)

    @classmethod
    def decode(cls, cur: Cursor) -> "SyncMessage":
        tag = cur.read_var_uint()
        buf = cur.read_buf()
        if tag == MSG_SYNC_STEP_1:
            return cls(tag, StateVector.decode_v1(buf))
        if tag in (MSG_SYNC_STEP_2, MSG_SYNC_UPDATE):
            return cls(tag, buf)
        raise UnsupportedMessage(f"sync tag {tag}")

    def __eq__(self, other):
        if not isinstance(other, SyncMessage):
            return NotImplemented
        return self.tag == other.tag and self.payload == other.payload

    def __repr__(self):
        names = {0: "SyncStep1", 1: "SyncStep2", 2: "Update"}
        return f"{names.get(self.tag, self.tag)}({self.payload!r})"


class Message:
    """Top-level protocol message (parity: protocol.rs:150-156)."""

    __slots__ = ("kind", "body")

    def __init__(self, kind: int, body):
        self.kind = kind
        self.body = body

    @classmethod
    def sync(cls, msg: SyncMessage) -> "Message":
        return cls(MSG_SYNC, msg)

    @classmethod
    def awareness(cls, update: AwarenessUpdate) -> "Message":
        return cls(MSG_AWARENESS, update)

    @classmethod
    def awareness_query(cls) -> "Message":
        return cls(MSG_QUERY_AWARENESS, None)

    @classmethod
    def auth(cls, deny_reason: Optional[str]) -> "Message":
        return cls(MSG_AUTH, deny_reason)

    @classmethod
    def custom(cls, tag: int, data: bytes) -> "Message":
        return cls(tag, data)

    def encode(self, w: Optional[Writer] = None) -> Writer:
        w = w if w is not None else Writer()
        if self.kind == MSG_SYNC:
            w.write_var_uint(MSG_SYNC)
            self.body.encode(w)
        elif self.kind == MSG_AUTH:
            w.write_var_uint(MSG_AUTH)
            if self.body is not None:
                w.write_var_uint(PERMISSION_DENIED)
                w.write_string(self.body)
            else:
                w.write_var_uint(PERMISSION_GRANTED)
        elif self.kind == MSG_QUERY_AWARENESS:
            w.write_var_uint(MSG_QUERY_AWARENESS)
        elif self.kind == MSG_AWARENESS:
            w.write_var_uint(MSG_AWARENESS)
            w.write_buf(self.body.encode_v1())
        else:
            w.write_u8(self.kind)
            w.write_buf(self.body)
        return w

    def encode_v1(self) -> bytes:
        return self.encode().to_bytes()

    @classmethod
    def decode(cls, cur: Cursor) -> "Message":
        tag = cur.read_var_uint()
        if tag == MSG_SYNC:
            return cls(MSG_SYNC, SyncMessage.decode(cur))
        if tag == MSG_AWARENESS:
            return cls(MSG_AWARENESS, AwarenessUpdate.decode_v1(cur.read_buf()))
        if tag == MSG_AUTH:
            if cur.read_var_uint() == PERMISSION_DENIED:
                return cls(MSG_AUTH, cur.read_string())
            return cls(MSG_AUTH, None)
        if tag == MSG_QUERY_AWARENESS:
            return cls(MSG_QUERY_AWARENESS, None)
        return cls(tag, cur.read_buf())

    def __eq__(self, other):
        if not isinstance(other, Message):
            return NotImplemented
        return self.kind == other.kind and self.body == other.body

    def __repr__(self):
        names = {0: "Sync", 1: "Awareness", 2: "Auth", 3: "AwarenessQuery"}
        return f"Message.{names.get(self.kind, self.kind)}({self.body!r})"


def busy_message(reason: str, retry_after_s: float = 0.0) -> Message:
    """Protocol-level overload reply (ISSUE-9): ``Busy(retry_after_ms,
    reason)``.  Sent instead of applying an update when admission control
    rejects it — the session stays alive and the client may re-send after
    ``retry_after_ms``."""
    w = Writer()
    w.write_var_uint(max(0, int(retry_after_s * 1e3)))
    w.write_string(reason)
    return Message.custom(MSG_BUSY, w.to_bytes())


def decode_busy(body: bytes) -> Tuple[float, str]:
    """(retry_after_s, reason) from a Busy message body."""
    cur = Cursor(body)
    retry_ms = cur.read_var_uint()
    return retry_ms / 1e3, cur.read_string()


def commit_message(tenant: str, commitment: int, round_: int = 0) -> Message:
    """Anti-entropy probe (ISSUE-13): one tenant's 64-bit state
    commitment, split lo/hi so each var_uint stays within 32 bits."""
    w = Writer()
    w.write_string(tenant)
    w.write_var_uint(commitment & 0xFFFFFFFF)
    w.write_var_uint((commitment >> 32) & 0xFFFFFFFF)
    w.write_var_uint(round_)
    return Message.custom(MSG_COMMIT, w.to_bytes())


def decode_commit(body: bytes) -> Tuple[str, int, int]:
    """(tenant, commitment, round) from a Commit message body."""
    cur = Cursor(body)
    tenant = cur.read_string()
    lo = cur.read_var_uint()
    hi = cur.read_var_uint()
    return tenant, (hi << 32) | lo, cur.read_var_uint()


class OwnershipHandoff(NamedTuple):
    """Typed cross-replica tenant-ownership transfer (ISSUE-13): the
    wire record a live migration or a failover broadcasts.  ``epoch``
    is a per-tenant monotonic counter — a receiver applies a handoff
    only when its epoch EXCEEDS the known one, so replayed or
    out-of-order handoffs can never regress ownership."""

    tenant: str
    owner: str  # replica id taking ownership
    epoch: int


def ownership_message(handoff: OwnershipHandoff) -> Message:
    w = Writer()
    w.write_string(handoff.tenant)
    w.write_string(handoff.owner)
    w.write_var_uint(handoff.epoch)
    return Message.custom(MSG_OWNERSHIP, w.to_bytes())


def decode_ownership(body: bytes) -> OwnershipHandoff:
    cur = Cursor(body)
    return OwnershipHandoff(
        cur.read_string(), cur.read_string(), cur.read_var_uint()
    )


def trace_message(trace: str, origin: str = "") -> Message:
    """Trace-context extension frame (ISSUE-15): the ambient trace id
    plus the replica id it is crossing FROM.  Applies to the next frame
    only; see the MSG_TRACE tag comment for the compatibility contract."""
    w = Writer()
    w.write_var_uint(TRACE_EXT_VERSION)
    w.write_string(trace)
    w.write_string(origin)
    return Message.custom(MSG_TRACE, w.to_bytes())


def decode_trace(body: bytes) -> Tuple[int, str, str]:
    """(ext_version, trace id, origin replica id) from a trace body."""
    cur = Cursor(body)
    return cur.read_var_uint(), cur.read_string(), cur.read_string()


def message_reader(data: bytes) -> Iterator[Message]:
    """Iterate over messages packed one after another (parity: MessageReader,
    protocol.rs:312-330)."""
    cur = Cursor(data)
    while cur.has_content():
        yield Message.decode(cur)


class Protocol:
    """Default y-sync handlers (parity: protocol.rs:42-135). Subclass to
    customize (e.g. auth); `handle_message` dispatches one incoming message
    and returns an optional reply.

    ``version`` is the wire-protocol version this peer SPEAKS — it gates
    what extensions other endpoints may send it (a ``version=1`` peer is
    never sent MSG_TRACE frames).  Tolerance is not gated: every Protocol
    ignores stray trace frames regardless of version, which is what lets
    a trace-annotated stream round-trip through an old peer unharmed."""

    def __init__(self, version: int = PROTOCOL_VERSION):
        self.version = version

    def start(self, awareness: Awareness) -> bytes:
        """Connection opening: SyncStep1(local sv) + awareness snapshot."""
        return b"".join(self.start_messages(awareness))

    def start_messages(self, awareness: Awareness) -> List[bytes]:
        """`start`, one bytes object per message (for framed transports).

        Subclasses overriding `start()` (the historical hook) still take
        effect: their concatenated greeting ships as one frame —
        `message_reader` on the receiving side handles both shapes. The
        `_in_start` guard keeps `super().start()` delegation from
        recursing (base `start` itself routes through this method)."""
        if type(self).start is not Protocol.start and not getattr(
            self, "_in_start", False
        ):
            self._in_start = True
            try:
                return [self.start(awareness)]
            finally:
                self._in_start = False
        sv = awareness.doc.state_vector()
        return [
            Message.sync(SyncMessage.step1(sv)).encode_v1(),
            Message.awareness(awareness.update()).encode_v1(),
        ]

    def handle_sync_step1(
        self, awareness: Awareness, sv: StateVector
    ) -> Optional[Message]:
        update = awareness.doc.encode_state_as_update_v1(sv)
        return Message.sync(SyncMessage.step2(update))

    def handle_sync_step2(
        self, awareness: Awareness, update: bytes
    ) -> Optional[Message]:
        awareness.doc.apply_update_v1(update)
        return None

    def handle_update(self, awareness: Awareness, update: bytes) -> Optional[Message]:
        return self.handle_sync_step2(awareness, update)

    def handle_auth(
        self, awareness: Awareness, deny_reason: Optional[str]
    ) -> Optional[Message]:
        if deny_reason is not None:
            raise PermissionDenied(deny_reason)
        return None

    def handle_awareness_query(self, awareness: Awareness) -> Optional[Message]:
        return Message.awareness(awareness.update())

    def handle_awareness_update(
        self, awareness: Awareness, update: AwarenessUpdate
    ) -> Optional[Message]:
        awareness.apply_update(update)
        return None

    def missing_handle(
        self, awareness: Awareness, tag: int, data: bytes
    ) -> Optional[Message]:
        raise UnsupportedMessage(f"message tag {tag}")

    def handle_message(self, awareness: Awareness, msg: Message) -> Optional[Message]:
        if msg.kind == MSG_SYNC:
            sub: SyncMessage = msg.body
            if sub.tag == MSG_SYNC_STEP_1:
                return self.handle_sync_step1(awareness, sub.payload)
            if sub.tag == MSG_SYNC_STEP_2:
                return self.handle_sync_step2(awareness, sub.payload)
            return self.handle_update(awareness, sub.payload)
        if msg.kind == MSG_AUTH:
            return self.handle_auth(awareness, msg.body)
        if msg.kind == MSG_QUERY_AWARENESS:
            return self.handle_awareness_query(awareness)
        if msg.kind == MSG_AWARENESS:
            return self.handle_awareness_update(awareness, msg.body)
        if msg.kind == MSG_TRACE:
            # forward-compat contract: trace frames are advisory context,
            # never content — any handler that sees one (transports
            # normally intercept them first) drops it without reply
            return None
        return self.missing_handle(awareness, msg.kind, msg.body)
