"""Device-backed sync server: y-sync tenants fanned into batch engine slots.

This closes the north-star loop (SURVEY §0 / BASELINE): clients speak the
y-sync protocol to `SyncServer`; updates land in the batched engine through
`BatchIngestor` — one `apply_update_batch` dispatch integrates one queued
update per tenant, with the ingestor's pending semantics absorbing
out-of-order arrival per slot without stalling the batch.

Two serving modes:

- mirrored (default, round-1 behavior): host tenant docs remain the
  protocol endpoints (diffs via `Doc.encode_state_as_update_v1`); the
  device batch shadows them. Every update integrates twice — useful when
  host-side observers/types must stay live, but the host is the
  bottleneck.
- **device-authoritative** (`device_authoritative=True`): the device
  batch IS the document store. SyncStep1 is answered from device state
  via `encode_diff_batch` + the pipelined finisher
  (`batch_doc.DiffPipeline`, ISSUE-10; store.rs:204-248 semantics over
  block columns), incoming updates are queued straight to
  the slot without a host apply, and the host tenant doc is demoted to
  an awareness/metadata anchor that never sees document content. This is
  the serving loop where the batch engine adds capacity instead of
  shadowing the host (VERDICT r1 #7).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ytpu.core.state_vector import StateVector
from ytpu.encoding.lib0 import Writer
from ytpu.models.ingest import BatchIngestor
from ytpu.sync.protocol import (
    MSG_SYNC,
    MSG_SYNC_STEP_1,
    Message,
    SyncMessage,
    message_reader,
)
from ytpu.sync.server import DeviceBatchFull, Session, SyncServer

__all__ = ["DeviceBatchFull", "DeviceSyncServer"]


class DeviceSyncServer(SyncServer):
    """A SyncServer whose tenants live in device doc slots.

    `n_docs` bounds the tenant count (one slot per tenant, assigned on
    first touch). Updates accumulate per slot and ship on `flush_device()`
    — call it per request batch, on a timer, or from the serving loop.
    Multi-root tenants (doc.rs:156-228, the reference's normal doc shape)
    are device-resident: the first named root maps onto the implicit
    device branch, later ones anchor through per-doc BLOCK_ROOT_ANCHOR
    rows the ingestor creates from the wire prescan.
    """

    def __init__(
        self,
        n_docs: Optional[int] = None,
        capacity: int = 2048,
        ingestor: Optional[BatchIngestor] = None,
        device_authoritative: bool = False,
        diff_sub_batch: int = 512,
        diff_depth: int = 2,
        telemetry_port: Optional[int] = None,
        shard_docs: bool = False,
        **kwargs,
    ):
        super().__init__(**kwargs)
        if ingestor is None:
            if n_docs is None:
                raise ValueError("pass n_docs or an ingestor")
            ingestor = BatchIngestor(n_docs, capacity, shard_docs=shard_docs)
        # the ingestor is the single source of truth for the slot count
        self.ingestor = ingestor
        # doc-axis sub-batching / sharding knob (ISSUE-20): surfaced in
        # telemetry and threaded into the default ingestor above (an
        # explicitly-passed ingestor keeps its own setting)
        self.shard_docs = bool(getattr(ingestor, "shard_docs", shard_docs))
        self.device_authoritative = device_authoritative
        from ytpu.utils import metrics

        self._diffs_encoded = metrics.counter(
            "sync.diffs_encoded", labelnames=("tenant",)
        )
        self._slots_gauge = metrics.gauge("sync.device_slots_assigned")
        self._queue_depth = metrics.gauge("sync.device_queue_depth")
        self._slot_of: Dict[str, int] = {}
        # pipelined encode/diff driver (ISSUE-10): every SyncStep1 answer
        # and batched fan-out routes through it — single-tenant calls take
        # its inline one-sub-batch path, many-tenant fan-outs overlap
        # device compaction / D2H / native finisher as staged sub-batches
        from ytpu.models.batch_doc import DiffPipeline

        self._diff_pipeline = DiffPipeline(
            sub_batch=diff_sub_batch, depth=diff_depth
        )
        # per-tenant wire root name (the batch engine maps any single-root
        # tenant onto one device branch; the name must round-trip on the
        # wire — doc.rs root branches are keyed by name). Learned from the
        # native wire prescan of every inbound update.
        self._root_names: Dict[str, str] = {}
        # tenants demoted to the host path: a second distinct root name
        # appeared (multi-root tenants — doc.rs:156-228's normal shape —
        # exceed the single-root device scope, so they are served from the
        # host doc instead of being silently aliased onto one root)
        self._host_tenants: set = set()
        # slot allocation: next fresh slot + slots reclaimed by demotions
        self._next_slot = 0
        self._free_slots: List[int] = []
        self._queues: List[List[bytes]] = [
            [] for _ in range(ingestor.n_docs)
        ]
        # per-queued-update request trace ids, in lockstep with _queues
        # (ISSUE-11): the device-dispatch span names the requests whose
        # updates it ships, closing the net → admission → dispatch chain
        self._queue_traces: List[List[Optional[str]]] = [
            [] for _ in range(ingestor.n_docs)
        ]
        self._last_dispatch = metrics.gauge("sync.last_dispatch_unix")
        # live telemetry plane (ISSUE-11): `telemetry_port` starts the
        # scrapeable HTTP endpoint on its own daemon thread (0 = any
        # free port; None = off). docs/observability.md §Live telemetry.
        self.telemetry = None
        if telemetry_port is not None:
            from ytpu.utils.telemetry import TelemetryServer

            self.telemetry = TelemetryServer(port=telemetry_port)
            self.telemetry.add_provider("server", self._telemetry_provider)
            self.telemetry.start()

    def _telemetry_provider(self) -> Dict:
        """`/snapshot` extras: the serving-side state a scraper wants
        next to the raw metrics (JSON-safe, lock-free reads), plus the
        per-tenant occupancy/fragmentation ledger (ISSUE-18) — one
        scrape-time device pull per snapshot, never on the serve path."""
        out = {
            "tenants": len(self.tenants),
            "slots_assigned": len(self._slot_of),
            "n_docs": self.ingestor.n_docs,
            "queued_updates": self.pending_device_updates(),
            "device_authoritative": self.device_authoritative,
            "shard_docs": self.shard_docs,
        }
        try:
            out["capacity"] = self.capacity_snapshot()
        except Exception as e:  # scrape must not take the server down
            out["capacity"] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def capacity_snapshot(self) -> Dict:
        """Per-tenant slot-occupancy ledger: live / dead (tombstoned,
        GC-able) / free rows per assigned tenant slot, summing to the
        slot capacity, plus batch-wide totals. Backs the ``capacity``
        section of `/snapshot` and the per-tenant
        ``capacity.tenant_*_rows`` gauges."""
        from ytpu.utils import metrics

        live, dead, free = self.ingestor.capacity_ledger()
        slot_cap = int(live[0] + dead[0] + free[0]) if len(live) else 0
        tenants: Dict[str, Dict] = {}
        live_g = metrics.gauge("capacity.tenant_live_rows", labelnames=("tenant",))
        dead_g = metrics.gauge("capacity.tenant_dead_rows", labelnames=("tenant",))
        free_g = metrics.gauge("capacity.tenant_free_rows", labelnames=("tenant",))
        for name, slot in sorted(self._slot_of.items()):
            row = {
                "slot": slot,
                "live_rows": int(live[slot]),
                "dead_rows": int(dead[slot]),
                "free_rows": int(free[slot]),
                "dead_fraction": round(
                    int(dead[slot])
                    / float(max(int(live[slot]) + int(dead[slot]), 1)),
                    6,
                ),
            }
            tenants[name] = row
            live_g.labels(tenant=name).set(row["live_rows"])
            dead_g.labels(tenant=name).set(row["dead_rows"])
            free_g.labels(tenant=name).set(row["free_rows"])
        return {
            "slot_capacity": slot_cap,
            "live_rows": int(sum(int(x) for x in live)),
            "dead_rows": int(sum(int(x) for x in dead)),
            "free_rows": int(sum(int(x) for x in free)),
            "tenants": tenants,
        }

    def _enqueue(self, slot: int, payload: bytes) -> None:
        """Queue one update for a slot, recording the ambient request
        trace id (None outside a traced request) in lockstep."""
        from ytpu.utils.trace import current_trace_id

        self._queues[slot].append(payload)
        self._queue_traces[slot].append(current_trace_id())

    # --- slot management -------------------------------------------------------

    def slot_of(self, tenant_name: str) -> int:
        """The device slot of an EXISTING tenant (KeyError otherwise)."""
        slot = self._slot_of.get(tenant_name)
        if slot is None:
            raise KeyError(f"tenant {tenant_name!r} has no device slot")
        return slot

    def _assign_slot(self, tenant_name: str) -> int:
        slot = self._slot_of.get(tenant_name)
        if slot is None:
            if self._free_slots:
                slot = self._free_slots.pop()
            elif self._next_slot < self.ingestor.n_docs:
                slot = self._next_slot
                self._next_slot += 1
            else:
                raise DeviceBatchFull(
                    f"device batch is full ({self.ingestor.n_docs} tenant slots)"
                )
            self._slot_of[tenant_name] = slot
            self._slots_gauge.set(len(self._slot_of))
        return slot

    def tenant(self, name: str):
        first_touch = name not in self.tenants
        if first_touch:
            # reserve the slot FIRST: exhaustion must fail before the tenant
            # registers, or retries would create an unmirrored ghost tenant
            self._assign_slot(name)
        t = super().tenant(name)
        if first_touch and not self.device_authoritative:
            # mirrored mode: shadow every host apply into the device queue
            # (device-authoritative tenants queue in receive_frames and
            # never touch the host doc).  The slot is resolved per event,
            # not captured — a live rebalance moves the tenant's slot out
            # from under this observer (ISSUE-9); a demoted host-resident
            # tenant has no slot and mirrors nothing
            def mirror(payload: bytes, origin, txn, _name=name):
                slot = self._slot_of.get(_name)
                if slot is not None:
                    self._enqueue(slot, payload)

            t.awareness.doc.observe_update_v1(mirror)
        return t

    # --- device-authoritative protocol path ------------------------------------

    def connect_frames(self, tenant_name: str):
        if not self.device_authoritative or tenant_name in self._host_tenants:
            return super().connect_frames(tenant_name)
        t = self.tenant(tenant_name)
        self._next_session += 1
        session = Session(self._next_session, tenant_name, self)
        t.sessions.append(session)
        self._sessions_gauge.inc()
        # greeting SyncStep1 carries the DEVICE state vector (flush first
        # so queued updates are reflected in the mirror)
        self.flush_device()
        sv = self.device_state_vector(tenant_name)
        return session, [
            Message.sync(SyncMessage.step1(sv)).encode_v1(),
            Message.awareness(t.awareness.update()).encode_v1(),
        ]

    def receive_frames(self, session: Session, data: bytes) -> List[bytes]:
        """Like `SyncServer.receive_frames`, but malformed-frame errors
        are isolated to the offending session (ISSUE-6): a frame that
        fails to parse or apply marks THIS session dead (`net.bad_frames`
        counter) and returns no replies instead of propagating into the
        serving loop — one hostile peer cannot take down a device batch
        that is serving every other tenant.  Device-step failures raised
        by `flush_device` are NOT caught here: those indict the batch,
        not a session, and keep their flight-recorder dump semantics."""
        try:
            return self._receive_frames_unsafe(session, data)
        except Exception as e:
            from ytpu.utils import metrics, tracer

            metrics.counter("net.bad_frames").inc()
            # the flight-recorder ring keeps WHAT threw (bounded,
            # drop-oldest: a hostile peer can't grow it) — a real
            # server-side bug must stay distinguishable from peer junk
            tracer.instant(
                "net.bad_frame",
                error=repr(e),
                tenant=session.tenant,
                session=session.id,
            )
            self._dropped.labels("bad_frame").inc()
            session.dead = True
            session.outbox = []
            self.disconnect(session)
            return []

    def _receive_frames_unsafe(
        self, session: Session, data: bytes
    ) -> List[bytes]:
        if not self.device_authoritative or session.tenant in self._host_tenants:
            return super().receive_frames(session, data)
        t = self.tenant(session.tenant)
        slot = self.slot_of(session.tenant)
        replies: List[bytes] = []
        msgs = list(message_reader(data))
        for i, msg in enumerate(msgs):
            if msg.kind == MSG_SYNC:
                sub: SyncMessage = msg.body
                if sub.tag == MSG_SYNC_STEP_1:
                    diff = self.device_encode_diff(session.tenant, sub.payload)
                    replies.append(
                        Message.sync(SyncMessage.step2(diff)).encode_v1()
                    )
                else:  # SyncStep2 / Update: straight to the device slot
                    ok, busy = self._admit_update(session)
                    if not ok:
                        if busy is not None:
                            replies.append(busy)
                        if session.dead:
                            break  # shed
                        continue
                    # record the tenant's root names (the first becomes the
                    # wire primary); non-primary roots stay device-resident
                    # via the ingestor's BLOCK_ROOT_ANCHOR rows — multi-root
                    # tenants are served from the batch like any other
                    # (doc.rs:156-228 is the reference's normal doc shape)
                    self._note_roots(session.tenant, sub.payload)
                    self._enqueue(slot, sub.payload)
                    self._applied.inc()
                    t.applied.inc()
                    self.applied_local += 1
                    # broadcast at-least-once (idempotent CRDT updates;
                    # the host path dedups via observer events, the device
                    # path trades that for never touching a host doc)
                    frame = Message.sync(
                        SyncMessage.update(sub.payload)
                    ).encode_v1()
                    tframe = self._trace_frame()
                    for other in t.sessions:
                        if other is not session:
                            if tframe is not None:
                                other.push(tframe)
                            other.push(frame)
                continue
            reply = self.protocol.handle_message(t.awareness, msg)
            if reply is not None:
                replies.append(reply.encode_v1())
        return replies

    @staticmethod
    def _scan_root_names(payload: bytes) -> List[str]:
        """Distinct root-parent names in a wire update, in block order.
        Uses the native columnar prescan (the same C++ pass the ingest
        fast lane runs — microseconds), falling back to the host decoder
        when the native library is absent."""
        from ytpu.native import decode_update_columns

        cols = decode_update_columns(payload)
        names: List[str] = []
        if cols is not None and not cols.error:
            for i in range(cols.n_blocks):
                n = cols.parent_name(i)
                if n and n not in names:
                    names.append(n)
            return names
        from ytpu.core.update import Update

        try:
            up = Update.decode_v1(payload)
        except Exception:
            return names
        for blocks in up.blocks.values():
            for b in blocks:
                p = getattr(b, "parent", None)
                if isinstance(p, str) and p not in names:
                    names.append(p)
        return names

    def _note_roots(self, tenant: str, payload: bytes) -> bool:
        """Record the tenant's root names from one inbound update; True
        when the tenant just turned multi-root (observability only — the
        batch engine anchors non-primary roots per doc, so multi-root
        tenants stay device-resident)."""
        names = self._scan_root_names(payload)
        if not names:
            return False
        known = self._root_names.get(tenant)
        if known is None:
            self._root_names[tenant] = known = names[0]
        if any(n != known for n in names):
            from ytpu.utils import metrics

            metrics.counter("sync.multi_root_tenants").inc()
            return True
        return False

    def _demote_to_host(self, tenant: str) -> None:
        """Escape hatch: move a tenant from its device slot to the host
        path (integrate everything queued, materialize the host doc from
        device state, route through `SyncServer` from now on). No longer
        used for multi-root tenants — the batch engine serves those via
        per-doc root anchors — but kept for operational fallback."""
        self.flush_device()
        doc = self.tenant(tenant).awareness.doc
        diff = self.device_encode_diff(tenant, doc.state_vector())
        self._host_tenants.add(tenant)
        # the apply fires the tenant's broadcast observer once (all
        # sessions receive a full-state update frame — idempotent)
        doc.apply_update_v1(diff)
        # reclaim the device slot for future tenants
        slot = self._slot_of.pop(tenant)
        self._slots_gauge.set(len(self._slot_of))
        self.ingestor.reset_slot(slot)
        self._free_slots.append(slot)

    def _tenant_queue_depth(self, tenant_name: str) -> int:
        """Admission input (ISSUE-9): this tenant's pending device-queue
        depth (0 for unassigned/host tenants — nothing device-bound)."""
        slot = self._slot_of.get(tenant_name)
        return 0 if slot is None else len(self._queues[slot])

    def release_tenant(self, tenant_name: str) -> None:
        """Cross-replica migration support (ISSUE-13): free a tenant's
        device slot after its hot-doc ownership moved to another mesh
        replica.  The tenant stays fully servable — `_demote_to_host`
        materializes the host doc from device state first — so existing
        sessions keep their protocol endpoints while the device slot
        follows ownership (`ReplicaMesh.migrate_tenant(...,
        free_source_slot=True)`).  A no-op for tenants that are already
        host-resident or never held a slot."""
        if tenant_name in self._host_tenants:
            return
        if tenant_name not in self._slot_of:
            return
        self._demote_to_host(tenant_name)

    def rebalance_tenant(
        self, tenant_name: str, to_slot: Optional[int] = None
    ) -> int:
        """Move a tenant to a different device slot LIVE (ISSUE-9): the
        mid-soak rebalance a real multi-tenant pod performs when one
        batch slot runs hot.  Returns the new slot.

        Parity-safe by construction: the tenant's full device state
        (pending stash folded in, exactly `device_encode_diff` vs the
        empty state vector) re-ingests into the fresh slot as one wire
        update, whose host planning rebuilds the slot's SV mirror — so
        the move rides the same exactness contract as any other update.
        Mirrored tenants re-ingest from the authoritative host doc
        instead.  Sessions stay connected (slot identity is server
        internal); queued updates flush first so nothing is re-homed
        mid-queue."""
        from ytpu.utils import metrics

        old = self.slot_of(tenant_name)
        if tenant_name in self._host_tenants:
            raise ValueError(f"tenant {tenant_name!r} is host-resident")
        self.flush_device()
        if self.device_authoritative:
            payload = self.device_encode_diff(tenant_name, StateVector())
        else:
            payload = self.doc(tenant_name).encode_state_as_update_v1()
        # allocate the destination BEFORE releasing the source: a full
        # batch must fail the rebalance, not strand the tenant slotless
        if to_slot is None:
            if self._free_slots:
                to_slot = self._free_slots.pop()
            elif self._next_slot < self.ingestor.n_docs:
                to_slot = self._next_slot
                self._next_slot += 1
            else:
                raise DeviceBatchFull(
                    "no free slot to rebalance into "
                    f"({self.ingestor.n_docs} tenant slots)"
                )
        else:
            if not 0 <= to_slot < self.ingestor.n_docs:
                raise ValueError(
                    f"slot {to_slot} out of range "
                    f"({self.ingestor.n_docs} tenant slots)"
                )
            if any(
                t != tenant_name and s == to_slot
                for t, s in self._slot_of.items()
            ):
                raise ValueError(f"slot {to_slot} is already assigned")
            # claim the explicit destination out of the allocator so a
            # later _assign_slot can never hand it to a second tenant:
            # pull it from the free list, or — when it lies beyond the
            # allocation frontier — advance the frontier past it,
            # freeing the slots skipped over
            if to_slot in self._free_slots:
                self._free_slots.remove(to_slot)
            elif to_slot >= self._next_slot:
                self._free_slots.extend(range(self._next_slot, to_slot))
                self._next_slot = to_slot + 1
        self.ingestor.reset_slot(old)
        if old != to_slot:
            self._free_slots.append(old)
        self._slot_of[tenant_name] = to_slot
        self._enqueue(to_slot, payload)
        self.flush_device()
        metrics.counter("sync.rebalances").inc()
        return to_slot

    def tenant_state_vector(self, tenant_name: str) -> StateVector:
        if not self.device_authoritative or tenant_name in self._host_tenants:
            return super().tenant_state_vector(tenant_name)
        return self.device_state_vector(tenant_name)

    def device_state_vector(self, tenant_name: str) -> StateVector:
        """The device mirror's state vector for one tenant (real ids)."""
        slot = self.slot_of(tenant_name)
        return StateVector(dict(self.ingestor.svs[slot].clocks))

    def _remote_matrix(self, slot_svs) -> "tuple[np.ndarray, int]":
        """One [n_docs, n_clients] remote-clock matrix over interned
        clients (n_clients pow2 to bound `encode_diff_batch` retraces),
        with each (slot, StateVector) pair filling its slot's row."""
        interner = self.ingestor.enc.interner
        n_clients = 1
        while n_clients < max(2, len(interner)):
            n_clients *= 2
        remote = np.zeros((self.ingestor.n_docs, n_clients), dtype=np.int32)
        for slot, sv in slot_svs:
            for client, clock in sv:
                idx = interner.to_idx.get(client)
                if idx is not None and idx < n_clients:
                    remote[slot, idx] = clock
        return remote, n_clients

    def _merge_pending(self, slot: int, payload: bytes) -> bytes:
        """Fold a slot's pending stash into an encoded diff, exactly like
        the reference's merge_pending (transaction.rs:247-263)."""
        ing = self.ingestor
        pending = ing.pending_update(slot)
        pending_ds = ing.pending_ds(slot)
        if pending is None and pending_ds is None:
            return payload
        from ytpu.compat import merge_updates
        from ytpu.core.update import Update as _U

        extras = []
        if pending is not None:
            extras.append(pending.encode_v1())
        if pending_ds is not None:
            # stashed delete ranges must reach fresh replicas too
            extras.append(_U({}, pending_ds).encode_v1())
        return merge_updates(payload, *extras)

    def device_encode_diff(
        self, tenant_name: str, remote_sv: StateVector
    ) -> bytes:
        """Sync step 2 answered from device state: `encode_diff_batch`
        masks/offsets on device, the pipelined finisher (`DiffPipeline`,
        ISSUE-10) compacts the shipped rows on device and emits wire
        bytes from ONE packed host tensor, and any pending stash folds in
        exactly like the reference's merge_pending (transaction.rs:
        247-263).  A single tenant takes the pipeline's inline
        one-sub-batch path (no thread hops); `device_encode_diff_many`
        is the fan-out entry that actually overlaps the stages."""
        import jax.numpy as jnp

        from ytpu.models.batch_doc import encode_diff_batch

        self.flush_device()
        ing = self.ingestor
        slot = self.slot_of(tenant_name)
        remote, n_clients = self._remote_matrix([(slot, remote_sv)])
        ship, offsets, _local, deleted = encode_diff_batch(
            ing.state, jnp.asarray(remote), n_clients
        )
        payload = self._diff_pipeline.run(
            ing.state,
            [slot],
            ship,
            offsets,
            deleted,
            ing.enc,
            payloads=ing.payloads,
            root_name=self._root_names.get(tenant_name),
        )[0]
        payload = self._merge_pending(slot, payload)
        self._diffs_encoded.labels(tenant_name).inc()
        return payload

    def device_encode_diff_many(self, requests) -> List[bytes]:
        """Batched sync-step-2 fan-out (ISSUE-10): answer MANY tenants'
        SyncStep1s in one device selection + one pipelined finisher pass
        — the shape a million-user fan-out actually ships.  `requests`
        is an iterable of (tenant_name, StateVector); returns the v1
        payloads in request order.  One request per tenant (two SVs for
        one tenant would collide on the slot's remote-clock row — issue
        separate calls for that)."""
        requests = list(requests)
        if not requests:
            return []
        import jax.numpy as jnp

        from ytpu.models.batch_doc import encode_diff_batch

        self.flush_device()
        ing = self.ingestor
        slots = [self.slot_of(t) for t, _ in requests]
        if len(set(slots)) != len(slots):
            raise ValueError(
                "device_encode_diff_many takes one request per tenant; "
                "duplicate tenants collide on the slot's remote-clock row"
            )
        remote, n_clients = self._remote_matrix(
            [(s, sv) for s, (_, sv) in zip(slots, requests)]
        )
        ship, offsets, _local, deleted = encode_diff_batch(
            ing.state, jnp.asarray(remote), n_clients
        )
        # the native finisher call carries ONE root name: group requests
        # by their tenant's wire root (usually a single group) and run
        # the pipeline per group
        out: List[Optional[bytes]] = [None] * len(requests)
        groups: Dict[Optional[str], List[int]] = {}
        for i, (t, _) in enumerate(requests):
            groups.setdefault(self._root_names.get(t), []).append(i)
        for root, idxs in groups.items():
            res = self._diff_pipeline.run(
                ing.state,
                [slots[i] for i in idxs],
                ship,
                offsets,
                deleted,
                ing.enc,
                payloads=ing.payloads,
                root_name=root,
            )
            for i, p in zip(idxs, res):
                out[i] = self._merge_pending(slots[i], p)
        for t, _ in requests:
            self._diffs_encoded.labels(t).inc()
        return out  # type: ignore[return-value]

    # --- device dispatch -------------------------------------------------------

    def pending_device_updates(self) -> int:
        return sum(len(q) for q in self._queues)

    def flush_device(self, max_steps: Optional[int] = None) -> int:
        """Ship queued updates to the device; one update per slot per step.

        Returns the number of batch steps dispatched. Slots with deeper
        queues keep shipping while others ride as no-ops (the engine's
        padding rows), so a chatty tenant never blocks a quiet one.

        Observability: the `sync.device_queue_depth` gauge tracks the
        total queued updates before/after each flush, and a device-step
        failure dumps the tracer's flight-recorder ring (`YTPU_TRACE`)
        before re-raising — a kernel abort leaves a replayable trace.
        """
        import time as _time

        from ytpu.utils import tracer

        depth_gauge = self._queue_depth
        depth_gauge.set(sum(len(q) for q in self._queues))
        steps = 0
        while any(self._queues) and (max_steps is None or steps < max_steps):
            # peek, apply, THEN pop — a failing step must not drop the other
            # slots' already-dequeued updates. The apply histogram times the
            # real device step here (the SLO metric), not the enqueue.
            payloads = [q[0] if q else None for q in self._queues]
            # dispatch span (ISSUE-11): names the request trace ids whose
            # updates this batch step ships, so the Chrome trace links a
            # frame's net/admission spans to the device dispatch that
            # integrated it (plus the ambient ctx of whoever flushed)
            span = (
                tracer.span(
                    "sync.dispatch",
                    step=steps,
                    traces=[
                        t[0] for t in self._queue_traces if t and t[0]
                    ],
                )
                if tracer.enabled
                else None
            )
            try:
                with self._apply_hist.time():
                    if span is not None:
                        with span:
                            self.ingestor.apply_bytes(payloads)
                    else:
                        self.ingestor.apply_bytes(payloads)
            except Exception as e:
                tracer.dump_on_error(error=e)
                raise
            for q in self._queues:
                if q:
                    q.pop(0)
            for t in self._queue_traces:
                if t:
                    t.pop(0)
            steps += 1
        if steps:
            # only a REAL dispatch refreshes the freshness gauge: the
            # serve loop flushes on every frame/idle tick, and an
            # empty-queue flush must not make /healthz report a device
            # that never dispatched as fresh
            self._last_dispatch.set(_time.time())
        depth_gauge.set(sum(len(q) for q in self._queues))
        return steps

    def device_text(self, tenant_name: str) -> str:
        """The device-side rendering of a tenant's root text (for parity
        checks and serving reads off the batch)."""
        from ytpu.models.batch_doc import get_string

        slot = self.slot_of(tenant_name)
        return get_string(self.ingestor.state, slot, self.ingestor.payloads)

    def device_diff(self, tenant_name: str) -> list:
        """Formatted-run rendering (Text.diff() shape) of a tenant's root
        text straight from the device block columns."""
        from ytpu.models.batch_doc import get_diff

        slot = self.slot_of(tenant_name)
        return get_diff(self.ingestor.state, slot, self.ingestor.payloads)

    def device_tree(self, tenant_name: str) -> dict:
        from ytpu.models.batch_doc import get_tree

        slot = self.slot_of(tenant_name)
        return get_tree(
            self.ingestor.state,
            slot,
            self.ingestor.payloads,
            self.ingestor.enc.keys,
            interner=self.ingestor.enc.interner,
        )
