"""Device-backed sync server: y-sync tenants fanned into batch engine slots.

This closes the north-star loop (SURVEY §0 / BASELINE): clients speak the
y-sync protocol to `SyncServer`; every update a tenant doc applies is also
queued for its device slot and shipped to the batched engine through
`BatchIngestor` — one `apply_update_batch` dispatch integrates one queued
update per tenant. The host tenant docs remain the protocol endpoints
(diffs, awareness, observers); the device batch is the scalable compute
plane over the same wire bytes, with the ingestor's pending semantics
absorbing out-of-order arrival per slot without stalling the batch.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ytpu.models.ingest import BatchIngestor
from ytpu.sync.server import DeviceBatchFull, SyncServer

__all__ = ["DeviceBatchFull", "DeviceSyncServer"]


class DeviceSyncServer(SyncServer):
    """A SyncServer whose tenants mirror into device doc slots.

    `n_docs` bounds the tenant count (one slot per tenant, assigned on
    first touch). Updates accumulate per slot and ship on `flush_device()`
    — call it per request batch, on a timer, or from the serving loop.
    Flagship scope: single-root tenants (the batch encoder maps named
    roots onto one device root branch).
    """

    def __init__(
        self,
        n_docs: Optional[int] = None,
        capacity: int = 2048,
        ingestor: Optional[BatchIngestor] = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        if ingestor is None:
            if n_docs is None:
                raise ValueError("pass n_docs or an ingestor")
            ingestor = BatchIngestor(n_docs, capacity)
        # the ingestor is the single source of truth for the slot count
        self.ingestor = ingestor
        self._slot_of: Dict[str, int] = {}
        self._queues: List[List[bytes]] = [
            [] for _ in range(ingestor.n_docs)
        ]

    # --- slot management -------------------------------------------------------

    def slot_of(self, tenant_name: str) -> int:
        """The device slot of an EXISTING tenant (KeyError otherwise)."""
        slot = self._slot_of.get(tenant_name)
        if slot is None:
            raise KeyError(f"tenant {tenant_name!r} has no device slot")
        return slot

    def _assign_slot(self, tenant_name: str) -> int:
        slot = self._slot_of.get(tenant_name)
        if slot is None:
            if len(self._slot_of) >= self.ingestor.n_docs:
                raise DeviceBatchFull(
                    f"device batch is full ({self.ingestor.n_docs} tenant slots)"
                )
            slot = len(self._slot_of)
            self._slot_of[tenant_name] = slot
        return slot

    def tenant(self, name: str):
        first_touch = name not in self.tenants
        if first_touch:
            # reserve the slot FIRST: exhaustion must fail before the tenant
            # registers, or retries would create an unmirrored ghost tenant
            slot = self._assign_slot(name)
        t = super().tenant(name)
        if first_touch:

            def mirror(payload: bytes, origin, txn, _slot=slot):
                self._queues[_slot].append(payload)

            t.awareness.doc.observe_update_v1(mirror)
        return t

    # --- device dispatch -------------------------------------------------------

    def pending_device_updates(self) -> int:
        return sum(len(q) for q in self._queues)

    def flush_device(self, max_steps: Optional[int] = None) -> int:
        """Ship queued updates to the device; one update per slot per step.

        Returns the number of batch steps dispatched. Slots with deeper
        queues keep shipping while others ride as no-ops (the engine's
        padding rows), so a chatty tenant never blocks a quiet one.
        """
        steps = 0
        while any(self._queues) and (max_steps is None or steps < max_steps):
            # peek, apply, THEN pop — a failing step must not drop the other
            # slots' already-dequeued updates
            payloads = [q[0] if q else None for q in self._queues]
            self.ingestor.apply_bytes(payloads)
            for q in self._queues:
                if q:
                    q.pop(0)
            steps += 1
        return steps

    def device_text(self, tenant_name: str) -> str:
        """The device-side rendering of a tenant's root text (for parity
        checks and serving reads off the batch)."""
        from ytpu.models.batch_doc import get_string

        slot = self.slot_of(tenant_name)
        return get_string(self.ingestor.state, slot, self.ingestor.payloads)

    def device_diff(self, tenant_name: str) -> list:
        """Formatted-run rendering (Text.diff() shape) of a tenant's root
        text straight from the device block columns."""
        from ytpu.models.batch_doc import get_diff

        slot = self.slot_of(tenant_name)
        return get_diff(self.ingestor.state, slot, self.ingestor.payloads)

    def device_tree(self, tenant_name: str) -> dict:
        from ytpu.models.batch_doc import get_tree

        slot = self.slot_of(tenant_name)
        return get_tree(
            self.ingestor.state,
            slot,
            self.ingestor.payloads,
            self.ingestor.enc.keys,
            interner=self.ingestor.enc.interner,
        )
