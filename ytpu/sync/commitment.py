"""Incrementally-updatable per-tenant state commitments (ISSUE-13).

Anti-entropy at federation scale needs cheaper convergence checks than
flushing and comparing full state: with N replicas and T tenants every
round would otherwise render T texts per replica.  Following the Vector
Commitments with Efficient Updates direction (PAPERS.md), each replica
maintains a per-tenant **homomorphic digest of the op lattice** — one
integer a peer can compare in O(1) per tenant per round, updated in
O(delta) as ops integrate, never by walking state.

The commitment is an additive (mod 2^64) fold over clock units: client
``c``'s lattice ``[0, n_c)`` contributes ``A(c)·T(n_c) + B(c)·n_c``
where ``A``/``B`` are per-client mixed constants and ``T(n) = n(n-1)/2``
(the closed form of ``Σ_{j<n} (A(c)·j + B(c))``).  Additivity over
disjoint clock ranges is what makes it *incrementally updatable*: a
delta ``[old, new)`` folds in as ``A·(T(new)−T(old)) + B·(new−old)``
without revisiting history, and the same value is reached regardless of
how the ops were chunked, split, or merged on the way in.

The device twin (``batch_doc.commit_fold_blocks`` → the
``integrate_kernel`` readout word) computes the identical fold, 32-bit
over the packed block columns, as a vectorized reduction inside the
already-dispatched lazy readout — per-block ``A(c)·(s·l + T(l)) + B(c)·l``
sums to the per-client closed form exactly because block rows tile the
lattice (splits/merges/GC conversions preserve ``(client, clock, len)``
coverage).  ``device_commit_of_clocks`` is its pure-Python oracle.

What the commitment can and cannot detect (docs/serving.md §Federation):
it covers the **op lattice** — any replica that missed, dropped, or
fabricated ops disagrees — but NOT content bytes behind an intact
lattice, and NOT tombstone-set divergence between replicas whose SVs
already agree (y-sync step2 ships the full delete set, so that requires
a lost partial delivery).  A mismatch that survives a converged sync is
therefore a *state-tracking* fault — `replica.DivergenceFault` — not a
sync gap.

The ``commit.corrupt`` fault site (docs/robustness.md) fires inside the
incremental fold, XORing one delta: the poisoned tracker disagrees with
every peer forever after (incremental state, nothing re-derives it),
which is exactly the silent-divergence shape the anti-entropy check
exists to catch.  ``recompute`` is the recovery: an authoritative
rebuild from the current state vector.
"""

from __future__ import annotations

from typing import Dict, Iterable, Mapping, Tuple

from ytpu.utils.faults import faults

__all__ = [
    "MASK32",
    "MASK64",
    "TenantCommitments",
    "commitment_of_clocks",
    "device_commit_of_clocks",
    "lattice_term",
    "mix32",
    "mix64",
    "tri",
]

MASK32 = 0xFFFFFFFF
MASK64 = 0xFFFFFFFFFFFFFFFF

#: XOR mask an armed ``commit.corrupt`` spec applies to one incremental
#: delta (overridable per spec via ``xor=``) — any nonzero value works;
#: this one is visible in hex dumps
CORRUPT_XOR = 0x9E3779B97F4A7C15


def tri(n: int) -> int:
    """T(n) = n(n-1)/2 — the sum of clocks below ``n`` (exact int)."""
    return n * (n - 1) // 2


def mix64(x: int) -> int:
    """splitmix64 finalizer: the per-client parameter generator for the
    64-bit host commitment."""
    x = (x + 0x9E3779B97F4A7C15) & MASK64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & MASK64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & MASK64
    return (x ^ (x >> 31)) & MASK64


def mix32(x: int) -> int:
    """32-bit finalizer — MUST stay bit-identical to the jnp/uint32 mix
    in ``batch_doc.commit_fold_blocks`` (the device readout word); this
    is its host-side oracle."""
    x &= MASK32
    x = ((x ^ (x >> 16)) * 0x7FEB352D) & MASK32
    x = ((x ^ (x >> 15)) * 0x846CA68B) & MASK32
    return (x ^ (x >> 16)) & MASK32


def _params64(client: int) -> Tuple[int, int]:
    return mix64(2 * client + 1), mix64(2 * client + 2)


def lattice_term(client: int, lo: int, hi: int) -> int:
    """Contribution of client ``client``'s clock range ``[lo, hi)`` to
    the 64-bit commitment — additive over disjoint ranges."""
    a, b = _params64(client)
    return (a * (tri(hi) - tri(lo)) + b * (hi - lo)) & MASK64


def commitment_of_clocks(clocks: Mapping[int, int]) -> int:
    """Full (non-incremental) 64-bit commitment of a state vector,
    given as ``{client_id: clock}`` — the authoritative rebuild the
    incremental tracker must always agree with."""
    total = 0
    for client, clock in clocks.items():
        total = (total + lattice_term(client, 0, clock)) & MASK64
    return total


def device_commit_of_clocks(clocks: Mapping[int, int]) -> int:
    """Pure-Python oracle of the DEVICE commitment readout word
    (`integrate_kernel.N_READOUT`'s last word): the 32-bit fold
    ``Σ_c mix32(2c+1)·T(n_c) + mix32(2c+2)·n_c`` over the packed
    state's client id space (raw ids on the identity-rank replay path,
    interned indices on the ingest path)."""
    total = 0
    for client, clock in clocks.items():
        a = mix32(2 * client + 1)
        b = mix32(2 * client + 2)
        total = (total + a * tri(clock) + b * clock) & MASK32
    return total


class TenantCommitments:
    """One replica's per-tenant incremental commitment trackers.

    ``refresh(tenant, sv)`` folds the state-vector delta since the last
    call in O(changed clients) and returns the current commitment — the
    value a `ReplicaMesh` anti-entropy round exchanges.  The fold is the
    ``commit.corrupt`` injection site: a fired spec XORs the delta, so
    the tracker silently diverges from its own state (the fault the
    commitment check must catch; a recompute would mask it).
    """

    def __init__(self) -> None:
        self._clocks: Dict[str, Dict[int, int]] = {}
        self._commit: Dict[str, int] = {}

    def get(self, tenant: str) -> int:
        return self._commit.get(tenant, 0)

    def refresh(self, tenant: str, sv: Iterable[Tuple[int, int]]) -> int:
        """Fold ``sv`` (iterable of ``(client, clock)`` — a
        `StateVector` iterates that way) into the tracker; returns the
        commitment.  Clocks only grow under CRDT sync; a clock that
        went BACKWARD (restored-from-checkpoint server) forces an
        authoritative recompute instead of folding garbage."""
        clocks = self._clocks.setdefault(tenant, {})
        items = list(sv)
        if any(clock < clocks.get(client, 0) for client, clock in items):
            return self.recompute(tenant, items)
        delta = 0
        for client, clock in items:
            old = clocks.get(client, 0)
            if clock > old:
                delta = (delta + lattice_term(client, old, clock)) & MASK64
                clocks[client] = clock
        if delta:
            if faults.active:
                spec = faults.fire("commit.corrupt", tenant=tenant)
                if spec is not None:
                    delta ^= int(spec.args.get("xor", CORRUPT_XOR)) & MASK64
            self._commit[tenant] = (
                self._commit.get(tenant, 0) + delta
            ) & MASK64
        return self._commit.get(tenant, 0)

    def recompute(self, tenant: str, sv: Iterable[Tuple[int, int]]) -> int:
        """Authoritative rebuild from scratch — the recovery path for a
        quarantined (divergent) tenant: discards any poisoned
        incremental state."""
        clocks = {client: clock for client, clock in sv}
        self._clocks[tenant] = dict(clocks)
        self._commit[tenant] = commitment_of_clocks(clocks)
        return self._commit[tenant]

    def forget(self, tenant: str) -> None:
        self._clocks.pop(tenant, None)
        self._commit.pop(tenant, None)
