"""y-sync protocol + Awareness + the multi-tenant server loop."""

from .awareness import Awareness, AwarenessUpdate, AwarenessUpdateEntry
from .protocol import (
    Message,
    PermissionDenied,
    Protocol,
    SyncMessage,
    UnsupportedMessage,
    message_reader,
)
from .server import Session, SyncServer


def __getattr__(name: str):
    # lazy: DeviceSyncServer pulls jax + the batch engine; the host-only
    # control plane (protocol, Awareness, SyncServer) must import without it
    if name == "DeviceSyncServer":
        from .device_server import DeviceSyncServer

        return DeviceSyncServer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "Awareness",
    "AwarenessUpdate",
    "AwarenessUpdateEntry",
    "Message",
    "SyncMessage",
    "Protocol",
    "message_reader",
    "PermissionDenied",
    "UnsupportedMessage",
    "SyncServer",
    "DeviceSyncServer",
    "Session",
]
