"""y-sync protocol + Awareness + the multi-tenant server loop."""

from .awareness import Awareness, AwarenessUpdate, AwarenessUpdateEntry
from .protocol import (
    Message,
    PermissionDenied,
    Protocol,
    SyncMessage,
    UnsupportedMessage,
    message_reader,
)
from .server import Session, SyncServer

__all__ = [
    "Awareness",
    "AwarenessUpdate",
    "AwarenessUpdateEntry",
    "Message",
    "SyncMessage",
    "Protocol",
    "message_reader",
    "PermissionDenied",
    "UnsupportedMessage",
    "SyncServer",
    "Session",
]
