"""TCP transport for the y-sync protocol (SURVEY §5.8).

The reference keeps sockets out of the core crate (ecosystem providers —
yrs-warp etc. — supply transports over the transport-agnostic `Protocol`,
sync/protocol.rs:8-31). ytpu ships one batteries-included transport so the
multi-tenant server is usable end to end without extra dependencies:
asyncio TCP with lib0-style framing.

Wire format per connection:
- client → server, first frame: the tenant/room name (UTF-8);
- every frame after that, both directions: one y-sync / Awareness message
  exactly as `Protocol` encodes it;
- a frame is a lib0 var-uint length followed by that many bytes (the same
  `write_buf` layout the protocol messages use internally).

One `SyncServer` (or `DeviceSyncServer`) instance serves all connections;
each connection becomes a `Session`. Replies go straight back; broadcasts
land in the other sessions' outboxes, and every connection handler pushes
its OWN outbox to its socket after each processed frame or `idle_flush`
wakeup (one writer per task — no cross-coroutine drain races). With a
`DeviceSyncServer`, `flush_every` controls how often queued updates ship
to the device batch.
"""

from __future__ import annotations

import asyncio
from typing import Optional, Tuple

from ytpu.encoding.lib0 import EncodingError, Writer
from ytpu.sync.protocol import (
    Message,
    PermissionDenied,
    SyncMessage,
    UnsupportedMessage,
    message_reader,
)
from ytpu.sync.server import DeviceBatchFull, SyncServer
from ytpu.utils import metrics

# transport series (module-cached children: zero lookups per frame)
_FRAMES_IN = metrics.counter("net.frames_in")
_FRAMES_OUT = metrics.counter("net.frames_out")
_BYTES_IN = metrics.counter("net.bytes_in")
_BYTES_OUT = metrics.counter("net.bytes_out")
_CONNECTIONS = metrics.gauge("net.connections")

# protocol-level garbage from a peer tears the connection down quietly
_PEER_ERRORS = (
    asyncio.IncompleteReadError,
    ConnectionError,
    EncodingError,
    UnsupportedMessage,
    PermissionDenied,
    UnicodeDecodeError,
    ValueError,
)

__all__ = ["serve", "SyncClient", "read_frame", "write_frame"]

_MAX_FRAME = 64 * 1024 * 1024


async def read_frame(
    reader: asyncio.StreamReader, first_byte_timeout: Optional[float] = None
) -> Optional[bytes]:
    """One varint-length-prefixed frame; None on clean EOF or first-byte
    timeout.

    The timeout applies ONLY to the first byte: once a frame has started,
    the read runs to completion — cancelling mid-frame would leave
    consumed bytes behind and desync the stream."""
    first = reader.read(1)
    if first_byte_timeout is not None:
        try:
            b = await asyncio.wait_for(first, first_byte_timeout)
        except asyncio.TimeoutError:
            return None
    else:
        b = await first
    if not b:
        return None  # clean EOF between frames
    shift = 0
    size = 0
    header = 0
    while True:
        header += 1
        size |= (b[0] & 0x7F) << shift
        shift += 7
        if b[0] < 0x80:
            break
        if shift > 63:
            raise ConnectionError("oversized frame varint")
        b = await reader.read(1)
        if not b:
            # EOF inside a length prefix is truncation, not a clean close
            raise ConnectionError("eof inside frame header")
    if size > _MAX_FRAME:
        raise ConnectionError(f"frame of {size} bytes exceeds limit")
    data = await reader.readexactly(size)
    _FRAMES_IN.inc()
    # header + payload, matching bytes_out (which counts the framed
    # write): the two series used to disagree by the varint prefix
    _BYTES_IN.inc(header + len(data))
    return data


def write_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    w = Writer()
    w.write_buf(payload)
    buf = w.to_bytes()
    _FRAMES_OUT.inc()
    _BYTES_OUT.inc(len(buf))
    writer.write(buf)


async def serve(
    server: SyncServer,
    host: str = "127.0.0.1",
    port: int = 0,
    flush_every: int = 1,
    idle_flush: float = 0.2,
) -> Tuple[asyncio.AbstractServer, int]:
    """Start serving; returns (asyncio server, bound port).

    `idle_flush`: how long a connection may sit idle before its own queued
    broadcasts are pushed out anyway. Each handler writes ONLY its own
    socket — a broadcast enqueued by another connection's frame (or by an
    in-process write: server-side transaction, replica link) ships on this
    connection's next frame or idle wakeup. One writer per task means no
    two coroutines ever await drain() on the same transport."""

    async def handle(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        session = None
        frames_seen = 0
        _CONNECTIONS.inc()
        try:
            hello = await read_frame(reader)
            if hello is None:
                return
            tenant = hello.decode("utf-8")
            try:
                session, greeting = server.connect_frames(tenant)
            except DeviceBatchFull:
                return  # capacity: reject quietly
            for frame in greeting:
                write_frame(writer, frame)
            await writer.drain()
            while True:
                frame = await read_frame(reader, first_byte_timeout=idle_flush)
                if frame is None:
                    if reader.at_eof():
                        break
                else:
                    for f in server.receive_frames(session, frame):
                        write_frame(writer, f)
                    frames_seen += 1
                    if flush_every and frames_seen % flush_every == 0:
                        flush = getattr(server, "flush_device", None)
                        if flush is not None:
                            flush()
                # own outbox only (frame processed or idle wakeup)
                for payload in server.drain(session):
                    write_frame(writer, payload)
                await writer.drain()
                if session.dead:
                    break  # slow consumer: evicted by Session.push
        except _PEER_ERRORS:
            pass
        finally:
            _CONNECTIONS.dec()
            if session is not None:
                server.disconnect(session)
            writer.close()

    srv = await asyncio.start_server(handle, host, port)
    bound = srv.sockets[0].getsockname()[1]
    return srv, bound


class SyncClient:
    """Minimal asyncio client: sync a local `Doc` with a served tenant.

    The client half of the handshake (sync/protocol.rs default handlers):
    send SyncStep1, answer the server's SyncStep1 with SyncStep2, apply
    its SyncStep2/Update messages, and push local edits as Updates.
    """

    def __init__(self, doc):
        self.doc = doc
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self._unsub = None

    async def connect(self, host: str, port: int, tenant: str) -> None:
        self.reader, self.writer = await asyncio.open_connection(host, port)
        write_frame(self.writer, tenant.encode("utf-8"))
        write_frame(
            self.writer,
            Message.sync(SyncMessage.step1(self.doc.state_vector())).encode_v1(),
        )
        await self.writer.drain()

        def on_update(payload: bytes, origin, txn) -> None:
            if origin == "net":
                return  # do not echo remote updates back
            write_frame(
                self.writer,
                Message.sync(SyncMessage.update(payload)).encode_v1(),
            )

        self._unsub = self.doc.observe_update_v1(on_update)

    async def pump(self, max_frames: int = 1, timeout: float = 2.0) -> int:
        """Process up to `max_frames` inbound frames; returns the count."""
        n = 0
        while n < max_frames:
            frame = await read_frame(self.reader, first_byte_timeout=timeout)
            if frame is None:
                break
            for msg in message_reader(frame):
                if msg.kind != 0:
                    continue  # presence et al. — not this client's concern
                body = msg.body
                if body.tag == 0:  # server's SyncStep1 → reply SyncStep2
                    diff = self.doc.encode_state_as_update_v1(body.payload)
                    write_frame(
                        self.writer,
                        Message.sync(SyncMessage.step2(diff)).encode_v1(),
                    )
                    await self.writer.drain()
                else:  # SyncStep2 / Update → apply
                    self.doc.apply_update_v1(body.payload, origin="net")
            n += 1
        return n

    async def flush(self) -> None:
        if self.writer is not None:
            await self.writer.drain()

    async def close(self) -> None:
        if self._unsub is not None:
            self._unsub()
            self._unsub = None
        if self.writer is not None:
            self.writer.close()
            try:
                await self.writer.wait_closed()
            except Exception:
                pass
