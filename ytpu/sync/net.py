"""TCP transport for the y-sync protocol (SURVEY §5.8).

The reference keeps sockets out of the core crate (ecosystem providers —
yrs-warp etc. — supply transports over the transport-agnostic `Protocol`,
sync/protocol.rs:8-31). ytpu ships one batteries-included transport so the
multi-tenant server is usable end to end without extra dependencies:
asyncio TCP with lib0-style framing.

Wire format per connection:
- client → server, first frame: the tenant/room name (UTF-8);
- every frame after that, both directions: one y-sync / Awareness message
  exactly as `Protocol` encodes it;
- a frame is a lib0 var-uint length followed by that many bytes (the same
  `write_buf` layout the protocol messages use internally).

One `SyncServer` (or `DeviceSyncServer`) instance serves all connections;
each connection becomes a `Session`. Replies go straight back; broadcasts
land in the other sessions' outboxes, and every connection handler pushes
its OWN outbox to its socket after each processed frame or `idle_flush`
wakeup (one writer per task — no cross-coroutine drain races). With a
`DeviceSyncServer`, `flush_every` controls how often queued updates ship
to the device batch.
"""

from __future__ import annotations

import asyncio
import random
from typing import Optional, Tuple

from ytpu.encoding.lib0 import EncodingError, Writer
from ytpu.sync.protocol import (
    MSG_TRACE,
    Message,
    PermissionDenied,
    SyncMessage,
    UnsupportedMessage,
    decode_trace,
    message_reader,
    trace_message,
)
from ytpu.sync.server import DeviceBatchFull, SyncServer
from ytpu.utils import metrics, trace_context, tracer
from ytpu.utils.faults import faults
from ytpu.utils.trace import current_trace, resume_trace

# transport series (module-cached children: zero lookups per frame)
_FRAMES_IN = metrics.counter("net.frames_in")
_FRAMES_OUT = metrics.counter("net.frames_out")
_BYTES_IN = metrics.counter("net.bytes_in")
_BYTES_OUT = metrics.counter("net.bytes_out")
_CONNECTIONS = metrics.gauge("net.connections")
# resilience series (ISSUE-6, docs/robustness.md)
_FRAME_TIMEOUTS = metrics.counter("net.frame_timeouts")
_BAD_FRAMES = metrics.counter("net.bad_frames")
_CONNECT_RETRIES = metrics.counter("net.connect_retries")
_RECONNECTS = metrics.counter("net.reconnects")
# per-session serving series (ISSUE-9): how many sessions are live right
# now, and — when one drops — WHY, so soak shed decisions are
# attributable from the one-line bench JSON (reasons: "bad_frame" for
# frames that failed to parse/apply, "timeout" for mid-frame stalls,
# "disconnect" for abortive transport closes that sent no bad frame,
# "shed" from admission/slow-consumer eviction in sync/server,
# "update_drop" for policy=drop refusals that keep the session,
# "failover" for sessions a killed replica dropped wholesale — they
# reconnect to a mesh survivor, ISSUE-13)
_SESSIONS_ACTIVE = metrics.gauge("net.sessions_active")
_SESSIONS_DROPPED = metrics.counter(
    "net.sessions_dropped", labelnames=("reason",)
)


class FrameTimeout(ConnectionError):
    """A peer stalled mid-frame past the whole-frame deadline.  The
    stream is desynced by construction (part of the frame was consumed)
    — the connection must be dropped; a reconnect resyncs via the
    state-vector handshake."""


# protocol-level garbage from a peer tears the connection down quietly
# (FrameTimeout is a ConnectionError: a stalled peer is peer-local too)
_PEER_ERRORS = (
    asyncio.IncompleteReadError,
    ConnectionError,
    EncodingError,
    UnsupportedMessage,
    PermissionDenied,
    UnicodeDecodeError,
    ValueError,
)

__all__ = [
    "serve",
    "SyncClient",
    "FrameTimeout",
    "connect_with_backoff",
    "read_frame",
    "write_frame",
]

_MAX_FRAME = 64 * 1024 * 1024

#: whole-frame deadline default: generous enough for a 64 MiB frame on a
#: slow link, small enough that a wedged peer frees its session the same
#: minute (override per call site)
FRAME_DEADLINE = 30.0


async def read_frame(
    reader: asyncio.StreamReader,
    first_byte_timeout: Optional[float] = None,
    frame_timeout: Optional[float] = FRAME_DEADLINE,
) -> Optional[bytes]:
    """One varint-length-prefixed frame; None on clean EOF or first-byte
    timeout.

    `first_byte_timeout` is the idle poll: no frame has started, so
    timing out is clean (None).  `frame_timeout` is the whole-frame
    deadline covering everything AFTER the first byte — a peer that
    stalls mid-frame used to hang the reader forever (the old timeout
    covered only the first byte).  Hitting it raises `FrameTimeout`: the
    partially-consumed frame has desynced the stream, so the connection
    is unusable and must be dropped (counted in `net.frame_timeouts`)."""
    stall = faults.delay_s("net.delay")
    if stall:
        await asyncio.sleep(stall)
    first = reader.read(1)
    if first_byte_timeout is not None:
        try:
            b = await asyncio.wait_for(first, first_byte_timeout)
        except asyncio.TimeoutError:
            return None
    else:
        b = await first
    if not b:
        return None  # clean EOF between frames

    async def rest() -> bytes:
        nonlocal b
        shift = 0
        size = 0
        header = 0
        while True:
            header += 1
            size |= (b[0] & 0x7F) << shift
            shift += 7
            if b[0] < 0x80:
                break
            if shift > 63:
                raise ConnectionError("oversized frame varint")
            b = await reader.read(1)
            if not b:
                # EOF inside a length prefix is truncation, not a clean
                # close
                raise ConnectionError("eof inside frame header")
        if size > _MAX_FRAME:
            raise ConnectionError(f"frame of {size} bytes exceeds limit")
        data = await reader.readexactly(size)
        _FRAMES_IN.inc()
        # header + payload, matching bytes_out (which counts the framed
        # write): the two series used to disagree by the varint prefix
        _BYTES_IN.inc(header + len(data))
        return data

    if frame_timeout is None:
        return await rest()
    try:
        return await asyncio.wait_for(rest(), frame_timeout)
    except asyncio.TimeoutError:
        _FRAME_TIMEOUTS.inc()
        raise FrameTimeout(
            f"peer stalled mid-frame past the {frame_timeout}s deadline"
        ) from None


def write_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    if faults.active:
        if faults.fire("net.drop") is not None:
            return  # injected frame loss: nothing reaches the wire
        if faults.fire("net.truncate") is not None:
            # header + half the payload: the reader sees a started frame
            # that never completes — the whole-frame deadline's shape
            w = Writer()
            w.write_buf(payload)
            buf = w.to_bytes()
            cut = buf[: max(1, len(buf) - max(1, len(payload) // 2))]
            _BYTES_OUT.inc(len(cut))
            writer.write(cut)
            return
    w = Writer()
    w.write_buf(payload)
    buf = w.to_bytes()
    _FRAMES_OUT.inc()
    _BYTES_OUT.inc(len(buf))
    writer.write(buf)


async def serve(
    server: SyncServer,
    host: str = "127.0.0.1",
    port: int = 0,
    flush_every: int = 1,
    idle_flush: float = 0.2,
    frame_deadline: Optional[float] = FRAME_DEADLINE,
) -> Tuple[asyncio.AbstractServer, int]:
    """Start serving; returns (asyncio server, bound port).

    `idle_flush`: how long a connection may sit idle before its own queued
    broadcasts are pushed out anyway. Each handler writes ONLY its own
    socket — a broadcast enqueued by another connection's frame (or by an
    in-process write: server-side transaction, replica link) ships on this
    connection's next frame or idle wakeup. One writer per task means no
    two coroutines ever await drain() on the same transport.

    Error isolation (ISSUE-6): every failure inside one connection's
    handler — peer garbage, a mid-frame stall past `frame_deadline`, or
    an unexpected server-side exception while processing a frame — is
    confined to that session: the session is dropped (and counted in
    `net.bad_frames` when a frame triggered it) while the accept loop
    and every other session keep serving."""

    async def handle(reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        session = None
        frames_seen = 0
        _CONNECTIONS.inc()
        try:
            # the hello needs a FIRST-byte deadline too: frame_timeout
            # only starts after byte one, so a connect-and-say-nothing
            # peer would otherwise pin this handler (and its socket)
            # forever
            hello = await read_frame(
                reader,
                first_byte_timeout=frame_deadline,
                frame_timeout=frame_deadline,
            )
            if hello is None:
                return
            tenant = hello.decode("utf-8")
            try:
                session, greeting = server.connect_frames(tenant)
            except DeviceBatchFull:
                return  # capacity: reject quietly
            _SESSIONS_ACTIVE.inc()
            for frame in greeting:
                write_frame(writer, frame)
            await writer.drain()
            pending_trace = None  # wire trace ctx riding ahead of one frame
            while True:
                frame = await read_frame(
                    reader,
                    first_byte_timeout=idle_flush,
                    frame_timeout=frame_deadline,
                )
                if frame is None:
                    if reader.at_eof():
                        break
                elif frame and frame[0] == MSG_TRACE:
                    # wire trace-context extension (ISSUE-15): consumed
                    # at the transport, applies to the NEXT frame only —
                    # the frame that follows re-enters the sender's
                    # trace instead of minting a fresh id
                    if tracer.enabled:
                        try:
                            _v, _tid, _torigin = decode_trace(
                                next(message_reader(frame)).body
                            )
                            pending_trace = (_tid, _torigin)
                        except Exception:
                            pending_trace = None
                else:
                    # end-to-end request tracing (ISSUE-11): ONE trace id
                    # per inbound frame, carried by the ambient context
                    # through admission → apply/queue → device dispatch →
                    # reply, so a YTPU_TRACE dump shows the frame's full
                    # host-side life. Disabled tracer = shared no-op
                    # context, zero per-frame allocation.  A wire trace
                    # context that preceded this frame resumes the
                    # SENDER's id (ISSUE-15 cross-replica propagation).
                    tr, pending_trace = pending_trace, None
                    if tr is not None and tracer.enabled:
                        tctx = resume_trace(
                            tr[0], tr[1], tenant=tenant, session=session.id
                        )
                    else:
                        tctx = trace_context(tenant=tenant, session=session.id)
                    with tctx:
                        try:
                            with tracer.span("net.frame", bytes=len(frame)):
                                replies = server.receive_frames(
                                    session, frame
                                )
                            with tracer.span(
                                "net.reply", frames=len(replies)
                            ):
                                for f in replies:
                                    write_frame(writer, f)
                        except _PEER_ERRORS:
                            # malformed frame: this session's problem only
                            _BAD_FRAMES.inc()
                            _SESSIONS_DROPPED.labels("bad_frame").inc()
                            break
                        except Exception as e:
                            # a server-side bug triggered by one frame
                            # must not escape into asyncio's exception
                            # handler N times per reconnect storm; the
                            # session drops, the accept loop lives — and
                            # the flight recorder keeps what threw
                            # (bounded ring)
                            _BAD_FRAMES.inc()
                            _SESSIONS_DROPPED.labels("bad_frame").inc()
                            tracer.instant(
                                "net.bad_frame",
                                error=repr(e),
                                tenant=session.tenant,
                                session=session.id,
                            )
                            break
                        frames_seen += 1
                        if flush_every and frames_seen % flush_every == 0:
                            flush = getattr(server, "flush_device", None)
                            if flush is not None:
                                flush()
                # own outbox only (frame processed or idle wakeup)
                for payload in server.drain(session):
                    write_frame(writer, payload)
                await writer.drain()
                if session.dead:
                    break  # slow consumer: evicted by Session.push
        except FrameTimeout:
            # mid-frame stall past the deadline: attributable separately
            # from peer garbage (FrameTimeout IS a ConnectionError, so it
            # must be caught before the generic peer-error band)
            if session is not None:
                _SESSIONS_DROPPED.labels("timeout").inc()
        except _PEER_ERRORS:
            # this band is mostly abortive transport closes (RST, EOF
            # inside a header) — a real malformed FRAME is counted
            # bad_frame at the receive loop above; conflating the two
            # would mis-attribute plain peer deaths in a churny soak
            if session is not None:
                _SESSIONS_DROPPED.labels("disconnect").inc()
        finally:
            _CONNECTIONS.dec()
            if session is not None:
                _SESSIONS_ACTIVE.dec()
                server.disconnect(session)
            writer.close()

    srv = await asyncio.start_server(handle, host, port)
    bound = srv.sockets[0].getsockname()[1]
    return srv, bound


async def connect_with_backoff(
    host: str,
    port: int,
    retries: int = 4,
    backoff: float = 0.05,
    backoff_max: float = 2.0,
) -> Tuple[asyncio.StreamReader, asyncio.StreamWriter]:
    """`asyncio.open_connection` under the hardened-transport defaults
    (ISSUE-6): a refused/unreachable connect retries up to `retries`
    times with exponential backoff + full jitter (`backoff`·2^k capped
    at `backoff_max`, each × U[0.5, 1.5)) so a thundering herd of
    reconnecting peers spreads out.  Re-attempts count in
    `net.connect_retries`.  Shared by `SyncClient.connect` and the
    replica-mesh links (`ytpu.sync.replica`), so client and
    server↔server dialing can never drift apart."""
    delay = backoff
    attempt = 0
    while True:
        try:
            return await asyncio.open_connection(host, port)
        except OSError:
            if attempt >= retries:
                raise
            attempt += 1
            _CONNECT_RETRIES.inc()
            await asyncio.sleep(delay * (0.5 + random.random()))
            delay = min(delay * 2, backoff_max)


class SyncClient:
    """Minimal asyncio client: sync a local `Doc` with a served tenant.

    The client half of the handshake (sync/protocol.rs default handlers):
    send SyncStep1, answer the server's SyncStep1 with SyncStep2, apply
    its SyncStep2/Update messages, and push local edits as Updates.
    """

    def __init__(self, doc):
        self.doc = doc
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self._unsub = None
        self._endpoint: Optional[Tuple[str, int, str]] = None

    async def connect(
        self,
        host: str,
        port: int,
        tenant: str,
        retries: int = 4,
        backoff: float = 0.05,
        backoff_max: float = 2.0,
    ) -> None:
        """Open the connection and start the handshake.

        A refused/unreachable connect retries up to `retries` times with
        exponential backoff + full jitter (`backoff`·2^k, capped at
        `backoff_max`, each multiplied by U[0.5, 1.5)) so a thundering
        herd of reconnecting clients spreads out (`net.connect_retries`
        counts the re-attempts).  The SyncStep1 sent here carries the
        doc's CURRENT state vector, so the same call is the resync path:
        after a reconnect the server's SyncStep2 fills exactly the gap."""
        self.reader, self.writer = await connect_with_backoff(
            host, port, retries=retries, backoff=backoff,
            backoff_max=backoff_max,
        )
        self._endpoint = (host, port, tenant)
        write_frame(self.writer, tenant.encode("utf-8"))
        write_frame(
            self.writer,
            Message.sync(SyncMessage.step1(self.doc.state_vector())).encode_v1(),
        )
        await self.writer.drain()

        def on_update(payload: bytes, origin, txn) -> None:
            if origin == "net":
                return  # do not echo remote updates back
            if tracer.enabled:
                # ship the ambient trace id ahead of the update
                # (ISSUE-15): the server resumes it around the apply,
                # and every peer rebroadcast carries it onward
                ctx = current_trace()
                if ctx is not None:
                    write_frame(
                        self.writer,
                        trace_message(
                            str(ctx.get("trace", "")),
                            str(ctx.get("replica", "") or ""),
                        ).encode_v1(),
                    )
            write_frame(
                self.writer,
                Message.sync(SyncMessage.update(payload)).encode_v1(),
            )

        self._unsub = self.doc.observe_update_v1(on_update)

    async def reconnect(self, **connect_kw) -> None:
        """Reconnect-with-resync after a dropped/desynced connection
        (FrameTimeout, eviction, transport error): tear down the old
        transport and redo `connect` to the remembered endpoint — the
        state-vector handshake pulls whatever this client missed while
        disconnected, and pending local edits re-ship on the doc's next
        update (counted in `net.reconnects`)."""
        if self._endpoint is None:
            raise RuntimeError("reconnect before a successful connect")
        host, port, tenant = self._endpoint
        await self.close()
        await self.connect(host, port, tenant, **connect_kw)
        # counted only once connect() succeeded: the metric's contract
        # is reconnect-with-resync, not reconnect attempts
        _RECONNECTS.inc()

    async def pump(
        self,
        max_frames: int = 1,
        timeout: float = 2.0,
        frame_timeout: Optional[float] = FRAME_DEADLINE,
    ) -> int:
        """Process up to `max_frames` inbound frames; returns the count.

        `timeout` is the idle first-byte poll (no frame = return early);
        `frame_timeout` is the whole-frame deadline — a server that
        stalls mid-frame raises `FrameTimeout` instead of hanging this
        client forever (reconnect() is the recovery)."""
        n = 0
        while n < max_frames:
            frame = await read_frame(
                self.reader,
                first_byte_timeout=timeout,
                frame_timeout=frame_timeout,
            )
            if frame is None:
                break
            for msg in message_reader(frame):
                if msg.kind != 0:
                    continue  # presence et al. — not this client's concern
                body = msg.body
                if body.tag == 0:  # server's SyncStep1 → reply SyncStep2
                    diff = self.doc.encode_state_as_update_v1(body.payload)
                    write_frame(
                        self.writer,
                        Message.sync(SyncMessage.step2(diff)).encode_v1(),
                    )
                    await self.writer.drain()
                else:  # SyncStep2 / Update → apply
                    self.doc.apply_update_v1(body.payload, origin="net")
            n += 1
        return n

    async def flush(self) -> None:
        if self.writer is not None:
            await self.writer.drain()

    async def close(self) -> None:
        if self._unsub is not None:
            self._unsub()
            self._unsub = None
        if self.writer is not None:
            self.writer.close()
            try:
                await self.writer.wait_closed()
            except Exception:
                pass
