"""Multi-tenant sync server loop (host control plane).

The reference is a library: its "server" is whatever embeds the y-sync
`Protocol` per connection (ecosystem crates like yrs-warp; see
/root/reference/yrs/src/sync/protocol.rs:8-31 for the handshake contract).
ytpu ships the batched equivalent as a first-class component: one server
hosts many tenant docs, terminates the y-sync protocol per (tenant, session),
and broadcasts document/awareness changes to subscribed sessions.

Transport-agnostic: callers pump bytes via `connect` / `receive` and deliver
the returned frames. The in-process tests drive it directly; a DCN/gRPC
frontend feeds the same loop; updates applied here can be mirrored into
`ytpu.models.batch_doc` slots for device-side fan-in (round-2 wiring).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ytpu.core import Doc
from ytpu.utils import trace_span
from ytpu.utils.trace import current_trace, tracer

from .awareness import Awareness
from .protocol import (
    TRACE_WIRE_VERSION,
    Message,
    Protocol,
    SyncMessage,
    message_reader,
    trace_message,
)

__all__ = ["DeviceBatchFull", "SyncServer", "Session"]


class DeviceBatchFull(RuntimeError):
    """All tenant slots of a device-backed server's batch are assigned."""


class Session:
    __slots__ = (
        "id", "tenant", "server", "outbox", "dead", "mesh_link",
        "_depth_gauge",
    )

    #: broadcast frames a session may hold undelivered before it is
    #: declared a slow consumer and evicted (its transport handler sees
    #: `dead` and closes). Unbounded outboxes let one stalled TCP peer
    #: grow server memory without limit while its tenant stays busy.
    OUTBOX_CAP = 4096

    def __init__(self, id_: int, tenant: str, server: "SyncServer"):
        self.id = id_
        self.tenant = tenant
        self.server = server
        self.outbox: List[bytes] = []
        self.dead = False
        # mesh-internal sessions (peer replication links) are not client
        # traffic: admission must never Busy-refuse them, or replication
        # under a tight client bound silently diverges (ISSUE-16)
        self.mesh_link = False
        # cached gauge child: the push hot path updates a high-water mark
        # with one O(1) call, no name lookups (SURVEY §5.5)
        self._depth_gauge = server._outbox_depth

    def push(self, frame: bytes) -> None:
        """Queue a broadcast frame, evicting the session when it is too
        far behind. Dead sessions drop frames (their connection is about
        to close; a reconnect resyncs via SyncStep1)."""
        if self.dead:
            return
        self.outbox.append(frame)
        self._depth_gauge.set_max(len(self.outbox))
        if len(self.outbox) > self.OUTBOX_CAP:
            self.dead = True
            self.outbox = []
            self.server._evictions.inc()
            # a slow-consumer eviction is a shed: attributable in the
            # per-reason drop series next to admission sheds (ISSUE-9)
            self.server._dropped.labels("shed").inc()


class _Tenant:
    __slots__ = ("awareness", "sessions", "applied")

    def __init__(self, doc: Doc):
        self.awareness = Awareness(doc)
        self.sessions: List[Session] = []
        self.applied = None  # per-tenant labeled counter child (set by server)


class SyncServer:
    def __init__(self, protocol: Optional[Protocol] = None, doc_factory=None):
        from ytpu.utils import metrics

        self.protocol = protocol or Protocol()
        self.tenants: Dict[str, _Tenant] = {}
        self._doc_factory = doc_factory or (lambda name: Doc())
        self._next_session = 0
        self._apply_hist = metrics.histogram("sync.apply_update")
        self._applied = metrics.counter("sync.updates_applied")
        # per-tenant apply series (labeled family; children cached per
        # tenant at first touch) + session/queue-depth gauges
        self._tenant_applied = metrics.counter(
            "sync.tenant_updates_applied", labelnames=("tenant",)
        )
        self._sessions_gauge = metrics.gauge("sync.sessions")
        self._outbox_depth = metrics.gauge("sync.outbox_depth")
        self._evictions = metrics.counter("sync.slow_consumer_evictions")
        # per-reason session-drop attribution (ISSUE-9 satellite; shared
        # family with sync/net.py so transport- and server-layer drops
        # land in one series)
        self._dropped = metrics.counter(
            "net.sessions_dropped", labelnames=("reason",)
        )
        self._busy_replies = metrics.counter("sync.busy_replies")
        #: per-INSTANCE applied count (the registry counters above are
        #: process-global — N in-proc mesh replicas share them, so the
        #: `/fleet` per-replica exposition needs a server-local tally)
        self.applied_local = 0
        #: optional `ytpu.serving.AdmissionController` consulted per
        #: inbound update; None (default) admits everything — the
        #: pre-ISSUE-9 behavior, zero cost on the hot path
        self.admission = None

    # --- tenant / doc management ----------------------------------------------

    def tenant(self, name: str) -> _Tenant:
        t = self.tenants.get(name)
        if t is None:
            doc = self._doc_factory(name)
            t = _Tenant(doc)
            t.applied = self._tenant_applied.labels(name)
            self.tenants[name] = t
            # live update broadcast: one observer per tenant doc
            def broadcast(payload: bytes, origin, txn, _name=name):
                frame = Message.sync(SyncMessage.update(payload)).encode_v1()
                tframe = self._trace_frame()
                for session in self.tenants[_name].sessions:
                    if origin is not session:
                        if tframe is not None:
                            session.push(tframe)
                        session.push(frame)

            doc.observe_update_v1(broadcast)
        return t

    def _trace_frame(self) -> Optional[bytes]:
        """The wire trace-context frame to push IMMEDIATELY BEFORE a
        rebroadcast update (ISSUE-15), or None when tracing is off / no
        request context is ambient / this server speaks a pre-trace
        protocol version (emission is version-gated; tolerance is not)."""
        if not tracer.enabled:
            return None
        if getattr(self.protocol, "version", 1) < TRACE_WIRE_VERSION:
            return None
        ctx = current_trace()
        if ctx is None:
            return None
        return trace_message(
            str(ctx.get("trace", "")), str(ctx.get("replica", "") or "")
        ).encode_v1()

    def doc(self, name: str) -> Doc:
        return self.tenant(name).awareness.doc

    def tenant_state_vector(self, name: str):
        """The authoritative state vector for a tenant (host doc here;
        device-backed servers override for device-authoritative slots)."""
        return self.doc(name).state_vector()

    # --- session lifecycle ------------------------------------------------------

    def connect(self, tenant_name: str) -> Tuple[Session, bytes]:
        """Open a session; returns (session, greeting bytes to send)."""
        session, frames = self.connect_frames(tenant_name)
        return session, b"".join(frames)

    def connect_frames(self, tenant_name: str) -> Tuple[Session, List[bytes]]:
        """Like `connect`, but one bytes object per greeting message."""
        t = self.tenant(tenant_name)
        self._next_session += 1
        session = Session(self._next_session, tenant_name, self)
        t.sessions.append(session)
        self._sessions_gauge.inc()
        return session, self.protocol.start_messages(t.awareness)

    def disconnect(self, session: Session) -> None:
        t = self.tenants.get(session.tenant)
        if t and session in t.sessions:
            t.sessions.remove(session)
            self._sessions_gauge.dec()

    def drop_sessions(self, reason: str = "failover") -> int:
        """Kill every live session at once (replica failover, shutdown):
        each is marked dead, disconnected, and counted in
        `net.sessions_dropped{reason=}` — the attribution a federated
        soak needs to prove its sessions actually failed over rather
        than idling (ISSUE-13).  Returns the number dropped; clients
        recover by reconnecting (the state-vector handshake resyncs)."""
        n = 0
        dropped = self._dropped.labels(reason)
        for t in list(self.tenants.values()):
            for session in list(t.sessions):
                session.dead = True
                session.outbox = []
                self.disconnect(session)
                dropped.inc()
                n += 1
        return n

    # --- admission (ISSUE-9) ----------------------------------------------------

    def _tenant_queue_depth(self, tenant_name: str) -> int:
        """Current device-queue depth for a tenant (0 on a host-only
        server — there is no device queue to bound; the rate limiter
        still applies).  `DeviceSyncServer` overrides."""
        return 0

    def _admit_update(self, session: Session):
        """Consult the admission controller for ONE inbound update.

        Returns ``(admitted, reply)``: admitted updates proceed; refused
        ones either carry a Busy ``reply`` (policy "defer"), drop
        silently ("drop"), or shed the session ("shed" — the session is
        marked dead and disconnected, `net.sessions_dropped{reason=
        "shed"}`)."""
        adm = self.admission
        if adm is None or session.mesh_link:
            # peer replication bypasses the client valve: a refused peer
            # update is not load shedding, it is data loss in flight
            return True, None
        from ytpu.serving.admission import Overload

        try:
            adm.admit(
                session.tenant,
                queue_depth=self._tenant_queue_depth(session.tenant),
            )
            return True, None
        except Overload as e:
            if adm.policy == "shed":
                session.dead = True
                session.outbox = []
                self.disconnect(session)
                self._dropped.labels("shed").inc()
                return False, None
            if adm.policy == "drop":
                self._dropped.labels("update_drop").inc()
                return False, None
            self._busy_replies.inc()
            return False, adm.busy_reply(e)

    # --- message pumping --------------------------------------------------------

    def receive(self, session: Session, data: bytes) -> bytes:
        """Process incoming frames; returns direct reply bytes (concatenated).

        Broadcasts to other sessions land in their `outbox`."""
        return b"".join(self.receive_frames(session, data))

    def receive_frames(self, session: Session, data: bytes) -> List[bytes]:
        """Like `receive`, but one bytes object per reply message — framed
        transports (sync/net.py) forward these without re-parsing.

        Observability (SURVEY §5.5): every applied update is counted and its
        apply latency lands in the `sync.apply_update` histogram — the p99 of
        this series is the BASELINE SLO metric."""
        t = self.tenant(session.tenant)
        replies: List[bytes] = []
        hist = self._apply_hist
        applied = self._applied
        for msg in message_reader(data):
            if msg.kind == 0 and msg.body.tag in (1, 2):  # SyncStep2 / Update
                ok, busy = self._admit_update(session)
                if not ok:
                    if busy is not None:
                        replies.append(busy)
                    if session.dead:
                        break  # shed: the transport sees dead and closes
                    continue
                # apply with the session as origin so we don't echo it back
                with hist.time(), trace_span(
                    "apply_update", tenant=session.tenant
                ):
                    t.awareness.doc.apply_update_v1(
                        msg.body.payload, origin=session
                    )
                applied.inc()
                t.applied.inc()
                self.applied_local += 1
                continue
            if msg.kind == 1:  # Awareness: apply + broadcast to others
                t.awareness.apply_update(msg.body)
                frame = Message.awareness(msg.body).encode_v1()
                for other in t.sessions:
                    if other is not session:
                        other.push(frame)
                continue
            reply = self.protocol.handle_message(t.awareness, msg)
            if reply is not None:
                replies.append(reply.encode_v1())
        return replies

    def drain(self, session: Session) -> List[bytes]:
        out = session.outbox
        session.outbox = []
        return out
