"""Awareness — ephemeral per-client presence state.

Behavioral parity target: /root/reference/yrs/src/sync/awareness.rs
(`Awareness` :35, apply semantics with clock precedence + local-state
resurrection :364-470, `AwarenessUpdate` wire form :511-563, pluggable
`Clock` sync/time.rs:5).

Presence is not CRDT data: it's a per-client (clock, json) cell with
last-writer-wins on the clock, a remove-on-null convention, and a liveness
timeout (30s in the y-protocols ecosystem). Device-optional by design — in
the batched engine this is a host-side `[clients] x (clock, json)` table.
"""

from __future__ import annotations

import json as _json
import time as _time
from typing import Any as PyAny, Callable, Dict, List, NamedTuple, Optional

from ytpu.encoding.lib0 import Cursor, Writer

__all__ = ["Awareness", "AwarenessUpdate", "AwarenessUpdateEntry", "AwarenessEvent"]

NULL_STR = "null"
# The y-protocols liveness convention: entries older than this are dropped.
OUTDATED_TIMEOUT_MS = 30_000


class AwarenessUpdateEntry(NamedTuple):
    clock: int
    json: str


class AwarenessUpdate:
    """Serializable snapshot of awareness states (parity: awareness.rs:511-545)."""

    __slots__ = ("clients",)

    def __init__(self, clients: Optional[Dict[int, AwarenessUpdateEntry]] = None):
        self.clients: Dict[int, AwarenessUpdateEntry] = clients or {}

    def encode_v1(self) -> bytes:
        w = Writer()
        w.write_var_uint(len(self.clients))
        for client_id, entry in self.clients.items():
            w.write_var_uint(client_id)
            w.write_var_uint(entry.clock)
            w.write_string(entry.json)
        return w.to_bytes()

    @classmethod
    def decode_v1(cls, data: bytes) -> "AwarenessUpdate":
        cur = Cursor(data)
        n = cur.read_var_uint()
        clients = {}
        for _ in range(n):
            client_id = cur.read_var_uint()
            clock = cur.read_var_uint()
            json = cur.read_string()
            clients[client_id] = AwarenessUpdateEntry(clock, json)
        return cls(clients)

    def __eq__(self, other):
        if not isinstance(other, AwarenessUpdate):
            return NotImplemented
        return self.clients == other.clients


class AwarenessEvent(NamedTuple):
    added: List[int]
    updated: List[int]
    removed: List[int]


class _MetaClientState(NamedTuple):
    clock: int
    last_updated: float  # ms


class Awareness:
    def __init__(self, doc, clock: Optional[Callable[[], float]] = None):
        self.doc = doc
        self.states: Dict[int, str] = {}  # client -> JSON string
        self.meta: Dict[int, _MetaClientState] = {}
        self.on_update_subs: List[Callable] = []
        self.on_change_subs: List[Callable] = []
        self._now = clock or (lambda: _time.time() * 1000.0)

    @property
    def client_id(self) -> int:
        return self.doc.client_id

    # --- local state -----------------------------------------------------------

    def local_state(self) -> Optional[PyAny]:
        raw = self.states.get(self.client_id)
        return _json.loads(raw) if raw is not None else None

    def set_local_state(self, state: PyAny) -> None:
        """Set (or with None: clear) this client's presence."""
        client = self.client_id
        if state is None:
            self.remove_state(client)
            return
        prev = self.meta.get(client)
        clock = (prev.clock if prev else 0) + 1
        json = _json.dumps(state, separators=(",", ":"))
        self._apply_entry(client, clock, json)

    def clean_local_state(self) -> None:
        self.remove_state(self.client_id)

    def remove_state(self, client: int) -> None:
        """Clear a client's state, marking it disconnected (parity:
        awareness.rs:217 remove_state; surfaced as ywasm
        removeAwarenessStates). A DIRECT removal — the local-state
        resurrection guard in `apply_update` only applies to entries
        received from remote peers, never to deliberate local removals.
        The bumped clock makes the removal win precedence at peers."""
        prev = self.meta.get(client)
        clock = (prev.clock if prev else 0) + 1
        self.meta[client] = _MetaClientState(clock, self._now())
        was_present = self.states.pop(client, None) is not None
        if was_present:
            event = AwarenessEvent([], [], [client])
            for cb in list(self.on_change_subs):
                cb(self, event)
            for cb in list(self.on_update_subs):
                cb(self, event)

    def remove_states(self, clients) -> None:
        for client in clients:
            self.remove_state(client)

    # --- wire ------------------------------------------------------------------

    def update(self) -> AwarenessUpdate:
        """Snapshot of all known client states."""
        return self.update_with_clients(list(self.states.keys()))

    def update_with_clients(self, clients) -> AwarenessUpdate:
        out = {}
        for client in clients:
            meta = self.meta.get(client)
            if meta is None:
                continue
            out[client] = AwarenessUpdateEntry(
                meta.clock, self.states.get(client, NULL_STR)
            )
        return AwarenessUpdate(out)

    def apply_update(self, update: AwarenessUpdate) -> Optional[AwarenessEvent]:
        """Parity: awareness.rs:364-470 (clock precedence, null removal,
        local-state resurrection)."""
        added: List[int] = []
        updated: List[int] = []
        removed: List[int] = []
        now = self._now()
        for client_id, entry in update.clients.items():
            clock = entry.clock
            new = None if entry.json == NULL_STR else entry.json
            prev = self.meta.get(client_id)
            if prev is not None:
                is_removed = (
                    prev.clock == clock and new is None and client_id in self.states
                )
                if prev.clock < clock or is_removed:
                    if new is None:
                        if client_id == self.client_id and client_id in self.states:
                            # never let a remote peer remove our own state:
                            # bump the clock and keep it (re-broadcast upstream)
                            clock += 1
                        else:
                            if self.states.pop(client_id, None) is not None:
                                removed.append(client_id)
                    else:
                        updated.append(client_id)
                        self.states[client_id] = new
                    self.meta[client_id] = _MetaClientState(clock, now)
            else:
                self.meta[client_id] = _MetaClientState(clock, now)
                if new is not None:
                    self.states[client_id] = new
                    added.append(client_id)
        if added or updated or removed:
            event = AwarenessEvent(added, updated, removed)
            for cb in list(self.on_change_subs):
                cb(self, event)
            for cb in list(self.on_update_subs):
                cb(self, event)
            return event
        return None

    def _apply_entry(self, client: int, clock: int, json: str) -> None:
        self.apply_update(
            AwarenessUpdate({client: AwarenessUpdateEntry(clock, json)})
        )

    # --- liveness --------------------------------------------------------------

    def remove_outdated(self, timeout_ms: float = OUTDATED_TIMEOUT_MS) -> List[int]:
        """Drop remote entries not refreshed within `timeout_ms`."""
        now = self._now()
        stale = [
            c
            for c, m in self.meta.items()
            if c != self.client_id and now - m.last_updated > timeout_ms
        ]
        removed = []
        for client in stale:
            meta = self.meta[client]
            if client in self.states:
                removed.append(client)
            # removal is modeled as a null update with a bumped clock
            self.apply_update(
                AwarenessUpdate(
                    {client: AwarenessUpdateEntry(meta.clock + 1, NULL_STR)}
                )
            )
        return removed

    # --- observers -------------------------------------------------------------

    def on_update(self, cb: Callable) -> Callable[[], None]:
        self.on_update_subs.append(cb)
        return lambda: self.on_update_subs.remove(cb)

    def on_change(self, cb: Callable) -> Callable[[], None]:
        self.on_change_subs.append(cb)
        return lambda: self.on_change_subs.remove(cb)

    def all_states(self) -> Dict[int, PyAny]:
        return {c: _json.loads(s) for c, s in self.states.items()}
