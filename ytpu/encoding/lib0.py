"""lib0 binary encoding primitives (v1 wire compatibility layer).

This module implements the lib0 encoding conventions used by the Yjs ecosystem
so that ytpu documents are wire-compatible with Yjs/Yrs peers:

- unsigned varints: little-endian 7-bit groups, 0x80 continuation
  (reference behavior: /root/reference/yrs/src/encoding/varint.rs:194-260)
- signed varints: first byte carries 6 payload bits + sign bit 0x40
  (reference behavior: varint.rs:204-281)
- strings: varUint byte-length prefix + UTF-8 payload
- buffers: varUint length prefix + raw bytes
- floats/ints: big-endian fixed width (reference: encoding/read.rs:141-171)
- `Any` values: descending type-tag bytes 127..116
  (reference: /root/reference/yrs/src/any.rs:37-183)

The implementation is written from the wire-format description, tpu-first:
the same byte layout is what the device-side decoder kernels in
`ytpu.ops.decode` parse out of raw u8 buffers in HBM.
"""

from __future__ import annotations

import json
import math
import struct
from typing import Any as PyAny

__all__ = [
    "Cursor",
    "Writer",
    "Undefined",
    "EncodingError",
    "read_any",
    "write_any",
    "any_to_json",
    "any_from_json",
]

F64_MAX_SAFE_INTEGER = 2**53 - 1
F64_MIN_SAFE_INTEGER = -F64_MAX_SAFE_INTEGER


class EncodingError(Exception):
    """Raised on malformed lib0 input (truncated buffer, bad varint, bad tag)."""


class _UndefinedType:
    """JS `undefined` sentinel (distinct from None which maps to JS null)."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self) -> str:
        return "Undefined"

    def __bool__(self) -> bool:
        return False


Undefined = _UndefinedType()


class BigInt(int):
    """Marker for values that must encode with the BigInt tag (122)."""


class Cursor:
    """Read cursor over an immutable byte buffer."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def has_content(self) -> bool:
        return self.pos < len(self.buf)

    def read_u8(self) -> int:
        try:
            b = self.buf[self.pos]
        except IndexError:
            raise EncodingError("end of buffer") from None
        self.pos += 1
        return b

    def read_exact(self, n: int) -> bytes:
        end = self.pos + n
        if end > len(self.buf):
            raise EncodingError("end of buffer")
        out = self.buf[self.pos : end]
        self.pos = end
        return out

    def read_var_uint(self) -> int:
        num = 0
        shift = 0
        while True:
            b = self.read_u8()
            num |= (b & 0x7F) << shift
            shift += 7
            if b < 0x80:
                return num
            if shift > 70:
                raise EncodingError("varint too long")

    def read_var_int(self) -> int:
        """Signed varint: 6 payload bits + sign in the first byte."""
        b = self.read_u8()
        num = b & 0x3F
        negative = (b & 0x40) != 0
        if (b & 0x80) == 0:
            return -num if negative else num
        shift = 6
        while True:
            b = self.read_u8()
            num |= (b & 0x7F) << shift
            shift += 7
            if b < 0x80:
                return -num if negative else num
            if shift > 70:
                raise EncodingError("varint too long")

    def read_var_int_signed(self) -> tuple[int, bool]:
        """Like read_var_int but also reports the raw sign bit (distinguishes -0)."""
        b = self.read_u8()
        num = b & 0x3F
        negative = (b & 0x40) != 0
        if (b & 0x80) == 0:
            return (-num if negative else num), negative
        shift = 6
        while True:
            b = self.read_u8()
            num |= (b & 0x7F) << shift
            shift += 7
            if b < 0x80:
                return (-num if negative else num), negative
            if shift > 70:
                raise EncodingError("varint too long")

    def read_buf(self) -> bytes:
        n = self.read_var_uint()
        return self.read_exact(n)

    def read_string(self) -> str:
        return self.read_buf().decode("utf-8", errors="surrogatepass")

    def read_f32(self) -> float:
        return struct.unpack(">f", self.read_exact(4))[0]

    def read_f64(self) -> float:
        return struct.unpack(">d", self.read_exact(8))[0]

    def read_i64(self) -> int:
        return struct.unpack(">q", self.read_exact(8))[0]

    def read_u64(self) -> int:
        return struct.unpack(">Q", self.read_exact(8))[0]

    def read_to_end(self) -> bytes:
        out = self.buf[self.pos :]
        self.pos = len(self.buf)
        return out


class Writer:
    """Append-only byte writer."""

    __slots__ = ("buf",)

    def __init__(self):
        self.buf = bytearray()

    def to_bytes(self) -> bytes:
        return bytes(self.buf)

    def __len__(self) -> int:
        return len(self.buf)

    def write_u8(self, value: int) -> None:
        self.buf.append(value & 0xFF)

    def write_raw(self, data: bytes) -> None:
        self.buf.extend(data)

    def write_var_uint(self, value: int) -> None:
        if value < 0:
            raise ValueError(f"negative value for var_uint: {value}")
        while value >= 0x80:
            self.buf.append(0x80 | (value & 0x7F))
            value >>= 7
        self.buf.append(value)

    def write_var_int(self, value: int, force_negative: bool = False) -> None:
        negative = value < 0 or force_negative
        if value < 0:
            value = -value
        first = (0x3F & value) | (0x40 if negative else 0)
        value >>= 6
        if value > 0:
            first |= 0x80
        self.buf.append(first)
        while value > 0:
            b = value & 0x7F
            value >>= 7
            if value > 0:
                b |= 0x80
            self.buf.append(b)

    def write_buf(self, data: bytes) -> None:
        self.write_var_uint(len(data))
        self.buf.extend(data)

    def write_string(self, s: str) -> None:
        self.write_buf(s.encode("utf-8", errors="surrogatepass"))

    def write_f32(self, value: float) -> None:
        self.buf.extend(struct.pack(">f", value))

    def write_f64(self, value: float) -> None:
        self.buf.extend(struct.pack(">d", value))

    def write_i64(self, value: int) -> None:
        self.buf.extend(struct.pack(">q", value))

    def write_u64(self, value: int) -> None:
        self.buf.extend(struct.pack(">Q", value))


# --- Any (JSON-superset scalar) ------------------------------------------------
# Type tags descend from 127 (reference: any.rs:93-116).

_TAG_UNDEFINED = 127
_TAG_NULL = 126
_TAG_INTEGER = 125
_TAG_FLOAT32 = 124
_TAG_FLOAT64 = 123
_TAG_BIGINT = 122
_TAG_FALSE = 121
_TAG_TRUE = 120
_TAG_STRING = 119
_TAG_MAP = 118
_TAG_ARRAY = 117
_TAG_BUFFER = 116


def read_any(cur: Cursor) -> PyAny:
    tag = cur.read_u8()
    if tag == _TAG_UNDEFINED:
        return Undefined
    if tag == _TAG_NULL:
        return None
    if tag == _TAG_INTEGER:
        return cur.read_var_int()
    if tag == _TAG_FLOAT32:
        return cur.read_f32()
    if tag == _TAG_FLOAT64:
        return cur.read_f64()
    if tag == _TAG_BIGINT:
        return cur.read_i64()
    if tag == _TAG_FALSE:
        return False
    if tag == _TAG_TRUE:
        return True
    if tag == _TAG_STRING:
        return cur.read_string()
    if tag == _TAG_MAP:
        n = cur.read_var_uint()
        out = {}
        for _ in range(n):
            key = cur.read_string()
            out[key] = read_any(cur)
        return out
    if tag == _TAG_ARRAY:
        n = cur.read_var_uint()
        return [read_any(cur) for _ in range(n)]
    if tag == _TAG_BUFFER:
        return cur.read_buf()
    raise EncodingError(f"unexpected Any tag {tag}")


def write_any(w: Writer, value: PyAny) -> None:
    if value is Undefined:
        w.write_u8(_TAG_UNDEFINED)
    elif value is None:
        w.write_u8(_TAG_NULL)
    elif value is True:
        w.write_u8(_TAG_TRUE)
    elif value is False:
        w.write_u8(_TAG_FALSE)
    elif isinstance(value, str):
        w.write_u8(_TAG_STRING)
        w.write_string(value)
    elif isinstance(value, BigInt):
        w.write_u8(_TAG_BIGINT)
        w.write_i64(value)
    elif isinstance(value, int):
        if F64_MIN_SAFE_INTEGER <= value <= F64_MAX_SAFE_INTEGER:
            w.write_u8(_TAG_INTEGER)
            w.write_var_int(value)
        else:
            w.write_u8(_TAG_BIGINT)
            w.write_i64(value)
    elif isinstance(value, float):
        if value.is_integer() and F64_MIN_SAFE_INTEGER <= value <= F64_MAX_SAFE_INTEGER:
            w.write_u8(_TAG_INTEGER)
            w.write_var_int(int(value))
        elif (
            not math.isnan(value)
            and abs(value) <= 3.4028234663852886e38
            and struct.unpack(">f", struct.pack(">f", value))[0] == value
        ):
            w.write_u8(_TAG_FLOAT32)
            w.write_f32(value)
        else:
            w.write_u8(_TAG_FLOAT64)
            w.write_f64(value)
    elif isinstance(value, (bytes, bytearray, memoryview)):
        w.write_u8(_TAG_BUFFER)
        w.write_buf(bytes(value))
    elif isinstance(value, dict):
        w.write_u8(_TAG_MAP)
        w.write_var_uint(len(value))
        for key, item in value.items():
            w.write_string(str(key))
            write_any(w, item)
    elif isinstance(value, (list, tuple)):
        w.write_u8(_TAG_ARRAY)
        w.write_var_uint(len(value))
        for item in value:
            write_any(w, item)
    else:
        raise TypeError(f"cannot encode {type(value)!r} as Any")


def any_to_json(value: PyAny) -> str:
    """JSON string form used by the v1 codec for Embed/Format payloads."""
    if value is Undefined:
        return "undefined"
    return json.dumps(value, separators=(",", ":"), ensure_ascii=False)


def any_from_json(src: str) -> PyAny:
    if src == "undefined" or src == "":
        return Undefined
    return json.loads(src)
