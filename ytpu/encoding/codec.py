"""Update codec abstraction: v1 scalar and v2 columnar encoders/decoders.

Behavioral parity targets:
- v1: /root/reference/yrs/src/updates/encoder.rs:80-180, decoder.rs:76-190
- v2: encoder.rs:182-528 (columnar layout + IntDiffOptRle / UIntOptRle /
  Rle / String column compressors), decoder.rs:195-505.

The v2 format is struct-of-arrays on the wire: separate RLE-compressed
columns for key-clocks, clients, left/right clocks, info bytes, strings,
parent-info, type refs and lens, concatenated behind a feature-flag byte.
This is exactly the device-side tensor layout of `ytpu.models.batch_doc` —
a v2 payload maps 1:1 onto update-batch columns.
"""

from __future__ import annotations

from typing import Any as PyAny, Dict, List, Optional, Tuple

from .lib0 import (
    Cursor,
    EncodingError,
    Writer,
    any_from_json,
    any_to_json,
    read_any,
    write_any,
)
from ytpu.core.content import utf16_len

__all__ = ["EncoderV1", "DecoderV1", "EncoderV2", "DecoderV2"]


# --- v1: plain varint streams -------------------------------------------------


class EncoderV1:
    __slots__ = ("w",)

    def __init__(self):
        self.w = Writer()

    def to_bytes(self) -> bytes:
        return self.w.to_bytes()

    # raw writes
    def write_u8(self, v: int) -> None:
        self.w.write_u8(v)

    def write_var(self, v: int) -> None:
        self.w.write_var_uint(v)

    def write_buf(self, data: bytes) -> None:
        self.w.write_buf(data)

    def write_string(self, s: str) -> None:
        self.w.write_string(s)

    # codec-specific channels
    def reset_ds_cur_val(self) -> None:
        pass

    def write_ds_clock(self, clock: int) -> None:
        self.w.write_var_uint(clock)

    def write_ds_len(self, length: int) -> None:
        self.w.write_var_uint(length)

    def write_left_id(self, id_) -> None:
        self.w.write_var_uint(id_.client)
        self.w.write_var_uint(id_.clock)

    write_right_id = write_left_id

    def write_client(self, client: int) -> None:
        self.w.write_var_uint(client)

    def write_info(self, info: int) -> None:
        self.w.write_u8(info)

    def write_parent_info(self, is_root_name: bool) -> None:
        self.w.write_var_uint(1 if is_root_name else 0)

    def write_type_ref(self, tag: int) -> None:
        self.w.write_u8(tag)

    def write_raw(self, data: bytes) -> None:
        """Verbatim wire bytes (re-emission of device-retained spans)."""
        self.w.write_raw(data)

    def write_len(self, length: int) -> None:
        self.w.write_var_uint(length)

    def write_any(self, value: PyAny) -> None:
        write_any(self.w, value)

    def write_json(self, value: PyAny) -> None:
        self.w.write_string(any_to_json(value))

    def write_key(self, key: str) -> None:
        self.w.write_string(key)


class DecoderV1:
    __slots__ = ("cur",)

    def __init__(self, data):
        self.cur = data if isinstance(data, Cursor) else Cursor(data)

    def has_content(self) -> bool:
        return self.cur.has_content()

    def read_u8(self) -> int:
        return self.cur.read_u8()

    def read_var(self) -> int:
        return self.cur.read_var_uint()

    def read_buf(self) -> bytes:
        return self.cur.read_buf()

    def read_string(self) -> str:
        return self.cur.read_string()

    def reset_ds_cur_val(self) -> None:
        pass

    def read_ds_clock(self) -> int:
        return self.cur.read_var_uint()

    def read_ds_len(self) -> int:
        return self.cur.read_var_uint()

    def read_id(self) -> Tuple[int, int]:
        return self.cur.read_var_uint(), self.cur.read_var_uint()

    read_left_id = read_id
    read_right_id = read_id

    def read_client(self) -> int:
        return self.cur.read_var_uint()

    def read_info(self) -> int:
        return self.cur.read_u8()

    def read_parent_info(self) -> bool:
        return self.cur.read_var_uint() == 1

    def read_type_ref(self) -> int:
        return self.cur.read_u8()

    def read_len(self) -> int:
        return self.cur.read_var_uint()

    def read_any(self) -> PyAny:
        return read_any(self.cur)

    def read_json(self) -> PyAny:
        return any_from_json(self.cur.read_string())

    def read_key(self) -> str:
        return self.cur.read_string()


# --- v2 column compressors (parity: encoder.rs:353-528) -----------------------


class _IntDiffOptRleEncoder:
    __slots__ = ("w", "last", "count", "diff")

    def __init__(self):
        self.w = Writer()
        self.last = 0
        self.count = 0
        self.diff = 0

    def write_u32(self, value: int) -> None:
        diff = value - self.last
        if self.diff == diff and self.count > 0:
            self.last = value
            self.count += 1
        else:
            self._flush()
            self.count = 1
            self.diff = diff
            self.last = value

    def _flush(self) -> None:
        if self.count > 0:
            encoded = (self.diff << 1) | (0 if self.count == 1 else 1)
            self.w.write_var_int(encoded)
            if self.count > 1:
                self.w.write_var_uint(self.count - 2)

    def to_bytes(self) -> bytes:
        self._flush()
        return self.w.to_bytes()


class _UIntOptRleEncoder:
    __slots__ = ("w", "last", "count")

    def __init__(self):
        self.w = Writer()
        self.last = 0
        self.count = 0

    def write_u64(self, value: int) -> None:
        if self.last == value and self.count > 0:
            self.count += 1
        else:
            self._flush()
            self.count = 1
            self.last = value

    def _flush(self) -> None:
        if self.count > 0:
            if self.count == 1:
                self.w.write_var_int(self.last)
            else:
                # negative signals a run; -0 is meaningful (force_negative)
                self.w.write_var_int(-self.last, force_negative=True)
                self.w.write_var_uint(self.count - 2)

    def to_bytes(self) -> bytes:
        self._flush()
        return self.w.to_bytes()


class _RleEncoder:
    __slots__ = ("w", "last", "count")

    def __init__(self):
        self.w = Writer()
        self.last: Optional[int] = None
        self.count = 0

    def write_u8(self, value: int) -> None:
        if self.last == value:
            self.count += 1
        else:
            if self.count > 0:
                self.w.write_var_uint(self.count - 1)
            self.count = 1
            self.w.write_u8(value)
            self.last = value

    def to_bytes(self) -> bytes:
        return self.w.to_bytes()


class _StringEncoder:
    __slots__ = ("parts", "lens")

    def __init__(self):
        self.parts: List[str] = []
        self.lens = _UIntOptRleEncoder()

    def write(self, s: str) -> None:
        self.parts.append(s)
        self.lens.write_u64(utf16_len(s))

    def to_bytes(self) -> bytes:
        w = Writer()
        w.write_string("".join(self.parts))
        w.write_raw(self.lens.to_bytes())
        return w.to_bytes()


class _IntDiffOptRleDecoder:
    __slots__ = ("cur", "last", "count", "diff")

    def __init__(self, data: bytes):
        self.cur = Cursor(data)
        self.last = 0
        self.count = 0
        self.diff = 0

    def read_u32(self) -> int:
        if self.count == 0:
            diff = self.cur.read_var_int()
            has_count = diff & 1
            self.diff = diff >> 1
            self.count = self.cur.read_var_uint() + 2 if has_count else 1
        self.last += self.diff
        self.count -= 1
        return self.last


class _UIntOptRleDecoder:
    __slots__ = ("cur", "last", "count")

    def __init__(self, data: bytes, cursor: Optional[Cursor] = None):
        self.cur = cursor if cursor is not None else Cursor(data)
        self.last = 0
        self.count = 0

    def read_u64(self) -> int:
        if self.count == 0:
            value, negative = self.cur.read_var_int_signed()
            if negative:
                self.count = self.cur.read_var_uint() + 2
                self.last = -value
            else:
                self.count = 1
                self.last = value
        self.count -= 1
        return self.last


class _RleDecoder:
    __slots__ = ("cur", "last", "count")

    def __init__(self, data: bytes):
        self.cur = Cursor(data)
        self.last = 0
        self.count = 0

    def read_u8(self) -> int:
        if self.count == 0:
            self.last = self.cur.read_u8()
            if self.cur.has_content():
                self.count = self.cur.read_var_uint() + 1
            else:
                self.count = -1  # repeat forever
        self.count -= 1
        return self.last


class _StringDecoder:
    __slots__ = ("buf", "pos", "lens")

    def __init__(self, data: bytes):
        cur = Cursor(data)
        raw = cur.read_buf()
        self.buf = raw.decode("utf-8", errors="surrogatepass")
        self.pos = 0
        self.lens = _UIntOptRleDecoder(b"", cursor=cur)

    def read_str(self) -> str:
        remaining = self.lens.read_u64()
        start = self.pos
        i = start
        n = len(self.buf)
        while remaining > 0 and i < n:
            remaining -= 2 if ord(self.buf[i]) > 0xFFFF else 1
            i += 1
        self.pos = i
        return self.buf[start:i]


# --- v2 encoder/decoder -------------------------------------------------------


class EncoderV2:
    __slots__ = (
        "rest",
        "ds_curr_val",
        "sequencer",
        "key_clock",
        "client",
        "left_clock",
        "right_clock",
        "info",
        "string",
        "parent_info",
        "type_ref",
        "len_enc",
    )

    def __init__(self):
        self.rest = Writer()
        self.ds_curr_val = 0
        self.sequencer = 0
        self.key_clock = _IntDiffOptRleEncoder()
        self.client = _UIntOptRleEncoder()
        self.left_clock = _IntDiffOptRleEncoder()
        self.right_clock = _IntDiffOptRleEncoder()
        self.info = _RleEncoder()
        self.string = _StringEncoder()
        self.parent_info = _RleEncoder()
        self.type_ref = _UIntOptRleEncoder()
        self.len_enc = _UIntOptRleEncoder()

    def to_bytes(self) -> bytes:
        w = Writer()
        w.write_u8(0)  # feature flag
        w.write_buf(self.key_clock.to_bytes())
        w.write_buf(self.client.to_bytes())
        w.write_buf(self.left_clock.to_bytes())
        w.write_buf(self.right_clock.to_bytes())
        w.write_buf(self.info.to_bytes())
        w.write_buf(self.string.to_bytes())
        w.write_buf(self.parent_info.to_bytes())
        w.write_buf(self.type_ref.to_bytes())
        w.write_buf(self.len_enc.to_bytes())
        w.write_raw(self.rest.to_bytes())
        return w.to_bytes()

    # raw writes land in the rest buffer
    def write_u8(self, v: int) -> None:
        self.rest.write_u8(v)

    def write_var(self, v: int) -> None:
        self.rest.write_var_uint(v)

    def write_buf(self, data: bytes) -> None:
        self.rest.write_buf(data)

    def write_string(self, s: str) -> None:
        self.string.write(s)

    # channels
    def reset_ds_cur_val(self) -> None:
        self.ds_curr_val = 0

    def write_ds_clock(self, clock: int) -> None:
        diff = clock - self.ds_curr_val
        self.ds_curr_val = clock
        self.rest.write_var_uint(diff)

    def write_ds_len(self, length: int) -> None:
        self.rest.write_var_uint(length - 1)
        self.ds_curr_val += length

    def write_left_id(self, id_) -> None:
        self.client.write_u64(id_.client)
        self.left_clock.write_u32(id_.clock)

    def write_right_id(self, id_) -> None:
        self.client.write_u64(id_.client)
        self.right_clock.write_u32(id_.clock)

    def write_client(self, client: int) -> None:
        self.client.write_u64(client)

    def write_info(self, info: int) -> None:
        self.info.write_u8(info)

    def write_parent_info(self, is_root_name: bool) -> None:
        self.parent_info.write_u8(1 if is_root_name else 0)

    def write_type_ref(self, tag: int) -> None:
        self.type_ref.write_u64(tag)

    def write_len(self, length: int) -> None:
        self.len_enc.write_u64(length)

    def write_any(self, value: PyAny) -> None:
        write_any(self.rest, value)

    def write_json(self, value: PyAny) -> None:
        write_any(self.rest, value)

    def write_key(self, key: str) -> None:
        # bug-compatible with Yjs/yrs: the key table is never filled, so every
        # key writes a fresh string and a fresh sequencer clock
        # (encoder.rs:327-334)
        self.key_clock.write_u32(self.sequencer)
        self.sequencer += 1
        self.string.write(key)


class DecoderV2:
    __slots__ = (
        "rest",
        "ds_curr_val",
        "keys",
        "key_clock",
        "client",
        "left_clock",
        "right_clock",
        "info",
        "string",
        "parent_info",
        "type_ref",
        "len_dec",
    )

    def __init__(self, data: bytes):
        cur = Cursor(data)
        if cur.has_content():
            cur.read_u8()  # feature flag
        self.key_clock = _IntDiffOptRleDecoder(cur.read_buf())
        self.client = _UIntOptRleDecoder(cur.read_buf())
        self.left_clock = _IntDiffOptRleDecoder(cur.read_buf())
        self.right_clock = _IntDiffOptRleDecoder(cur.read_buf())
        self.info = _RleDecoder(cur.read_buf())
        self.string = _StringDecoder(cur.read_buf())
        self.parent_info = _RleDecoder(cur.read_buf())
        self.type_ref = _UIntOptRleDecoder(cur.read_buf())
        self.len_dec = _UIntOptRleDecoder(cur.read_buf())
        self.rest = Cursor(cur.read_to_end())
        self.ds_curr_val = 0
        self.keys: List[str] = []

    def has_content(self) -> bool:
        return self.rest.has_content()

    def read_u8(self) -> int:
        return self.rest.read_u8()

    def read_var(self) -> int:
        return self.rest.read_var_uint()

    def read_buf(self) -> bytes:
        return self.rest.read_buf()

    def read_string(self) -> str:
        return self.string.read_str()

    def reset_ds_cur_val(self) -> None:
        self.ds_curr_val = 0

    def read_ds_clock(self) -> int:
        self.ds_curr_val += self.rest.read_var_uint()
        return self.ds_curr_val

    def read_ds_len(self) -> int:
        diff = self.rest.read_var_uint() + 1
        self.ds_curr_val += diff
        return diff

    def read_left_id(self) -> Tuple[int, int]:
        return self.client.read_u64(), self.left_clock.read_u32()

    def read_right_id(self) -> Tuple[int, int]:
        return self.client.read_u64(), self.right_clock.read_u32()

    def read_client(self) -> int:
        return self.client.read_u64()

    def read_info(self) -> int:
        return self.info.read_u8()

    def read_parent_info(self) -> bool:
        return self.parent_info.read_u8() == 1

    def read_type_ref(self) -> int:
        return self.type_ref.read_u64()

    def read_len(self) -> int:
        return self.len_dec.read_u64()

    def read_any(self) -> PyAny:
        return read_any(self.rest)

    def read_json(self) -> PyAny:
        return read_any(self.rest)

    def read_key(self) -> str:
        key_clock = self.key_clock.read_u32()
        if key_clock < len(self.keys):
            return self.keys[key_clock]
        key = self.string.read_str()
        self.keys.append(key)
        return key
