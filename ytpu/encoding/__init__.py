"""lib0-compatible binary encoding (v1; v2 columnar codec in `v2`)."""

from .lib0 import (
    Cursor,
    EncodingError,
    Undefined,
    Writer,
    any_from_json,
    any_to_json,
    read_any,
    write_any,
)

__all__ = [
    "Cursor",
    "Writer",
    "Undefined",
    "EncodingError",
    "read_any",
    "write_any",
    "any_to_json",
    "any_from_json",
]
