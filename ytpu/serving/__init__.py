"""Serving subsystem (ISSUE-9): scenario traffic generation, admission
control + backpressure, and the multi-tenant soak driver that scores the
sync stack against SLOs (docs/serving.md)."""

from .admission import (
    AdmissionController,
    Overload,
    QueueFull,
    RateLimited,
    TokenBucket,
)
from .autopilot import AutopilotConfig, FleetAutopilot, RecoveryExhausted
from .canary import CanaryProber
from .scenario import Event, Scenario, ScenarioConfig
from .soak import (
    CANARY_PREFIX,
    FederatedSoakDriver,
    SoakDriver,
    run_soak_tcp,
    server_state_digest,
)

__all__ = [
    "AdmissionController",
    "AutopilotConfig",
    "CANARY_PREFIX",
    "CanaryProber",
    "Event",
    "FederatedSoakDriver",
    "FleetAutopilot",
    "Overload",
    "QueueFull",
    "RateLimited",
    "RecoveryExhausted",
    "Scenario",
    "ScenarioConfig",
    "SoakDriver",
    "TokenBucket",
    "run_soak_tcp",
    "server_state_digest",
]
