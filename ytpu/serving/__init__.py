"""Serving subsystem (ISSUE-9): scenario traffic generation, admission
control + backpressure, and the multi-tenant soak driver that scores the
sync stack against SLOs (docs/serving.md)."""

from .admission import (
    AdmissionController,
    Overload,
    QueueFull,
    RateLimited,
    TokenBucket,
)
from .canary import CanaryProber
from .scenario import Event, Scenario, ScenarioConfig
from .soak import (
    CANARY_PREFIX,
    FederatedSoakDriver,
    SoakDriver,
    run_soak_tcp,
    server_state_digest,
)

__all__ = [
    "AdmissionController",
    "CANARY_PREFIX",
    "CanaryProber",
    "Event",
    "FederatedSoakDriver",
    "Overload",
    "QueueFull",
    "RateLimited",
    "Scenario",
    "ScenarioConfig",
    "SoakDriver",
    "TokenBucket",
    "run_soak_tcp",
    "server_state_digest",
]
