"""Closed-loop fleet autopilot (ISSUE-16 tentpole).

Every prior PR grew either the *read* surface (per-tenant queue depths,
admission Busy rates, `replica.convergence_lag{tenant=}`, canary
availability and rw-lag, the ownership/migration timeline) or an
*actuator* (`ReplicaMesh.migrate_tenant` / `kill_replica` /
`recover_tenant`, the admission knobs) — but nothing connected them.
`FleetAutopilot` is that control loop: on a fixed tick it assembles a
structured **fleet snapshot** from existing registries and mesh state
(no new device syncs — decisions are O(snapshot)) and acts through the
existing actuators:

1. **Hot-tenant migration** — per-tenant load scores (device-queue
   depth + windowed applied-update deltas, with the global apply-p99
   window folded in as a quantized *pressure* level) move Zipf-hot
   tenants off overloaded replicas via `migrate_tenant`.  Replica
   overload uses **hysteresis** (enter at ``load_high``, exit at
   ``load_low``) and every migrated tenant starts a **cooldown**
   (``migrate_cooldown_ticks``), so an oscillating load signal provably
   cannot flap the same tenant back and forth (the damping test bounds
   the action count).
2. **Adaptive admission** — Busy-rate + queue-depth windows retune the
   attached `AdmissionController` live (the ISSUE-16 runtime setters):
   a high Busy rate over *shallow* queues means the knob, not the
   device, is the bottleneck → relax the queue bound / rate toward
   their maxima; a high Busy rate over *deep* queues is genuine
   overload → clamp the hottest tenant with a per-tenant override so
   the other tenants keep their budget.
3. **Quarantine recovery** — `DivergenceFault` quarantines are driven
   through `recover_tenant` with bounded exponential backoff
   (``recovery_backoff_base * mult^attempts`` ticks, capped), giving up
   into the typed terminal state `RecoveryExhausted` after
   ``max_recoveries`` failed attempts.
4. **Scripted maintenance drain** — `drain_replica(rid)` migrates every
   owned tenant away, then decommissions the replica
   (`ReplicaMesh.decommission`: remaining sessions close with
   ``reason="drain"``, the canary stops scoring it), so the scheduled
   `kill_replica` that follows drops **zero** sessions and never dents
   `canary.availability` (ISSUE-16 satellite).  `schedule_drain(rid,
   at_tick)` scripts the whole sequence onto the tick clock.

Every decision appends to a bounded, seq-numbered **action journal**
(policy, action, reason, a trimmed inputs snapshot, outcome) exposed
via the `/snapshot` section ``autopilot`` and the ``autopilot.*``
metric families.  The journal is the replayability contract: every
value in it is derived from deterministic state (tick numbers, queue
depths, counter deltas, the seeded RNG) — never wall-clock readings —
so the same seed + the same scenario produce a **byte-identical**
journal (`journal_bytes` / `journal_digest`).  The injected clock is
used only for non-journaled telemetry.  The one caveat is the latency
*pressure* term: the apply-p99 window is quantized into coarse pressure
levels (``p99_pressure_s`` bands), so determinism holds whenever the
p99 stays within one band — in-process soaks sit far below band 1.

Fault sites (docs/robustness.md): ``autopilot.stall`` skips whole
ticks (the mesh must degrade gracefully back to manual behavior, never
corrupt) and ``autopilot.misfire`` injects one wrong-but-legal action —
a seeded-random migration — which byte parity must survive, because
every actuator the autopilot is allowed to call is parity-safe.
"""

from __future__ import annotations

import hashlib
import json
import random
import time
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional

from ytpu.utils import metrics
from ytpu.utils.faults import faults
from ytpu.utils.slo import HistogramWindow
from ytpu.utils.trace import tracer

from .soak import CANARY_PREFIX

__all__ = ["AutopilotConfig", "FleetAutopilot", "RecoveryExhausted"]

_TICKS = metrics.counter("autopilot.ticks")
_ACTIONS = metrics.counter("autopilot.actions", labelnames=("policy",))
_STALLS = metrics.counter("autopilot.stalls")
_JOURNAL_SEQ = metrics.gauge("autopilot.journal_seq")
_RECOVERY_EXHAUSTED = metrics.gauge("autopilot.recovery_exhausted")
_DRAINED = metrics.gauge("autopilot.drained_replicas")


class RecoveryExhausted:
    """Typed terminal state for a quarantined tenant the autopilot gave
    up on: ``max_recoveries`` attempts failed, backoff is abandoned and
    the tenant stays quarantined for the operator.  Kept (not raised) in
    `FleetAutopilot.terminal` — giving up is a *state*, not an error the
    control loop should die on."""

    __slots__ = ("tenant", "attempts", "tick")

    def __init__(self, tenant: str, attempts: int, tick: int):
        self.tenant = tenant
        self.attempts = attempts
        self.tick = tick

    def __repr__(self):
        return (
            f"RecoveryExhausted({self.tenant!r}, attempts={self.attempts}, "
            f"tick={self.tick})"
        )


class AutopilotConfig:
    """Knobs for every policy (see module docstring).  Plain attributes
    so a test or bench leg overrides exactly what it needs."""

    def __init__(self, **kw):
        # --- hot-tenant migration ---
        self.load_high = 16.0        # replica load: enter overloaded
        self.load_low = 6.0          # replica load: exit overloaded
        self.migrate_cooldown_ticks = 8
        # --- adaptive admission ---
        self.busy_high = 0.05        # Busy-rate that triggers action
        self.queue_relax_depth = 8   # shallow queues => knob-bound: relax
        self.queue_high = 32         # deep queues => overload: clamp
        self.queue_bound_mult = 8
        self.queue_bound_max = 4096
        self.rate_mult = 4.0
        self.rate_max = 1e6
        self.tenant_queue_clamp = 8
        self.admission_cooldown_ticks = 2
        # --- quarantine recovery ---
        self.max_recoveries = 4
        self.recovery_backoff_base = 1
        self.recovery_backoff_mult = 2
        self.recovery_backoff_cap = 16
        # --- latency pressure quantization ---
        self.p99_pressure_s = 0.25   # band width; in-proc p99 sits in band 0
        for k, v in kw.items():
            if not hasattr(self, k):
                raise TypeError(f"unknown autopilot knob {k!r}")
            setattr(self, k, v)


class FleetAutopilot:
    """The deterministic control loop (see module docstring).

    ``mesh`` is duck-typed to the `ReplicaMesh` surface the policies
    read and actuate (``replicas`` / ``owner`` / ``quarantined`` /
    ``migrate_tenant`` / ``recover_tenant`` / ``kill_replica`` /
    ``decommission``), so damping/backoff unit tests drive the decision
    logic against a stub fleet.  ``snapshot_fn`` (tests only) replaces
    the whole snapshot assembly with a synthetic signal generator —
    the decision path underneath runs unchanged."""

    def __init__(
        self,
        mesh,
        admission=None,
        config: Optional[AutopilotConfig] = None,
        seed: int = 0,
        clock: Callable[[], float] = time.monotonic,
        journal_cap: int = 256,
        snapshot_fn: Optional[Callable[[], Dict]] = None,
    ):
        self.mesh = mesh
        self.admission = admission
        self.cfg = config or AutopilotConfig()
        self.seed = int(seed)
        self._clock = clock
        self._snapshot_fn = snapshot_fn
        # seeded like every deterministic component (FaultSpec, Scenario):
        # crc32 of "<seed>:autopilot" — used ONLY for the misfire payload
        self._rng = random.Random(
            zlib.crc32(f"{self.seed}:autopilot".encode()) & 0xFFFFFFFF
        )
        self.tick_no = 0
        self.journal: deque = deque(maxlen=max(1, journal_cap))
        self._seq = 0
        self.last_tick_at: Optional[float] = None  # telemetry only
        # migration state
        self._overloaded: set = set()
        self._cooldown: Dict[str, int] = {}  # tenant -> blocked until tick
        # admission state
        self._adm_cooldown_until = 0
        # recovery state
        self._recovery: Dict[str, Dict[str, int]] = {}
        self.terminal: Dict[str, RecoveryExhausted] = {}
        # maintenance state
        self._maintenance: Dict[int, tuple] = {}  # tick -> (rid, kill)
        self.drained: set = set()
        # windowed inputs: counter baselines are taken at construction so
        # the first tick scores only THIS run's traffic.  Cached objects,
        # not fresh registry lookups at read time (metrics.reset()
        # orphaning — the `_admission_values` discipline).
        self._applied_family = metrics.counter(
            "sync.tenant_updates_applied", labelnames=("tenant",)
        )
        self._applied_base: Dict[str, int] = {}
        from . import admission as _adm

        self._rejected = _adm._REJECTED
        self._admitted = _adm._ADMITTED
        self._busy_base = self._read_busy()
        self._admitted_base = self._admitted.value
        self._apply_w = HistogramWindow(metrics.histogram("sync.apply_update"))

    # ------------------------------------------------------------- inputs

    def _read_busy(self) -> int:
        """Admission refusals (the Busy-reply sources), from the
        admission module's own cached counter children."""
        return int(
            self._rejected.labels("queue_full").value
            + self._rejected.labels("rate_limited").value
        )

    def _pressure(self) -> int:
        """The apply-p99 window quantized into coarse pressure bands —
        the only wall-derived input, deliberately so coarse that every
        run of one scenario lands the same band (journal determinism)."""
        return int(self._apply_w.quantile(0.99) / self.cfg.p99_pressure_s)

    def _fleet_snapshot(self) -> Dict:
        """One structured, deterministic view of the fleet: per-tenant
        load scores (queue depth + applied delta), per-replica load sums
        and states, quarantines, and the Busy window.  Assembled from
        state the mesh/registries already hold — no device syncs."""
        mesh = self.mesh
        tenants: Dict[str, Dict] = {}
        for t in sorted(mesh.owner):
            if t.startswith(CANARY_PREFIX):
                continue  # probe traffic is not load
            rid = mesh.owner[t][0]
            rep = mesh.replicas[rid]
            depth = 0
            if rep.alive:
                depth = int(rep.server._tenant_queue_depth(t))
            applied = int(self._applied_family.labels(t).value)
            # first sight of a tenant baselines at its CURRENT value:
            # the registry counter is process-cumulative, and a delta
            # against an earlier run's tally would make the first
            # window's load depend on process history — breaking the
            # byte-identical-journal contract across back-to-back runs
            base = self._applied_base.get(t)
            delta = 0 if base is None else applied - base
            self._applied_base[t] = applied
            tenants[t] = {
                "owner": rid,
                "depth": depth,
                "applied": delta,
                "load": depth + delta,
            }
        replicas: Dict[str, Dict] = {}
        for rid in sorted(mesh.replicas):
            rep = mesh.replicas[rid]
            owned = [t for t, v in tenants.items() if v["owner"] == rid]
            replicas[rid] = {
                "alive": bool(rep.alive),
                "decommissioned": rid in getattr(
                    mesh, "decommissioned", ()
                ),
                "owned": owned,
                "load": sum(tenants[t]["load"] for t in owned),
            }
        busy = self._read_busy()
        admitted = int(self._admitted.value)
        busy_d = busy - self._busy_base
        admitted_d = admitted - self._admitted_base
        self._busy_base, self._admitted_base = busy, admitted
        denom = busy_d + admitted_d
        return {
            "tick": self.tick_no,
            "tenants": tenants,
            "replicas": replicas,
            "quarantined": sorted(
                t for t in mesh.quarantined if t not in self.terminal
            ),
            "busy": busy_d,
            "admitted": admitted_d,
            "busy_rate": round(busy_d / denom, 4) if denom else 0.0,
            "pressure": self._pressure(),
        }

    # ------------------------------------------------------------ journal

    def _journal(
        self,
        policy: str,
        action: str,
        reason: str,
        inputs: Dict,
        outcome,
        count_action: bool = True,
    ) -> Dict:
        self._seq += 1
        entry = {
            "seq": self._seq,
            "tick": self.tick_no,
            "policy": policy,
            "action": action,
            "reason": reason,
            "inputs": inputs,
            "outcome": outcome,
        }
        self.journal.append(entry)
        _JOURNAL_SEQ.set(self._seq)
        if count_action:
            _ACTIONS.labels(policy).inc()
        return entry

    def journal_bytes(self) -> bytes:
        """The (bounded) journal in canonical JSON-lines form — the
        byte-identity surface: same seed + same scenario ⇒ identical
        bytes."""
        return "\n".join(
            json.dumps(e, sort_keys=True, separators=(",", ":"))
            for e in self.journal
        ).encode()

    def journal_digest(self) -> str:
        return hashlib.sha256(self.journal_bytes()).hexdigest()

    # --------------------------------------------------------------- tick

    def tick(self) -> List[Dict]:
        """One control-loop pass: snapshot, then every policy in a fixed
        order (maintenance → recovery → migration → admission — drains
        first so nothing migrates TOWARD a replica about to leave).
        Returns the journal entries appended this tick."""
        self.tick_no += 1
        _TICKS.inc()
        self.last_tick_at = self._clock()
        with tracer.span("autopilot.tick", tick=self.tick_no):
            if faults.active and faults.fire("autopilot.stall") is not None:
                _STALLS.inc()
                return [
                    self._journal(
                        "fault", "stall",
                        "injected autopilot.stall: tick skipped",
                        {}, "skipped", count_action=False,
                    )
                ]
            snap = (
                self._snapshot_fn()
                if self._snapshot_fn is not None
                else self._fleet_snapshot()
            )
            snap.setdefault("tick", self.tick_no)
            out: List[Dict] = []
            out += self._maintenance_policy()
            out += self._recovery_policy(snap)
            out += self._migration_policy(snap)
            out += self._admission_policy(snap)
            if faults.active:
                spec = faults.fire("autopilot.misfire")
                if spec is not None:
                    out += self._misfire(snap)
            return out

    # ------------------------------------------------- policy: maintenance

    def schedule_drain(self, rid: str, at_tick: int, kill: bool = True):
        """Script a maintenance drain of ``rid`` at ``at_tick`` (and the
        drained `kill_replica` right after it, unless ``kill=False``)."""
        self._maintenance[int(at_tick)] = (rid, bool(kill))

    def drain_replica(self, rid: str) -> int:
        """Migrate every tenant ``rid`` owns to the least-loaded other
        replica, then decommission it (remaining sessions close with
        ``reason="drain"``; the canary stops scoring it) — after this a
        `kill_replica(rid, drain=True)` drops zero sessions.  Returns
        the tenants moved."""
        mesh = self.mesh
        targets = [
            r for r in sorted(mesh.replicas)
            if r != rid
            and mesh.replicas[r].alive
            and r not in getattr(mesh, "decommissioned", ())
        ]
        if not targets:
            raise ValueError(f"cannot drain {rid!r}: no live target replica")
        moved = 0
        owned = sorted(
            t for t, (o, _e) in mesh.owner.items()
            if o == rid and not t.startswith(CANARY_PREFIX)
        )
        for i, t in enumerate(owned):
            dst = targets[i % len(targets)]
            epoch = mesh.migrate_tenant(t, dst)
            moved += 1
            self._journal(
                "maintenance", "drain_migrate",
                f"drain {rid}: move {t} to {dst}",
                {"replica": rid, "tenant": t, "dst": dst},
                {"epoch": epoch},
            )
        decommission = getattr(mesh, "decommission", None)
        closed = decommission(rid) if decommission is not None else 0
        self.drained.add(rid)
        _DRAINED.set(len(self.drained))
        self._journal(
            "maintenance", "decommission",
            f"drain {rid}: decommissioned ({moved} tenants moved)",
            {"replica": rid, "moved": moved},
            {"sessions_closed": closed},
        )
        return moved

    def _maintenance_policy(self) -> List[Dict]:
        out: List[Dict] = []
        for at_tick in sorted(self._maintenance):
            if at_tick > self.tick_no:
                continue
            rid, kill = self._maintenance.pop(at_tick)
            rep = self.mesh.replicas.get(rid)
            if rep is None or not rep.alive:
                continue
            seq_before = self._seq
            self.drain_replica(rid)
            out.extend(e for e in self.journal if e["seq"] > seq_before)
            if kill:
                dropped = self.mesh.kill_replica(rid, drain=True)
                out.append(
                    self._journal(
                        "maintenance", "kill",
                        f"scheduled maintenance kill of drained {rid}",
                        {"replica": rid, "scheduled_tick": at_tick},
                        {"sessions_dropped": dropped},
                    )
                )
        return out

    # --------------------------------------------------- policy: recovery

    def _recovery_policy(self, snap: Dict) -> List[Dict]:
        out: List[Dict] = []
        cfg = self.cfg
        for t in snap.get("quarantined", ()):
            st = self._recovery.setdefault(
                t, {"attempts": 0, "next": self.tick_no}
            )
            if self.tick_no < st["next"]:
                continue
            ok = bool(self.mesh.recover_tenant(t))
            if ok:
                out.append(
                    self._journal(
                        "recovery", "recover",
                        f"quarantined {t}: recovery succeeded",
                        {"tenant": t, "attempts": st["attempts"] + 1},
                        "recovered",
                    )
                )
                self._recovery.pop(t, None)
                continue
            st["attempts"] += 1
            if st["attempts"] >= cfg.max_recoveries:
                self.terminal[t] = RecoveryExhausted(
                    t, st["attempts"], self.tick_no
                )
                _RECOVERY_EXHAUSTED.set(len(self.terminal))
                self._recovery.pop(t, None)
                out.append(
                    self._journal(
                        "recovery", "give_up",
                        f"quarantined {t}: {st['attempts']} attempts failed, "
                        "abandoning to RecoveryExhausted",
                        {"tenant": t, "attempts": st["attempts"]},
                        "exhausted",
                    )
                )
                continue
            backoff = min(
                cfg.recovery_backoff_base
                * cfg.recovery_backoff_mult ** st["attempts"],
                cfg.recovery_backoff_cap,
            )
            st["next"] = self.tick_no + backoff
            out.append(
                self._journal(
                    "recovery", "backoff",
                    f"quarantined {t}: attempt {st['attempts']} failed, "
                    f"retry in {backoff} ticks",
                    {"tenant": t, "attempts": st["attempts"]},
                    {"retry_tick": st["next"]},
                )
            )
        return out

    # -------------------------------------------------- policy: migration

    def _migration_policy(self, snap: Dict) -> List[Dict]:
        out: List[Dict] = []
        cfg = self.cfg
        replicas = snap.get("replicas", {})
        tenants = snap.get("tenants", {})
        live = {
            rid: r for rid, r in replicas.items()
            if r.get("alive") and not r.get("decommissioned")
        }
        if len(live) < 2:
            return out
        # hysteresis: enter the overloaded set at load_high, leave at
        # load_low — a load hovering between the watermarks changes
        # nothing, which is the anti-flap half the cooldown can't cover
        for rid in sorted(live):
            load = live[rid]["load"]
            if rid in self._overloaded and load <= cfg.load_low:
                self._overloaded.discard(rid)
            elif rid not in self._overloaded and load >= cfg.load_high:
                self._overloaded.add(rid)
        self._overloaded &= set(live)
        for rid in sorted(self._overloaded):
            cands = [
                t for t in live[rid]["owned"]
                if self._cooldown.get(t, 0) <= self.tick_no
                and t not in snap.get("quarantined", ())
            ]
            if not cands:
                continue
            hot = max(cands, key=lambda t: (tenants[t]["load"], t))
            dst = min(
                (r for r in sorted(live) if r != rid),
                key=lambda r: (live[r]["load"], r),
            )
            epoch = self.mesh.migrate_tenant(hot, dst)
            self._cooldown[hot] = self.tick_no + cfg.migrate_cooldown_ticks
            out.append(
                self._journal(
                    "migration", "migrate",
                    f"{rid} overloaded (load {live[rid]['load']} >= "
                    f"{cfg.load_high:g}): move hottest tenant {hot} to {dst}",
                    {
                        "tenant": hot,
                        "src": rid,
                        "dst": dst,
                        "replica_load": live[rid]["load"],
                        "tenant_load": tenants[hot]["load"],
                        "dst_load": live[dst]["load"],
                        "pressure": snap.get("pressure", 0),
                    },
                    {
                        "epoch": epoch,
                        "cooldown_until": self._cooldown[hot],
                    },
                )
            )
        return out

    # -------------------------------------------------- policy: admission

    def _admission_policy(self, snap: Dict) -> List[Dict]:
        out: List[Dict] = []
        adm = self.admission
        cfg = self.cfg
        if adm is None or self.tick_no < self._adm_cooldown_until:
            return out
        busy_rate = snap.get("busy_rate", 0.0)
        if snap.get("busy", 0) == 0 or busy_rate < cfg.busy_high:
            return out
        tenants = snap.get("tenants", {})
        max_depth = max(
            (v["depth"] for v in tenants.values()), default=0
        )
        inputs = {
            "busy": snap.get("busy", 0),
            "admitted": snap.get("admitted", 0),
            "busy_rate": busy_rate,
            "max_depth": max_depth,
        }
        if max_depth <= cfg.queue_relax_depth:
            # Busy storm over shallow queues: the admission knob is the
            # bottleneck, not the device — relax toward the maxima
            if (
                adm.max_queue is not None
                and adm.max_queue < cfg.queue_bound_max
            ):
                new_bound = min(
                    int(adm.max_queue * cfg.queue_bound_mult) + 1,
                    cfg.queue_bound_max,
                )
                old = adm.max_queue
                adm.set_queue_bound(new_bound)
                out.append(
                    self._journal(
                        "admission", "relax_queue_bound",
                        f"busy_rate {busy_rate:g} over shallow queues "
                        f"(depth {max_depth}): bound {old} -> {new_bound}",
                        inputs, {"max_queue": new_bound},
                    )
                )
            if adm.bucket is not None and adm.bucket.rate < cfg.rate_max:
                old_rate = adm.bucket.rate
                new_rate = min(old_rate * cfg.rate_mult, cfg.rate_max)
                adm.set_rate(new_rate)
                out.append(
                    self._journal(
                        "admission", "relax_rate",
                        f"busy_rate {busy_rate:g} over shallow queues: "
                        f"rate {old_rate:g} -> {new_rate:g}",
                        inputs, {"rate": new_rate},
                    )
                )
        elif max_depth >= cfg.queue_high and tenants:
            # genuine overload: clamp the hottest tenant's queue with a
            # per-tenant override so the others keep their budget
            hot = max(
                sorted(tenants), key=lambda t: (tenants[t]["load"], t)
            )
            adm.set_tenant_queue_bound(hot, cfg.tenant_queue_clamp)
            out.append(
                self._journal(
                    "admission", "clamp_tenant",
                    f"busy_rate {busy_rate:g} over deep queues (depth "
                    f"{max_depth}): clamp {hot} to {cfg.tenant_queue_clamp}",
                    {**inputs, "tenant": hot},
                    {"tenant_queue_bound": cfg.tenant_queue_clamp},
                )
            )
        if out:
            self._adm_cooldown_until = (
                self.tick_no + cfg.admission_cooldown_ticks
            )
        return out

    # ---------------------------------------------------- policy: misfire

    def _misfire(self, snap: Dict) -> List[Dict]:
        """`autopilot.misfire`: one wrong-but-legal action — a seeded-
        random migration.  Legal because `migrate_tenant` is parity-safe
        by construction; wrong because no load signal asked for it."""
        mesh = self.mesh
        live = [
            rid for rid in sorted(mesh.replicas)
            if mesh.replicas[rid].alive
            and rid not in getattr(mesh, "decommissioned", ())
        ]
        cands = sorted(
            t for t in mesh.owner
            if not t.startswith(CANARY_PREFIX)
            and t not in mesh.quarantined
            and mesh.owner[t][0] in live
        )
        if not cands or len(live) < 2:
            return []
        tenant = self._rng.choice(cands)
        src = mesh.owner[tenant][0]
        dst = self._rng.choice([r for r in live if r != src])
        epoch = mesh.migrate_tenant(tenant, dst)
        return [
            self._journal(
                "misfire", "migrate",
                f"injected autopilot.misfire: pointless {tenant} "
                f"{src} -> {dst}",
                {"tenant": tenant, "src": src, "dst": dst},
                {"epoch": epoch},
            )
        ]

    # ------------------------------------------------------------- export

    def snapshot(self) -> Dict:
        """`/snapshot` section ``autopilot``: the journal (bounded) plus
        the controller's live state — what an operator reads to answer
        "what did the autopilot just do, and why"."""
        return {
            "tick": self.tick_no,
            "seed": self.seed,
            "journal_seq": self._seq,
            "journal_digest": self.journal_digest(),
            "journal": list(self.journal),
            "overloaded": sorted(self._overloaded),
            "cooldowns": dict(sorted(self._cooldown.items())),
            "drained": sorted(self.drained),
            "terminal": {
                t: {"attempts": s.attempts, "tick": s.tick}
                for t, s in sorted(self.terminal.items())
            },
        }

    def attach(self, telemetry) -> None:
        telemetry.add_provider("autopilot", self.snapshot)

    def report(self) -> Dict:
        """Scored summary for soak/bench reports (counts only — the full
        journal lives in `snapshot`)."""
        by_policy: Dict[str, int] = {}
        for e in self.journal:
            if e["policy"] != "fault":
                by_policy[e["policy"]] = by_policy.get(e["policy"], 0) + 1
        return {
            "ticks": self.tick_no,
            "actions": self._seq,
            "actions_by_policy": dict(sorted(by_policy.items())),
            "journal_digest": self.journal_digest(),
            "drained": sorted(self.drained),
            "terminal": sorted(self.terminal),
        }
